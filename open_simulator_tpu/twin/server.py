"""`simon twin` — the live digital-twin daemon.

One resident process per cluster: a tail thread keeps the mirror
current (twin/mirror.py) while HTTP handlers answer operational
questions against it (twin/queries.py), behind the same cost-predictive
admission control `simon serve` runs (serve/admission.py).

JSON-over-HTTP API (docs/TWIN.md):

- ``POST /v1/whatif`` — body is the serve envelope
  (``{"apps": [{"name":..., "yaml":"..."}]}`` or raw YAML): would
  these apps fit right now?
- ``POST /v1/drain`` — ``{"nodes": [...]}`` and/or
  ``{"selector": {"rack": "r7"}}``: can I cordon these nodes now?
- ``POST /v1/nplusk`` — ``{"k": 1, "trials": 32, "seed": 1}``: does
  the live placement survive any K-node outage?
- ``POST /v1/forecast`` — ``{"horizonSeconds": 3600,
  "rateScale": 2.0, ...}``: timeline windows stepped forward from the
  current mirrored state.
- ``GET /healthz`` — liveness + readiness (mirror staleness, apply
  errors, open breakers) + mirror stats.
- ``GET /metrics`` — Prometheus text: agreement-rate, mirror-lag and
  backlog gauges (the alertable pair), delta/divergence/flap
  counters, query latency histograms, plus the full resilience and
  observatory expositions serve exports.

Lifecycle: SIGTERM/SIGINT stops the tail, waits for in-flight queries
to finish writing, and exits 0.
"""

from __future__ import annotations

import json
import logging
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..models.validation import InputError
from ..obs import telemetry
from ..runtime.errors import GuardError
from ..serve.admission import AdmissionController, estimate_request_pods
from ..utils.trace import COUNTERS
from . import queries

log = logging.getLogger(__name__)

QUERY_HISTO = "twin/query"


class TwinAdmission(AdmissionController):
    """Serve's cost-predictive admission pointed at the twin's own
    latency histogram: shed with Retry-After when the p95 query time
    times the queue ahead busts the budget. (The HBM verdict stays on
    the compiled-scan cost table — same site the queries dispatch.)"""

    def _predicted_tick_s(self) -> float:
        from ..obs.histo import HISTOS

        h = HISTOS.peek(QUERY_HISTO)
        if h is None:
            return 0.0
        return float(h.percentile(95.0))


def render_twin_metrics(daemon: "TwinDaemon") -> bytes:
    """Prometheus exposition: the twin block first, then the shadow
    divergence counters the mirror's replayer feeds, then the shared
    resilience + observatory blocks (serve/server.py helpers — one
    exposition dialect across both daemons)."""
    from ..serve.server import (
        _observatory_lines,
        _resilience_lines,
        _telemetry_lines,
    )

    snap = COUNTERS.snapshot()
    counts, gauges = snap["counts"], snap["gauges"]
    lines = []

    def metric(name, kind, help_text, value):
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name} {value}")

    stats = daemon.mirror.stats()
    metric(
        "simon_twin_agreement_rate", "gauge",
        "Agreement rate of the mirror's divergence audit (1.0 = the real "
        "scheduler and simon fully agree).",
        gauges.get("twin_agreement_rate", 1.0),
    )
    metric(
        "simon_twin_mirror_lag_seconds", "gauge",
        "Age of the oldest observed-but-unapplied step (mirror staleness).",
        gauges.get("twin_mirror_lag_seconds", 0.0),
    )
    metric(
        "simon_twin_backlog", "gauge",
        "Observed steps waiting for bounded catch-up.",
        gauges.get("twin_backlog", 0.0),
    )
    metric(
        "simon_twin_pending_pods", "gauge",
        "Pods the real scheduler has not placed (the forecast requeue set).",
        stats["pendingPods"],
    )
    metric(
        "simon_twin_nodes", "gauge",
        "Nodes currently mirrored.", stats["nodes"],
    )
    for key, help_text in (
        ("twin_polls_total", "Tail polls attempted (flaps included)."),
        ("twin_tail_flaps_total", "Polls that failed and backed off."),
        ("twin_tail_deferred_steps_total", "Steps deferred past a bounded catch-up round."),
        ("twin_deltas_applied_total", "Cluster deltas applied to the warm mirror."),
        ("twin_delta_reloads_total", "Deltas that forced a state rebuild (node_drain only)."),
        ("twin_delta_skips_total", "Deltas skipped on live-tail races (counted, never fatal)."),
        ("twin_apply_errors_total", "Steps the substrate could not apply (mirror degraded)."),
        ("twin_whatif_total", "What-if queries answered."),
        ("twin_drain_total", "Drain-safety queries answered."),
        ("twin_nplusk_total", "N+K survivability queries answered."),
        ("twin_forecast_total", "Capacity forecasts answered."),
        ("twin_query_dispatches_total", "Warm device dispatches spent on queries."),
        ("twin_queries_shed_total", "Queries shed 429 by admission."),
    ):
        # twin_polls_total is derived from the gauge (poll_once counts
        # polls on the mirror, exported as a gauge)
        value = (
            int(gauges.get("twin_polls", 0.0))
            if key == "twin_polls_total"
            else counts.get(key, 0)
        )
        metric(f"simon_{key}", "counter", help_text, value)
    # the shadow divergence vocabulary (the mirror IS a shadow replay)
    for key, help_text in (
        ("shadow_steps_total", "Mirror steps applied (decisions + deltas)."),
        ("shadow_decisions_total", "Real scheduler decisions mirrored."),
        ("shadow_agree_total", "Decisions simon agreed with."),
        ("shadow_divergence_total", "Decisions simon diverged on."),
        ("shadow_warm_recompiles_total", "Jit-cache misses on an already-seen mirror shape."),
        ("shadow_ingest_event_decisions_total", "Tail decisions sourced from scheduler Event objects."),
        ("shadow_ingest_diff_decisions_total", "Tail decisions inferred from pod diffs alone."),
    ):
        metric(f"simon_{key}", "counter", help_text, counts.get(key, 0))
    # NOTE: _observatory_lines already includes the histogram
    # exposition; appending histo.prometheus_lines() again here used to
    # emit every latency family twice — duplicate samples a Prometheus
    # scraper rejects (caught by the exposition conformance test)
    lines.extend(_resilience_lines(snap))
    lines.extend(_observatory_lines(snap))
    lines.extend(_telemetry_lines(snap, daemon.slo_engine))
    lines.append("")
    return "\n".join(lines).encode()


def parse_whatif_body(raw: bytes, content_type: str):
    """The serve request dialect reused verbatim: the answer to 'would
    this deployment fit' must not depend on which daemon you asked."""
    from ..serve.server import parse_request_body

    req, _deadline, _trace = parse_request_body(raw, content_type)
    return req


def _parse_json_object(raw: bytes) -> dict:
    try:
        doc = json.loads(raw.decode("utf-8")) if raw.strip() else {}
    except (UnicodeDecodeError, ValueError) as e:
        raise InputError(f"body is not valid JSON: {e}") from e
    if not isinstance(doc, dict):
        raise InputError("body must be a JSON object")
    return doc


def canonical_body(payload: dict) -> bytes:
    return json.dumps(payload, sort_keys=True, separators=(",", ":")).encode()


class TwinDaemon:
    """Owns the HTTP server, the tail thread, and the drain
    lifecycle."""

    def __init__(
        self,
        mirror,
        host: str = "127.0.0.1",
        port: int = 8080,
        poll_interval_s: float = 2.0,
        max_polls: Optional[int] = None,
        tick_budget_s: Optional[float] = None,
        max_request_pods: Optional[int] = None,
        drain_timeout_s: float = 30.0,
        budget=None,
        slo_engine=None,
        obs_cadence_s: float = 1.0,
        snapshot_path: Optional[str] = None,
        checkpoint_interval: Optional[int] = None,
        keep_checkpoints: int = 2,
    ):
        if poll_interval_s <= 0:
            raise InputError(
                f"--poll-interval must be > 0s, got {poll_interval_s}"
            )
        self.mirror = mirror
        self.poll_interval_s = poll_interval_s
        self.max_polls = max_polls
        self.drain_timeout_s = drain_timeout_s
        self.budget = budget
        self.slo_engine = slo_engine
        self.telemetry = telemetry.TelemetryRuntime(
            cadence_s=obs_cadence_s, slo_engine=slo_engine
        )
        self.admission = TwinAdmission(
            max_batch=1,
            tick_budget_s=tick_budget_s,
            max_request_pods=max_request_pods,
        )
        # bounded-recovery checkpoints (runtime/checkpoint.py): the
        # same ladder serve runs — verified mirror snapshots every
        # --checkpoint-interval applied steps, journal compacted to
        # the unabsorbed suffix (the mirror's journal was attached by
        # the CLI before this daemon was built)
        self.checkpoints = None
        if snapshot_path and checkpoint_interval:
            from ..runtime.checkpoint import CheckpointManager, checkpoint_dir
            from .mirror import (
                capture_mirror,
                twin_keep_record,
                twin_materialized_digest,
            )

            self.checkpoints = CheckpointManager(
                checkpoint_dir(snapshot_path),
                interval=checkpoint_interval,
                keep=keep_checkpoints,
                capture=lambda: capture_mirror(self.mirror),
                materialized_digest=twin_materialized_digest,
                journal=mirror.journal,
                keep_record=twin_keep_record,
                label="twin",
            )
        self._stop = threading.Event()
        self._tail_done = threading.Event()
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._inflight_zero = threading.Event()
        self._inflight_zero.set()
        daemon = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                log.debug("%s %s", self.address_string(), fmt % args)

            def _send(self, status: int, body: bytes,
                      content_type="application/json", headers=()):
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    status, reasons = daemon.readiness()
                    # degraded readiness advertises the SAME backoff
                    # hint as the admission 429 path (p95 query time x
                    # queries in flight), so probers and LBs back off
                    # uniformly with shed clients
                    hdrs = ()
                    retry_after = None
                    if reasons:
                        with daemon._inflight_lock:
                            waiting = max(daemon._inflight, 0)
                        retry_after = daemon.admission.retry_after_hint(
                            waiting
                        )
                        hdrs = (("Retry-After", str(retry_after)),)
                    self._send(200, canonical_body({
                        "ok": True,
                        "status": status,
                        "degraded": bool(reasons),
                        "reasons": reasons,
                        "retryAfterSeconds": retry_after,
                        "sloAlerting": (
                            daemon.slo_engine.alerting()
                            if daemon.slo_engine is not None
                            else []
                        ),
                        # serve-parity identity (docs/FLEET.md): the
                        # fields fleet-style supervision of twin
                        # replicas verifies restore identity against
                        "cluster": daemon.mirror.replayer.report.fingerprint,
                        "deltaSeq": daemon.mirror.applied_seq(),
                        "checkpoint": (
                            daemon.checkpoints.stats()
                            if daemon.checkpoints is not None
                            else None
                        ),
                        "mirror": daemon.mirror.stats(),
                    }), headers=hdrs)
                elif self.path == "/v1/state-digest":
                    # the same dict-identity triple serve exposes: a
                    # replacement twin is correct iff this matches the
                    # mirror it replaced
                    self._send(200, canonical_body({
                        "fingerprint": daemon.mirror.replayer.report.fingerprint,
                        "deltaSeq": daemon.mirror.applied_seq(),
                        "stateDigest": daemon.mirror.state_digest(),
                    }))
                elif self.path == "/metrics":
                    self._send(
                        200,
                        render_twin_metrics(daemon),
                        content_type="text/plain; version=0.0.4",
                    )
                elif self.path.startswith("/v1/obs/series"):
                    status, doc = telemetry.series_endpoint(self.path)
                    self._send(status, canonical_body(doc))
                elif self.path == "/v1/obs/snapshot":
                    self._send(
                        200,
                        canonical_body(
                            telemetry.snapshot_doc(
                                daemon.slo_engine,
                                runtime=daemon.telemetry,
                                extra={
                                    "daemon": "twin",
                                    "health": daemon.readiness()[0],
                                },
                            )
                        ),
                    )
                else:
                    self._send(404, json.dumps({"error": "not found"}).encode())

            def do_POST(self):
                if self.path == "/debug/dump":
                    length = int(self.headers.get("Content-Length") or 0)
                    status, doc = telemetry.handle_debug_dump(
                        self.rfile.read(length),
                        slo_engine=daemon.slo_engine,
                        runtime=daemon.telemetry,
                        label="twin",
                    )
                    self._send(status, canonical_body(doc))
                    return
                route = {
                    "/v1/whatif": daemon._q_whatif,
                    "/v1/drain": daemon._q_drain,
                    "/v1/nplusk": daemon._q_nplusk,
                    "/v1/forecast": daemon._q_forecast,
                }.get(self.path)
                if route is None:
                    self._send(404, json.dumps({"error": "not found"}).encode())
                    return
                with daemon._inflight_lock:
                    daemon._inflight += 1
                    daemon._inflight_zero.clear()
                try:
                    self._route(route)
                finally:
                    with daemon._inflight_lock:
                        daemon._inflight -= 1
                        if daemon._inflight == 0:
                            daemon._inflight_zero.set()

            def _route(self, route):
                # the serve request-ID contract verbatim: accepted or
                # minted, bound for the query's whole scope (mirror
                # probes and scan spans all stamp it), echoed on every
                # response
                rid = telemetry.ensure_request_id(
                    self.headers.get(telemetry.REQUEST_ID_HEADER)
                )
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length)
                with telemetry.request_scope(rid):
                    status, payload, headers = daemon.answer(
                        route,
                        raw,
                        self.headers.get("Content-Type", ""),
                        rid=rid,
                    )
                headers = tuple(headers) + (
                    (telemetry.REQUEST_ID_HEADER, rid),
                )
                self._send(status, payload, headers=headers)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.host, self.port = self.httpd.server_address[:2]
        self._server_thread = threading.Thread(
            target=self.httpd.serve_forever, name="simon-twin-http", daemon=True
        )
        self._tail_thread = threading.Thread(
            target=self._tail_loop, name="simon-twin-tail", daemon=True
        )

    # -- query dispatch -----------------------------------------------------

    def answer(self, route, raw: bytes, content_type: str, rid: str = ""):
        """One admission-gated query evaluation. Returns
        (status, body bytes, headers). ``rid`` is the request's
        correlation ID — carried in every error/shed body (the 200
        body stays a pure function of the query, echoed in the
        response header by the handler instead)."""
        from ..obs.histo import HISTOS
        from ..obs.spans import RECORDER

        def err_body(doc: dict) -> bytes:
            if rid:
                doc = {**doc, "requestId": rid}
            return canonical_body(doc)

        try:
            est_pods, call = route(raw, content_type)
        except (InputError, ValueError) as e:
            return 400, err_body({"error": str(e)}), ()
        with self._inflight_lock:
            waiting = self._inflight - 1  # queries ahead of this one
        verdict = self.admission.decide(
            est_pods=est_pods, queue_depth=max(waiting, 0)
        )
        if verdict.action == "shed":
            COUNTERS.inc("twin_queries_shed_total")
            return (
                429,
                err_body({"error": verdict.reason, "shed": True}),
                (("Retry-After", str(verdict.retry_after_s)),),
            )
        t0 = time.perf_counter()
        try:
            with RECORDER.span("twin/request"):
                out = call()
        except (InputError, ValueError) as e:
            return 400, err_body({"error": str(e)}), ()
        except GuardError as e:
            # classified degradation (device OOM mid-query, injected
            # fault): a typed 500, the daemon stays up
            COUNTERS.inc("twin_query_errors_total")
            return (
                500,
                err_body({"error": str(e), "type": type(e).__name__}),
                (),
            )
        HISTOS.observe(QUERY_HISTO, time.perf_counter() - t0)
        return 200, canonical_body(out), ()

    def _q_whatif(self, raw, content_type):
        req = parse_whatif_body(raw, content_type)
        return (
            estimate_request_pods(req),
            lambda: queries.whatif(self.mirror, req.apps),
        )

    def _q_drain(self, raw, content_type):
        doc = _parse_json_object(raw)
        nodes = doc.get("nodes") or ()
        selector = doc.get("selector")
        if not isinstance(nodes, (list, tuple)):
            raise InputError('"nodes" must be a list of node names')
        return (
            0,
            lambda: queries.drain(self.mirror, nodes=nodes, selector=selector),
        )

    def _q_nplusk(self, raw, content_type):
        doc = _parse_json_object(raw)
        return (
            0,
            lambda: queries.nplusk(
                self.mirror,
                k=int(doc.get("k", 1)),
                trials=int(doc.get("trials", 32)),
                seed=int(doc.get("seed", 1)),
            ),
        )

    def _q_forecast(self, raw, content_type):
        doc = _parse_json_object(raw)
        horizon = doc.get("horizonSeconds")
        if horizon is None:
            raise InputError('forecast needs "horizonSeconds"')
        rate = doc.get("arrivalRate")
        return (
            0,
            lambda: queries.forecast(
                self.mirror,
                horizon_s=float(horizon),
                arrival_rate=None if rate is None else float(rate),
                rate_scale=float(doc.get("rateScale", 1.0)),
                seed=int(doc.get("seed", 1)),
                policy=str(doc.get("policy", "static:0")),
                cadence_s=float(doc.get("cadenceSeconds", 60.0)),
                warmup_s=float(doc.get("warmupSeconds", 0.0)),
                max_nodes=int(doc.get("maxNodes", 0)),
                engine=str(doc.get("engine", "oracle")),
                mean_lifetime_s=float(doc.get("meanLifetimeSeconds", 600.0)),
            ),
        )

    # -- the tail loop ------------------------------------------------------

    def _tail_loop(self):
        from ..runtime.errors import ExecutionHalted
        from ..runtime.retry import backoff_delay

        flaps = 0
        polls = 0
        try:
            while not self._stop.is_set():
                if self.budget is not None:
                    self.budget.check(f"twin tail (poll {polls})")
                if self.max_polls is not None and polls >= self.max_polls:
                    # the mirror stays queryable at its final state —
                    # which must include every OBSERVED step, not just
                    # the caught-up prefix
                    self.mirror.drain_backlog(budget=self.budget)
                    break
                if getattr(self.mirror.source, "exhausted", False):
                    # recorded feeds run dry; the mirror stays
                    # queryable at its final state until signaled
                    self.mirror.drain_backlog(budget=self.budget)
                    if self.checkpoints is not None:
                        self.checkpoints.note_delta(self.mirror.applied_seq())
                    break
                applied = self.mirror.poll_once(budget=self.budget)
                polls += 1
                if applied > 0 and self.checkpoints is not None:
                    self.checkpoints.note_delta(self.mirror.applied_seq())
                if applied < 0:
                    flaps += 1
                    delay = min(
                        backoff_delay("twin-tail", min(flaps, 6)),
                        self.poll_interval_s,
                    )
                else:
                    flaps = 0
                    delay = self.poll_interval_s
                self._stop.wait(timeout=delay)
        except ExecutionHalted:
            log.warning("twin tail halted by deadline; mirror frozen")
        finally:
            self._tail_done.set()

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        self.telemetry.start()
        if self.checkpoints is not None:
            self.checkpoints.start()
        self._server_thread.start()
        self._tail_thread.start()
        log.info("simon twin listening on %s:%d", self.host, self.port)

    def readiness(self):
        from ..runtime.retry import breaker_states

        reasons = list(self.mirror.degraded_reasons())
        for endpoint, st in sorted(breaker_states().items()):
            if st["open"]:
                reasons.append(f"circuit breaker open: {endpoint}")
        if self.slo_engine is not None:
            reasons.extend(self.slo_engine.reasons())
        if self.checkpoints is not None:
            reasons.extend(self.checkpoints.degraded_reasons())
        return ("degraded" if reasons else "ok"), reasons

    def begin_shutdown(self):
        self._stop.set()

    def shutdown(self) -> int:
        self.begin_shutdown()
        self._tail_done.wait(timeout=self.drain_timeout_s)
        self._inflight_zero.wait(timeout=min(self.drain_timeout_s, 10.0))
        if self.checkpoints is not None:
            # stop the worker before the journal closes underneath it
            self.checkpoints.stop()
        if self.mirror.journal is not None:
            self.mirror.journal.close()
        self.telemetry.stop()
        self.httpd.shutdown()
        self.httpd.server_close()
        return 0

    def run_until_signaled(self) -> int:
        def handler(signum, frame):
            log.info("received signal %d: draining", signum)
            self.begin_shutdown()
            self._wake.set()

        self._wake = threading.Event()
        prev_term = signal.signal(signal.SIGTERM, handler)
        prev_int = signal.signal(signal.SIGINT, handler)
        try:
            self._wake.wait()
            return self.shutdown()
        finally:
            signal.signal(signal.SIGTERM, prev_term)
            signal.signal(signal.SIGINT, prev_int)
