"""`simon twin` — the live digital twin (ROADMAP item 4).

One resident process that continuously mirrors a real cluster and
answers anything against LIVE state: the shadow tailer's ingest
(shadow/ingest.py), the serve daemon's warm sessions, and the
timeline's forward stepping, fused on one substrate — the typed
``ClusterDelta`` vocabulary and its incremental applicator
(twin/deltas.py). See docs/TWIN.md.
"""

from .deltas import (  # noqa: F401
    APPLIED,
    DELTA_KINDS,
    RELOADED,
    SKIPPED,
    ClusterDelta,
    MirrorApplicator,
    cold_reload,
    deltas_to_events,
    from_shadow_op,
    materialize,
    state_dict,
    steps_to_deltas,
)
