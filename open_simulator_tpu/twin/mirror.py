"""The resident per-cluster mirror: one warm replayer continuously fed
by a live cluster (or a recorded feed), always current, always
queryable.

A ``ClusterMirror`` fuses the three previously separate CLIs:

- **ingest** — steps come from a ``StepSource``: ``LiveSource`` wraps
  the shadow tailer's poll-diff loop (shadow/ingest.py, now
  event/binding-aware), ``FeedSource`` replays a recorded decision
  log at a configurable batch per poll (the self-conformance and CI
  path: simon tails its own recorded feed and must agree with itself
  100%).
- **apply** — every step routes through the shadow replayer, whose
  state lives on the cluster-delta substrate (twin/deltas.py): pod
  deltas are incremental commits on copy-on-write NodeStates, the
  probe replays the real scheduler's decision against the warm mirror
  and classifies the divergence, and reality commits — exactly PR 7's
  audit loop, now resident.
- **observe** — agreement-rate, mirror-lag (age of the oldest
  unapplied observed step), backlog depth, flap and apply-error
  counts stream to the process counter registry as alertable gauges
  (``/metrics``, twin/server.py).

Concurrency: the tail loop and the query engines (twin/queries.py)
share ``self._lock`` — queries see a consistent mirror, the tail
never applies mid-query. Polls are bounded by ``max_catchup`` steps
per round (a recovered flap's giant diff converges across rounds
instead of blocking queries for its full length).

Failure posture (docs/ROBUSTNESS.md): a failed poll is a counted flap
with deterministic backoff (the tail survives apiserver restarts); a
step the substrate cannot apply (torn feed, corrupt record, injected
``twin.apply_delta`` fault) is counted, skipped, and surfaces as a
``degraded`` reason in ``/healthz`` — the mirror keeps serving with
the staleness visible rather than dying mid-shift.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import List, Optional, Tuple

from ..models.validation import InputError
from ..runtime.errors import GuardError
from ..utils.trace import COUNTERS
from .deltas import MirrorApplicator  # noqa: F401  (re-export for callers)

#: backlog depth past which /healthz reports the mirror degraded
BACKLOG_DEGRADED = 4096


class LiveSource:
    """Step source over a live cluster: the shadow tailer's
    poll-diff-normalize loop (one paged LIST per poll, retry/breaker
    hardened underneath). When the caller already bootstrapped the
    tailer (the CLI needs the node LIST to build the mirror's cluster
    first), the recorded ``boot_steps`` replay from here instead of a
    second LIST."""

    def __init__(self, tailer, boot_steps: Optional[list] = None):
        self.tailer = tailer
        self._boot_steps = boot_steps
        self.exhausted = False  # a live cluster never runs out

    def bootstrap(self) -> Tuple[List[dict], list]:
        if self._boot_steps is not None:
            steps, self._boot_steps = self._boot_steps, None
            return [], steps
        return self.tailer.bootstrap()

    def poll(self) -> list:
        return self.tailer.poll()


class FeedSource:
    """Step source over a recorded decision log: each poll yields the
    next ``batch`` steps until the feed is exhausted. This is the
    mirror's self-conformance harness — tailing a feed simon itself
    recorded must replay at agreement 1.0 — and the CI smoke's
    synthetic live cluster."""

    def __init__(self, steps: list, batch: int = 64):
        if batch < 1:
            raise InputError(f"feed batch must be >= 1, got {batch}")
        self._steps = collections.deque(steps)
        self.batch = batch
        self.total = len(steps)

    @property
    def exhausted(self) -> bool:
        return not self._steps

    def bootstrap(self) -> Tuple[List[dict], list]:
        return [], []  # the cluster comes from the config

    def poll(self) -> list:
        out = []
        while self._steps and len(out) < self.batch:
            out.append(self._steps.popleft())
        return out


class ClusterMirror:
    """One mirrored cluster plus its tail-loop state. All mirrored
    state is guarded by ``lock`` — the tail thread applies under it,
    query engines read under it."""

    def __init__(
        self,
        cluster,
        source,
        engine: str = "tpu",
        max_catchup: int = 256,
    ):
        from ..shadow.replay import ShadowReplayer

        if max_catchup < 1:
            raise InputError(
                f"--max-catchup must be >= 1, got {max_catchup} (0 would "
                "never apply the backlog and the mirror would stop advancing)"
            )
        self.source = source
        self.max_catchup = int(max_catchup)
        self._lock = threading.RLock()
        self.replayer = ShadowReplayer(
            cluster, engine=engine, explain_divergences=False
        )
        # (observed_monotonic, step) — steps wait here between the
        # poll that observed them and the bounded catch-up that
        # applies them; the oldest entry's age IS the mirror lag
        self._backlog: "collections.deque" = collections.deque()
        self.polls = 0
        self.flaps = 0
        self.apply_errors = 0
        self.started_at = time.monotonic()

    # -- locking (query engines hold the mirror across one evaluation) --

    @property
    def lock(self) -> threading.RLock:
        return self._lock

    # `replayer` is bound once in __init__ and never rebound; only its
    # INTERIOR state needs the lock, so handing out the reference
    # itself is race-free
    @property
    def applicator(self) -> MirrorApplicator:  # simonlint: disable=CONC001 - immutable reference; interior mutation happens under the lock in _apply_step/stats
        return self.replayer._app

    @property
    def oracle(self):  # simonlint: disable=CONC001 - immutable reference (see applicator)
        return self.replayer.oracle

    @property
    def engine(self):  # simonlint: disable=CONC001 - immutable reference (see applicator)
        return self.replayer._engine

    # -- lifecycle ----------------------------------------------------------

    def bootstrap(self):
        """First contact: LiveSource LISTs the cluster and the mirror
        applies the bootstrap placement deltas; FeedSource mirrors are
        born from the config's cluster and bootstrap is a no-op."""
        nodes, steps = self.source.bootstrap()
        with self._lock:
            for st in steps:
                self._apply_step(st)
        self._export()
        return nodes

    def poll_once(self, budget=None) -> int:
        """One tail round: poll the source (a failure is a counted
        flap, never fatal), enqueue observed steps, apply at most
        ``max_catchup`` of the backlog under the lock. Returns the
        number of steps applied; raises nothing but ExecutionHalted
        (budget) and unclassified faults (which must stay loud)."""
        from ..runtime import inject as _inject
        from ..runtime.errors import ExternalIOError

        with self._lock:
            poll_no = self.polls
        try:
            # chaos seam: a `twin.poll` fault lands like a real
            # apiserver flap (reset/timeout/http:NNN/exio). The
            # network LIST runs OUTSIDE the mirror lock — a slow or
            # wedged apiserver must never block queries
            _inject.fire("twin.poll", poll=poll_no)
            steps = self.source.poll()
        except (ExternalIOError, OSError):
            with self._lock:
                self.flaps += 1
                self.polls += 1
            COUNTERS.inc("twin_tail_flaps_total")
            self._export()
            return -1  # the caller backs off
        now = time.monotonic()
        applied = 0
        with self._lock:
            self._backlog.extend((now, st) for st in steps)
            while self._backlog and applied < self.max_catchup:
                if budget is not None:
                    budget.check(f"twin tail (poll {poll_no}, catch-up)")
                _obs, st = self._backlog.popleft()
                self._apply_step(st)
                applied += 1
            if self._backlog:
                COUNTERS.inc(
                    "twin_tail_deferred_steps_total", len(self._backlog)
                )
            self.polls += 1
        self._export()
        return applied

    def drain_backlog(self, budget=None) -> int:
        """Apply every deferred step (shutdown / end-of-feed path)."""
        applied = 0
        with self._lock:
            while self._backlog:
                if budget is not None:
                    budget.check("twin tail (final catch-up)")
                _obs, st = self._backlog.popleft()
                self._apply_step(st)
                applied += 1
        self._export()
        return applied

    def _apply_step(self, st):  # simonlint: disable=CONC001 - callers hold self._lock (poll_once/drain_backlog/bootstrap)
        try:
            self.replayer.step(st)
        except (GuardError, InputError) as e:
            # a step the substrate cannot apply (torn feed, injected
            # fault, corrupt record): counted and skipped — the mirror
            # keeps serving, /healthz carries the degradation
            self.apply_errors += 1
            COUNTERS.inc("twin_apply_errors_total")
            from ..utils.trace import GLOBAL

            GLOBAL.append_note(
                "twin-apply-error", f"step {getattr(st, 'seq', '?')}: {str(e)[:120]}"
            )

    # -- observability ------------------------------------------------------

    def _lag_locked(self) -> float:  # simonlint: disable=CONC001 - caller holds self._lock (the _locked suffix contract)
        if not self._backlog:
            return 0.0
        return max(0.0, time.monotonic() - self._backlog[0][0])

    def mirror_lag_s(self) -> float:
        """Age of the oldest observed-but-unapplied step (0.0 when the
        mirror is current) — the alertable staleness signal."""
        with self._lock:
            return self._lag_locked()

    def agreement_rate(self) -> float:
        with self._lock:
            return self.replayer.report.agreement_rate

    def _export(self):
        with self._lock:
            rep = self.replayer.report
            agreement = rep.agreement_rate
            backlog = float(len(self._backlog))
            polls = float(self.polls)
            lag = self._lag_locked()
        COUNTERS.gauge("twin_agreement_rate", agreement)
        COUNTERS.gauge("twin_mirror_lag_seconds", round(lag, 6))
        COUNTERS.gauge("twin_backlog", backlog)
        COUNTERS.gauge("twin_polls", polls)

    def degraded_reasons(self) -> List[str]:
        reasons = []
        with self._lock:
            apply_errors = self.apply_errors
            backlog = len(self._backlog)
            lag = self._lag_locked()
        if apply_errors:
            reasons.append(
                f"{apply_errors} delta step(s) could not be applied "
                "(mirror may be stale; see twin_apply_errors_total)"
            )
        if backlog > BACKLOG_DEGRADED:
            reasons.append(
                f"tail backlog {backlog} steps deep "
                f"(> {BACKLOG_DEGRADED}); mirror lag {lag:.1f}s"
            )
        return reasons

    def stats(self) -> dict:
        exhausted = bool(getattr(self.source, "exhausted", False))
        with self._lock:
            rep = self.replayer.report
            app = self.replayer._app
            return {
                "polls": self.polls,
                "flaps": self.flaps,
                "backlog": len(self._backlog),
                "mirrorLagSeconds": round(self._lag_locked(), 6),
                "steps": rep.steps,
                "decisions": rep.decisions,
                "agreementRate": rep.agreement_rate,
                "divergences": rep.divergence_count,
                "warmRecompiles": rep.warm_recompiles,
                "reloads": rep.reloads,
                "deltasApplied": app.applied,
                "deltaSkips": app.skips,
                "applyErrors": self.apply_errors,
                "pendingPods": len(app.pending),
                "nodes": len(app.oracle.nodes),
                "feedExhausted": exhausted,
            }

    # -- state snapshot (the timeline bridge) -------------------------------

    def snapshot_cluster(self):  # simonlint: disable=CONC001 - caller holds self.lock (queries.forecast takes it across the snapshot)
        """The mirrored state as a loadable cluster: current nodes plus
        every committed pod in its bound form — what a capacity
        forecast steps forward from (twin/queries.py) and what
        ``simon apply`` would load if the mirror were written to disk.
        Caller holds the lock."""
        import copy

        from ..models.decode import ResourceTypes

        cluster = ResourceTypes()
        cluster.nodes = [copy.deepcopy(ns.node) for ns in self.oracle.nodes]
        cluster.pods = [
            copy.deepcopy(p) for ns in self.oracle.nodes for p in ns.pods
        ]
        base = self.replayer.cluster
        cluster.pod_disruption_budgets = list(base.pod_disruption_budgets)
        cluster.priority_classes = list(base.priority_classes)
        return cluster
