"""The resident per-cluster mirror: one warm replayer continuously fed
by a live cluster (or a recorded feed), always current, always
queryable.

A ``ClusterMirror`` fuses the three previously separate CLIs:

- **ingest** — steps come from a ``StepSource``: ``LiveSource`` wraps
  the shadow tailer's poll-diff loop (shadow/ingest.py, now
  event/binding-aware), ``FeedSource`` replays a recorded decision
  log at a configurable batch per poll (the self-conformance and CI
  path: simon tails its own recorded feed and must agree with itself
  100%).
- **apply** — every step routes through the shadow replayer, whose
  state lives on the cluster-delta substrate (twin/deltas.py): pod
  deltas are incremental commits on copy-on-write NodeStates, the
  probe replays the real scheduler's decision against the warm mirror
  and classifies the divergence, and reality commits — exactly PR 7's
  audit loop, now resident.
- **observe** — agreement-rate, mirror-lag (age of the oldest
  unapplied observed step), backlog depth, flap and apply-error
  counts stream to the process counter registry as alertable gauges
  (``/metrics``, twin/server.py).

Concurrency: the tail loop and the query engines (twin/queries.py)
share ``self._lock`` — queries see a consistent mirror, the tail
never applies mid-query. Polls are bounded by ``max_catchup`` steps
per round (a recovered flap's giant diff converges across rounds
instead of blocking queries for its full length).

Failure posture (docs/ROBUSTNESS.md): a failed poll is a counted flap
with deterministic backoff (the tail survives apiserver restarts); a
step the substrate cannot apply (torn feed, corrupt record, injected
``twin.apply_delta`` fault) is counted, skipped, and surfaces as a
``degraded`` reason in ``/healthz`` — the mirror keeps serving with
the staleness visible rather than dying mid-shift.
"""

from __future__ import annotations

import collections
import copy
import threading
import time
from typing import List, Optional, Tuple

from ..models.validation import InputError
from ..runtime.errors import GuardError
from ..runtime.journal import Journal, config_fingerprint
from ..utils.trace import COUNTERS
from .deltas import MirrorApplicator  # noqa: F401  (re-export for callers)

#: backlog depth past which /healthz reports the mirror degraded
BACKLOG_DEGRADED = 4096

TWIN_SNAPSHOT_VERSION = 1


class TwinSnapshotJournal(Journal):
    """The twin's durable step journal (``--snapshot``): same crash-
    safe JSONL format/recovery as every other journal, its own
    fault-injection crash point. One record per successfully applied
    mirror step — the delta stream a restarted twin replays (after a
    checkpoint restore bounds the suffix, runtime/checkpoint.py)."""

    inject_site = "journal.fsync.twin"


def open_twin_snapshot(path: str) -> TwinSnapshotJournal:
    """Create-or-resume the twin step journal at ``path``."""
    fp = config_fingerprint(
        {"format": "twin-mirror-snapshot", "version": TWIN_SNAPSHOT_VERSION}
    )
    return TwinSnapshotJournal.open(path, fp)


def twin_keep_record(rec: dict, upto_seq: int) -> bool:
    """Checkpoint-compaction predicate for the twin journal: a
    verified checkpoint at seq N absorbs every journaled step with
    ``seq <= N``; everything else is retained."""
    if rec.get("kind") != "mirror" or rec.get("event") != "step":
        return True
    seq = rec.get("seq")
    return not (isinstance(seq, int) and seq <= upto_seq)


class LiveSource:
    """Step source over a live cluster: the shadow tailer's
    poll-diff-normalize loop (one paged LIST per poll, retry/breaker
    hardened underneath). When the caller already bootstrapped the
    tailer (the CLI needs the node LIST to build the mirror's cluster
    first), the recorded ``boot_steps`` replay from here instead of a
    second LIST."""

    def __init__(self, tailer, boot_steps: Optional[list] = None):
        self.tailer = tailer
        self._boot_steps = boot_steps
        self.exhausted = False  # a live cluster never runs out

    def bootstrap(self) -> Tuple[List[dict], list]:
        if self._boot_steps is not None:
            steps, self._boot_steps = self._boot_steps, None
            return [], steps
        return self.tailer.bootstrap()

    def poll(self) -> list:
        return self.tailer.poll()


class FeedSource:
    """Step source over a recorded decision log: each poll yields the
    next ``batch`` steps until the feed is exhausted. This is the
    mirror's self-conformance harness — tailing a feed simon itself
    recorded must replay at agreement 1.0 — and the CI smoke's
    synthetic live cluster."""

    def __init__(self, steps: list, batch: int = 64):
        if batch < 1:
            raise InputError(f"feed batch must be >= 1, got {batch}")
        self._steps = collections.deque(steps)
        self.batch = batch
        self.total = len(steps)

    @property
    def exhausted(self) -> bool:
        return not self._steps

    def bootstrap(self) -> Tuple[List[dict], list]:
        return [], []  # the cluster comes from the config

    def poll(self) -> list:
        out = []
        while self._steps and len(out) < self.batch:
            out.append(self._steps.popleft())
        return out


class ClusterMirror:
    """One mirrored cluster plus its tail-loop state. All mirrored
    state is guarded by ``lock`` — the tail thread applies under it,
    query engines read under it."""

    def __init__(
        self,
        cluster,
        source,
        engine: str = "tpu",
        max_catchup: int = 256,
    ):
        from ..shadow.replay import ShadowReplayer

        if max_catchup < 1:
            raise InputError(
                f"--max-catchup must be >= 1, got {max_catchup} (0 would "
                "never apply the backlog and the mirror would stop advancing)"
            )
        self.source = source
        self.max_catchup = int(max_catchup)
        self._lock = threading.RLock()
        self.replayer = ShadowReplayer(
            cluster, engine=engine, explain_divergences=False
        )
        # (observed_monotonic, step) — steps wait here between the
        # poll that observed them and the bounded catch-up that
        # applies them; the oldest entry's age IS the mirror lag
        self._backlog: "collections.deque" = collections.deque()
        self.polls = 0
        self.flaps = 0
        self.apply_errors = 0
        # the externally checkable applied-step sequence (the twin
        # analogue of serve's deltaSeq, exposed at /healthz and
        # /v1/state-digest) — restore identity is verified against it
        self.delta_seq = 0
        # durable step journal (attach AFTER any replay: replayed
        # steps are already on disk and must not re-append)
        self.journal: Optional[Journal] = None
        self.started_at = time.monotonic()

    # -- locking (query engines hold the mirror across one evaluation) --

    @property
    def lock(self) -> threading.RLock:
        return self._lock

    # `replayer` is bound once in __init__ and never rebound; only its
    # INTERIOR state needs the lock, so handing out the reference
    # itself is race-free
    @property
    def applicator(self) -> MirrorApplicator:  # simonlint: disable=CONC001 - immutable reference; interior mutation happens under the lock in _apply_step/stats
        return self.replayer._app

    @property
    def oracle(self):  # simonlint: disable=CONC001 - immutable reference (see applicator)
        return self.replayer.oracle

    @property
    def engine(self):  # simonlint: disable=CONC001 - immutable reference (see applicator)
        return self.replayer._engine

    # -- lifecycle ----------------------------------------------------------

    def bootstrap(self):
        """First contact: LiveSource LISTs the cluster and the mirror
        applies the bootstrap placement deltas; FeedSource mirrors are
        born from the config's cluster and bootstrap is a no-op."""
        nodes, steps = self.source.bootstrap()
        with self._lock:
            for st in steps:
                # the journal append inside must be atomic with the state
                # mutation: a step must never be applied-but-unjournaled
                self._apply_step(st)  # simonlint: disable=CONC002
        self._export()
        return nodes

    def poll_once(self, budget=None) -> int:
        """One tail round: poll the source (a failure is a counted
        flap, never fatal), enqueue observed steps, apply at most
        ``max_catchup`` of the backlog under the lock. Returns the
        number of steps applied; raises nothing but ExecutionHalted
        (budget) and unclassified faults (which must stay loud)."""
        from ..runtime import inject as _inject
        from ..runtime.errors import ExternalIOError

        with self._lock:
            poll_no = self.polls
        try:
            # chaos seam: a `twin.poll` fault lands like a real
            # apiserver flap (reset/timeout/http:NNN/exio). The
            # network LIST runs OUTSIDE the mirror lock — a slow or
            # wedged apiserver must never block queries
            _inject.fire("twin.poll", poll=poll_no)
            steps = self.source.poll()
        except (ExternalIOError, OSError):
            with self._lock:
                self.flaps += 1
                self.polls += 1
            COUNTERS.inc("twin_tail_flaps_total")
            self._export()
            return -1  # the caller backs off
        now = time.monotonic()
        applied = 0
        with self._lock:
            self._backlog.extend((now, st) for st in steps)
            while self._backlog and applied < self.max_catchup:
                if budget is not None:
                    budget.check(f"twin tail (poll {poll_no}, catch-up)")
                _obs, st = self._backlog.popleft()
                # journal append atomic with the mutation (see bootstrap)
                self._apply_step(st)  # simonlint: disable=CONC002
                applied += 1
            if self._backlog:
                COUNTERS.inc(
                    "twin_tail_deferred_steps_total", len(self._backlog)
                )
            self.polls += 1
        self._export()
        return applied

    def drain_backlog(self, budget=None) -> int:
        """Apply every deferred step (shutdown / end-of-feed path)."""
        applied = 0
        with self._lock:
            while self._backlog:
                if budget is not None:
                    budget.check("twin tail (final catch-up)")
                _obs, st = self._backlog.popleft()
                # journal append atomic with the mutation (see bootstrap)
                self._apply_step(st)  # simonlint: disable=CONC002
                applied += 1
        self._export()
        return applied

    def _apply_step(self, st):  # simonlint: disable=CONC001 - callers hold self._lock (poll_once/drain_backlog/bootstrap/replay)
        try:
            self.replayer.step(st)
        except (GuardError, InputError) as e:
            # a step the substrate cannot apply (torn feed, injected
            # fault, corrupt record): counted and skipped — the mirror
            # keeps serving, /healthz carries the degradation
            self.apply_errors += 1
            COUNTERS.inc("twin_apply_errors_total")
            from ..utils.trace import GLOBAL

            GLOBAL.append_note(
                "twin-apply-error", f"step {getattr(st, 'seq', '?')}: {str(e)[:120]}"
            )
            return
        self.delta_seq += 1
        if self.journal is not None:
            self.journal.append(
                {
                    "kind": "mirror",
                    "event": "step",
                    "seq": self.delta_seq,
                    "step": st.as_record(),
                }
            )

    # -- observability ------------------------------------------------------

    def _lag_locked(self) -> float:  # simonlint: disable=CONC001 - caller holds self._lock (the _locked suffix contract)
        if not self._backlog:
            return 0.0
        return max(0.0, time.monotonic() - self._backlog[0][0])

    def mirror_lag_s(self) -> float:
        """Age of the oldest observed-but-unapplied step (0.0 when the
        mirror is current) — the alertable staleness signal."""
        with self._lock:
            return self._lag_locked()

    def agreement_rate(self) -> float:
        with self._lock:
            return self.replayer.report.agreement_rate

    def state_digest(self) -> str:
        """Canonical digest of the mirrored capacity state (the
        delta-substrate ``state_dict`` — twin/deltas.py), the twin's
        ``/v1/state-digest`` value: a restored or replacement mirror
        is correct iff its digest equals the one it replaced. Cheap:
        no device work, safe to poll."""
        from .deltas import state_dict

        with self._lock:
            return config_fingerprint(state_dict(self.replayer._app))

    def applied_seq(self) -> int:
        with self._lock:
            return self.delta_seq

    def _export(self):
        with self._lock:
            rep = self.replayer.report
            agreement = rep.agreement_rate
            backlog = float(len(self._backlog))
            polls = float(self.polls)
            lag = self._lag_locked()
        COUNTERS.gauge("twin_agreement_rate", agreement)
        COUNTERS.gauge("twin_mirror_lag_seconds", round(lag, 6))
        COUNTERS.gauge("twin_backlog", backlog)
        COUNTERS.gauge("twin_polls", polls)

    def degraded_reasons(self) -> List[str]:
        reasons = []
        with self._lock:
            apply_errors = self.apply_errors
            backlog = len(self._backlog)
            lag = self._lag_locked()
        if apply_errors:
            reasons.append(
                f"{apply_errors} delta step(s) could not be applied "
                "(mirror may be stale; see twin_apply_errors_total)"
            )
        if backlog > BACKLOG_DEGRADED:
            reasons.append(
                f"tail backlog {backlog} steps deep "
                f"(> {BACKLOG_DEGRADED}); mirror lag {lag:.1f}s"
            )
        return reasons

    def stats(self) -> dict:
        exhausted = bool(getattr(self.source, "exhausted", False))
        with self._lock:
            rep = self.replayer.report
            app = self.replayer._app
            return {
                "polls": self.polls,
                "flaps": self.flaps,
                "backlog": len(self._backlog),
                "mirrorLagSeconds": round(self._lag_locked(), 6),
                "steps": rep.steps,
                "decisions": rep.decisions,
                "agreementRate": rep.agreement_rate,
                "divergences": rep.divergence_count,
                "warmRecompiles": rep.warm_recompiles,
                "reloads": rep.reloads,
                "deltasApplied": app.applied,
                "deltaSkips": app.skips,
                "deltaSeq": self.delta_seq,
                "applyErrors": self.apply_errors,
                "pendingPods": len(app.pending),
                "nodes": len(app.oracle.nodes),
                "feedExhausted": exhausted,
            }

    # -- state snapshot (the timeline bridge) -------------------------------

    def snapshot_cluster(self):  # simonlint: disable=CONC001 - caller holds self.lock (queries.forecast takes it across the snapshot)
        """The mirrored state as a loadable cluster: current nodes plus
        every committed pod in its bound form — what a capacity
        forecast steps forward from (twin/queries.py) and what
        ``simon apply`` would load if the mirror were written to disk.
        Caller holds the lock."""
        import copy

        from ..models.decode import ResourceTypes

        cluster = ResourceTypes()
        cluster.nodes = [copy.deepcopy(ns.node) for ns in self.oracle.nodes]
        cluster.pods = [
            copy.deepcopy(p) for ns in self.oracle.nodes for p in ns.pods
        ]
        base = self.replayer.cluster
        cluster.pod_disruption_budgets = list(base.pod_disruption_budgets)
        cluster.priority_classes = list(base.priority_classes)
        return cluster


# -- checkpoint capture / materialization (runtime/checkpoint.py) -----------


def capture_mirror(mirror: ClusterMirror):
    """The CheckpointManager ``capture`` hook for a twin mirror: one
    consistent cut under the mirror lock — identity (the base-cluster
    fingerprint the divergence report carries), the applied-step
    sequence, the capacity-state digest, and a payload that
    re-materializes the applicator: nodes, bound pods (per-node, in
    placement order, each stamped with its node), pending pods, and
    the pdb/priority context the oracle rebuild needs."""
    from ..runtime.checkpoint import CheckpointState
    from .deltas import state_dict

    with mirror.lock:
        app = mirror.applicator
        bound = []
        for ns in app.oracle.nodes:
            for p in ns.pods:
                pod = copy.deepcopy(p)
                pod.setdefault("spec", {})["nodeName"] = ns.name
                bound.append(pod)
        payload = {
            "nodes": [copy.deepcopy(ns.node) for ns in app.oracle.nodes],
            "bound": bound,
            "pending": [copy.deepcopy(p) for p in app.pending.values()],
            "pdbs": copy.deepcopy(app.cluster.pod_disruption_budgets),
            "priorityClasses": copy.deepcopy(app.cluster.priority_classes),
        }
        return CheckpointState(
            fingerprint=mirror.replayer.report.fingerprint,
            delta_seq=mirror.delta_seq,
            state_digest=config_fingerprint(state_dict(app)),
            payload=payload,
        )


def twin_materialized_digest(payload: dict) -> str:
    """State digest of a FRESH materialization of a twin checkpoint
    payload: a new oracle-engine applicator over the payload nodes,
    every bound pod re-placed, the pending queue refilled —
    ``state_dict`` is engine-independent (it reads only oracle
    NodeStates), so this digest matching the live mirror's proves the
    payload restores to the same capacity state."""
    from ..models.decode import ResourceTypes
    from .deltas import _own_pod, _pod_key, state_dict

    cold = ResourceTypes()
    cold.nodes = [copy.deepcopy(n) for n in payload.get("nodes", [])]
    cold.pod_disruption_budgets = copy.deepcopy(payload.get("pdbs", []))
    cold.priority_classes = copy.deepcopy(payload.get("priorityClasses", []))
    app = MirrorApplicator(cold, engine="oracle")
    for pod in payload.get("bound", []):
        p = _own_pod(pod)
        app.oracle.place_existing_pod(p)
        app._bound[_pod_key(p)] = (p.get("spec") or {}).get("nodeName") or ""
    for pod in payload.get("pending", []):
        app.pending[_pod_key(pod)] = _own_pod(pod)
    return config_fingerprint(state_dict(app))


def restore_mirror_state(mirror: ClusterMirror, payload: dict, seq: int):
    """Adopt a VERIFIED checkpoint payload as the mirror's state (the
    caller has already proven ``twin_materialized_digest(payload)``
    equals the checkpoint header's digest): rebuild the applicator's
    oracle over the payload nodes, re-place the bound pods, refill the
    pending queue and the bound index, and pin ``delta_seq`` so the
    journal suffix replay skips exactly the absorbed prefix."""
    from .deltas import _own_pod, _pod_key

    with mirror.lock:
        app = mirror.applicator
        app._build([copy.deepcopy(n) for n in payload.get("nodes", [])])
        app.pending.clear()
        app._bound.clear()
        for pod in payload.get("bound", []):
            p = _own_pod(pod)
            app.oracle.place_existing_pod(p)
            app._bound[_pod_key(p)] = (
                (p.get("spec") or {}).get("nodeName") or ""
            )
        for pod in payload.get("pending", []):
            app.pending[_pod_key(pod)] = _own_pod(pod)
        mirror.delta_seq = int(seq)


def replay_mirror_journal(mirror: ClusterMirror, path: str) -> dict:
    """Snapshot-then-suffix bootstrap for a restarted twin (the twin
    analogue of fleet/replay.replay_into_session): restore the newest
    trustable checkpoint generation (refused generations fall back
    loudly, ``ckpt_restore_fallback_total``), then replay the
    journal's step records with ``seq`` past the restored sequence.
    Read-only on the journal file — the caller attaches the mirror's
    append journal (``open_twin_snapshot``) AFTER this returns, so
    replayed steps never re-append."""
    from ..fleet.replay import read_session_events
    from ..runtime.checkpoint import (
        CheckpointMismatch,
        checkpoint_dir,
        list_checkpoints,
        load_checkpoint,
    )
    from ..shadow.log import Step

    t0 = time.monotonic()
    restored = None
    generations = list_checkpoints(checkpoint_dir(path))
    for seq, gen_path in generations:
        try:
            header, payload = load_checkpoint(
                gen_path, expect_fingerprint=mirror.replayer.report.fingerprint
            )
            fresh = twin_materialized_digest(payload)
            if fresh != header["stateDigest"]:
                raise CheckpointMismatch(
                    f"{gen_path}: payload re-materializes to digest "
                    f"{fresh!r}, header claims {header['stateDigest']!r}; "
                    "refusing this generation"
                )
            restore_mirror_state(mirror, payload, header["deltaSeq"])
        except CheckpointMismatch as e:
            COUNTERS.inc("ckpt_restore_fallback_total")
            import logging

            logging.getLogger("simon.twin").warning(
                "twin checkpoint generation refused, falling back to the "
                "previous one (longer replay, never silent wrong state): %s",
                e,
            )
            continue
        COUNTERS.inc("ckpt_restore_total")
        restored = {
            "deltaSeq": int(header["deltaSeq"]),
            "stateDigest": header["stateDigest"],
            "path": gen_path,
        }
        break
    base_seq = restored["deltaSeq"] if restored else 0
    fp = config_fingerprint(
        {"format": "twin-mirror-snapshot", "version": TWIN_SNAPSHOT_VERSION}
    )
    try:
        records, dropped = read_session_events(path, fp)
    except InputError:
        if restored is None:
            raise
        # checkpoint restored but the journal is unreadable: serve the
        # verified snapshot state rather than dying (the suffix since
        # the checkpoint is lost and SAID so)
        records, dropped = [], 0
    summary = {
        "steps": 0,
        "skippedPrefix": 0,
        "checkpoint": restored,
        "dropped": dropped,
    }
    with mirror.lock:
        for rec in records:
            if rec.get("kind") != "mirror" or rec.get("event") != "step":
                continue
            seq = rec.get("seq")
            if isinstance(seq, int) and seq <= base_seq:
                summary["skippedPrefix"] += 1
                continue
            mirror._apply_step(Step.from_record(rec["step"]))
            if isinstance(seq, int):
                # pin to the journaled sequence (an apply error must
                # not let replayed seqs drift from the recorded ones)
                mirror.delta_seq = int(seq)
            summary["steps"] += 1
    COUNTERS.inc("fleet_replay_deltas_total", summary["steps"])
    if summary["skippedPrefix"]:
        COUNTERS.inc(
            "ckpt_restore_deltas_skipped_total", summary["skippedPrefix"]
        )
    if dropped:
        COUNTERS.inc("fleet_replay_torn_tail_total", dropped)
    if restored:
        COUNTERS.gauge(
            "ckpt_restore_seconds", round(time.monotonic() - t0, 6)
        )
    if generations and restored is None:
        import logging

        logging.getLogger("simon.twin").warning(
            "all %d twin checkpoint generation(s) refused; recovering by "
            "full journal replay",
            len(generations),
        )
    return summary
