"""The cluster-delta substrate: one typed vocabulary of live-cluster
state changes and ONE incremental applicator that keeps a warm mirror
current (ROADMAP item 4's core refactor).

Three subsystems previously each carried their own ad-hoc dialect of
"the cluster changed": the shadow replayer's decision-log delta ops
(shadow/log.py), the serve session's implicit full-reload-per-config
posture, and the timeline's event stream (timeline/events.py). This
module is the shared floor under all three:

- ``ClusterDelta`` — six kinds: ``node_join`` / ``node_drain`` (node
  churn), ``pod_bind`` / ``pod_evict`` (scheduled capacity changes),
  ``pod_arrive`` / ``pod_delete`` (pending-queue changes). JSON
  round-trip (``as_record``/``from_record``), lossless conversion
  from the shadow decision-log op dialect (``from_shadow_op``) and to
  timeline events (``deltas_to_events``).

- ``MirrorApplicator`` — mutates a warm ``Oracle`` (and, on the tpu
  engine, its ``TpuEngine``) IN PLACE, one delta at a time: a
  ``pod_bind`` is one incremental ``place_existing_pod`` on a
  copy-on-write ``NodeState``, a ``pod_evict`` one ``evict_pod``, a
  ``node_join`` one ``add_node`` — never a cluster reload, and never
  a re-encode of anything but the affected state (the cross-run
  identity caches of PR 3 keep the pristine ``ClusterStatic`` and
  node templates warm; a probe after a pod delta re-dispatches the
  same compiled scan shapes, so warm deltas cost ZERO jit-cache
  misses — measured by the obs recompile counters, CI-gated in
  tests/test_twin.py). The ONE exception is ``node_drain``: node
  identity is baked into every index and encoding, so a drain is a
  counted state rebuild from the survivors (``twin_delta_reloads_-
  total`` — the same rule the shadow replayer always had for
  ``remove_node``).

- conformance machinery — ``materialize`` folds a delta stream into
  the cold-reload form (final nodes, bound pods in bind order,
  pending pods), ``cold_reload`` builds a fresh applicator from it,
  and ``state_dict`` canonicalizes an applicator's full capacity
  state (per-node pods, request totals, scalars, ports, GPU devices,
  storage VGs, plus the pending queue). The substrate's contract —
  applying any recorded delta stream to a warm mirror is dict-equal
  to a cold reload of the resulting cluster — is an equality between
  two ``state_dict`` values, gated in CI. (Commit-sequence numbers
  are deliberately outside the canonical state: they encode arrival
  history, which a cold reload of the *resulting* cluster does not
  have.)

Consumers: the shadow replayer's ``_apply_delta`` delegates here
(shadow/replay.py), the twin mirror tails a live cluster through it
(twin/mirror.py), ``simon serve`` applies pushed deltas to warm
sessions through the same vocabulary (``POST /v1/cluster-delta``,
serve/session.py), and the twin's capacity forecast steps timeline
windows forward from applicator state (``deltas_to_events`` +
twin/queries.py).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..models.validation import InputError
from ..utils.trace import COUNTERS

NODE_JOIN = "node_join"
NODE_DRAIN = "node_drain"
POD_BIND = "pod_bind"
POD_EVICT = "pod_evict"
POD_ARRIVE = "pod_arrive"
POD_DELETE = "pod_delete"

DELTA_KINDS = (NODE_JOIN, NODE_DRAIN, POD_BIND, POD_EVICT, POD_ARRIVE, POD_DELETE)

#: apply() outcomes (callers map them onto their own counters)
APPLIED = "applied"
SKIPPED = "skipped"
RELOADED = "reloaded"


def _pod_key(pod: dict) -> Tuple[str, str]:
    meta = (pod or {}).get("metadata") or {}
    return (meta.get("namespace") or "default", meta.get("name", ""))


def _own_pod(p: dict) -> dict:
    """Shallow-clone a pod's mutation surface (bind writes
    spec.nodeName / status / metadata.annotations) so applying a delta
    never pollutes the caller's record objects."""
    q = dict(p)
    q["spec"] = dict(p.get("spec") or {})
    meta = dict(p.get("metadata") or {})
    if meta.get("annotations") is not None:
        meta["annotations"] = dict(meta["annotations"])
    q["metadata"] = meta
    if isinstance(q.get("status"), dict):
        q["status"] = dict(q["status"])
    return q


@dataclass
class ClusterDelta:
    """One observed cluster state change.

    ``pod_bind`` carries the pod in its UNBOUND form plus the node the
    scheduler chose (``node_name``) — the applicator writes the
    binding; ``pod_arrive`` carries an unbound pod entering the
    pending queue; ``pod_evict`` / ``pod_delete`` reference pods by
    namespace/name (``pod_evict`` also names the node for a targeted
    walk). ``node_join`` carries the node object, ``node_drain`` its
    name."""

    kind: str
    pod: Optional[dict] = None
    node: Optional[dict] = None
    node_name: str = ""
    namespace: str = "default"
    name: str = ""

    def __post_init__(self):
        if self.kind not in DELTA_KINDS:
            raise InputError(f"unknown cluster-delta kind {self.kind!r}")
        if self.kind in (POD_BIND, POD_ARRIVE):
            if not isinstance(self.pod, dict):
                raise InputError(f"{self.kind} delta has no pod object")
            ns, name = _pod_key(self.pod)
            if not name:
                raise InputError(f"{self.kind} delta pod has no metadata.name")
            self.namespace, self.name = ns, name
        if self.kind == POD_ARRIVE and (self.pod.get("spec") or {}).get("nodeName"):
            raise InputError(
                "pod_arrive delta pod carries spec.nodeName — a bound "
                "arrival is a pod_bind delta"
            )
        if self.kind == POD_BIND and not self.node_name:
            raise InputError("pod_bind delta has no node_name")
        if self.kind == NODE_JOIN:
            if not isinstance(self.node, dict):
                raise InputError("node_join delta has no node object")
            self.node_name = (self.node.get("metadata") or {}).get("name") or ""
            if not self.node_name:
                raise InputError("node_join delta node has no metadata.name")
        if self.kind == NODE_DRAIN and not self.node_name:
            raise InputError("node_drain delta has no node_name")
        if self.kind in (POD_EVICT, POD_DELETE) and not self.name:
            raise InputError(f"{self.kind} delta has no pod name")

    @property
    def pod_key(self) -> Tuple[str, str]:
        return (self.namespace, self.name)

    def as_record(self) -> dict:
        rec: dict = {"kind": self.kind}
        if self.kind in (POD_BIND, POD_ARRIVE):
            rec["pod"] = self.pod
            if self.kind == POD_BIND:
                rec["node"] = self.node_name
        elif self.kind in (POD_EVICT, POD_DELETE):
            rec["namespace"] = self.namespace
            rec["name"] = self.name
            if self.node_name:
                rec["node"] = self.node_name
        elif self.kind == NODE_JOIN:
            rec["node"] = self.node
        else:  # node_drain
            rec["name"] = self.node_name
        return rec

    @classmethod
    def from_record(cls, rec: dict) -> "ClusterDelta":
        if not isinstance(rec, dict):
            raise InputError("cluster-delta record is not an object")
        kind = rec.get("kind")
        if kind in (POD_BIND, POD_ARRIVE):
            return cls(kind=kind, pod=rec.get("pod"),
                       node_name=str(rec.get("node") or ""))
        if kind in (POD_EVICT, POD_DELETE):
            return cls(
                kind=kind,
                namespace=str(rec.get("namespace") or "default"),
                name=str(rec.get("name") or ""),
                node_name=str(rec.get("node") or ""),
            )
        if kind == NODE_JOIN:
            return cls(kind=kind, node=rec.get("node"))
        if kind == NODE_DRAIN:
            return cls(kind=kind, node_name=str(rec.get("name") or ""))
        raise InputError(f"unknown cluster-delta kind {kind!r}")


# -- the shadow decision-log dialect ------------------------------------


def from_shadow_op(op: dict) -> ClusterDelta:
    """One decision-log delta op (shadow/log.py vocabulary) as a
    ClusterDelta. ``place_pod`` splits into pod + node (the pod object
    keeps its recorded form; the applicator re-owns it)."""
    kind = op.get("op")
    if kind == "place_pod":
        pod = op.get("pod") or {}
        node = (pod.get("spec") or {}).get("nodeName") or ""
        unbound = _own_pod(pod)
        unbound["spec"].pop("nodeName", None)
        return ClusterDelta(kind=POD_BIND, pod=unbound, node_name=node)
    if kind == "evict_pod":
        return ClusterDelta(
            kind=POD_EVICT,
            namespace=str(op.get("namespace") or "default"),
            name=str(op.get("name") or ""),
            node_name=str(op.get("node") or ""),
        )
    if kind == "add_node":
        return ClusterDelta(kind=NODE_JOIN, node=op.get("node"))
    if kind == "remove_node":
        return ClusterDelta(kind=NODE_DRAIN, node_name=str(op.get("name") or ""))
    raise InputError(f"unknown delta op {kind!r}")


def steps_to_deltas(steps) -> List[ClusterDelta]:
    """A decision-log step stream folded into pure state deltas: each
    step's delta ops convert 1:1; a decision step becomes the state
    change it caused (``pod_bind`` when the real scheduler placed the
    pod, ``pod_arrive`` when it failed — the pod exists, pending).
    This is the stream the conformance gate replays both warm and
    cold."""
    out: List[ClusterDelta] = []
    for st in steps:
        for op in st.deltas:
            out.append(from_shadow_op(op))
        if st.kind == "decision":
            if st.node:
                out.append(
                    ClusterDelta(kind=POD_BIND, pod=st.pod, node_name=st.node)
                )
            else:
                out.append(ClusterDelta(kind=POD_ARRIVE, pod=st.pod))
    return out


def deltas_to_events(
    deltas: List[ClusterDelta], t0: float = 0.0, spacing: float = 1.0
) -> list:
    """A delta stream as timeline events (timeline/events.py), spaced
    ``spacing`` seconds apart from ``t0`` — the bridge that lets
    timeline windows step forward over recorded or mirrored delta
    streams (the twin forecast seeds its pending queue through this;
    bound pods arrive pinned via their spec.nodeName)."""
    from ..timeline import events as tev

    out = []
    t = t0
    for i, d in enumerate(deltas):
        if d.kind == POD_ARRIVE:
            out.append(tev.Event(time=t, kind=tev.POD_ARRIVAL, seq=i,
                                 pod=copy.deepcopy(d.pod)))
        elif d.kind == POD_BIND:
            pod = _own_pod(d.pod)
            pod["spec"]["nodeName"] = d.node_name
            out.append(tev.Event(time=t, kind=tev.POD_ARRIVAL, seq=i, pod=pod))
        elif d.kind in (POD_EVICT, POD_DELETE):
            out.append(tev.Event(
                time=t, kind=tev.POD_DEPARTURE, seq=i,
                pod_ref=f"{d.namespace}/{d.name}",
            ))
        elif d.kind == NODE_JOIN:
            out.append(tev.Event(time=t, kind=tev.NODE_JOIN, seq=i,
                                 node=copy.deepcopy(d.node)))
        else:  # node_drain
            out.append(tev.Event(time=t, kind=tev.NODE_DRAIN, seq=i,
                                 node_name=d.node_name))
        t += spacing
    return out


# -- the incremental applicator -----------------------------------------


class MirrorApplicator:
    """Owns one warm Oracle (+ optional TpuEngine) and the pending-pod
    queue, and applies ClusterDeltas to them in place.

    The applicator is the ONLY mutation path of a mirrored cluster:
    the shadow replayer, the twin mirror, and the conformance gate all
    route through ``apply``, so the application semantics cannot fork
    per subsystem. ``apply`` returns APPLIED / SKIPPED / RELOADED —
    SKIPPED covers the live-tail races a resident mirror must survive
    (a bind naming a node the mirror never saw, an evict for a pod
    already gone), counted, never fatal."""

    def __init__(self, cluster, engine: str = "tpu"):
        if engine not in ("tpu", "oracle"):
            raise InputError(f"unknown mirror engine {engine!r}")
        self.cluster = cluster
        self.engine_kind = engine
        self.reloads = 0
        self.skips = 0
        self.applied = 0
        #: pending (observed-but-unbound) pods, insertion-ordered
        self.pending: "Dict[Tuple[str, str], dict]" = {}
        #: bound pods by key -> node name (re-bind = evict + place)
        self._bound: Dict[Tuple[str, str], str] = {}
        self._build(list(cluster.nodes))

    def _build(self, nodes: List[dict]):
        from ..scheduler.oracle import Oracle

        self.oracle = Oracle(
            nodes,
            pdbs=self.cluster.pod_disruption_budgets,
            priority_classes=self.cluster.priority_classes,
        )
        self.engine = None
        if self.engine_kind == "tpu":
            from ..scheduler.engine import TpuEngine

            self.engine = TpuEngine(self.oracle)

    # -- application -------------------------------------------------------

    def apply(self, delta: ClusterDelta) -> str:
        """Apply one delta; returns APPLIED, SKIPPED, or RELOADED."""
        from ..runtime import inject as _inject

        # chaos seam (runtime/inject.py): a fault here lands exactly
        # where a torn feed or corrupt record would
        _inject.fire("twin.apply_delta", kind=delta.kind)
        out = self._apply(delta)
        COUNTERS.inc(f"twin_delta_{delta.kind}_total")
        if out == SKIPPED:
            self.skips += 1
            COUNTERS.inc("twin_delta_skips_total")
        else:
            self.applied += 1
            COUNTERS.inc("twin_deltas_applied_total")
            if out == RELOADED:
                self.reloads += 1
                COUNTERS.inc("twin_delta_reloads_total")
        return out

    def _apply(self, delta: ClusterDelta) -> str:
        kind = delta.kind
        if kind == POD_BIND:
            return self._bind(delta)
        if kind == POD_EVICT:
            return self._evict(delta.pod_key, delta.node_name or None)
        if kind == POD_ARRIVE:
            self.pending[delta.pod_key] = _own_pod(delta.pod)
            return APPLIED
        if kind == POD_DELETE:
            if self.pending.pop(delta.pod_key, None) is None:
                return SKIPPED
            return APPLIED
        if kind == NODE_JOIN:
            if delta.node_name in self.oracle.node_index:
                return SKIPPED  # re-join of a known node
            self.oracle.add_node(delta.node)
            return APPLIED
        # node_drain
        return self._drain(delta.node_name)

    def _bind(self, delta: ClusterDelta) -> str:
        oracle = self.oracle
        if delta.node_name not in oracle.node_index:
            # bound to a node the mirror never saw (live-tail race /
            # dangling pre-bind): tracked by the apiserver only, never
            # by the scheduler — skip, counted
            return SKIPPED
        key = delta.pod_key
        if key in self._bound:
            # a re-bind of a live key (delete+recreate collapsed into
            # one poll): evict the stale binding first
            self._evict(key, self._bound.get(key))
        pod = _own_pod(delta.pod)
        pod["spec"]["nodeName"] = delta.node_name
        oracle.place_existing_pod(pod)
        self._bound[key] = delta.node_name
        self.pending.pop(key, None)
        return APPLIED

    def _evict(self, key: Tuple[str, str], node_name: Optional[str]) -> str:
        # an evict can also target a PENDING pod (a failed-then-deleted
        # pod disappearing from the tail): removal from the queue is a
        # real application, not a skip
        if key not in self._bound and self.pending.pop(key, None) is not None:
            return APPLIED
        oracle = self.oracle
        # the named node first (the common case), then the bound index,
        # then a full walk: a live tail can name a STALE node (the pod
        # rebound within one poll window) and the cold-reload side
        # drops the pod unconditionally — the warm side must find it
        # wherever it actually sits or conformance forks
        names = []
        for cand in (node_name, self._bound.get(key)):
            if cand and cand not in names:
                names.append(cand)
        names.extend(n for n in oracle.node_index if n not in names)
        for name in names:
            idx = oracle.node_index.get(name or "")
            if idx is None:
                continue
            ns = oracle.nodes[idx]
            for p in ns.pods:
                if _pod_key(p) == key:
                    oracle.evict_pod(ns, p)
                    self._bound.pop(key, None)
                    return APPLIED
        return SKIPPED

    def _drain(self, name: str) -> str:
        """Node identity is baked into every index and encoding, so a
        drain is the one delta that rebuilds: survivors re-place their
        committed pods on a fresh oracle (pods of the drained node die
        with it). Counted — the cost is visible, never hidden."""
        oracle = self.oracle
        if name not in oracle.node_index:
            raise InputError(f"node_drain delta names unknown node {name!r}")
        survivors = [ns for ns in oracle.nodes if ns.name != name]
        nodes = [ns.node for ns in survivors]
        committed = [p for ns in survivors for p in ns.pods]
        self._build(nodes)
        self._bound = {
            k: n for k, n in self._bound.items() if n != name
        }
        for p in committed:
            self.oracle.place_existing_pod(p)
        return RELOADED

    # -- decision integration ----------------------------------------------

    def commit_decision(self, pod: dict, node_idx: int) -> None:
        """Commit a REAL scheduler decision into the mirror (the
        replayer's commit-reality path): the same binding code the
        serial engine uses, with the bound-key index updated so later
        deltas referencing this pod resolve incrementally."""
        from ..runtime import inject as _inject

        # chaos seam: a decision commit IS a pod_bind delta in
        # substrate terms — same fault surface as apply()
        _inject.fire("twin.apply_delta", kind="decision_commit")
        if self.engine is not None:
            self.engine.commit_host(pod, node_idx)
        else:
            self.oracle._reserve_and_bind(pod, self.oracle.nodes[int(node_idx)])
        key = _pod_key(pod)
        self._bound[key] = self.oracle.nodes[int(node_idx)].name
        self.pending.pop(key, None)

    def note_pending(self, pod: dict) -> None:
        """Track a pod the real scheduler FAILED to place: it exists,
        pending — the population the twin's capacity forecast requeues
        (queries.py)."""
        self.pending[_pod_key(pod)] = _own_pod(pod)

    # -- canonical state ---------------------------------------------------

    def state_dict(self) -> dict:
        return state_dict(self)


def state_dict(app: MirrorApplicator) -> dict:
    """Canonical capacity state of a mirrored cluster: everything the
    scheduler reads when it filters and scores, in a deterministic
    JSON-able form. Two mirrors with equal state_dicts answer every
    what-if question identically — this equality IS the delta-vs-cold-
    reload conformance contract."""
    from ..models import storage as stor

    nodes = {}
    for ns in app.oracle.nodes:
        entry: dict = {
            "pods": sorted(
                "%s/%s" % _pod_key(p) for p in ns.pods
            ),
            "mcpu": ns.req_mcpu,
            "mem": ns.req_mem,
            "eph": ns.req_eph,
            "floorMcpu": ns.req_floor_mcpu,
            "floorMem": ns.req_floor_mem,
            "nzMcpu": ns.nz_mcpu,
            "nzMem": ns.nz_mem,
            "scalars": {k: v for k, v in sorted(ns.req_scalar.items()) if v},
            "ports": sorted(list(t) for t in ns.used_ports),
        }
        if ns.gpu is not None:
            entry["gpu"] = {
                "used": list(ns.gpu.used),
                "allocatable": ns.gpu.allocatable_count(),
                "gpuCount": ns.alloc_int(stor.GPU_COUNT_ANNO),
            }
        if ns.storage is not None:
            entry["storage"] = {
                "vgs": [int(vg.requested) for vg in ns.storage.vgs],
                "devices": [bool(d.is_allocated) for d in ns.storage.devices],
            }
        nodes[ns.name] = entry
    return {
        "nodes": nodes,
        "pending": sorted("%s/%s" % k for k in app.pending),
    }


# -- cold-reload conformance --------------------------------------------


@dataclass
class Materialized:
    """The cold-reload form of (base cluster, delta stream): the final
    node list, the bound pods in bind order (each carrying its
    spec.nodeName), and the still-pending pods."""

    nodes: List[dict] = field(default_factory=list)
    bound: List[dict] = field(default_factory=list)
    pending: List[dict] = field(default_factory=list)


def materialize(base_nodes: List[dict], deltas: List[ClusterDelta]) -> Materialized:
    """Fold a delta stream over a base node list into the resulting
    cluster — the input a cold full reload would load. Mirrors the
    applicator's skip semantics exactly (a bind to a never-seen node
    is dropped in both; pods of a drained node die with it), so warm
    and cold diverge only if the applicator has a bug."""
    nodes: "Dict[str, dict]" = {}
    for n in base_nodes:
        name = (n.get("metadata") or {}).get("name", "")
        nodes[name] = n
    bound: "Dict[Tuple[str, str], dict]" = {}
    pending: "Dict[Tuple[str, str], dict]" = {}
    for d in deltas:
        if d.kind == NODE_JOIN:
            nodes.setdefault(d.node_name, d.node)
        elif d.kind == NODE_DRAIN:
            if d.node_name not in nodes:
                raise InputError(
                    f"node_drain delta names unknown node {d.node_name!r}"
                )
            nodes.pop(d.node_name)
            for key in [
                k for k, p in bound.items()
                if (p.get("spec") or {}).get("nodeName") == d.node_name
            ]:
                bound.pop(key)
        elif d.kind == POD_BIND:
            if d.node_name not in nodes:
                continue  # the applicator's counted skip
            pod = _own_pod(d.pod)
            pod["spec"]["nodeName"] = d.node_name
            # rebind: drop the stale entry so bind ORDER stays the
            # replay order of the surviving binding
            bound.pop(d.pod_key, None)
            bound[d.pod_key] = pod
            pending.pop(d.pod_key, None)
        elif d.kind == POD_EVICT:
            if bound.pop(d.pod_key, None) is None:
                pending.pop(d.pod_key, None)
        elif d.kind == POD_ARRIVE:
            pending[d.pod_key] = _own_pod(d.pod)
        else:  # pod_delete
            pending.pop(d.pod_key, None)
    return Materialized(
        nodes=list(nodes.values()),
        bound=list(bound.values()),
        pending=list(pending.values()),
    )


def cold_reload(cluster, deltas: List[ClusterDelta], engine: str = "oracle") -> MirrorApplicator:
    """Build the ground-truth applicator: a fresh Oracle over the
    materialized node list, every surviving bound pod placed in bind
    order, the pending queue rebuilt. ``state_dict(cold_reload(...))``
    is what a warm mirror must equal after applying the same stream."""
    m = materialize(cluster.nodes, deltas)
    cold_cluster = cluster.copy()
    cold_cluster.nodes = m.nodes
    app = MirrorApplicator(cold_cluster, engine=engine)
    for pod in m.bound:
        # deep-own: place_existing_pod may stamp GPU annotations
        p = _own_pod(pod)
        app.oracle.place_existing_pod(p)
        app._bound[_pod_key(p)] = (p.get("spec") or {}).get("nodeName") or ""
    for pod in m.pending:
        app.pending[_pod_key(pod)] = _own_pod(pod)
    return app
