"""On-demand queries against the live mirror — what-if, drain safety,
N+K survivability, capacity forecast — all answered from WARM state.

Every query follows the same shape: under the mirror lock, build the
question as (unbound pods, node-validity mask), answer it with ONE
masked scan dispatch over the warm engine's current dynamic state
(``TpuEngine.scan_active(active, valid=...)`` — the chaos substrate's
per-scenario node mask, so a drain question is literally an outage
scenario row evaluated against live state), then mirror the placements
into a scratch host oracle for failure reasons that read their own
step's state (the engine-replay contract of scheduler/engine.py).
Nothing commits: the mirror is read, never mutated, and the compiled
scan re-dispatches warm shapes (zero jit-cache misses on repeat query
shapes — the serve property, now against live state).

The capacity forecast is the timeline bridge: the mirrored state
snapshots into a loadable cluster (``ClusterMirror.snapshot_cluster``),
the mirror's pending pods requeue as arrivals THROUGH the delta
substrate (``deltas_to_events``), synthetic future arrivals extend the
stream, and the windowed stepper (timeline/stepper.py) steps it
forward — "what happens to pending at 2x the current arrival rate"
answered from the cluster as it is right now.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models.validation import InputError
from ..utils.trace import COUNTERS

#: forecast arrival-stream cap (one query must stay bounded even at a
#: silly rate x horizon product); overflow is reported, never silent
FORECAST_MAX_ARRIVALS = 5000


def _pod_key(pod: dict) -> Tuple[str, str]:
    meta = (pod or {}).get("metadata") or {}
    return (meta.get("namespace") or "default", meta.get("name", ""))


def _unbind(pod: dict) -> dict:
    """A committed pod back in its schedulable form (the evict_pod
    strip: binding, phase, GPU device stamp)."""
    from ..models import storage as stor

    q = copy.deepcopy(pod)
    (q.get("spec") or {}).pop("nodeName", None)
    q.pop("status", None)
    anno = (q.get("metadata") or {}).get("annotations")
    if anno:
        anno.pop(stor.GPU_INDEX_ANNO, None)
    return q


def _expand_apps(apps, nodes: List[dict]) -> List[dict]:
    """Expand request apps exactly like a standalone run (the serve
    Session's expansion: counter reset, apps in order, each app's pods
    through the queue sorts)."""
    from ..models import workloads as wl
    from ..scheduler.core import _sort_app_pods

    wl.reset_name_counter()
    pods: List[dict] = []
    for app in apps:
        app_pods = wl.generate_valid_pods_from_app(app.name, app.resource, nodes)
        pods.extend(_sort_app_pods(app_pods))
    return pods


def _scan_pods(mirror, pods: List[dict], valid: Optional[np.ndarray]) -> np.ndarray:
    """Placements for `pods` against the mirror's CURRENT state with
    candidate nodes gated by `valid`: one warm masked-scan dispatch on
    the tpu engine, or the serial probe walk on the host oracle.
    Returns placements[P]: node index, -1 unschedulable, or -3 for
    dangling pods (unknown spec.nodeName — tracked, never scheduled)."""
    oracle = mirror.oracle
    node_index = oracle.node_index
    out = np.full(len(pods), -3, dtype=np.int64)
    batch_idx = []
    for i, pod in enumerate(pods):
        name = (pod.get("spec") or {}).get("nodeName")
        if name and name not in node_index:
            continue
        batch_idx.append(i)
    if not batch_idx:
        return out
    engine = mirror.engine
    if engine is not None:
        COUNTERS.inc("twin_query_dispatches_total")
        # the twin IS the incremental design: the mirror's committed
        # pods are warm state, the query pods are the dispatched
        # suffix — account them in the same counter family the serve
        # committed scan feeds (incremental/store.incremental_block).
        # The O(nodes) pod-count walk is noise next to the query's own
        # scratch replay (which re-places every committed pod)
        COUNTERS.inc("incremental_suffix_pods_total", len(batch_idx))
        COUNTERS.inc(
            "incremental_prefix_reused_pods_total",
            sum(len(ns.pods) for ns in oracle.nodes),
        )
        engine.begin_batch([pods[i] for i in batch_idx])
        placements = engine.scan_active(
            np.ones(len(batch_idx), dtype=bool), valid=valid
        )
        for pos, i in enumerate(batch_idx):
            out[i] = int(placements[pos])
        return out
    # serial probe walk (engine="oracle"): same semantics as the scan —
    # sequential commit on a scratch oracle, NO preemption (queries are
    # probes; the read-only contract of shadow/replay.py)
    scratch = _scratch_oracle(mirror, valid)
    for i in batch_idx:
        pod = copy.deepcopy(pods[i])
        name = (pod.get("spec") or {}).get("nodeName")
        if name:
            scratch.place_existing_pod(pod)
            out[i] = node_index[name]
            continue
        feasible, _reasons, _codes = scratch._find_feasible(pod)
        if valid is not None:
            # cordoned nodes exist but take no new pods (the scan path
            # gets this from its node_valid mask)
            feasible = [ns for ns in feasible if bool(valid[ns.index])]
        if not feasible:
            out[i] = -1
            continue
        scores = scratch._prioritize(pod, feasible)
        best, best_score = feasible[0], scores[0]
        for ns, sc in zip(feasible[1:], scores[1:]):
            if sc > best_score:
                best, best_score = ns, sc
        scratch._reserve_and_bind(pod, best)
        out[i] = node_index[best.name]
    return out


def _scratch_oracle(mirror, valid: Optional[np.ndarray], exclude_pods=frozenset()):
    """A disposable host oracle mirroring the current committed state:
    same node list (so placements carry over by index), every committed
    pod re-placed except `exclude_pods` keys, nodes outside `valid`
    left empty (their pods are the displaced set being rescheduled).
    Mutating it never touches the mirror."""
    from ..scheduler.oracle import Oracle

    live = mirror.oracle
    base = mirror.replayer.cluster
    scratch = Oracle(
        [ns.node for ns in live.nodes],
        pdbs=base.pod_disruption_budgets,
        priority_classes=base.priority_classes,
    )
    for idx, ns in enumerate(live.nodes):
        if valid is not None and not bool(valid[idx]):
            continue
        for p in ns.pods:
            if _pod_key(p) in exclude_pods:
                continue
            scratch.place_existing_pod(copy.deepcopy(p))
    return scratch


def _failure_reason(scratch, pod: dict, valid: Optional[np.ndarray], n_masked: int) -> str:
    """The standalone-run failure message at this pod's own step state,
    with masked-off nodes accounted as a scenario reason (the drain /
    outage questions cordon nodes; the message must say so instead of
    pretending the cluster shrank)."""
    from ..scheduler.oracle import Oracle

    reasons: Dict[str, int] = {}
    ctx = scratch._pod_filter_ctx(pod)
    pre = scratch._prefilter(pod)
    for idx, ns in enumerate(scratch.nodes):
        if valid is not None and not bool(valid[idx]):
            continue
        r = scratch._check_node(pod, ctx, pre, ns)
        if r is not None:
            reasons[r[0]] = reasons.get(r[0], 0) + 1
    if n_masked:
        reasons["node(s) cordoned in this scenario"] = n_masked
    return Oracle._failure_message(pod, reasons)


def _answer(mirror, pods, placements, valid, exclude=frozenset()) -> dict:
    """Mirror scan placements into a scratch oracle in scan order and
    produce the canonical answer: placements for scheduled pods,
    standalone-formula reasons for failures (computed at each
    failure's own step state — a later pod's failure sees the earlier
    pods' placements, exactly like a standalone run)."""
    scratch = _scratch_oracle(mirror, valid, exclude_pods=exclude)
    n_masked = 0 if valid is None else int((~np.asarray(valid, bool)).sum())
    placed, failed, dangling = [], [], []
    for i, pod in enumerate(pods):
        place = int(placements[i])
        ns_name, name = _pod_key(pod)
        pod2 = copy.deepcopy(pod)
        if place == -3:
            dangling.append({"namespace": ns_name, "name": name})
            continue
        if (pod.get("spec") or {}).get("nodeName"):
            scratch.place_existing_pod(pod2)
            placed.append(
                {"namespace": ns_name, "name": name,
                 "node": pod["spec"]["nodeName"], "pinned": True}
            )
        elif place < 0:
            failed.append({
                "namespace": ns_name,
                "name": name,
                "reason": _failure_reason(scratch, pod2, valid, n_masked),
            })
        else:
            node = scratch.nodes[place]
            scratch._reserve_and_bind(pod2, node)
            placed.append(
                {"namespace": ns_name, "name": name, "node": node.name}
            )
    return {
        "success": not failed,
        "placed": len(placed),
        "failedCount": len(failed),
        "placements": placed,
        "unscheduledPods": failed,
        "danglingPods": dangling,
    }


# -- the four queries ----------------------------------------------------


def whatif(mirror, apps) -> dict:
    """POST /v1/whatif: would these apps fit RIGHT NOW? One warm scan
    of the expanded request against current mirrored state."""
    with mirror.lock:
        COUNTERS.inc("twin_whatif_total")
        pods = _expand_apps(apps, [ns.node for ns in mirror.oracle.nodes])
        placements = _scan_pods(mirror, pods, valid=None)
        out = _answer(mirror, pods, placements, valid=None)
        out["kind"] = "whatif"
        out["mirror"] = mirror.stats()
        return out


def resolve_drain_set(mirror, nodes=(), selector=None) -> List[int]:
    """Node indices to cordon: explicit names plus a label selector
    (``{"rack": "r7"}`` cordons rack 7). Caller holds the lock."""
    oracle = mirror.oracle
    picked = set()
    for name in nodes or ():
        idx = oracle.node_index.get(str(name))
        if idx is None:
            raise InputError(f"drain names unknown node {name!r}")
        picked.add(int(idx))
    if selector:
        if not isinstance(selector, dict):
            raise InputError("drain selector must be an object of node labels")
        for idx, ns in enumerate(oracle.nodes):
            labels = ns.labels
            if all(labels.get(k) == v for k, v in selector.items()):
                picked.add(idx)
    if not picked:
        raise InputError("drain resolved no nodes (names empty, selector matched nothing)")
    if len(picked) >= len(oracle.nodes):
        raise InputError("drain would cordon every node in the cluster")
    return sorted(picked)


def _evaluate_outage(mirror, drained: List[int]) -> dict:
    """One outage scenario against live state: pods of the drained
    nodes become the displaced set (daemonset-owned pods die with the
    node — the chaos displacement rule), the scan re-places them with
    the drained nodes masked invalid, the scratch replay yields
    reasons. Caller holds the lock."""
    from ..models.kubeclient import _owned_by_daemonset

    oracle = mirror.oracle
    valid = np.ones(len(oracle.nodes), dtype=bool)
    valid[drained] = False
    displaced, lost_ds = [], 0
    exclude = set()
    for idx in drained:
        for p in oracle.nodes[idx].pods:
            if _owned_by_daemonset(p):
                lost_ds += 1
                continue
            displaced.append(_unbind(p))
            exclude.add(_pod_key(p))
    placements = _scan_pods(mirror, displaced, valid=valid)
    out = _answer(mirror, displaced, placements, valid=valid, exclude=exclude)
    out["drainedNodes"] = [oracle.nodes[i].name for i in drained]
    out["displaced"] = len(displaced)
    out["lostDaemonSetPods"] = lost_ds
    out["safe"] = out["success"]
    return out


def drain(mirror, nodes=(), selector=None) -> dict:
    """POST /v1/drain: can I cordon these nodes (this rack) right now
    without stranding their pods? The displaced pods re-simulate
    against the remaining live capacity via the chaos substrate's
    node-validity mask — one warm dispatch."""
    with mirror.lock:
        COUNTERS.inc("twin_drain_total")
        drained = resolve_drain_set(mirror, nodes=nodes, selector=selector)
        out = _evaluate_outage(mirror, drained)
        out["kind"] = "drain"
        out["mirror"] = mirror.stats()
        return out


def nplusk(mirror, k: int = 1, trials: int = 32, seed: int = 1) -> dict:
    """POST /v1/nplusk: does the LIVE placement survive any K-node
    outage? Exhaustive when the scenario space fits in ``trials``,
    seeded-sampled otherwise (resilience/chaos.sampled_failure_sets —
    the N+K machinery of `simon chaos`, pointed at mirrored state)."""
    from ..resilience.chaos import sampled_failure_sets

    if k < 1:
        raise InputError(f"nplusk k must be >= 1, got {k}")
    if trials < 1:
        raise InputError(f"nplusk trials must be >= 1, got {trials}")
    with mirror.lock:
        COUNTERS.inc("twin_nplusk_total")
        n = len(mirror.oracle.nodes)
        if k >= n:
            raise InputError(f"cannot fail {k} of {n} node(s)")
        combos, mode = sampled_failure_sets(list(range(n)), k, trials, seed)
        survived = 0
        worst = None
        scenarios = []
        for combo in combos:
            res = _evaluate_outage(mirror, list(combo))
            ok = res["safe"]
            survived += 1 if ok else 0
            scenarios.append({
                "nodes": res["drainedNodes"],
                "safe": ok,
                "displaced": res["displaced"],
                "unplaced": res["failedCount"],
            })
            if not ok and (worst is None or res["failedCount"] > worst["unplaced"]):
                worst = scenarios[-1]
        return {
            "kind": "nplusk",
            "k": k,
            "mode": mode,
            "scenarios": len(combos),
            "survived": survived,
            "survivable": survived == len(combos),
            "worst": worst,
            "outages": scenarios,
            "mirror": mirror.stats(),
        }


def forecast(
    mirror,
    horizon_s: float,
    arrival_rate: Optional[float] = None,
    rate_scale: float = 1.0,
    seed: int = 1,
    policy: str = "static:0",
    cadence_s: float = 60.0,
    warmup_s: float = 0.0,
    max_nodes: int = 0,
    new_node_spec: Optional[dict] = None,
    engine: str = "oracle",
    mean_lifetime_s: float = 600.0,
    budget=None,
) -> dict:
    """POST /v1/forecast: timeline windows stepped forward from the
    CURRENT mirrored state. The mirror's pending pods requeue at t=0
    (through the delta substrate), synthetic arrivals extend the
    stream at ``arrival_rate`` (default: the observed decision rate of
    the tail, scaled by ``rateScale``), and the windowed stepper races
    the requested autoscaler policy over it."""
    import time as _time

    from ..timeline.autoscaler import parse_policies
    from ..timeline.compare import run_policies
    from ..timeline.events import EventHeap, SyntheticSpec, generate_synthetic
    from .deltas import POD_ARRIVE, ClusterDelta, deltas_to_events

    if horizon_s <= 0:
        raise InputError(f"forecast horizon must be > 0s, got {horizon_s}")
    if rate_scale <= 0:
        raise InputError(f"forecast rateScale must be > 0, got {rate_scale}")
    with mirror.lock:
        COUNTERS.inc("twin_forecast_total")
        snapshot = mirror.snapshot_cluster()
        pending = [copy.deepcopy(p) for p in mirror.applicator.pending.values()]
        decisions = mirror.replayer.report.decisions
        uptime = max(_time.monotonic() - mirror.started_at, 1e-9)
    rate = arrival_rate
    if rate is None:
        observed = decisions / uptime
        rate = observed * rate_scale
    else:
        rate = rate * rate_scale
    arrivals = int(rate * horizon_s)
    truncated = False
    if arrivals > FORECAST_MAX_ARRIVALS:
        arrivals, truncated = FORECAST_MAX_ARRIVALS, True
    if arrivals <= 0 and not pending:
        return {
            "kind": "forecast",
            "horizonSeconds": horizon_s,
            "arrivalRate": rate,
            "arrivals": 0,
            "pendingSeeded": 0,
            "policies": [],
            "note": "nothing to forecast: no pending pods and a zero arrival rate",
        }
    node_names = [
        (n.get("metadata") or {}).get("name", "") for n in snapshot.nodes
    ]
    # pending pods requeue at t=0 through the substrate bridge; seqs
    # re-stamp in push order so merged pending + synthetic streams
    # stay a canonical, strictly-ordered trace
    heap = EventHeap()
    for ev in deltas_to_events(
        [ClusterDelta(kind=POD_ARRIVE, pod=p) for p in pending],
        t0=0.0,
        spacing=0.0,
    ):
        ev.seq = -1
        heap.push(ev)
    if arrivals > 0:
        spec = SyntheticSpec(
            arrivals=arrivals,
            arrival_rate=rate,
            mean_lifetime_s=mean_lifetime_s,
            seed=seed,
        )
        for ev in generate_synthetic(spec, node_names):
            if ev.time <= horizon_s:
                ev.seq = -1
                heap.push(ev)
    events = heap.drain()
    cmp_ = run_policies(
        snapshot,
        events,
        parse_policies([policy]),
        new_node_spec=new_node_spec,
        max_nodes=max_nodes,
        cadence_s=cadence_s,
        warmup_s=warmup_s,
        engine=engine,
        budget=budget,
    )
    out = {
        "kind": "forecast",
        "horizonSeconds": horizon_s,
        "arrivalRate": round(rate, 6),
        "arrivals": arrivals,
        "truncated": truncated,
        "pendingSeeded": len(pending),
        "windows": cmp_.windows,
        "dispatches": cmp_.dispatches,
        "engine": cmp_.engine,
        "policies": [
            {
                "policy": tl.policy,
                "final": tl.final.as_dict() if tl.final else None,
                "peakPending": tl.peak_pending,
                "peakNodes": tl.peak_nodes,
                "decisions": len(tl.decisions),
                "displaced": tl.displaced_total,
            }
            for tl in cmp_.policies
        ],
    }
    return out
