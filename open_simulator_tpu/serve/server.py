"""`simon serve` — the long-lived what-if scheduling daemon.

JSON-over-HTTP API (docs/SERVING.md):

- ``POST /v1/simulate`` — body is either a JSON object
  ``{"apps": [{"name": ..., "yaml": "..."}], "deadlineSeconds": N,
  "trace": bool}`` or raw YAML (treated as one unnamed app). Replies
  200 with the canonical simulate answer (byte-identical to a
  standalone ``simulate()`` of the same request), 400 on undecodable
  input, 503 with a machine-readable PARTIAL body when shed
  (queue full / draining / queue-expired deadline).
- ``GET /healthz`` — liveness + the loaded cluster's fingerprint.
- ``GET /metrics`` — Prometheus text: QPS, queue depth, batch fill,
  latency p50/p95, shed and dispatch counters.

Lifecycle: SIGTERM (or SIGINT) stops intake, drains in-flight and
queued requests through the coalescer, and exits 0; if
``--drain-timeout`` expires first, leftovers are shed and the exit
code is 3 (the deadline-partial code — docs/ROBUSTNESS.md).
"""

from __future__ import annotations

import json
import logging
import signal
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from ..models.decode import ResourceTypes, decode_yaml_content
from ..obs import telemetry
from ..runtime.budget import Budget
from ..runtime.errors import EXIT_OK, EXIT_PARTIAL_DEADLINE
from ..scheduler.core import AppResource
from ..utils.trace import COUNTERS
from .admission import (
    AdmissionController,
    estimate_request_pods,
    sanitize_tenant,
)
from .coalescer import Coalescer, PendingRequest
from .session import Session, WhatIfRequest
from .sessions import SessionCache, open_snapshot

log = logging.getLogger(__name__)

# wait bound for a handler thread whose request IS being evaluated: the
# dispatcher always answers (even shed/error paths), so this only trips
# if the dispatcher thread died — answer 500 instead of hanging the
# client transport forever
_RESULT_WAIT_SLACK_S = 600.0


def parse_request_body(raw: bytes, content_type: str):
    """-> (WhatIfRequest, deadline_s or None, want_trace). Raises
    ValueError on undecodable input (the handler answers 400).

    The JSON envelope is recognized by Content-Type OR by shape (a
    JSON object with an "apps" key): a client that forgets the
    Content-Type header must not have its envelope silently YAML-
    decoded into an empty workload and answered 200 "success" —
    a wrong answer indistinguishable from "everything fits"."""
    deadline = None
    want_trace = False
    doc = None
    if "json" in (content_type or "").lower():
        try:
            doc = json.loads(raw.decode("utf-8"))
        except (UnicodeDecodeError, ValueError) as e:
            raise ValueError(f"body is not valid JSON: {e}") from e
        if not isinstance(doc, dict):
            raise ValueError("JSON body must be an object")
    else:
        try:
            sniffed = json.loads(raw.decode("utf-8"))
            if isinstance(sniffed, dict) and "apps" in sniffed:
                doc = sniffed
        except (UnicodeDecodeError, ValueError):  # noqa: S110 - sniff only: a non-JSON body is the normal raw-YAML case, decoded (with real errors) just below
            pass
    if doc is not None:
        if doc.get("deadlineSeconds") is not None:
            deadline = float(doc["deadlineSeconds"])
            if deadline <= 0:
                raise ValueError("deadlineSeconds must be > 0")
        want_trace = bool(doc.get("trace", False))
        apps_spec = doc.get("apps")
        if not isinstance(apps_spec, list) or not apps_spec:
            raise ValueError('JSON body needs a non-empty "apps" list')
        apps: List[AppResource] = []
        for i, a in enumerate(apps_spec):
            if not isinstance(a, dict) or not isinstance(a.get("yaml"), str):
                raise ValueError(f'apps[{i}] needs a "yaml" string')
            apps.append(
                AppResource(
                    name=str(a.get("name") or f"app-{i}"),
                    resource=_decode_app_yaml(a["yaml"], i),
                )
            )
        return (
            WhatIfRequest(
                apps=apps, tenant=sanitize_tenant(doc.get("tenant"))
            ),
            deadline,
            want_trace,
        )
    # raw YAML: one unnamed app
    try:
        text = raw.decode("utf-8")
    except UnicodeDecodeError as e:
        raise ValueError(f"body is not UTF-8 YAML: {e}") from e
    resource = _decode_app_yaml(text, 0)
    if all(not getattr(resource, f) for f in vars(resource)):
        # parsed, but nothing simulatable: almost certainly a malformed
        # request (unknown kinds, or a JSON envelope that failed the
        # shape sniff) — a 200 for an empty workload would be a wrong
        # answer, not an answer
        raise ValueError(
            "body decoded to no recognized Kubernetes objects; send "
            'either k8s YAML or the {"apps": [...]} JSON envelope'
        )
    return (
        WhatIfRequest(apps=[AppResource(name="app-0", resource=resource)]),
        deadline,
        want_trace,
    )


def _decode_app_yaml(text: str, i: int) -> ResourceTypes:
    import yaml

    try:
        return decode_yaml_content([text])
    except yaml.YAMLError as e:
        raise ValueError(f"apps[{i}]: invalid YAML: {e}") from e


def render_metrics(coalescer: Coalescer, slo_engine=None) -> bytes:
    """Prometheus text exposition of the process-wide counters
    (utils/trace.COUNTERS)."""
    snap = COUNTERS.snapshot()
    counts = snap["counts"]
    lines = []

    def metric(name, kind, help_text, value):
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name} {value}")

    metric(
        "simon_serve_requests_total", "counter",
        "Requests answered (any status).", counts.get("serve_requests_total", 0),
    )
    metric(
        "simon_serve_shed_total", "counter",
        "Requests shed (overload, drain, or queue-expired deadline).",
        counts.get("serve_shed_total", 0),
    )
    metric(
        "simon_serve_shed_overload_total", "counter",
        "Sheds due to a full queue.", counts.get("serve_shed_overload_total", 0),
    )
    metric(
        "simon_serve_shed_deadline_total", "counter",
        "Sheds due to a deadline that expired in the queue.",
        counts.get("serve_shed_deadline_total", 0),
    )
    metric(
        "simon_serve_device_dispatches_total", "counter",
        "Batched device dispatches (one per coalesced scan chunk).",
        counts.get("serve_device_dispatches_total", 0),
    )
    metric(
        "simon_serve_batches_total", "counter",
        "Coalescer ticks that evaluated at least one request.",
        counts.get("serve_batches_total", 0),
    )
    metric(
        "simon_serve_batch_errors_total", "counter",
        "Coalescer ticks that failed and answered 500.",
        counts.get("serve_batch_errors_total", 0),
    )
    metric(
        "simon_serve_queue_depth", "gauge",
        "Requests currently queued.", coalescer.depth,
    )
    metric(
        "simon_serve_batch_fill_mean", "gauge",
        "Mean requests per coalesced tick (recent window).",
        round(COUNTERS.mean("serve_batch_fill"), 4),
    )
    metric(
        "simon_serve_qps", "gauge",
        "Completions per second over the trailing 60s.",
        round(COUNTERS.rate("serve_completions"), 4),
    )
    metric(
        "simon_serve_latency_p50_seconds", "gauge",
        "Median request latency (recent window).",
        round(COUNTERS.percentile("serve_latency_seconds", 50), 6),
    )
    metric(
        "simon_serve_latency_p95_seconds", "gauge",
        "p95 request latency (recent window).",
        round(COUNTERS.percentile("serve_latency_seconds", 95), 6),
    )
    # flight-recorder profiling counters (obs/profile.py): the same
    # registry the bench harness reads, so daemon and bench report
    # dispatch/recompile cost identically
    metric(
        "simon_jax_dispatches_total", "counter",
        "JAX jitted device dispatches (scan / scenario / sweep entry points).",
        counts.get("jax_dispatches_total", 0),
    )
    metric(
        "simon_jax_recompiles_total", "counter",
        "JAX jit-cache misses (XLA recompilations).",
        counts.get("jax_recompiles_total", 0),
    )
    metric(
        "simon_device_transfer_d2h_bytes_total", "counter",
        "Bytes materialized host-side from device outputs.",
        counts.get("device_transfer_d2h_bytes_total", 0),
    )
    metric(
        "simon_device_transfer_h2d_bytes_total", "counter",
        "Bytes shipped to the device (scenario masks and friends).",
        counts.get("device_transfer_h2d_bytes_total", 0),
    )
    # shadow divergence auditor (shadow/replay.py): zero until a shadow
    # replay runs in this process, but always exported so dashboards
    # can rely on the series existing
    for key, help_text in (
        ("shadow_steps_total", "Shadow replay steps applied (decisions + deltas)."),
        ("shadow_decisions_total", "Real scheduler decisions replayed."),
        ("shadow_agree_total", "Replayed decisions simon agreed with."),
        ("shadow_divergence_total", "Replayed decisions simon diverged on."),
        ("shadow_divergence_node_total", "Node-divergences (same pod, different node)."),
        ("shadow_divergence_feasibility_total", "Feasibility-divergences (one side unschedulable)."),
        ("shadow_divergence_ordering_total", "Ordering-divergences (preemption/arrival-order evidence)."),
        ("shadow_warm_recompiles_total", "Jit-cache misses on an already-seen replay shape."),
        ("shadow_reloads_total", "Replay state reloads forced by node removal."),
        ("shadow_delta_skips_total", "Cluster-delta ops skipped (stale live-tail races)."),
        ("shadow_ingest_event_decisions_total", "Tail decisions sourced from scheduler Event objects."),
        ("shadow_ingest_diff_decisions_total", "Tail decisions inferred from pod diffs alone."),
        ("shadow_ingest_event_mismatch_total", "Scheduled events whose node contradicted the pod spec."),
        ("shadow_ingest_events_unsupported_total", "Events endpoints that failed the one-time probe."),
    ):
        metric(f"simon_{key}", "counter", help_text, counts.get(key, 0))
    metric(
        "simon_shadow_agreement_rate", "gauge",
        "Agreement rate of the most recent shadow replay (1.0 = full).",
        snap["gauges"].get("shadow_agreement_rate", 1.0),
    )
    lines.extend(_resilience_lines(snap))
    lines.extend(_observatory_lines(snap))
    lines.extend(_telemetry_lines(snap, slo_engine))
    lines.append("")
    return "\n".join(lines).encode()


def _telemetry_lines(snap: dict, slo_engine=None) -> List[str]:
    """Production-telemetry exposition shared by serve and twin
    (docs/OBSERVABILITY.md): span-recorder truncation, series-store
    occupancy, and the ``simon_slo_*`` burn-rate block when an SLO
    config is loaded."""
    from ..obs.spans import RECORDER

    counts = snap["counts"]
    lines: List[str] = []

    def metric(name, kind, help_text, value):
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name} {value}")

    metric(
        "simon_spans_dropped_total", "counter",
        "Spans lost to the recorder cap (cap mode) or overwritten "
        "oldest-first (ring mode) — nonzero means exported traces are "
        "a window, not the whole run.",
        counts.get("spans_dropped_total", 0),
    )
    metric(
        "simon_obs_series", "gauge",
        "Signals resident in the telemetry ring store.",
        telemetry.SERIES.stats()["series"],
    )
    metric(
        "simon_obs_spans_resident", "gauge",
        "Spans currently held by the flight recorder.",
        RECORDER.count if RECORDER.enabled else 0,
    )
    metric(
        "simon_telemetry_sample_errors_total", "counter",
        "Telemetry sampling passes that failed (loop survives them).",
        counts.get("telemetry_sample_errors_total", 0),
    )
    if slo_engine is not None:
        lines.extend(slo_engine.prometheus_lines())
    return lines


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _resilience_lines(snap: dict) -> List[str]:
    """Circuit-breaker / retry / watchdog / admission / session-cache
    exposition (docs/ROBUSTNESS.md, docs/SERVING.md): the degradation
    machinery's own state, so 'is the daemon degraded and why' is one
    scrape, not a log dive."""
    from ..runtime.retry import breaker_states

    counts = snap["counts"]
    gauges = snap["gauges"]
    lines: List[str] = []

    def metric(name, kind, help_text, value):
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name} {value}")

    # -- circuit breakers (runtime/retry.py)
    states = breaker_states()
    lines.append(
        "# HELP simon_breaker_state Circuit-breaker state per endpoint "
        "(0 closed, 1 open, 0.5 half-open probe window)."
    )
    lines.append("# TYPE simon_breaker_state gauge")
    for endpoint, st in sorted(states.items()):
        lines.append(
            f'simon_breaker_state{{endpoint="{_escape_label(endpoint)}"}} '
            f"{st['state']}"
        )
    for key, help_text in (
        ("breaker_opens_total", "Circuit-breaker open transitions."),
        ("breaker_recloses_total", "Breakers re-closed after a successful half-open probe."),
    ):
        metric(f"simon_{key}", "counter", help_text, counts.get(key, 0))
    # -- retry attempts (per endpoint only: a bare aggregate sample in
    # the same family would make sum() over the family double-count)
    lines.append(
        "# HELP simon_retry_attempts_total Failed I/O attempts that "
        "entered the retry/backoff path, per endpoint."
    )
    lines.append("# TYPE simon_retry_attempts_total counter")
    ep_keys = sorted(
        k for k in counts if k.startswith("retry_attempts_ep:")
    )
    for key in ep_keys:
        endpoint = key.split(":", 1)[1]
        lines.append(
            f'simon_retry_attempts_total{{endpoint="{_escape_label(endpoint)}"}} '
            f"{counts[key]}"
        )
    if not ep_keys:
        # zero-activity daemons still expose the family (scrape
        # continuity): one sample, no endpoint has retried yet
        lines.append(
            f'simon_retry_attempts_total{{endpoint=""}} '
            f"{counts.get('retry_attempts_total', 0)}"
        )
    # -- dispatcher watchdog (serve/coalescer.py)
    for key, help_text in (
        ("serve_watchdog_restarts_total", "Dispatcher threads restarted by the watchdog."),
        ("serve_dispatcher_casualties_total", "In-flight requests failed typed by a dispatcher death."),
    ):
        metric(f"simon_{key}", "counter", help_text, counts.get(key, 0))
    # -- admission control (serve/admission.py)
    for key, help_text in (
        ("serve_admission_total", "Admission verdicts issued."),
        ("serve_admission_serial_total", "Requests serially routed by admission (predicted HBM / oversize)."),
        ("serve_admission_shed_total", "Requests shed 429 by admission (predicted latency past the tick budget)."),
    ):
        metric(f"simon_{key}", "counter", help_text, counts.get(key, 0))
    # -- per-tenant accounting
    for prefix, name, help_text in (
        ("serve_tenant_requests:", "simon_serve_tenant_requests_total",
         "Requests received per tenant (any verdict)."),
        ("serve_tenant_shed:", "simon_serve_tenant_shed_total",
         "Requests shed per tenant (admission 429 + overload/drain 503)."),
    ):
        keys = sorted(k for k in counts if k.startswith(prefix))
        if keys:
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} counter")
            for key in keys:
                tenant = key.split(":", 1)[1]
                lines.append(
                    f'{name}{{tenant="{_escape_label(tenant)}"}} {counts[key]}'
                )
    # -- warm-session cluster deltas (/v1/cluster-delta, twin substrate)
    for key, help_text in (
        ("serve_deltas_applied_total", "Cluster deltas applied to the warm session."),
        ("serve_delta_skips_total", "Deltas skipped (no matching roster pod / known node)."),
        ("serve_delta_reloads_total", "Deltas that rebuilt the session (node drains; daemonset node churn)."),
    ):
        metric(f"simon_{key}", "counter", help_text, counts.get(key, 0))
    # -- session cache (serve/sessions.py)
    metric(
        "simon_serve_sessions", "gauge",
        "Warm sessions resident in the LRU.", gauges.get("serve_sessions", 1),
    )
    metric(
        "simon_serve_session_evictions_total", "counter",
        "Warm sessions evicted (capacity + ledger pressure).",
        counts.get("serve_session_evictions_total", 0),
    )
    # -- bounded-recovery checkpoints (runtime/checkpoint.py)
    for key, help_text in (
        ("ckpt_writes_total", "Verified checkpoint generations written."),
        ("ckpt_write_errors_total", "Checkpoint attempts that failed (write or verify); the previous generation stays authoritative."),
        ("ckpt_verify_failures_total", "Written snapshots whose digest did NOT re-materialize — refused and deleted, never compacted against."),
        ("ckpt_compactions_total", "Journal compactions after a verified checkpoint."),
        ("ckpt_compacted_records_total", "Journal records truncated as absorbed by a verified checkpoint."),
        ("ckpt_compact_errors_total", "Compaction failures (journal left intact; replay stays seq-bounded)."),
        ("ckpt_pruned_total", "Old checkpoint generations removed by the --keep-checkpoints policy."),
        ("ckpt_restore_total", "Bootstraps that restored from a verified checkpoint."),
        ("ckpt_restore_fallback_total", "Checkpoint generations refused at restore (torn/corrupt/stale) — fell back to an older one or full replay."),
        ("ckpt_restore_deltas_skipped_total", "Journal delta records skipped at restore as absorbed by the checkpoint."),
        ("fleet_replay_deltas_total", "Journal delta records actually replayed at restore (the bounded suffix)."),
    ):
        metric(f"simon_{key}", "counter", help_text, counts.get(key, 0))
    for key, help_text in (
        ("ckpt_restore_seconds", "Wall-clock of the last checkpoint restore (snapshot load + verify + suffix replay)."),
        ("ckpt_write_seconds", "Wall-clock of the last checkpoint write + verify."),
    ):
        if key in gauges:
            metric(f"simon_{key}", "gauge", help_text, gauges[key])
    # -- fault injection (runtime/inject.py): nonzero only when armed
    metric(
        "simon_inject_fired_total", "counter",
        "Chaos faults fired by the armed SIMON_INJECT spec (0 in production).",
        counts.get("inject_fired_total", 0),
    )
    return lines


def _observatory_lines(snap: dict) -> List[str]:
    """Compiled-cost / memory-ledger / latency-histogram exposition
    (docs/OBSERVABILITY.md): the ``simon_jax_cost_*`` per-site gauges
    from the AOT cost registry, the device-memory gauges and
    predictive-ladder counters, per-site latency histograms with
    p50/p95/p99, and the top spans by exclusive time when the span
    recorder is armed (--trace-out) — the long-running daemon's
    hot-span view, previously bench-only."""
    from ..obs import histo, spans
    from ..obs.costs import COSTS

    counts, gauges = snap["counts"], snap["gauges"]
    lines: List[str] = []

    def metric(name, kind, help_text, value):
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name} {value}")

    # -- AOT compiled-cost table (obs/costs.py)
    sites = COSTS.sites()
    if sites:
        for field, help_text in (
            ("flops", "FLOPs per dispatch of the site's last-compiled executable."),
            ("bytes_accessed", "Bytes accessed per dispatch (last compile)."),
            ("argument_bytes", "Argument HBM bytes of the last compile."),
            ("output_bytes", "Output HBM bytes of the last compile."),
            ("temp_bytes", "XLA temp-buffer HBM bytes of the last compile."),
        ):
            lines.append(
                f"# HELP simon_jax_cost_{field} {help_text}"
            )
            lines.append(f"# TYPE simon_jax_cost_{field} gauge")
            for site in sites:
                lines.append(
                    f'simon_jax_cost_{field}{{site="{site}"}} '
                    f"{gauges.get(f'jax_cost_{field}_{site}', 0)}"
                )
        lines.append(
            "# HELP simon_jax_cost_signatures Compiled shape-signatures per site."
        )
        lines.append("# TYPE simon_jax_cost_signatures gauge")
        for site in sites:
            lines.append(
                f'simon_jax_cost_signatures{{site="{site}"}} '
                f"{COSTS.signatures(site)}"
            )
    metric(
        "simon_jax_cost_compiles_total", "counter",
        "Ahead-of-time compiles (one per new shape-signature per site).",
        counts.get("jax_cost_compiles_total", 0),
    )
    # -- persistent artifact store (incremental/store.py)
    metric(
        "simon_aot_store_hit_total", "counter",
        "Executables loaded from the persistent artifact store instead "
        "of compiling (--aot-store).",
        counts.get("aot_store_hit_total", 0),
    )
    metric(
        "simon_aot_store_miss_total", "counter",
        "Store probes that found no entry (first compile of a shape).",
        counts.get("aot_store_miss_total", 0),
    )
    metric(
        "simon_aot_store_reject_total", "counter",
        "Store entries refused loudly: corrupt, torn, or wrong "
        "toolchain digest — each one recompiled cleanly.",
        counts.get("aot_store_reject_total", 0),
    )
    metric(
        "simon_aot_store_save_total", "counter",
        "Fresh compiles serialized back to the store (tmp+rename).",
        counts.get("aot_store_save_total", 0),
    )
    # -- delta re-simulation (incremental/resim.py)
    metric(
        "simon_incremental_suffix_pods_total", "counter",
        "Pod rows actually re-dispatched by incremental paths (what-if "
        "suffixes, delta re-simulations, timeline window free rows).",
        counts.get("incremental_suffix_pods_total", 0),
    )
    metric(
        "simon_incremental_prefix_reused_pods_total", "counter",
        "Pod rows whose committed placements were reused instead of "
        "re-scanned.",
        counts.get("incremental_prefix_reused_pods_total", 0),
    )
    metric(
        "simon_incremental_resims_total", "counter",
        "Suffix re-simulations applied to a committed scan.",
        counts.get("incremental_resims_total", 0),
    )
    metric(
        "simon_incremental_full_rebuilds_total", "counter",
        "Committed-scan full re-scans (conservative rule or degraded "
        "fault path; results identical either way).",
        counts.get("incremental_full_rebuilds_total", 0),
    )
    metric(
        "simon_incremental_fallbacks_total", "counter",
        "Classified faults that degraded an incremental path to the "
        "full one.",
        counts.get("incremental_fallbacks_total", 0),
    )
    metric(
        "simon_jax_cost_flops_dispatched_total", "counter",
        "FLOPs itemized across every AOT dispatch.",
        counts.get("jax_cost_flops_dispatched_total", 0),
    )
    # -- device-memory ledger (obs/ledger.py)
    metric(
        "simon_device_mem_bytes_in_use", "gauge",
        "Device bytes in use at the last ledger poll.",
        gauges.get("device_mem_bytes_in_use", 0),
    )
    metric(
        "simon_device_mem_peak_bytes", "gauge",
        "Peak device bytes observed by the ledger this process.",
        gauges.get("device_mem_peak_bytes", 0),
    )
    # per-device rows: every mesh device, labeled — a sharded dispatch
    # lives or dies on the TIGHTEST shard, not the device-0 number
    from ..obs.ledger import LEDGER

    per_device = LEDGER.device_summary()
    if per_device:
        lines.append(
            "# HELP simon_device_mem_device_bytes_in_use Device bytes in "
            "use at the last ledger poll, per device."
        )
        lines.append("# TYPE simon_device_mem_device_bytes_in_use gauge")
        for row in per_device:
            lines.append(
                f'simon_device_mem_device_bytes_in_use{{device="{row["device"]}"}} '
                f"{row['in_use']}"
            )
        if any(row.get("limit") for row in per_device):
            lines.append(
                "# HELP simon_device_mem_device_bytes_limit Per-device "
                "allocator budget (or the even SIMON_DEVICE_MEM_BUDGET slice)."
            )
            lines.append("# TYPE simon_device_mem_device_bytes_limit gauge")
            for row in per_device:
                if row.get("limit"):
                    lines.append(
                        f'simon_device_mem_device_bytes_limit{{device="{row["device"]}"}} '
                        f"{row['limit']}"
                    )
    for key, help_text in (
        ("ledger_predictions_total", "predict_fit verdicts issued."),
        ("ledger_predict_fit_total", "Dispatches predicted to fit."),
        ("ledger_predict_unfit_total", "Dispatches predicted NOT to fit (split/skipped before launch)."),
        ("ledger_predict_hit_total", "Predicted-fit chunks that ran without OOM."),
        ("ledger_predict_miss_total", "Predicted-fit chunks that OOMed anyway."),
        ("guard_oom_predicted_total", "Chunks split/degraded predictively, zero doomed dispatches."),
        ("guard_oom_reactive_total", "Device OOMs caught reactively (the halving fallback)."),
        ("guard_rung_predicted_skips_total", "Ladder rungs skipped on a ledger verdict."),
        ("mesh_layout_scenario_total", "Dispatches the layout planner sharded on the scenario axis."),
        ("mesh_layout_node_total", "Dispatches the layout planner sharded on the node axis."),
        ("mesh_layout_none_total", "Dispatches the planner kept on the single-device ladder."),
    ):
        metric(f"simon_{key}", "counter", help_text, counts.get(key, 0))
    # -- latency histograms (obs/histo.py)
    lines.extend(histo.prometheus_lines())
    # -- hot spans by exclusive time (span recorder armed only);
    # cached: the always-armed daemon ring must not be copied and
    # walked per scrape (spans.top_spans_cached, 30s refresh)
    if spans.RECORDER.enabled:
        top = spans.top_spans_cached(5)
        if top:
            lines.append(
                "# HELP simon_span_exclusive_seconds Top spans by exclusive "
                "(self) wall-clock since the recorder was armed."
            )
            lines.append("# TYPE simon_span_exclusive_seconds gauge")
            for row in top:
                lines.append(
                    f'simon_span_exclusive_seconds{{span="{row["name"]}"}} '
                    f"{row['exclusive_ms'] / 1e3:.6f}"
                )
    return lines


class ServeDaemon:
    """Owns the HTTP server, the coalescer, and the drain lifecycle."""

    def __init__(
        self,
        session: Session,
        host: str = "127.0.0.1",
        port: int = 8080,
        max_batch: int = 16,
        queue_depth: int = 64,
        default_deadline_s: Optional[float] = None,
        drain_timeout_s: float = 30.0,
        tick_budget_s: Optional[float] = None,
        max_request_pods: Optional[int] = None,
        max_sessions: int = 8,
        snapshot_path: Optional[str] = None,
        checkpoint_interval: Optional[int] = None,
        keep_checkpoints: int = 2,
        slo_engine=None,
        obs_cadence_s: float = 1.0,
    ):
        self.session = session
        self.default_deadline_s = default_deadline_s
        self.drain_timeout_s = drain_timeout_s
        self.slo_engine = slo_engine
        # the resident telemetry loop: counters/gauges/percentiles/
        # ledger into the series rings on a cadence, SLO evaluation
        # riding each sample (obs/telemetry.py)
        self.telemetry = telemetry.TelemetryRuntime(
            cadence_s=obs_cadence_s, slo_engine=slo_engine
        )
        self.admission = AdmissionController(
            max_batch=max_batch,
            tick_budget_s=tick_budget_s,
            max_request_pods=max_request_pods,
        )
        snapshot = open_snapshot(snapshot_path) if snapshot_path else None
        self.sessions = SessionCache(capacity=max_sessions, snapshot=snapshot)
        # bounded-recovery checkpoints (runtime/checkpoint.py): verified
        # snapshots of the committed session every --checkpoint-interval
        # deltas, journal compacted to the unabsorbed suffix — replay on
        # the NEXT bootstrap is O(interval), not O(lifetime)
        self.checkpoints = None
        if snapshot is not None and checkpoint_interval:
            from ..runtime.checkpoint import CheckpointManager, checkpoint_dir
            from .session import session_checkpoint_state, verify_payload_digest
            from .sessions import serve_keep_record

            self.checkpoints = CheckpointManager(
                checkpoint_dir(snapshot_path),
                interval=checkpoint_interval,
                keep=keep_checkpoints,
                capture=lambda: session_checkpoint_state(self.session),
                materialized_digest=lambda payload: verify_payload_digest(
                    self.session, payload
                ),
                journal=snapshot,
                keep_record=serve_keep_record(session.fingerprint),
                label="serve",
            )
        # the configured cluster is pinned: ledger pressure and
        # capacity evict secondaries only (serve/sessions.py)
        self.sessions.add(session, pinned=True)
        self.coalescer = Coalescer(
            session,
            max_batch=max_batch,
            queue_depth=queue_depth,
            on_tick=self.sessions.check_pressure,
        )
        self._shutdown = threading.Event()
        # simulate requests currently inside do_POST (parse -> reply
        # WRITTEN): the drain waits for this to reach zero so "exit 0"
        # really means every answered request reached its client's
        # socket, not just the coalescer (handler threads are daemonic
        # and would otherwise die mid-write at process exit)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._inflight_zero = threading.Event()
        self._inflight_zero.set()
        daemon = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # stdlib logs to stderr per request
                log.debug("%s %s", self.address_string(), fmt % args)

            def _send(self, status: int, body: bytes, content_type="application/json", headers=()):
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    status, reasons = daemon.readiness()
                    # degraded readiness advertises the SAME backoff
                    # hint as the admission 429 path, so probers (the
                    # fleet router, external LBs) back off uniformly
                    # with shed clients instead of hot-looping
                    hdrs = ()
                    retry_after = None
                    if reasons:
                        retry_after = daemon.admission.retry_after_hint(
                            daemon.coalescer.depth
                        )
                        hdrs = (("Retry-After", str(retry_after)),)
                    self._send(
                        200,
                        json.dumps(
                            {
                                "ok": True,
                                "status": status,
                                "degraded": bool(reasons),
                                "reasons": reasons,
                                "retryAfterSeconds": retry_after,
                                "cluster": daemon.session.fingerprint,
                                "deltaSeq": daemon.session.delta_seq,
                                "queueDepth": daemon.coalescer.depth,
                                "sessions": daemon.sessions.stats(),
                                "sloAlerting": (
                                    daemon.slo_engine.alerting()
                                    if daemon.slo_engine is not None
                                    else []
                                ),
                                "checkpoint": (
                                    daemon.checkpoints.stats()
                                    if daemon.checkpoints is not None
                                    else None
                                ),
                                "draining": daemon._shutdown.is_set(),
                            }
                        ).encode(),
                        headers=hdrs,
                    )
                elif self.path == "/v1/state-digest":
                    # the fleet dict-identity gate (docs/FLEET.md): a
                    # replacement replica is correct iff this triple
                    # matches the replica it replaced
                    self._send(
                        200,
                        json.dumps(
                            {
                                "fingerprint": daemon.session.fingerprint,
                                "deltaSeq": daemon.session.delta_seq,
                                "stateDigest": daemon.session.state_digest(),
                            },
                            sort_keys=True,
                        ).encode(),
                    )
                elif self.path == "/metrics":
                    self._send(
                        200,
                        render_metrics(daemon.coalescer, daemon.slo_engine),
                        content_type="text/plain; version=0.0.4",
                    )
                elif self.path.startswith("/v1/obs/series"):
                    status, doc = telemetry.series_endpoint(self.path)
                    self._send(
                        status,
                        json.dumps(doc, sort_keys=True).encode(),
                    )
                elif self.path == "/v1/obs/snapshot":
                    self._send(
                        200,
                        json.dumps(
                            telemetry.snapshot_doc(
                                daemon.slo_engine,
                                runtime=daemon.telemetry,
                                extra={
                                    "daemon": "serve",
                                    "health": daemon.readiness()[0],
                                    "queueDepth": daemon.coalescer.depth,
                                },
                            ),
                            sort_keys=True,
                        ).encode(),
                    )
                else:
                    self._send(404, json.dumps({"error": "not found"}).encode())

            def do_POST(self):
                if self.path == "/v1/cluster-delta":
                    self._do_cluster_delta()
                    return
                if self.path == "/debug/dump":
                    length = int(self.headers.get("Content-Length") or 0)
                    status, doc = telemetry.handle_debug_dump(
                        self.rfile.read(length),
                        slo_engine=daemon.slo_engine,
                        runtime=daemon.telemetry,
                        label="serve",
                    )
                    self._send(
                        status, json.dumps(doc, sort_keys=True).encode()
                    )
                    return
                if self.path != "/v1/simulate":
                    self._send(404, json.dumps({"error": "not found"}).encode())
                    return
                with daemon._inflight_lock:
                    daemon._inflight += 1
                    daemon._inflight_zero.clear()
                try:
                    self._do_simulate()
                finally:
                    with daemon._inflight_lock:
                        daemon._inflight -= 1
                        if daemon._inflight == 0:
                            daemon._inflight_zero.set()

            def _do_cluster_delta(self):
                """POST /v1/cluster-delta: apply a ClusterDelta stream
                (twin/deltas.py vocabulary) to the warm primary
                session — ROADMAP item 2's watch-style delta update.
                Body: one delta record or ``{"deltas": [...]}``. Every
                record FULLY validates before any applies — shape,
                pod validity, and node-reference consistency walked
                against the session's node set — so a typo'd stream
                mutates nothing (400); each applied delta journals to
                the session snapshot (--snapshot), so a restarted
                daemon can see what its warm state had absorbed."""
                import copy as _copy

                from ..models import workloads as _wl
                from ..models.validation import InputError
                from ..twin import deltas as _dl
                from ..twin.deltas import ClusterDelta

                rid = telemetry.ensure_request_id(
                    self.headers.get(telemetry.REQUEST_ID_HEADER)
                )
                rid_header = (telemetry.REQUEST_ID_HEADER, rid)
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length)
                try:
                    doc = json.loads(raw.decode("utf-8"))
                    if isinstance(doc, dict) and "deltas" in doc:
                        recs = doc["deltas"]
                    elif isinstance(doc, dict):
                        recs = [doc]
                    else:
                        raise InputError(
                            'body must be a delta object or {"deltas": [...]}'
                        )
                    if not isinstance(recs, list) or not recs:
                        raise InputError('"deltas" must be a non-empty list')
                    deltas = [ClusterDelta.from_record(r) for r in recs]
                    # node-reference consistency over the stream
                    # (joins add, drains need presence) and pod
                    # validity — the apply loop re-runs the same
                    # validation, so this pre-pass makes the 400 path
                    # mutation-free without forking semantics
                    names = {
                        (n.get("metadata") or {}).get("name")
                        for n in daemon.session.cluster.nodes
                    }
                    for d in deltas:
                        if d.kind == _dl.NODE_JOIN:
                            names.add(d.node_name)
                        elif d.kind == _dl.NODE_DRAIN:
                            if d.node_name not in names:
                                raise InputError(
                                    "node_drain delta names unknown "
                                    f"node {d.node_name!r}"
                                )
                            names.discard(d.node_name)
                        elif d.kind in (_dl.POD_BIND, _dl.POD_ARRIVE):
                            _wl.pod_from_pod(_copy.deepcopy(d.pod))
                except (UnicodeDecodeError, ValueError, InputError) as e:
                    self._send(
                        400,
                        json.dumps(
                            {"error": str(e), "requestId": rid}
                        ).encode(),
                        headers=(rid_header,),
                    )
                    return
                if daemon._shutdown.is_set():
                    from .coalescer import partial_body

                    self._send(
                        503,
                        partial_body(
                            "drain", "daemon is draining", request_id=rid
                        ),
                        headers=(rid_header,),
                    )
                    return
                counts = {"applied": 0, "skipped": 0, "reloads": 0}
                try:
                    for d, rec in zip(deltas, recs):
                        out, seq = daemon.session.apply_delta_seq(d)
                        daemon.sessions.record_delta(
                            daemon.session.fingerprint,
                            rec,
                            request_id=rid,
                            seq=seq,
                        )
                        if daemon.checkpoints is not None:
                            daemon.checkpoints.note_delta(seq)
                        if out == "skipped":
                            counts["skipped"] += 1
                        else:
                            counts["applied"] += 1
                            if out == "reloaded":
                                counts["reloads"] += 1
                except InputError as e:
                    # mid-stream application error (e.g. a drain naming
                    # an unknown node): report what landed — the
                    # journal holds the applied prefix
                    self._send(
                        409,
                        json.dumps(
                            {
                                "error": str(e),
                                **counts,
                                "deltaSeq": daemon.session.delta_seq,
                                "requestId": rid,
                            }
                        ).encode(),
                        headers=(rid_header,),
                    )
                    return
                self._send(
                    200,
                    json.dumps(
                        {**counts, "deltaSeq": daemon.session.delta_seq}
                    ).encode(),
                    headers=(rid_header,),
                )

            def _do_simulate(self):
                # request correlation end-to-end (obs/telemetry.py):
                # the caller's X-Simon-Request-Id (else a minted one)
                # is bound for the whole handler scope — every span
                # recorded while THIS request is parsed/admitted/
                # answered carries it — echoed on every response
                # (200/400/429/503/500) and carried in every shed/
                # PARTIAL body. The 200 body itself stays byte-
                # identical to standalone simulate() (the coalescing
                # conformance contract): correlation lives in headers
                # and error/shed bodies only.
                rid = telemetry.ensure_request_id(
                    self.headers.get(telemetry.REQUEST_ID_HEADER)
                )
                with telemetry.request_scope(rid):
                    self._do_simulate_correlated(rid)

            def _do_simulate_correlated(self, rid: str):
                from ..obs.spans import RECORDER

                rid_header = (telemetry.REQUEST_ID_HEADER, rid)
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length)
                try:
                    req, deadline, want_trace = parse_request_body(
                        raw, self.headers.get("Content-Type", "")
                    )
                except ValueError as e:
                    self._send(
                        400,
                        json.dumps(
                            {"error": str(e), "requestId": rid}
                        ).encode(),
                        headers=(rid_header,),
                    )
                    return
                if deadline is None:
                    deadline = daemon.default_deadline_s
                from .coalescer import partial_body

                header_tenant = self.headers.get("X-Simon-Tenant")
                tenant = (
                    sanitize_tenant(header_tenant)
                    if header_tenant
                    else req.tenant
                )
                COUNTERS.inc(f"serve_tenant_requests:{tenant}")
                # cost-predictive admission BEFORE the queue: 429 when
                # the predicted wait busts the tick budget, serial
                # routing when the predicted HBM would not fit
                with RECORDER.span("serve/request/admission"):
                    verdict = daemon.admission.decide(
                        est_pods=estimate_request_pods(req),
                        queue_depth=daemon.coalescer.depth,
                    )
                if verdict.action == "shed":
                    # serve_admission_shed_total counted by decide()
                    COUNTERS.inc("serve_shed_total")
                    COUNTERS.inc(f"serve_tenant_shed:{tenant}")
                    self._send(
                        429,
                        partial_body(
                            "admission", verdict.reason, request_id=rid
                        ),
                        headers=(
                            ("Retry-After", str(verdict.retry_after_s)),
                            rid_header,
                        ),
                    )
                    return
                # cross-process trace context (fleet router hop): a
                # malformed header degrades to (None, 0), never a 4xx
                trace_parent, trace_hop = telemetry.parse_trace_context(
                    self.headers.get(telemetry.TRACE_CONTEXT_HEADER)
                )
                pending = PendingRequest(
                    request=req,
                    budget=Budget(deadline),
                    route="serial" if verdict.action == "serial" else "batch",
                    tenant=tenant,
                    route_reason=verdict.reason,
                    request_id=rid,
                    trace_parent=trace_parent,
                    trace_hop=trace_hop,
                )
                if not daemon.coalescer.submit(pending):
                    draining = daemon._shutdown.is_set()
                    COUNTERS.inc(f"serve_tenant_shed:{tenant}")
                    self._send(
                        503,
                        partial_body(
                            "drain" if draining else "overload",
                            "daemon is draining for shutdown"
                            if draining
                            else f"queue full at depth {daemon.coalescer.queue_depth}",
                            request_id=rid,
                        ),
                        headers=(
                            ("Retry-After", str(daemon.coalescer.retry_after_s())),
                            rid_header,
                        ),
                    )
                    return
                wait = (deadline or 0) + _RESULT_WAIT_SLACK_S
                if not pending.done.wait(timeout=wait):
                    self._send(
                        500,
                        json.dumps(
                            {
                                "error": "dispatcher unresponsive",
                                "requestId": rid,
                            }
                        ).encode(),
                        headers=(rid_header,),
                    )
                    return
                reply = pending.reply
                headers = [
                    ("X-Simon-Engine", str(reply.meta.get("engine", ""))),
                    ("X-Simon-Batch-Size", str(reply.meta.get("batchSize", ""))),
                    rid_header,
                ]
                if reply.meta.get("incremental"):
                    # diagnostic only: the body is byte-identical to the
                    # full path; this names the suffix-dispatch route
                    headers.append(
                        ("X-Simon-Incremental", str(reply.meta["incremental"]))
                    )
                if want_trace:
                    headers.append(
                        ("X-Simon-Trace", json.dumps(reply.meta, sort_keys=True))
                    )
                with RECORDER.span("serve/request/reply"):
                    self._send(reply.status, reply.body, headers=headers)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.host, self.port = self.httpd.server_address[:2]
        self._server_thread = threading.Thread(
            target=self.httpd.serve_forever,
            name="simon-serve-http",
            daemon=True,
        )

    def start(self):
        self.telemetry.start()
        self.coalescer.start()
        if self.checkpoints is not None:
            self.checkpoints.start()
        self._server_thread.start()
        log.info("simon serve listening on %s:%d", self.host, self.port)

    def readiness(self):
        """-> (status, reasons): "ok" or "degraded" with one reason
        per degradation the daemon is living with — an open circuit
        breaker, a dispatcher the watchdog had to restart, or the
        device-memory ledger past its budget. Liveness stays "ok":
        true either way (the process IS up); readiness-aware clients
        route on ``status`` (docs/SERVING.md)."""
        from ..obs.ledger import device_memory_stats
        from ..runtime.retry import breaker_states

        reasons = []
        for endpoint, st in sorted(breaker_states().items()):
            if st["open"]:
                reasons.append(f"circuit breaker open: {endpoint}")
        if self.coalescer.restarts:
            reasons.append(
                f"dispatcher watchdog fired {self.coalescer.restarts} "
                "time(s) this process"
            )
        in_use, limit, _src = device_memory_stats()
        if limit and in_use > limit:
            reasons.append(
                f"device memory over budget ({in_use} > {limit} bytes)"
            )
        if self.slo_engine is not None:
            reasons.extend(self.slo_engine.reasons())
        if self.checkpoints is not None:
            reasons.extend(self.checkpoints.degraded_reasons())
        return ("degraded" if reasons else "ok"), reasons

    def begin_shutdown(self):
        """Stop intake (new submits shed as draining); idempotent."""
        self._shutdown.set()
        self.coalescer.close()

    def shutdown(self) -> int:
        """Drain and stop. Returns the process exit code: 0 when every
        queued request was answered within --drain-timeout, 3 (the
        deadline-partial code) when leftovers had to be shed."""
        self.begin_shutdown()  # also closes coalescer intake
        drained = self.coalescer.drain(timeout=self.drain_timeout_s)
        # the coalescer answered every request; now wait for the
        # handler threads to finish WRITING those answers (bounded: a
        # wedged client socket must not hold the exit hostage)
        self._inflight_zero.wait(timeout=min(self.drain_timeout_s, 10.0))
        if self.checkpoints is not None:
            # the worker must not race the journal close below (drain
            # appends, then closes the snapshot the compactor rewrites)
            self.checkpoints.stop()
        self.sessions.drain()  # journal surviving warm sessions
        self.telemetry.stop()  # one final sample so dumps see the end
        self.httpd.shutdown()
        self.httpd.server_close()
        if not drained:
            log.warning(
                "drain timeout (%.1fs) expired with requests still queued; shed",
                self.drain_timeout_s,
            )
        return EXIT_OK if drained else EXIT_PARTIAL_DEADLINE

    def run_until_signaled(self) -> int:
        """Block until SIGTERM/SIGINT, then drain and return the exit
        code. Installs handlers (main thread only)."""

        def handler(signum, frame):
            log.info("received signal %d: draining", signum)
            self.begin_shutdown()
            self._wake.set()

        self._wake = threading.Event()
        prev_term = signal.signal(signal.SIGTERM, handler)
        prev_int = signal.signal(signal.SIGINT, handler)
        try:
            self._wake.wait()
            return self.shutdown()
        finally:
            signal.signal(signal.SIGTERM, prev_term)
            signal.signal(signal.SIGINT, prev_int)
