"""`simon serve`: the long-lived what-if scheduling daemon.

Three layers (docs/SERVING.md):

- ``session``  — one warm loaded cluster; answers request batches as
  scenario rows of a single batched masked scan, byte-identical to
  standalone ``simulate()`` runs
- ``coalescer`` — bounded queue + single dispatcher thread draining up
  to ``max_batch`` requests per tick (micro-batching), deadline sheds,
  drain-on-shutdown
- ``server`` — JSON-over-HTTP surface (``POST /v1/simulate``,
  ``GET /healthz``, ``GET /metrics``), SIGTERM drain lifecycle
"""

from .coalescer import Coalescer, PendingRequest, partial_body
from .server import ServeDaemon, parse_request_body, render_metrics
from .session import Session, WhatIfReply, WhatIfRequest, result_payload

__all__ = [
    "Coalescer",
    "PendingRequest",
    "partial_body",
    "ServeDaemon",
    "parse_request_body",
    "render_metrics",
    "Session",
    "WhatIfReply",
    "WhatIfRequest",
    "result_payload",
]
