"""Cost-predictive admission control for `simon serve`.

The bounded queue (serve/coalescer.py) sheds on DEPTH — it reacts
after the backlog exists. This module sheds on PREDICTED COST before
a request ever occupies a queue slot, using the observability the
r10 observatory already exports:

- **Predicted HBM** (obs/costs.py + obs/ledger.py): would one more
  full coalesced tick of the batched scan fit in device memory next
  to what is live right now? When the AOT ``memory_analysis`` says
  no, the request is SERIALLY ROUTED — the deterministic host oracle
  answers it (byte-identical body, ``X-Simon-Engine: serial``) and
  the doomed dispatch never launches. The serial rung cannot OOM, so
  memory pressure degrades throughput, never availability.
- **Predicted latency** (obs/histo.py): the p95 of the coalescer's
  evaluate phase times the ticks already queued ahead is the wait
  this request would see. Past ``--tick-budget`` the request is SHED
  with **429 Too Many Requests** and a ``Retry-After`` derived from
  the same prediction — the client-visible half of the contract:
  429 = "you would not get an answer in time, come back in N",
  503 = "the queue itself is full / draining" (docs/SERVING.md).
- **Oversize requests** (``--max-request-pods``): a request whose
  estimated pod count exceeds the bound routes serial — one giant
  request must not recompile the scan for everyone else's shapes.

Per-tenant accounting: every verdict counts under the request's
tenant (``X-Simon-Tenant`` header or the JSON envelope's ``tenant``
key), exported as ``simon_serve_tenant_requests_total{tenant=...}`` /
``..._shed_total{tenant=...}`` so a noisy neighbor is visible in one
/metrics scrape.

With no tick budget configured and no device-memory budget known,
every verdict is ``admit`` — admission control costs nothing until
the signals it needs exist (conformance tests run in that mode).
"""

from __future__ import annotations

import math
import re
import threading
from dataclasses import dataclass
from typing import Optional

from ..utils.trace import COUNTERS

_TENANT_RE = re.compile(r"[^A-Za-z0-9_.-]")
DEFAULT_TENANT = "default"

#: distinct tenant labels a daemon will ever mint; the client-supplied
#: header must not grow counters/exposition without bound for the life
#: of the process, so tenant N+1.. all collapse into one bucket
MAX_TENANTS = 64
OVERFLOW_TENANT = "overflow"
_seen_tenants: set = set()
_tenants_lock = threading.Lock()

#: the jit site whose AOT memory analysis prices a coalesced tick
SCAN_SITE = "scenario_scan"


def sanitize_tenant(raw: Optional[str]) -> str:
    """Counter/label-safe tenant name (bounded in charset, length AND
    cardinality: a tenant header must not be able to mint unbounded
    metric keys, nor smuggle quotes into exposition). Once
    ``MAX_TENANTS`` distinct names exist, further new names share the
    ``overflow`` bucket — known tenants keep their own series."""
    if not raw:
        return DEFAULT_TENANT
    name = _TENANT_RE.sub("_", str(raw))[:64] or DEFAULT_TENANT
    with _tenants_lock:
        if name in _seen_tenants:
            return name
        if len(_seen_tenants) >= MAX_TENANTS:
            return OVERFLOW_TENANT
        _seen_tenants.add(name)
    return name


def reset_tenant_registry():
    """Forget seen tenants (tests: the registry is process-global)."""
    with _tenants_lock:
        _seen_tenants.clear()


@dataclass
class Verdict:
    """One admission decision. ``action``: admit | serial | shed."""

    action: str
    reason: str = ""
    retry_after_s: int = 1

    @property
    def admitted(self) -> bool:
        return self.action != "shed"


class AdmissionController:
    """Stateless policy over the process-wide observability registries
    (cost registry, memory ledger, latency histograms) — all state it
    reads is already maintained by the instrumented dispatch path."""

    def __init__(
        self,
        max_batch: int,
        tick_budget_s: Optional[float] = None,
        max_request_pods: Optional[int] = None,
    ):
        self.max_batch = max(1, int(max_batch))
        self.tick_budget_s = tick_budget_s
        self.max_request_pods = max_request_pods

    # -- the three signals --------------------------------------------------

    def _predicted_tick_s(self) -> float:
        """p95 of the coalescer's evaluate phase; 0.0 until observed."""
        from ..obs.histo import HISTOS

        h = HISTOS.peek("serve/evaluate")
        if h is None:
            return 0.0
        return float(h.percentile(95.0))

    def _hbm_fits(self) -> Optional[bool]:
        """Ledger verdict for one more full-batch dispatch of the
        scan site; None until the site compiled or no budget known."""
        from ..obs.costs import COSTS
        from ..obs.ledger import LEDGER

        est = COSTS.estimate_bytes(SCAN_SITE, self.max_batch)
        if est is None:
            return None
        return LEDGER.predict_fit(int(est), label="serve_admission")

    # -- policy -------------------------------------------------------------

    def decide(self, *, est_pods: int, queue_depth: int) -> Verdict:
        """One verdict per incoming request, BEFORE it takes a queue
        slot. Order: oversize (cheapest, request-local), predicted
        HBM (degrades to serial), predicted latency (sheds).
        Tenant-blind by design: per-tenant accounting lives with the
        caller (do_POST), and tenancy never changes an answer."""
        COUNTERS.inc("serve_admission_total")
        if (
            self.max_request_pods is not None
            and est_pods > self.max_request_pods
        ):
            COUNTERS.inc("serve_admission_serial_total")
            return Verdict(
                "serial",
                f"estimated {est_pods} pods exceeds "
                f"--max-request-pods {self.max_request_pods}",
            )
        if self._hbm_fits() is False:
            COUNTERS.inc("serve_admission_serial_total")
            return Verdict(
                "serial",
                "memory ledger predicts a full coalesced tick will not "
                "fit in device memory; routing to the serial oracle",
            )
        if self.tick_budget_s:
            tick_s = self._predicted_tick_s()
            if tick_s > 0.0:
                ticks_ahead = queue_depth // self.max_batch + 1
                predicted_wait = tick_s * ticks_ahead
                if predicted_wait > self.tick_budget_s:
                    COUNTERS.inc("serve_admission_shed_total")
                    return Verdict(
                        "shed",
                        f"predicted wait {predicted_wait:.3f}s "
                        f"(p95 tick {tick_s:.3f}s x {ticks_ahead} "
                        f"tick(s) queued) exceeds --tick-budget "
                        f"{self.tick_budget_s:g}s",
                        retry_after_s=max(1, math.ceil(predicted_wait)),
                    )
        return Verdict("admit")

    def retry_after_hint(self, queue_depth: int = 0) -> int:
        """The backoff hint a degraded /healthz advertises, derived
        from the SAME latency prediction as the 429 shed path (p95
        coalescer tick x ticks queued ahead, floored at one second) —
        probers and load balancers back off uniformly with shed
        clients instead of hot-looping a degraded replica."""
        tick_s = self._predicted_tick_s()
        ticks_ahead = queue_depth // self.max_batch + 1
        return max(1, math.ceil(tick_s * ticks_ahead))


def estimate_request_pods(req) -> int:
    """Cheap pre-expansion pod-count estimate of a WhatIfRequest:
    workload replicas are declared in the spec, so the estimate reads
    them without paying generate_valid_pods_from_app (which runs on
    the dispatcher thread, after admission)."""
    total = 0
    for app in req.apps:
        res = app.resource
        total += len(getattr(res, "pods", ()) or ())
        for field in (
            "deployments",
            "stateful_sets",
            "replica_sets",
            "replication_controllers",
            "jobs",
            "cron_jobs",
        ):
            for obj in getattr(res, field, ()) or ():
                spec = obj.get("spec") or {}
                replicas = spec.get("replicas")
                if replicas is None:
                    replicas = spec.get("parallelism", 1)
                try:
                    total += max(1, int(replicas))
                except (TypeError, ValueError):
                    total += 1
        for ds in getattr(res, "daemon_sets", ()) or ():
            total += 1  # per-node expansion is cluster-sized; count one
    return total
