"""Micro-batching request coalescer — the serving core of `simon serve`.

The shape is continuous batching from inference serving, applied to
what-if scheduling: HTTP handler threads only parse and enqueue; ONE
dispatcher thread drains up to ``max_batch`` queued requests per tick
and answers all of them with a single batched device dispatch
(serve/session.evaluate_batch), so B concurrent requests cost
``ceil(B / max_batch)`` dispatches instead of B — the counters at
``/metrics`` prove it (tests/test_serve.py asserts the bound).

Backpressure contract (docs/SERVING.md):

- the queue is BOUNDED (``queue_depth``): a submit against a full
  queue is rejected immediately — the HTTP layer turns that into
  503 + Retry-After, the shed counter increments, and the daemon's
  latency distribution stays honest instead of growing an unbounded
  tail (the same load-shedding posture as runtime/retry's circuit
  breakers: fail fast, recover fast)
- every request carries a ``Budget`` (runtime/budget.py): a request
  whose deadline expired while it sat in the queue is SHED at pickup
  with a machine-readable PARTIAL body — device time is never spent on
  an answer nobody is waiting for. Once dispatched, a request runs to
  completion (the scan has no per-request halt boundary).
- SIGTERM drains: ``close()`` stops intake (submits reject as
  draining), the dispatcher finishes every queued request, then the
  thread exits. ``drain(timeout)`` bounds the wait; leftovers past the
  timeout are shed with the drain body.

Single-dispatcher concurrency contract: all expansion, encode, scan,
and replay run on the dispatcher thread — the warm identity caches are
effectively single-threaded (docs/PERFORMANCE.md "warm-cache
concurrency contract"); handler threads touch only the queue, the
counters, and their own parsed request.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import List, Optional

from ..obs.histo import HISTOS
from ..runtime.budget import Budget
from ..utils.trace import COUNTERS
from .session import Session, WhatIfReply, WhatIfRequest


def partial_body(reason: str, message: str) -> bytes:
    """Machine-readable shed body — the HTTP analogue of the CLI's
    PARTIAL report (cli._emit_partial): same `partial`/`reason` keys,
    so one client-side parser reads both surfaces."""
    return json.dumps(
        {"partial": True, "reason": reason, "message": message}
    ).encode()


@dataclass
class PendingRequest:
    """One enqueued question plus its rendezvous with the handler
    thread (`done` fires when `reply` is set)."""

    request: WhatIfRequest
    budget: Budget
    enqueued_at: float = field(default_factory=time.monotonic)
    done: threading.Event = field(default_factory=threading.Event)
    reply: Optional[WhatIfReply] = None

    def finish(self, reply: WhatIfReply):
        self.reply = reply
        self.done.set()


class Coalescer:
    def __init__(
        self,
        session: Session,
        max_batch: int = 16,
        queue_depth: int = 64,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        self.session = session
        self.max_batch = max_batch
        self.queue_depth = queue_depth
        self._queue: deque = deque()
        self._lock = threading.Lock()
        self._wakeup = threading.Event()
        self._closing = False
        self._drained = threading.Event()
        # tests set this to hold the dispatcher between ticks, so a
        # burst enqueued while held provably coalesces into one tick
        self.hold: Optional[threading.Event] = None
        self._thread = threading.Thread(
            target=self._run, name="simon-serve-dispatcher", daemon=True
        )

    def start(self):
        self._thread.start()

    # -- intake (handler threads) -------------------------------------------

    def submit(self, req: PendingRequest) -> bool:
        """Enqueue; False = rejected (queue full or draining). The
        caller owns the 503 rendering."""
        with self._lock:
            if self._closing or len(self._queue) >= self.queue_depth:
                COUNTERS.inc("serve_shed_total")
                COUNTERS.inc(
                    "serve_shed_draining_total"
                    if self._closing
                    else "serve_shed_overload_total"
                )
                return False
            self._queue.append(req)
            COUNTERS.gauge("serve_queue_depth", len(self._queue))
        self._wakeup.set()
        return True

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def retry_after_s(self) -> int:
        """Overload hint: how long until the backlog plausibly clears,
        from the observed per-tick latency (>= 1s so clients never busy
        spin)."""
        tick_s = COUNTERS.mean("serve_tick_seconds") or 1.0
        ticks = max(1, -(-self.depth // self.max_batch))
        return max(1, int(round(ticks * tick_s)))

    def _finish_counted(self, pending: PendingRequest, reply: WhatIfReply):
        """Answer one request AND account for it: every answered
        request — simulate result or shed — counts in
        serve_requests_total and the latency window ('Requests
        answered (any status)', serve/server.render_metrics), so the
        exported distribution keeps its worst cases exactly when the
        daemon is shedding."""
        latency = time.monotonic() - pending.enqueued_at
        COUNTERS.observe("serve_latency_seconds", latency)
        # the long-memory histogram complement of the bounded-window
        # observation above: never evicts, exported as Prometheus
        # histogram exposition with p50/p95/p99 (obs/histo.py)
        HISTOS.observe("serve/request", latency)
        COUNTERS.mark("serve_completions")
        COUNTERS.inc("serve_requests_total")
        pending.finish(reply)

    # -- dispatch (the one dispatcher thread) -------------------------------

    def _drain_tick(self) -> List[PendingRequest]:
        """Take up to max_batch requests, shedding any whose deadline
        already expired in the queue (503 PARTIAL, no device time)."""
        picked: List[PendingRequest] = []
        while len(picked) < self.max_batch:
            with self._lock:
                if not self._queue:
                    break
                req = self._queue.popleft()
                COUNTERS.gauge("serve_queue_depth", len(self._queue))
            if req.budget.expired() or req.budget.interrupted:
                COUNTERS.inc("serve_shed_total")
                COUNTERS.inc("serve_shed_deadline_total")
                self._finish_counted(
                    req,
                    WhatIfReply(
                        status=503,
                        body=partial_body(
                            "deadline",
                            f"deadline of {req.budget.deadline_s:g}s expired "
                            f"after {req.budget.elapsed():.2f}s in the queue",
                        ),
                        meta={"engine": "shed-deadline"},
                    ),
                )
                continue
            picked.append(req)
        return picked

    def _run(self):
        while True:
            if self.hold is not None:
                self.hold.wait()
            self._wakeup.wait(timeout=0.05)
            self._wakeup.clear()
            batch = self._drain_tick()
            if not batch:
                with self._lock:
                    if self._closing and not self._queue:
                        break
                continue
            t0 = time.monotonic()
            COUNTERS.observe("serve_batch_fill", len(batch))
            COUNTERS.inc("serve_batches_total")
            for p in batch:
                HISTOS.observe("serve/queue_wait", t0 - p.enqueued_at)
            try:
                replies = self.session.evaluate_batch(
                    [p.request for p in batch]
                )
            except Exception as e:  # noqa: BLE001 - the daemon must outlive any one batch
                # a failed batch answers its waiters (500) and the
                # dispatcher keeps serving; an unhandled raise here
                # would strand every queued request forever
                COUNTERS.inc("serve_batch_errors_total")
                replies = [
                    WhatIfReply(
                        status=500,
                        body=json.dumps(
                            {"error": f"evaluation failed: {e}"}
                        ).encode(),
                        meta={"engine": "error"},
                    )
                    for _ in batch
                ]
            tick_s = time.monotonic() - t0
            COUNTERS.observe("serve_tick_seconds", tick_s)
            HISTOS.observe("serve/evaluate", tick_s)
            for pending, reply in zip(batch, replies):
                reply.meta.setdefault("batchSize", len(batch))
                reply.meta["queueSeconds"] = round(
                    t0 - pending.enqueued_at, 6
                )
                self._finish_counted(pending, reply)
        self._drained.set()

    # -- shutdown -----------------------------------------------------------

    def close(self):
        """Stop intake; the dispatcher exits once the queue is empty."""
        with self._lock:
            self._closing = True
        self._wakeup.set()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every queued request is answered (True) or the
        timeout passes (False — leftovers are shed with the drain
        body so no handler thread waits forever)."""
        self.close()
        ok = self._drained.wait(timeout=timeout)
        if not ok:
            while True:
                with self._lock:
                    if not self._queue:
                        break
                    req = self._queue.popleft()
                COUNTERS.inc("serve_shed_total")
                COUNTERS.inc("serve_shed_draining_total")
                self._finish_counted(
                    req,
                    WhatIfReply(
                        status=503,
                        body=partial_body(
                            "drain",
                            "daemon shutting down before this request "
                            "could be evaluated",
                        ),
                        meta={"engine": "shed-drain"},
                    ),
                )
        return ok
