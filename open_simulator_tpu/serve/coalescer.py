"""Micro-batching request coalescer — the serving core of `simon serve`.

The shape is continuous batching from inference serving, applied to
what-if scheduling: HTTP handler threads only parse and enqueue; ONE
dispatcher thread drains up to ``max_batch`` queued requests per tick
and answers all of them with a single batched device dispatch
(serve/session.evaluate_batch), so B concurrent requests cost
``ceil(B / max_batch)`` dispatches instead of B — the counters at
``/metrics`` prove it (tests/test_serve.py asserts the bound).

Backpressure contract (docs/SERVING.md):

- the queue is BOUNDED (``queue_depth``): a submit against a full
  queue is rejected immediately — the HTTP layer turns that into
  503 + Retry-After, the shed counter increments, and the daemon's
  latency distribution stays honest instead of growing an unbounded
  tail (the same load-shedding posture as runtime/retry's circuit
  breakers: fail fast, recover fast)
- every request carries a ``Budget`` (runtime/budget.py): a request
  whose deadline expired while it sat in the queue is SHED at pickup
  with a machine-readable PARTIAL body — device time is never spent on
  an answer nobody is waiting for. Once dispatched, a request runs to
  completion (the scan has no per-request halt boundary).
- SIGTERM drains: ``close()`` stops intake (submits reject as
  draining), the dispatcher finishes every queued request, then the
  thread exits. ``drain(timeout)`` bounds the wait; leftovers past the
  timeout are shed with the drain body.

Single-dispatcher concurrency contract: all expansion, encode, scan,
and replay run on the dispatcher thread — the warm identity caches are
effectively single-threaded (docs/PERFORMANCE.md "warm-cache
concurrency contract"); handler threads touch only the queue, the
counters, and their own parsed request.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import List, Optional

from ..obs.histo import HISTOS
from ..runtime import inject as _inject
from ..runtime.budget import Budget
from ..utils.trace import COUNTERS
from .session import Session, WhatIfReply, WhatIfRequest

log = logging.getLogger(__name__)

# dispatcher-death watchdog poll interval: cheap enough to always run,
# fast enough that a died dispatcher answers its casualties typed well
# before any client's deadline
WATCHDOG_INTERVAL_S = 0.25


def partial_body(
    reason: str, message: str, request_id: Optional[str] = None
) -> bytes:
    """Machine-readable shed body — the HTTP analogue of the CLI's
    PARTIAL report (cli._emit_partial): same `partial`/`reason` keys,
    so one client-side parser reads both surfaces. ``request_id``
    (when the request got far enough to have one) rides along so a
    caller-supplied correlation ID survives the shed path verbatim."""
    doc = {"partial": True, "reason": reason, "message": message}
    if request_id:
        doc["requestId"] = request_id
    return json.dumps(doc).encode()


@dataclass
class PendingRequest:
    """One enqueued question plus its rendezvous with the handler
    thread (`done` fires when `reply` is set). ``route`` is the
    admission verdict: "batch" rides the coalesced scan, "serial"
    was routed to the host oracle (predicted-HBM / oversize —
    serve/admission.py). ``tenant`` attributes the request's
    counters."""

    request: WhatIfRequest
    budget: Budget
    route: str = "batch"
    tenant: str = "default"
    route_reason: str = ""
    # correlation ID (X-Simon-Request-Id or minted — obs/telemetry.py):
    # echoed in reply headers/shed bodies, stamped on the request's
    # span subtree, distinct per member of a coalesced batch
    request_id: str = ""
    # cross-process trace context (X-Simon-Trace-Context): the fleet
    # router's forward-span id + hop count. Span ids are process-local,
    # so the remote parent rides the serve/request root as an ATTR
    # (fleet/trace.py stitches the two id spaces into one tree)
    trace_parent: Optional[int] = None
    trace_hop: int = 0
    enqueued_at: float = field(default_factory=time.monotonic)
    # perf_counter twin of enqueued_at: synthesized per-request spans
    # (queue_wait/evaluate) must live in the recorder's clock domain
    enqueued_perf: float = field(default_factory=time.perf_counter)
    done: threading.Event = field(default_factory=threading.Event)
    reply: Optional[WhatIfReply] = None

    def finish(self, reply: WhatIfReply):
        self.reply = reply
        self.done.set()


class Coalescer:
    def __init__(
        self,
        session: Session,
        max_batch: int = 16,
        queue_depth: int = 64,
        on_tick=None,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        self.session = session
        self.max_batch = max_batch
        self.queue_depth = queue_depth
        self.on_tick = on_tick  # daemon hook (session-cache pressure check)
        self._queue: deque = deque()
        self._lock = threading.Lock()
        self._wakeup = threading.Event()
        self._closing = False
        self._drained = threading.Event()
        # requests popped from the queue but not yet answered: if the
        # dispatcher thread DIES mid-batch, the watchdog fails exactly
        # these typed instead of leaving their handlers waiting forever
        self._inflight_batch: List[PendingRequest] = []
        # tests set this to hold the dispatcher between ticks, so a
        # burst enqueued while held provably coalesces into one tick
        self.hold: Optional[threading.Event] = None
        # dispatcher-thread management is its own lock: the watchdog
        # swaps the thread while handler threads keep using _lock for
        # the queue (consistent order: _restart_lock before _lock)
        self._restart_lock = threading.Lock()
        self._thread = self._new_dispatcher()
        self._watchdog_thread = threading.Thread(
            target=self._watch, name="simon-serve-watchdog", daemon=True
        )
        self.restarts = 0

    def _new_dispatcher(self) -> threading.Thread:
        return threading.Thread(
            target=self._run, name="simon-serve-dispatcher", daemon=True
        )

    def start(self):
        with self._restart_lock:
            self._thread.start()
        self._watchdog_thread.start()

    # -- dispatcher watchdog ------------------------------------------------

    def _watch(self):
        """Monitor loop: as long as the coalescer is live, a died
        dispatcher thread is restarted and its in-flight requests are
        failed typed (docs/SERVING.md). Exits once the drain
        completes."""
        while not self._drained.wait(timeout=WATCHDOG_INTERVAL_S):
            self.ensure_dispatcher()

    def ensure_dispatcher(self) -> bool:
        """Restart a died dispatcher; returns True when a restart
        happened. The died thread's picked-but-unanswered requests are
        answered 500 with a typed body — a dead dispatcher must fail
        loudly, never wedge the queue behind handler threads waiting
        on replies that will never come."""
        with self._restart_lock:
            t = self._thread
            if t.is_alive() or not t.ident or self._drained.is_set():
                return False
            with self._lock:
                casualties = self._inflight_batch
                self._inflight_batch = []
            self.restarts += 1
            fresh = self._new_dispatcher()
            self._thread = fresh
        COUNTERS.inc("serve_watchdog_restarts_total")
        log.error(
            "serve dispatcher thread died; restarting (restart #%d, "
            "%d in-flight request(s) failed typed)",
            self.restarts, len(casualties),
        )
        for p in casualties:
            COUNTERS.inc("serve_dispatcher_casualties_total")
            self._finish_counted(
                p,
                WhatIfReply(
                    status=500,
                    body=json.dumps(
                        {
                            "error": "dispatcher thread died while this "
                            "request was being evaluated; the watchdog "
                            "restarted it",
                            "errorType": "ConformanceError",
                        }
                    ).encode(),
                    meta={"engine": "watchdog"},
                ),
            )
        fresh.start()
        self._wakeup.set()
        return True

    # -- intake (handler threads) -------------------------------------------

    def submit(self, req: PendingRequest) -> bool:
        """Enqueue; False = rejected (queue full or draining). The
        caller owns the 503 rendering."""
        with self._lock:
            if self._closing or len(self._queue) >= self.queue_depth:
                COUNTERS.inc("serve_shed_total")
                COUNTERS.inc(
                    "serve_shed_draining_total"
                    if self._closing
                    else "serve_shed_overload_total"
                )
                return False
            self._queue.append(req)
            COUNTERS.gauge("serve_queue_depth", len(self._queue))
        self._wakeup.set()
        return True

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def retry_after_s(self) -> int:
        """Overload hint: how long until the backlog plausibly clears,
        from the observed per-tick latency (>= 1s so clients never busy
        spin)."""
        tick_s = COUNTERS.mean("serve_tick_seconds") or 1.0
        ticks = max(1, -(-self.depth // self.max_batch))
        return max(1, int(round(ticks * tick_s)))

    def _finish_counted(self, pending: PendingRequest, reply: WhatIfReply):
        """Answer one request AND account for it: every answered
        request — simulate result or shed — counts in
        serve_requests_total and the latency window ('Requests
        answered (any status)', serve/server.render_metrics), so the
        exported distribution keeps its worst cases exactly when the
        daemon is shedding."""
        latency = time.monotonic() - pending.enqueued_at
        COUNTERS.observe("serve_latency_seconds", latency)
        # the long-memory histogram complement of the bounded-window
        # observation above: never evicts, exported as Prometheus
        # histogram exposition with p50/p95/p99 (obs/histo.py)
        HISTOS.observe("serve/request", latency)
        COUNTERS.mark("serve_completions")
        COUNTERS.inc("serve_requests_total")
        pending.finish(reply)

    # -- dispatch (the one dispatcher thread) -------------------------------

    def _drain_tick(self) -> List[PendingRequest]:
        """Take up to max_batch requests, shedding any whose deadline
        already expired in the queue (503 PARTIAL, no device time)."""
        picked: List[PendingRequest] = []
        while len(picked) < self.max_batch:
            with self._lock:
                if not self._queue:
                    break
                req = self._queue.popleft()
                COUNTERS.gauge("serve_queue_depth", len(self._queue))
            if req.budget.expired() or req.budget.interrupted:
                COUNTERS.inc("serve_shed_total")
                COUNTERS.inc("serve_shed_deadline_total")
                self._record_request_spans(req, evaluated=False)
                self._finish_counted(
                    req,
                    WhatIfReply(
                        status=503,
                        body=partial_body(
                            "deadline",
                            f"deadline of {req.budget.deadline_s:g}s expired "
                            f"after {req.budget.elapsed():.2f}s in the queue",
                            request_id=req.request_id,
                        ),
                        meta={"engine": "shed-deadline"},
                    ),
                )
                continue
            picked.append(req)
        return picked

    def _run(self):
        while True:
            if self.hold is not None:
                self.hold.wait()
            self._wakeup.wait(timeout=0.05)
            self._wakeup.clear()
            # chaos seam: `serve.tick` faults land HERE, on the
            # dispatcher thread — a `crash` clause kills the thread
            # (InjectedCrash is a BaseException) and the watchdog must
            # restart it; Exception-shaped faults ride the per-batch
            # recovery below once a batch is in flight
            _inject.fire("serve.tick")
            batch = self._drain_tick()
            if not batch:
                with self._lock:
                    if self._closing and not self._queue:
                        break
                continue
            with self._lock:
                self._inflight_batch = list(batch)
            # NO finally here: if _evaluate_tick dies (a crash-shaped
            # BaseException, or a bug in the reply bookkeeping), the
            # batch must STAY in _inflight_batch so the watchdog can
            # fail exactly these requests typed — clearing it on the
            # way down would strand their handlers waiting forever
            self._evaluate_tick(batch)
            with self._lock:
                self._inflight_batch = []
            if self.on_tick is not None:
                try:
                    self.on_tick()
                except Exception:  # noqa: BLE001 - a failing pressure hook must not kill the dispatcher
                    log.exception("serve on_tick hook failed")
        self._drained.set()

    def _evaluate_tick(self, batch: List[PendingRequest]):
        """Answer one tick's worth of picked requests: admission-
        routed serial requests individually through the host oracle,
        everything else in ONE coalesced device dispatch. Under the
        flight recorder, the tick is one ``serve/batch`` span LINKING
        every member's request ID, and each member gets its own
        synthesized span subtree (queue_wait / evaluate) stamped with
        its ID — N requests, N traceable subtrees, zero extra device
        work."""
        from ..obs.spans import RECORDER

        t0 = time.monotonic()
        t0_perf = time.perf_counter()
        COUNTERS.observe("serve_batch_fill", len(batch))
        COUNTERS.inc("serve_batches_total")
        for p in batch:
            HISTOS.observe("serve/queue_wait", t0 - p.enqueued_at)
        scan = [p for p in batch if p.route != "serial"]
        serial = [p for p in batch if p.route == "serial"]
        with RECORDER.span(
            "serve/batch",
            requests=len(batch),
            request_ids=[p.request_id for p in batch if p.request_id],
        ) as batch_span:
            replies: List[WhatIfReply] = []
            if scan:
                try:
                    replies = self.session.evaluate_batch(
                        [p.request for p in scan]
                    )
                except Exception as e:  # noqa: BLE001 - the daemon must outlive any one batch
                    # a failed batch answers its waiters (500) and the
                    # dispatcher keeps serving; an unhandled raise here
                    # would strand every queued request forever
                    COUNTERS.inc("serve_batch_errors_total")
                    replies = [
                        self._error_reply(e, p.request_id) for p in scan
                    ]
            serial_replies: List[WhatIfReply] = []
            for p in serial:
                try:
                    serial_replies.append(
                        self.session.evaluate_serial(
                            p.request, reason=p.route_reason or "admission"
                        )
                    )
                except Exception as e:  # noqa: BLE001 - ditto: one bad serial request must not strand the rest
                    COUNTERS.inc("serve_batch_errors_total")
                    serial_replies.append(
                        self._error_reply(e, p.request_id)
                    )
        tick_s = time.monotonic() - t0
        t1_perf = time.perf_counter()
        COUNTERS.observe("serve_tick_seconds", tick_s)
        HISTOS.observe("serve/evaluate", tick_s)
        for pending, reply in list(zip(scan, replies)) + list(
            zip(serial, serial_replies)
        ):
            reply.meta.setdefault("batchSize", len(batch))
            reply.meta["queueSeconds"] = round(t0 - pending.enqueued_at, 6)
            self._record_request_spans(
                pending,
                evaluated=True,
                t0_perf=t0_perf,
                t1_perf=t1_perf,
                batch_span=batch_span,
                engine=str(reply.meta.get("engine", "")),
            )
            self._finish_counted(pending, reply)

    @staticmethod
    def _record_request_spans(
        pending: PendingRequest,
        evaluated: bool,
        t0_perf: Optional[float] = None,
        t1_perf: Optional[float] = None,
        batch_span=None,
        engine: str = "",
    ):
        """Synthesize one request's span subtree from timings the
        dispatcher already measured: a ``serve/request`` root spanning
        enqueue -> answer, with ``queue_wait`` and (when the request
        was evaluated rather than shed) ``evaluate`` children — each
        stamped with the request's own ID, the batch span linked on
        the root. Host-side bookkeeping only: correlation costs zero
        jit-cache misses by construction (CI-gated)."""
        from ..obs.spans import RECORDER

        if not RECORDER.enabled:
            return
        now_perf = time.perf_counter()
        attrs = {"request_id": pending.request_id or None}
        if batch_span is not None:
            attrs["batch_span"] = batch_span
        if engine:
            attrs["engine"] = engine
        if pending.trace_parent is not None:
            attrs["remote_parent"] = pending.trace_parent
            attrs["fleet_hop"] = pending.trace_hop
        if not evaluated:
            attrs["shed"] = True
        root = RECORDER.record_span(
            "serve/request", pending.enqueued_perf, now_perf, **attrs
        )
        if root is None:
            return
        wait_end = t0_perf if evaluated and t0_perf is not None else now_perf
        RECORDER.record_span(
            "serve/request/queue_wait",
            pending.enqueued_perf,
            wait_end,
            parent_id=root,
            request_id=pending.request_id or None,
        )
        if evaluated and t0_perf is not None and t1_perf is not None:
            RECORDER.record_span(
                "serve/request/evaluate",
                t0_perf,
                t1_perf,
                parent_id=root,
                request_id=pending.request_id or None,
            )

    @staticmethod
    def _error_reply(e: Exception, request_id: str = "") -> WhatIfReply:
        """Typed 500 body: the taxonomy class name rides along so a
        client (and the chaos matrix) can route on the failure kind
        without parsing message text."""
        doc = {
            "error": f"evaluation failed: {e}",
            "errorType": type(e).__name__,
        }
        if request_id:
            doc["requestId"] = request_id
        return WhatIfReply(
            status=500,
            body=json.dumps(doc).encode(),
            meta={"engine": "error"},
        )

    # -- shutdown -----------------------------------------------------------

    def close(self):
        """Stop intake; the dispatcher exits once the queue is empty."""
        with self._lock:
            self._closing = True
        self._wakeup.set()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every queued request is answered (True) or the
        timeout passes (False — leftovers are shed with the drain
        body so no handler thread waits forever)."""
        self.close()
        ok = self._drained.wait(timeout=timeout)
        if not ok:
            while True:
                with self._lock:
                    if not self._queue:
                        break
                    req = self._queue.popleft()
                COUNTERS.inc("serve_shed_total")
                COUNTERS.inc("serve_shed_draining_total")
                self._finish_counted(
                    req,
                    WhatIfReply(
                        status=503,
                        body=partial_body(
                            "drain",
                            "daemon shutting down before this request "
                            "could be evaluated",
                            request_id=req.request_id,
                        ),
                        meta={"engine": "shed-drain"},
                    ),
                )
        return ok
