"""Warm-session LRU + crash-safe session snapshot journal.

ROADMAP item 2's multi-tenancy shape: a fleet daemon holds MANY warm
``Session``s (one per cluster fingerprint), each pinning an Oracle,
a ClusterStatic encoding, and compiled executables in device memory.
Device memory is finite; this module bounds the fleet:

- **LRU by capacity** (``--max-sessions``): admitting a session past
  the bound evicts the least-recently-used one (its encodings and
  jit-cache references become collectable; the next request for that
  cluster pays a rebuild, not an OOM).
- **Ledger-pressure eviction**: the coalescer's tick callback asks
  ``check_pressure()`` — when the device-memory ledger reports live
  bytes past the pressure fraction of the budget, the LRU session is
  evicted BEFORE the next dispatch OOMs (the predictive posture of
  obs/ledger.py applied to session state instead of chunk sizes).
- The **primary** session (the daemon's configured cluster) is
  pinned: eviction applies to secondaries only, so `simon serve`
  never sheds the cluster it was started for.

Every admit/evict/drain appends one record to the **session snapshot
journal** (``--snapshot PATH``) — the serve instance of the PR-2
crash-safe JSONL discipline (fsync per append, torn tail recovered,
interior damage refused loudly) and the fourth JSONL writer in the
torn-tail chaos matrix (tests/test_torn_tail.py). A restarted daemon
resumes the snapshot and logs which clusters were warm when the
previous process died — the warm-restart signal for item 3's
persisted compile artifacts.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import List, Optional

from ..runtime.journal import Journal, config_fingerprint
from ..utils.trace import COUNTERS

#: fraction of the device budget past which the cache starts evicting
PRESSURE_FRACTION = 0.85

SNAPSHOT_VERSION = 1


class SessionSnapshotJournal(Journal):
    """The serve-subsystem journal: same format/recovery machinery,
    its own fault-injection crash point."""

    inject_site = "journal.fsync.serve"


def open_snapshot(path: str) -> SessionSnapshotJournal:
    """Create-or-resume the session snapshot at ``path`` (the
    ``--journal`` semantics: idempotent across daemon restarts)."""
    fp = config_fingerprint(
        {"format": "serve-session-snapshot", "version": SNAPSHOT_VERSION}
    )
    return SessionSnapshotJournal.open(path, fp)


def serve_keep_record(fingerprint: str):
    """The serve snapshot's checkpoint-compaction predicate: a verified
    checkpoint at delta seq N absorbs every journaled delta for this
    cluster with ``seq <= N``, so compaction drops exactly those.
    Non-delta records (admit/evict/drain), other clusters' deltas, and
    deltas past N are retained. Deltas journaled WITHOUT a seq (a
    pre-checkpoint-era journal) are also dropped: they were present
    when the checkpoint captured the session, hence absorbed by
    definition — and restore refuses to blind-apply unsequenced
    records on top of a checkpoint anyway (fleet/replay.py counts
    them loudly instead)."""

    def keep(rec: dict, upto_seq: int) -> bool:
        if (
            rec.get("kind") != "session"
            or rec.get("event") != "delta"
            or rec.get("fingerprint") != fingerprint
        ):
            return True
        seq = rec.get("seq")
        return isinstance(seq, int) and seq > upto_seq

    return keep


class SessionCache:
    """Fingerprint-keyed LRU of warm Sessions. All mutation under one
    lock; eviction never runs device work (dropping references is the
    whole point)."""

    def __init__(
        self,
        capacity: int = 8,
        snapshot: Optional[Journal] = None,
        pressure_fraction: float = PRESSURE_FRACTION,
    ):
        if capacity < 1:
            raise ValueError(f"session capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.pressure_fraction = pressure_fraction
        self._snapshot = snapshot
        self._lock = threading.Lock()
        self._sessions: "OrderedDict[str, object]" = OrderedDict()
        self._pinned: set = set()
        self.evictions = 0

    # -- snapshot ------------------------------------------------------------

    def _record(self, event: str, fingerprint: str, **extra):
        if self._snapshot is None:
            return
        self._snapshot.append(
            {"kind": "session", "event": event, "fingerprint": fingerprint, **extra}
        )

    def record_delta(
        self,
        fingerprint: str,
        delta_record: dict,
        request_id: str = "",
        seq: Optional[int] = None,
    ):
        """Journal one applied cluster delta (POST /v1/cluster-delta):
        the snapshot then carries not just WHICH clusters were warm at
        a crash but what delta stream their warm state had absorbed —
        fsync'd per append like every session event. ``request_id``
        correlates the journal line with the HTTP request that carried
        the delta (the X-Simon-Request-Id contract); ``seq`` is the
        exact session delta sequence the apply assigned — the handle
        checkpoint compaction and snapshot-then-suffix replay filter
        on (fleet/replay.py)."""
        extra = {}
        if request_id:
            extra["requestId"] = request_id
        if seq is not None:
            extra["seq"] = int(seq)
        self._record("delta", fingerprint, delta=delta_record, **extra)

    # -- membership ----------------------------------------------------------

    def add(self, session, pinned: bool = False) -> List[str]:
        """Admit a session (most-recently-used position); returns the
        fingerprints evicted to stay within capacity."""
        fp = session.fingerprint
        with self._lock:
            self._sessions[fp] = session
            self._sessions.move_to_end(fp)
            if pinned:
                self._pinned.add(fp)
            evicted = self._evict_over_capacity_locked()
        self._record("admit", fp, pinned=pinned)
        for gone in evicted:
            self._note_eviction(gone, "capacity")
        COUNTERS.gauge("serve_sessions", float(len(self)))
        return evicted

    def get(self, fingerprint: str):
        """The warm session for a fingerprint (refreshes recency), or
        None — the caller builds and ``add``s."""
        with self._lock:
            s = self._sessions.get(fingerprint)
            if s is not None:
                self._sessions.move_to_end(fingerprint)
        return s

    def __len__(self) -> int:
        with self._lock:
            return len(self._sessions)

    def fingerprints(self) -> List[str]:
        with self._lock:
            return list(self._sessions)

    # -- eviction ------------------------------------------------------------

    def _evict_over_capacity_locked(self) -> List[str]:  # simonlint: disable=CONC001 - caller holds self._lock (the _locked suffix contract)
        evicted = []
        # oldest-first walk; pinned sessions are skipped, so a cache
        # of only pinned sessions can exceed capacity by their count
        while len(self._sessions) > self.capacity:
            victim = next(
                (fp for fp in self._sessions if fp not in self._pinned), None
            )
            if victim is None:
                break
            del self._sessions[victim]
            evicted.append(victim)
        return evicted

    def _note_eviction(self, fingerprint: str, reason: str):
        with self._lock:
            self.evictions += 1
        COUNTERS.inc("serve_session_evictions_total")
        COUNTERS.inc(f"serve_session_evictions_{reason}_total")
        COUNTERS.gauge("serve_sessions", float(len(self)))
        self._record("evict", fingerprint, reason=reason)

    def evict_lru(self, reason: str) -> Optional[str]:
        """Drop the least-recently-used unpinned session; returns its
        fingerprint (None when nothing is evictable)."""
        with self._lock:
            victim = next(
                (fp for fp in self._sessions if fp not in self._pinned), None
            )
            if victim is None:
                return None
            del self._sessions[victim]
        self._note_eviction(victim, reason)
        return victim

    def check_pressure(self) -> Optional[str]:
        """Ledger-pressure hook (called from the coalescer's tick
        callback): when live device bytes exceed the pressure fraction
        of the known budget, evict the LRU session. Returns the
        evicted fingerprint, or None (no budget known / no pressure /
        nothing evictable)."""
        from ..obs.ledger import device_memory_stats

        in_use, limit, _src = device_memory_stats()
        if not limit or in_use <= limit * self.pressure_fraction:
            return None
        return self.evict_lru("ledger_pressure")

    # -- lifecycle -----------------------------------------------------------

    def drain(self):
        """Journal the surviving sessions at shutdown (the warm-state
        inventory a restarted daemon reads back) and close the
        snapshot."""
        for fp in self.fingerprints():
            self._record("drain", fp)
        if self._snapshot is not None:
            self._snapshot.close()

    def stats(self) -> dict:
        with self._lock:
            return {
                "sessions": len(self._sessions),
                "pinned": len(self._pinned),
                "capacity": self.capacity,
                "evictions": self.evictions,
            }
