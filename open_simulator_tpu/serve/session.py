"""Warm what-if session: one loaded cluster, many simulate questions.

The one-shot CLI pays process startup, cluster build, encode, and XLA
compile for every question (SURVEY.md §0; the reference's
pkg/simulator/core.go:64-103 is strictly one-shot). A ``Session`` loads
the cluster ONCE and keeps everything derivable from it warm across
requests:

- the ``Oracle`` over the cluster nodes (never mutated — replay happens
  on per-request oracles), whose ``ClusterStatic`` encoding is cached
  inside the shared ``TpuEngine``
- the expanded cluster pods and the generated-name counter state after
  their expansion, replayed before every request's app expansion so a
  coalesced request mints exactly the pod names a standalone
  ``simulate()`` would (models/workloads.name_counter_state)
- the jitted scenario scan (engine._scenario_scan_jit): same-shaped
  request batches across dispatches hit the jit cache

``evaluate_batch`` answers B requests with ONE device dispatch: each
request becomes one scenario row of a batched masked scan — the same
per-scenario pod-activity masking the capacity sweep and the chaos
engine use (parallel/sweep.py) — and each row's placements replay into
a fresh per-request oracle for the report. Responses are byte-identical
to a standalone ``simulate()`` of the same request (conformance-gated,
tests/test_serve.py); requests the batched scan cannot model (priority
/ preemption semantics, per-pod host callbacks) fall back to a real
``simulate()`` call inside the dispatcher, so the answer is identical
either way — only the latency differs.

The session is keyed by a fingerprint of the loaded cluster
(runtime/journal.config_fingerprint), reported at ``/healthz`` so
clients can detect a daemon serving stale state after a config change.
"""

from __future__ import annotations

import copy
import json
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from ..models import workloads as wl
from ..models.decode import ResourceTypes
from ..runtime.journal import config_fingerprint
from ..scheduler.core import (
    AppResource,
    NodeStatus,
    SimulateResult,
    UnscheduledPod,
    _sort_app_pods,
    simulate,
)
from ..scheduler.oracle import Oracle
from ..utils.trace import COUNTERS

# pod absent from a scenario — must match the scan sentinel
# (parallel/sweep.py asserts the same identity against ops.scan)
INACTIVE = -2


@dataclass
class WhatIfRequest:
    """One decoded /v1/simulate question: apps in deployment order.
    ``tenant`` is the accounting identity (JSON envelope ``tenant``
    key / X-Simon-Tenant header) — it never changes the answer, only
    whose counters the request lands in (serve/admission.py)."""

    apps: List[AppResource]
    tenant: str = "default"


@dataclass
class WhatIfReply:
    """The evaluated answer. `body` is the canonical response bytes
    (byte-identical across the coalesced and serial paths); `meta` is
    per-request diagnostics exported as HTTP headers, NEVER mixed into
    the body (a batch-dependent body would break the conformance
    contract)."""

    status: int
    body: bytes
    meta: dict = field(default_factory=dict)


def result_payload(result: SimulateResult) -> bytes:
    """Canonical response body of one simulate answer. Key-sorted,
    separator-normalized JSON: the bytes are a pure function of the
    placements and reasons, so coalesced and standalone evaluations of
    the same request compare equal byte-for-byte."""
    out = {
        "success": not result.unscheduled_pods,
        "unscheduledPods": [
            {
                "namespace": (up.pod.get("metadata") or {}).get("namespace"),
                "name": (up.pod.get("metadata") or {}).get("name"),
                "reason": up.reason,
            }
            for up in result.unscheduled_pods
        ],
        "nodes": [
            {
                "name": (ns.node.get("metadata") or {}).get("name"),
                "pods": [
                    {
                        "namespace": (p.get("metadata") or {}).get("namespace"),
                        "name": (p.get("metadata") or {}).get("name"),
                        "app": ((p.get("metadata") or {}).get("labels") or {}).get(
                            "simon/app-name"
                        ),
                    }
                    for p in ns.pods
                ],
            }
            for ns in result.node_status
        ],
    }
    return json.dumps(out, sort_keys=True, separators=(",", ":")).encode()


# Shallow-clone of a pod's mutation surface (bind writes spec.nodeName
# / status.phase / metadata.annotations) so replaying a scenario never
# pollutes the shared cluster pods or a request's expansion — the next
# batch re-encodes those dicts and a stale nodeName would read as a
# pin. ONE definition, shared with the committed-scan machinery: the
# mutation surface must never diverge between the two replay paths.
from ..incremental.resim import own_pod as _own_pod  # noqa: E402


class Session:
    """One warm cluster + the machinery to answer request batches.

    With ``incremental`` (the default; ``--no-incremental`` disables),
    the session keeps its cluster pods COMMITTED in a resident oracle
    (incremental/resim.CommittedScan): each what-if tick then scans
    ONLY the request pods (the suffix) against that warm state instead
    of re-scanning the whole roster per scenario row, and a
    ``/v1/cluster-delta`` re-simulates only the journal suffix the
    conservative dependency rule says could change. Bodies stay
    byte-identical to the full path (conformance-gated); ineligible
    clusters (priority, plugins) and classified faults degrade to the
    full path, counted and trace-noted."""

    def __init__(self, cluster: ResourceTypes, incremental: bool = True):
        import threading

        from ..scheduler.engine import TpuEngine
        from ..scheduler.preemption import build_priority_resolver, pod_uses_priority
        from ..utils.trace import phase

        self.cluster = cluster
        self.incremental = bool(incremental)
        self._committed = None  # CommittedScan, built lazily
        self._committed_broken = False  # classified build fault: stay full
        self.fingerprint = config_fingerprint(
            {k: getattr(cluster, k) for k in sorted(vars(cluster))}
        )
        # delta application (apply_delta) vs the dispatcher's ticks:
        # one reentrant lock serializes roster/oracle mutation against
        # batch evaluation (the dispatcher is single-threaded, but
        # /v1/cluster-delta arrives on handler threads). A _reload()
        # re-runs this constructor while HOLDING the lock — it must
        # never be rebound mid-rebuild, or a concurrent thread would
        # acquire a fresh unheld lock and see a half-built session
        if getattr(self, "_delta_lock", None) is None:
            self._delta_lock = threading.RLock()
        self.delta_seq = 0
        self.delta_reloads = 0
        with phase("serve/session-build"):
            wl.reset_name_counter()
            pods: List[dict] = []
            pods.extend(wl.pods_excluding_daemon_sets(cluster))
            # bare cluster pods expand 1:1 and FIRST; delta arrivals
            # insert at the end of that section so warm roster order
            # equals the cold expansion order of the materialized
            # cluster (cluster.pods + deltas, then workloads, then
            # daemonsets)
            self._bare_end = len(cluster.pods)
            for ds in cluster.daemon_sets:
                pods.extend(wl.pods_from_daemon_set(ds, cluster.nodes))
            self.cluster_pods = pods
            # every request's app expansion restarts from this state
            self._counter0 = wl.name_counter_state()
            self.oracle = Oracle(cluster.nodes)
            self.engine = TpuEngine(self.oracle)
            self._resolver = build_priority_resolver(cluster.priority_classes)
            # the batched scan cannot model priority/preemption or
            # per-pod host callbacks; a cluster that carries either
            # routes EVERY request through the serial path. The gate
            # must cover every condition scheduler/core treats as
            # scan-breaking, or batched answers would diverge from
            # simulate(): permit/stateful hooks (needs_serial), a
            # custom queue-sort comparator (reorders pods before the
            # scan would see them), a custom post_filter (acts on ANY
            # failed pod — core routes those through the escape path),
            # and priority-bearing cluster pods
            self.force_serial_reason = ""
            registry = self.oracle.registry
            if registry.needs_serial:
                self.force_serial_reason = "plugin registry needs serial engine"
            elif registry.queue_sort_plugin is not None:
                self.force_serial_reason = "custom queue-sort plugin orders pods"
            elif registry.has_post_filter:
                self.force_serial_reason = "custom post_filter plugin registered"
            elif any(pod_uses_priority(p, self._resolver) for p in pods):
                self.force_serial_reason = "cluster pods carry priority"
            self._pod_uses_priority = pod_uses_priority

    def state_digest(self) -> str:
        """Canonical digest of the delta-mutated session state (node
        set + pod roster) — the fleet dict-identity gate
        (docs/FLEET.md): a journal-replayed replacement replica must
        report the SAME digest as the replica it replaced. Cheap on
        purpose: no committed-scan build, no device work, so
        GET /v1/state-digest is safe to poll."""
        from ..runtime.journal import config_fingerprint

        with self._delta_lock:
            return config_fingerprint(
                [
                    (n.get("metadata") or {}).get("name")
                    for n in self.cluster.nodes
                ],
                self.cluster_pods,
            )

    def warm(self):
        """Pre-compile the scan for a small request shape and build the
        ClusterStatic encoding, so the first real request does not pay
        the daemon's cold start. Real traffic with other shapes still
        compiles once per shape (jit cache, persistent across
        requests)."""
        warm_app = ResourceTypes()
        warm_app.pods = [
            {
                "kind": "Pod",
                "metadata": {"name": "serve-warm", "namespace": "default"},
                "spec": {
                    "containers": [
                        {
                            "name": "c",
                            "image": "warm",
                            "resources": {
                                "requests": {"cpu": "1m", "memory": "1Mi"}
                            },
                        }
                    ],
                    "schedulerName": "default-scheduler",
                },
            }
        ]
        self.evaluate_batch(
            [WhatIfRequest(apps=[AppResource("serve-warm", warm_app)])]
        )

    # -- expansion ----------------------------------------------------------

    def _expand_request(self, req: WhatIfRequest) -> List[dict]:
        """Expand one request's apps exactly like a standalone run:
        counter re-seated to the post-cluster state, apps in order,
        each app's pods through the affinity/toleration queue sorts
        (the zero-priority ordering of scheduler/core.schedule_app)."""
        wl.set_name_counter(self._counter0)
        pods: List[dict] = []
        for app in req.apps:
            app_pods = wl.generate_valid_pods_from_app(
                app.name, app.resource, self.cluster.nodes
            )
            pods.extend(_sort_app_pods(app_pods))
        return pods

    # -- evaluation ---------------------------------------------------------

    def evaluate_batch(self, reqs: List[WhatIfRequest]) -> List[WhatIfReply]:
        """Answer every request of one coalesced tick: expansion and
        routing per request, then ONE batched device dispatch for all
        scan-eligible scenarios (chunk-halving on device OOM, serial
        host-oracle floor — runtime/guard.run_chunked), then per
        request a replay into a fresh oracle and the canonical body.

        Under `--trace-out` each tick is one span on the dispatcher
        thread's own tree (`serve/tick`, batch size attached), with the
        expand/encode/scan/replay phases nesting below it."""
        from ..obs.spans import RECORDER

        with RECORDER.span("serve/tick", requests=len(reqs)):
            # deltas (/v1/cluster-delta, handler threads) never land
            # mid-tick: a batch evaluates against one consistent state
            with self._delta_lock:
                return self._evaluate_batch(reqs)

    def _evaluate_batch(self, reqs: List[WhatIfRequest]) -> List[WhatIfReply]:
        from ..models.validation import InputError
        from ..runtime.guard import run_chunked
        from ..utils.trace import phase

        replies: List[Optional[WhatIfReply]] = [None] * len(reqs)
        expanded: List[Optional[List[dict]]] = [None] * len(reqs)
        batched: List[int] = []
        with phase("serve/expand"):
            for r_i, req in enumerate(reqs):
                try:
                    pods = self._expand_request(req)
                except (InputError, ValueError, KeyError) as e:
                    replies[r_i] = WhatIfReply(
                        status=400,
                        body=json.dumps(
                            {"error": f"invalid request: {e}"}
                        ).encode(),
                        meta={"engine": "rejected"},
                    )
                    continue
                expanded[r_i] = pods
                if self.force_serial_reason or any(
                    self._pod_uses_priority(p, self._resolver) for p in pods
                ):
                    replies[r_i] = self._evaluate_serial(
                        req,
                        reason=self.force_serial_reason
                        or "request carries priority",
                    )
                else:
                    batched.append(r_i)
        if not batched:
            return replies

        # one pod axis for the whole tick: cluster pods first (active
        # in every scenario), then each request's pods (active only in
        # its own row) — scenario r's scan order equals the standalone
        # run's schedule order. With a committed scan resident
        # (incremental/resim.py) the cluster pods are ALREADY committed
        # in its warm oracle, so the pod axis carries only the request
        # pods (the suffix) and the roster is never re-scanned — the
        # sequential-commit property keeps placements identical
        # (exactly the multi-batch contract of schedule_app)
        committed = self._committed_scan()
        scan_engine = committed.engine if committed is not None else self.engine
        scan_oracle = committed.oracle if committed is not None else self.oracle
        all_pods = [] if committed is not None else list(self.cluster_pods)
        req_span = {}
        for r_i in batched:
            lo = len(all_pods)
            all_pods.extend(expanded[r_i])
            req_span[r_i] = (lo, len(all_pods))
        node_index = scan_oracle.node_index
        # pods pinned to unknown nodes never reach the scheduler
        # (begin_batch contract; reference simulator.go:221-229)
        pos_of = np.full(len(all_pods), -1, dtype=np.int64)
        batch_idx = []
        for i, pod in enumerate(all_pods):
            name = (pod.get("spec") or {}).get("nodeName")
            if name and name not in node_index:
                continue
            pos_of[i] = len(batch_idx)
            batch_idx.append(i)
        n_batch = len(batch_idx)
        n_cluster = len(all_pods) - sum(
            hi - lo for lo, hi in req_span.values()
        )

        bidx_arr = np.asarray(batch_idx, dtype=np.int64)
        actives = np.zeros((len(batched), n_batch), dtype=bool)
        for row, r_i in enumerate(batched):
            lo, hi = req_span[r_i]
            actives[row] = (bidx_arr < n_cluster) | (
                (bidx_arr >= lo) & (bidx_arr < hi)
            )
        if committed is not None:
            # suffix accounting: this tick dispatched only the request
            # pods; the committed roster rode along as warm state
            COUNTERS.inc("incremental_suffix_pods_total", n_batch)
            COUNTERS.inc(
                "incremental_prefix_reused_pods_total", committed.total
            )

        if n_batch:
            with phase("serve/encode"):
                scan_engine.begin_batch([all_pods[i] for i in batch_idx])

            def evaluate(lo, hi):
                COUNTERS.inc("serve_device_dispatches_total")
                rows = scan_engine.scan_scenarios(actives[lo:hi])
                return [np.asarray(r) for r in rows]

            def serial_fallback(i):
                return self._serial_placements(
                    actives[i], batch_idx, all_pods, base=committed
                )

            from ..obs.costs import COSTS

            rows = run_chunked(
                evaluate,
                len(batched),
                label="serve",
                serial_fallback=serial_fallback,
                estimate=COSTS.chunk_estimator("scenario_scan"),
            )
        else:
            rows = [np.zeros(0, dtype=np.int64) for _ in batched]

        with phase("serve/replay"):
            for row, r_i in enumerate(batched):
                lo, hi = req_span[r_i]
                # lo >= n_cluster always, so this is scan order
                scenario_pods = [
                    (i, all_pods[i])
                    for i in list(range(n_cluster)) + list(range(lo, hi))
                ]
                meta = {"engine": "coalesced-scan"}
                if committed is not None:
                    result = self._assemble_incremental(
                        committed, scenario_pods, rows[row], pos_of
                    )
                    # same coalesced contract, suffix-only dispatch;
                    # the body stays byte-identical — only this
                    # diagnostic header differs
                    meta["incremental"] = "suffix"
                else:
                    result = self._replay(scenario_pods, rows[row], pos_of)
                replies[r_i] = WhatIfReply(
                    status=200, body=result_payload(result), meta=meta
                )
        return replies

    # -- incremental committed state (incremental/resim.py) -----------------

    def _committed_scan(self):
        """The resident CommittedScan, built lazily at the first
        eligible batched tick (so daemon warm-up pays the one full
        scan, not the first caller). None = run the full per-tick
        path: incremental off, cluster ineligible (serial reasons),
        or a classified fault latched the degradation."""
        if (
            not self.incremental
            or self.force_serial_reason
            or self._committed_broken
        ):
            return None
        if self._committed is None:
            from ..incremental.resim import CommittedScan
            from ..runtime.errors import (
                BackendUnavailable,
                CompileFailure,
                DeviceOOM,
                ExternalIOError,
            )
            from ..utils.trace import GLOBAL

            try:
                self._committed = CommittedScan(
                    self.cluster.nodes, self.cluster_pods
                )
            except (
                DeviceOOM, CompileFailure, BackendUnavailable,
                ExternalIOError,
            ) as e:
                import logging

                COUNTERS.inc("incremental_fallbacks_total")
                GLOBAL.note(
                    "incremental-degraded",
                    f"committed build: {type(e).__name__}",
                )
                logging.getLogger(__name__).warning(
                    "incremental committed scan unavailable (%s); serving "
                    "the full per-tick scan path", e,
                )
                self._committed_broken = True
                return None
        return self._committed

    def _update_committed(self, kind, positions=(), insert_position=None):
        """Delta follow-up: re-simulate the affected journal suffix of
        the resident committed scan (suffix_for_delta's conservative
        rule), falling back to the full re-scan — identical results —
        on a classified fault. Caller holds the delta lock."""
        if self._committed is None:
            return
        if self.force_serial_reason:
            # the delta made the cluster scan-ineligible (priority):
            # every later request routes serial; drop the warm state
            self._committed = None
            return
        from ..incremental.resim import CommittedScan, suffix_for_delta
        from ..runtime.errors import (
            BackendUnavailable,
            CompileFailure,
            DeviceOOM,
            ExternalIOError,
        )
        from ..utils.trace import GLOBAL

        committed = self._committed
        decision = suffix_for_delta(
            kind,
            len(self.cluster_pods),
            positions=positions,
            insert_position=insert_position,
            has_side_effects=not committed.bulk_eligible,
        )
        try:
            if decision.trivial:
                return
            if decision.full:
                GLOBAL.note("incremental-full-rescan", decision.reason)
                COUNTERS.inc("incremental_full_rebuilds_total")
                self._committed = CommittedScan(
                    self.cluster.nodes, self.cluster_pods
                )
            else:
                self._committed = committed.resimulate(
                    self.cluster_pods, decision.start
                )
        except (
            DeviceOOM, CompileFailure, BackendUnavailable, ExternalIOError,
        ) as e:
            import logging

            COUNTERS.inc("incremental_fallbacks_total")
            GLOBAL.note(
                "incremental-degraded", f"{kind}: {type(e).__name__}"
            )
            logging.getLogger(__name__).warning(
                "incremental suffix re-simulation degraded to a full "
                "re-scan (%s)", e,
            )
            try:
                COUNTERS.inc("incremental_full_rebuilds_total")
                self._committed = CommittedScan(
                    self.cluster.nodes, self.cluster_pods
                )
            except (
                DeviceOOM, CompileFailure, BackendUnavailable,
                ExternalIOError,
            ):
                # even the full re-scan is faulting: revert to the
                # (guard-laddered) per-tick path until a reload
                self._committed = None
                self._committed_broken = True

    def _assemble_incremental(
        self, committed, scenario_pods, placements, pos_of
    ) -> SimulateResult:
        """One scenario's SimulateResult on top of the committed
        prefix. All-placed scenarios (the warm common case) append the
        request placements to the committed node lists — zero host
        replay of the roster. A scenario with failures takes the
        exact-reasons path: a scratch oracle seeded from the committed
        state, request pods replayed per the engine-replay contract,
        so reasons read their own step's state — still no device
        work. Committed-pod failures carry their build-time reasons
        (same prefix state, deterministic formula)."""
        oracle = committed.oracle
        has_failure = False
        for i, pod in scenario_pods:
            pos = int(pos_of[i])
            if pos < 0:
                continue
            place = int(placements[pos])
            if place == INACTIVE:
                continue
            if place < 0 and not (pod.get("spec") or {}).get("nodeName"):
                has_failure = True
                break
        if has_failure:
            return self._replay_on_committed(
                committed, scenario_pods, placements, pos_of
            )
        appended = {}
        for i, pod in scenario_pods:
            pos = int(pos_of[i])
            if pos < 0:
                continue  # dangling: tracked, absent from node status
            place = int(placements[pos])
            if place == INACTIVE:  # pragma: no cover - defensive
                continue
            name = (pod.get("spec") or {}).get("nodeName")
            idx = oracle.node_index[name] if name else place
            appended.setdefault(int(idx), []).append(pod)
        status = [
            NodeStatus(
                node=ns.node,
                pods=list(ns.pods) + appended.get(idx, []),
            )
            for idx, ns in enumerate(oracle.nodes)
        ]
        return SimulateResult(
            unscheduled_pods=list(committed.failed), node_status=status
        )

    def _replay_on_committed(
        self, committed, scenario_pods, placements, pos_of
    ) -> SimulateResult:
        """Exact-reasons scenario replay: scratch oracle holding the
        committed state (host-only place_existing walk over the
        committed node lists — the twin's _scratch_oracle pattern),
        then the request pods in scan order."""
        oracle = Oracle([ns.node for ns in committed.oracle.nodes])
        for ns in committed.oracle.nodes:
            for p in ns.pods:
                oracle.place_existing_pod(_own_pod(p))
        failed: List[UnscheduledPod] = list(committed.failed)
        for i, pod in scenario_pods:
            pos = int(pos_of[i])
            if pos < 0:
                continue
            place = int(placements[pos])
            if place == INACTIVE:  # pragma: no cover - defensive
                continue
            pod2 = _own_pod(pod)
            if (pod.get("spec") or {}).get("nodeName"):
                oracle.place_existing_pod(pod2)
            elif place < 0:
                _, reasons, _ = oracle._find_feasible(pod2)
                failed.append(
                    UnscheduledPod(
                        pod=pod2,
                        reason=Oracle._failure_message(pod2, reasons),
                    )
                )
            else:
                oracle._reserve_and_bind(pod2, oracle.nodes[place])
        status = [
            NodeStatus(node=ns.node, pods=list(ns.pods)) for ns in oracle.nodes
        ]
        return SimulateResult(unscheduled_pods=failed, node_status=status)

    def _replay(self, scenario_pods, placements, pos_of) -> SimulateResult:
        """Mirror one scenario's placements into a fresh host oracle in
        scan order — the engine-replay contract of scheduler/engine.py:
        failure reasons read the oracle state of their own step, so
        they match what the standalone run reports. Pods replay as
        copies (_own_pod): the session's shared dicts stay pristine for
        the next batch's encode."""
        oracle = Oracle([ns.node for ns in self.oracle.nodes])
        failed: List[UnscheduledPod] = []
        for i, pod in scenario_pods:
            pos = int(pos_of[i])
            pod2 = _own_pod(pod)
            if pos < 0:
                # dangling (unknown spec.nodeName): tracked, never
                # scheduled, absent from node status — like simulate()
                continue
            place = int(placements[pos])
            if place == INACTIVE:  # pragma: no cover - defensive
                continue
            if (pod.get("spec") or {}).get("nodeName"):
                oracle.place_existing_pod(pod2)
            elif place < 0:
                _, reasons, _ = oracle._find_feasible(pod2)
                failed.append(
                    UnscheduledPod(
                        pod=pod2, reason=Oracle._failure_message(pod2, reasons)
                    )
                )
            else:
                oracle._reserve_and_bind(pod2, oracle.nodes[place])
        status = [
            NodeStatus(node=ns.node, pods=list(ns.pods)) for ns in oracle.nodes
        ]
        return SimulateResult(unscheduled_pods=failed, node_status=status)

    def _serial_placements(
        self, active, batch_idx, all_pods, base=None
    ) -> np.ndarray:
        """Deterministic host-oracle evaluation of ONE scenario row —
        the guard ladder's floor when even a single-scenario dispatch
        dies on the device. Same conventions as the scan: node index,
        -1 unschedulable, INACTIVE for masked-off positions. ``base``
        (a CommittedScan) seeds the scratch with the committed state
        first — the incremental path's rows carry only request pods,
        so the roster must arrive through the prefix."""
        oracle = Oracle([ns.node for ns in self.oracle.nodes])
        if base is not None:
            for ns in base.oracle.nodes:
                for p in ns.pods:
                    oracle.place_existing_pod(_own_pod(p))
        node_index = self.oracle.node_index
        out = np.full(len(batch_idx), INACTIVE, dtype=np.int64)
        for pos, i in enumerate(batch_idx):
            if not active[pos]:
                continue
            pod2 = _own_pod(all_pods[i])
            if (pod2.get("spec") or {}).get("nodeName"):
                oracle.place_existing_pod(pod2)
                out[pos] = node_index[pod2["spec"]["nodeName"]]
                continue
            name, _reason = oracle.schedule_pod(pod2)
            out[pos] = -1 if name is None else node_index[name]
        return out

    def evaluate_serial(self, req: WhatIfRequest, reason: str) -> WhatIfReply:
        """Admission-routed serial evaluation (serve/admission.py):
        the same full-fidelity path the scan-ineligible requests take,
        exposed for requests ROUTED serial by policy (predicted HBM
        pressure, oversize) rather than by semantics. The body stays
        byte-identical to the coalesced answer — only the engine
        header and the latency differ."""
        return self._evaluate_serial(req, reason=reason)

    def _evaluate_serial(self, req: WhatIfRequest, reason: str) -> WhatIfReply:
        """The full-fidelity path for requests the batched scan cannot
        model: a real simulate() over deep copies (the session's loaded
        cluster must stay pristine — simulate binds pods in place)."""
        from ..utils.trace import phase

        with phase("serve/serial"), self._delta_lock:
            wl.reset_name_counter()
            cluster = copy.deepcopy(self.cluster)
            apps = [
                AppResource(a.name, copy.deepcopy(a.resource)) for a in req.apps
            ]
            result = simulate(cluster, apps, engine="tpu")
        return WhatIfReply(
            status=200,
            body=result_payload(result),
            meta={"engine": "serial", "serialReason": reason},
        )

    # -- cluster deltas (the shared substrate, twin/deltas.py) --------------

    def apply_delta(self, delta) -> str:
        """Apply one ``ClusterDelta`` to this WARM session — ROADMAP
        item 2's watch-style delta update, on the twin substrate's
        vocabulary. Roster application: arrived/bound pods enter the
        session's pod roster at the bare-pod boundary (so they ride
        every subsequent tick exactly where a cold reload of the
        mutated cluster would expand them), evict/delete remove by
        key, a node join is one incremental ``add_node``. Node drains
        — and any node delta on a daemonset-bearing cluster, whose
        per-node pods consume the generated-name counter — REBUILD the
        session (counted, ``serve_delta_reloads_total``). The
        conformance contract (tests/test_twin.py, CI-gated): after any
        delta stream, this session answers byte-identically to a fresh
        Session over its mutated ``self.cluster``."""
        return self.apply_delta_seq(delta)[0]

    def apply_delta_seq(self, delta) -> "tuple[str, int]":
        """``apply_delta`` returning ``(outcome, seq)`` where ``seq``
        is the EXACT delta sequence this apply was assigned under the
        lock. The journal record must be stamped with this value, not
        a later read of ``self.delta_seq`` — under concurrent handler
        threads the later read can observe another thread's apply, and
        a misstamped record would double-apply (or drop) a delta on
        snapshot-then-suffix restore."""
        from ..twin.deltas import RELOADED, SKIPPED

        with self._delta_lock:
            out = self._apply_delta(delta)
            self.delta_seq += 1
            seq = self.delta_seq
            COUNTERS.inc(f"serve_delta_{delta.kind}_total")
            if out == SKIPPED:
                COUNTERS.inc("serve_delta_skips_total")
            else:
                COUNTERS.inc("serve_deltas_applied_total")
                if out == RELOADED:
                    COUNTERS.inc("serve_delta_reloads_total")
        return out, seq

    def restore_state(self, cluster: ResourceTypes, delta_seq: int) -> str:
        """Adopt a checkpointed cluster as this session's committed
        state (runtime/checkpoint.py): swap the cluster in, rebuild via
        ``_reload`` (fresh expansion/oracle/engine — identical to a
        cold load of the mutated cluster), and advance ``delta_seq`` to
        the checkpoint's sequence so the journal suffix replay skips
        exactly the absorbed prefix. The caller verifies the payload
        digest BEFORE calling this (fleet/replay.restore_into_session);
        a refused checkpoint must leave the session untouched."""
        with self._delta_lock:
            self.cluster = cluster
            out = self._reload()
            self.delta_seq = int(delta_seq)
        return out

    def _apply_delta(self, delta) -> str:
        from ..twin import deltas as dl

        kind = delta.kind
        if kind in (dl.POD_ARRIVE, dl.POD_BIND):
            raw = copy.deepcopy(delta.pod)
            if kind == dl.POD_BIND:
                raw.setdefault("spec", {})["nodeName"] = delta.node_name
            # re-arrival of a live key replaces the stale entry (its
            # roster slot moves to the section end — the order a cold
            # reload of the mutated cluster.pods list would expand)
            removed_at = self._remove_roster_pod(delta.pod_key)
            valid = wl.pod_from_pod(copy.deepcopy(raw))
            insert_at = self._bare_end
            self.cluster.pods.append(raw)
            self.cluster_pods.insert(self._bare_end, valid)
            self._bare_end += 1
            if not self.force_serial_reason and self._pod_uses_priority(
                valid, self._resolver
            ):
                self.force_serial_reason = "cluster pods carry priority"
            self._update_committed(
                kind, positions=(removed_at,), insert_position=insert_at
            )
            return dl.APPLIED
        if kind in (dl.POD_EVICT, dl.POD_DELETE):
            removed_at = self._remove_roster_pod(delta.pod_key)
            if removed_at is None:
                return dl.SKIPPED
            self._update_committed(kind, positions=(removed_at,))
            return dl.APPLIED
        if kind == dl.NODE_JOIN:
            if any(
                (n.get("metadata") or {}).get("name") == delta.node_name
                for n in self.cluster.nodes
            ):
                return dl.SKIPPED  # re-join of a known node
            self.cluster.nodes.append(delta.node)
            if self.cluster.daemon_sets:
                return self._reload()
            self.oracle.add_node(delta.node)
            self._update_committed(kind)
            return dl.APPLIED
        # node_drain: node identity is baked into every encoding
        from ..models.validation import InputError

        if not any(
            (n.get("metadata") or {}).get("name") == delta.node_name
            for n in self.cluster.nodes
        ):
            raise InputError(
                f"node_drain delta names unknown node {delta.node_name!r}"
            )
        self.cluster.nodes = [
            n
            for n in self.cluster.nodes
            if (n.get("metadata") or {}).get("name") != delta.node_name
        ]
        return self._reload()

    def _remove_roster_pod(self, key) -> Optional[int]:
        """Drop a bare-section roster pod (and its cluster.pods source
        entry) by (namespace, name); returns the roster position it
        held (the suffix rule's touch point) or None when the key is
        unknown. Workload-expanded replicas are out of scope: their
        source object is the workload, which a delta stream cannot
        partially shrink — counted skip instead."""
        for i in range(self._bare_end):
            meta = self.cluster_pods[i].get("metadata") or {}
            if (meta.get("namespace") or "default", meta.get("name", "")) == key:
                self.cluster_pods.pop(i)
                self._bare_end -= 1
                for j, p in enumerate(self.cluster.pods):
                    pm = p.get("metadata") or {}
                    if (
                        pm.get("namespace") or "default",
                        pm.get("name", ""),
                    ) == key:
                        self.cluster.pods.pop(j)
                        break
                return i
        return None

    def _reload(self) -> str:
        """Counted session rebuild over the mutated cluster: the
        constructor body re-runs (fresh oracle/engine/expansion) with
        the caller still holding the delta lock (the constructor
        preserves an existing lock, so no thread can observe the
        half-built state); the session identity (fingerprint) and
        delta bookkeeping survive. The cross-run identity caches keep
        unchanged node templates and pristine encodings warm
        underneath."""
        from ..twin.deltas import RELOADED

        fp = self.fingerprint
        seq, reloads = self.delta_seq, self.delta_reloads
        self.__init__(self.cluster, incremental=self.incremental)
        self.fingerprint = fp
        self.delta_seq, self.delta_reloads = seq, reloads + 1
        return RELOADED


# -- checkpoint capture / materialization (runtime/checkpoint.py) -----------


def cluster_payload(cluster: ResourceTypes) -> dict:
    """The delta-mutated cluster as a JSON-clean checkpoint payload:
    one key per ResourceTypes field, deep-copied so the snapshot writer
    never aliases the live roster the handler threads keep mutating."""
    return {
        f: copy.deepcopy(getattr(cluster, f))
        for f in cluster.__dataclass_fields__
    }


def cluster_from_payload(payload: dict) -> ResourceTypes:
    """Inverse of ``cluster_payload``; unknown keys (a future field
    this build does not model) are refused by the caller's toolchain
    gate before this runs, so plain field assignment suffices."""
    cluster = ResourceTypes()
    for f in cluster.__dataclass_fields__:
        setattr(cluster, f, copy.deepcopy(payload.get(f, [])))
    return cluster


def materialized_state_digest(cluster: ResourceTypes) -> str:
    """``Session.state_digest()`` of a FRESH expansion over a cluster,
    WITHOUT building a Session (no oracle, no engine, no device work).
    By the warm==cold conformance contract the warm roster order equals
    the cold expansion order of the mutated cluster — so this digest
    matching a live session's proves the checkpoint payload
    re-materializes to the same committed state. Callers verifying
    against a LIVE session must hold that session's ``_delta_lock``:
    the generated-name counter this expansion saves/restores is global
    and is otherwise raced by request expansion."""
    saved = wl.name_counter_state()
    try:
        wl.reset_name_counter()
        pods: List[dict] = []
        pods.extend(wl.pods_excluding_daemon_sets(cluster))
        for ds in cluster.daemon_sets:
            pods.extend(wl.pods_from_daemon_set(ds, cluster.nodes))
    finally:
        wl.set_name_counter(saved)
    return config_fingerprint(
        [(n.get("metadata") or {}).get("name") for n in cluster.nodes],
        pods,
    )


def verify_payload_digest(session: Session, payload: dict) -> str:
    """The CheckpointManager ``materialized_digest`` hook for a serve
    session: re-materialize the payload cluster and digest it, under
    the session's delta lock (the name-counter race documented on
    ``materialized_state_digest``)."""
    with session._delta_lock:
        return materialized_state_digest(cluster_from_payload(payload))


def session_checkpoint_state(session: Session):
    """The CheckpointManager ``capture`` hook: one consistent cut of
    the committed session — the ``/v1/state-digest`` triple plus the
    full mutated cluster — taken under the delta lock so the captured
    ``delta_seq`` counts exactly the deltas the payload absorbed."""
    from ..runtime.checkpoint import CheckpointState

    with session._delta_lock:
        return CheckpointState(
            fingerprint=session.fingerprint,
            delta_seq=session.delta_seq,
            state_digest=session.state_digest(),
            payload=cluster_payload(session.cluster),
        )
