"""Fault-injection resilience: batched node-outage sweeps, N+K
capacity planning, and perturbation (cordon/taint/degrade) studies.

The reference answers "does this plan fit?"; this package answers
"does this plan *survive*?" — see docs/RESILIENCE.md for the chaos
model and resilience/chaos.py for the engine.
"""

from .chaos import (  # noqa: F401
    ChaosEngine,
    ChaosReport,
    OutageScenario,
    ScenarioOutcome,
    perturbed_cluster,
    perturbed_scenario_sweep,
    raise_plan_to_nplusk,
    sampled_failure_sets,
)
