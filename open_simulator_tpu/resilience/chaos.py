"""Fault-injection engine: batched node-outage sweeps over a committed
placement.

The capacity sweep (parallel/sweep.py) already evaluates masked
node-subset scenarios under one vmapped scan — exactly the substrate a
survivability analysis needs: every outage scenario is one more mask
row, so a full K-failure sweep costs one batched scan instead of
thousands of serial re-simulations.

Chaos model (docs/RESILIENCE.md):

- Start from a COMMITTED placement (the minimal feasible capacity
  plan's scan placements, or any probe's).
- An outage scenario fails a set of nodes. Pods the scheduler placed on
  surviving nodes STAY THERE (pinned in the scan — real rescheduling
  cannot move survivors); pods displaced from failed nodes are free and
  reschedule through the full filter+score cycle against the residual
  capacity. Daemonset pods die with their node (the controller would
  not recreate them elsewhere); pods whose ORIGINAL spec.nodeName names
  a failed node are node-bound and cannot move.
- Single-node failures are enumerated exhaustively; K-node failures by
  deterministic seeded sampling over the Go math/rand port
  (utils/gorand.py), so a report is reproducible from (seed, trials).
- Perturbations (cordon / taint / capacity degradation) mutate the
  cluster the scenarios are evaluated against, while the committed
  baseline stays the clean cluster's: "the plan was committed, THEN the
  world got worse".

Failing scenarios are explained by replaying the scan placements into
host oracle state (apply/applier.py replay_masked) and asking the
oracle why each displaced pod found no node. An N+K capacity plan
(raise_plan_to_nplusk, `simon apply --tolerate-node-failures K`)
escalates the planned node count until every evaluated scenario
survives, then re-simulates one sampled outage SERIALLY
(CapacitySweep.serial_scenario) as an independent confirmation.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from math import comb
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..models.validation import InputError
from ..parallel.sweep import CapacitySweep, ProbeResult
from ..utils.gorand import GoRand

# failing scenarios explained (oracle reason per failed pod) before the
# report degrades to counts-only rows; a 100-scenario sweep with many
# failures must not pay 100 host replays to describe itself
MAX_EXPLAINED_SCENARIOS = 5
MAX_REASONS_PER_SCENARIO = 10


@dataclass
class OutageScenario:
    kind: str  # "single" | "multi" | "sampled" | "replacement"
    failed: Tuple[int, ...]  # sweep node indices that fail
    failed_names: Tuple[str, ...]

    def label(self) -> str:
        return "+".join(self.failed_names) if self.failed_names else "(no outage)"


@dataclass
class ScenarioOutcome:
    scenario: OutageScenario
    displaced: int  # scheduler-placed pods whose node failed
    rescheduled: int  # displaced pods that found a new node
    unschedulable: int  # NEWLY unschedulable (was placed at baseline)
    baseline_unsched: int  # already failing at baseline, still failing
    lost_daemonset: int  # daemonset pods that die with their node
    lost_node_bound: int  # original spec.nodeName pods on a failed node
    cpu_util: float  # surviving-node utilization after rescheduling
    mem_util: float
    reasons: List[Tuple[str, str]] = field(default_factory=list)
    # sweep pod indices of the newly-unschedulable pods (bounded by the
    # pod count of one scenario; the N+K escalation reads these to
    # prove a failure unreachable by adding nodes)
    unschedulable_pods: Tuple[int, ...] = ()

    @property
    def survives(self) -> bool:
        return self.unschedulable == 0


@dataclass
class ChaosReport:
    failures: int
    seed: int
    mode: str  # how the scenario set was generated
    baseline_count: int  # committed new-node count
    baseline_unscheduled: int
    outcomes: List[ScenarioOutcome] = field(default_factory=list)
    serial_confirmed: Optional[str] = None  # label of the serially
    # re-simulated scenario, set by confirm_serial on success
    # deadline/SIGINT halted the sweep at a chunk boundary: `outcomes`
    # holds only the completed scenarios out of `planned`
    partial: bool = False
    planned: int = 0  # scenarios the full sweep would evaluate

    @property
    def total(self) -> int:
        return len(self.outcomes)

    @property
    def survived(self) -> int:
        return sum(1 for o in self.outcomes if o.survives)

    @property
    def all_survived(self) -> bool:
        return self.survived == self.total

    def worst(self) -> Optional[ScenarioOutcome]:
        if not self.outcomes:
            return None
        return max(
            self.outcomes, key=lambda o: (o.unschedulable, o.displaced)
        )

    def as_dict(self) -> dict:
        return {
            "failures": self.failures,
            "seed": self.seed,
            "mode": self.mode,
            "baselineNewNodeCount": self.baseline_count,
            "baselineUnscheduled": self.baseline_unscheduled,
            "survived": self.survived,
            "total": self.total,
            "partial": self.partial,
            "plannedScenarios": self.planned or self.total,
            "serialConfirmed": self.serial_confirmed,
            "scenarios": [
                {
                    "kind": o.scenario.kind,
                    "failedNodes": list(o.scenario.failed_names),
                    "displaced": o.displaced,
                    "rescheduled": o.rescheduled,
                    "unschedulable": o.unschedulable,
                    "baselineUnscheduled": o.baseline_unsched,
                    "lostDaemonSet": o.lost_daemonset,
                    "lostNodeBound": o.lost_node_bound,
                    "cpuUtil": round(o.cpu_util, 2),
                    "memUtil": round(o.mem_util, 2),
                    "survives": o.survives,
                    "reasons": [
                        {"pod": p, "reason": r} for p, r in o.reasons
                    ],
                }
                for o in self.outcomes
            ],
        }

    def render_text(self) -> str:
        from ..apply.report import render_table

        lines = [
            f"Fault-injection survivability: K={self.failures}, "
            f"{self.total} scenario(s) ({self.mode}), seed {self.seed}",
        ]
        if self.partial:
            lines.append(
                f"PARTIAL: {self.total}/{self.planned} scenario(s) "
                "evaluated before the run halted (deadline/interrupt)"
            )
        lines += [
            f"baseline: {self.baseline_count} new node(s), "
            f"{self.baseline_unscheduled} unschedulable pod(s)",
            f"SURVIVED {self.survived}/{self.total} scenario(s)"
            + (
                f" — serial re-simulation confirmed [{self.serial_confirmed}]"
                if self.serial_confirmed
                else ""
            ),
        ]
        rows = [
            [
                o.scenario.label(),
                str(o.displaced),
                str(o.rescheduled),
                str(o.unschedulable),
                str(o.lost_daemonset),
                str(o.lost_node_bound),
                f"{o.cpu_util:.1f}%",
                f"{o.mem_util:.1f}%",
                "yes" if o.survives else "NO",
            ]
            for o in self.outcomes
        ]
        lines.append(
            render_table(
                [
                    "Failed Node(s)",
                    "Displaced",
                    "Rescheduled",
                    "Unschedulable",
                    "Lost(ds)",
                    "Lost(bound)",
                    "CPU",
                    "Mem",
                    "Survives",
                ],
                rows,
            )
        )
        for o in self.outcomes:
            if o.reasons:
                lines.append(f"unschedulable pods of [{o.scenario.label()}]:")
                for pod_ref, reason in o.reasons:
                    lines.append(f"  {pod_ref}: {reason}")
        return "\n".join(lines)


def sampled_failure_sets(
    eligible: Sequence[int], k: int, trials: int, seed: int
) -> Tuple[List[Tuple[int, ...]], str]:
    """K-subsets of `eligible` to fail: exhaustive when the space is no
    larger than `trials`, otherwise `trials` deterministic draws from
    the seeded Go math/rand stream (partial Fisher-Yates per draw;
    duplicates collapse). Returns (sorted index tuples, mode)."""
    elig = sorted(eligible)
    if k > len(elig):
        raise InputError(
            f"cannot fail {k} of {len(elig)} node(s); lower --failures"
        )
    if comb(len(elig), k) <= trials:
        return [tuple(c) for c in itertools.combinations(elig, k)], "exhaustive"
    rng = GoRand(seed)
    seen = set()
    out: List[Tuple[int, ...]] = []
    for _ in range(trials):
        pool = list(elig)
        pick = [pool.pop(rng.intn(len(pool))) for _ in range(k)]
        key = tuple(sorted(pick))
        if key not in seen:
            seen.add(key)
            out.append(key)
    return out, "sampled"


def perturbed_cluster(cluster, cordon=(), taints=(), degrade=None):
    """A copy of `cluster` with scheduling-visible perturbations applied
    to named base nodes: `cordon` marks nodes unschedulable
    (node.kubernetes.io/unschedulable semantics — existing pods stay,
    displaced pods cannot land there), `taints` is a sequence of
    (node_names_or_None, taint_dict) appended to node specs (None =
    every base node), `degrade` is (percent, node_names_or_None) scaling
    allocatable cpu/memory DOWN by `percent` (a brownout: the nodes
    still exist but answer for less capacity)."""
    import copy as copymod

    from ..utils.quantity import q_milli, q_value

    cordon = set(cordon or ())
    taints = list(taints or ())
    affected = set(cordon)
    for names, _ in taints:
        affected |= set(names) if names else {None}
    if degrade is not None:
        pct, names = degrade
        if not 0 <= pct <= 100:
            raise InputError(f"degrade percent {pct} outside [0, 100]")
        affected |= set(names) if names else {None}
    out = cluster.copy()
    out.nodes = []
    known = set()
    for node in cluster.nodes:
        name = (node.get("metadata") or {}).get("name")
        known.add(name)
        hit = name in affected or None in affected
        node = copymod.deepcopy(node) if hit else node
        if name in cordon:
            node.setdefault("spec", {})["unschedulable"] = True
        for names, taint in taints:
            if names is None or name in names:
                node.setdefault("spec", {}).setdefault("taints", []).append(
                    dict(taint)
                )
        if degrade is not None:
            pct, names = degrade
            if names is None or name in names:
                scale = (100 - pct) / 100.0
                for section in ("allocatable", "capacity"):
                    res = (node.get("status") or {}).get(section)
                    if not res:
                        continue
                    if "cpu" in res:
                        res["cpu"] = f"{int(q_milli(res['cpu']) * scale)}m"
                    if "memory" in res:
                        res["memory"] = str(int(q_value(res["memory"]) * scale))
        out.nodes.append(node)
    for bad in (affected - {None}) - known:
        raise InputError(f"perturbation names unknown node {bad!r}")
    return out


def displaced_free_mask(placed, valid, had, active) -> np.ndarray:
    """Scheduler-placed pods whose node is outside `valid`: freed to
    reschedule through the full filter+score cycle — the chaos
    displacement rule, shared with the timeline stepper's node-drain /
    spot-reclaim application (timeline/stepper.py). Node-bound pods
    (`had` — original spec.nodeName) and pods inactive in the scenario
    (daemonset pods of the failed node) are NOT displaced: they are
    lost with the node."""
    placed = np.asarray(placed)
    return (
        (~np.asarray(had))
        & (placed >= 0)
        & ~np.asarray(valid)[np.clip(placed, 0, None)]
        & np.asarray(active)
    )


def _pod_identity(pods) -> list:
    out = []
    for p in pods:
        meta = p.get("metadata") or {}
        out.append((meta.get("namespace"), meta.get("name")))
    return out


def perturbed_scenario_sweep(
    cluster,
    apps,
    new_node_spec,
    max_count: int,
    cordon=(),
    taints=(),
    degrade=None,
    use_greed: bool = False,
    score_weights=None,
) -> Optional[CapacitySweep]:
    """The perturbed re-encoding outage scenarios are evaluated
    against, or None when no perturbation was requested. Resets the
    workload name counter first so the expansion matches the baseline
    sweep's (the ChaosEngine constructor checks they are identical)."""
    if not cordon and not taints and degrade is None:
        return None
    from ..models.workloads import reset_name_counter

    reset_name_counter()
    return CapacitySweep(
        perturbed_cluster(cluster, cordon=cordon, taints=taints, degrade=degrade),
        apps,
        new_node_spec,
        max_count,
        use_greed=use_greed,
        score_weights=score_weights,
    )


class ChaosEngine:
    """Outage-scenario evaluation of one committed placement.

    `sweep` is the encoding the placement was committed on;
    `scenario_sweep` (optional) is a perturbed re-encoding of the same
    cluster the outages are evaluated against — the two must expand the
    identical pod sequence (checked), since placements are carried over
    by pod index."""

    def __init__(
        self,
        sweep: CapacitySweep,
        count: int,
        baseline_placements,
        scenario_sweep: Optional[CapacitySweep] = None,
    ):
        self.sweep = sweep
        self.scen = scenario_sweep or sweep
        if scenario_sweep is not None:
            if [ns.name for ns in sweep.oracle.nodes] != [
                ns.name for ns in scenario_sweep.oracle.nodes
            ] or _pod_identity(sweep.pods) != _pod_identity(scenario_sweep.pods):
                raise ValueError(
                    "perturbed cluster changed the node list or pod "
                    "expansion; chaos scenarios cannot carry the committed "
                    "placement over by index"
                )
        self.count = count
        self.base_valid = self.scen.node_valid(count)
        self.base_active = self.scen.pod_active(self.base_valid)
        self.baseline = np.asarray(baseline_placements).astype(np.int64)
        self.orig_pin = np.asarray(self.scen.batch.pinned_node).astype(np.int64)
        self.had = np.asarray(self.scen.had_node_name)
        self.node_names = [ns.name for ns in self.scen.oracle.nodes]

    @classmethod
    def from_cluster(
        cls,
        cluster,
        apps,
        new_node_spec=None,
        count: int = 0,
        use_greed: bool = False,
        score_weights=None,
        cordon=(),
        taints=(),
        degrade=None,
    ) -> "ChaosEngine":
        """Encode the cluster at the committed count, probe the baseline
        placement, and (when perturbations are given) re-encode the
        perturbed variant for scenario evaluation. Workload expansion
        names pods from a process-global counter, so it is reset before
        each encoding — the two expansions must be identical for
        placements to carry over by index."""
        from ..models.workloads import reset_name_counter

        reset_name_counter()
        sweep = CapacitySweep(
            cluster, apps, new_node_spec, count,
            use_greed=use_greed, score_weights=score_weights,
        )
        baseline = sweep.probe(count).placements
        scen_sweep = perturbed_scenario_sweep(
            cluster, apps, new_node_spec, count,
            cordon=cordon, taints=taints, degrade=degrade,
            use_greed=use_greed, score_weights=score_weights,
        )
        return cls(sweep, count, baseline, scenario_sweep=scen_sweep)

    # -- scenario generation ------------------------------------------------

    def build_scenarios(
        self, failures: int, seed: int = 1, trials: int = 32
    ) -> Tuple[List[OutageScenario], str]:
        """Single-node outages exhaustively; K >= 2 adds seeded-sampled
        K-subsets (surviving K failures subsumes surviving fewer only
        scenario-by-scenario, so the singles stay in the set); K <= 0 is
        the replacement study (no outage, full re-placement — the
        perturbation-only question)."""
        names = self.node_names
        if failures <= 0:
            return [OutageScenario("replacement", (), ())], "replacement"
        elig = [i for i in range(self.scen.n) if self.base_valid[i]]
        scens = [
            OutageScenario("single", (i,), (names[i],)) for i in elig
        ]
        mode = "exhaustive singles"
        if failures >= 2:
            combos, sample_mode = sampled_failure_sets(
                elig, failures, trials, seed
            )
            scens.extend(
                OutageScenario(
                    "multi" if sample_mode == "exhaustive" else "sampled",
                    c,
                    tuple(names[i] for i in c),
                )
                for c in combos
            )
            mode = f"singles + {sample_mode} {failures}-subsets"
        return scens, mode

    def _masks(self, scen: OutageScenario):
        """(node_valid, pod_active, pinned, displaced_mask) for one
        scenario. Survivor pods pin to their committed nodes (pins
        commit unconditionally — the placement was feasible when
        committed); displaced scheduler-placed pods are freed; original
        spec.nodeName pins are kept verbatim so the scan's
        pinned-to-invalid INACTIVE convention marks them node-bound."""
        valid = self.base_valid.copy()
        for i in scen.failed:
            valid[i] = False
        active = self.scen.pod_active(valid)
        b = self.baseline
        if scen.kind == "replacement":
            pinned = np.where(self.had, self.orig_pin, -1).astype(np.int64)
            displaced = np.zeros(len(b), dtype=bool)
        else:
            pinned = np.where(
                self.had, self.orig_pin, np.where(b >= 0, b, -1)
            ).astype(np.int64)
            # pods inactive in the scenario (daemonset pods of failed
            # nodes) die with the node — lost, not displaced
            displaced = displaced_free_mask(b, valid, self.had, active)
            pinned[displaced] = -1
        return valid, active, pinned, displaced

    # -- evaluation ---------------------------------------------------------

    def _scenario_key(self, scen: OutageScenario) -> str:
        """Journal key of one scenario verdict: the committed count plus
        the failure set (the journal fingerprint already pins the
        config, flags, seed, and perturbations)."""
        return f"{self.count}:{scen.kind}:{'+'.join(scen.failed_names)}"

    @staticmethod
    def _outcome_record(o: ScenarioOutcome) -> dict:
        return {
            "scenKind": o.scenario.kind,
            "failed": [int(i) for i in o.scenario.failed],
            "failedNames": list(o.scenario.failed_names),
            "displaced": o.displaced,
            "rescheduled": o.rescheduled,
            "unschedulable": o.unschedulable,
            "baselineUnsched": o.baseline_unsched,
            "lostDaemonSet": o.lost_daemonset,
            "lostNodeBound": o.lost_node_bound,
            "cpuUtil": o.cpu_util,
            "memUtil": o.mem_util,
            "reasons": [[p, r] for p, r in o.reasons],
            "unschedulablePods": [int(i) for i in o.unschedulable_pods],
        }

    @staticmethod
    def _outcome_from_record(scen: OutageScenario, rec: dict) -> ScenarioOutcome:
        return ScenarioOutcome(
            scenario=scen,
            displaced=int(rec["displaced"]),
            rescheduled=int(rec["rescheduled"]),
            unschedulable=int(rec["unschedulable"]),
            baseline_unsched=int(rec["baselineUnsched"]),
            lost_daemonset=int(rec["lostDaemonSet"]),
            lost_node_bound=int(rec["lostNodeBound"]),
            cpu_util=float(rec["cpuUtil"]),
            mem_util=float(rec["memUtil"]),
            reasons=[(p, r) for p, r in rec.get("reasons") or []],
            unschedulable_pods=tuple(
                int(i) for i in rec.get("unschedulablePods") or ()
            ),
        )

    def _outcome(self, scen, masks, row, cpu, mem, explain_left) -> ScenarioOutcome:
        valid, active, _pinned, displaced = masks
        b = self.baseline
        newly = (row == -1) & (b >= 0)
        outcome = ScenarioOutcome(
            scenario=scen,
            displaced=int(displaced.sum()),
            rescheduled=int((displaced & (row >= 0)).sum()),
            unschedulable=int(newly.sum()),
            baseline_unsched=int(((row == -1) & (b == -1)).sum()),
            lost_daemonset=int((self.base_active & ~active).sum()),
            lost_node_bound=int(
                (
                    self.had
                    & (self.orig_pin >= 0)
                    & ~valid[np.clip(self.orig_pin, 0, None)]
                ).sum()
            ),
            cpu_util=float(cpu),
            mem_util=float(mem),
            unschedulable_pods=tuple(int(i) for i in np.flatnonzero(newly)),
        )
        if outcome.unschedulable and explain_left > 0:
            outcome.reasons = self._explain(valid, row, newly)
        return outcome

    def run(
        self,
        failures: int = 1,
        seed: int = 1,
        trials: int = 32,
        explain: int = MAX_EXPLAINED_SCENARIOS,
        budget=None,
        journal=None,
    ) -> ChaosReport:
        """Evaluate the scenario set against the committed placement.

        With a `journal`, scenarios whose verdict is already journaled
        are reconstructed without any device work and only the
        remainder rides the batched sweep; fresh verdicts are appended
        as they land. With a `budget`, the sweep halts between device
        chunks: the raised ExecutionHalted carries a PARTIAL ChaosReport
        (completed scenarios only, journaled) as its payload."""
        from ..runtime.errors import ExecutionHalted
        from ..utils.trace import GLOBAL, phase

        scens, mode = self.build_scenarios(failures, seed, trials)
        report = ChaosReport(
            failures=failures,
            seed=seed,
            mode=mode,
            baseline_count=self.count,
            baseline_unscheduled=int((self.baseline == -1).sum()),
            planned=len(scens),
        )
        outcomes: List[Optional[ScenarioOutcome]] = [None] * len(scens)
        eval_idx: List[int] = []
        if journal is not None:
            for s_i, scen in enumerate(scens):
                rec = journal.get_scenario(self._scenario_key(scen))
                if rec is not None:
                    outcomes[s_i] = self._outcome_from_record(scen, rec)
                else:
                    eval_idx.append(s_i)
            if len(eval_idx) < len(scens):
                GLOBAL.append_note(
                    "chaos-journal",
                    f"count {self.count}: {len(scens) - len(eval_idx)}/"
                    f"{len(scens)} scenario verdict(s) replayed from the "
                    "journal",
                )
        else:
            eval_idx = list(range(len(scens)))

        masks = {s_i: self._masks(scens[s_i]) for s_i in eval_idx}
        halted = None
        rows: dict = {}
        if eval_idx:
            try:
                with phase("chaos/sweep"):
                    placements, _unsched, cpu, mem, _vg = self.scen.probe_scenarios(
                        np.stack([masks[i][0] for i in eval_idx]),
                        np.stack([masks[i][1] for i in eval_idx]),
                        np.stack([masks[i][2] for i in eval_idx]),
                        budget=budget,
                    )
                rows = {
                    s_i: (placements[k], cpu[k], mem[k])
                    for k, s_i in enumerate(eval_idx)
                }
            except ExecutionHalted as e:
                halted = e
                partial = getattr(e, "partial_results", None) or []
                rows = {
                    s_i: (r[0], r[2], r[3])
                    for s_i, r in zip(eval_idx, partial)
                    if r is not None
                }
        explain_left = explain
        for s_i, scen in enumerate(scens):
            if outcomes[s_i] is not None:
                continue
            if s_i not in rows:
                continue
            row, cpu_i, mem_i = rows[s_i]
            outcome = self._outcome(
                scen, masks[s_i], row, cpu_i, mem_i, explain_left
            )
            if outcome.reasons:
                explain_left -= 1
            outcomes[s_i] = outcome
            if journal is not None:
                journal.record_scenario(
                    self._scenario_key(scen), self._outcome_record(outcome)
                )
        report.outcomes = [o for o in outcomes if o is not None]
        report.partial = halted is not None
        GLOBAL.note(
            "chaos-scenarios",
            f"{report.survived}/{report.total} survive (K={failures}, "
            f"{mode}, seed {seed})"
            + (f" [partial: {report.total}/{report.planned}]" if report.partial else ""),
        )
        if halted is not None:
            halted.partial = {"phase": "chaos-sweep", "report": report.as_dict()}
            # hand the assembled partial report to the caller too
            halted.partial_report = report
            raise halted
        return report

    def _explain(self, valid, row, newly) -> List[Tuple[str, str]]:
        """Oracle reasons for a failing scenario's newly-unschedulable
        pods: replay the scan placements into host state, then ask the
        FULLY-loaded oracle why each failed pod finds no node. (The
        replay's own at-position reasons would describe a half-empty
        cluster — chaos placements commit every survivor before any
        displaced pod, so only the end state explains the failure.)"""
        from ..apply.applier import replay_masked
        from ..scheduler.oracle import Oracle

        _result, oracle = replay_masked(self.scen, valid, row)
        out = []
        for p_i in np.flatnonzero(newly)[:MAX_REASONS_PER_SCENARIO]:
            pod = self.scen.pods[int(p_i)]
            meta = pod.get("metadata") or {}
            _, reasons_map, _ = oracle._find_feasible(pod)
            out.append(
                (
                    f"{meta.get('namespace') or 'default'}/{meta.get('name') or ''}",
                    Oracle._failure_message(pod, reasons_map),
                )
            )
        return out

    def confirm_serial(self, scen: OutageScenario) -> Tuple[bool, int]:
        """Independent confirmation: re-simulate one scenario through
        the serial oracle (no scan, no batching) and count newly
        unschedulable pods. (ok, newly_unschedulable)."""
        from ..utils.trace import GLOBAL, phase

        valid, active, pinned, _ = self._masks(scen)
        with phase("chaos/serial-confirm"):
            placements, _reasons = self.scen.serial_scenario(
                valid, active, pinned, pins_first=True
            )
        newly = int(((placements == -1) & (self.baseline >= 0)).sum())
        GLOBAL.note(
            "chaos-serial-confirm",
            f"[{scen.label()}]: "
            + ("ok" if newly == 0 else f"{newly} newly unschedulable"),
        )
        return newly == 0, newly


def _escalation_cannot_help(engine: "ChaosEngine", report: ChaosReport):
    """Proof that adding candidate nodes can NEVER rescue a failing
    scenario, so the escalation can stop instead of walking to
    max_count. Adding nodes helps a displaced pod two ways: directly
    (the pod lands on a new node) or indirectly (other pods move to the
    new nodes, freeing a surviving node the pod is allowed on). Both
    are impossible only when the pod is statically rejected
    (nodeSelector / taint / nodeName) by the candidate spec AND by
    every node surviving the scenario — or, for an open-local pod, when
    neither the spec nor any surviving node has local storage at all
    (capacity on storage nodes can be freed; absent VGs/devices
    cannot). Returns a human reason or None (the stagnation backstop
    handles the merely-slow cases)."""
    sweep = engine.scen
    if sweep.max_count == 0:
        return "no newNode spec to escalate with"
    sf = np.asarray(sweep.static.static_feasible)
    cls = np.asarray(sweep.batch.class_of_pod)
    c_enc = sweep.cluster_enc
    new_i = sweep.n_base  # all candidate nodes share the spec
    new_has_storage = bool(
        c_enc.vg_cap[new_i].sum()
        or c_enc.ssd_cap[new_i].sum()
        or c_enc.hdd_cap[new_i].sum()
    )
    node_has_storage = (
        c_enc.vg_cap.sum(axis=1)
        + c_enc.ssd_cap.sum(axis=1)
        + c_enc.hdd_cap.sum(axis=1)
    ) > 0
    for o in report.outcomes:
        if o.survives:
            continue
        valid = engine.base_valid.copy()
        for i in o.scenario.failed:
            valid[i] = False
        for p_i in o.unschedulable_pods:
            why = None
            sf_p = sf[cls[p_i]]
            if not sf_p[new_i] and not (sf_p & valid).any():
                why = (
                    "statically rejected (nodeSelector/taint/nodeName) by "
                    "the candidate newNode spec and every surviving node"
                )
            elif (
                sweep.batch.wants_storage[cls[p_i]]
                and not new_has_storage
                and not (node_has_storage & valid).any()
            ):
                why = (
                    "wants open-local storage; neither the candidate "
                    "newNode spec nor any surviving node has any"
                )
            if why is not None:
                meta = sweep.pods[p_i].get("metadata") or {}
                return (
                    f"pod {meta.get('namespace', 'default')}/"
                    f"{meta.get('name', '')} in scenario "
                    f"[{o.scenario.label()}] {why}"
                )
    return None


def raise_plan_to_nplusk(
    sweep: CapacitySweep,
    best: ProbeResult,
    feasible,
    failures: int,
    seed: int = 1,
    trials: int = 32,
    budget=None,
    journal=None,
) -> Tuple[Optional[ProbeResult], Optional[ChaosReport]]:
    """Escalate a feasible capacity plan until its committed placement
    survives every evaluated K-failure scenario (`simon apply
    --tolerate-node-failures K`). Returns (probe, report); probe is
    None when N+K is unreachable — even at max_count, provably (a
    failing pod the candidate spec statically rejects), or after the
    failure set stagnates across escalations. A surviving plan is only
    returned after one sampled outage scenario re-simulates SERIALLY to
    the same verdict — a batched-scan bug must not certify a fake N+K
    plan.

    `budget` halts the escalation at its safe boundaries (between
    escalations and between device chunks) with a machine-readable
    partial payload; `journal` makes the escalation resumable — probe
    results ride the sweep's attached journal and every scenario
    verdict is appended as it lands, so a resumed run re-executes zero
    journaled work."""
    from ..runtime.errors import ExecutionHalted
    from ..utils.trace import GLOBAL

    probe = best
    stagnant = 0
    prev_failure_sig = None

    def _partial(exc, report=None):
        exc.partial = {
            "phase": "nplusk-escalation",
            "tolerateFailures": failures,
            "count": probe.count,
            "planFeasibleAtCount": True,
            "chaos": (exc.partial or {}).get("report")
            if isinstance(exc.partial, dict)
            else (report.as_dict() if report is not None else None),
        }
        return exc

    while True:
        if budget is not None:
            try:
                budget.check("N+K escalation boundary")
            except ExecutionHalted as e:
                raise _partial(e)
        engine = ChaosEngine(sweep, probe.count, probe.placements)
        try:
            report = engine.run(
                failures=failures, seed=seed, trials=trials, explain=0,
                budget=budget, journal=journal,
            )
        except ExecutionHalted as e:
            raise _partial(e)
        GLOBAL.append_note(
            "nplusk-escalation",
            f"count {probe.count}: {report.survived}/{report.total} survive",
        )
        if report.all_survived:
            worst = report.worst()
            ok, newly = engine.confirm_serial(worst.scenario)
            if not ok:  # pragma: no cover - defensive
                from ..runtime.errors import ConformanceError

                raise ConformanceError(
                    f"N+{failures} serial confirmation disagreed with the "
                    f"batched sweep on [{worst.scenario.label()}]: {newly} "
                    "newly unschedulable pod(s) in the serial re-simulation"
                )
            report.serial_confirmed = worst.scenario.label()
            return probe, report
        reason = _escalation_cannot_help(engine, report)
        if reason is not None:
            GLOBAL.note("nplusk-unreachable", reason)
            return None, report
        # stagnation backstop: identical failing scenarios with
        # identical failure counts across consecutive escalations mean
        # added nodes are not absorbing this outage (e.g. a pinned-pod
        # capacity hole) — stop after three no-progress rounds
        sig = tuple(
            (o.scenario.failed, o.unschedulable)
            for o in report.outcomes
            if not o.survives
        )
        if sig == prev_failure_sig:
            stagnant += 1
            if stagnant >= 3:
                GLOBAL.note(
                    "nplusk-unreachable",
                    f"failure set unchanged for {stagnant} escalations "
                    f"at count {probe.count}",
                )
                return None, report
        else:
            stagnant = 0
            prev_failure_sig = sig
        if probe.count >= sweep.max_count:
            return None, report
        count = probe.count + 1
        while count <= sweep.max_count:
            # each escalation probe is a device scan; without a check
            # here a deadline expiring mid-escalation would not halt
            # until the next outer N+K boundary (RT001)
            if budget is not None:
                try:
                    budget.check("N+K escalation probe")
                except ExecutionHalted as e:
                    raise _partial(e, report)
            candidate = sweep.probe(count)
            if feasible(candidate):
                probe = candidate
                break
            count += 1
        else:
            return None, report
