"""Host-side tensorization of cluster + pod-batch state.

The fake-apiserver object store of the reference (client-go ObjectTracker
+ scheduler cache snapshot, vendor/.../internal/cache/snapshot.go:29)
collapses into dense arrays:

- per-node allocatable vectors (cpu milli, memory bytes, ephemeral,
  pod slots) and a generic `[R, N]` allocatable matrix for the Simon
  max-share score (plugin/simon.go:44-67)
- per-pod-CLASS static matrices `[U, N]`: everything that does not
  depend on placement state — taint/affinity/nodename/unschedulable
  feasibility, preferred-node-affinity raw scores, PreferNoSchedule
  intolerable-taint counts, NodePreferAvoidPods, ImageLocality, Simon
  raw shares. Pods expanded from the same workload share a class, so
  the O(pods x nodes) host work shrinks to O(classes x nodes).
- a small host-port vocabulary with a pairwise conflict matrix
  (wildcard-IP semantics of HostPortInfo.CheckConflict)
- per-device GPU memory state for the open-gpu-share plugin

Dynamic state (requested resources, pod counts, port usage, GPU usage)
lives in the scan carry (ops/scan.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from fractions import Fraction
from typing import Dict, List

import numpy as np

from ..models import labels as lbl
from ..models import requests as req
from ..models import storage as stor
from ..utils.memo import IdentityMemo, register_cache
from .profiles import freeze as _freeze
from .profiles import node_profiles_cached as _shared_node_profiles
from .profiles import uses_match_fields as _uses_match_fields
from .terms import TermTables, build_term_tables, combined_pref_carry, combined_pref_init
from ..scheduler.oracle import (
    Oracle,
    _pod_host_ports,
    IMG_MIN_THRESHOLD,
    IMG_MAX_CONTAINER_THRESHOLD,
    MAX_NODE_SCORE,
)


def _ceil(v: Fraction) -> int:
    return -((-v.numerator) // v.denominator)


@dataclass
class ClusterStatic:
    """Placement-independent cluster tensors."""

    n: int
    node_names: List[str]
    alloc_mcpu: np.ndarray  # [N] i64
    alloc_mem: np.ndarray  # [N] i64
    alloc_eph: np.ndarray  # [N] i64
    alloc_pods: np.ndarray  # [N] i64
    # Simon score: allocatable matrix over the union of resource names
    simon_resources: List[str]
    simon_alloc: np.ndarray  # [R, N] f64
    # scalar (extended) resources tracked by NodeResourcesFit
    scalar_names: List[str]
    scalar_alloc: np.ndarray  # [S, N] i64
    # GPU share
    g: int  # max devices on any node
    gpu_count: np.ndarray  # [N] i64
    gpu_per_dev: np.ndarray  # [N] i64
    gpu_total: np.ndarray  # [N] i64 (capacity gpu-mem)
    # open-local storage: VGs and exclusive devices (devices sorted
    # ascending by capacity per media type, CheckExclusiveResource...
    # semantics, open-local algo/common.go:290-351)
    v: int  # max VGs per node
    vg_cap: np.ndarray  # [N, V] i64
    vg_valid: np.ndarray  # [N, V] bool
    has_storage: np.ndarray  # [N] bool (node has the storage annotation)
    d_ssd: int
    d_hdd: int
    ssd_cap: np.ndarray  # [N, Ds] i64 (ascending)
    ssd_valid: np.ndarray  # [N, Ds] bool
    hdd_cap: np.ndarray  # [N, Dh] i64 (ascending)
    hdd_valid: np.ndarray  # [N, Dh] bool
    # ports vocabulary
    port_vocab: List[tuple]
    port_conflict: np.ndarray  # [Pt, Pt] bool


@dataclass
class DynamicState:
    """The scan carry, as host arrays (mirrors oracle NodeState)."""

    used_mcpu: np.ndarray
    used_mem: np.ndarray
    used_eph: np.ndarray
    used_scalar: np.ndarray  # [S, N]
    nz_mcpu: np.ndarray
    nz_mem: np.ndarray
    pod_cnt: np.ndarray
    ports_used: np.ndarray  # [N, Pt] bool
    gpu_used: np.ndarray  # [N, G] i64
    vg_used: np.ndarray  # [N, V] i64
    ssd_used: np.ndarray  # [N, Ds] bool
    hdd_used: np.ndarray  # [N, Dh] bool


@dataclass
class PodBatch:
    """A batch of pods to schedule, class-deduplicated."""

    p: int
    u: int
    class_of_pod: np.ndarray  # [P] i32
    pinned_node: np.ndarray  # [P] i32, -1 when loose
    # per-class request vectors
    req_mcpu: np.ndarray  # [U]
    req_mem: np.ndarray
    req_eph: np.ndarray
    req_scalar: np.ndarray  # [U, S]
    has_request: np.ndarray  # [U] bool (any nonzero native/scalar request)
    nz_mcpu: np.ndarray
    nz_mem: np.ndarray
    gpu_mem: np.ndarray  # [U] per-GPU memory
    gpu_cnt: np.ndarray  # [U]
    want_ports: np.ndarray  # [U, Pt] bool (ports the pod binds)
    conflict_ports: np.ndarray  # [U, Pt] bool (vocab entries that would conflict)
    # open-local volume requests (sizes padded with 0)
    lvm_sizes: np.ndarray  # [U, Lv] i64, in declaration order
    ssd_sizes: np.ndarray  # [U, Sv] i64, ascending
    hdd_sizes: np.ndarray  # [U, Hv] i64, ascending
    wants_storage: np.ndarray  # [U] bool
    terms: TermTables  # affinity/spread tables
    # out-of-tree custom plugins (stateless: folded per class)
    custom_raw: np.ndarray  # [K, U, N] i64 raw scores (K>=1, dummy row 0)
    custom_mode: np.ndarray  # [K] i32: 0 none, 1 default, 2 reverse, 3 minmax
    custom_weight: np.ndarray  # [K] i64
    # static per-class matrices
    static_feasible: np.ndarray  # [U, N] bool
    simon_raw: np.ndarray  # [U, N] i64
    nodeaff_raw: np.ndarray  # [U, N] i64
    taint_intol: np.ndarray  # [U, N] i64
    avoid_score: np.ndarray  # [U, N] i64
    image_score: np.ndarray  # [U, N] i64
    # one representative pod per class (host-only, never shipped to
    # device): the bulk replay resolves per-class commit summaries from
    # these (engine.build_bulk_tables) — class members share
    # request/port content by class-key construction
    class_pods: list = None


# the expensive spec-side deep freeze runs once per workload template
# instead of once per pod (~7 s saved at 100k pods): replica clones
# share their containers / tolerations / affinity / selector objects
# (workloads.py _expand_template; utils/memo.py contract)
_SPEC_KEY_MEMO = IdentityMemo()


def _spec_key(spec: dict):
    parts = (
        spec.get("containers"),
        spec.get("initContainers"),
        spec.get("nodeSelector"),
        spec.get("affinity"),
        spec.get("topologySpreadConstraints"),
        spec.get("tolerations"),
        spec.get("overhead"),
    )
    return _SPEC_KEY_MEMO.get(parts, lambda: _freeze_spec_parts(spec))


def _freeze_spec_parts(spec: dict):
    containers = [
        {
            "resources": c.get("resources"),
            "ports": c.get("ports"),
            "image": c.get("image"),
        }
        for c in spec.get("containers") or []
    ]
    inits = [{"resources": c.get("resources")} for c in spec.get("initContainers") or []]
    return _freeze(
        {
            "nodeSelector": spec.get("nodeSelector"),
            "affinity": spec.get("affinity"),
            "topologySpreadConstraints": spec.get("topologySpreadConstraints"),
            "tolerations": spec.get("tolerations"),
            "overhead": spec.get("overhead"),
            "containers": containers,
            "inits": inits,
        }
    )


class _InternedKey:
    """A (spec_key, frozen_labels) pair with its deep hash computed
    once. Canonicalized by content in _KEY_INTERN, so equal content —
    even from distinct templates — is the SAME object and the classes
    dict compares by the `is` fast path instead of re-hashing a nested
    tuple per pod (the r4 capacity host-tail item)."""

    __slots__ = ("key", "_hash")

    def __init__(self, key):
        self.key = key
        self._hash = hash(key)

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return self is other or (
            isinstance(other, _InternedKey) and self.key == other.key
        )


_KEY_INTERN: dict = {}
_CLASS_PREFIX_MEMO = IdentityMemo()
register_cache(_KEY_INTERN.clear)


def _class_prefix(spec: dict, labels):
    """Identity-memoized, content-interned heavy part of the class key.
    The memo sources are every object `_spec_key`/`_freeze` read, so an
    identity hit implies identical content; template-expanded replicas
    share all of them (workloads._expand_template)."""

    def make():
        k = (_spec_key(spec), _freeze(labels))
        tok = _KEY_INTERN.get(k)
        if tok is None:
            tok = _KEY_INTERN[k] = _InternedKey(k)
        return tok

    return _CLASS_PREFIX_MEMO.get(
        (
            spec.get("containers"),
            spec.get("initContainers"),
            spec.get("nodeSelector"),
            spec.get("affinity"),
            spec.get("topologySpreadConstraints"),
            spec.get("tolerations"),
            spec.get("overhead"),
            labels,
        ),
        make,
    )


def _class_key(pod: dict):
    spec = pod.get("spec") or {}
    meta = pod.get("metadata") or {}
    anno = meta.get("annotations") or {}
    ctrl_kind = None
    for r in meta.get("ownerReferences") or ():
        if r.get("controller"):
            ctrl_kind = r.get("kind")
            break
    # content-based equality is preserved: the interned prefix compares
    # by content (identical content from distinct templates interns to
    # one object), per-pod cheap fields ride alongside
    return (
        _class_prefix(spec, meta.get("labels")),
        meta.get("namespace"),
        spec.get("nodeName"),
        spec.get("hostNetwork"),
        anno.get(stor.GPU_MEM_ANNO),
        anno.get(stor.GPU_COUNT_ANNO),
        anno.get(stor.ANNO_POD_LOCAL_STORAGE),
        ctrl_kind,
    )


# cross-run ClusterStatic cache: planners and benches call simulate()
# repeatedly over the SAME decoded node dicts, and a fresh Oracle's
# pristine (alloc_epoch == 0) encoding is a pure function of those
# source objects — same identity-memo warm-cache contract as the
# request/port memos (utils/memo.py; clear_all_memos releases it).
# Sharing the ClusterStatic object across runs also keeps the pallas
# device-plan caches warm (they key on plan identity derived from it).
# port_vocab/port_conflict are per-batch fields set by encode_batch
# BEFORE every use, so sharing the carrier object is safe
# single-threaded. GPU runs bump alloc_epoch and bypass this cache.
_CLUSTER_MEMO = IdentityMemo(max_entries=64)


def encode_cluster_cached(oracle: Oracle) -> ClusterStatic:
    src = getattr(oracle, "source_nodes", None)
    if src is None or oracle.alloc_epoch != 0:
        return encode_cluster(oracle)
    return _CLUSTER_MEMO.get(tuple(src), lambda: encode_cluster(oracle))


def encode_cluster(oracle: Oracle) -> ClusterStatic:
    nodes = oracle.nodes
    n = len(nodes)
    alloc_mcpu = np.array([ns.alloc_milli_cpu() for ns in nodes], dtype=np.int64)
    alloc_mem = np.array([ns.alloc_int(req.MEMORY) for ns in nodes], dtype=np.int64)
    alloc_eph = np.array([ns.alloc_int(req.EPHEMERAL) for ns in nodes], dtype=np.int64)
    alloc_pods = np.array([ns.alloc_int(req.PODS) for ns in nodes], dtype=np.int64)

    simon_resources = sorted({name for ns in nodes for name in ns.alloc})
    simon_alloc = np.zeros((len(simon_resources), n), dtype=np.float64)
    for r_i, name in enumerate(simon_resources):
        for n_i, ns in enumerate(nodes):
            simon_alloc[r_i, n_i] = float(ns.alloc.get(name, Fraction(0)))

    scalar_names = sorted(
        {
            name
            for ns in nodes
            for name in ns.alloc
            if name not in (req.CPU, req.MEMORY, req.EPHEMERAL, req.PODS)
            and req.is_scalar_resource(name)
        }
    )
    scalar_alloc = np.zeros((len(scalar_names), n), dtype=np.int64)
    for s_i, name in enumerate(scalar_names):
        for n_i, ns in enumerate(nodes):
            scalar_alloc[s_i, n_i] = ns.alloc_int(name)

    gpu_count = np.array([ns.gpu.count if ns.gpu else 0 for ns in nodes], dtype=np.int64)
    gpu_per_dev = np.array(
        [ns.gpu.per_device_mem if ns.gpu else 0 for ns in nodes], dtype=np.int64
    )
    gpu_total = np.array(
        [stor.node_total_gpu_memory(ns.node) for ns in nodes], dtype=np.int64
    )
    g = int(gpu_count.max()) if n else 0

    # open-local storage layout
    has_storage = np.array([ns.storage is not None for ns in nodes], dtype=bool)
    v = max((len(ns.storage.vgs) for ns in nodes if ns.storage), default=0)
    d_ssd = max(
        (
            sum(1 for d in ns.storage.devices if d.media_type == "ssd")
            for ns in nodes
            if ns.storage
        ),
        default=0,
    )
    d_hdd = max(
        (
            sum(1 for d in ns.storage.devices if d.media_type == "hdd")
            for ns in nodes
            if ns.storage
        ),
        default=0,
    )
    vg_cap = np.zeros((n, max(v, 1)), dtype=np.int64)
    vg_valid = np.zeros((n, max(v, 1)), dtype=bool)
    ssd_cap = np.zeros((n, max(d_ssd, 1)), dtype=np.int64)
    ssd_valid = np.zeros((n, max(d_ssd, 1)), dtype=bool)
    hdd_cap = np.zeros((n, max(d_hdd, 1)), dtype=np.int64)
    hdd_valid = np.zeros((n, max(d_hdd, 1)), dtype=bool)
    for n_i, ns in enumerate(nodes):
        if not ns.storage:
            continue
        for v_i, vg in enumerate(ns.storage.vgs):
            vg_cap[n_i, v_i] = vg.capacity
            vg_valid[n_i, v_i] = True
        # devices ascending by capacity (stable), matching the oracle's
        # _device_fit sort; is_allocated state goes in DynamicState
        for media, cap_arr, valid_arr in (
            ("ssd", ssd_cap, ssd_valid),
            ("hdd", hdd_cap, hdd_valid),
        ):
            devs = sorted(
                (d for d in ns.storage.devices if d.media_type == media),
                key=lambda d: d.capacity,
            )
            for d_i, dev in enumerate(devs):
                cap_arr[n_i, d_i] = dev.capacity
                valid_arr[n_i, d_i] = True

    # port vocab built later (needs the pod batch); placeholder
    return ClusterStatic(
        n=n,
        node_names=[ns.name for ns in nodes],
        alloc_mcpu=alloc_mcpu,
        alloc_mem=alloc_mem,
        alloc_eph=alloc_eph,
        alloc_pods=alloc_pods,
        simon_resources=simon_resources,
        simon_alloc=simon_alloc,
        scalar_names=scalar_names,
        scalar_alloc=scalar_alloc,
        g=g,
        gpu_count=gpu_count,
        gpu_per_dev=gpu_per_dev,
        gpu_total=gpu_total,
        v=v,
        vg_cap=vg_cap,
        vg_valid=vg_valid,
        has_storage=has_storage,
        d_ssd=d_ssd,
        d_hdd=d_hdd,
        ssd_cap=ssd_cap,
        ssd_valid=ssd_valid,
        hdd_cap=hdd_cap,
        hdd_valid=hdd_valid,
        port_vocab=[],
        port_conflict=np.zeros((0, 0), dtype=bool),
    )


def encode_dynamic(oracle: Oracle, cluster: ClusterStatic) -> DynamicState:
    nodes = oracle.nodes
    n = cluster.n
    s = len(cluster.scalar_names)
    pt = len(cluster.port_vocab)
    g = max(cluster.g, 1)
    st = DynamicState(
        used_mcpu=np.array([ns.req_mcpu for ns in nodes], dtype=np.int64),
        used_mem=np.array([ns.req_mem for ns in nodes], dtype=np.int64),
        used_eph=np.array([ns.req_eph for ns in nodes], dtype=np.int64),
        used_scalar=np.zeros((s, n), dtype=np.int64),
        nz_mcpu=np.array([ns.nz_mcpu for ns in nodes], dtype=np.int64),
        nz_mem=np.array([ns.nz_mem for ns in nodes], dtype=np.int64),
        pod_cnt=np.array([len(ns.pods) for ns in nodes], dtype=np.int64),
        ports_used=np.zeros((n, pt), dtype=bool),
        gpu_used=np.zeros((n, g), dtype=np.int64),
        vg_used=np.zeros((n, max(cluster.v, 1)), dtype=np.int64),
        ssd_used=np.zeros((n, max(cluster.d_ssd, 1)), dtype=bool),
        hdd_used=np.zeros((n, max(cluster.d_hdd, 1)), dtype=bool),
    )
    for s_i, name in enumerate(cluster.scalar_names):
        for n_i, ns in enumerate(nodes):
            st.used_scalar[s_i, n_i] = ns.req_scalar.get(name, 0)
    for n_i, ns in enumerate(nodes):
        for port in ns.used_ports:
            if port in cluster.port_vocab:
                st.ports_used[n_i, cluster.port_vocab.index(port)] = True
        if ns.gpu:
            for g_i, used in enumerate(ns.gpu.used):
                st.gpu_used[n_i, g_i] = used
        if ns.storage:
            for v_i, vg in enumerate(ns.storage.vgs):
                st.vg_used[n_i, v_i] = vg.requested
            for media, used_arr in (("ssd", st.ssd_used), ("hdd", st.hdd_used)):
                devs = sorted(
                    (d for d in ns.storage.devices if d.media_type == media),
                    key=lambda d: d.capacity,
                )
                for d_i, dev in enumerate(devs):
                    used_arr[n_i, d_i] = dev.is_allocated
    return st


def _ports_conflict_pair(a: tuple, b: tuple) -> bool:
    (aip, aproto, aport), (bip, bproto, bport) = a, b
    if aport != bport or aproto != bproto:
        return False
    return aip == "0.0.0.0" or bip == "0.0.0.0" or aip == bip


def _image_scores_by_profile(
    pod: dict, oracle: Oracle, rep_idx, profile_counts
) -> np.ndarray:
    """ImageLocality raw scores per node profile (mirrors
    Oracle._score_image_locality bit for bit; image spread counts come
    from profile counts instead of a scan over every node)."""
    containers = (pod.get("spec") or {}).get("containers") or []
    nc = len(rep_idx)
    if not containers:
        return np.zeros(nc, dtype=np.int64)
    total_nodes = len(oracle.nodes)
    wanted = set()
    norm_names = []
    for c in containers:
        name = c.get("image", "")
        if ":" not in name.rsplit("/", 1)[-1]:
            name = name + ":latest"
        wanted.add(name)
        norm_names.append(name)
    # per-profile image presence/size
    rep_images: List[dict] = []
    for r in rep_idx:
        images = {}
        for img in ((oracle.nodes[int(r)].node.get("status") or {}).get("images")) or []:
            size = int(img.get("sizeBytes", 0))
            for name in img.get("names") or []:
                if name in wanted:
                    images[name] = size
        rep_images.append(images)
    spread: Dict[str, int] = {w: 0 for w in wanted}
    for c_i, images in enumerate(rep_images):
        for name in images:
            spread[name] += int(profile_counts[c_i])
    out = np.zeros(nc, dtype=np.int64)
    max_threshold = IMG_MAX_CONTAINER_THRESHOLD * len(containers)
    for c_i, images in enumerate(rep_images):
        s = 0
        for name in norm_names:
            if name in images:
                s += int(images[name] * (spread[name] / total_nodes))
        s = min(max(s, IMG_MIN_THRESHOLD), max_threshold)
        out[c_i] = (
            MAX_NODE_SCORE * (s - IMG_MIN_THRESHOLD) // (max_threshold - IMG_MIN_THRESHOLD)
        )
    return out


def encode_batch(
    oracle: Oracle, cluster: ClusterStatic, pods: List[dict], groups=None
) -> PodBatch:
    """Build class-deduplicated static tensors for a pod batch.

    `groups` is the optional (group_of, firsts) content-group index
    from workload expansion (workloads.ExpandIndex): group members are
    content-identical except metadata.name, so the class key, host
    ports, and pin target resolve once per GROUP and broadcast to pods
    by numpy indexing — the class-dedup loop drops from O(pods) dict
    work to O(groups)."""
    # port vocabulary over batch + existing usage
    vocab: List[tuple] = []
    seen = set()
    for ns in oracle.nodes:
        for port in sorted(ns.used_ports):
            if port not in seen:
                seen.add(port)
                vocab.append(port)
    port_scan = pods if groups is None else groups[1]
    for pod in port_scan:
        for port in _pod_host_ports(pod):
            if port not in seen:
                seen.add(port)
                vocab.append(port)
    pt = len(vocab)
    conflict = np.zeros((pt, pt), dtype=bool)
    for i in range(pt):
        for j in range(pt):
            conflict[i, j] = _ports_conflict_pair(vocab[i], vocab[j])
    cluster.port_vocab = vocab
    cluster.port_conflict = conflict

    # class dedup
    class_ids: Dict[str, int] = {}
    class_pods: List[dict] = []
    if groups is not None:
        group_of, firsts = groups
        ng = len(firsts)
        g2c = np.zeros(ng, dtype=np.int32)
        g_pin = np.full(ng, -1, dtype=np.int32)
        node_index = oracle.node_index
        for g_i, first in enumerate(firsts):
            key = _class_key(first)
            if key not in class_ids:
                class_ids[key] = len(class_pods)
                class_pods.append(first)
            g2c[g_i] = class_ids[key]
            node_name = (first.get("spec") or {}).get("nodeName")
            if node_name:
                g_pin[g_i] = node_index.get(node_name, -1)
        if len(pods):
            class_of_pod = g2c[group_of].astype(np.int32, copy=False)
            pinned = g_pin[group_of].astype(np.int32, copy=False)
        else:
            class_of_pod = np.zeros(0, dtype=np.int32)
            pinned = np.full(0, -1, dtype=np.int32)
    else:
        class_of_pod = np.zeros(len(pods), dtype=np.int32)
        pinned = np.full(len(pods), -1, dtype=np.int32)
        for p_i, pod in enumerate(pods):
            key = _class_key(pod)
            if key not in class_ids:
                class_ids[key] = len(class_pods)
                class_pods.append(pod)
            class_of_pod[p_i] = class_ids[key]
            node_name = (pod.get("spec") or {}).get("nodeName")
            if node_name:
                pinned[p_i] = oracle.node_index.get(node_name, -1)

    u = len(class_pods)
    n = cluster.n
    s = len(cluster.scalar_names)

    req_mcpu = np.zeros(u, dtype=np.int64)
    req_mem = np.zeros(u, dtype=np.int64)
    req_eph = np.zeros(u, dtype=np.int64)
    req_scalar = np.zeros((u, s), dtype=np.int64)
    has_request = np.zeros(u, dtype=bool)
    nz_mcpu = np.zeros(u, dtype=np.int64)
    nz_mem = np.zeros(u, dtype=np.int64)
    gpu_mem = np.zeros(u, dtype=np.int64)
    gpu_cnt = np.zeros(u, dtype=np.int64)
    want_ports = np.zeros((u, pt), dtype=bool)
    conflict_ports = np.zeros((u, pt), dtype=bool)
    class_volumes = [stor.parse_pod_local_volumes(p) for p in class_pods]
    lv = max((len(lvm) for lvm, _dev in class_volumes), default=0)
    sv = max(
        (sum(1 for d in dev if d.kind == "SSD") for _lvm, dev in class_volumes), default=0
    )
    hv = max(
        (sum(1 for d in dev if d.kind == "HDD") for _lvm, dev in class_volumes), default=0
    )
    lvm_sizes = np.zeros((u, max(lv, 1)), dtype=np.int64)
    ssd_sizes = np.zeros((u, max(sv, 1)), dtype=np.int64)
    hdd_sizes = np.zeros((u, max(hv, 1)), dtype=np.int64)
    wants_storage = np.zeros(u, dtype=bool)
    static_feasible = np.ones((u, n), dtype=bool)
    simon_raw = np.zeros((u, n), dtype=np.int64)
    nodeaff_raw = np.zeros((u, n), dtype=np.int64)
    taint_intol = np.zeros((u, n), dtype=np.int64)
    avoid_score = np.zeros((u, n), dtype=np.int64)
    image_score = np.zeros((u, n), dtype=np.int64)

    node_class_of, rep_idx = _shared_node_profiles(
        [ns.node for ns in oracle.nodes], class_pods,
        cache_sources=getattr(oracle, "source_nodes", None),
    )
    profile_counts = np.bincount(node_class_of, minlength=len(rep_idx))

    for u_i, pod in enumerate(class_pods):
        spec = pod.get("spec") or {}
        requests = req.pod_requests(pod)
        req_mcpu[u_i] = _ceil(requests.get(req.CPU, Fraction(0)) * 1000)
        req_mem[u_i] = _ceil(requests.get(req.MEMORY, Fraction(0)))
        req_eph[u_i] = _ceil(requests.get(req.EPHEMERAL, Fraction(0)))
        any_scalar = False
        for s_i, name in enumerate(cluster.scalar_names):
            if name in requests:
                req_scalar[u_i, s_i] = _ceil(requests[name])
                any_scalar = any_scalar or req_scalar[u_i, s_i] != 0
        # scalar request on a resource NO node advertises still blocks
        # scheduling via fitsRequest; treat as statically infeasible
        unknown_scalar = any(
            name not in (req.CPU, req.MEMORY, req.EPHEMERAL, req.PODS)
            and req.is_scalar_resource(name)
            and name not in cluster.scalar_names
            and _ceil(requests[name]) > 0
            for name in requests
        )
        has_request[u_i] = bool(
            req_mcpu[u_i] or req_mem[u_i] or req_eph[u_i] or any_scalar or unknown_scalar
        )
        nz_mcpu[u_i] = req.pod_nonzero_request(pod, req.CPU)
        nz_mem[u_i] = req.pod_nonzero_request(pod, req.MEMORY)
        g_mem, g_cnt = stor.pod_gpu_request(pod)
        gpu_mem[u_i] = g_mem
        gpu_cnt[u_i] = g_cnt
        lvm_vols, dev_vols = class_volumes[u_i]
        wants_storage[u_i] = bool(lvm_vols or dev_vols)
        for i, vol in enumerate(lvm_vols):
            lvm_sizes[u_i, i] = vol.size
        # device volumes ascending by size per media (the oracle's
        # _device_fit sorts them the same way)
        for kind, arr in (("SSD", ssd_sizes), ("HDD", hdd_sizes)):
            sizes = sorted(v.size for v in dev_vols if v.kind == kind)
            for i, size in enumerate(sizes):
                arr[u_i, i] = size
        for port in _pod_host_ports(pod):
            w_i = vocab.index(port)
            want_ports[u_i, w_i] = True
        conflict_ports[u_i] = (
            want_ports[u_i].astype(np.int32) @ conflict.astype(np.int32)
        ) > 0

        tolerations = spec.get("tolerations") or []
        unsched_tolerated = lbl.tolerations_tolerate_taint(
            tolerations,
            {"key": "node.kubernetes.io/unschedulable", "effect": "NoSchedule"},
        )
        simon_empty = not requests and not req.pod_limits(pod)

        # label/taint feasibility + static scores, evaluated once per
        # node profile (per node when the class reads node names)
        if _uses_match_fields(spec):
            dom = np.arange(n, dtype=np.int64)
            inv = None
        else:
            dom = rep_idx
            inv = node_class_of
        nd = len(dom)
        ok_d = np.empty(nd, dtype=bool)
        aff_d = np.empty(nd, dtype=np.int64)
        intol_d = np.empty(nd, dtype=np.int64)
        for j in range(nd):
            ns = oracle.nodes[int(dom[j])]
            node = ns.node
            nspec = node.get("spec") or {}
            taints = nspec.get("taints") or []
            ok = True
            if nspec.get("unschedulable") and not unsched_tolerated:
                ok = False
            if ok and unknown_scalar:
                ok = False
            if ok and lbl.find_untolerated_taint(taints, tolerations):
                ok = False
            if ok and not lbl.pod_matches_node_selector_and_affinity(spec, node):
                ok = False
            ok_d[j] = ok
            aff_d[j] = lbl.preferred_node_affinity_score(spec, node)
            intol_d[j] = lbl.count_intolerable_prefer_no_schedule(taints, tolerations)
        if inv is None:
            static_feasible[u_i] = ok_d
            nodeaff_raw[u_i] = aff_d
            taint_intol[u_i] = intol_d
            avoid_score[u_i] = _avoid_scores(pod, oracle)
            image_score[u_i] = _image_scores(pod, oracle)
        else:
            static_feasible[u_i] = ok_d[inv]
            nodeaff_raw[u_i] = aff_d[inv]
            taint_intol[u_i] = intol_d[inv]
            rep_states = [oracle.nodes[int(r)] for r in rep_idx]
            avoid_score[u_i] = np.asarray(
                Oracle._score_prefer_avoid_pods(oracle, pod, rep_states),
                dtype=np.int64,
            )[inv]
            image_score[u_i] = _image_scores_by_profile(
                pod, oracle, rep_idx, profile_counts
            )[inv]

        # Simon raw share (static: pod annotations never enter podReq),
        # vectorized over the node axis (plugin/simon.go:44-67 semantics)
        if simon_empty:
            simon_raw[u_i] = MAX_NODE_SCORE
        else:
            pr = np.array(
                [float(requests.get(name, Fraction(0))) for name in cluster.simon_resources],
                dtype=np.float64,
            )
            avail = cluster.simon_alloc - pr[:, None]  # [R, N]
            with np.errstate(divide="ignore", invalid="ignore"):
                share = np.where(
                    avail == 0.0,
                    (pr != 0.0).astype(np.float64)[:, None],
                    pr[:, None] / avail,
                )
            res = np.maximum(share.max(axis=0), 0.0) if len(pr) else np.zeros(n)
            simon_raw[u_i] = (MAX_NODE_SCORE * res).astype(np.int64)

    # out-of-tree custom plugins: stateless verdicts folded per class
    # (the engine-side analogue of WithFrameworkOutOfTreeRegistry)
    plugins = oracle.registry.plugins
    k = max(len(plugins), 1)
    custom_raw = np.zeros((k, u, n), dtype=np.int64)
    custom_mode = np.zeros(k, dtype=np.int32)
    custom_weight = np.zeros(k, dtype=np.int64)
    mode_ids = {"none": 0, "default": 1, "reverse": 2, "minmax": 3}
    for k_i, plugin in enumerate(plugins):
        custom_mode[k_i] = mode_ids[plugin.normalize]
        custom_weight[k_i] = plugin.weight
        for u_i, pod in enumerate(class_pods):
            for n_i, ns in enumerate(oracle.nodes):
                if not static_feasible[u_i, n_i]:
                    continue  # already ruled out; raw score is masked anyway
                if not plugin.filter(pod, ns.node):
                    static_feasible[u_i, n_i] = False
                else:
                    custom_raw[k_i, u_i, n_i] = int(plugin.score(pod, ns.node))

    terms = build_term_tables(oracle, class_pods, profiles=(node_class_of, rep_idx))

    return PodBatch(
        p=len(pods),
        u=u,
        class_of_pod=class_of_pod,
        pinned_node=pinned,
        req_mcpu=req_mcpu,
        req_mem=req_mem,
        req_eph=req_eph,
        req_scalar=req_scalar,
        has_request=has_request,
        nz_mcpu=nz_mcpu,
        nz_mem=nz_mem,
        gpu_mem=gpu_mem,
        gpu_cnt=gpu_cnt,
        want_ports=want_ports,
        conflict_ports=conflict_ports,
        lvm_sizes=lvm_sizes,
        ssd_sizes=ssd_sizes,
        hdd_sizes=hdd_sizes,
        wants_storage=wants_storage,
        terms=terms,
        custom_raw=custom_raw,
        custom_mode=custom_mode,
        custom_weight=custom_weight,
        static_feasible=static_feasible,
        simon_raw=simon_raw,
        nodeaff_raw=nodeaff_raw,
        taint_intol=taint_intol,
        avoid_score=avoid_score,
        image_score=image_score,
        class_pods=class_pods,
    )


def features_of_batch(cluster: ClusterStatic, batch: PodBatch, weights=None,
                      sample: bool = False):
    """ScanFeatures from the host-side encodings — same result as
    scan.features_of(static, pinned) but without device->host transfers
    (the arrays are still numpy here). `weights` is an optional
    schedconfig.ScoreWeights overlay (static per compile); `sample`
    routes selectHost through the carried Go RNG (oracle
    select_host="sample")."""
    from .scan import ScanFeatures

    t = batch.terms
    return ScanFeatures(
        sample=sample,
        weights=weights,
        gpu=bool(batch.gpu_mem.max(initial=0) > 0),
        storage=bool(batch.wants_storage.any()),
        ipa=bool((t.cls_rows >= 0).any() or (t.cls_group_id >= 0).any()),
        hard_spread=bool((t.cls_h_rows >= 0).any()),
        soft_spread=bool((t.cls_s_rows >= 0).any()),
        ports=bool(batch.want_ports.any()),
        scalars=cluster.scalar_alloc.shape[0] > 0,
        custom=bool((batch.custom_weight != 0).any()),
        pins=bool((batch.pinned_node >= 0).any()),
        custom_spec=tuple(
            zip(
                (int(m) for m in batch.custom_mode),
                (int(w) for w in batch.custom_weight),
            )
        ),
    )


def to_scan_static(cluster: ClusterStatic, batch: PodBatch):
    """Assemble the ScanStatic NamedTuple (device arrays) from host
    encodings — the single place the scan's input layout is defined."""
    import jax.numpy as jnp

    from . import scan as scan_ops

    n, g = cluster.n, max(cluster.g, 1)
    dev_valid = np.zeros((n, g), dtype=bool)
    for i in range(n):
        dev_valid[i, : cluster.gpu_count[i]] = True
    return scan_ops.ScanStatic(
        alloc_mcpu=jnp.asarray(cluster.alloc_mcpu),
        alloc_mem=jnp.asarray(cluster.alloc_mem),
        alloc_eph=jnp.asarray(cluster.alloc_eph),
        alloc_pods=jnp.asarray(cluster.alloc_pods),
        scalar_alloc=jnp.asarray(cluster.scalar_alloc),
        gpu_per_dev=jnp.asarray(cluster.gpu_per_dev),
        gpu_total=jnp.asarray(cluster.gpu_total),
        gpu_count=jnp.asarray(cluster.gpu_count),
        dev_valid=jnp.asarray(dev_valid),
        vg_cap=jnp.asarray(cluster.vg_cap),
        vg_valid=jnp.asarray(cluster.vg_valid),
        has_storage=jnp.asarray(cluster.has_storage),
        ssd_cap=jnp.asarray(cluster.ssd_cap),
        ssd_valid=jnp.asarray(cluster.ssd_valid),
        hdd_cap=jnp.asarray(cluster.hdd_cap),
        hdd_valid=jnp.asarray(cluster.hdd_valid),
        static_feasible=jnp.asarray(batch.static_feasible),
        simon_raw=jnp.asarray(batch.simon_raw),
        nodeaff_raw=jnp.asarray(batch.nodeaff_raw),
        taint_intol=jnp.asarray(batch.taint_intol),
        avoid_score=jnp.asarray(batch.avoid_score),
        image_score=jnp.asarray(batch.image_score),
        req_mcpu=jnp.asarray(batch.req_mcpu),
        req_mem=jnp.asarray(batch.req_mem),
        req_eph=jnp.asarray(batch.req_eph),
        req_scalar=jnp.asarray(batch.req_scalar),
        has_request=jnp.asarray(batch.has_request),
        nz_mcpu=jnp.asarray(batch.nz_mcpu),
        nz_mem=jnp.asarray(batch.nz_mem),
        gpu_mem=jnp.asarray(batch.gpu_mem),
        gpu_cnt=jnp.asarray(batch.gpu_cnt),
        want_ports=jnp.asarray(batch.want_ports),
        conflict_ports=jnp.asarray(batch.conflict_ports),
        lvm_sizes=jnp.asarray(batch.lvm_sizes),
        ssd_sizes=jnp.asarray(batch.ssd_sizes),
        hdd_sizes=jnp.asarray(batch.hdd_sizes),
        wants_storage=jnp.asarray(batch.wants_storage),
        topo_val=jnp.asarray(batch.terms.topo_val),
        term_match=jnp.asarray(batch.terms.match),
        carry_anti_req=jnp.asarray(batch.terms.carry_anti_req),
        carry_aff_pref_w=jnp.asarray(batch.terms.carry_aff_pref_w),
        carry_pref_comb=jnp.asarray(combined_pref_carry(batch.terms)),
        carry_anti_pref_w=jnp.asarray(batch.terms.carry_anti_pref_w),
        cls_rows=jnp.asarray(batch.terms.cls_rows),
        group_of_row=jnp.asarray(batch.terms.group_of_row),
        match_all=jnp.asarray(batch.terms.match_all),
        cls_group_rows=jnp.asarray(batch.terms.cls_group_rows),
        cls_group_id=jnp.asarray(batch.terms.cls_group_id),
        h_row=jnp.asarray(batch.terms.h_row),
        h_self=jnp.asarray(batch.terms.h_self),
        h_max_skew=jnp.asarray(batch.terms.h_max_skew),
        h_cand_nodes=jnp.asarray(batch.terms.h_cand_nodes),
        cls_h_rows=jnp.asarray(batch.terms.cls_h_rows),
        s_row=jnp.asarray(batch.terms.s_row),
        s_is_host=jnp.asarray(batch.terms.s_is_host),
        s_max_skew=jnp.asarray(batch.terms.s_max_skew),
        s_q=jnp.asarray(batch.terms.s_q),
        cls_s_rows=jnp.asarray(batch.terms.cls_s_rows),
        cls_s_haskeys=jnp.asarray(batch.terms.cls_s_haskeys),
        g_topo_val=jnp.asarray(batch.terms.topo_val[batch.terms.group_rows]),
        s_topo_val=jnp.asarray(batch.terms.topo_val[batch.terms.s_row]),
        s_val_onehot=jnp.asarray(_soft_value_onehot(batch.terms)),
        custom_raw=jnp.asarray(batch.custom_raw),
        custom_mode=jnp.asarray(batch.custom_mode),
        custom_weight=jnp.asarray(batch.custom_weight),
    )


def _soft_value_onehot(t) -> np.ndarray:
    """[Cs, Vs, N] static value one-hot for the soft-spread distinct-
    domain count (scan.py soft_score). Hostname rows stay all-zero —
    their domain count is the eligible-node count (s_is_host branch) —
    so Vs is bounded by the small non-hostname vocab, not N."""
    s_tv = t.topo_val[t.s_row]  # [Cs, N]
    if not (t.cls_s_rows >= 0).any():
        # no real soft constraint: Cs=1 is pure padding whose s_row
        # points at row 0 — without this gate a hostname row 0 would
        # blow Vs up to N (an O(N^2) one-hot nobody reads)
        return np.zeros((s_tv.shape[0], 1, s_tv.shape[1]), dtype=bool)
    nonhost = ~t.s_is_host
    vs = 1
    if nonhost.any():
        mx = int(s_tv[nonhost].max(initial=-1))
        vs = max(mx + 1, 1)
    out = np.zeros((s_tv.shape[0], vs, s_tv.shape[1]), dtype=bool)
    for c_i in range(s_tv.shape[0]):
        if not nonhost[c_i]:
            continue
        vals = s_tv[c_i]
        mask = vals >= 0
        out[c_i, vals[mask], np.nonzero(mask)[0]] = True
    return out


def _value_to_node_space(init_v: np.ndarray, topo: np.ndarray) -> np.ndarray:
    """[R, V] value-space counts -> [R, N] node-space (count at each
    node's own value; 0 where the key is missing)."""
    g = np.take_along_axis(init_v, np.maximum(topo, 0).astype(np.int64), axis=1)
    return np.where(topo >= 0, g, 0)


def to_scan_state(dyn: DynamicState, batch: PodBatch):
    import jax.numpy as jnp

    from . import scan as scan_ops

    t = batch.terms
    tv = t.topo_val
    return scan_ops.ScanState(
        used_mcpu=jnp.asarray(dyn.used_mcpu),
        used_mem=jnp.asarray(dyn.used_mem),
        used_eph=jnp.asarray(dyn.used_eph),
        used_scalar=jnp.asarray(dyn.used_scalar),
        nz_mcpu=jnp.asarray(dyn.nz_mcpu),
        nz_mem=jnp.asarray(dyn.nz_mem),
        pod_cnt=jnp.asarray(dyn.pod_cnt),
        ports_used=jnp.asarray(dyn.ports_used),
        gpu_used=jnp.asarray(dyn.gpu_used),
        vg_used=jnp.asarray(dyn.vg_used),
        ssd_used=jnp.asarray(dyn.ssd_used),
        hdd_used=jnp.asarray(dyn.hdd_used),
        tgt=jnp.asarray(_value_to_node_space(t.init_tgt, tv)),
        own_anti_req=jnp.asarray(_value_to_node_space(t.init_own_anti_req, tv)),
        own_aff_pref_w=jnp.asarray(
            _value_to_node_space(combined_pref_init(t), tv)
        ),
        own_anti_pref_w=jnp.asarray(_value_to_node_space(t.init_own_anti_pref_w, tv)),
        group_counts=jnp.asarray(
            _value_to_node_space(t.init_group_counts, tv[t.group_rows])
        ),
        group_total=jnp.asarray(t.init_group_counts.sum(axis=1)),
        soft_counts=jnp.asarray(
            _value_to_node_space(t.init_soft_counts, tv[t.s_row])
        ),
    )


def _avoid_scores(pod: dict, oracle: Oracle) -> np.ndarray:
    out = np.zeros(len(oracle.nodes), dtype=np.int64)
    scores = Oracle._score_prefer_avoid_pods(oracle, pod, oracle.nodes)
    out[:] = scores
    return out


def _image_scores(pod: dict, oracle: Oracle) -> np.ndarray:
    out = np.zeros(len(oracle.nodes), dtype=np.int64)
    scores = Oracle._score_image_locality(oracle, pod, oracle.nodes)
    out[:] = scores
    return out
