"""Host-side tensorization of cluster + pod-batch state.

The fake-apiserver object store of the reference (client-go ObjectTracker
+ scheduler cache snapshot, vendor/.../internal/cache/snapshot.go:29)
collapses into dense arrays:

- per-node allocatable vectors (cpu milli, memory bytes, ephemeral,
  pod slots) and a generic `[R, N]` allocatable matrix for the Simon
  max-share score (plugin/simon.go:44-67)
- per-pod-CLASS static matrices `[U, N]`: everything that does not
  depend on placement state — taint/affinity/nodename/unschedulable
  feasibility, preferred-node-affinity raw scores, PreferNoSchedule
  intolerable-taint counts, NodePreferAvoidPods, ImageLocality, Simon
  raw shares. Pods expanded from the same workload share a class, so
  the O(pods x nodes) host work shrinks to O(classes x nodes).
- a small host-port vocabulary with a pairwise conflict matrix
  (wildcard-IP semantics of HostPortInfo.CheckConflict)
- per-device GPU memory state for the open-gpu-share plugin

Dynamic state (requested resources, pod counts, port usage, GPU usage)
lives in the scan carry (ops/scan.py).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from fractions import Fraction
from typing import Dict, List, Optional

import numpy as np

from ..models import labels as lbl
from ..models import requests as req
from ..models import storage as stor
from ..scheduler.oracle import (
    GpuState,
    NodeState,
    Oracle,
    _pod_host_ports,
    IMG_MIN_THRESHOLD,
    IMG_MAX_CONTAINER_THRESHOLD,
    MAX_NODE_SCORE,
)


class EngineUnsupported(Exception):
    """Raised when the pod batch (or existing cluster state) uses a
    feature the vectorized engine does not cover yet; the caller falls
    back to the serial oracle."""


def _ceil(v: Fraction) -> int:
    return -((-v.numerator) // v.denominator)


def _has_pod_affinity(pod: dict) -> bool:
    aff = ((pod.get("spec") or {}).get("affinity")) or {}
    return bool(aff.get("podAffinity") or aff.get("podAntiAffinity"))


def _has_spread(pod: dict) -> bool:
    return bool((pod.get("spec") or {}).get("topologySpreadConstraints"))


def _has_local_storage(pod: dict) -> bool:
    lvm, dev = stor.parse_pod_local_volumes(pod)
    return bool(lvm or dev)


@dataclass
class ClusterStatic:
    """Placement-independent cluster tensors."""

    n: int
    node_names: List[str]
    alloc_mcpu: np.ndarray  # [N] i64
    alloc_mem: np.ndarray  # [N] i64
    alloc_eph: np.ndarray  # [N] i64
    alloc_pods: np.ndarray  # [N] i64
    # Simon score: allocatable matrix over the union of resource names
    simon_resources: List[str]
    simon_alloc: np.ndarray  # [R, N] f64
    # scalar (extended) resources tracked by NodeResourcesFit
    scalar_names: List[str]
    scalar_alloc: np.ndarray  # [S, N] i64
    # GPU share
    g: int  # max devices on any node
    gpu_count: np.ndarray  # [N] i64
    gpu_per_dev: np.ndarray  # [N] i64
    gpu_total: np.ndarray  # [N] i64 (capacity gpu-mem)
    # ports vocabulary
    port_vocab: List[tuple]
    port_conflict: np.ndarray  # [Pt, Pt] bool


@dataclass
class DynamicState:
    """The scan carry, as host arrays (mirrors oracle NodeState)."""

    used_mcpu: np.ndarray
    used_mem: np.ndarray
    used_eph: np.ndarray
    used_scalar: np.ndarray  # [S, N]
    nz_mcpu: np.ndarray
    nz_mem: np.ndarray
    pod_cnt: np.ndarray
    ports_used: np.ndarray  # [N, Pt] bool
    gpu_used: np.ndarray  # [N, G] i64


@dataclass
class PodBatch:
    """A batch of pods to schedule, class-deduplicated."""

    p: int
    u: int
    class_of_pod: np.ndarray  # [P] i32
    pinned_node: np.ndarray  # [P] i32, -1 when loose
    # per-class request vectors
    req_mcpu: np.ndarray  # [U]
    req_mem: np.ndarray
    req_eph: np.ndarray
    req_scalar: np.ndarray  # [U, S]
    has_request: np.ndarray  # [U] bool (any nonzero native/scalar request)
    nz_mcpu: np.ndarray
    nz_mem: np.ndarray
    gpu_mem: np.ndarray  # [U] per-GPU memory
    gpu_cnt: np.ndarray  # [U]
    want_ports: np.ndarray  # [U, Pt] bool (ports the pod binds)
    conflict_ports: np.ndarray  # [U, Pt] bool (vocab entries that would conflict)
    # static per-class matrices
    static_feasible: np.ndarray  # [U, N] bool
    simon_raw: np.ndarray  # [U, N] i64
    nodeaff_raw: np.ndarray  # [U, N] i64
    taint_intol: np.ndarray  # [U, N] i64
    avoid_score: np.ndarray  # [U, N] i64
    image_score: np.ndarray  # [U, N] i64


def _class_key(pod: dict) -> str:
    spec = pod.get("spec") or {}
    meta = pod.get("metadata") or {}
    anno = meta.get("annotations") or {}
    refs = meta.get("ownerReferences") or []
    ctrl = next((r for r in refs if r.get("controller")), None)
    containers = [
        {
            "resources": c.get("resources"),
            "ports": c.get("ports"),
            "image": c.get("image"),
        }
        for c in spec.get("containers") or []
    ]
    inits = [{"resources": c.get("resources")} for c in spec.get("initContainers") or []]
    key = {
        "ns": meta.get("namespace"),
        "nodeSelector": spec.get("nodeSelector"),
        "affinity": spec.get("affinity"),
        "tolerations": spec.get("tolerations"),
        "nodeName": spec.get("nodeName"),
        "hostNetwork": spec.get("hostNetwork"),
        "overhead": spec.get("overhead"),
        "containers": containers,
        "inits": inits,
        "gpu_mem": anno.get(stor.GPU_MEM_ANNO),
        "gpu_cnt": anno.get(stor.GPU_COUNT_ANNO),
        "owner_kind": (ctrl or {}).get("kind"),
    }
    return json.dumps(key, sort_keys=True, default=str)


def encode_cluster(oracle: Oracle) -> ClusterStatic:
    nodes = oracle.nodes
    n = len(nodes)
    alloc_mcpu = np.array([ns.alloc_milli_cpu() for ns in nodes], dtype=np.int64)
    alloc_mem = np.array([ns.alloc_int(req.MEMORY) for ns in nodes], dtype=np.int64)
    alloc_eph = np.array([ns.alloc_int(req.EPHEMERAL) for ns in nodes], dtype=np.int64)
    alloc_pods = np.array([ns.alloc_int(req.PODS) for ns in nodes], dtype=np.int64)

    simon_resources = sorted({name for ns in nodes for name in ns.alloc})
    simon_alloc = np.zeros((len(simon_resources), n), dtype=np.float64)
    for r_i, name in enumerate(simon_resources):
        for n_i, ns in enumerate(nodes):
            simon_alloc[r_i, n_i] = float(ns.alloc.get(name, Fraction(0)))

    scalar_names = sorted(
        {
            name
            for ns in nodes
            for name in ns.alloc
            if name not in (req.CPU, req.MEMORY, req.EPHEMERAL, req.PODS)
            and req.is_scalar_resource(name)
        }
    )
    scalar_alloc = np.zeros((len(scalar_names), n), dtype=np.int64)
    for s_i, name in enumerate(scalar_names):
        for n_i, ns in enumerate(nodes):
            scalar_alloc[s_i, n_i] = ns.alloc_int(name)

    gpu_count = np.array([ns.gpu.count if ns.gpu else 0 for ns in nodes], dtype=np.int64)
    gpu_per_dev = np.array(
        [ns.gpu.per_device_mem if ns.gpu else 0 for ns in nodes], dtype=np.int64
    )
    gpu_total = np.array(
        [stor.node_total_gpu_memory(ns.node) for ns in nodes], dtype=np.int64
    )
    g = int(gpu_count.max()) if n else 0

    # port vocab built later (needs the pod batch); placeholder
    return ClusterStatic(
        n=n,
        node_names=[ns.name for ns in nodes],
        alloc_mcpu=alloc_mcpu,
        alloc_mem=alloc_mem,
        alloc_eph=alloc_eph,
        alloc_pods=alloc_pods,
        simon_resources=simon_resources,
        simon_alloc=simon_alloc,
        scalar_names=scalar_names,
        scalar_alloc=scalar_alloc,
        g=g,
        gpu_count=gpu_count,
        gpu_per_dev=gpu_per_dev,
        gpu_total=gpu_total,
        port_vocab=[],
        port_conflict=np.zeros((0, 0), dtype=bool),
    )


def encode_dynamic(oracle: Oracle, cluster: ClusterStatic) -> DynamicState:
    nodes = oracle.nodes
    n = cluster.n
    s = len(cluster.scalar_names)
    pt = len(cluster.port_vocab)
    g = max(cluster.g, 1)
    st = DynamicState(
        used_mcpu=np.array([ns.req_mcpu for ns in nodes], dtype=np.int64),
        used_mem=np.array([ns.req_mem for ns in nodes], dtype=np.int64),
        used_eph=np.array([ns.req_eph for ns in nodes], dtype=np.int64),
        used_scalar=np.zeros((s, n), dtype=np.int64),
        nz_mcpu=np.array([ns.nz_mcpu for ns in nodes], dtype=np.int64),
        nz_mem=np.array([ns.nz_mem for ns in nodes], dtype=np.int64),
        pod_cnt=np.array([len(ns.pods) for ns in nodes], dtype=np.int64),
        ports_used=np.zeros((n, pt), dtype=bool),
        gpu_used=np.zeros((n, g), dtype=np.int64),
    )
    for s_i, name in enumerate(cluster.scalar_names):
        for n_i, ns in enumerate(nodes):
            st.used_scalar[s_i, n_i] = ns.req_scalar.get(name, 0)
    for n_i, ns in enumerate(nodes):
        for port in ns.used_ports:
            if port in cluster.port_vocab:
                st.ports_used[n_i, cluster.port_vocab.index(port)] = True
        if ns.gpu:
            for g_i, used in enumerate(ns.gpu.used):
                st.gpu_used[n_i, g_i] = used
    return st


def _ports_conflict_pair(a: tuple, b: tuple) -> bool:
    (aip, aproto, aport), (bip, bproto, bport) = a, b
    if aport != bport or aproto != bproto:
        return False
    return aip == "0.0.0.0" or bip == "0.0.0.0" or aip == bip


def encode_batch(oracle: Oracle, cluster: ClusterStatic, pods: List[dict]) -> PodBatch:
    """Build class-deduplicated static tensors for a pod batch.

    Raises EngineUnsupported for features the scan does not cover yet
    (inter-pod affinity, topology spread, open-local volumes) — both on
    incoming pods and on pods already in the cluster (whose terms would
    influence scoring of newcomers).
    """
    for pod in pods:
        if _has_pod_affinity(pod) or _has_spread(pod) or _has_local_storage(pod):
            raise EngineUnsupported("pod uses affinity/spread/local-storage")
    for ns in oracle.nodes:
        for pod in ns.pods:
            if _has_pod_affinity(pod):
                raise EngineUnsupported("existing pod has pod-affinity terms")

    # port vocabulary over batch + existing usage
    vocab: List[tuple] = []
    seen = set()
    for ns in oracle.nodes:
        for port in sorted(ns.used_ports):
            if port not in seen:
                seen.add(port)
                vocab.append(port)
    for pod in pods:
        for port in _pod_host_ports(pod):
            if port not in seen:
                seen.add(port)
                vocab.append(port)
    pt = len(vocab)
    conflict = np.zeros((pt, pt), dtype=bool)
    for i in range(pt):
        for j in range(pt):
            conflict[i, j] = _ports_conflict_pair(vocab[i], vocab[j])
    cluster.port_vocab = vocab
    cluster.port_conflict = conflict

    # class dedup
    class_ids: Dict[str, int] = {}
    class_pods: List[dict] = []
    class_of_pod = np.zeros(len(pods), dtype=np.int32)
    pinned = np.full(len(pods), -1, dtype=np.int32)
    for p_i, pod in enumerate(pods):
        key = _class_key(pod)
        if key not in class_ids:
            class_ids[key] = len(class_pods)
            class_pods.append(pod)
        class_of_pod[p_i] = class_ids[key]
        node_name = (pod.get("spec") or {}).get("nodeName")
        if node_name:
            pinned[p_i] = oracle.node_index.get(node_name, -1)

    u = len(class_pods)
    n = cluster.n
    s = len(cluster.scalar_names)

    req_mcpu = np.zeros(u, dtype=np.int64)
    req_mem = np.zeros(u, dtype=np.int64)
    req_eph = np.zeros(u, dtype=np.int64)
    req_scalar = np.zeros((u, s), dtype=np.int64)
    has_request = np.zeros(u, dtype=bool)
    nz_mcpu = np.zeros(u, dtype=np.int64)
    nz_mem = np.zeros(u, dtype=np.int64)
    gpu_mem = np.zeros(u, dtype=np.int64)
    gpu_cnt = np.zeros(u, dtype=np.int64)
    want_ports = np.zeros((u, pt), dtype=bool)
    conflict_ports = np.zeros((u, pt), dtype=bool)
    static_feasible = np.ones((u, n), dtype=bool)
    simon_raw = np.zeros((u, n), dtype=np.int64)
    nodeaff_raw = np.zeros((u, n), dtype=np.int64)
    taint_intol = np.zeros((u, n), dtype=np.int64)
    avoid_score = np.zeros((u, n), dtype=np.int64)
    image_score = np.zeros((u, n), dtype=np.int64)

    for u_i, pod in enumerate(class_pods):
        spec = pod.get("spec") or {}
        requests = req.pod_requests(pod)
        req_mcpu[u_i] = _ceil(requests.get(req.CPU, Fraction(0)) * 1000)
        req_mem[u_i] = _ceil(requests.get(req.MEMORY, Fraction(0)))
        req_eph[u_i] = _ceil(requests.get(req.EPHEMERAL, Fraction(0)))
        any_scalar = False
        for s_i, name in enumerate(cluster.scalar_names):
            if name in requests:
                req_scalar[u_i, s_i] = _ceil(requests[name])
                any_scalar = any_scalar or req_scalar[u_i, s_i] != 0
        # scalar request on a resource NO node advertises still blocks
        # scheduling via fitsRequest; treat as statically infeasible
        unknown_scalar = any(
            name not in (req.CPU, req.MEMORY, req.EPHEMERAL, req.PODS)
            and req.is_scalar_resource(name)
            and name not in cluster.scalar_names
            and _ceil(requests[name]) > 0
            for name in requests
        )
        has_request[u_i] = bool(
            req_mcpu[u_i] or req_mem[u_i] or req_eph[u_i] or any_scalar or unknown_scalar
        )
        nz_mcpu[u_i] = req.pod_nonzero_request(pod, req.CPU)
        nz_mem[u_i] = req.pod_nonzero_request(pod, req.MEMORY)
        g_mem, g_cnt = stor.pod_gpu_request(pod)
        gpu_mem[u_i] = g_mem
        gpu_cnt[u_i] = g_cnt
        for port in _pod_host_ports(pod):
            w_i = vocab.index(port)
            want_ports[u_i, w_i] = True
        conflict_ports[u_i] = (
            want_ports[u_i].astype(np.int32) @ conflict.astype(np.int32)
        ) > 0

        tolerations = spec.get("tolerations") or []
        unsched_tolerated = lbl.tolerations_tolerate_taint(
            tolerations,
            {"key": "node.kubernetes.io/unschedulable", "effect": "NoSchedule"},
        )
        simon_req = {name: float(requests.get(name, Fraction(0))) for name in cluster.simon_resources}
        simon_empty = not requests and not req.pod_limits(pod)

        for n_i, ns in enumerate(oracle.nodes):
            node = ns.node
            nspec = node.get("spec") or {}
            ok = True
            if nspec.get("unschedulable") and not unsched_tolerated:
                ok = False
            if ok and unknown_scalar:
                ok = False
            if ok and lbl.find_untolerated_taint(nspec.get("taints") or [], tolerations):
                ok = False
            if ok and not lbl.pod_matches_node_selector_and_affinity(spec, node):
                ok = False
            static_feasible[u_i, n_i] = ok
            nodeaff_raw[u_i, n_i] = lbl.preferred_node_affinity_score(spec, node)
            taint_intol[u_i, n_i] = lbl.count_intolerable_prefer_no_schedule(
                nspec.get("taints") or [], tolerations
            )
            # Simon raw share (static: pod annotations never enter podReq)
            if simon_empty:
                simon_raw[u_i, n_i] = MAX_NODE_SCORE
            else:
                res = 0.0
                for r_i, name in enumerate(cluster.simon_resources):
                    pr = simon_req[name]
                    avail = cluster.simon_alloc[r_i, n_i] - pr
                    share = (0.0 if pr == 0 else 1.0) if avail == 0 else pr / avail
                    res = max(res, share)
                simon_raw[u_i, n_i] = int(MAX_NODE_SCORE * res)
        avoid_score[u_i] = _avoid_scores(pod, oracle)
        image_score[u_i] = _image_scores(pod, oracle)

    return PodBatch(
        p=len(pods),
        u=u,
        class_of_pod=class_of_pod,
        pinned_node=pinned,
        req_mcpu=req_mcpu,
        req_mem=req_mem,
        req_eph=req_eph,
        req_scalar=req_scalar,
        has_request=has_request,
        nz_mcpu=nz_mcpu,
        nz_mem=nz_mem,
        gpu_mem=gpu_mem,
        gpu_cnt=gpu_cnt,
        want_ports=want_ports,
        conflict_ports=conflict_ports,
        static_feasible=static_feasible,
        simon_raw=simon_raw,
        nodeaff_raw=nodeaff_raw,
        taint_intol=taint_intol,
        avoid_score=avoid_score,
        image_score=image_score,
    )


def _avoid_scores(pod: dict, oracle: Oracle) -> np.ndarray:
    out = np.zeros(len(oracle.nodes), dtype=np.int64)
    scores = Oracle._score_prefer_avoid_pods(oracle, pod, oracle.nodes)
    out[:] = scores
    return out


def _image_scores(pod: dict, oracle: Oracle) -> np.ndarray:
    out = np.zeros(len(oracle.nodes), dtype=np.int64)
    scores = Oracle._score_image_locality(oracle, pod, oracle.nodes)
    out[:] = scores
    return out
