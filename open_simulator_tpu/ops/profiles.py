"""Node-profile deduplication for host-side encoding.

The reference evaluates every (pod, node) pair on 16 goroutines
(vendor/.../parallelize/parallelism.go). Here the host encode collapses
both axes: pods dedup into classes (ops/encode.py) and nodes dedup into
*profiles* — the tuple of node attributes the batch's static encodings
actually read (referenced labels, taints, unschedulable, preferAvoid
annotation, images). All label/taint feasibility and static scoring run
once per (class, profile) and scatter back to [U, N].

Pod classes whose node affinity uses matchFields read node *names*,
which profiles exclude — those classes fall back to per-node work
(daemonset pods pin via matchFields, models/workloads.py).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

__all__ = [
    "freeze",
    "referenced_label_keys",
    "node_profile_key",
    "node_profiles",
    "uses_match_fields",
]


def freeze(obj):
    """Recursively convert YAML-shaped data into a hashable tuple tree
    (dicts sorted by key). ~4x faster than json.dumps for dedup keys."""
    if isinstance(obj, dict):
        return tuple(sorted((k, freeze(v)) for k, v in obj.items()))
    if isinstance(obj, (list, tuple)):
        return tuple(freeze(v) for v in obj)
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return str(obj)


def referenced_label_keys(class_pods: List[dict]):
    """(value_keys, presence_keys): label keys whose values the batch's
    selectors/affinity expressions read, and keys where only *presence*
    matters (spread topology keys — their values feed the term tables
    per node directly, never through profiles). Restricting node
    profiles to these lets nodes that differ only in unreferenced
    labels (e.g. the per-node hostname label) share a profile."""
    value_keys = set()
    presence_keys = set()
    for pod in class_pods:
        spec = pod.get("spec") or {}
        value_keys.update((spec.get("nodeSelector") or {}).keys())
        aff = spec.get("affinity") or {}
        node_aff = aff.get("nodeAffinity") or {}
        req = node_aff.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
        terms = list(req.get("nodeSelectorTerms") or [])
        for wt in node_aff.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
            terms.append(wt.get("preference") or {})
        for term in terms:
            for e in term.get("matchExpressions") or []:
                value_keys.add(e.get("key"))
        for c in spec.get("topologySpreadConstraints") or []:
            presence_keys.add(c.get("topologyKey", ""))
    return frozenset(value_keys), frozenset(presence_keys - value_keys)


def node_profile_key(node: dict, value_keys: frozenset, presence_keys: frozenset):
    """Everything the per-class static encodings read from a node except
    its name."""
    meta = node.get("metadata") or {}
    spec = node.get("spec") or {}
    status = node.get("status") or {}
    labels = meta.get("labels") or {}
    return freeze(
        [
            {k: labels[k] for k in value_keys if k in labels},
            sorted(k for k in presence_keys if k in labels),
            spec.get("taints"),
            bool(spec.get("unschedulable")),
            (meta.get("annotations") or {}).get(
                "scheduler.alpha.kubernetes.io/preferAvoidPods"
            ),
            status.get("images"),
        ]
    )


# cross-run profile cache: planners and benches re-encode batches over
# the SAME decoded node dicts, and the profile partition is a pure
# function of (node content, referenced label keysets). Keyed on the
# ORACLE'S SOURCE node identities (strong refs held in the entry — the
# utils/memo.py proof-of-identity contract; oracle clones copy label /
# taint content verbatim and nothing mutates them mid-run: chaos
# perturbations deepcopy before editing) plus the keysets by value.
_PROFILE_CACHE: dict = {}
_PROFILE_CACHE_MAX = 128


def _register_profile_cache():
    from ..utils.memo import register_cache

    register_cache(_PROFILE_CACHE.clear)


_register_profile_cache()


def node_profiles_cached(nodes, class_pods, cache_sources=None):
    """node_profiles with a cross-run cache keyed on `cache_sources`
    (the oracle's pre-clone node dicts, scheduler/oracle.py
    `source_nodes`); falls back to a fresh computation when no source
    identity is available."""
    if cache_sources is None or len(cache_sources) != len(nodes):
        return node_profiles(nodes, class_pods)
    value_keys, presence_keys = referenced_label_keys(class_pods)
    key = (tuple(map(id, cache_sources)), value_keys, presence_keys)
    hit = _PROFILE_CACHE.get(key)
    if hit is not None:
        return hit[1]
    result = node_profiles(nodes, class_pods, _keys=(value_keys, presence_keys))
    if len(_PROFILE_CACHE) >= _PROFILE_CACHE_MAX:
        _PROFILE_CACHE.clear()
    # hold the sources so their ids stay live (key hit == identity)
    _PROFILE_CACHE[key] = (list(cache_sources), result)
    return result


def node_profiles(nodes: List[dict], class_pods: List[dict], _keys=None):
    """Returns (node_class_of[N] i32, rep_idx[NC] node indices)."""
    value_keys, presence_keys = (
        _keys if _keys is not None else referenced_label_keys(class_pods)
    )
    prof_ids: Dict[object, int] = {}
    n = len(nodes)
    node_class_of = np.empty(n, dtype=np.int32)
    rep_idx: List[int] = []
    for n_i, node in enumerate(nodes):
        key = node_profile_key(node, value_keys, presence_keys)
        cid = prof_ids.get(key)
        if cid is None:
            cid = len(rep_idx)
            prof_ids[key] = cid
            rep_idx.append(n_i)
        node_class_of[n_i] = cid
    return node_class_of, np.asarray(rep_idx, dtype=np.int64)


def uses_match_fields(spec: dict) -> bool:
    """matchFields terms read node names, which the node-profile dedup
    deliberately excludes."""
    aff = (spec.get("affinity") or {}).get("nodeAffinity") or {}
    req = aff.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    for term in req.get("nodeSelectorTerms") or []:
        if term.get("matchFields"):
            return True
    for wt in aff.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
        if (wt.get("preference") or {}).get("matchFields"):
            return True
    return False
