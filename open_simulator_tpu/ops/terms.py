"""Term tables: inter-pod affinity and topology-spread state encoding.

The order-dependent plugins carry state that previous placements feed:

- InterPodAffinity (vendor/.../interpodaffinity/filtering.go:241-430,
  scoring.go:47-270): required (anti)affinity of the incoming pod,
  required anti-affinity of existing pods, and four kinds of preferred
  contributions.
- PodTopologySpread (vendor/.../podtopologyspread/filtering.go:197-337,
  scoring.go:60-270): per-topology-domain match counts with min-count
  skew checks and log-weighted scoring.

All of them reduce to counts over (term row, topology value) where a
"term row" is a deduplicated (label selector, namespace set, topology
key) triple. This module builds the tables in VALUE space `[T, V]`
(natural for the host-side init accounting); the scan carries them in
NODE space `[T, N]` — count at each node's own value, converted in
encode.to_scan_state — so per-step reads are row indexing and commits
are masked broadcasts (value-space gathers/scatters lower to
per-element ops on TPU and dominated the step cost).

Topology-value space: per-key vocab over node labels; rows whose key is
kubernetes.io/hostname use the node index itself as the value id, so V
= max(non-hostname vocab, N) when hostname terms exist.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ..models import labels as lbl

HOSTNAME_KEY = "kubernetes.io/hostname"
HARD_POD_AFFINITY_WEIGHT = 1


def combined_pref_init(tables: "TermTables"):
    """Init for the combined own-affinity state (one array holds
    HARD_POD_AFFINITY_WEIGHT x required + preferred weights — their
    only reader sums them, scoring.go processExistingPod). Single
    definition keeps the XLA and Pallas paths in lockstep."""
    return (
        HARD_POD_AFFINITY_WEIGHT * tables.init_own_aff_req
        + tables.init_own_aff_pref_w
    )


def combined_pref_carry(tables: "TermTables"):
    """Per-(row, class) commit increment for the combined state."""
    return (
        HARD_POD_AFFINITY_WEIGHT * tables.carry_aff_req
        + tables.carry_aff_pref_w
    )


def _selector_key(selector) -> str:
    return json.dumps(selector, sort_keys=True, default=str)


@dataclass
class _Row:
    selector: Optional[dict]
    namespaces: frozenset
    topo_key: str

    def matches_pod(self, pod: dict) -> bool:
        meta = pod.get("metadata") or {}
        ns = meta.get("namespace") or "default"
        if ns not in self.namespaces:
            return False
        return lbl.match_labels_selector(self.selector, meta.get("labels") or {})


@dataclass
class TermTables:
    t: int  # term rows
    v: int  # topology-value space
    a: int  # required-affinity group rows
    ch: int  # hard spread constraint instances
    cs: int  # soft spread constraint instances
    rmax: int  # max relevant rows per class
    gmax: int  # max group rows per class
    hmax: int  # max hard spread rows per class
    smax: int  # max soft spread rows per class

    topo_val: np.ndarray  # [T, N] i32 (-1 = key missing)
    # per-class statics
    match: np.ndarray  # [T, U] bool
    carry_anti_req: np.ndarray  # [T, U] i64
    carry_aff_req: np.ndarray  # [T, U] i64
    carry_aff_pref_w: np.ndarray  # [T, U] i64
    carry_anti_pref_w: np.ndarray  # [T, U] i64
    cls_rows: np.ndarray  # [U, Rmax] i32 (-1 pad): rows relevant to class
    # required-affinity groups
    group_rows: np.ndarray  # [A] i32 -> term row
    group_of_row: np.ndarray  # [A] i32 -> group id
    match_all: np.ndarray  # [Gn, U] bool: class matches ALL terms of group
    cls_group_rows: np.ndarray  # [U, Gmax] i32 (-1 pad): A-rows of class's group
    cls_group_id: np.ndarray  # [U] i32 (-1 = no required affinity)
    # hard topology spread
    h_row: np.ndarray  # [Ch] i32 -> term row (selector counts)
    h_self: np.ndarray  # [Ch, U] bool (pod matches own constraint selector)
    h_max_skew: np.ndarray  # [Ch] i64
    h_cand_nodes: np.ndarray  # [Ch, N] bool (candidate nodes; values derive in-step)
    cls_h_rows: np.ndarray  # [U, Hmax] i32 (-1 pad)
    # soft topology spread
    s_row: np.ndarray  # [Cs] i32 -> term row
    s_is_host: np.ndarray  # [Cs] bool
    s_max_skew: np.ndarray  # [Cs] i64
    s_q: np.ndarray  # [Cs, N] bool (qualifying nodes for counting)
    cls_s_rows: np.ndarray  # [U, Smax] i32 (-1 pad)
    cls_s_haskeys: np.ndarray  # [U, N] bool (node has ALL soft keys of class)
    # initial counts (existing cluster pods)
    init_tgt: np.ndarray  # [T, V]
    init_own_anti_req: np.ndarray  # [T, V]
    init_own_aff_req: np.ndarray  # [T, V]
    init_own_aff_pref_w: np.ndarray  # [T, V]
    init_own_anti_pref_w: np.ndarray  # [T, V]
    init_group_counts: np.ndarray  # [A, V]
    init_soft_counts: np.ndarray  # [Cs, V]


class _TableBuilder:
    def __init__(self, nodes: List[dict]):
        self.nodes = nodes
        self.rows: List[_Row] = []
        self.row_ids: Dict[str, int] = {}
        self.key_vocab: Dict[str, Dict[str, int]] = {}
        self.has_hostname = False

    def row(self, selector, namespaces: frozenset, topo_key: str) -> int:
        key = f"{_selector_key(selector)}|{sorted(namespaces)}|{topo_key}"
        if key not in self.row_ids:
            self.row_ids[key] = len(self.rows)
            self.rows.append(_Row(selector, namespaces, topo_key))
            if topo_key == HOSTNAME_KEY:
                self.has_hostname = True
        return self.row_ids[key]

    def value_id(self, topo_key: str, value: str, node_idx: int) -> int:
        if topo_key == HOSTNAME_KEY:
            return node_idx
        vocab = self.key_vocab.setdefault(topo_key, {})
        if value not in vocab:
            vocab[value] = len(vocab)
        return vocab[value]


def _pod_terms(pod: dict):
    """All four term categories of a pod, as resolved AffinityTerms."""
    return (
        lbl.resolve_affinity_terms(
            pod, "podAffinity", "requiredDuringSchedulingIgnoredDuringExecution"
        ),
        lbl.resolve_affinity_terms(
            pod, "podAntiAffinity", "requiredDuringSchedulingIgnoredDuringExecution"
        ),
        lbl.resolve_affinity_terms(
            pod, "podAffinity", "preferredDuringSchedulingIgnoredDuringExecution"
        ),
        lbl.resolve_affinity_terms(
            pod, "podAntiAffinity", "preferredDuringSchedulingIgnoredDuringExecution"
        ),
    )


def _spread_constraints(pod: dict, mode: str) -> list:
    out = []
    ns = (pod.get("metadata") or {}).get("namespace") or "default"
    for c in (pod.get("spec") or {}).get("topologySpreadConstraints") or []:
        when = c.get("whenUnsatisfiable", "DoNotSchedule")
        if when != mode:
            continue
        out.append(
            {
                "selector": c.get("labelSelector"),
                "ns": frozenset([ns]),
                "key": c.get("topologyKey", ""),
                "max_skew": int(c.get("maxSkew", 1)),
            }
        )
    return out


def build_term_tables(oracle, class_pods: List[dict], profiles=None) -> TermTables:
    """Construct the tables from the batch classes + existing pods.

    class_pods: one representative pod dict per class.
    profiles: optional (node_class_of, rep_idx) from ops/profiles.py,
    to share the node-profile dedup with encode_batch.
    """
    nodes = [ns.node for ns in oracle.nodes]
    n = len(nodes)
    u = len(class_pods)
    b = _TableBuilder(nodes)

    # -- discover rows from batch classes and existing pods ---------------
    cls_terms = [_pod_terms(p) for p in class_pods]
    existing_pods = [(p, ns.index) for ns in oracle.nodes for p in ns.pods]
    ex_terms = [_pod_terms(p) for p, _ in existing_pods]

    def rows_for(terms) -> List[List[int]]:
        return [[b.row(t.selector, t.namespaces, t.topology_key) for t in cat] for cat in terms]

    cls_term_rows = [rows_for(terms) for terms in cls_terms]
    ex_term_rows = [rows_for(terms) for terms in ex_terms]

    cls_hard = [_spread_constraints(p, "DoNotSchedule") for p in class_pods]
    cls_soft = [_spread_constraints(p, "ScheduleAnyway") for p in class_pods]
    for cs in cls_hard + cls_soft:
        for c in cs:
            c["row"] = b.row(c["selector"], c["ns"], c["key"])

    # -- topology values ---------------------------------------------------
    # one pass per distinct topology key (not per row): nodes are read
    # once per key, rows sharing the key share the value column
    node_labels = [((node.get("metadata") or {}).get("labels") or {}) for node in nodes]
    key_vals: Dict[str, np.ndarray] = {}
    for row in b.rows:
        if row.topo_key in key_vals:
            continue
        vals = np.full(n, -1, dtype=np.int32)
        for n_i, labels in enumerate(node_labels):
            if row.topo_key in labels:
                vals[n_i] = b.value_id(row.topo_key, labels[row.topo_key], n_i)
        key_vals[row.topo_key] = vals
    t = max(len(b.rows), 1)
    v_vocab = max((len(vv) for vv in b.key_vocab.values()), default=0)
    v = max(v_vocab, n if b.has_hostname else 0, 1)

    topo_val = np.full((t, n), -1, dtype=np.int32)
    for t_i, row in enumerate(b.rows):
        topo_val[t_i] = key_vals[row.topo_key]

    # -- per-class match/carry --------------------------------------------
    match = np.zeros((t, u), dtype=bool)
    carry_anti_req = np.zeros((t, u), dtype=np.int64)
    carry_aff_req = np.zeros((t, u), dtype=np.int64)
    carry_aff_pref_w = np.zeros((t, u), dtype=np.int64)
    carry_anti_pref_w = np.zeros((t, u), dtype=np.int64)
    for u_i, pod in enumerate(class_pods):
        for t_i, row in enumerate(b.rows):
            match[t_i, u_i] = row.matches_pod(pod)
        aff_req, anti_req, aff_pref, anti_pref = cls_terms[u_i]
        r_aff, r_anti, r_paff, r_panti = cls_term_rows[u_i]
        for term, r in zip(aff_req, r_aff):
            carry_aff_req[r, u_i] += 1
        for term, r in zip(anti_req, r_anti):
            carry_anti_req[r, u_i] += 1
        for term, r in zip(aff_pref, r_paff):
            carry_aff_pref_w[r, u_i] += term.weight
        for term, r in zip(anti_pref, r_panti):
            carry_anti_pref_w[r, u_i] += term.weight

    # relevant rows per class: any carried term or any selector match
    relevant = (
        match
        | (carry_anti_req > 0)
        | (carry_aff_req > 0)
        | (carry_aff_pref_w != 0)
        | (carry_anti_pref_w != 0)
    )
    rmax = max(int(relevant.sum(axis=0).max()) if u else 0, 1)
    cls_rows = np.full((u, rmax), -1, dtype=np.int32)
    for u_i in range(u):
        idx = np.nonzero(relevant[:, u_i])[0]
        cls_rows[u_i, : len(idx)] = idx

    # -- required-affinity groups -----------------------------------------
    group_keys: Dict[tuple, int] = {}
    group_rows_list: List[int] = []
    group_of_row_list: List[int] = []
    cls_group_id = np.full(u, -1, dtype=np.int32)
    groups_terms: List[list] = []
    for u_i, pod in enumerate(class_pods):
        aff_req = cls_terms[u_i][0]
        if not aff_req:
            continue
        gk = tuple(sorted(cls_term_rows[u_i][0]))
        if gk not in group_keys:
            group_keys[gk] = len(group_keys)
            groups_terms.append(aff_req)
            for r in cls_term_rows[u_i][0]:
                group_rows_list.append(r)
                group_of_row_list.append(group_keys[gk])
        cls_group_id[u_i] = group_keys[gk]
    gn = max(len(group_keys), 1)
    a = max(len(group_rows_list), 1)
    group_rows = np.zeros(a, dtype=np.int32)
    group_of_row = np.zeros(a, dtype=np.int32)
    for i, (r, g) in enumerate(zip(group_rows_list, group_of_row_list)):
        group_rows[i] = r
        group_of_row[i] = g
    match_all = np.zeros((gn, u), dtype=bool)
    for gk, g_i in group_keys.items():
        terms = groups_terms[g_i]
        for u_i, pod in enumerate(class_pods):
            match_all[g_i, u_i] = all(term.matches_pod(pod) for term in terms)
    gmax = max((int((group_of_row == g).sum()) for g in range(gn)), default=1)
    gmax = max(gmax, 1)
    cls_group_rows = np.full((u, gmax), -1, dtype=np.int32)
    for u_i in range(u):
        g = cls_group_id[u_i]
        if g < 0:
            continue
        idx = np.nonzero(group_of_row == g)[0]
        cls_group_rows[u_i, : len(idx)] = idx

    # -- per-class node masks (profile-deduplicated) ----------------------
    # selector/affinity match and topo-key presence run once per node
    # profile and scatter to [N] (ops/profiles.py)
    from .profiles import node_profiles, uses_match_fields

    if profiles is not None:
        prof_of, prof_reps = profiles
    else:
        prof_of, prof_reps = node_profiles(nodes, class_pods)
    _match_cache: Dict[int, np.ndarray] = {}

    def _sel_match_mask(u_i: int) -> np.ndarray:
        """bool[N]: nodes passing the class's nodeSelector + required
        node affinity (filtering.go:231-247 candidate filtering)."""
        m = _match_cache.get(u_i)
        if m is not None:
            return m
        spec = class_pods[u_i].get("spec") or {}
        if uses_match_fields(spec):
            m = np.fromiter(
                (lbl.pod_matches_node_selector_and_affinity(spec, node) for node in nodes),
                bool,
                n,
            )
        else:
            ok = np.fromiter(
                (
                    lbl.pod_matches_node_selector_and_affinity(spec, nodes[int(r)])
                    for r in prof_reps
                ),
                bool,
                len(prof_reps),
            )
            m = ok[prof_of]
        _match_cache[u_i] = m
        return m

    def _haskeys_mask(constraints: list) -> np.ndarray:
        """bool[N]: node has every constraint's topology key."""
        keys = [c["key"] for c in constraints]
        ok = np.fromiter(
            (all(k in node_labels[int(r)] for k in keys) for r in prof_reps),
            bool,
            len(prof_reps),
        )
        return ok[prof_of]

    # -- hard spread constraint instances ---------------------------------
    h_entries: Dict[tuple, int] = {}
    h_list: List[dict] = []
    cls_h: List[List[int]] = [[] for _ in range(u)]
    for u_i, constraints in enumerate(cls_hard):
        if not constraints:
            continue
        # candidate nodes: pass pod's nodeSelector/affinity AND have
        # every constraint key (filtering.go:231-247)
        cand_mask = _sel_match_mask(u_i) & _haskeys_mask(constraints)
        cand_nodes = np.nonzero(cand_mask)[0]
        for c in constraints:
            key = (
                c["row"],
                c["max_skew"],
                cand_mask.tobytes(),
                _selector_key(c["selector"]),
            )
            if key not in h_entries:
                h_entries[key] = len(h_list)
                h_list.append({**c, "cand_nodes": cand_nodes})
            cls_h[u_i].append(h_entries[key])
    ch = max(len(h_list), 1)
    h_row = np.zeros(ch, dtype=np.int32)
    h_max_skew = np.ones(ch, dtype=np.int64)
    h_cand_nodes = np.zeros((ch, n), dtype=bool)
    h_self = np.zeros((ch, u), dtype=bool)
    for c_i, c in enumerate(h_list):
        h_row[c_i] = c["row"]
        h_max_skew[c_i] = c["max_skew"]
        for n_i in c["cand_nodes"]:
            h_cand_nodes[c_i, n_i] = True
        row = b.rows[c["row"]]
        for u_i, pod in enumerate(class_pods):
            h_self[c_i, u_i] = row.matches_pod(pod)
    hmax = max((len(x) for x in cls_h), default=1)
    hmax = max(hmax, 1)
    cls_h_rows = np.full((u, hmax), -1, dtype=np.int32)
    for u_i, lst in enumerate(cls_h):
        cls_h_rows[u_i, : len(lst)] = lst

    # -- soft spread constraint instances ---------------------------------
    s_entries: Dict[tuple, int] = {}
    s_list: List[dict] = []
    cls_s: List[List[int]] = [[] for _ in range(u)]
    cls_s_haskeys = np.ones((u, n), dtype=bool)
    for u_i, constraints in enumerate(cls_soft):
        if not constraints:
            continue
        # qualifying nodes for counting (scoring.go processAllNode):
        # nodeSelector/affinity AND all soft keys present
        haskeys = _haskeys_mask(constraints)
        cls_s_haskeys[u_i] = haskeys
        q = haskeys & _sel_match_mask(u_i)
        for c in constraints:
            key = (c["row"], c["max_skew"], q.tobytes())
            if key not in s_entries:
                s_entries[key] = len(s_list)
                s_list.append({**c, "q": q.copy()})
            cls_s[u_i].append(s_entries[key])
    cs = max(len(s_list), 1)
    s_row = np.zeros(cs, dtype=np.int32)
    s_is_host = np.zeros(cs, dtype=bool)
    s_max_skew = np.ones(cs, dtype=np.int64)
    s_q = np.zeros((cs, n), dtype=bool)
    for c_i, c in enumerate(s_list):
        s_row[c_i] = c["row"]
        s_is_host[c_i] = c["key"] == HOSTNAME_KEY
        s_max_skew[c_i] = c["max_skew"]
        s_q[c_i] = c["q"]
    smax = max((len(x) for x in cls_s), default=1)
    smax = max(smax, 1)
    cls_s_rows = np.full((u, smax), -1, dtype=np.int32)
    for u_i, lst in enumerate(cls_s):
        cls_s_rows[u_i, : len(lst)] = lst

    # -- initial counts from existing pods --------------------------------
    init_tgt = np.zeros((t, v), dtype=np.int64)
    init_own_anti_req = np.zeros((t, v), dtype=np.int64)
    init_own_aff_req = np.zeros((t, v), dtype=np.int64)
    init_own_aff_pref_w = np.zeros((t, v), dtype=np.int64)
    init_own_anti_pref_w = np.zeros((t, v), dtype=np.int64)
    init_group_counts = np.zeros((a, v), dtype=np.int64)
    init_soft_counts = np.zeros((cs, v), dtype=np.int64)
    for (pod, n_i), terms, term_rows in zip(existing_pods, ex_terms, ex_term_rows):
        for t_i, row in enumerate(b.rows):
            if row.matches_pod(pod):
                val = topo_val[t_i, n_i]
                if val >= 0:
                    init_tgt[t_i, val] += 1
        aff_req, anti_req, aff_pref, anti_pref = terms
        r_aff, r_anti, r_paff, r_panti = term_rows
        for term, r in zip(aff_req, r_aff):
            val = topo_val[r, n_i]
            if val >= 0:
                init_own_aff_req[r, val] += 1
        for term, r in zip(anti_req, r_anti):
            val = topo_val[r, n_i]
            if val >= 0:
                init_own_anti_req[r, val] += 1
        for term, r in zip(aff_pref, r_paff):
            val = topo_val[r, n_i]
            if val >= 0:
                init_own_aff_pref_w[r, val] += term.weight
        for term, r in zip(anti_pref, r_panti):
            val = topo_val[r, n_i]
            if val >= 0:
                init_own_anti_pref_w[r, val] += term.weight
        for a_i in range(len(group_rows_list)):
            g_i = group_of_row_list[a_i]
            # group counting: pod must match ALL terms of the group
            if all(term.matches_pod(pod) for term in groups_terms[g_i]):
                r = group_rows_list[a_i]
                val = topo_val[r, n_i]
                if val >= 0:
                    init_group_counts[a_i, val] += 1
        for c_i, c in enumerate(s_list):
            if c["q"][n_i]:
                row = b.rows[c["row"]]
                if row.matches_pod(pod):
                    val = topo_val[c["row"], n_i]
                    if val >= 0:
                        init_soft_counts[c_i, val] += 1

    return TermTables(
        t=t,
        v=v,
        a=a,
        ch=ch,
        cs=cs,
        rmax=rmax,
        gmax=gmax,
        hmax=hmax,
        smax=smax,
        topo_val=topo_val,
        match=match,
        carry_anti_req=carry_anti_req,
        carry_aff_req=carry_aff_req,
        carry_aff_pref_w=carry_aff_pref_w,
        carry_anti_pref_w=carry_anti_pref_w,
        cls_rows=cls_rows,
        group_rows=group_rows,
        group_of_row=group_of_row,
        match_all=match_all,
        cls_group_rows=cls_group_rows,
        cls_group_id=cls_group_id,
        h_row=h_row,
        h_self=h_self,
        h_max_skew=h_max_skew,
        h_cand_nodes=h_cand_nodes,
        cls_h_rows=cls_h_rows,
        s_row=s_row,
        s_is_host=s_is_host,
        s_max_skew=s_max_skew,
        s_q=s_q,
        cls_s_rows=cls_s_rows,
        cls_s_haskeys=cls_s_haskeys,
        init_tgt=init_tgt,
        init_own_anti_req=init_own_anti_req,
        init_own_aff_req=init_own_aff_req,
        init_own_aff_pref_w=init_own_aff_pref_w,
        init_own_anti_pref_w=init_own_anti_pref_w,
        init_group_counts=init_group_counts,
        init_soft_counts=init_soft_counts,
    )
