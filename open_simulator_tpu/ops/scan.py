"""Sequential-commit scheduling scan.

The serial one-pod-at-a-time semantics of the reference
(pkg/simulator/simulator.go:218-243: create pod -> block until the
scheduler round-trips -> next pod) become a `jax.lax.scan` over the pod
queue. Each step is the whole scheduling cycle of
generic_scheduler.Schedule (core/generic_scheduler.go:131-180) fused
over the node axis:

  filter  = static_feasible  & NodeResourcesFit & NodePorts & GPU fit
  score   = Balanced + Least + ImageLocality + NodeAffinity'
            + PreferAvoid*10000 + TopologySpread' * 2 + TaintToleration'
            + Simon' + GpuShare' + OpenLocal'     (' = normalized)
  select  = first-index argmax over feasible nodes
  commit  = rank-1 state update (requested vectors, pod count, ports,
            per-device GPU memory)

All integer arithmetic is int64 to bit-match the serial oracle.
selectHost tie-break is pinned to the first maximum in node order (the
reference reservoir-samples, generic_scheduler.go:186-209 — documented
deviation shared with the oracle).

Pinned pods (spec.nodeName already set) flow through the same scan as
forced placements so that interleavings of pinned and loose pods see
the same intermediate states as the serial path.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..obs import profile as _obs_profile
from ..scheduler.schedconfig import DEFAULT_SCORE_WEIGHTS as _DEFAULT_WEIGHTS

MAX_SCORE = 100


class ScanFeatures(NamedTuple):
    """Which optional subsystems the current batch actually exercises.

    Passed as a static (hashable) jit argument so XLA compiles a scan
    specialized to the batch: a batch with no GPU pods carries no GPU
    allocator in its step, a batch with no affinity terms carries no
    gather/scatter machinery, etc. Every gate is exactness-preserving —
    the disabled block's contribution is the identity (all-feasible /
    zero score) whenever the feature is unused, so placements are
    bit-identical to the ungated scan.
    """

    gpu: bool
    storage: bool
    ipa: bool  # inter-pod (anti-)affinity filters + score
    hard_spread: bool  # required topologySpreadConstraints
    soft_spread: bool  # ScheduleAnyway topologySpreadConstraints
    ports: bool
    scalars: bool  # extended scalar resources
    custom: bool  # out-of-tree plugin scores
    pins: bool  # any pod arrives with spec.nodeName
    # ((mode, weight), ...) per custom plugin, so each unrolled plugin
    # emits only its one normalization; None = modes unknown at trace
    # time, select among all three with jnp.where
    custom_spec: tuple = None
    # in-tree + simulator score-plugin weights from an optional
    # KubeSchedulerConfiguration (scheduler/schedconfig.py). Static, so
    # XLA constant-folds zero-weight plugins out of the step entirely;
    # None = the default profile weights.
    weights: tuple = None
    # selectHost="sample": reservoir sampling over score ties with the
    # Go math/rand stream carried in the scan state (_sample_select);
    # requires init.rng_hist (the GoRand 607-output history)
    sample: bool = False

    @property
    def terms(self) -> bool:
        """Whether per-topology target counts (state.tgt) are live."""
        return self.ipa or self.hard_spread or self.soft_spread


ALL_FEATURES = ScanFeatures(*([True] * 9))


# trace-safe by explicit guard: the tracer isinstance check below
# bails to the pure ALL_FEATURES value before any np.asarray runs on
# a traced input, so the host reads only ever see concrete arrays
def features_of(static: "ScanStatic", pinned_node, weights=None,
                sample: bool = False) -> ScanFeatures:  # simonlint: disable=JAX001
    """Derive the feature set host-side.

    Inputs are normally concrete arrays; when called from inside a
    jit/vmap trace (an external caller wrapping run_scan in its own
    jit), falls back to ALL_FEATURES — the ungated scan, slower but
    placement-identical.
    """
    import numpy as np

    import jax

    if any(
        isinstance(x, jax.core.Tracer)
        for x in (static.gpu_mem, static.wants_storage, pinned_node)
    ):
        return ALL_FEATURES._replace(weights=weights, sample=sample)

    a = np.asarray
    return ScanFeatures(
        sample=sample,
        weights=weights,
        gpu=bool(a(static.gpu_mem).max(initial=0) > 0),
        storage=bool(a(static.wants_storage).any()),
        ipa=bool(
            (a(static.cls_rows) >= 0).any() or (a(static.cls_group_id) >= 0).any()
        ),
        hard_spread=bool((a(static.cls_h_rows) >= 0).any()),
        soft_spread=bool((a(static.cls_s_rows) >= 0).any()),
        ports=bool(a(static.want_ports).any()),
        scalars=static.scalar_alloc.shape[0] > 0,
        custom=bool((a(static.custom_weight) != 0).any()),
        pins=bool((a(pinned_node) >= 0).any()),
        custom_spec=tuple(
            zip(
                (int(m) for m in a(static.custom_mode)),
                (int(w) for w in a(static.custom_weight)),
            )
        ),
    )


class ScanStatic(NamedTuple):
    """Arrays closed over by the compiled scan (static per batch)."""

    alloc_mcpu: jnp.ndarray  # [N]
    alloc_mem: jnp.ndarray
    alloc_eph: jnp.ndarray
    alloc_pods: jnp.ndarray
    scalar_alloc: jnp.ndarray  # [S, N]
    gpu_per_dev: jnp.ndarray  # [N]
    gpu_total: jnp.ndarray  # [N]
    gpu_count: jnp.ndarray  # [N]
    dev_valid: jnp.ndarray  # [N, G] bool (device exists on node)
    # open-local storage
    vg_cap: jnp.ndarray  # [N, V]
    vg_valid: jnp.ndarray  # [N, V]
    has_storage: jnp.ndarray  # [N] bool
    ssd_cap: jnp.ndarray  # [N, Ds] ascending
    ssd_valid: jnp.ndarray  # [N, Ds]
    hdd_cap: jnp.ndarray  # [N, Dh] ascending
    hdd_valid: jnp.ndarray  # [N, Dh]
    # per-class static matrices
    static_feasible: jnp.ndarray  # [U, N]
    simon_raw: jnp.ndarray  # [U, N]
    nodeaff_raw: jnp.ndarray  # [U, N]
    taint_intol: jnp.ndarray  # [U, N]
    avoid_score: jnp.ndarray  # [U, N]
    image_score: jnp.ndarray  # [U, N]
    # per-class request vectors
    req_mcpu: jnp.ndarray  # [U]
    req_mem: jnp.ndarray
    req_eph: jnp.ndarray
    req_scalar: jnp.ndarray  # [U, S]
    has_request: jnp.ndarray  # [U] bool
    nz_mcpu: jnp.ndarray
    nz_mem: jnp.ndarray
    gpu_mem: jnp.ndarray  # [U]
    gpu_cnt: jnp.ndarray  # [U]
    want_ports: jnp.ndarray  # [U, Pt]
    conflict_ports: jnp.ndarray  # [U, Pt]
    lvm_sizes: jnp.ndarray  # [U, Lv]
    ssd_sizes: jnp.ndarray  # [U, Sv] ascending
    hdd_sizes: jnp.ndarray  # [U, Hv] ascending
    wants_storage: jnp.ndarray  # [U] bool
    # inter-pod affinity + topology spread term tables (ops/terms.py)
    topo_val: jnp.ndarray  # [T, N] i32
    term_match: jnp.ndarray  # [T, U] bool
    carry_anti_req: jnp.ndarray  # [T, U]
    carry_aff_pref_w: jnp.ndarray  # [T, U]
    carry_anti_pref_w: jnp.ndarray  # [T, U]
    cls_rows: jnp.ndarray  # [U, Rmax]
    # prefolded commit increment for the combined own-affinity state:
    # HARD_POD_AFFINITY_WEIGHT * carry_aff_req + carry_aff_pref_w
    carry_pref_comb: jnp.ndarray  # [T, U]
    group_of_row: jnp.ndarray  # [A]
    match_all: jnp.ndarray  # [Gn, U]
    cls_group_rows: jnp.ndarray  # [U, Gmax]
    cls_group_id: jnp.ndarray  # [U]
    h_row: jnp.ndarray  # [Ch]
    h_self: jnp.ndarray  # [Ch, U]
    h_max_skew: jnp.ndarray  # [Ch]
    h_cand_nodes: jnp.ndarray  # [Ch, N]
    cls_h_rows: jnp.ndarray  # [U, Hmax]
    s_row: jnp.ndarray  # [Cs]
    s_is_host: jnp.ndarray  # [Cs]
    s_max_skew: jnp.ndarray  # [Cs]
    s_q: jnp.ndarray  # [Cs, N]
    cls_s_rows: jnp.ndarray  # [U, Smax]
    cls_s_haskeys: jnp.ndarray  # [U, N]
    # node-space term helpers (see ScanState: counts live on the node
    # axis, so per-step updates are masked broadcasts, not scatters)
    g_topo_val: jnp.ndarray  # [A, N] i32 = topo_val[group_rows]
    s_topo_val: jnp.ndarray  # [Cs, N] i32 = topo_val[s_row]
    # value one-hot for the soft-spread distinct-domain count; hostname
    # rows are all-zero (their domain count is just the eligible-node
    # count, s_is_host branch) so Vs stays at the small non-hostname
    # vocab instead of N
    s_val_onehot: jnp.ndarray  # [Cs, Vs, N] bool
    custom_raw: jnp.ndarray  # [K, U, N]
    custom_mode: jnp.ndarray  # [K]
    custom_weight: jnp.ndarray  # [K]


class ScanState(NamedTuple):
    used_mcpu: jnp.ndarray  # [N]
    used_mem: jnp.ndarray
    used_eph: jnp.ndarray
    used_scalar: jnp.ndarray  # [S, N]
    nz_mcpu: jnp.ndarray
    nz_mem: jnp.ndarray
    pod_cnt: jnp.ndarray
    ports_used: jnp.ndarray  # [N, Pt] bool
    gpu_used: jnp.ndarray  # [N, G]
    vg_used: jnp.ndarray  # [N, V]
    ssd_used: jnp.ndarray  # [N, Ds] bool
    hdd_used: jnp.ndarray  # [N, Dh] bool
    # affinity/spread counts in NODE space: entry [row, n] is the count
    # at node n's topology value (topo_val[row, n]); nodes sharing a
    # value share the count, nodes missing the key hold 0. This keeps
    # per-step reads as plain row indexing and per-step updates as
    # masked broadcasts over (topo_val == placed value) — value-space
    # [T, V] scatters/gathers lower to per-element ops on TPU and were
    # ~10x the cost of the whole rest of the step.
    tgt: jnp.ndarray  # [T, N] pods matching row selector at n's value
    own_anti_req: jnp.ndarray  # [T, N] carried required anti-affinity
    # combined HARD_POD_AFFINITY_WEIGHT*required-affinity + preferred-
    # affinity weight (their only reader sums them, scoring.go
    # processExistingPod — one state array instead of two)
    own_aff_pref_w: jnp.ndarray  # [T, N]
    own_anti_pref_w: jnp.ndarray  # [T, N] carried preferred-anti weight
    group_counts: jnp.ndarray  # [A, N] all-terms-match counts per group row
    group_total: jnp.ndarray  # [A] total matching pods per group row
    soft_counts: jnp.ndarray  # [Cs, N] qualifying-node-restricted counts
    # sample-mode Go math/rand state: the last 607 outputs of the
    # ALFG(607,273) recurrence in order (utils/gorand.py history()),
    # plus a sticky flag set if a draw ever needs more than
    # _RNG_KMAX consecutive rejection retries (p < 1e-17 per draw; the
    # engine raises SampleRngOverflow before committing anything and
    # core reruns the batch on the serial oracle).
    # None (the default) on non-sample batches keeps the pytree stable.
    rng_hist: jnp.ndarray = None  # [607] uint64
    rng_overflow: jnp.ndarray = None  # [] bool


class _LocalCtx:
    """Node-axis reduction context for the single-device scan: every
    cross-node combine is the identity (the local reduction already saw
    the whole axis), node gathers are plain indexing, and the select is
    a plain first-max argmax. The mesh-sharded scan (parallel/mesh.py)
    substitutes a context whose combines are `lax.pmax`/`psum`/... over
    the mesh axis and whose gathers broadcast the owning shard's value,
    so ONE step implementation serves both layouts — the sharded scan
    can never drift semantically from the single-device one."""

    axis = None

    def combine_max(self, x):
        return x

    def combine_min(self, x):
        return x

    def combine_sum(self, x):
        return x

    def combine_any(self, x):
        return x

    def gather_vec(self, vec, idx):
        """vec[idx] where idx is a GLOBAL node index (vec is the full
        node axis here; a local shard under the sharded ctx)."""
        return vec[idx]

    def gather_cols(self, arr, idx):
        """arr[..., idx] at a global node index (values >= -1)."""
        return arr[..., idx]

    def first_max_index(self, masked):
        """GLOBAL index of the first maximum along the node axis."""
        return jnp.argmax(masked)

    def commit_onehot(self, placement, commit, n_local):
        """One-hot of a GLOBAL placement over the LOCAL node slice,
        zero everywhere when commit is False (out-of-shard indices
        one-hot to all-zeros by jax.nn.one_hot's out-of-range rule)."""
        return jax.nn.one_hot(
            jnp.maximum(placement, 0), n_local, dtype=jnp.int64
        ) * commit.astype(jnp.int64)


LOCAL_CTX = _LocalCtx()


def _default_normalize(raw, feasible, reverse: bool, ctx=LOCAL_CTX):
    """DefaultNormalizeScore (plugins/helper/normalize_score.go:26-53)
    over the feasible set."""
    masked = jnp.where(feasible, raw, 0)
    max_count = ctx.combine_max(jnp.max(masked))
    base = jnp.where(max_count > 0, MAX_SCORE * raw // jnp.maximum(max_count, 1), 0)
    if reverse:
        out = jnp.where(max_count > 0, MAX_SCORE - base, MAX_SCORE)
    else:
        out = base
    return out


def _minmax_normalize(raw, feasible, ctx=LOCAL_CTX):
    """Simon/GpuShare/OpenLocal NormalizeScore (plugin/simon.go:75-100)
    over the feasible set; all-equal collapses to MinNodeScore=0."""
    big = jnp.iinfo(jnp.int64).max
    hi = ctx.combine_max(jnp.max(jnp.where(feasible, raw, -big)))
    lo = ctx.combine_min(jnp.min(jnp.where(feasible, raw, big)))
    rng = hi - lo
    return jnp.where(rng > 0, (raw - lo) * MAX_SCORE // jnp.maximum(rng, 1), 0)


def _least_requested(requested, capacity):
    """leastRequestedScore (noderesources/least_allocated.go:108-117)."""
    ok = (capacity > 0) & (requested <= capacity)
    return jnp.where(ok, (capacity - requested) * MAX_SCORE // jnp.maximum(capacity, 1), 0)


def _local_storage_eval(static: "ScanStatic", state: "ScanState", u):
    """Open-Local filter + score + hypothetical allocation, all nodes
    at once.

    LVM (open-local common.go ProcessLVMPVCPredicate/Binpack): each
    volume in declaration order goes to the VG with the least free
    space that still fits (ties: lowest VG index). Devices
    (ProcessDevicePVC): per media type, volumes ascending meet free
    devices ascending by capacity, first fit. Score = ScoreLVM +
    ScoreDevice (common.go:660-692, 753-761) with the Binpack strategy.

    Returns (ok[N], raw_score[N], vg_take[N,V], ssd_take[N,Ds] bool,
    hdd_take[N,Dh] bool).
    """
    n, v = static.vg_cap.shape
    big = jnp.iinfo(jnp.int64).max
    wants = static.wants_storage[u]

    vg_take = jnp.zeros((n, v), dtype=jnp.int64)
    lvm_ok = jnp.ones((n,), dtype=bool)
    for i in range(static.lvm_sizes.shape[1]):
        size = static.lvm_sizes[u, i]
        free = static.vg_cap - state.vg_used - vg_take
        eligible = static.vg_valid & (free >= size)
        chosen = jnp.argmin(jnp.where(eligible, free, big), axis=1)
        ok_i = jnp.any(eligible, axis=1)
        onehot = jax.nn.one_hot(chosen, v, dtype=jnp.int64) * ok_i[:, None]
        active = size > 0
        vg_take = vg_take + jnp.where(active, onehot * size, 0)
        lvm_ok = lvm_ok & (ok_i | ~active)

    def fit_devices(cap, valid, used, sizes):
        """First-fit of ascending sizes onto ascending-capacity free
        devices; returns (ok[N], take[N,D] bool, frac_sum[N], count)."""
        d = cap.shape[1]
        take = jnp.zeros(cap.shape, dtype=bool)
        ok = jnp.ones((cap.shape[0],), dtype=bool)
        frac = jnp.zeros((cap.shape[0],), dtype=jnp.float64)
        cnt = jnp.zeros((cap.shape[0],), dtype=jnp.int64)
        for i in range(sizes.shape[1]):
            size = sizes[u, i]
            active = size > 0
            eligible = valid & ~used & ~take & (cap >= size)
            ok_i = jnp.any(eligible, axis=1)
            # first eligible in ascending-capacity order
            chosen = jnp.argmax(eligible, axis=1)
            onehot = jax.nn.one_hot(chosen, d, dtype=bool) & eligible.any(axis=1)[:, None]
            take = take | (onehot & active)
            chosen_cap = jnp.take_along_axis(cap, chosen[:, None], axis=1)[:, 0]
            frac = frac + jnp.where(
                active & ok_i, size / jnp.maximum(chosen_cap, 1), 0.0
            )
            cnt = cnt + jnp.where(active & ok_i, 1, 0)
            ok = ok & (ok_i | ~active)
        return ok, take, frac, cnt

    ssd_ok, ssd_take, ssd_frac, ssd_cnt = fit_devices(
        static.ssd_cap, static.ssd_valid, state.ssd_used, static.ssd_sizes
    )
    hdd_ok, hdd_take, hdd_frac, hdd_cnt = fit_devices(
        static.hdd_cap, static.hdd_valid, state.hdd_used, static.hdd_sizes
    )

    ok = (~wants) | (static.has_storage & lvm_ok & ssd_ok & hdd_ok)

    # ScoreLVM (Binpack): mean over touched VGs of used/capacity * 10
    touched = vg_take > 0
    lvm_frac = jnp.sum(
        jnp.where(touched, vg_take / jnp.maximum(static.vg_cap, 1), 0.0), axis=1
    )
    lvm_cnt = jnp.sum(touched, axis=1)
    lvm_score = jnp.where(
        lvm_cnt > 0, (lvm_frac / jnp.maximum(lvm_cnt, 1) * 10).astype(jnp.int64), 0
    )
    # ScoreDevice: mean over ALL device units of requested/allocated * 10
    dev_cnt = ssd_cnt + hdd_cnt
    dev_score = jnp.where(
        dev_cnt > 0,
        ((ssd_frac + hdd_frac) / jnp.maximum(dev_cnt, 1) * 10).astype(jnp.int64),
        0,
    )
    raw = jnp.where(wants & static.has_storage, lvm_score + dev_score, 0)
    return ok, raw, vg_take, ssd_take, hdd_take


HARD_POD_AFFINITY_WEIGHT = 1  # interpodaffinity args default

# sample-mode rejection-retry bound per Intn draw: Go's Int31n rejects
# values above 2^31-1 - 2^31%n (probability < n/2^31 ~ 5e-6 at bench
# node counts), so >4 consecutive rejections has probability < 1e-17
# per draw — if it ever happens the overflow flag trips and the engine
# reruns the batch serially instead of diverging from the Go stream
_RNG_KMAX = 4
_MASK63 = (1 << 63) - 1


def _rng_gen_words(hist, wbuf: int):
    """The next `wbuf` outputs of the ALFG(607,273) recurrence from an
    ordered 607-output history, vectorized in blocks: outputs
    n..n+272 depend only on the current history (y_n = y_{n-607} +
    y_{n-273}), so each block is one uint64 vector add."""
    outs = []
    h = hist
    for _ in range(-(-wbuf // 273)):
        nw = h[:273] + h[334:607]  # uint64 wraps mod 2^64
        outs.append(nw)
        h = jnp.concatenate([h[273:], nw])
    return jnp.concatenate(outs)[:wbuf]


def _sample_select(masked, feasible, consume, rng_hist, n: int):
    """selectHost reservoir sampling (generic_scheduler.go:186-209)
    with bit-exact Go math/rand consumption, vectorized over nodes.

    The serial walk keeps a running max and, at every node TYING it,
    draws Intn(cnt) (replacing the candidate on 0). Vectorized:
    - running max = cummax; a node is an IMPROVEMENT when it strictly
      exceeds the previous prefix max, a TIE when it equals the
      current one without improving,
    - cnt at a tie = ties since the last improvement + 1 (segmented
      count via the cumsum-at-last-improvement trick),
    - the j-th tie in node order consumes the j-th Intn draw; each
      draw takes 1 + (#rejections) int31 words (Rand.Int31n's
      modulo-bias rejection loop; power-of-two n never rejects), so
      word offsets are a fixpoint of the per-draw consumption —
      iterated to convergence (rejections are ~1e-6 rare),
    - the selected node is the LAST improvement-or-winning-draw.

    Returns (best index, new history, overflow flag). `consume` gates
    the whole thing (inactive/pinned/unschedulable pods draw nothing).
    """
    i64 = jnp.int64
    neg = jnp.iinfo(i64).min
    cm = jax.lax.cummax(masked)
    prev = jnp.concatenate([jnp.array([neg], masked.dtype), cm[:-1]])
    imp = feasible & (masked > prev)
    tie = feasible & ~imp & (masked == cm)
    tie = tie & consume
    imp = imp & consume
    tie_i = tie.astype(i64)
    cumt = jnp.cumsum(tie_i)
    cumt_excl = cumt - tie_i
    # ties before the current run started (cumt_excl at the last
    # improvement; cumt_excl is nondecreasing so cummax works)
    base = jax.lax.cummax(jnp.where(imp, cumt_excl, -1))
    cnt = jnp.where(tie, cumt - base + 1, 2)
    pow2 = (cnt & (cnt - 1)) == 0
    maxv = (2**31 - 1) - (2**31) % cnt

    idx = jnp.arange(n, dtype=i64)
    wbuf = n + 64
    words = _rng_gen_words(rng_hist, wbuf)
    w31 = ((words & jnp.uint64(_MASK63)) >> jnp.uint64(32)).astype(i64)

    # fast path: the N-index gathers are the dominant cost (~55us per
    # gather at 4k nodes) and a draw REJECTS with probability < 5e-6,
    # so resolve all draws with ONE gather assuming no rejections and
    # take the fixpoint branch only when one actually occurred
    o0 = cumt_excl
    w0 = w31[jnp.clip(o0, 0, wbuf - 1)]
    rej0 = tie & ~pow2 & (w0 > maxv)

    def no_rejections(_):
        return tie_i, w0, jnp.zeros((), bool)

    def with_rejections(_):
        def consumption(c):
            o = jnp.cumsum(c) - c
            cc = tie_i
            lead = tie
            for k in range(_RNG_KMAX):
                w = w31[jnp.clip(o + k, 0, wbuf - 1)]
                rej = lead & ~pow2 & (w > maxv)
                cc = cc + rej.astype(i64)
                lead = rej
            return cc, lead

        def cond(st):
            c, prev_c, _, it = st
            return jnp.any(c != prev_c) & (it < 16)

        def body(st):
            c, _, ovf, it = st
            cc, lead = consumption(c)
            return cc, c, ovf | jnp.any(lead), it + 1

        c0, lead0 = consumption(tie_i)
        c, _, overflow, iters = jax.lax.while_loop(
            cond, body, (c0, tie_i, jnp.any(lead0), jnp.int32(0))
        )
        overflow = overflow | (iters >= 16)
        o = jnp.cumsum(c) - c
        acc = w31[jnp.clip(o + c - 1, 0, wbuf - 1)]
        return c, acc, overflow

    c, acc, overflow = jax.lax.cond(
        jnp.any(rej0), with_rejections, no_rejections, None
    )
    r = jnp.where(pow2, acc & (cnt - 1), acc % cnt)
    hit = tie & (r == 0)
    event = imp | hit
    best = jnp.max(jnp.where(event, idx, -1))
    t_used = jnp.sum(c)
    overflow = overflow | (t_used > wbuf - _RNG_KMAX)
    ext = jnp.concatenate([rng_hist, words])
    new_hist = jax.lax.dynamic_slice(ext, (t_used,), (607,))
    return best, new_hist, overflow, t_used


def _terms_eval(static: "ScanStatic", state: "ScanState", u, node_valid, features,
                ctx=LOCAL_CTX):
    """InterPodAffinity filter + raw score and PodTopologySpread hard
    filter + soft score for pod class u over all nodes.

    Returns (ipa_ok[N], spread_ok[N], ipa_raw[N] i64, soft_score fn).
    The soft-spread score depends on the feasible set, so it is returned
    as a closure evaluated after all filters are combined.
    """
    n = static.topo_val.shape[1]
    big = jnp.iinfo(jnp.int64).max
    ones_n = jnp.ones((n,), dtype=bool)

    if features.ipa:
        # ---- relevant term rows of this class ----------------------------
        rows = static.cls_rows[u]  # [R]
        rvalid = rows >= 0
        r = jnp.maximum(rows, 0)
        vals = static.topo_val[r]  # [R, N]
        has = (vals >= 0) & rvalid[:, None]

        # state is node-space (ScanState docstring): counts at each
        # node's own value are plain row reads, no value gather
        def gather(counts_n):
            return jnp.where(has, counts_n[r], 0)

        tgt_at = gather(state.tgt)
        own_anti_at = gather(state.own_anti_req)
        own_affpref_at = gather(state.own_aff_pref_w)
        own_antipref_at = gather(state.own_anti_pref_w)

        m = static.term_match[r, u] & rvalid  # [R]
        c_anti = jnp.where(rvalid, static.carry_anti_req[r, u], 0)
        c_paff = jnp.where(rvalid, static.carry_aff_pref_w[r, u], 0)
        c_panti = jnp.where(rvalid, static.carry_anti_pref_w[r, u], 0)

        # satisfyExistingPodsAntiAffinity (filtering.go:313-326)
        fail_exist_anti = jnp.any(m[:, None] & (own_anti_at > 0), axis=0)
        # satisfyPodAntiAffinity (filtering.go:329-340)
        fail_own_anti = jnp.any((c_anti > 0)[:, None] & (tgt_at > 0), axis=0)

        # InterPodAffinity raw score (scoring.go processExistingPod);
        # own_affpref_at already carries HARD_POD_AFFINITY_WEIGHT x
        # required affinity (combined state array)
        ipa_raw = jnp.sum(
            (c_paff - c_panti)[:, None] * tgt_at
            + m[:, None] * (own_affpref_at - own_antipref_at),
            axis=0,
        )

        # satisfyPodAffinity (filtering.go:343-371)
        garc = static.cls_group_rows[u]  # [Gm]
        gvalid = garc >= 0
        ga = jnp.maximum(garc, 0)
        gvals = static.g_topo_val[ga]  # [Gm, N]
        has_g = gvals >= 0
        gc = jnp.where(has_g, state.group_counts[ga], 0)
        keys_ok = jnp.all(has_g | ~gvalid[:, None], axis=0)
        pods_exist = jnp.all((gc > 0) | ~gvalid[:, None], axis=0)
        total_counts = jnp.sum(jnp.where(gvalid, state.group_total[ga], 0))
        gid = static.cls_group_id[u]
        self_ok = static.match_all[jnp.maximum(gid, 0), u]
        bootstrap = (total_counts == 0) & self_ok
        aff_ok = (gid < 0) | (keys_ok & (pods_exist | bootstrap))

        ipa_ok = aff_ok & ~fail_own_anti & ~fail_exist_anti
    else:
        ipa_ok = ones_n
        ipa_raw = jnp.zeros((n,), dtype=jnp.int64)

    if features.hard_spread:
        # ---- hard topology spread (filtering.go:276-337) -----------------
        # candidate topology VALUES derive from candidate NODES restricted
        # by the scenario's node_valid mask (capacity sweep correctness).
        # Node-space counts make the per-value min a plain min over
        # candidate nodes (duplicate values cannot change a min), and
        # each node's own-value count a direct read. Membership of a
        # node's value in the candidate-value set reduces to candidate
        # membership of the node itself: any node where spread_ok is
        # consumed passes the pod's selector/affinity and carries the
        # key, so it IS a candidate (h_cand_nodes construction,
        # ops/terms.py).
        hc = static.cls_h_rows[u]  # [Hm]
        hvalid = hc >= 0
        h = jnp.maximum(hc, 0)
        hrow = static.h_row[h]
        hvals = static.topo_val[hrow]  # [Hm, N]
        cand_nodes = static.h_cand_nodes[h] & node_valid[None, :]  # [Hm, N]
        counts_h = state.tgt[hrow]  # [Hm, N] node-space
        minc = ctx.combine_min(jnp.min(jnp.where(cand_nodes, counts_h, big), axis=1))
        minc = jnp.where(ctx.combine_any(jnp.any(cand_nodes, axis=1)), minc, 0)
        pair_in = cand_nodes & (hvals >= 0)
        cnt_eff = jnp.where(pair_in, counts_h, 0)
        selfm = static.h_self[h, u]
        skew = cnt_eff + selfm[:, None] - minc[:, None]
        ok_c = (skew <= static.h_max_skew[h][:, None]) & (hvals >= 0)
        spread_ok = jnp.all(ok_c | ~hvalid[:, None], axis=0)
    else:
        spread_ok = ones_n

    if not features.soft_spread:
        # NormalizeScore's no-constraint branch: MaxNodeScore everywhere
        max_n = jnp.full((n,), MAX_SCORE, dtype=jnp.int64)
        return ipa_ok, spread_ok, ipa_raw, lambda feasible_final: max_n

    # ---- soft topology spread score (scoring.go) -------------------------
    sc = static.cls_s_rows[u]
    svalid = sc >= 0
    s = jnp.maximum(sc, 0)
    has_soft = jnp.any(svalid)

    def soft_score(feasible_final):
        srow = static.s_row[s]
        svals = static.topo_val[srow]  # [Sm, N]
        has_keys = static.cls_s_haskeys[u]  # [N]
        eligible = feasible_final & has_keys
        is_host = static.s_is_host[s]

        # distinct eligible topology domains: for non-hostname rows the
        # static value one-hot [Vs, N] turns "any eligible node with
        # value v" into an elementwise AND + reduce (Vs = small vocab);
        # hostname rows count eligible nodes directly (value == node)
        onehot = static.s_val_onehot[s]  # [Sm, Vs, N]
        present = ctx.combine_any(
            jnp.any(onehot & eligible[None, None, :], axis=2)
        )  # [Sm, Vs]
        sz_nonhost = jnp.sum(present, axis=1)
        sz = jnp.where(is_host, ctx.combine_sum(jnp.sum(eligible)), sz_nonhost)
        weight = jnp.log(sz.astype(jnp.float64) + 2.0)
        # node-space counts: each node already reads its own value
        cnt_soft = state.soft_counts[s]
        cnt_host = state.tgt[srow]
        cnt = jnp.where(is_host[:, None], cnt_host, cnt_soft) * (svals >= 0)
        score_f = jnp.sum(
            jnp.where(
                svalid[:, None],
                cnt * weight[:, None] + (static.s_max_skew[s] - 1)[:, None].astype(jnp.float64),
                0.0,
            ),
            axis=0,
        )
        raw = score_f.astype(jnp.int64)
        valid = feasible_final & has_keys
        any_valid = ctx.combine_any(jnp.any(valid))
        mx = ctx.combine_max(jnp.max(jnp.where(valid, raw, -big)))
        mn = ctx.combine_min(jnp.min(jnp.where(valid, raw, big)))
        normalized = jnp.where(
            mx == 0, MAX_SCORE, MAX_SCORE * (mx + mn - raw) // jnp.maximum(mx, 1)
        )
        out = jnp.where(valid, normalized, 0)
        out = jnp.where(any_valid, out, 0)
        return jnp.where(has_soft, out, MAX_SCORE)

    return ipa_ok, spread_ok, ipa_raw, soft_score


def _terms_commit(static: "ScanStatic", state: "ScanState", u, placement, commit,
                  features, ctx=LOCAL_CTX):
    """Rank-1 count updates after a commit (AddPod semantics of the
    PreFilterExtensions / next cycle's PreScore recomputation).

    Node-space form: incrementing the count at the placed value means
    incrementing every node sharing that value — a masked broadcast
    `(topo_val == placed value) * inc` over the full [T, N] table
    (value-space scatters lower to per-element stores on TPU). Rows not
    touched by this class carry a zero increment: term_match / carry_* /
    match_all columns are zero exactly where the old cls_rows-restricted
    scatters never wrote."""
    node = jnp.maximum(placement, 0)
    inc = commit.astype(jnp.int64)

    tgt = state.tgt
    own_anti = state.own_anti_req
    own_paff = state.own_aff_pref_w
    own_panti = state.own_anti_pref_w
    group_counts = state.group_counts
    group_total = state.group_total
    soft_counts = state.soft_counts

    if features.terms:
        # placed node's values: a cross-shard broadcast gather under
        # the mesh ctx (the committed node lives on exactly one shard)
        val_at = ctx.gather_cols(static.topo_val, node)  # [T]
        eq = (static.topo_val == val_at[:, None]) & (val_at >= 0)[:, None]
        eqi = eq.astype(jnp.int64)
        # target counts feed IPA filters/score, hard-spread skew checks,
        # and soft-spread host-level constraint counts
        tgt = tgt + (static.term_match[:, u].astype(jnp.int64) * inc)[:, None] * eqi

    if features.ipa:
        own_anti = own_anti + (static.carry_anti_req[:, u] * inc)[:, None] * eqi
        own_paff = own_paff + (static.carry_pref_comb[:, u] * inc)[:, None] * eqi
        own_panti = own_panti + (static.carry_anti_pref_w[:, u] * inc)[:, None] * eqi

        # group counts: all A rows
        g_val = ctx.gather_cols(static.g_topo_val, node)  # [A]
        g_ok = g_val >= 0
        g_eq = (static.g_topo_val == g_val[:, None]) & g_ok[:, None]
        g_match = jnp.take(static.match_all[:, u], static.group_of_row)  # [A]
        g_inc = (g_match & g_ok).astype(jnp.int64) * inc
        group_counts = group_counts + g_inc[:, None] * g_eq.astype(jnp.int64)
        group_total = group_total + g_inc

    if features.soft_spread:
        # soft spread counts: all Cs rows, restricted to qualifying
        # PLACED nodes (s_q gates who counts, not who reads)
        s_val = ctx.gather_cols(static.s_topo_val, node)  # [Cs]
        s_ok = (s_val >= 0) & ctx.gather_cols(static.s_q, node)
        s_eq = (static.s_topo_val == s_val[:, None]) & s_ok[:, None]
        s_match = jnp.take(static.term_match[:, u], static.s_row)  # [Cs]
        s_inc = (s_match & s_ok).astype(jnp.int64) * inc
        soft_counts = soft_counts + s_inc[:, None] * s_eq.astype(jnp.int64)

    return (
        tgt, own_anti, own_paff, own_panti,
        group_counts, group_total, soft_counts,
    )


def _gpu_allocate(avail, dev_valid, per_gpu_mem, count):
    """AllocateGpuId vectorized (open-gpu-share gpunodeinfo.go:232-291).

    Returns (found[N], take[N, G]): take = per-device number of GPU
    shares allocated. Single-GPU: tightest fit (min idle that fits,
    first index on ties). Multi-GPU: two-pointer greedy in device order,
    a device may host several shares.
    """
    fits = dev_valid & (avail >= per_gpu_mem)  # [N, G]
    # single
    big = jnp.iinfo(jnp.int64).max
    key = jnp.where(fits, avail, big)
    best = jnp.argmin(key, axis=1)  # first index on ties: matches strict '<'
    single_found = jnp.any(fits, axis=1)
    single_take = jax.nn.one_hot(best, avail.shape[1], dtype=jnp.int64) * single_found[
        :, None
    ].astype(jnp.int64)
    # multi: capacity in units of per_gpu_mem per device, greedy prefix
    cap = jnp.where(dev_valid, avail // jnp.maximum(per_gpu_mem, 1), 0)
    cap = jnp.maximum(cap, 0)
    prefix = jnp.cumsum(cap, axis=1) - cap  # exclusive prefix
    multi_take = jnp.clip(count - prefix, 0, cap)
    multi_found = jnp.sum(cap, axis=1) >= count
    take = jnp.where(count == 1, single_take, multi_take)
    found = jnp.where(count == 1, single_found, multi_found)
    return found, take


INACTIVE = -2  # pod not present in this scenario (capacity-sweep masking)


def run_scan(
    static: ScanStatic,
    init: ScanState,
    class_of_pod,
    pinned_node,
    features=None,
    weights=None,
):
    """Schedule every pod in order; returns (placements[P], final state).

    placements[p] = node index, or -1 when unschedulable. With
    features.sample the first element is a (placements[P],
    consumed_words[P]) PAIR — per-pod Go-RNG consumption, which the
    priority-scan engine uses to rewind the stream to an escape point
    (engine.rewind_sample_rng).
    """
    n = static.alloc_mcpu.shape[0]
    p = class_of_pod.shape[0]
    return run_scan_masked(
        static,
        init,
        class_of_pod,
        pinned_node,
        jnp.ones((n,), bool),
        jnp.ones((p,), bool),
        features=features,
        weights=weights,
    )


def run_scan_masked(
    static: ScanStatic,
    init: ScanState,
    class_of_pod,
    pinned_node,
    node_valid,
    pod_active,
    features=None,
    weights=None,
):
    """run_scan with scenario masks for the capacity sweep
    (pkg/apply/apply.go:186-239 re-imagined as a batched what-if):
    `node_valid[n]` gates candidate nodes, `pod_active[p]` skips pods
    that do not exist in this scenario (e.g. daemonset pods of disabled
    new nodes). Inactive pods commit nothing and report INACTIVE.

    The tiered priority engine is a second caller of the pod mask
    (engine.scan_active): escape rounds re-dispatch the SAME batch
    encoding with the committed prefix masked off, so every round
    reuses one compiled program (shapes never change) — the masked-pod
    contract it relies on is exactly the sweep's: an inactive pod
    mutates no carry state and, under features.sample, consumes ZERO
    Go-RNG words (the escape rewind arithmetic in
    engine.rewind_sample_rng depends on this).

    `features` (a ScanFeatures, static under jit) specializes the
    compiled scan to the subsystems the batch uses; None derives it from
    `static`/`pinned_node`, which must then be concrete arrays.
    `weights` (custom score weights) only applies when `features` is
    derived here; explicit `features` already carry theirs, so passing
    both is a caller bug.

    With features.sample the returned placements are a (placements,
    consumed_words) PAIR (see run_scan) and init.rng_hist must carry
    the GoRand 607-output history.
    """
    if features is not None and weights is not None:
        raise ValueError(
            "pass weights inside features (features_of_batch(..., weights=)) "
            "or alone, not both"
        )
    if features is None:
        features = features_of(static, pinned_node, weights=weights)
    if features.sample:
        if init.rng_hist is None:
            raise ValueError(
                "features.sample needs init.rng_hist (the GoRand "
                "607-output history; gorand.GoRand.history())"
            )
        if init.rng_overflow is None:
            init = init._replace(rng_overflow=jnp.zeros((), bool))
    return _run_scan_compiled(
        features, static, init, class_of_pod, pinned_node, node_valid, pod_active
    )


def _run_scan_compiled_impl(
    features: ScanFeatures,
    static: ScanStatic,
    init: ScanState,
    class_of_pod,
    pinned_node,
    node_valid,
    pod_active,
    ctx=LOCAL_CTX,
):
    # `ctx` (static at trace time) abstracts the node axis: LOCAL_CTX
    # is the whole-axis identity; the mesh-sharded scan passes a
    # collective-aware ctx and LOCAL node slices, so each step scores
    # its shard locally and combines max/min/sum/select across devices
    # (parallel/mesh.py). Sample mode stays LOCAL-only — the Go-RNG
    # prefix arithmetic is a serial scan over the full node axis.
    n = static.alloc_mcpu.shape[0]

    def step(state: ScanState, inp):
        u, pin, active = inp
        feasible = static.static_feasible[u] & node_valid
        # NodeResourcesFit (noderesources/fit.go:230-303)
        fit_pods = state.pod_cnt + 1 <= static.alloc_pods
        fit_cpu = static.alloc_mcpu >= static.req_mcpu[u] + state.used_mcpu
        fit_mem = static.alloc_mem >= static.req_mem[u] + state.used_mem
        fit_eph = static.alloc_eph >= static.req_eph[u] + state.used_eph
        fit_res = fit_cpu & fit_mem & fit_eph
        if features.scalars:
            fit_res = fit_res & jnp.all(
                static.scalar_alloc >= static.req_scalar[u][:, None] + state.used_scalar,
                axis=0,
            )
        # zero-request pods skip everything but the pod-count check
        fit = fit_pods & (fit_res | ~static.has_request[u])
        feasible = feasible & fit
        # NodePorts
        if features.ports:
            port_clash = jnp.any(
                state.ports_used & static.conflict_ports[u][None, :], axis=1
            )
            feasible = feasible & ~port_clash
        # GPU share
        if features.gpu:
            avail = static.gpu_per_dev[:, None] - state.gpu_used
            gpu_found, gpu_take = _gpu_allocate(
                avail, static.dev_valid, static.gpu_mem[u], static.gpu_cnt[u]
            )
            needs_gpu = static.gpu_mem[u] > 0
            gpu_ok = ~needs_gpu | ((static.gpu_total >= static.gpu_mem[u]) & gpu_found)
            feasible = feasible & gpu_ok
        # Open-Local
        if features.storage:
            local_ok, local_raw, vg_take, ssd_take, hdd_take = _local_storage_eval(
                static, state, u
            )
            feasible = feasible & local_ok
        # InterPodAffinity + PodTopologySpread
        ipa_ok, spread_ok, ipa_raw, soft_score = _terms_eval(
            static, state, u, node_valid, features, ctx=ctx
        )

        feasible = feasible & ipa_ok & spread_ok

        # ---- scores ----
        # Weights are static (a KubeSchedulerConfiguration overlay,
        # scheduler/schedconfig.py); zero-weight plugins are skipped at
        # trace time so XLA never sees them.
        w = features.weights if features.weights is not None else _DEFAULT_WEIGHTS
        total = jnp.zeros(n, dtype=jnp.int64)
        cpu_req_total = state.nz_mcpu + static.nz_mcpu[u]
        mem_req_total = state.nz_mem + static.nz_mem[u]
        if w.least:
            least = (
                _least_requested(cpu_req_total, static.alloc_mcpu)
                + _least_requested(mem_req_total, static.alloc_mem)
            ) // 2
            total = total + least * w.least
        if w.balanced:
            cpu_frac = cpu_req_total / jnp.maximum(static.alloc_mcpu, 1)
            cpu_frac = jnp.where(static.alloc_mcpu > 0, cpu_frac, 1.0)
            mem_frac = mem_req_total / jnp.maximum(static.alloc_mem, 1)
            mem_frac = jnp.where(static.alloc_mem > 0, mem_frac, 1.0)
            balanced = jnp.where(
                (cpu_frac >= 1) | (mem_frac >= 1),
                0,
                ((1 - jnp.abs(cpu_frac - mem_frac)) * MAX_SCORE).astype(jnp.int64),
            )
            total = total + balanced * w.balanced
        if w.nodeaff:
            nodeaff = _default_normalize(
                static.nodeaff_raw[u], feasible, reverse=False, ctx=ctx
            )
            total = total + nodeaff * w.nodeaff
        if w.tainttol:
            tainttol = _default_normalize(
                static.taint_intol[u], feasible, reverse=True, ctx=ctx
            )
            total = total + tainttol * w.tainttol
        if w.simon or w.gpushare:
            # Simon and Open-Gpu-Share share one formula (simon.go:44-67)
            simon = _minmax_normalize(static.simon_raw[u], feasible, ctx=ctx)
            total = total + simon * (w.simon + w.gpushare)
        if w.spread:
            # PodTopologySpread soft score (all MaxNodeScore when the pod
            # has no soft constraints — NormalizeScore maxScore==0 branch)
            spread = soft_score(feasible)
            total = total + spread * w.spread
        if w.image:
            total = total + static.image_score[u] * w.image
        if w.avoid:
            total = total + static.avoid_score[u] * w.avoid
        if features.ipa and w.ipa:
            # InterPodAffinity NormalizeScore (scoring.go:246-270): bounds
            # include 0, float divide, int64 truncation
            ipa_mx = jnp.maximum(
                ctx.combine_max(jnp.max(jnp.where(feasible, ipa_raw, 0))), 0
            )
            ipa_mn = jnp.minimum(
                ctx.combine_min(jnp.min(jnp.where(feasible, ipa_raw, 0))), 0
            )
            ipa_diff = (ipa_mx - ipa_mn).astype(jnp.float64)
            ipa = jnp.where(
                ipa_diff > 0,
                (MAX_SCORE * (ipa_raw - ipa_mn) / jnp.maximum(ipa_diff, 1.0)).astype(
                    jnp.int64
                ),
                0,
            )
            total = total + ipa * w.ipa
        if features.storage and w.openlocal:
            # Open-Local plugin
            total = total + _minmax_normalize(local_raw, feasible, ctx=ctx) * w.openlocal
        if features.custom:
            # out-of-tree custom plugins (static K, unrolled)
            for k_i in range(static.custom_raw.shape[0]):
                raw_k = static.custom_raw[k_i, u]
                if features.custom_spec is not None:
                    # modes/weights host-known: emit only the needed path
                    mode_k, weight_k = features.custom_spec[k_i]
                    if weight_k == 0:
                        continue
                    if mode_k == 0:
                        score_k = raw_k
                    elif mode_k == 1:
                        score_k = _default_normalize(
                            raw_k, feasible, reverse=False, ctx=ctx
                        )
                    elif mode_k == 2:
                        score_k = _default_normalize(
                            raw_k, feasible, reverse=True, ctx=ctx
                        )
                    else:
                        score_k = _minmax_normalize(raw_k, feasible, ctx=ctx)
                    total = total + score_k * weight_k
                    continue
                mode = static.custom_mode[k_i]
                norm_default = _default_normalize(
                    raw_k, feasible, reverse=False, ctx=ctx
                )
                norm_reverse = _default_normalize(
                    raw_k, feasible, reverse=True, ctx=ctx
                )
                norm_minmax = _minmax_normalize(raw_k, feasible, ctx=ctx)
                score_k = jnp.where(
                    mode == 0,
                    raw_k,
                    jnp.where(
                        mode == 1,
                        norm_default,
                        jnp.where(mode == 2, norm_reverse, norm_minmax),
                    ),
                )
                total = total + score_k * static.custom_weight[k_i]

        # ---- select: first max over feasible; pinned overrides ----
        neg = jnp.iinfo(jnp.int64).min
        masked = jnp.where(feasible, total, neg)
        found = ctx.combine_any(jnp.any(feasible))
        if features.sample:
            # reservoir sampling over ties with the Go math/rand
            # stream in the carry; pinned/inactive/unschedulable pods
            # consume nothing (the oracle never runs selectHost for
            # them)
            consume = active & found
            if features.pins:
                consume = consume & (pin < 0)
            best, new_rng_hist, step_ovf, consumed = _sample_select(
                masked, feasible, consume, state.rng_hist, n
            )
            new_rng_overflow = state.rng_overflow | step_ovf
        else:
            best = ctx.first_max_index(masked)
            new_rng_hist = state.rng_hist
            new_rng_overflow = state.rng_overflow
        placement = jnp.where(found, best, -1)
        if features.pins:
            placement = jnp.where(pin >= 0, pin, placement)
            # a pod pinned to a masked-out node does not exist in this
            # scenario; never commit resources outside node_valid
            pin_ok = ctx.gather_vec(node_valid, jnp.maximum(pin, 0))
            placement = jnp.where((pin >= 0) & ~pin_ok, INACTIVE, placement)
        placement = jnp.where(active, placement, INACTIVE)

        # ---- commit ----
        commit = placement >= 0
        (
            tgt, own_anti, own_paff, own_panti,
            group_counts, group_total, soft_counts,
        ) = _terms_commit(static, state, u, placement, commit, features, ctx=ctx)
        onehot = ctx.commit_onehot(placement, commit, n)
        new_state = ScanState(
            used_mcpu=state.used_mcpu + onehot * static.req_mcpu[u],
            used_mem=state.used_mem + onehot * static.req_mem[u],
            used_eph=state.used_eph + onehot * static.req_eph[u],
            used_scalar=(
                state.used_scalar + onehot[None, :] * static.req_scalar[u][:, None]
                if features.scalars
                else state.used_scalar
            ),
            nz_mcpu=state.nz_mcpu + onehot * static.nz_mcpu[u],
            nz_mem=state.nz_mem + onehot * static.nz_mem[u],
            pod_cnt=state.pod_cnt + onehot,
            ports_used=(
                state.ports_used
                | (onehot.astype(bool)[:, None] & static.want_ports[u][None, :])
                if features.ports
                else state.ports_used
            ),
            gpu_used=(
                state.gpu_used
                + jnp.where(needs_gpu, onehot[:, None] * gpu_take * static.gpu_mem[u], 0)
                if features.gpu
                else state.gpu_used
            ),
            vg_used=(
                state.vg_used + onehot[:, None] * vg_take
                if features.storage
                else state.vg_used
            ),
            ssd_used=(
                state.ssd_used | (onehot.astype(bool)[:, None] & ssd_take)
                if features.storage
                else state.ssd_used
            ),
            hdd_used=(
                state.hdd_used | (onehot.astype(bool)[:, None] & hdd_take)
                if features.storage
                else state.hdd_used
            ),
            tgt=tgt,
            own_anti_req=own_anti,
            own_aff_pref_w=own_paff,
            own_anti_pref_w=own_panti,
            group_counts=group_counts,
            group_total=group_total,
            soft_counts=soft_counts,
            rng_hist=new_rng_hist,
            rng_overflow=new_rng_overflow,
        )
        if features.sample:
            # per-pod word consumption rides along so the priority-scan
            # engine can REWIND the stream to an escape point (the scan
            # consumed draws for the whole batch, but escaped tails are
            # discarded and rescheduled)
            return new_state, (placement, consumed)
        return new_state, placement

    final_state, placements = jax.lax.scan(
        step, init, (class_of_pod, pinned_node, pod_active)
    )
    # sample mode: placements is a (placements[P], consumed_words[P])
    # pair — the engine unpacks it (no other caller runs sample)
    return placements, final_state


# The module-level scan jit, wrapped for dispatch/recompile accounting
# (obs/profile.py): every run_scan / run_scan_masked call is one
# counted device dispatch, and a grown jit cache is a counted
# recompile — the warm-cache contract the tiered engine and `simon
# serve` rely on is pinned by tests/test_obs.py through these counters.
_run_scan_compiled = _obs_profile.instrument_jit(
    jax.jit(_run_scan_compiled_impl, static_argnums=0), "scan",
    static_argnums=(0,),
)
