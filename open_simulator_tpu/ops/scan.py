"""Sequential-commit scheduling scan.

The serial one-pod-at-a-time semantics of the reference
(pkg/simulator/simulator.go:218-243: create pod -> block until the
scheduler round-trips -> next pod) become a `jax.lax.scan` over the pod
queue. Each step is the whole scheduling cycle of
generic_scheduler.Schedule (core/generic_scheduler.go:131-180) fused
over the node axis:

  filter  = static_feasible  & NodeResourcesFit & NodePorts & GPU fit
  score   = Balanced + Least + ImageLocality + NodeAffinity'
            + PreferAvoid*10000 + TopologySpread' * 2 + TaintToleration'
            + Simon' + GpuShare' + OpenLocal'     (' = normalized)
  select  = first-index argmax over feasible nodes
  commit  = rank-1 state update (requested vectors, pod count, ports,
            per-device GPU memory)

All integer arithmetic is int64 to bit-match the serial oracle.
selectHost tie-break is pinned to the first maximum in node order (the
reference reservoir-samples, generic_scheduler.go:186-209 — documented
deviation shared with the oracle).

Pinned pods (spec.nodeName already set) flow through the same scan as
forced placements so that interleavings of pinned and loose pods see
the same intermediate states as the serial path.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

MAX_SCORE = 100


class ScanStatic(NamedTuple):
    """Arrays closed over by the compiled scan (static per batch)."""

    alloc_mcpu: jnp.ndarray  # [N]
    alloc_mem: jnp.ndarray
    alloc_eph: jnp.ndarray
    alloc_pods: jnp.ndarray
    scalar_alloc: jnp.ndarray  # [S, N]
    gpu_per_dev: jnp.ndarray  # [N]
    gpu_total: jnp.ndarray  # [N]
    gpu_count: jnp.ndarray  # [N]
    dev_valid: jnp.ndarray  # [N, G] bool (device exists on node)
    # per-class static matrices
    static_feasible: jnp.ndarray  # [U, N]
    simon_raw: jnp.ndarray  # [U, N]
    nodeaff_raw: jnp.ndarray  # [U, N]
    taint_intol: jnp.ndarray  # [U, N]
    avoid_score: jnp.ndarray  # [U, N]
    image_score: jnp.ndarray  # [U, N]
    # per-class request vectors
    req_mcpu: jnp.ndarray  # [U]
    req_mem: jnp.ndarray
    req_eph: jnp.ndarray
    req_scalar: jnp.ndarray  # [U, S]
    has_request: jnp.ndarray  # [U] bool
    nz_mcpu: jnp.ndarray
    nz_mem: jnp.ndarray
    gpu_mem: jnp.ndarray  # [U]
    gpu_cnt: jnp.ndarray  # [U]
    want_ports: jnp.ndarray  # [U, Pt]
    conflict_ports: jnp.ndarray  # [U, Pt]


class ScanState(NamedTuple):
    used_mcpu: jnp.ndarray  # [N]
    used_mem: jnp.ndarray
    used_eph: jnp.ndarray
    used_scalar: jnp.ndarray  # [S, N]
    nz_mcpu: jnp.ndarray
    nz_mem: jnp.ndarray
    pod_cnt: jnp.ndarray
    ports_used: jnp.ndarray  # [N, Pt] bool
    gpu_used: jnp.ndarray  # [N, G]


def _default_normalize(raw, feasible, reverse: bool):
    """DefaultNormalizeScore (plugins/helper/normalize_score.go:26-53)
    over the feasible set."""
    masked = jnp.where(feasible, raw, 0)
    max_count = jnp.max(masked)
    base = jnp.where(max_count > 0, MAX_SCORE * raw // jnp.maximum(max_count, 1), 0)
    if reverse:
        out = jnp.where(max_count > 0, MAX_SCORE - base, MAX_SCORE)
    else:
        out = base
    return out


def _minmax_normalize(raw, feasible):
    """Simon/GpuShare/OpenLocal NormalizeScore (plugin/simon.go:75-100)
    over the feasible set; all-equal collapses to MinNodeScore=0."""
    big = jnp.iinfo(jnp.int64).max
    hi = jnp.max(jnp.where(feasible, raw, -big))
    lo = jnp.min(jnp.where(feasible, raw, big))
    rng = hi - lo
    return jnp.where(rng > 0, (raw - lo) * MAX_SCORE // jnp.maximum(rng, 1), 0)


def _least_requested(requested, capacity):
    """leastRequestedScore (noderesources/least_allocated.go:108-117)."""
    ok = (capacity > 0) & (requested <= capacity)
    return jnp.where(ok, (capacity - requested) * MAX_SCORE // jnp.maximum(capacity, 1), 0)


def _gpu_allocate(avail, dev_valid, per_gpu_mem, count):
    """AllocateGpuId vectorized (open-gpu-share gpunodeinfo.go:232-291).

    Returns (found[N], take[N, G]): take = per-device number of GPU
    shares allocated. Single-GPU: tightest fit (min idle that fits,
    first index on ties). Multi-GPU: two-pointer greedy in device order,
    a device may host several shares.
    """
    fits = dev_valid & (avail >= per_gpu_mem)  # [N, G]
    # single
    big = jnp.iinfo(jnp.int64).max
    key = jnp.where(fits, avail, big)
    best = jnp.argmin(key, axis=1)  # first index on ties: matches strict '<'
    single_found = jnp.any(fits, axis=1)
    single_take = jax.nn.one_hot(best, avail.shape[1], dtype=jnp.int64) * single_found[
        :, None
    ].astype(jnp.int64)
    # multi: capacity in units of per_gpu_mem per device, greedy prefix
    cap = jnp.where(dev_valid, avail // jnp.maximum(per_gpu_mem, 1), 0)
    cap = jnp.maximum(cap, 0)
    prefix = jnp.cumsum(cap, axis=1) - cap  # exclusive prefix
    multi_take = jnp.clip(count - prefix, 0, cap)
    multi_found = jnp.sum(cap, axis=1) >= count
    take = jnp.where(count == 1, single_take, multi_take)
    found = jnp.where(count == 1, single_found, multi_found)
    return found, take


INACTIVE = -2  # pod not present in this scenario (capacity-sweep masking)


@partial(jax.jit, static_argnums=())
def run_scan(static: ScanStatic, init: ScanState, class_of_pod, pinned_node):
    """Schedule every pod in order; returns (placements[P], final state).

    placements[p] = node index, or -1 when unschedulable.
    """
    n = static.alloc_mcpu.shape[0]
    p = class_of_pod.shape[0]
    return run_scan_masked(
        static,
        init,
        class_of_pod,
        pinned_node,
        jnp.ones((n,), bool),
        jnp.ones((p,), bool),
    )


@partial(jax.jit, static_argnums=())
def run_scan_masked(
    static: ScanStatic,
    init: ScanState,
    class_of_pod,
    pinned_node,
    node_valid,
    pod_active,
):
    """run_scan with scenario masks for the capacity sweep
    (pkg/apply/apply.go:186-239 re-imagined as a batched what-if):
    `node_valid[n]` gates candidate nodes, `pod_active[p]` skips pods
    that do not exist in this scenario (e.g. daemonset pods of disabled
    new nodes). Inactive pods commit nothing and report INACTIVE.
    """

    def step(state: ScanState, inp):
        u, pin, active = inp
        feasible = static.static_feasible[u] & node_valid
        # NodeResourcesFit (noderesources/fit.go:230-303)
        fit_pods = state.pod_cnt + 1 <= static.alloc_pods
        fit_cpu = static.alloc_mcpu >= static.req_mcpu[u] + state.used_mcpu
        fit_mem = static.alloc_mem >= static.req_mem[u] + state.used_mem
        fit_eph = static.alloc_eph >= static.req_eph[u] + state.used_eph
        fit_scalar = jnp.all(
            static.scalar_alloc >= static.req_scalar[u][:, None] + state.used_scalar,
            axis=0,
        )
        fit_res = fit_cpu & fit_mem & fit_eph & fit_scalar
        # zero-request pods skip everything but the pod-count check
        fit = fit_pods & (fit_res | ~static.has_request[u])
        # NodePorts
        port_clash = jnp.any(state.ports_used & static.conflict_ports[u][None, :], axis=1)
        # GPU share
        avail = static.gpu_per_dev[:, None] - state.gpu_used
        gpu_found, gpu_take = _gpu_allocate(
            avail, static.dev_valid, static.gpu_mem[u], static.gpu_cnt[u]
        )
        needs_gpu = static.gpu_mem[u] > 0
        gpu_ok = ~needs_gpu | ((static.gpu_total >= static.gpu_mem[u]) & gpu_found)

        feasible = feasible & fit & ~port_clash & gpu_ok

        # ---- scores ----
        cpu_req_total = state.nz_mcpu + static.nz_mcpu[u]
        mem_req_total = state.nz_mem + static.nz_mem[u]
        least = (
            _least_requested(cpu_req_total, static.alloc_mcpu)
            + _least_requested(mem_req_total, static.alloc_mem)
        ) // 2
        cpu_frac = cpu_req_total / jnp.maximum(static.alloc_mcpu, 1)
        cpu_frac = jnp.where(static.alloc_mcpu > 0, cpu_frac, 1.0)
        mem_frac = mem_req_total / jnp.maximum(static.alloc_mem, 1)
        mem_frac = jnp.where(static.alloc_mem > 0, mem_frac, 1.0)
        balanced = jnp.where(
            (cpu_frac >= 1) | (mem_frac >= 1),
            0,
            ((1 - jnp.abs(cpu_frac - mem_frac)) * MAX_SCORE).astype(jnp.int64),
        )
        nodeaff = _default_normalize(static.nodeaff_raw[u], feasible, reverse=False)
        tainttol = _default_normalize(static.taint_intol[u], feasible, reverse=True)
        simon = _minmax_normalize(static.simon_raw[u], feasible)
        # PodTopologySpread with no constraints normalizes every node to
        # MaxNodeScore (scoring.go NormalizeScore maxScore==0 branch);
        # InterPodAffinity and Open-Local contribute 0 without terms.
        spread = MAX_SCORE
        total = (
            balanced
            + static.image_score[u]
            + least
            + nodeaff
            + static.avoid_score[u] * 10000
            + spread * 2
            + tainttol
            + simon  # Simon plugin
            + simon  # Open-Gpu-Share plugin (identical formula)
        )

        # ---- select: first max over feasible; pinned overrides ----
        neg = jnp.iinfo(jnp.int64).min
        masked = jnp.where(feasible, total, neg)
        best = jnp.argmax(masked)
        found = jnp.any(feasible)
        placement = jnp.where(pin >= 0, pin, jnp.where(found, best, -1))
        # a pod pinned to a masked-out node does not exist in this
        # scenario; never commit resources outside node_valid
        pin_ok = node_valid[jnp.maximum(pin, 0)]
        placement = jnp.where((pin >= 0) & ~pin_ok, INACTIVE, placement)
        placement = jnp.where(active, placement, INACTIVE)

        # ---- commit ----
        commit = placement >= 0
        onehot = (
            jax.nn.one_hot(jnp.maximum(placement, 0), static.alloc_mcpu.shape[0], dtype=jnp.int64)
            * commit.astype(jnp.int64)
        )
        new_state = ScanState(
            used_mcpu=state.used_mcpu + onehot * static.req_mcpu[u],
            used_mem=state.used_mem + onehot * static.req_mem[u],
            used_eph=state.used_eph + onehot * static.req_eph[u],
            used_scalar=state.used_scalar + onehot[None, :] * static.req_scalar[u][:, None],
            nz_mcpu=state.nz_mcpu + onehot * static.nz_mcpu[u],
            nz_mem=state.nz_mem + onehot * static.nz_mem[u],
            pod_cnt=state.pod_cnt + onehot,
            ports_used=state.ports_used
            | (onehot.astype(bool)[:, None] & static.want_ports[u][None, :]),
            gpu_used=state.gpu_used
            + jnp.where(needs_gpu, onehot[:, None] * gpu_take * static.gpu_mem[u], 0),
            )
        return new_state, placement

    final_state, placements = jax.lax.scan(
        step, init, (class_of_pod, pinned_node, pod_active)
    )
    return placements, final_state
