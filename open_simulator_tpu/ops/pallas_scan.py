"""Fused Pallas TPU kernel for the sequential-commit scheduling scan.

The XLA `lax.scan` step (ops/scan.py) lowers to ~15-20 small kernels
per pod; at N=10k nodes each is latency-bound (~2-3us), so a 100k-pod
capacity probe costs ~3-4 s on a v5e chip. This module runs the ENTIRE
scan inside ONE `pl.pallas_call`: a `fori_loop` over pods with all
cluster state resident in VMEM as (R, 128) int32 tiles — per-step cost
collapses to pure VPU arithmetic with zero kernel-launch overhead.

Scope (automatic fallback to the XLA scan otherwise):
- no custom-plugin machinery (features gates, same contract as
  ScanFeatures). nodeName pins (`run_scan_pallas(pinned=...)`),
  hostPorts (per-(ip,proto,port) vocab bitmask tiles), extended
  scalar resources, and open-gpu-share device packing (per-device
  (G, R, 128) memory tiles, tightest-fit / two-pointer allocation
  mirroring scan.py _gpu_allocate; gpu+pins falls back) ARE in scope,
- open-local storage IS in scope (r5): the VG Binpack and device
  first-fit run in GCD-scaled int32, and the f64 ScoreLVM/ScoreDevice
  truncations — r4's measured reason for staying off the kernel —
  ride as host-precomputed SMEM tables indexed by the in-kernel
  assignment pattern (StorePlan docstring),
- inter-pod affinity + hard/soft topology spread ARE in scope: term
  count state rides in VMEM scratch as node-space (T, R, 128) i32
  tiles (ops/scan.py ScanState docstring), per-(class, slot) eval
  scalars are prefolded host-side into SMEM tables, init states stream
  in from ANY/HBM by DMA, and commits are masked broadcasts over
  (topo_val == placed value). Past the VMEM budget the plan
  auto-rewrites to the STREAMED layout (r5): term state lives in one
  HBM buffer and each pod step DMA-gathers only its class's rows
  (StreamTermsPlan docstring) — the ~12.3k-node cliff becomes a
  bandwidth slope (50k nodes measured),
- all quantities must fit exactness-preserving int32 encodings:
  memory/ephemeral values are divided by their collective GCD
  (floor-division identities keep every score and fit comparison
  bit-identical to the int64 XLA path), with magnitude guards
  (_build_terms bounds for counts/weights/raw scores).

Semantics replicated from ops/scan.py (which is conformance-tested
against the serial oracle):
- NodeResourcesFit (noderesources/fit.go:230-303) incl. the
  zero-request pod-count-only fast path,
- LeastAllocated / BalancedAllocation / NodeAffinity / TaintToleration
  / Simon / ImageLocality / NodePreferAvoidPods scores with their
  normalizes (normalize_score.go:26-53, simon.go:75-100),
- InterPodAffinity filter/score (filtering.go:241-430, scoring.go) and
  PodTopologySpread hard filter + soft score (podtopologyspread/),
- first-max tie rule over feasible nodes (documented deviation shared
  with the XLA engine, scan.py:19-21),
- capacity-sweep masking: node_valid gates candidates, inactive pods
  commit nothing and report INACTIVE.

Float care: BalancedAllocation runs in f32 (inputs are <=24-bit scaled
integers, fractions exact, only the final truncation is float). The
soft-spread score needs f64 (cnt * log(sz+2)); TPU Pallas has no f64,
so it runs in double-single f32: log tables are precomputed in f64 on
the host and split into (hi, lo) f32 pairs with hi further Veltkamp-
split into 12-bit halves, partial products of the 8/9-bit-split count
are exact in f32, and 2Sum chains carry the compensation — ~2^-45
relative error against the XLA path's f64, far below the integer
truncation granularity. Conformance tests (tests/test_pallas_scan.py,
tests/test_pallas_terms.py) pin agreement with the XLA path.

Host<->device traffic is the latency floor on a relay-attached chip
(~0.1s per blocking transfer): plan arrays are device-cached per plan
(_device_args), inputs ship as one batched device_put, and the six
state outputs return stacked as a single fetch.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import NamedTuple, Optional

import numpy as np

LANES = 128
SUBLANES = 8
NEG = -(2**31) + 1
BIG = 2**31 - 1
MAX_SCORE = 100
INACTIVE = -2

# magnitude guards: every intermediate must stay inside int32
_MAX_SCALED = (2**31 - 1) // (MAX_SCORE + 1)


class TermsCfg(NamedTuple):
    """Static shape/slot configuration of the term machinery (part of
    the compiled-kernel cache key)."""

    t: int  # logical term rows (bit positions)
    td: int  # distinct topology tiles
    tc: int  # count-state rows (rows some consumer reads as counts)
    tp: int  # pref-state rows (rows with preferred weights)
    bp: int  # bitplane count = ceil(t / 32)
    a: int  # required-affinity group rows
    gn: int  # group count
    csn: int  # non-hostname soft instances (with dedicated count state)
    cd: int  # distinct hard-spread candidate tiles
    sqd: int  # distinct soft qualifying-node tiles
    hkd: int  # distinct has-all-soft-keys tiles
    rmax: int  # per-class relevant-row slots
    gmax: int  # per-class group-row slots
    hmax: int  # per-class hard slots
    smax: int  # per-class soft slots
    cmax: int  # per-class commit slots
    scmax: int  # per-class non-host soft commit slots
    vs: int  # non-hostname soft vocab size
    has_ipa: bool
    has_hard: bool
    has_soft: bool
    # node-axis streaming (term state in HBM, per-pod row gather):
    # the three fields below are 0/False on resident plans
    stream: bool = False
    kmax: int = 0  # per-class gather slots (max distinct rows fetched)
    wmax: int = 0  # per-class write-back slots (max dirty rows)
    srows: int = 0  # rows of the unified HBM state buffer


class TermsPlan(NamedTuple):
    """Term-machinery arrays for the fused kernel.

    Memory design (v3): count state is kept ONLY for rows some consumer
    reads as counts (score carries, hard/soft spread); rows tested only
    as `> 0` (required anti-affinity existence, own-anti targets) live
    in int32 BITPLANES — exact, because those states are monotone under
    the scan's commit-only updates. Static (R, C) tiles (topology
    values, spread candidates, qualifying nodes, has-keys masks, class
    tables) are deduplicated to their distinct rows with host-resolved
    SMEM indices. Commits are SPARSE: each class carries at most cmax
    (row, update) slots instead of a dense (T, R, C) broadcast. This
    removes the T-proportional VMEM and per-step commit cost that
    barred term-heavy batches at 10k nodes from the fused kernel."""

    cfg: TermsCfg
    # --- VMEM tiles -------------------------------------------------
    topo_dist: np.ndarray  # (Td, R, C) i32 distinct topo values, -1 = missing
    g_topo3: np.ndarray  # (A, R, C) group-row topo values (dense, A small)
    cand_dist: np.ndarray  # (Cd, R, C) distinct hard candidate masks
    sq_dist: np.ndarray  # (Sqd, R, C) distinct soft qualifying masks
    hk_dist: np.ndarray  # (Hkd, R, C) distinct has-all-soft-keys masks
    g_match_au: np.ndarray  # (A, Ur_p, 128) match_all[group_of_row] (commit)
    # --- state inits (ANY memory; DMAed into scratch) ----------------
    tgt0_c: np.ndarray  # (Tc, R, C) init counts for count rows
    pref0_p: np.ndarray  # (Tp, R, C) combined preferred init
    panti0_p: np.ndarray  # (Tp, R, C)
    antib0: np.ndarray  # (Bp, R, C) init anti>0 bitplanes
    tposb0: np.ndarray  # (Bp, R, C) init tgt>0 bitplanes
    group0: np.ndarray  # (A, R, C)
    gtot0: np.ndarray  # (A, 8, 128) per-group-row totals, replicated
    soft0_nh: np.ndarray  # (Csn, R, C) init counts, non-host soft instances
    # --- SMEM eval slot tables (U, Rmax/Gmax/Hmax/Smax) --------------
    e_cnt: np.ndarray  # (U, Rmax) tgt_cnt idx (-1 = no count read)
    e_pref: np.ndarray  # (U, Rmax) pref idx (-1 = no pref read; folds match)
    e_cpd: np.ndarray  # (U, Rmax) carry_aff_pref_w - carry_anti_pref_w
    e_antip: np.ndarray  # (U, Rmax) anti bitplane idx
    e_antib: np.ndarray  # (U, Rmax) anti bitmask (0 = no test; folds m)
    e_tposp: np.ndarray  # (U, Rmax) tgt>0 plane idx
    e_tposb: np.ndarray  # (U, Rmax) tgt>0 bitmask (0 = no test; folds canti)
    gid_u: np.ndarray  # (U,)
    self_ok_u: np.ndarray  # (U,) match_all[gid, u]
    slot_grows: np.ndarray  # (U, Gmax) A-row idx
    h_topo: np.ndarray  # (U, Hmax) topo_dist idx (-1 = inactive)
    h_cnt: np.ndarray  # (U, Hmax) tgt_cnt idx
    h_cand: np.ndarray  # (U, Hmax) cand_dist idx
    h_skew: np.ndarray  # (U, Hmax) max skew
    h_selfm: np.ndarray  # (U, Hmax) h_self[h, u]
    s_topo_i: np.ndarray  # (U, Smax) topo_dist idx (-1 = inactive)
    s_ishost: np.ndarray  # (U, Smax)
    s_cnt: np.ndarray  # (U, Smax) tgt_cnt idx (host rows; -1 otherwise)
    s_nh: np.ndarray  # (U, Smax) soft_nh idx (non-host; -1 otherwise)
    s_skewm1: np.ndarray  # (U, Smax) max_skew - 1 (prefolded)
    # --- SMEM commit slot tables (U, Cmax) ---------------------------
    c_topo: np.ndarray  # topo_dist idx (-1 = inactive slot)
    c_cnt: np.ndarray  # tgt_cnt idx (-1 = no count update)
    c_pref: np.ndarray  # pref idx (-1 = no pref update)
    c_m: np.ndarray  # match increment
    c_prefc: np.ndarray  # combined preferred commit increment
    c_pantic: np.ndarray  # anti-preferred commit increment
    c_antip: np.ndarray  # anti plane idx
    c_antib: np.ndarray  # anti bitmask (0 = no bit set)
    c_tposp: np.ndarray  # tgt>0 plane idx
    c_tposb: np.ndarray  # tgt>0 bitmask (0 = no bit set)
    # --- SMEM non-host soft commit slots (U, SCmax) ------------------
    sc_nh: np.ndarray  # soft_nh idx (-1 = inactive)
    sc_topo: np.ndarray  # topo_dist idx
    sc_q: np.ndarray  # sq_dist idx
    sc_m: np.ndarray  # match increment
    # f64 log-weight tables split for double-single arithmetic:
    # w = log(sz+2) computed in f64 on host; hi/lo f32 split, hi further
    # split into 12-bit halves h1+h2 for exact f32 products. (Wr, 128)
    # f32 VMEM tiles — the tables are node-count sized (sz ranges
    # 0..n+1), so SMEM placement capped term plans at ~50k nodes; the
    # kernel reads them by dynamic sublane row + lane mask (wval)
    w_hi: np.ndarray  # (Wr, 128) f32
    w_lo: np.ndarray
    w_h1: np.ndarray
    w_h2: np.ndarray


class PallasPlan(NamedTuple):
    """Host-side (numpy) arrays prepared for the kernel, all padded to
    (R, 128) node tiles / int32."""

    n: int  # true node count
    r: int  # padded rows (multiple of 8)
    u: int  # class count
    # [R, C] node vectors
    alloc_mcpu: np.ndarray
    alloc_mem_s: np.ndarray  # fit-scaled
    alloc_eph_s: np.ndarray
    alloc_pods: np.ndarray
    alloc_nzmem_s: np.ndarray  # nz-scaled (balanced/least denominator)
    # class tables, deduplicated to distinct rows; clsmap (SMEM) maps
    # class u -> row per table: 0=feas 1=simon 2=base 3=nodeaff 4=taint
    # 5=haskeys (terms) 6/7 spare
    static_feasible: np.ndarray  # (Fd, R, C)
    simon_raw: np.ndarray  # (Sd, R, C)
    nodeaff_raw: np.ndarray  # (Nad, R, C)
    taint_intol: np.ndarray  # (Ttd, R, C)
    base_score: np.ndarray  # (Bd, R, C) prefolded image*w_image + avoid*w_avoid
    clsmap: np.ndarray  # (8, Up) i32
    # [U, 8] class scalars: req_mcpu, req_mem_s, req_eph_s, nz_mcpu,
    # nz_mem_s, has_request, 0, 0
    class_scalars: np.ndarray
    # init state [R, C] i32 x6
    init_used_mcpu: np.ndarray
    init_used_mem_s: np.ndarray
    init_used_eph_s: np.ndarray
    init_nz_mcpu: np.ndarray
    init_nz_mem_s: np.ndarray
    init_pod_cnt: np.ndarray
    # scales to recover true units
    s_mem: int
    s_eph: int
    s_nzmem: int
    # weights (least, balanced, simon+gpushare, nodeaff, tainttol,
    # spread, ipa)
    w: tuple
    has_nodeaff: bool
    has_taint: bool
    has_pins: bool  # any pod arrives with spec.nodeName
    # inter-pod affinity / topology-spread machinery (None = batch has
    # no terms)
    terms: Optional[TermsPlan]
    # extended scalar resources (noderesources/fit.go scalar path):
    # s_n resource kinds, per-kind GCD-scaled int32
    s_n: int = 0
    alloc_scal: Optional[np.ndarray] = None  # (S, R, C) VMEM
    iscal0: Optional[np.ndarray] = None  # (S, R, C) init used (ANY)
    req_scal: Optional[np.ndarray] = None  # (U*S,) SMEM
    # hostPorts (NodePorts plugin): occupancy as pw int32 bitplanes
    # over the port vocab, conflict/want masks as per-class words
    pw: int = 0
    ports0: Optional[np.ndarray] = None  # (Pw, R, C) init planes (ANY)
    want_w: Optional[np.ndarray] = None  # (U*Pw,) SMEM
    confl_w: Optional[np.ndarray] = None  # (U*Pw,) SMEM
    # open-gpu-share: g_n devices per node, memory in GCD-scaled int32
    g_n: int = 0
    gpu_per_dev: Optional[np.ndarray] = None  # (R, C) VMEM
    gpu_cnt_n: Optional[np.ndarray] = None  # (R, C) VMEM device counts
    gpu_tot: Optional[np.ndarray] = None  # (R, C) VMEM capacity gpu-mem
    igpu0: Optional[np.ndarray] = None  # (G, R, C) init used (ANY)
    gpu_mem_u: Optional[np.ndarray] = None  # (U,) SMEM per-GPU request
    gpu_cnt_u: Optional[np.ndarray] = None  # (U,) SMEM device count
    # open-local storage: VG binpack + exclusive-device fit in GCD-
    # scaled int32; the f64 ScoreLVM/ScoreDevice values ride as host-
    # precomputed SMEM tables indexed by (class, distinct node storage
    # config, in-kernel assignment pattern) — see _build_storage
    store: Optional["StorePlan"] = None


def _pad_nodes(vec: np.ndarray, r: int, fill=0) -> np.ndarray:
    out = np.full(r * LANES, fill, dtype=np.int32)
    out[: vec.shape[0]] = vec
    return out.reshape(r, LANES)


def _pad_class_table(tab: np.ndarray, r: int, fill=0) -> np.ndarray:
    u, n = tab.shape
    out = np.full((u, r * LANES), fill, dtype=np.int32)
    out[:, :n] = tab
    return out.reshape(u, r, LANES)


def _gcd_scale(*arrays) -> int:
    vals = np.concatenate([np.asarray(a, dtype=np.int64).ravel() for a in arrays])
    vals = vals[vals > 0]
    if vals.size == 0:
        return 1
    return int(np.gcd.reduce(vals))


def _pad_lanes(vec: np.ndarray, dtype=np.int32, fill=0) -> np.ndarray:
    """1-D vector -> (8, Lp) tile, data in row 0."""
    lp = max(-(-vec.shape[0] // LANES) * LANES, LANES)
    out = np.full((SUBLANES, lp), fill, dtype=dtype)
    out[0, : vec.shape[0]] = vec
    return out


def _pad_table(tab: np.ndarray, fill=0, dtype=np.int32) -> np.ndarray:
    """(X, Y) table -> (Xp, Yp) with sublane/lane padding."""
    x, y = tab.shape
    xp = max(-(-x // SUBLANES) * SUBLANES, SUBLANES)
    yp = max(-(-y // LANES) * LANES, LANES)
    out = np.full((xp, yp), fill, dtype=dtype)
    out[:x, :y] = tab
    return out


def _pad_stack(tab: np.ndarray, r: int, fill=0) -> np.ndarray:
    """(X, N) node table -> (Xp, R, C) i32 node tiles."""
    x, n = tab.shape
    xp = max(x, 1)
    out = np.full((xp, r * LANES), fill, dtype=np.int32)
    out[:x, :n] = tab
    return out.reshape(xp, r, LANES)


# slot-count caps keep the kernel's static unrolled loops small; a batch
# beyond them falls back to the XLA scan
_MAX_SLOTS = dict(rmax=8, gmax=4, hmax=4, smax=4, a=8, gn=8, vs=32,
                  cmax=8, scmax=4, kmax=64, wmax=32)
# DMA semaphores the streamed-terms gather round-robins over: enough to
# keep a pod step's row fetches in flight concurrently without paying a
# serialized wait per row
_STREAM_NSEM = 8
_MAX_COUNT = 1 << 17  # cnt exact-split bound for the soft f64 emulation
_MAX_T = 512
# pod classes the term kernel accepts: class-column tables span
# ceil(U/128) sublane rows (col_u reads one dynamically); the cap
# bounds their VMEM rows and the U-strided SMEM slot tables
_MAX_U = 4 * LANES
# total int32 entries across the SMEM-destined term tables (~1MB SMEM
# per core; stay well under it so Mosaic never fails at compile time)
_MAX_SMEM_ENTRIES = 200_000


def _dedup_rows(tab: np.ndarray):
    """(X, N) -> (distinct (D, N), idx[X]) by row content."""
    if tab.shape[0] == 0:
        return tab.reshape(0, tab.shape[1]), np.zeros(0, dtype=np.int32)
    seen: dict = {}
    idx = np.zeros(tab.shape[0], dtype=np.int32)
    rows = []
    for i in range(tab.shape[0]):
        key = tab[i].tobytes()
        j = seen.get(key)
        if j is None:
            j = len(rows)
            seen[key] = j
            rows.append(tab[i])
        idx[i] = j
    return np.stack(rows), idx


# why the most recent build_plan returned None — the engine copies it
# into the `batch-kernel` trace note so a fast-path fallback is never
# silent (VERDICT r2 weak #3 observability)
_LAST_REJECT: Optional[str] = None


def last_reject() -> Optional[str]:
    return _LAST_REJECT


def fallback_reason() -> str:
    """The trace-note suffix for a plan==None outcome, read immediately
    after a build_plan call — shared by every consumer so no fast-path
    fallback is ever noted without its reason."""
    if not should_use():
        return "no TPU backend"
    return _LAST_REJECT or "rejected"


def _reject(reason: str) -> None:
    global _LAST_REJECT
    _LAST_REJECT = reason
    return None


def _pr_rows(p_total: int) -> int:
    """Rows of the dense (Pr, 128) placement packing — the one
    definition shared by run_scan_pallas (output allocation) and
    decode_scan_output (row split); they must agree or the split lands
    mid-block."""
    rows = max(-(-p_total // LANES), 1)
    return -(-rows // SUBLANES) * SUBLANES


def _bit(r: int) -> int:
    """int32 bitmask for logical row r (bit r & 31 of plane r >> 5)."""
    return int(np.uint32(1 << (r & 31)).view(np.int32))


def _pack_bitplanes(mask_tn: np.ndarray) -> np.ndarray:
    """(T, N) bool -> (ceil(T/32), N) int32 planes, row r at bit r&31
    of plane r>>5."""
    t_rows, n_cols = mask_tn.shape
    bp = max(-(-t_rows // 32), 1)
    planes = np.zeros((bp, n_cols), dtype=np.uint32)
    for r_i in range(t_rows):
        planes[r_i >> 5] |= mask_tn[r_i].astype(np.uint32) << np.uint32(r_i & 31)
    return planes.view(np.int32)


def _build_terms(batch, features, r: int, p_total: int, n: int):
    """Term-machinery plan (see TermsPlan docstring for the memory
    design) plus the per-class haskeys map, or None when out of the
    kernel's scope."""
    t = batch.terms
    has_ipa = bool(features.ipa)
    has_hard = bool(features.hard_spread)
    has_soft = bool(features.soft_spread)

    if t.t > _MAX_T or t.rmax > _MAX_SLOTS["rmax"] or t.gmax > _MAX_SLOTS["gmax"]:
        return _reject("terms: instance/slot count over kernel bounds")
    if t.hmax > _MAX_SLOTS["hmax"] or t.smax > _MAX_SLOTS["smax"]:
        return _reject("terms: spread slot count over kernel bounds")
    if t.a > _MAX_SLOTS["a"] or len(t.match_all) > _MAX_SLOTS["gn"]:
        return _reject("terms: affinity-group count over kernel bounds")
    if batch.u > _MAX_U:
        # class-indexed lane tables span ceil(U/128) sublane rows; the
        # cap bounds their VMEM rows and the SMEM slot tables
        return _reject(f"terms: {batch.u} pod classes > {_MAX_U}-class scope")

    from .encode import _value_to_node_space
    from .terms import combined_pref_carry, combined_pref_init

    tv = t.topo_val
    u_n = batch.u
    carry_prefc = combined_pref_carry(t)
    pref_init = combined_pref_init(t)

    # int32 exactness bounds (documented in the module docstring)
    tgt0_all = _value_to_node_space(t.init_tgt, tv)
    pref0_all = _value_to_node_space(pref_init, tv)
    panti0_all = _value_to_node_space(t.init_own_anti_pref_w, tv)
    cnt_max = int(tgt0_all.max(initial=0)) + p_total
    pref_max = int(
        max(pref0_all.max(initial=0), panti0_all.max(initial=0))
    ) + p_total * int(
        max(np.abs(carry_prefc).max(initial=0), np.abs(t.carry_anti_pref_w).max(initial=0), 1)
    )
    ipa_raw_max = t.rmax * (
        int(
            (np.abs(t.carry_aff_pref_w) + np.abs(t.carry_anti_pref_w)).max(initial=0)
        )
        * cnt_max
        + 2 * pref_max
    )
    if cnt_max > _MAX_COUNT or pref_max > 2**30 or ipa_raw_max > 2**23:
        return _reject("terms: count/weight magnitudes exceed int32 exactness")

    # soft vocab for the distinct-domain loop
    vs = 1
    if has_soft:
        nonhost = ~t.s_is_host
        real = (t.cls_s_rows >= 0).any()
        if real and nonhost.any():
            mx = int(tv[t.s_row][nonhost].max(initial=-1))
            vs = max(mx + 1, 1)
        if vs > _MAX_SLOTS["vs"]:
            return _reject("terms: soft-spread domain vocab over kernel bound")

    # -- row storage classification ----------------------------------
    # count rows: some consumer reads them as COUNTS — score carries
    # (cpd != 0), hard-spread instances, host-topology soft instances.
    # pref rows: any preferred-weight data (init or carry).
    # Everything else is tested only as `> 0` and lives in bitplanes.
    cpd_tu = (t.carry_aff_pref_w - t.carry_anti_pref_w).astype(np.int64)
    cnt_need = np.zeros(t.t, dtype=bool)
    cnt_need[np.nonzero((cpd_tu != 0).any(axis=1))[0]] = True
    if has_hard:
        used_h = np.unique(t.cls_h_rows[t.cls_h_rows >= 0])
        cnt_need[t.h_row[used_h]] = True
    if has_soft:
        used_s = np.unique(t.cls_s_rows[t.cls_s_rows >= 0])
        host_s = used_s[t.s_is_host[used_s]]
        cnt_need[t.s_row[host_s]] = True
    pref_need = (
        (pref_init != 0).any(axis=1)
        | (t.init_own_anti_pref_w != 0).any(axis=1)
        | (carry_prefc != 0).any(axis=1)
        | (t.carry_anti_pref_w != 0).any(axis=1)
    )
    cnt_idx = np.full(t.t, -1, dtype=np.int32)
    cnt_rows = np.nonzero(cnt_need)[0]
    cnt_idx[cnt_rows] = np.arange(len(cnt_rows))
    pref_idx = np.full(t.t, -1, dtype=np.int32)
    pref_rows = np.nonzero(pref_need)[0]
    pref_idx[pref_rows] = np.arange(len(pref_rows))
    tc_n = max(len(cnt_rows), 1)
    tp_n = max(len(pref_rows), 1)
    bp_n = max(-(-t.t // 32), 1)

    # early VMEM pre-gate: the scratch state alone is a lower bound on
    # the final tile count (build_plan re-checks exactly); rejecting
    # here skips the O(U*T) slot-table construction for hopeless plans.
    # Only binding when streaming is disabled — a streamed plan keeps
    # this state in HBM, so over-budget scratch is exactly the case
    # build_plan's streaming rewrite exists for.
    scratch_tiles = tc_n + 2 * tp_n + 2 * bp_n + t.a
    if scratch_tiles * r * LANES * 4 > 13 * 2**20 and STREAM_FORCE is False:
        return _reject("terms: scratch state exceeds VMEM budget")

    # -- static dedup --------------------------------------------------
    topo_dist, topo_idx = _dedup_rows(tv)
    td_n = topo_dist.shape[0]
    cand_dist, cand_idx = _dedup_rows(t.h_cand_nodes.astype(np.int32))
    cd_n = max(cand_dist.shape[0], 1)
    hk_dist, hk_map = _dedup_rows(t.cls_s_haskeys.astype(np.int32))
    hkd_n = max(hk_dist.shape[0], 1)

    # -- non-host soft instances --------------------------------------
    nh_mask = ~t.s_is_host
    nh_insts = np.nonzero(nh_mask)[0]
    nh_idx = np.full(t.cs, -1, dtype=np.int32)
    nh_idx[nh_insts] = np.arange(len(nh_insts))
    csn_n = max(len(nh_insts), 1)
    if len(nh_insts):
        sq_dist, sq_idx_nh = _dedup_rows(t.s_q[nh_insts].astype(np.int32))
        sq_idx = np.full(t.cs, -1, dtype=np.int32)
        sq_idx[nh_insts] = sq_idx_nh
        soft0_nh = _value_to_node_space(
            t.init_soft_counts[nh_insts], tv[t.s_row[nh_insts]]
        )
    else:
        sq_dist = np.zeros((1, n), dtype=np.int32)
        sq_idx = np.full(t.cs, -1, dtype=np.int32)
        soft0_nh = np.zeros((1, n), dtype=np.int64)
    sqd_n = max(sq_dist.shape[0], 1)

    # -- eval slot tables (resolved storage indices) -------------------
    rmax = t.rmax
    e_cnt = np.full((u_n, rmax), -1, dtype=np.int32)
    e_pref = np.full((u_n, rmax), -1, dtype=np.int32)
    e_cpd = np.zeros((u_n, rmax), dtype=np.int64)
    e_antip = np.zeros((u_n, rmax), dtype=np.int32)
    e_antib = np.zeros((u_n, rmax), dtype=np.int32)
    e_tposp = np.zeros((u_n, rmax), dtype=np.int32)
    e_tposb = np.zeros((u_n, rmax), dtype=np.int32)
    for u_i in range(u_n):
        for k in range(rmax):
            row = int(t.cls_rows[u_i, k])
            if row < 0:
                continue
            cpd = int(cpd_tu[row, u_i])
            e_cpd[u_i, k] = cpd
            if cpd != 0:
                e_cnt[u_i, k] = cnt_idx[row]
            m_k = bool(t.match[row, u_i])
            if m_k and pref_idx[row] >= 0:
                e_pref[u_i, k] = pref_idx[row]
            e_antip[u_i, k] = row >> 5
            e_tposp[u_i, k] = row >> 5
            if m_k:
                e_antib[u_i, k] = _bit(row)
            if int(t.carry_anti_req[row, u_i]) > 0:
                e_tposb[u_i, k] = _bit(row)

    # -- commit slot tables --------------------------------------------
    # bit updates are emitted only for rows some class actually tests:
    # fail_exist tests anti bits on matched rows, fail_own tests tgt>0
    # bits on rows the class carries required anti-affinity for
    tested_exist = t.match.any(axis=1)
    tested_own = (t.carry_anti_req > 0).any(axis=1)
    commit_slots: list = [[] for _ in range(u_n)]
    for u_i in range(u_n):
        for row in range(t.t):
            m_i = int(t.match[row, u_i])
            prefc = int(carry_prefc[row, u_i])
            pantic = int(t.carry_anti_pref_w[row, u_i])
            canti = int(t.carry_anti_req[row, u_i])
            upd_cnt = bool(m_i) and cnt_idx[row] >= 0
            upd_pref = (prefc != 0 or pantic != 0) and pref_idx[row] >= 0
            upd_anti = canti > 0 and bool(tested_exist[row])
            upd_tpos = bool(m_i) and bool(tested_own[row])
            if not (upd_cnt or upd_pref or upd_anti or upd_tpos):
                continue
            commit_slots[u_i].append(
                dict(
                    topo=int(topo_idx[row]),
                    cnt=int(cnt_idx[row]) if upd_cnt else -1,
                    pref=int(pref_idx[row]) if upd_pref else -1,
                    m=m_i,
                    prefc=prefc,
                    pantic=pantic,
                    antip=row >> 5,
                    antib=_bit(row) if upd_anti else 0,
                    tposp=row >> 5,
                    tposb=_bit(row) if upd_tpos else 0,
                )
            )
    cmax = max((len(s) for s in commit_slots), default=0)
    cmax = max(cmax, 1)
    if cmax > _MAX_SLOTS["cmax"]:
        return _reject("terms: per-class commit slots over kernel bound")
    c_topo = np.full((u_n, cmax), -1, dtype=np.int32)
    c_cnt = np.full((u_n, cmax), -1, dtype=np.int32)
    c_pref = np.full((u_n, cmax), -1, dtype=np.int32)
    c_m = np.zeros((u_n, cmax), dtype=np.int32)
    c_prefc = np.zeros((u_n, cmax), dtype=np.int32)
    c_pantic = np.zeros((u_n, cmax), dtype=np.int32)
    c_antip = np.zeros((u_n, cmax), dtype=np.int32)
    c_antib = np.zeros((u_n, cmax), dtype=np.int32)
    c_tposp = np.zeros((u_n, cmax), dtype=np.int32)
    c_tposb = np.zeros((u_n, cmax), dtype=np.int32)
    for u_i, slots in enumerate(commit_slots):
        for j, s in enumerate(slots):
            c_topo[u_i, j] = s["topo"]
            c_cnt[u_i, j] = s["cnt"]
            c_pref[u_i, j] = s["pref"]
            c_m[u_i, j] = s["m"]
            c_prefc[u_i, j] = s["prefc"]
            c_pantic[u_i, j] = s["pantic"]
            c_antip[u_i, j] = s["antip"]
            c_antib[u_i, j] = s["antib"]
            c_tposp[u_i, j] = s["tposp"]
            c_tposb[u_i, j] = s["tposb"]

    # non-host soft commit slots
    sc_slots: list = [[] for _ in range(u_n)]
    if has_soft and len(nh_insts):
        for u_i in range(u_n):
            for inst in nh_insts:
                row = int(t.s_row[inst])
                if not t.match[row, u_i]:
                    continue
                sc_slots[u_i].append(
                    dict(nh=int(nh_idx[inst]), topo=int(topo_idx[row]),
                         q=int(sq_idx[inst]), m=1)
                )
    scmax = max((len(s) for s in sc_slots), default=0)
    scmax = max(scmax, 1)
    if scmax > _MAX_SLOTS["scmax"]:
        return _reject("terms: per-class score slots over kernel bound")
    sc_nh = np.full((u_n, scmax), -1, dtype=np.int32)
    sc_topo = np.zeros((u_n, scmax), dtype=np.int32)
    sc_q = np.zeros((u_n, scmax), dtype=np.int32)
    sc_m = np.zeros((u_n, scmax), dtype=np.int32)
    for u_i, slots in enumerate(sc_slots):
        for j, s in enumerate(slots):
            sc_nh[u_i, j] = s["nh"]
            sc_topo[u_i, j] = s["topo"]
            sc_q[u_i, j] = s["q"]
            sc_m[u_i, j] = s["m"]

    # -- hard / soft eval tables (resolved) ---------------------------
    hmax, smax = t.hmax, t.smax
    h_topo = np.full((u_n, hmax), -1, dtype=np.int32)
    h_cnt = np.zeros((u_n, hmax), dtype=np.int32)
    h_cand = np.zeros((u_n, hmax), dtype=np.int32)
    h_skew = np.zeros((u_n, hmax), dtype=np.int32)
    h_selfm = np.zeros((u_n, hmax), dtype=np.int32)
    for u_i in range(u_n):
        for k in range(hmax):
            inst = int(t.cls_h_rows[u_i, k])
            if inst < 0:
                continue
            row = int(t.h_row[inst])
            h_topo[u_i, k] = topo_idx[row]
            h_cnt[u_i, k] = cnt_idx[row]
            h_cand[u_i, k] = cand_idx[inst]
            h_skew[u_i, k] = int(t.h_max_skew[inst])
            h_selfm[u_i, k] = int(t.h_self[inst, u_i])
    s_topo_i = np.full((u_n, smax), -1, dtype=np.int32)
    s_ishost = np.zeros((u_n, smax), dtype=np.int32)
    s_cnt = np.full((u_n, smax), -1, dtype=np.int32)
    s_nh = np.full((u_n, smax), -1, dtype=np.int32)
    s_skewm1 = np.zeros((u_n, smax), dtype=np.int32)
    for u_i in range(u_n):
        for k in range(smax):
            inst = int(t.cls_s_rows[u_i, k])
            if inst < 0:
                continue
            row = int(t.s_row[inst])
            s_topo_i[u_i, k] = topo_idx[row]
            s_ishost[u_i, k] = int(t.s_is_host[inst])
            if t.s_is_host[inst]:
                s_cnt[u_i, k] = cnt_idx[row]
            else:
                s_nh[u_i, k] = nh_idx[inst]
            s_skewm1[u_i, k] = int(t.s_max_skew[inst]) - 1

    # -- state inits (node space, trimmed to stored rows) --------------
    tgt0_c = tgt0_all[cnt_rows] if len(cnt_rows) else np.zeros((1, n), np.int64)
    pref0_p = pref0_all[pref_rows] if len(pref_rows) else np.zeros((1, n), np.int64)
    panti0_p = panti0_all[pref_rows] if len(pref_rows) else np.zeros((1, n), np.int64)
    anti0_all = _value_to_node_space(t.init_own_anti_req, tv)
    antib0 = _pack_bitplanes(anti0_all > 0)
    tposb0 = _pack_bitplanes(tgt0_all > 0)
    group0 = _value_to_node_space(t.init_group_counts, tv[t.group_rows])

    # f64 log weights, double-single split (sz ranges over 0..n+1) —
    # node-count sized, so they live as (Wr, 128) VMEM tiles read by
    # dynamic sublane row (SMEM placement capped plans at ~50k nodes);
    # soft-free batches carry a 1-row dummy
    wn = n + 2 if has_soft else 1
    szv = np.arange(wn, dtype=np.float64)
    w64 = np.log(szv + 2.0)
    w_hi = w64.astype(np.float32)
    w_lo = (w64 - w_hi.astype(np.float64)).astype(np.float32)
    # 12-bit split of w_hi for exact f32 products with cnt <= 2^17
    scale = np.float32(2**12 + 1)
    tmp = w_hi * scale
    w_h1 = (tmp - (tmp - w_hi)).astype(np.float32)  # Veltkamp split
    w_h2 = (w_hi - w_h1).astype(np.float32)

    def wpack(v: np.ndarray) -> np.ndarray:
        r_w = -(-v.shape[0] // LANES)
        r_w = -(-r_w // SUBLANES) * SUBLANES
        out = np.zeros(r_w * LANES, dtype=np.float32)
        out[: v.shape[0]] = v
        return out.reshape(r_w, LANES)

    # class-column tables: ceil(U/128) sublane rows of 128 lanes each,
    # padded to the (8, 128) tile grain; the kernel's col_u selects row
    # u//128 dynamically and lane u%128 by mask
    u_rows = -(-max(u_n, 1) // LANES)
    u_rows_p = -(-u_rows // SUBLANES) * SUBLANES

    def tab_u(m, dtype=np.int32):
        """(X, U) -> (X, Ur_p, 128) class-column tile."""
        x = max(m.shape[0], 1)
        out = np.zeros((x, u_rows_p * LANES), dtype=dtype)
        out[: m.shape[0], : m.shape[1]] = m
        return out.reshape(x, u_rows_p, LANES)

    gid_u = t.cls_group_id.astype(np.int32)
    uu = np.arange(u_n)
    self_ok_u = np.where(
        gid_u >= 0, t.match_all[np.maximum(gid_u, 0), uu], False
    )

    cfg = TermsCfg(
        t=t.t, td=td_n, tc=tc_n, tp=tp_n, bp=bp_n, a=t.a,
        gn=len(t.match_all), csn=csn_n, cd=cd_n, sqd=sqd_n, hkd=hkd_n,
        rmax=rmax, gmax=t.gmax, hmax=hmax, smax=smax, cmax=cmax,
        scmax=scmax, vs=vs,
        has_ipa=has_ipa, has_hard=has_hard, has_soft=has_soft,
    )
    plan = TermsPlan(
        cfg=cfg,
        topo_dist=_pad_stack(topo_dist, r, fill=-1),
        g_topo3=_pad_stack(tv[t.group_rows], r, fill=-1),
        cand_dist=_pad_stack(cand_dist, r),
        sq_dist=_pad_stack(sq_dist, r),
        hk_dist=_pad_stack(hk_dist, r),
        g_match_au=tab_u(t.match_all[t.group_of_row].astype(np.int32)),
        tgt0_c=_pad_stack(tgt0_c, r),
        pref0_p=_pad_stack(pref0_p, r),
        panti0_p=_pad_stack(panti0_p, r),
        antib0=_pad_stack(antib0, r),
        tposb0=_pad_stack(tposb0, r),
        group0=_pad_stack(group0, r),
        gtot0=np.ascontiguousarray(
            np.broadcast_to(
                t.init_group_counts.sum(axis=1).astype(np.int32)[:, None, None],
                (max(t.a, 1), SUBLANES, LANES),
            )
        ),
        soft0_nh=_pad_stack(soft0_nh, r),
        # (U, slot) tables ship FLATTENED 1-D: SMEM pads every row of a
        # 2-D array to a full 512B lane-row, so (100, 3) would cost
        # 51KB of the ~1MB SMEM; 1-D costs its actual bytes
        e_cnt=e_cnt.reshape(-1), e_pref=e_pref.reshape(-1),
        e_cpd=e_cpd.astype(np.int32).reshape(-1),
        e_antip=e_antip.reshape(-1), e_antib=e_antib.reshape(-1),
        e_tposp=e_tposp.reshape(-1), e_tposb=e_tposb.reshape(-1),
        gid_u=gid_u,
        self_ok_u=self_ok_u.astype(np.int32),
        slot_grows=t.cls_group_rows.astype(np.int32).reshape(-1),
        h_topo=h_topo.reshape(-1), h_cnt=h_cnt.reshape(-1),
        h_cand=h_cand.reshape(-1), h_skew=h_skew.reshape(-1),
        h_selfm=h_selfm.reshape(-1),
        s_topo_i=s_topo_i.reshape(-1), s_ishost=s_ishost.reshape(-1),
        s_cnt=s_cnt.reshape(-1), s_nh=s_nh.reshape(-1),
        s_skewm1=s_skewm1.reshape(-1),
        c_topo=c_topo.reshape(-1), c_cnt=c_cnt.reshape(-1),
        c_pref=c_pref.reshape(-1), c_m=c_m.reshape(-1),
        c_prefc=c_prefc.reshape(-1), c_pantic=c_pantic.reshape(-1),
        c_antip=c_antip.reshape(-1), c_antib=c_antib.reshape(-1),
        c_tposp=c_tposp.reshape(-1), c_tposb=c_tposb.reshape(-1),
        sc_nh=sc_nh.reshape(-1), sc_topo=sc_topo.reshape(-1),
        sc_q=sc_q.reshape(-1), sc_m=sc_m.reshape(-1),
        w_hi=wpack(w_hi),
        w_lo=wpack(w_lo),
        w_h1=wpack(w_h1),
        w_h2=wpack(w_h2),
    )
    smem_entries = sum(
        getattr(plan, name).size
        for name, space in _TERM_FIELDS
        if space == "smem"
    )
    if smem_entries > _MAX_SMEM_ENTRIES:
        # reject here rather than let Mosaic fail at compile time —
        # the caller falls back to the XLA scan
        return _reject(
            f"terms: {smem_entries} SMEM slot-table entries over budget"
        )
    return plan, hk_map


# the term-machinery kernel beats the XLA scan on term-heavy batches
# (affinity-stress: 0.20s vs 0.26s, and the gap widens off the relay's
# ~0.1s/transfer latency floor); on by default, opt out for debugging
TERMS_DEFAULT_ENABLE = True

# streamed-terms routing: None = auto (stream only when the resident
# term state exceeds the VMEM budget), True = force streaming for any
# terms batch (conformance tests / bench A/B), False = never stream
# (resident-or-XLA, the r4 behavior)
STREAM_FORCE: Optional[bool] = None


def build_plan(cluster, batch, dyn, features, weights=None,
               allow_terms: Optional[bool] = None) -> Optional[PallasPlan]:
    """Build a kernel plan from the (numpy) ClusterStatic + PodBatch +
    DynamicState, or None when the batch is outside the fast path's
    scope."""
    if features.custom:
        return _reject("custom-plugin machinery (XLA scan carries it)")
    if getattr(features, "sample", False):
        return _reject(
            "sample-mode selectHost (XLA scan carries the Go RNG)"
        )
    if features.gpu and features.pins:
        # forced gpu commits would need device allocation outside the
        # feasibility gate; rare combination, XLA scan carries it
        return _reject("gpu batch with nodeName pins")
    if allow_terms is None:
        allow_terms = TERMS_DEFAULT_ENABLE
    if not allow_terms and (
        features.ipa or features.hard_spread or features.soft_spread
    ):
        return _reject("terms disabled (allow_terms=False)")

    from ..scheduler.schedconfig import DEFAULT_SCORE_WEIGHTS, ScoreWeights

    w = ScoreWeights(*weights) if weights is not None else DEFAULT_SCORE_WEIGHTS

    a = np.asarray
    alloc_mcpu = a(cluster.alloc_mcpu, dtype=np.int64)
    alloc_mem = a(cluster.alloc_mem, dtype=np.int64)
    alloc_eph = a(cluster.alloc_eph, dtype=np.int64)
    alloc_pods = a(cluster.alloc_pods, dtype=np.int64)
    req_mcpu = a(batch.req_mcpu, dtype=np.int64)
    req_mem = a(batch.req_mem, dtype=np.int64)
    req_eph = a(batch.req_eph, dtype=np.int64)
    nz_mcpu = a(batch.nz_mcpu, dtype=np.int64)
    nz_mem = a(batch.nz_mem, dtype=np.int64)
    init_used_mcpu = a(dyn.used_mcpu, dtype=np.int64)
    init_used_mem = a(dyn.used_mem, dtype=np.int64)
    init_used_eph = a(dyn.used_eph, dtype=np.int64)
    init_nz_mcpu = a(dyn.nz_mcpu, dtype=np.int64)
    init_nz_mem = a(dyn.nz_mem, dtype=np.int64)
    init_pod_cnt = a(dyn.pod_cnt, dtype=np.int64)

    s_mem = _gcd_scale(alloc_mem, req_mem, init_used_mem)
    s_eph = _gcd_scale(alloc_eph, req_eph, init_used_eph)
    s_nzmem = _gcd_scale(alloc_mem, nz_mem, init_nz_mem)

    simon_raw = a(batch.simon_raw, dtype=np.int64)
    nodeaff_raw = a(batch.nodeaff_raw, dtype=np.int64)
    taint_intol = a(batch.taint_intol, dtype=np.int64)
    image_score = a(batch.image_score, dtype=np.int64)
    avoid_score = a(batch.avoid_score, dtype=np.int64)
    base_score = image_score * int(w.image) + avoid_score * int(w.avoid)

    # int32 exactness guards
    checks = [
        alloc_mcpu.max(initial=0) <= _MAX_SCALED,
        (alloc_mem // s_mem).max(initial=0) <= _MAX_SCALED,
        (alloc_eph // s_eph).max(initial=0) <= _MAX_SCALED,
        (alloc_mem // s_nzmem).max(initial=0) <= _MAX_SCALED,
        alloc_pods.max(initial=0) <= _MAX_SCALED,
        simon_raw.max(initial=0) <= _MAX_SCALED,
        simon_raw.min(initial=0) >= 0,
        nodeaff_raw.max(initial=0) <= _MAX_SCALED,
        nodeaff_raw.min(initial=0) >= 0,
        taint_intol.max(initial=0) <= _MAX_SCALED,
        taint_intol.min(initial=0) >= 0,
        np.abs(base_score).max(initial=0) <= 2**24,
        # balanced runs in f32: its scaled inputs must be f32-exact
        (alloc_mem // s_nzmem).max(initial=0) < 2**24,
        alloc_mcpu.max(initial=0) < 2**24,
    ]
    if not all(bool(c) for c in checks):
        return _reject("resource/score magnitudes exceed int32/f32 exactness")

    n = alloc_mcpu.shape[0]
    u = req_mcpu.shape[0]
    r = -(-n // LANES)
    r = -(-r // SUBLANES) * SUBLANES  # row count multiple of 8

    if features.pins:
        # forced pin commits bypass the feasibility gate, so per-node
        # usage is no longer bounded by alloc: bound the worst case
        # (all pinned pods on one node) against the f32/int32 guards
        pin_mask = a(batch.pinned_node) >= 0
        pin_cls = a(batch.class_of_pod)[pin_mask]
        pin_c = int(req_mcpu[pin_cls].sum())
        pin_m = int((req_mem // s_mem)[pin_cls].sum())
        pin_nzc = int(nz_mcpu[pin_cls].sum())
        pin_nzm = int((nz_mem // s_nzmem)[pin_cls].sum())
        worst = max(
            int(init_used_mcpu.max(initial=0)) + pin_c,
            int((init_used_mem // s_mem).max(initial=0)) + pin_m,
            int(init_nz_mcpu.max(initial=0)) + pin_nzc,
            int((init_nz_mem // s_nzmem).max(initial=0)) + pin_nzm,
        )
        if worst >= 2**24:
            return _reject("pinned-pod worst-case usage exceeds f32 exactness")

    # extended scalar resources: per-kind GCD scaling + int32 guards
    s_n = 0
    alloc_scal = iscal0 = req_scal_t = None
    if features.scalars:
        scal_alloc = a(cluster.scalar_alloc, dtype=np.int64)
        req_scalar = a(batch.req_scalar, dtype=np.int64)
        used_scal0 = a(dyn.used_scalar, dtype=np.int64)
        s_n = scal_alloc.shape[0]
        if s_n > 8:
            return _reject(f"{s_n} scalar resource kinds > 8-kind scope")
        scales = []
        for s_i in range(s_n):
            sc = _gcd_scale(scal_alloc[s_i], req_scalar[:, s_i], used_scal0[s_i])
            scales.append(sc)
        scal_s = np.stack([scal_alloc[s_i] // scales[s_i] for s_i in range(s_n)])
        req_s = np.stack(
            [req_scalar[:, s_i] // scales[s_i] for s_i in range(s_n)], axis=1
        )
        used_s0 = np.stack([used_scal0[s_i] // scales[s_i] for s_i in range(s_n)])
        worst_scal = used_s0.max(initial=0)
        if features.pins:
            pin_mask = a(batch.pinned_node) >= 0
            pin_cls = a(batch.class_of_pod)[pin_mask]
            worst_scal = worst_scal + req_s[pin_cls].sum(axis=0).max(initial=0)
        if (
            scal_s.max(initial=0) > _MAX_SCALED
            or req_s.max(initial=0) > _MAX_SCALED
            or worst_scal >= 2**30
        ):
            return _reject("scalar-resource magnitudes exceed int32 exactness")
        alloc_scal = _pad_stack(scal_s, r)
        iscal0 = _pad_stack(used_s0, r)
        req_scal_t = req_s.astype(np.int32).reshape(-1)  # (U*S,) row-major

    # open-gpu-share: per-device memory state (G tiles), tightest-fit /
    # two-pointer allocation mirrored from ops/scan.py _gpu_allocate
    g_n = 0
    gpu_per_dev_s = gpu_cnt_nodes = gpu_tot_s = igpu0 = None
    gpu_mem_u = gpu_cnt_u = None
    if features.gpu:
        gused0_raw = a(dyn.gpu_used, dtype=np.int64)
        # encode pads the device axis to >= 1 even for gpu-free nodes;
        # per_dev = 0 there makes every device unfit, which is correct
        g_n = int(gused0_raw.shape[1])
        if g_n > 8:
            return _reject(f"{g_n} GPU devices per node > 8-device scope")
        gper = a(cluster.gpu_per_dev, dtype=np.int64)
        gcnt = a(cluster.gpu_count, dtype=np.int64)
        gtot = a(cluster.gpu_total, dtype=np.int64)
        bmem = a(batch.gpu_mem, dtype=np.int64)
        s_gpu = _gcd_scale(gper, bmem, gused0_raw)
        gper_s = gper // s_gpu
        gtot_f = gtot // s_gpu  # exact for >= vs scaled bmem (bmem % s == 0)
        bmem_s = bmem // s_gpu
        gused0_s = gused0_raw // s_gpu
        if (
            gper_s.max(initial=0) > _MAX_SCALED
            or gtot_f.max(initial=0) > _MAX_SCALED
            or bmem_s.max(initial=0) > _MAX_SCALED
        ):
            return _reject("gpu-memory magnitudes exceed int32 exactness")
        gpu_per_dev_s = _pad_nodes(gper_s, r)
        gpu_cnt_nodes = _pad_nodes(gcnt, r)
        gpu_tot_s = _pad_nodes(gtot_f, r)
        igpu0 = _pad_stack(np.ascontiguousarray(gused0_s.T), r)
        gpu_mem_u = bmem_s.astype(np.int32)
        gpu_cnt_u = a(batch.gpu_cnt, dtype=np.int64).astype(np.int32)

    # hostPorts: occupancy bitplanes over the port vocab
    pw = 0
    ports0 = want_w = confl_w = None
    if features.ports:
        want_p = a(batch.want_ports).astype(bool)
        confl_p = a(batch.conflict_ports).astype(bool)
        pt = want_p.shape[1]
        if pt > 8 * 32:
            return _reject(f"{pt} distinct host ports > 256-port scope")
        pw = max(-(-pt // 32), 1)
        ports0 = _pad_stack(_pack_bitplanes(a(dyn.ports_used).astype(bool).T), r)

        def pack_words(tab):  # (U, Pt) bool -> (U*Pw,) i32 words
            # same bit layout as the node-space planes (_pack_bitplanes:
            # port p at bit p&31 of word p>>5), transposed to per-class
            words = _pack_bitplanes(tab.T).T  # (U, Pw)
            if words.shape[1] < pw:  # pad classes with no ports
                words = np.pad(words, ((0, 0), (0, pw - words.shape[1])))
            return np.ascontiguousarray(words).reshape(-1)

        want_w = pack_words(want_p)
        confl_w = pack_words(confl_p)

    store = None
    if features.storage:
        store = _build_storage(cluster, batch, dyn, r)
        if store is None:
            return None

    terms = None
    hk_map = None
    if features.ipa or features.hard_spread or features.soft_spread:
        p_total = int(a(batch.class_of_pod).shape[0])
        built = _build_terms(batch, features, r, p_total, n)
        if built is None:
            return None
        terms, hk_map = built

    class_scalars = np.zeros((u, 8), dtype=np.int32)
    class_scalars[:, 0] = req_mcpu
    class_scalars[:, 1] = req_mem // s_mem
    class_scalars[:, 2] = req_eph // s_eph
    class_scalars[:, 3] = nz_mcpu
    class_scalars[:, 4] = nz_mem // s_nzmem
    class_scalars[:, 5] = a(batch.has_request).astype(np.int32)

    # class tables deduplicated to distinct rows; clsmap resolves class
    # u -> row per table (big-U batches often share a handful of
    # distinct node patterns across hundreds of classes)
    feas_d, feas_i = _dedup_rows(a(batch.static_feasible).astype(np.int32))
    simon_d, simon_i = _dedup_rows(simon_raw)
    base_d, base_i = _dedup_rows(base_score)
    na_d, na_i = _dedup_rows(nodeaff_raw)
    tt_d, tt_i = _dedup_rows(taint_intol)
    clsmap = np.zeros((8, max(u, 1)), dtype=np.int32)
    clsmap[0, :u] = feas_i
    clsmap[1, :u] = simon_i
    clsmap[2, :u] = base_i
    clsmap[3, :u] = na_i
    clsmap[4, :u] = tt_i
    if hk_map is not None:
        clsmap[5, :u] = hk_map
    clsmap = clsmap.reshape(-1)  # 1-D for SMEM (see TermsPlan note)

    plan = PallasPlan(
        n=n,
        r=r,
        u=u,
        alloc_mcpu=_pad_nodes(alloc_mcpu, r),
        alloc_mem_s=_pad_nodes(alloc_mem // s_mem, r),
        alloc_eph_s=_pad_nodes(alloc_eph // s_eph, r),
        alloc_pods=_pad_nodes(alloc_pods, r),
        alloc_nzmem_s=_pad_nodes(alloc_mem // s_nzmem, r),
        static_feasible=_pad_class_table(feas_d, r),
        simon_raw=_pad_class_table(simon_d, r),
        nodeaff_raw=_pad_class_table(na_d, r),
        taint_intol=_pad_class_table(tt_d, r),
        base_score=_pad_class_table(base_d, r),
        clsmap=clsmap,
        class_scalars=class_scalars,
        init_used_mcpu=_pad_nodes(init_used_mcpu, r),
        init_used_mem_s=_pad_nodes(init_used_mem // s_mem, r),
        init_used_eph_s=_pad_nodes(init_used_eph // s_eph, r),
        init_nz_mcpu=_pad_nodes(init_nz_mcpu, r),
        init_nz_mem_s=_pad_nodes(init_nz_mem // s_nzmem, r),
        init_pod_cnt=_pad_nodes(init_pod_cnt, r),
        s_mem=s_mem,
        s_eph=s_eph,
        s_nzmem=s_nzmem,
        w=(int(w.least), int(w.balanced), int(w.simon) + int(w.gpushare),
           int(w.nodeaff), int(w.tainttol), int(w.spread), int(w.ipa),
           int(w.openlocal)),
        has_nodeaff=bool(nodeaff_raw.any()),
        has_taint=bool(taint_intol.any()),
        has_pins=bool(features.pins),
        terms=terms,
        s_n=s_n,
        alloc_scal=alloc_scal,
        iscal0=iscal0,
        req_scal=req_scal_t,
        pw=pw,
        ports0=ports0,
        want_w=want_w,
        confl_w=confl_w,
        g_n=g_n,
        gpu_per_dev=gpu_per_dev_s,
        gpu_cnt_n=gpu_cnt_nodes,
        gpu_tot=gpu_tot_s,
        igpu0=igpu0,
        gpu_mem_u=gpu_mem_u,
        gpu_cnt_u=gpu_cnt_u,
        store=store,
    )

    # VMEM budget (~16MB/core): count the PERSISTENT (R, C) tiles
    # directly from the plan arrays. State-init INPUTS live in ANY
    # (HBM) and are DMAed into scratch, so scratch counts once.
    base_tiles = (
        5  # alloc vectors
        + 6 * 2  # state inputs + output copies
        + 1  # valid
        + plan.static_feasible.shape[0]
        + plan.simon_raw.shape[0]
        + plan.base_score.shape[0]
        + (plan.nodeaff_raw.shape[0] if plan.has_nodeaff else 0)
        + (plan.taint_intol.shape[0] if plan.has_taint else 0)
        + (3 + plan.g_n if plan.g_n else 0)  # gpu statics + used scratch
        + 2 * s_n  # scalar alloc + used scratch
        + pw  # port occupancy planes
        + (
            # caps + storow/has_store + used scratch per slot
            2 * (store.cfg.v + store.cfg.ds + store.cfg.dh) + 2
            if store is not None
            else 0
        )
    )
    tiles = base_tiles
    if terms is not None:
        tc_ = terms.cfg
        tiles += (
            terms.topo_dist.shape[0]
            + terms.g_topo3.shape[0]
            + (terms.cand_dist.shape[0] if tc_.has_hard else 0)
            + (terms.sq_dist.shape[0] if tc_.has_soft else 0)
            + (terms.hk_dist.shape[0] if tc_.has_soft else 0)
            # scratch: tgt + pref + panti + 2 bitplane sets + group + soft
            + tc_.tc + 2 * tc_.tp + 2 * tc_.bp + tc_.a
            + (tc_.csn if tc_.has_soft else 0)
        )
    budget = 13 * 2**20
    rbytes = r * LANES * 4
    # the (Wr, 128) f32 log-weight tables are node-count sized VMEM
    w_bytes = 4 * terms.w_hi.size * 4 if terms is not None else 0
    if tiles * rbytes + w_bytes > budget or (
        STREAM_FORCE and terms is not None
    ):
        # resident term state does not fit: rewrite to the streamed
        # layout (state in HBM, per-pod class-local row gather) before
        # giving up on the fused kernel
        if terms is None or STREAM_FORCE is False:
            return _reject("cluster state exceeds VMEM budget")
        sp = _stream_pack(terms, u, hk_map)
        if sp is None:
            return None  # _stream_pack recorded the reject reason
        stream_bytes = (base_tiles + sp.cfg.kmax) * rbytes + w_bytes + 4 * (
            sp.g_topo3.size + sp.g_match_au.size
            + sp.group0.size + sp.gtot0.size
        )
        if stream_bytes > budget:
            return _reject(
                "cluster state exceeds VMEM budget even with streamed terms"
            )
        smem_entries = sum(
            getattr(sp, nm).size
            for nm, space in _STREAM_TERM_FIELDS
            if space == "smem"
        )
        if smem_entries > _MAX_SMEM_ENTRIES:
            return _reject("terms: streamed SMEM slot tables over budget")
        plan = plan._replace(terms=sp)
    global _LAST_REJECT
    _LAST_REJECT = None
    return plan


# ordered (TermsPlan field, memory space) spec of the term-block kernel
# inputs — the single source of truth shared by the arg packer
# (_device_args), the BlockSpec assignment, and the kernel's unpacking
_TERM_FIELDS = (
    ("topo_dist", "vmem"), ("g_topo3", "vmem"), ("cand_dist", "vmem"),
    ("sq_dist", "vmem"), ("hk_dist", "vmem"), ("g_match_au", "vmem"),
    ("tgt0_c", "any"), ("pref0_p", "any"), ("panti0_p", "any"),
    ("antib0", "any"), ("tposb0", "any"), ("group0", "any"),
    ("gtot0", "any"), ("soft0_nh", "any"),
    ("e_cnt", "smem"), ("e_pref", "smem"), ("e_cpd", "smem"),
    ("e_antip", "smem"), ("e_antib", "smem"),
    ("e_tposp", "smem"), ("e_tposb", "smem"),
    ("gid_u", "smem"), ("self_ok_u", "smem"), ("slot_grows", "smem"),
    ("h_topo", "smem"), ("h_cnt", "smem"), ("h_cand", "smem"),
    ("h_skew", "smem"), ("h_selfm", "smem"),
    ("s_topo_i", "smem"), ("s_ishost", "smem"), ("s_cnt", "smem"),
    ("s_nh", "smem"), ("s_skewm1", "smem"),
    ("c_topo", "smem"), ("c_cnt", "smem"), ("c_pref", "smem"),
    ("c_m", "smem"), ("c_prefc", "smem"), ("c_pantic", "smem"),
    ("c_antip", "smem"), ("c_antib", "smem"),
    ("c_tposp", "smem"), ("c_tposb", "smem"),
    ("sc_nh", "smem"), ("sc_topo", "smem"), ("sc_q", "smem"),
    ("sc_m", "smem"),
    ("w_hi", "vmem"), ("w_lo", "vmem"), ("w_h1", "vmem"), ("w_h2", "vmem"),
)


class StoreCfg(NamedTuple):
    """Static shape configuration of the open-local storage block
    (part of the compiled-kernel cache key)."""

    v: int  # VG slots per node
    ds: int  # SSD device slots
    dh: int  # HDD device slots
    lv: int  # LVM volume slots per class
    sv: int  # SSD volume slots per class
    hv: int  # HDD volume slots per class
    sd: int  # distinct node storage-config rows
    plvm: int  # v ** lv assignment patterns
    pdev: int  # ds**sv * dh**hv assignment patterns


class StorePlan(NamedTuple):
    """Open-local storage arrays for the fused kernel.

    The VG Binpack choice and the device first-fit are exact integer
    comparisons once every byte quantity is divided by the collective
    GCD (_gcd_scale), so the FILTER and the hypothetical ALLOCATION run
    in int32 bit-identically to the XLA path (ops/scan.py
    _local_storage_eval, open-local algo.go:487,574). The SCORES are
    f64 with truncation in the reference (take/cap means x 10) — the
    r4 measured reason the plugin stayed off the kernel. Instead of
    emulating f64 in-kernel, the score of every reachable outcome is
    precomputed ON THE HOST in real f64: an outcome is fully described
    by (pod class, the node's distinct storage config row, which
    VG/device slot each volume landed on), so the kernel computes the
    assignment PATTERN (a base-V / base-D digit string) during the
    integer binpack and looks the score up from an SMEM table —
    bit-exact against the XLA scan because IEEE division of the
    GCD-scaled integers rounds the same real quotient.
    """

    cfg: StoreCfg
    # VMEM node tiles (caps are GCD-scaled, invalid slots folded to 0)
    vg_cap_s: np.ndarray  # (V, R, C)
    ssd_cap_s: np.ndarray  # (Ds, R, C)
    hdd_cap_s: np.ndarray  # (Dh, R, C)
    has_store: np.ndarray  # (R, C) 0/1
    storow: np.ndarray  # (R, C) distinct storage-config row per node
    # init state (ANY -> scratch)
    ivg0: np.ndarray  # (V, R, C) scaled init requested
    issd0: np.ndarray  # (Ds, R, C) 0/1 allocated
    ihdd0: np.ndarray  # (Dh, R, C) 0/1
    # SMEM class tables (scaled volume sizes; 0 = inactive slot)
    lvm_mi: np.ndarray  # (U*Lv,)
    ssd_mi: np.ndarray  # (U*Sv,)
    hdd_mi: np.ndarray  # (U*Hv,)
    wants_u: np.ndarray  # (U,)
    # SMEM score tables: host-f64 ScoreLVM / ScoreDevice per
    # (class, storage row, assignment pattern)
    lvm_sc: np.ndarray  # (U*Sd*Plvm,)
    dev_sc: np.ndarray  # (U*Sd*Pdev,)
    # the collective GCD dividing every byte quantity — decode uses it
    # to return the exported final VG usage in true bytes
    scale: int = 1


# ordered (StorePlan field, memory space) spec — shared by the arg
# packer, BlockSpec assignment, and kernel unpacking (same contract as
# _TERM_FIELDS)
_STORE_FIELDS = (
    ("vg_cap_s", "vmem"), ("ssd_cap_s", "vmem"), ("hdd_cap_s", "vmem"),
    ("has_store", "vmem"), ("storow", "vmem"),
    ("ivg0", "any"), ("issd0", "any"), ("ihdd0", "any"),
    ("lvm_mi", "smem"), ("ssd_mi", "smem"), ("hdd_mi", "smem"),
    ("wants_u", "smem"), ("lvm_sc", "smem"), ("dev_sc", "smem"),
)

_MAX_STORE = dict(v=4, ds=4, dh=4, lv=4, sv=2, hv=2, sd=16, pat=256)


def _build_storage(cluster, batch, dyn, r: int) -> Optional[StorePlan]:
    """Open-local storage block for the fused kernel, or None (with the
    reject reason recorded) when out of scope."""
    a = np.asarray
    vg_cap = a(cluster.vg_cap, dtype=np.int64) * a(cluster.vg_valid, dtype=np.int64)
    ssd_cap = a(cluster.ssd_cap, dtype=np.int64) * a(cluster.ssd_valid, dtype=np.int64)
    hdd_cap = a(cluster.hdd_cap, dtype=np.int64) * a(cluster.hdd_valid, dtype=np.int64)
    vg_used0 = a(dyn.vg_used, dtype=np.int64)
    ssd_used0 = a(dyn.ssd_used).astype(np.int64)
    hdd_used0 = a(dyn.hdd_used).astype(np.int64)
    lvm = a(batch.lvm_sizes, dtype=np.int64)
    ssd = a(batch.ssd_sizes, dtype=np.int64)
    hdd = a(batch.hdd_sizes, dtype=np.int64)
    wants = a(batch.wants_storage).astype(np.int32)

    v = vg_cap.shape[1]
    ds_n = ssd_cap.shape[1]
    dh_n = hdd_cap.shape[1]
    lv = lvm.shape[1]
    sv = ssd.shape[1]
    hv = hdd.shape[1]
    if (v > _MAX_STORE["v"] or ds_n > _MAX_STORE["ds"]
            or dh_n > _MAX_STORE["dh"] or lv > _MAX_STORE["lv"]
            or sv > _MAX_STORE["sv"] or hv > _MAX_STORE["hv"]):
        return _reject("storage: VG/device/volume slot count over kernel scope")
    plvm = v ** lv
    pdev = (ds_n ** sv) * (dh_n ** hv)
    if plvm > _MAX_STORE["pat"] or pdev > _MAX_STORE["pat"]:
        return _reject("storage: assignment pattern space over kernel scope")

    s = _gcd_scale(vg_cap, ssd_cap, hdd_cap, vg_used0, lvm, ssd, hdd)
    vg_s = vg_cap // s
    ssd_s = ssd_cap // s
    hdd_s = hdd_cap // s
    vgu_s = vg_used0 // s
    lvm_s = lvm // s
    ssd_vs = ssd // s
    hdd_vs = hdd // s
    if max(vg_s.max(initial=0), ssd_s.max(initial=0),
           hdd_s.max(initial=0), vgu_s.max(initial=0),
           # volume sizes must fit int32 too: a size sharing no large
           # GCD with the capacities (scale ~1) would otherwise WRAP in
           # the int32 cast and silently diverge from the XLA scan
           lvm_s.max(initial=0), ssd_vs.max(initial=0),
           hdd_vs.max(initial=0)) > _MAX_SCALED:
        return _reject("storage: scaled capacities exceed int32 exactness")

    # distinct storage-config rows: caps alone determine every score
    # outcome (the dynamic part — takes — is the pattern)
    rows = np.hstack([vg_s, ssd_s, hdd_s])
    dist, storow = _dedup_rows(rows.astype(np.int32))
    sd = max(dist.shape[0], 1)
    if sd > _MAX_STORE["sd"]:
        return _reject("storage: distinct node storage configs over kernel scope")
    if sd * (plvm + pdev) > 256:
        # the in-kernel score lookup unrolls sd*(plvm+pdev) masked
        # selects per pod step; keep the instruction budget bounded
        return _reject("storage: score lookup unroll over kernel budget")

    u_n = lvm.shape[0]
    smem_entries = u_n * (lv + sv + hv + 1) + u_n * sd * (plvm + pdev)
    if smem_entries > _MAX_SMEM_ENTRIES // 2:
        return _reject("storage: score tables over SMEM budget")

    # host-f64 score tables, replicating _local_storage_eval's float
    # op order exactly (scaled values divide to the same real quotient
    # as the raw byte values, so IEEE rounding matches)
    lvm_sc = np.zeros((u_n, sd, plvm), dtype=np.int32)
    dev_sc = np.zeros((u_n, sd, pdev), dtype=np.int32)
    for u_i in range(u_n):
        if not wants[u_i]:
            continue
        for s_i in range(dist.shape[0]):
            caps = dist[s_i]
            vcaps = caps[:v].astype(np.float64)
            scaps = caps[v : v + ds_n].astype(np.float64)
            hcaps = caps[v + ds_n :].astype(np.float64)
            for p in range(plvm):
                takes = [0] * v
                digits = p
                for i in range(lv):
                    j = digits % v if v else 0
                    digits //= max(v, 1)
                    if lvm_s[u_i, i] > 0:
                        takes[j] += int(lvm_s[u_i, i])
                frac = np.float64(0.0)
                cnt = 0
                for j in range(v):
                    if takes[j] > 0:
                        frac += np.float64(takes[j]) / max(vcaps[j], 1.0)
                        cnt += 1
                if cnt > 0:
                    lvm_sc[u_i, s_i, p] = int(frac / max(cnt, 1) * 10.0)
            for q in range(pdev):
                sfrac = np.float64(0.0)
                hfrac = np.float64(0.0)
                cnt = 0
                digits = q
                for i in range(sv):
                    d = digits % ds_n if ds_n else 0
                    digits //= max(ds_n, 1)
                    if ssd_vs[u_i, i] > 0:
                        sfrac += np.float64(ssd_vs[u_i, i]) / max(scaps[d], 1.0)
                        cnt += 1
                for i in range(hv):
                    d = digits % dh_n if dh_n else 0
                    digits //= max(dh_n, 1)
                    if hdd_vs[u_i, i] > 0:
                        hfrac += np.float64(hdd_vs[u_i, i]) / max(hcaps[d], 1.0)
                        cnt += 1
                if cnt > 0:
                    dev_sc[u_i, s_i, q] = int((sfrac + hfrac) / max(cnt, 1) * 10.0)

    cfg = StoreCfg(v=v, ds=ds_n, dh=dh_n, lv=lv, sv=sv, hv=hv, sd=sd,
                   plvm=plvm, pdev=pdev)
    return StorePlan(
        cfg=cfg,
        vg_cap_s=_pad_stack(np.ascontiguousarray(vg_s.T), r),
        ssd_cap_s=_pad_stack(np.ascontiguousarray(ssd_s.T), r),
        hdd_cap_s=_pad_stack(np.ascontiguousarray(hdd_s.T), r),
        has_store=_pad_nodes(
            a(cluster.has_storage).astype(np.int32), r
        ),
        storow=_pad_nodes(storow, r),
        ivg0=_pad_stack(np.ascontiguousarray(vgu_s.T), r),
        issd0=_pad_stack(np.ascontiguousarray(ssd_used0.T), r),
        ihdd0=_pad_stack(np.ascontiguousarray(hdd_used0.T), r),
        lvm_mi=lvm_s.astype(np.int32).reshape(-1),
        ssd_mi=ssd_vs.astype(np.int32).reshape(-1),
        hdd_mi=hdd_vs.astype(np.int32).reshape(-1),
        wants_u=wants,
        lvm_sc=lvm_sc.reshape(-1),
        dev_sc=dev_sc.reshape(-1),
        scale=int(s),
    )


class StreamTermsPlan(NamedTuple):
    """Streamed-terms variant of TermsPlan (cfg.stream=True).

    Past the VMEM budget the resident design cannot hold the term
    state on-chip, but each pod only ever touches the rows its CLASS's
    slot tables reference — at most `kmax` distinct (R, C) node
    vectors. So every T-proportional array (count/pref/bitplane/soft
    state plus the deduplicated topo/cand/sq/haskeys statics) is
    concatenated into ONE (S, R, C) HBM buffer, the slot tables are
    rewritten host-side from array rows to per-class GATHER POSITIONS,
    and the kernel's pod step DMAs the class's row set into a (Kmax,
    R, C) VMEM scratch, runs the IDENTICAL eval/commit arithmetic on
    positions, and DMAs the <= wmax dirty rows back. Per-pod HBM
    traffic is kmax*R*512B (class-local), independent of the total
    term count T — the ~12.3k-node VMEM cliff (docs/PERFORMANCE.md)
    becomes a bandwidth slope instead.

    Only the small required-affinity group machinery (A rows) stays
    resident, because its eval reads every group row per pod.

    pref and panti share one index in the resident tables (same row of
    two arrays); in the unified buffer they are different global rows,
    so this plan carries separate e_panti/c_panti position tables (the
    resident kernel aliases them to e_pref/c_pref)."""

    cfg: TermsCfg
    state0: np.ndarray  # (S, R, C) i32 unified init state + statics (ANY)
    g_topo3: np.ndarray  # (A, R, C) resident group-row topo values
    g_match_au: np.ndarray  # (A, Ur_p, 128)
    group0: np.ndarray  # (A, R, C) DMAed to scratch
    gtot0: np.ndarray  # (A, 8, 128)
    # SMEM slot tables — same semantics as TermsPlan but values are
    # gather positions into the (Kmax, R, C) scratch
    e_cnt: np.ndarray
    e_pref: np.ndarray
    e_panti: np.ndarray
    e_cpd: np.ndarray
    e_antip: np.ndarray
    e_antib: np.ndarray
    e_tposp: np.ndarray
    e_tposb: np.ndarray
    gid_u: np.ndarray
    self_ok_u: np.ndarray
    slot_grows: np.ndarray
    h_topo: np.ndarray
    h_cnt: np.ndarray
    h_cand: np.ndarray
    h_skew: np.ndarray
    h_selfm: np.ndarray
    s_topo_i: np.ndarray
    s_ishost: np.ndarray
    s_cnt: np.ndarray
    s_nh: np.ndarray
    s_skewm1: np.ndarray
    c_topo: np.ndarray
    c_cnt: np.ndarray
    c_pref: np.ndarray
    c_panti: np.ndarray
    c_m: np.ndarray
    c_prefc: np.ndarray
    c_pantic: np.ndarray
    c_antip: np.ndarray
    c_antib: np.ndarray
    c_tposp: np.ndarray
    c_tposb: np.ndarray
    sc_nh: np.ndarray
    sc_topo: np.ndarray
    sc_q: np.ndarray
    sc_m: np.ndarray
    w_hi: np.ndarray
    w_lo: np.ndarray
    w_h1: np.ndarray
    w_h2: np.ndarray
    # streaming tables: per-class gather row ids (-1 = unused slot),
    # write-back (scratch position, global row) pairs (-1 = inactive),
    # per-class haskeys gather position
    gather: np.ndarray  # (U*Kmax,)
    wb_pos: np.ndarray  # (U*Wmax,)
    wb_gid: np.ndarray  # (U*Wmax,)
    hk_pos: np.ndarray  # (U,)


_STREAM_TERM_FIELDS = (
    ("state0", "any"),
    ("g_topo3", "vmem"), ("g_match_au", "vmem"),
    ("group0", "any"), ("gtot0", "any"),
    ("e_cnt", "smem"), ("e_pref", "smem"), ("e_panti", "smem"),
    ("e_cpd", "smem"), ("e_antip", "smem"), ("e_antib", "smem"),
    ("e_tposp", "smem"), ("e_tposb", "smem"),
    ("gid_u", "smem"), ("self_ok_u", "smem"), ("slot_grows", "smem"),
    ("h_topo", "smem"), ("h_cnt", "smem"), ("h_cand", "smem"),
    ("h_skew", "smem"), ("h_selfm", "smem"),
    ("s_topo_i", "smem"), ("s_ishost", "smem"), ("s_cnt", "smem"),
    ("s_nh", "smem"), ("s_skewm1", "smem"),
    ("c_topo", "smem"), ("c_cnt", "smem"), ("c_pref", "smem"),
    ("c_panti", "smem"), ("c_m", "smem"), ("c_prefc", "smem"),
    ("c_pantic", "smem"), ("c_antip", "smem"), ("c_antib", "smem"),
    ("c_tposp", "smem"), ("c_tposb", "smem"),
    ("sc_nh", "smem"), ("sc_topo", "smem"), ("sc_q", "smem"),
    ("sc_m", "smem"),
    ("w_hi", "vmem"), ("w_lo", "vmem"), ("w_h1", "vmem"), ("w_h2", "vmem"),
    ("gather", "smem"), ("wb_pos", "smem"), ("wb_gid", "smem"),
    ("hk_pos", "smem"),
)


def _stream_pack(terms: TermsPlan, u_n: int,
                 hk_map: Optional[np.ndarray]) -> Optional[StreamTermsPlan]:
    """Rewrite a resident TermsPlan into the streamed layout (see
    StreamTermsPlan docstring), or None when a class's row set exceeds
    the gather/write-back slot caps."""
    cfg = terms.cfg
    parts = [terms.tgt0_c, terms.pref0_p, terms.panti0_p, terms.antib0,
             terms.tposb0, terms.soft0_nh, terms.topo_dist,
             terms.cand_dist, terms.sq_dist, terms.hk_dist]
    offs = np.cumsum([0] + [p.shape[0] for p in parts])
    (b_tgt, b_pref, b_panti, b_anti, b_tpos, b_soft, b_topo, b_cand,
     b_sq, b_hk) = (int(o) for o in offs[:10])
    state0 = np.ascontiguousarray(np.concatenate(parts, axis=0))

    def t2(name, m):
        return np.asarray(getattr(terms, name)).reshape(u_n, m).copy()

    e_cnt = t2("e_cnt", cfg.rmax)
    e_pref = t2("e_pref", cfg.rmax)
    e_antip = t2("e_antip", cfg.rmax)
    e_antib = t2("e_antib", cfg.rmax)
    e_tposp = t2("e_tposp", cfg.rmax)
    e_tposb = t2("e_tposb", cfg.rmax)
    h_topo = t2("h_topo", cfg.hmax)
    h_cnt = t2("h_cnt", cfg.hmax)
    h_cand = t2("h_cand", cfg.hmax)
    s_topo_i = t2("s_topo_i", cfg.smax)
    s_cnt = t2("s_cnt", cfg.smax)
    s_nh = t2("s_nh", cfg.smax)
    c_topo = t2("c_topo", cfg.cmax)
    c_cnt = t2("c_cnt", cfg.cmax)
    c_pref = t2("c_pref", cfg.cmax)
    c_antip = t2("c_antip", cfg.cmax)
    c_antib = t2("c_antib", cfg.cmax)
    c_tposp = t2("c_tposp", cfg.cmax)
    c_tposb = t2("c_tposb", cfg.cmax)
    sc_nh = t2("sc_nh", cfg.scmax)
    sc_topo = t2("sc_topo", cfg.scmax)
    sc_q = t2("sc_q", cfg.scmax)
    n_panti = np.full((u_n, cfg.rmax), -1, dtype=np.int32)
    nc_panti = np.full((u_n, cfg.cmax), -1, dtype=np.int32)
    hk_pos = np.zeros(u_n, dtype=np.int32)

    glists: list = []
    wlists: list = []
    for u_i in range(u_n):
        pos: dict = {}

        def g(gid: int) -> int:
            p = pos.get(gid)
            if p is None:
                p = len(pos)
                pos[gid] = p
            return p

        for k in range(cfg.rmax):
            if e_cnt[u_i, k] >= 0:
                e_cnt[u_i, k] = g(b_tgt + e_cnt[u_i, k])
            if e_pref[u_i, k] >= 0:
                row = int(e_pref[u_i, k])
                e_pref[u_i, k] = g(b_pref + row)
                n_panti[u_i, k] = g(b_panti + row)
            e_antip[u_i, k] = (
                g(b_anti + e_antip[u_i, k]) if e_antib[u_i, k] != 0 else 0
            )
            e_tposp[u_i, k] = (
                g(b_tpos + e_tposp[u_i, k]) if e_tposb[u_i, k] != 0 else 0
            )
        for k in range(cfg.hmax):
            if h_topo[u_i, k] >= 0:
                h_topo[u_i, k] = g(b_topo + h_topo[u_i, k])
                h_cnt[u_i, k] = g(b_tgt + h_cnt[u_i, k])
                h_cand[u_i, k] = g(b_cand + h_cand[u_i, k])
        for k in range(cfg.smax):
            if s_topo_i[u_i, k] >= 0:
                s_topo_i[u_i, k] = g(b_topo + s_topo_i[u_i, k])
                if s_cnt[u_i, k] >= 0:
                    s_cnt[u_i, k] = g(b_tgt + s_cnt[u_i, k])
                if s_nh[u_i, k] >= 0:
                    s_nh[u_i, k] = g(b_soft + s_nh[u_i, k])
        if cfg.has_soft and hk_map is not None:
            hk_pos[u_i] = g(b_hk + int(hk_map[u_i]))
        # write-backs: every position a commit slot mutates
        wb: "OrderedDict" = OrderedDict()
        for j in range(cfg.cmax):
            if c_topo[u_i, j] >= 0:
                c_topo[u_i, j] = g(b_topo + c_topo[u_i, j])
            if c_cnt[u_i, j] >= 0:
                gid = b_tgt + int(c_cnt[u_i, j])
                p = g(gid)
                c_cnt[u_i, j] = p
                wb.setdefault(p, gid)
            if c_pref[u_i, j] >= 0:
                row = int(c_pref[u_i, j])
                gp, ga = b_pref + row, b_panti + row
                c_pref[u_i, j] = g(gp)
                nc_panti[u_i, j] = g(ga)
                wb.setdefault(g(gp), gp)
                wb.setdefault(g(ga), ga)
            if c_antib[u_i, j] != 0:
                gid = b_anti + int(c_antip[u_i, j])
                c_antip[u_i, j] = g(gid)
                wb.setdefault(g(gid), gid)
            else:
                c_antip[u_i, j] = 0
            if c_tposb[u_i, j] != 0:
                gid = b_tpos + int(c_tposp[u_i, j])
                c_tposp[u_i, j] = g(gid)
                wb.setdefault(g(gid), gid)
            else:
                c_tposp[u_i, j] = 0
        for j in range(cfg.scmax):
            if sc_nh[u_i, j] >= 0:
                gid = b_soft + int(sc_nh[u_i, j])
                sc_nh[u_i, j] = g(gid)
                wb.setdefault(g(gid), gid)
                sc_topo[u_i, j] = g(b_topo + sc_topo[u_i, j])
                sc_q[u_i, j] = g(b_sq + sc_q[u_i, j])
        glists.append(list(pos.keys()))
        wlists.append(list(wb.items()))

    kmax = max((len(gl) for gl in glists), default=0)
    kmax = max(kmax, 1)
    wmax = max((len(wl) for wl in wlists), default=0)
    wmax = max(wmax, 1)
    if kmax > _MAX_SLOTS["kmax"] or wmax > _MAX_SLOTS["wmax"]:
        return _reject("terms: per-class streamed row set over gather caps")
    gather = np.full((u_n, kmax), -1, dtype=np.int32)
    for u_i, gl in enumerate(glists):
        gather[u_i, : len(gl)] = gl
    wb_pos = np.zeros((u_n, wmax), dtype=np.int32)
    wb_gid = np.full((u_n, wmax), -1, dtype=np.int32)
    for u_i, wl in enumerate(wlists):
        for j, (p, gid) in enumerate(wl):
            wb_pos[u_i, j] = p
            wb_gid[u_i, j] = gid

    ncfg = cfg._replace(stream=True, kmax=kmax, wmax=wmax,
                        srows=int(state0.shape[0]))
    return StreamTermsPlan(
        cfg=ncfg,
        state0=state0,
        g_topo3=terms.g_topo3,
        g_match_au=terms.g_match_au,
        group0=terms.group0,
        gtot0=terms.gtot0,
        e_cnt=e_cnt.reshape(-1), e_pref=e_pref.reshape(-1),
        e_panti=n_panti.reshape(-1),
        e_cpd=terms.e_cpd,
        e_antip=e_antip.reshape(-1), e_antib=terms.e_antib,
        e_tposp=e_tposp.reshape(-1), e_tposb=terms.e_tposb,
        gid_u=terms.gid_u, self_ok_u=terms.self_ok_u,
        slot_grows=terms.slot_grows,
        h_topo=h_topo.reshape(-1), h_cnt=h_cnt.reshape(-1),
        h_cand=h_cand.reshape(-1), h_skew=terms.h_skew,
        h_selfm=terms.h_selfm,
        s_topo_i=s_topo_i.reshape(-1), s_ishost=terms.s_ishost,
        s_cnt=s_cnt.reshape(-1), s_nh=s_nh.reshape(-1),
        s_skewm1=terms.s_skewm1,
        c_topo=c_topo.reshape(-1), c_cnt=c_cnt.reshape(-1),
        c_pref=c_pref.reshape(-1), c_panti=nc_panti.reshape(-1),
        c_m=terms.c_m, c_prefc=terms.c_prefc, c_pantic=terms.c_pantic,
        c_antip=c_antip.reshape(-1), c_antib=terms.c_antib,
        c_tposp=c_tposp.reshape(-1), c_tposb=terms.c_tposb,
        sc_nh=sc_nh.reshape(-1), sc_topo=sc_topo.reshape(-1),
        sc_q=sc_q.reshape(-1), sc_m=terms.sc_m,
        w_hi=terms.w_hi, w_lo=terms.w_lo, w_h1=terms.w_h1,
        w_h2=terms.w_h2,
        gather=gather.reshape(-1),
        wb_pos=wb_pos.reshape(-1),
        wb_gid=wb_gid.reshape(-1),
        hk_pos=hk_pos,
    )


def _make_kernel(p_total: int, u_n: int, w: tuple, has_nodeaff: bool,
                 has_taint: bool, has_pins: bool, s_n: int, g_n: int,
                 pw: int, sc: Optional[StoreCfg], tc: Optional[TermsCfg]):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    w_least, w_bal, w_simon, w_na, w_tt, w_spread, w_ipa, w_ol = w

    # ---- ref layout: base inputs, term inputs, outputs, term scratch.
    # The na/tt class tables ride along only when their scores are live
    # (a [U, R, C] tile each — meaningful VMEM at U=100).
    BASE_IN = (
        18 + int(has_nodeaff) + int(has_taint)
        + (3 if s_n else 0) + (6 if g_n else 0) + (3 if pw else 0)
        + (len(_STORE_FIELDS) if sc is not None else 0)
    )
    stream = tc is not None and tc.stream
    term_fields = _STREAM_TERM_FIELDS if stream else _TERM_FIELDS
    TERM_IN = len(term_fields) if tc is not None else 0
    # storage plans export the final VG usage (capacity vg_util reads
    # it); streamed plans append the mutated HBM state buffer as an
    # extra output (ANY space; never fetched to the host)
    N_OUT = 7 + int(sc is not None) + int(stream)

    def two_sum(a, b):
        # Knuth 2Sum (branch-free, round-to-nearest f32): s + err == a + b
        s = a + b
        bb = s - a
        err = (a - (s - bb)) + (b - bb)
        return s, err

    def kernel(*refs):
        it = iter(refs[:BASE_IN])
        pod_scal_ref = next(it)  # (8, Pr, 128) i32: class, rc, rm, re,
        #   nzc, nzm, has_req, unused — pod p at [:, p//128, p%128]
        active_ref = next(it)  # (Pr, 128) i32
        valid_ref = next(it)  # (R, C) i32
        clsmap_ref = next(it)  # (8*U,) SMEM: class -> dedup table row,
        #   flattened row-major (table t, class u at [t * u_n + u])
        alloc_c_ref = next(it)
        alloc_m_ref = next(it)
        alloc_e_ref = next(it)
        alloc_p_ref = next(it)
        alloc_nzm_ref = next(it)
        feas_ref = next(it)  # (Fd, R, C) dedup rows
        simon_ref = next(it)
        na_ref = next(it) if has_nodeaff else None
        tt_ref = next(it) if has_taint else None
        base_ref = next(it)
        ic_ref = next(it)  # init-state inputs, copied into the state
        im_ref = next(it)  # outputs at kernel start (output aliasing
        ie_ref = next(it)  # does NOT initialize aliased outputs on TPU
        inzc_ref = next(it)  # — unread inputs are elided)
        inzm_ref = next(it)
        ipc_ref = next(it)
        if s_n:
            scal_alloc_ref = next(it)  # (S, R, C) VMEM
            iscal0_ref = next(it)  # (S, R, C) ANY, DMAed to scratch
            reqscal_ref = next(it)  # (U*S,) SMEM
        if g_n:
            gperdev_ref = next(it)  # (R, C) VMEM per-device memory
            gcntn_ref = next(it)  # (R, C) VMEM device counts
            gtot_ref = next(it)  # (R, C) VMEM capacity gpu-mem
            igpu0_ref = next(it)  # (G, R, C) ANY, DMAed to scratch
            gmem_ref = next(it)  # (U,) SMEM per-GPU request
            gcnt_ref = next(it)  # (U,) SMEM device count
        if pw:
            ports0_ref = next(it)  # (Pw, R, C) ANY, DMAed to scratch
            wantw_ref = next(it)  # (U*Pw,) SMEM
            conflw_ref = next(it)  # (U*Pw,) SMEM
        if sc is not None:
            srf = {nm: next(it) for nm, _ in _STORE_FIELDS}
        if tc is not None:
            tr = dict(zip((nm for nm, _ in term_fields),
                          refs[BASE_IN : BASE_IN + TERM_IN]))
            if not stream:
                topo_ref = tr["topo_dist"]
                cand_ref = tr["cand_dist"]
                sq_ref = tr["sq_dist"]
                haskeys_ref = tr["hk_dist"]
                # pref/panti share one index in the resident layout;
                # the body reads the *_panti tables uniformly
                tr["e_panti"] = tr["e_pref"]
                tr["c_panti"] = tr["c_pref"]
            gtopo_ref = tr["g_topo3"]
            gmatch_ref = tr["g_match_au"]
            gid_ref = tr["gid_u"]
            selfok_ref = tr["self_ok_u"]
            sgrows_ref = tr["slot_grows"]
            whi_ref, wlo_ref = tr["w_hi"], tr["w_lo"]
            wh1_ref, wh2_ref = tr["w_h1"], tr["w_h2"]
        outs = refs[BASE_IN + TERM_IN : BASE_IN + TERM_IN + N_OUT]
        (place_ref, st_c_ref, st_m_ref, st_e_ref,
         st_nzc_ref, st_nzm_ref, st_p_ref) = outs[:7]
        oi = 7
        if sc is not None:
            vg_out_ref = outs[oi]
            oi += 1
        state_out_ref = outs[oi] if stream else None
        extra = refs[BASE_IN + TERM_IN + N_OUT :]
        ei = 0
        if s_n:
            uscal_s = extra[ei]
            ei += 1
        if g_n:
            ugpu_s = extra[ei]
            ei += 1
        if pw:
            ports_pl = extra[ei]
            ei += 1
        if sc is not None:
            vgu_s, ssdu_s, hddu_s = extra[ei : ei + 3]
            ei += 3
        if tc is not None:
            if stream:
                group_s, gtot_s, gath_s = extra[ei : ei + 3]
                ei += 3
                state_sem = extra[ei]
                ei += 1
                # every streamed array lives in the one gathered
                # scratch; the body's reads/commits index POSITIONS
                tgt_s = pref_s = panti_s = gath_s
                antib_s = tposb_s = soft_s = gath_s
                topo_ref = cand_ref = sq_ref = gath_s
            else:
                (tgt_s, pref_s, panti_s, antib_s, tposb_s, group_s,
                 gtot_s, soft_s) = extra[ei : ei + 8]
                ei += 8
        if s_n or g_n or pw or sc is not None or tc is not None:
            dma_sem = extra[ei]

        shape = valid_ref.shape
        rows = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
        idx_mat = rows * LANES + cols
        lane_iota = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)

        valid = valid_ref[:] != 0
        alloc_c = alloc_c_ref[:]
        alloc_m = alloc_m_ref[:]
        alloc_e = alloc_e_ref[:]
        alloc_p = alloc_p_ref[:]
        alloc_nzm = alloc_nzm_ref[:]
        alloc_c_f = alloc_c.astype(jnp.float32)
        alloc_nzm_f = alloc_nzm.astype(jnp.float32)

        st_c_ref[:] = ic_ref[:]
        st_m_ref[:] = im_ref[:]
        st_e_ref[:] = ie_ref[:]
        st_nzc_ref[:] = inzc_ref[:]
        st_nzm_ref[:] = inzm_ref[:]
        st_p_ref[:] = ipc_ref[:]
        if s_n or g_n or pw or sc is not None or tc is not None:
            # init states arrive in ANY (HBM) so they do not double the
            # VMEM footprint of their scratch copies; one DMA each
            from jax.experimental.pallas import tpu as pltpu_mod

            copies = []
            if s_n:
                copies.append((iscal0_ref, uscal_s))
            if g_n:
                copies.append((igpu0_ref, ugpu_s))
            if pw:
                copies.append((ports0_ref, ports_pl))
            if sc is not None:
                copies += [
                    (srf["ivg0"], vgu_s),
                    (srf["issd0"], ssdu_s),
                    (srf["ihdd0"], hddu_s),
                ]
            if tc is not None:
                if stream:
                    # the mutable HBM state starts as a copy of the
                    # device-cached init buffer (one full-array DMA per
                    # CALL, not per pod) so repeated calls on one plan
                    # never re-upload from the host
                    copies += [
                        (tr["state0"], state_out_ref),
                        (tr["group0"], group_s),
                        (tr["gtot0"], gtot_s),
                    ]
                else:
                    copies += [
                        (tr["tgt0_c"], tgt_s),
                        (tr["pref0_p"], pref_s),
                        (tr["panti0_p"], panti_s),
                        (tr["antib0"], antib_s),
                        (tr["tposb0"], tposb_s),
                        (tr["group0"], group_s),
                        (tr["gtot0"], gtot_s),
                        (tr["soft0_nh"], soft_s),
                    ]
            for src_ref, dst_ref in copies:
                cp = pltpu_mod.make_async_copy(src_ref, dst_ref, dma_sem)
                cp.start()
                cp.wait()

        def step(p, prev_u):
            # carry = previous pod's class (streamed-terms gather skip;
            # -1 before the first pod). Dynamic lane-dim loads are
            # unsupported on TPU: read the pod's 128-lane row and
            # extract via a masked reduce
            pr = p // LANES
            pc = p % LANES
            lane = lane_iota == pc

            def pod_scalar(s):
                row = pod_scal_ref[s, pl.ds(pr, 1), :]
                return jnp.sum(jnp.where(lane, row, 0))

            u = pod_scalar(0)
            rc = pod_scalar(1)
            rm = pod_scalar(2)
            re = pod_scalar(3)
            nzc = pod_scalar(4)
            nzm = pod_scalar(5)
            has_req = pod_scalar(6)
            active = jnp.sum(jnp.where(lane, active_ref[pl.ds(pr, 1), :], 0))
            # dedup-table rows for this pod's class (SMEM scalar reads)
            fu = clsmap_ref[u]
            su = clsmap_ref[u_n + u]
            bu = clsmap_ref[2 * u_n + u]

            if stream:
                # gather this class's term-state rows from HBM into the
                # (Kmax, R, C) scratch — ONLY on a class switch. While
                # consecutive pods share a class the scratch stays
                # authoritative (commits land in-scratch) and the dirty
                # rows of the PREVIOUS class are flushed back here, so
                # replica runs pay one gather+flush per class, not per
                # pod. All fetches start first (round-robin over the
                # semaphore array) so they overlap, then one wait pass;
                # positions beyond a class's row set (gid < 0) are
                # skipped and never read by the tables.
                @pl.when(u != prev_u)
                def _switch():
                    _flush_class(jnp.maximum(prev_u, 0), prev_u >= 0)
                    for k in range(tc.kmax):
                        g_k = tr["gather"][u * tc.kmax + k]

                        @pl.when(g_k >= 0)
                        def _(k=k, g_k=g_k):
                            pltpu_mod.make_async_copy(
                                state_out_ref.at[pl.ds(g_k, 1)],
                                gath_s.at[pl.ds(k, 1)],
                                state_sem.at[k % _STREAM_NSEM],
                            ).start()
                    for k in range(tc.kmax):
                        g_k = tr["gather"][u * tc.kmax + k]

                        @pl.when(g_k >= 0)
                        def _(k=k, g_k=g_k):
                            pltpu_mod.make_async_copy(
                                state_out_ref.at[pl.ds(g_k, 1)],
                                gath_s.at[pl.ds(k, 1)],
                                state_sem.at[k % _STREAM_NSEM],
                            ).wait()

            used_c = st_c_ref[:]
            used_m = st_m_ref[:]
            used_e = st_e_ref[:]
            st_nzc = st_nzc_ref[:]
            st_nzm = st_nzm_ref[:]
            pod_cnt = st_p_ref[:]

            fit = (
                (used_c + rc <= alloc_c)
                & (used_m + rm <= alloc_m)
                & (used_e + re <= alloc_e)
            )
            if s_n:
                # extended scalar resources join NodeResourcesFit
                # (fit.go scalar path), inside the zero-request gate
                for s in range(s_n):
                    rq = reqscal_ref[u * s_n + s]
                    fit = fit & (uscal_s[s] + rq <= scal_alloc_ref[s])
            if g_n:
                # open-gpu-share filter + allocation choice, mirroring
                # ops/scan.py _gpu_allocate exactly: tightest fit
                # (strict '<', first device on ties) for one GPU,
                # two-pointer greedy prefix in device order for several
                gm = gmem_ref[u]
                gc = gcnt_ref[u]
                gm1 = jnp.maximum(gm, 1)
                perdev = gperdev_ref[:]
                cntn = gcntn_ref[:]
                gpu_fits_any = jnp.zeros(shape, bool)
                gpu_best_key = jnp.full(shape, BIG, jnp.int32)
                gpu_best_dev = jnp.full(shape, -1, jnp.int32)
                gpu_caps = []
                gpu_prefix = []
                run_prefix = jnp.zeros(shape, jnp.int32)
                for g in range(g_n):
                    dvalid = cntn > g
                    availg = perdev - ugpu_s[g]
                    fitg = dvalid & (availg >= gm)
                    gpu_fits_any = gpu_fits_any | fitg
                    keyg = jnp.where(fitg, availg, BIG)
                    better = keyg < gpu_best_key
                    gpu_best_key = jnp.where(better, keyg, gpu_best_key)
                    gpu_best_dev = jnp.where(better, g, gpu_best_dev)
                    capg = jnp.maximum(
                        jnp.where(dvalid, availg // gm1, 0), 0
                    )
                    gpu_caps.append(capg)
                    gpu_prefix.append(run_prefix)
                    run_prefix = run_prefix + capg
                needs_gpu = gm > 0
                # select over i32 (Mosaic cannot legalize i1-vector
                # select), same pattern as the pin override
                gpu_found = (
                    jnp.where(
                        gc == 1,
                        gpu_fits_any.astype(jnp.int32),
                        (run_prefix >= gc).astype(jnp.int32),
                    )
                    != 0
                )
                gpu_ok = ~needs_gpu | ((gtot_ref[:] >= gm) & gpu_found)
            feas = (
                (feas_ref[fu] != 0)
                & valid
                & (pod_cnt + 1 <= alloc_p)
                & (fit | (has_req == 0))
            )
            if g_n:
                feas = feas & gpu_ok
            if pw:
                # NodePorts: conflict when any occupied port matches the
                # class's conflict mask (HostPortInfo.CheckConflict)
                clash = jnp.zeros(shape, bool)
                for w_i in range(pw):
                    clash = clash | (
                        (ports_pl[w_i] & conflw_ref[u * pw + w_i]) != 0
                    )
                feas = feas & ~clash

            if sc is not None:
                # open-local: VG Binpack + exclusive-device first-fit,
                # mirroring ops/scan.py _local_storage_eval in scaled
                # int32. The assignment PATTERN (base-V/base-D digit
                # string) indexes the host-f64 score tables later.
                wants_s = srf["wants_u"][u]
                lvm_ok = jnp.ones(shape, bool)
                pat_lvm = jnp.zeros(shape, jnp.int32)
                take_vg = [jnp.zeros(shape, jnp.int32) for _ in range(sc.v)]
                vg_free = [
                    srf["vg_cap_s"][j] - vgu_s[j] for j in range(sc.v)
                ]
                for i in range(sc.lv):
                    vsz = srf["lvm_mi"][u * sc.lv + i]
                    act = (vsz > 0).astype(jnp.int32)
                    best_free = jnp.full(shape, BIG, jnp.int32)
                    best_j = jnp.zeros(shape, jnp.int32)
                    for j in range(sc.v):
                        fj = vg_free[j] - take_vg[j]
                        # cap=0 (invalid VG) keeps fj <= 0 < vsz for any
                        # active volume, so validity needs no extra mask
                        keyj = jnp.where(fj >= vsz, fj, BIG)
                        better = keyj < best_free  # strict: ties keep lowest j
                        best_free = jnp.where(better, keyj, best_free)
                        best_j = jnp.where(better, j, best_j)
                    ok_i = best_free < BIG
                    for j in range(sc.v):
                        selj = ok_i & (best_j == j)
                        take_vg[j] = take_vg[j] + jnp.where(selj, vsz, 0)
                    lvm_ok = lvm_ok & (ok_i | (act == 0))
                    pat_lvm = pat_lvm + (
                        jnp.where(ok_i, best_j, 0) * ((sc.v ** i) * act)
                    )

                def fit_dev(d_n, vol_n, cap_nm, used_s, mi_nm, mult0):
                    """First-fit ascending sizes onto the first free
                    device with room (scan.py fit_devices); returns
                    (ok, taken per slot, pattern contribution)."""
                    d_ok = jnp.ones(shape, bool)
                    pat = jnp.zeros(shape, jnp.int32)
                    taken = [jnp.zeros(shape, bool) for _ in range(d_n)]
                    mult = mult0
                    for i in range(vol_n):
                        dsz = srf[mi_nm][u * vol_n + i]
                        act_d = (dsz > 0).astype(jnp.int32)
                        found = jnp.zeros(shape, bool)
                        chosen = jnp.zeros(shape, jnp.int32)
                        for d in range(d_n):
                            cd = srf[cap_nm][d]
                            elig = (
                                (used_s[d] == 0)
                                & ~taken[d]
                                & (cd >= dsz)
                                & (cd > 0)
                            )
                            newly = elig & ~found
                            chosen = jnp.where(newly, d, chosen)
                            found = found | elig
                        for d in range(d_n):
                            seld = found & (chosen == d) & (act_d != 0)
                            taken[d] = taken[d] | seld
                        d_ok = d_ok & (found | (act_d == 0))
                        pat = pat + jnp.where(found, chosen, 0) * (mult * act_d)
                        mult *= d_n
                    return d_ok, taken, pat

                ssd_okv, taken_ssd, pat_s = fit_dev(
                    sc.ds, sc.sv, "ssd_cap_s", ssdu_s, "ssd_mi", 1
                )
                hdd_okv, taken_hdd, pat_h = fit_dev(
                    sc.dh, sc.hv, "hdd_cap_s", hddu_s, "hdd_mi",
                    sc.ds ** sc.sv,
                )
                pat_dev = pat_s + pat_h
                has_s = srf["has_store"][:] != 0
                store_ok = has_s & lvm_ok & ssd_okv & hdd_okv
                feas = feas & (store_ok | (wants_s == 0))

            # ---- inter-pod affinity + topology spread ----
            # Eval reads state directly: count/pref state is zero at
            # nodes whose topology key is missing (init masked, commits
            # eq-gated), and inactive slots carry zero scalars, so no
            # per-node key mask is needed.
            if tc is not None and tc.has_ipa:
                fail_exist = jnp.zeros(shape, bool)
                fail_own = jnp.zeros(shape, bool)
                ipa_raw = jnp.zeros(shape, jnp.int32)
                for k in range(tc.rmax):
                    ci = tr["e_cnt"][u * tc.rmax + k]
                    tgtk = tgt_s[jnp.maximum(ci, 0)] * (ci >= 0)
                    pi = tr["e_pref"][u * tc.rmax + k]
                    pa = tr["e_panti"][u * tc.rmax + k]
                    pv = (pi >= 0).astype(jnp.int32)
                    pix = jnp.maximum(pi, 0)
                    pax = jnp.maximum(pa, 0)
                    ipa_raw = (
                        ipa_raw
                        + tr["e_cpd"][u * tc.rmax + k] * tgtk
                        + (pref_s[pix] - panti_s[pax]) * pv
                    )
                    ab = tr["e_antib"][u * tc.rmax + k]
                    fail_exist = fail_exist | (
                        (antib_s[tr["e_antip"][u * tc.rmax + k]] & ab) != 0
                    )
                    tb = tr["e_tposb"][u * tc.rmax + k]
                    fail_own = fail_own | (
                        (tposb_s[tr["e_tposp"][u * tc.rmax + k]] & tb) != 0
                    )

                # satisfyPodAffinity: required-affinity groups
                gid = gid_ref[u]
                keys_ok = jnp.ones(shape, bool)
                pods_exist = jnp.ones(shape, bool)
                total_g = jnp.zeros((), jnp.int32)
                for k in range(tc.gmax):
                    a_k = sgrows_ref[u * tc.gmax + k]
                    gv = a_k >= 0
                    ak = jnp.maximum(a_k, 0)
                    gvals = gtopo_ref[ak]
                    hasg = gvals >= 0
                    gck = jnp.where(hasg, group_s[ak], 0)
                    keys_ok = keys_ok & (hasg | ~gv)
                    pods_exist = pods_exist & ((gck > 0) | ~gv)
                    tot_k = jnp.sum(gtot_s[ak, 0:1, 0:1])
                    total_g = total_g + jnp.where(gv, tot_k, 0)
                self_ok = selfok_ref[u] != 0
                bootstrap = (total_g == 0) & self_ok
                aff_ok = (gid < 0) | (keys_ok & (pods_exist | bootstrap))
                feas = feas & aff_ok & ~fail_own & ~fail_exist

            if tc is not None and tc.has_hard:
                for k in range(tc.hmax):
                    ti = tr["h_topo"][u * tc.hmax + k]
                    hv = ti >= 0
                    hvals = topo_ref[jnp.maximum(ti, 0)]
                    cand = (cand_ref[jnp.maximum(tr["h_cand"][u * tc.hmax + k], 0)] != 0) & valid
                    counts = tgt_s[jnp.maximum(tr["h_cnt"][u * tc.hmax + k], 0)]
                    minc = jnp.min(jnp.where(cand, counts, BIG))
                    minc = jnp.where(jnp.any(cand), minc, 0)
                    cnt_eff = jnp.where(cand & (hvals >= 0), counts, 0)
                    selfm = tr["h_selfm"][u * tc.hmax + k]
                    skew = cnt_eff + selfm - minc
                    maxskew = tr["h_skew"][u * tc.hmax + k]
                    ok_c = (skew <= maxskew) & (hvals >= 0)
                    feas = feas & (ok_c | ~hv)

            # ---- scores ----
            # LeastAllocated (least_allocated.go:108-117)
            totc = st_nzc + nzc
            totm = st_nzm + nzm
            ok_c = (alloc_c > 0) & (totc <= alloc_c)
            ok_m = (alloc_nzm > 0) & (totm <= alloc_nzm)
            least_c = jnp.where(
                ok_c, (alloc_c - totc) * MAX_SCORE // jnp.maximum(alloc_c, 1), 0
            )
            least_m = jnp.where(
                ok_m, (alloc_nzm - totm) * MAX_SCORE // jnp.maximum(alloc_nzm, 1), 0
            )
            total = base_ref[bu] + ((least_c + least_m) // 2) * w_least

            if w_bal:
                # BalancedAllocation: fractions are exact in f32 (inputs
                # < 2^24); only the final truncation is float
                cpu_frac = totc.astype(jnp.float32) / jnp.maximum(alloc_c_f, 1.0)
                cpu_frac = jnp.where(alloc_c > 0, cpu_frac, 1.0)
                mem_frac = totm.astype(jnp.float32) / jnp.maximum(alloc_nzm_f, 1.0)
                mem_frac = jnp.where(alloc_nzm > 0, mem_frac, 1.0)
                balanced = jnp.where(
                    (cpu_frac >= 1.0) | (mem_frac >= 1.0),
                    0,
                    ((1.0 - jnp.abs(cpu_frac - mem_frac)) * MAX_SCORE).astype(
                        jnp.int32
                    ),
                )
                total = total + balanced * w_bal

            if w_simon:
                raw = simon_ref[su]
                hi = jnp.max(jnp.where(feas, raw, NEG))
                lo = jnp.min(jnp.where(feas, raw, BIG))
                rng = hi - lo
                sim = jnp.where(
                    rng > 0, (raw - lo) * MAX_SCORE // jnp.maximum(rng, 1), 0
                )
                total = total + sim * w_simon

            if w_na and has_nodeaff:
                raw = na_ref[clsmap_ref[3 * u_n + u]]
                mx = jnp.max(jnp.where(feas, raw, 0))
                na = jnp.where(mx > 0, MAX_SCORE * raw // jnp.maximum(mx, 1), 0)
                total = total + na * w_na

            if w_tt and has_taint:
                raw = tt_ref[clsmap_ref[4 * u_n + u]]
                mx = jnp.max(jnp.where(feas, raw, 0))
                base = jnp.where(mx > 0, MAX_SCORE * raw // jnp.maximum(mx, 1), 0)
                tt = jnp.where(mx > 0, MAX_SCORE - base, MAX_SCORE)
                total = total + tt * w_tt

            if tc is not None and tc.has_ipa and w_ipa:
                # InterPodAffinity NormalizeScore (scoring.go:246-270):
                # integer division reproduces the f64-truncate result for
                # these magnitudes (|numerator| < 2^31, denominator >= 1)
                mxi = jnp.maximum(jnp.max(jnp.where(feas, ipa_raw, 0)), 0)
                mni = jnp.minimum(jnp.min(jnp.where(feas, ipa_raw, 0)), 0)
                diff = mxi - mni
                ipa_sc = jnp.where(
                    diff > 0,
                    (MAX_SCORE * (ipa_raw - mni)) // jnp.maximum(diff, 1),
                    0,
                )
                total = total + ipa_sc * w_ipa

            if tc is not None and tc.has_soft and w_spread:
                # PodTopologySpread soft score (scoring.go). The XLA path
                # computes cnt*log(sz+2) in f64; f64 is unavailable here,
                # so the product runs in double-single f32 (split tables
                # w_h1/w_h2/w_lo, exact partial products, 2Sum chains) —
                # ~2^-45 relative error, then integer truncation.
                if stream:
                    hkeys = gath_s[tr["hk_pos"][u]] != 0
                else:
                    hkeys = haskeys_ref[clsmap_ref[5 * u_n + u]] != 0
                eligible = feas & hkeys
                acc_hi = jnp.zeros(shape, jnp.float32)
                acc_lo = jnp.zeros(shape, jnp.float32)
                any_svalid = jnp.zeros((), bool)
                for k in range(tc.smax):
                    sti = tr["s_topo_i"][u * tc.smax + k]
                    sv = sti >= 0
                    any_svalid = any_svalid | sv
                    svals = topo_ref[jnp.maximum(sti, 0)]
                    is_host = tr["s_ishost"][u * tc.smax + k] != 0
                    sz_host = jnp.sum((eligible).astype(jnp.int32))
                    sz_nh = jnp.zeros((), jnp.int32)
                    for v in range(tc.vs):
                        sz_nh = sz_nh + jnp.any(eligible & (svals == v)).astype(
                            jnp.int32
                        )
                    sz = jnp.where(is_host, sz_host, sz_nh)

                    def wval(ref, idx=sz):
                        # (Wr, 128) f32 VMEM table read at a traced
                        # scalar index: dynamic sublane row + lane mask
                        # (same pattern as pod_scalar)
                        row = ref[pl.ds(idx // LANES, 1), :]
                        return jnp.sum(
                            jnp.where(lane_iota == idx % LANES, row, 0.0)
                        )

                    whi = wval(whi_ref)
                    wlo = wval(wlo_ref)
                    wh1 = wval(wh1_ref)
                    wh2 = wval(wh2_ref)
                    ci_s = tr["s_cnt"][u * tc.smax + k]
                    cnt_host = tgt_s[jnp.maximum(ci_s, 0)]
                    cnt_soft = soft_s[jnp.maximum(tr["s_nh"][u * tc.smax + k], 0)]
                    cnt = jnp.where(is_host, cnt_host, cnt_soft) * (
                        svals >= 0
                    ).astype(jnp.int32)
                    c2 = cnt % 256
                    c1 = (cnt - c2).astype(jnp.float32)
                    c2f = c2.astype(jnp.float32)
                    # exact partial products (<=21-bit each)
                    hi_p, e1 = two_sum(c1 * wh1, c1 * wh2)
                    hi_p, e2 = two_sum(hi_p, c2f * wh1)
                    hi_p, e3 = two_sum(hi_p, c2f * wh2)
                    lo_p = e1 + e2 + e3 + cnt.astype(jnp.float32) * wlo
                    skew_k = tr["s_skewm1"][u * tc.smax + k].astype(jnp.float32)
                    hi_p, e4 = two_sum(hi_p, skew_k)
                    lo_p = lo_p + e4
                    hi_p = jnp.where(sv, hi_p, 0.0)
                    lo_p = jnp.where(sv, lo_p, 0.0)
                    acc_hi, e5 = two_sum(acc_hi, hi_p)
                    acc_lo = acc_lo + e5 + lo_p
                # truncate acc_hi + acc_lo toward zero (scores >= 0)
                base_f = jnp.floor(acc_hi)
                frac = (acc_hi - base_f) + acc_lo
                adj = jnp.where(frac >= 1.0, 1, jnp.where(frac < 0.0, -1, 0))
                raw_s = base_f.astype(jnp.int32) + adj
                validm = feas & hkeys
                anyv = jnp.any(validm)
                mxs = jnp.max(jnp.where(validm, raw_s, -BIG))
                mns = jnp.min(jnp.where(validm, raw_s, BIG))
                norm_s = jnp.where(
                    mxs == 0,
                    MAX_SCORE,
                    (MAX_SCORE * (mxs + mns - raw_s)) // jnp.maximum(mxs, 1),
                )
                soft_sc = jnp.where(validm, norm_s, 0)
                soft_sc = jnp.where(anyv, soft_sc, 0)
                soft_sc = jnp.where(any_svalid, soft_sc, MAX_SCORE)
                total = total + soft_sc * w_spread
            elif w_spread:
                # no soft constraints anywhere: NormalizeScore's
                # no-constraint branch is MaxNodeScore on every node — a
                # constant that cannot change the argmax; omitted
                pass

            if sc is not None and w_ol:
                # Open-Local raw score: host-f64 table value at (class,
                # storage row, assignment pattern), then the same
                # min-max normalize as Simon (scan.py _minmax_normalize)
                raw_st = jnp.zeros(shape, jnp.int32)
                srow = srf["storow"][:]
                for s_i in range(sc.sd):
                    srm = srow == s_i
                    base_l = (u * sc.sd + s_i) * sc.plvm
                    for p in range(sc.plvm):
                        msk = srm & (pat_lvm == p)
                        raw_st = raw_st + jnp.where(
                            msk, srf["lvm_sc"][base_l + p], 0
                        )
                    base_d = (u * sc.sd + s_i) * sc.pdev
                    for q in range(sc.pdev):
                        msk = srm & (pat_dev == q)
                        raw_st = raw_st + jnp.where(
                            msk, srf["dev_sc"][base_d + q], 0
                        )
                raw_st = jnp.where(has_s & (wants_s != 0), raw_st, 0)
                hi_st = jnp.max(jnp.where(feas, raw_st, NEG))
                lo_st = jnp.min(jnp.where(feas, raw_st, BIG))
                rng_st = hi_st - lo_st
                ol_sc = jnp.where(
                    rng_st > 0,
                    (raw_st - lo_st) * MAX_SCORE // jnp.maximum(rng_st, 1),
                    0,
                )
                total = total + ol_sc * w_ol

            masked = jnp.where(feas, total, NEG)
            m = jnp.max(masked)
            found = m > NEG
            cand = jnp.where(feas & (masked == m), idx_mat, BIG)
            best = jnp.min(cand)

            place = jnp.where(found, best, -1)
            if has_pins:
                # spec.nodeName overrides selection regardless of
                # feasibility (scan.py: pinned pods commit as forced
                # placements); a pin outside node_valid is INACTIVE
                pin = pod_scalar(7)
                pinc = jnp.maximum(pin, 0)
                vrow = valid_ref[pl.ds(pinc // LANES, 1), :]
                pin_ok = (
                    jnp.sum(jnp.where(lane_iota == pinc % LANES, vrow, 0)) != 0
                )
                place = jnp.where(
                    pin >= 0, jnp.where(pin_ok, pin, INACTIVE), place
                )
            place = jnp.where(active != 0, place, INACTIVE)
            # dynamic lane-dim stores are unsupported on TPU: rewrite
            # only the pod's 128-lane row, lane-selected via the mask
            prow = place_ref[pl.ds(pr, 1), :]
            place_ref[pl.ds(pr, 1), :] = jnp.where(lane, place, prow)

            do = place >= 0
            sel = (idx_mat == place) & do
            st_c_ref[:] = used_c + jnp.where(sel, rc, 0)
            st_m_ref[:] = used_m + jnp.where(sel, rm, 0)
            st_e_ref[:] = used_e + jnp.where(sel, re, 0)
            st_nzc_ref[:] = st_nzc + jnp.where(sel, nzc, 0)
            st_nzm_ref[:] = st_nzm + jnp.where(sel, nzm, 0)
            st_p_ref[:] = pod_cnt + jnp.where(sel, 1, 0)
            if s_n or pw:
                sel_i = sel.astype(jnp.int32)
            if s_n:
                for s in range(s_n):
                    uscal_s[s] = uscal_s[s] + reqscal_ref[u * s_n + s] * sel_i
            if g_n:
                # charge the chosen devices at the placed node only
                # (scan.py commit: gpu_used += onehot * take * gpu_mem[u])
                for g in range(g_n):
                    single_take = (
                        (gpu_best_dev == g) & gpu_fits_any
                    ).astype(jnp.int32)
                    multi_take = jnp.clip(gc - gpu_prefix[g], 0, gpu_caps[g])
                    take_g = jnp.where(gc == 1, single_take, multi_take)
                    charge = jnp.where(needs_gpu, take_g * gm, 0)
                    ugpu_s[g] = ugpu_s[g] + jnp.where(sel, charge, 0)
            if pw:
                for w_i in range(pw):
                    ports_pl[w_i] = ports_pl[w_i] | (
                        wantw_ref[u * pw + w_i] * sel_i
                    )
            if sc is not None:
                # commit the hypothetical allocation at the placed node
                # (scan.py: vg_used += onehot*vg_take, ssd/hdd_used |=
                # onehot & take)
                for j in range(sc.v):
                    vgu_s[j] = vgu_s[j] + jnp.where(sel, take_vg[j], 0)
                for d in range(sc.ds):
                    ssdu_s[d] = jnp.where(sel & taken_ssd[d], 1, ssdu_s[d])
                for d in range(sc.dh):
                    hddu_s[d] = jnp.where(sel & taken_hdd[d], 1, hddu_s[d])

            if tc is not None:
                inc = do.astype(jnp.int32)
                nr = jnp.where(do, place // LANES, 0)
                nc = jnp.where(do, place % LANES, 0)
                lane_nc = (lane_iota == nc)[None, :, :]  # (1, 1, C)
                lane_nc2 = lane_iota == nc  # (1, C) for 2D slabs
                lane_u3 = lane_iota == u % LANES  # (1, LANES)

                def col_u(tab_ref):
                    """Class-u column of a (X, Ur_p, 128) table ->
                    (X, 1, 1) i32 (dynamic sublane row u//128, lane
                    u%128 by mask — same pattern as pod_scalar)."""
                    slab = tab_ref[:, pl.ds(u // LANES, 1), :]
                    return jnp.sum(
                        jnp.where(lane_u3, slab, 0), axis=2, keepdims=True
                    )

                def val_at(t3_ref):
                    """(X, R, C) tile values at the placed node -> (X, 1, 1)."""
                    colslab = t3_ref[:, pl.ds(nr, 1), :]  # (X, 1, C)
                    return jnp.sum(
                        jnp.where(lane_nc, colslab, 0), axis=2, keepdims=True
                    )

                def val_at_row(t3_ref, idx):
                    """Row idx of a (X, R, C) tile at the placed node -> scalar."""
                    slab = t3_ref[idx, pl.ds(nr, 1), :]  # (1, C)
                    return jnp.sum(jnp.where(lane_nc2, slab, 0))

                # SPARSE commit: each class updates at most cmax
                # (row, topo) slots — count rows as += increments, bit
                # rows as monotone ORs. Inactive slots multiply to zero
                # (their read-modify-write of row 0 adds 0).
                for j in range(tc.cmax):
                    ti = tr["c_topo"][u * tc.cmax + j]
                    tix = jnp.maximum(ti, 0)
                    tvals = topo_ref[tix]
                    valt = val_at_row(topo_ref, tix)
                    upd = (
                        (tvals == valt) & (valt >= 0) & (ti >= 0)
                    ).astype(jnp.int32) * inc
                    ci = tr["c_cnt"][u * tc.cmax + j]
                    cix = jnp.maximum(ci, 0)
                    tgt_s[cix] = tgt_s[cix] + tr["c_m"][u * tc.cmax + j] * upd * (ci >= 0)
                    if tc.has_ipa:
                        pi2 = tr["c_pref"][u * tc.cmax + j]
                        pa2 = tr["c_panti"][u * tc.cmax + j]
                        pix = jnp.maximum(pi2, 0)
                        pax = jnp.maximum(pa2, 0)
                        pfac = upd * (pi2 >= 0)
                        pref_s[pix] = pref_s[pix] + tr["c_prefc"][u * tc.cmax + j] * pfac
                        panti_s[pax] = panti_s[pax] + tr["c_pantic"][u * tc.cmax + j] * pfac
                        ap = tr["c_antip"][u * tc.cmax + j]
                        antib_s[ap] = antib_s[ap] | (tr["c_antib"][u * tc.cmax + j] * upd)
                        tp_ = tr["c_tposp"][u * tc.cmax + j]
                        tposb_s[tp_] = tposb_s[tp_] | (tr["c_tposb"][u * tc.cmax + j] * upd)

                if tc.has_ipa:
                    g_valt = val_at(gtopo_ref)  # (A, 1, 1)
                    g_eq = ((gtopo_ref[:] == g_valt) & (g_valt >= 0)).astype(
                        jnp.int32
                    )
                    g_m = col_u(gmatch_ref)[: tc.a] * (g_valt >= 0)
                    group_s[:] = group_s[:] + (g_m * inc) * g_eq
                    gtot_s[:] = gtot_s[:] + g_m * inc
                if tc.has_soft:
                    for j in range(tc.scmax):
                        si = tr["sc_nh"][u * tc.scmax + j]
                        six = jnp.maximum(si, 0)
                        sti2 = jnp.maximum(tr["sc_topo"][u * tc.scmax + j], 0)
                        stvals = topo_ref[sti2]
                        s_valt = val_at_row(topo_ref, sti2)
                        s_q_at = (
                            val_at_row(sq_ref, jnp.maximum(tr["sc_q"][u * tc.scmax + j], 0))
                            != 0
                        )
                        s_upd = (
                            (stvals == s_valt)
                            & (s_valt >= 0)
                            & (si >= 0)
                            & s_q_at
                        ).astype(jnp.int32) * inc
                        soft_s[six] = soft_s[six] + tr["sc_m"][u * tc.scmax + j] * s_upd

            return u

        if stream:
            # flush the dirty rows of class `cu` back to HBM (no-op
            # when `valid` is False, i.e. before the first pod). The
            # waits double as the ordering barrier against the next
            # class's gather of the same rows.
            def _flush_class(cu, valid_c):
                for j in range(tc.wmax):
                    w_g = tr["wb_gid"][cu * tc.wmax + j]
                    w_p = tr["wb_pos"][cu * tc.wmax + j]

                    @pl.when(valid_c & (w_g >= 0))
                    def _(j=j, w_g=w_g, w_p=w_p):
                        pltpu_mod.make_async_copy(
                            gath_s.at[pl.ds(jnp.maximum(w_p, 0), 1)],
                            state_out_ref.at[pl.ds(w_g, 1)],
                            state_sem.at[j % _STREAM_NSEM],
                        ).start()
                for j in range(tc.wmax):
                    w_g = tr["wb_gid"][cu * tc.wmax + j]
                    w_p = tr["wb_pos"][cu * tc.wmax + j]

                    @pl.when(valid_c & (w_g >= 0))
                    def _(j=j, w_g=w_g, w_p=w_p):
                        pltpu_mod.make_async_copy(
                            gath_s.at[pl.ds(jnp.maximum(w_p, 0), 1)],
                            state_out_ref.at[pl.ds(w_g, 1)],
                            state_sem.at[j % _STREAM_NSEM],
                        ).wait()

        last_u = jax.lax.fori_loop(0, p_total, step, jnp.int32(-1))
        if sc is not None:
            # export the final VG usage (scaled) for the capacity
            # sweep's vg_util (decode_scan_output converts to bytes)
            vg_out_ref[:] = vgu_s[:]
        if stream:
            # the final class's commits live only in scratch until here
            _flush_class(jnp.maximum(last_u, 0), last_u >= 0)

    return kernel


class _Compiled(NamedTuple):
    fn: object


_COMPILED_CACHE: dict = {}

# device-resident copies of a plan's (numpy) arrays: the axon relay
# makes per-call host->device transfers expensive (~10ms per array;
# a terms plan ships ~55 arrays), so transfer once per plan. Keyed by
# id(plan) with a strong ref pinning it (utils/memo.py contract).
# LRU-ordered: hits move-to-end so eviction under >16 live plans
# (concurrent sweeps) targets the coldest plan, not the hot one.
_DEVICE_PLAN_CACHE: "OrderedDict" = OrderedDict()

# host-packed scenario-invariant pod-scalar rows, same identity contract
_POD_SCAL_CACHE: "OrderedDict" = OrderedDict()

# both caches pin finished plans (host numpy + device buffers) until
# eviction; release them with the memos at the planner boundary
from ..utils.memo import register_cache as _register_cache  # noqa: E402

_register_cache(_DEVICE_PLAN_CACHE.clear)
_register_cache(_POD_SCAL_CACHE.clear)


def _plan_args_np(plan: PallasPlan) -> list:
    """The plan's kernel-input arrays, in ref order (host numpy)."""
    args = [
        plan.clsmap,
        plan.alloc_mcpu, plan.alloc_mem_s, plan.alloc_eph_s, plan.alloc_pods,
        plan.alloc_nzmem_s,
        plan.static_feasible, plan.simon_raw,
    ]
    if plan.has_nodeaff:
        args.append(plan.nodeaff_raw)
    if plan.has_taint:
        args.append(plan.taint_intol)
    args += [
        plan.base_score,
        plan.init_used_mcpu, plan.init_used_mem_s, plan.init_used_eph_s,
        plan.init_nz_mcpu, plan.init_nz_mem_s, plan.init_pod_cnt,
    ]
    if plan.s_n:
        args += [plan.alloc_scal, plan.iscal0, plan.req_scal]
    if plan.g_n:
        args += [
            plan.gpu_per_dev, plan.gpu_cnt_n, plan.gpu_tot,
            plan.igpu0, plan.gpu_mem_u, plan.gpu_cnt_u,
        ]
    if plan.pw:
        args += [plan.ports0, plan.want_w, plan.confl_w]
    if plan.store is not None:
        args += [getattr(plan.store, name) for name, _ in _STORE_FIELDS]
    if plan.terms is not None:
        fields = (
            _STREAM_TERM_FIELDS
            if plan.terms.cfg.stream
            else _TERM_FIELDS
        )
        args += [getattr(plan.terms, name) for name, _ in fields]
    return args


def _plan_metas(args: list) -> tuple:
    """(shape, dtype) layout of the packed plan buffer — part of the
    compiled-call cache key (dedup-table row counts vary per plan even
    at one TermsCfg, so the layout is not derivable from the cfg)."""
    return tuple((a.shape, str(np.asarray(a).dtype)) for a in args)


def _unpack_flat(flat, metas, off=None):
    """Traced inverse of the host-side pack: slice/reshape/bitcast the
    single flat int32 buffer back into the kernel's input arrays.
    Runs INSIDE the compiled call so the slices fuse into the one XLA
    program — no intermediate device buffers materialize (the axon
    relay pays ~25ms of serialized latency per buffer it touches,
    which made per-array plan shipping cost 0.5-1.4s per plan). With
    `off` (a traced scalar) the plan sits at a dynamic offset inside a
    GROUP buffer holding many plans (preload_plan_group)."""
    import jax.numpy as jnp
    from jax import lax

    outs = []
    o = 0
    for shape, dt in metas:
        n = int(np.prod(shape)) if shape else 1
        if off is None:
            seg = flat[o : o + n]
        else:
            seg = lax.dynamic_slice_in_dim(flat, off + o, n)
        seg = seg.reshape(shape)
        if dt == "float32":
            seg = lax.bitcast_convert_type(seg, jnp.float32)
        outs.append(seg)
        o += n
    return outs


def preload_plan_group(plans: list) -> None:
    """Ship MANY plans' packed buffers in ONE host->device transfer:
    the group concatenates into a single flat array, and each plan's
    cache entry records its offset — the compiled call then slices at
    a traced offset (_unpack_flat off). A multi-spec what-if's first
    round otherwise pays one serialized relay message per plan."""
    import jax

    entries = []
    flats = []
    o = 0
    for plan in plans:
        hit = _DEVICE_PLAN_CACHE.get(id(plan))
        if (hit is not None and hit[0] is plan) or any(
            e[0] is plan for e in entries
        ):
            continue  # already shipped
        args = _plan_args_np(plan)
        metas = _plan_metas(args)
        flat = np.concatenate(
            [np.ascontiguousarray(a).view(np.int32).reshape(-1) for a in args]
        )
        entries.append((plan, o, metas))
        flats.append(flat)
        o += int(flat.size)
    if not flats:
        return
    big = np.concatenate(flats)
    with jax.enable_x64(False):
        big_dev = jax.device_put(big)
    # insert the whole group first, THEN trim: per-insert eviction
    # could evict this group's own earlier entries when the group
    # exceeds the cap (freeing nothing — they share one buffer) and
    # silently re-serialize those plans' transfers
    for plan, off, metas in entries:
        _DEVICE_PLAN_CACHE[id(plan)] = (plan, (big_dev, off), metas)
    while len(_DEVICE_PLAN_CACHE) > max(16, len(entries)):
        _DEVICE_PLAN_CACHE.popitem(last=False)


def _device_args(plan: PallasPlan):
    """The plan's packed device buffer (ONE flat int32 array, ONE
    host->device transfer, cached per plan) plus its layout metas."""
    import jax

    hit = _DEVICE_PLAN_CACHE.get(id(plan))
    if hit is not None and hit[0] is plan:
        _DEVICE_PLAN_CACHE.move_to_end(id(plan))
        return hit[1], hit[2]
    args = _plan_args_np(plan)
    metas = _plan_metas(args)
    flat = np.concatenate(
        [np.ascontiguousarray(a).view(np.int32).reshape(-1) for a in args]
    )
    with jax.enable_x64(False):
        dev = jax.device_put(flat)
    if len(_DEVICE_PLAN_CACHE) >= 16:
        # evict the least-recently-used entry; a wholesale clear would
        # drop the device copies of plans still in active use
        _DEVICE_PLAN_CACHE.popitem(last=False)
    _DEVICE_PLAN_CACHE[id(plan)] = (plan, dev, metas)
    return dev, metas

# None = auto (use the kernel only on a real TPU backend — the Pallas
# interpreter would crawl at bench scale on CPU); tests set True to
# exercise the integration paths under interpret mode
FORCE_ENABLE: Optional[bool] = None


def kernel_label(plan: "PallasPlan") -> str:
    """The trace/bench label for a built plan — one definition so the
    engine's batch-kernel note and the bench's backend tag can never
    disagree about which kernel layout ran."""
    if plan.terms is not None and plan.terms.cfg.stream:
        return "pallas-stream"
    return "pallas"


def should_use() -> bool:
    """Whether eligible callers should run the fused kernel."""
    if FORCE_ENABLE is not None:
        return FORCE_ENABLE
    import jax

    return jax.default_backend() == "tpu"


def run_scan_pallas(plan: PallasPlan, class_of_pod, pod_active, node_valid,
                    pinned=None, interpret=None, defer=False):
    """Run the fused scan. Returns (placements[P] np.int32, final used
    dict in TRUE units for utilization reporting). `pinned` ([P] node
    index or -1; required when the plan was built with pins) forces
    spec.nodeName placements. `interpret` forces the Pallas interpreter
    (None = auto: interpret off-TPU). With `defer=True` the raw DEVICE
    output array is returned unfetched, so a caller dispatching many
    scans (defrag depths) can stack them and pay the ~0.1s relay sync
    once; decode each row-block with decode_scan_output."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    p_total = int(np.asarray(class_of_pod).shape[0])
    # dense (Pr, 128) packing: a (P, 1) VMEM array would be lane-padded
    # 128x by the (8, 128) tile layout (51 MB at 100k pods)
    pr_rows = _pr_rows(p_total)
    p_pad = pr_rows * LANES
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    tc = plan.terms.cfg if plan.terms is not None else None
    sc = plan.store.cfg if plan.store is not None else None
    flat_dev, metas = _device_args(plan)
    grouped = isinstance(flat_dev, tuple)
    key = (p_total, plan.r, plan.u, plan.w, plan.has_nodeaff, plan.has_taint,
           plan.has_pins, plan.s_n, plan.g_n, plan.pw, sc, tc, metas,
           grouped, interpret)
    cached = _COMPILED_CACHE.get(key)
    if cached is None:
        kernel = _make_kernel(p_total, plan.u, plan.w, plan.has_nodeaff,
                              plan.has_taint, plan.has_pins, plan.s_n,
                              plan.g_n, plan.pw, sc, tc)
        rc = (plan.r, LANES)
        base_n = (
            18 + int(plan.has_nodeaff) + int(plan.has_taint)
            + (3 if plan.s_n else 0) + (6 if plan.g_n else 0)
            + (3 if plan.pw else 0)
            + (len(_STORE_FIELDS) if sc is not None else 0)
        )
        stream = tc is not None and tc.stream
        term_fields = _STREAM_TERM_FIELDS if stream else _TERM_FIELDS
        n_in = base_n + (len(term_fields) if tc is not None else 0)
        # memory spaces: clsmap (base idx 3) in SMEM; the scalar/port
        # blocks sit at the end of the base args (alloc VMEM, init ANY,
        # tables SMEM); term-block spaces come from _TERM_FIELDS
        smem_idx = {3}
        any_idx = set()
        off = 18 + int(plan.has_nodeaff) + int(plan.has_taint)
        if plan.s_n:
            any_idx.add(off + 1)  # iscal0
            smem_idx.add(off + 2)  # req_scal
            off += 3
        if plan.g_n:
            any_idx.add(off + 3)  # igpu0
            smem_idx.update((off + 4, off + 5))  # gpu_mem_u / gpu_cnt_u
            off += 6
        if plan.pw:
            any_idx.add(off)  # ports0
            smem_idx.update((off + 1, off + 2))  # want/conflict words
            off += 3
        if sc is not None:
            for soff, (_, space) in enumerate(_STORE_FIELDS):
                if space == "any":
                    any_idx.add(off + soff)
                elif space == "smem":
                    smem_idx.add(off + soff)
            off += len(_STORE_FIELDS)
        if tc is not None:
            for toff, (_, space) in enumerate(term_fields):
                if space == "any":
                    any_idx.add(base_n + toff)
                elif space == "smem":
                    smem_idx.add(base_n + toff)

        scratch = []
        if plan.s_n or plan.g_n or plan.pw or sc is not None or tc is not None:
            from jax.experimental.pallas import tpu as _pltpu

            rl = (plan.r, LANES)
            if plan.s_n:
                scratch.append(_pltpu.VMEM((plan.s_n,) + rl, jnp.int32))
            if plan.g_n:
                scratch.append(_pltpu.VMEM((plan.g_n,) + rl, jnp.int32))
            if plan.pw:
                scratch.append(_pltpu.VMEM((plan.pw,) + rl, jnp.int32))
            if sc is not None:
                scratch += [
                    _pltpu.VMEM((sc.v,) + rl, jnp.int32),  # vg used
                    _pltpu.VMEM((sc.ds,) + rl, jnp.int32),  # ssd used
                    _pltpu.VMEM((sc.dh,) + rl, jnp.int32),  # hdd used
                ]
            if tc is not None:
                if stream:
                    scratch += [
                        _pltpu.VMEM((tc.a,) + rl, jnp.int32),  # group
                        _pltpu.VMEM((tc.a, SUBLANES, LANES), jnp.int32),
                        _pltpu.VMEM((tc.kmax,) + rl, jnp.int32),  # gather
                        _pltpu.SemaphoreType.DMA((_STREAM_NSEM,)),
                    ]
                else:
                    scratch += [
                        _pltpu.VMEM((tc.tc,) + rl, jnp.int32),  # tgt counts
                        _pltpu.VMEM((tc.tp,) + rl, jnp.int32),  # pref (combined)
                        _pltpu.VMEM((tc.tp,) + rl, jnp.int32),  # panti
                        _pltpu.VMEM((tc.bp,) + rl, jnp.int32),  # anti>0 bitplanes
                        _pltpu.VMEM((tc.bp,) + rl, jnp.int32),  # tgt>0 bitplanes
                        _pltpu.VMEM((tc.a,) + rl, jnp.int32),  # group
                        _pltpu.VMEM((tc.a, SUBLANES, LANES), jnp.int32),  # gtot
                        _pltpu.VMEM((tc.csn,) + rl, jnp.int32),  # soft non-host
                    ]
            scratch.append(_pltpu.SemaphoreType.DMA)

        n_ps = 8 * pr_rows * LANES
        n_act = pr_rows * LANES
        n_val = plan.r * LANES

        @jax.jit
        def call(percall, flat_plan):
            # both the per-call inputs and the plan ship as ONE packed
            # buffer each; the slices fuse into this program
            # (_unpack_flat) so no per-array device buffers ever
            # materialize — the relay pays ~25ms of serialized latency
            # per buffer it touches. Grouped plans add their offset as
            # the trailing percall element.
            off = percall[n_ps + n_act + n_val] if grouped else None
            arrays = [
                percall[:n_ps].reshape(8, pr_rows, LANES),
                percall[n_ps : n_ps + n_act].reshape(pr_rows, LANES),
                percall[n_ps + n_act : n_ps + n_act + n_val].reshape(
                    plan.r, LANES
                ),
            ] + _unpack_flat(flat_plan, metas, off)

            def spec(i):
                if i in any_idx:
                    return pl.BlockSpec(memory_space=pl.ANY)
                if i in smem_idx:
                    return pl.BlockSpec(memory_space=pltpu.SMEM)
                return pl.BlockSpec(memory_space=pltpu.VMEM)
            out_shape = [
                jax.ShapeDtypeStruct((pr_rows, LANES), jnp.int32),
            ] + [jax.ShapeDtypeStruct(rc, jnp.int32) for _ in range(6)]
            out_specs = [
                pl.BlockSpec(memory_space=pltpu.VMEM) for _ in range(7)
            ]
            if sc is not None:
                # final VG usage (capacity vg_util)
                out_shape.append(
                    jax.ShapeDtypeStruct((sc.v,) + rc, jnp.int32)
                )
                out_specs.append(pl.BlockSpec(memory_space=pltpu.VMEM))
            if stream:
                # the mutated term-state buffer stays in HBM (ANY) and
                # is never fetched; listing it as an output gives the
                # kernel a writable destination for the row DMAs
                out_shape.append(
                    jax.ShapeDtypeStruct((tc.srows, plan.r, LANES), jnp.int32)
                )
                out_specs.append(pl.BlockSpec(memory_space=pl.ANY))
            outs = pl.pallas_call(
                kernel,
                out_shape=tuple(out_shape),
                in_specs=[spec(i) for i in range(n_in)],
                out_specs=tuple(out_specs),
                scratch_shapes=scratch,
                interpret=interpret,
            )(*arrays)
            # ONE output array (placements + 6 states + any VG usage
            # concatenated on the row axis): every host-blocking point
            # on the relay costs ~0.1s regardless of size, so the whole
            # call must have exactly one — the single fetch below
            fetched = list(outs[:7])
            if sc is not None:
                fetched.append(outs[7].reshape(sc.v * plan.r, LANES))
            return jnp.concatenate(fetched, axis=0)

        cached = _Compiled(fn=call)
        _COMPILED_CACHE[key] = cached

    def pack(vec):
        out = np.zeros(p_pad, dtype=np.int32)
        out[:p_total] = vec
        return out.reshape(pr_rows, LANES)

    cls = np.asarray(class_of_pod, dtype=np.int32)
    # per-pod scalar rows: class + class-derived request scalars,
    # gathered host-side so the kernel never lane-indexes a class table;
    # row 7 carries the nodeName pin (-1 = loose). Rows 0-6 are
    # scenario-invariant — memoize per (plan, class array) so sweeps
    # that loop scenarios (defrag depths, capacity counts) pack once.
    memo_key = (id(plan), id(class_of_pod))
    hit = _POD_SCAL_CACHE.get(memo_key)
    if hit is not None and hit[0] is plan and hit[1] is class_of_pod:
        _POD_SCAL_CACHE.move_to_end(memo_key)
        pod_scal = hit[2].copy()
    else:
        pod_scal = np.zeros((8, pr_rows, LANES), dtype=np.int32)
        pod_scal[0] = pack(cls)
        for s in range(6):
            pod_scal[1 + s] = pack(plan.class_scalars[cls, s])
        if len(_POD_SCAL_CACHE) >= 16:
            _POD_SCAL_CACHE.popitem(last=False)
        _POD_SCAL_CACHE[memo_key] = (plan, class_of_pod, pod_scal.copy())
    if plan.has_pins:
        if pinned is None:
            raise ValueError("plan has pins: pass the pinned[] array")
        pin_vec = np.asarray(pinned, dtype=np.int32)
        pod_scal[7] = pack(np.where(pin_vec >= 0, pin_vec, -1))
    elif pinned is not None and (np.asarray(pinned) >= 0).any():
        raise ValueError("pinned pods but the plan was built without pins")
    active_2d = pack(np.asarray(pod_active).astype(np.int32))
    valid = _pad_nodes(np.asarray(node_valid).astype(np.int32), plan.r)

    # the engine enables x64 globally (ops/__init__.py) for the XLA
    # scan's int64 semantics, but this kernel is int32 by construction
    # and Mosaic's convert rules recurse on x64-promoted loop indices —
    # trace and run with x64 off
    with jax.enable_x64(False):
        # per-call inputs ride as ONE packed numpy buffer straight into
        # the dispatch: the implicit transfer pipelines with the
        # dispatch so the single np.asarray fetch is the call's only
        # sync point
        parts = [pod_scal.reshape(-1), active_2d.reshape(-1), valid.reshape(-1)]
        if grouped:
            flat_dev, off_v = flat_dev
            parts.append(np.array([off_v], dtype=np.int32))
        percall = np.concatenate(parts)
        out_d = cached.fn(percall, flat_dev)
        if defer:
            # caller batches several scans (e.g. defrag depths) and
            # fetches them stacked in ONE sync via decode_scan_output
            return out_d
        out = np.asarray(out_d)
    return decode_scan_output(plan, out, p_total)


def run_scan_pallas_batch(plan: PallasPlan, class_of_pod, scenarios):
    """Several scan scenarios with ONE device sync: each dispatches
    deferred, the outputs stack on the device, and one fetch pays the
    relay's per-sync latency for all of them (defrag depths, paired
    capacity probes). `scenarios` is a list of (pod_active, node_valid,
    pinned) triples; returns [(placements, final), ...]. Keeping the
    dispatch/stack/decode protocol here means the kernel's output
    row-split contract has exactly one consumer module."""
    import jax.numpy as jnp

    outs = [
        run_scan_pallas(
            plan, class_of_pod, pod_active, node_valid, pinned=pin, defer=True
        )
        for pod_active, node_valid, pin in scenarios
    ]
    stacked = np.asarray(jnp.stack(outs))
    p_total = int(np.asarray(class_of_pod).shape[0])
    return [decode_scan_output(plan, row, p_total) for row in stacked]


def decode_scan_output(plan: PallasPlan, out: np.ndarray, p_total: int):
    """Split a fetched kernel output row-block into (placements, final
    used dict) — the tail of run_scan_pallas, exposed for deferred
    (stacked-fetch) callers."""
    pr_rows = _pr_rows(p_total)
    place = out[:pr_rows]
    states = out[pr_rows : pr_rows + 6 * plan.r]
    place = place.reshape(-1)[:p_total]
    # map padded slots: any placement index beyond n means "no node"
    place = np.where((place >= 0) & (place >= plan.n), -1, place)
    st = states.reshape(6, -1)[:, : plan.n].astype(np.int64)
    final = {
        "used_mcpu": st[0],
        "used_mem": st[1] * plan.s_mem,
        "nz_mcpu": st[3],
        "nz_mem": st[4] * plan.s_nzmem,
        "pod_cnt": st[5],
    }
    if plan.store is not None:
        v = plan.store.cfg.v
        vg_rows = out[pr_rows + 6 * plan.r : pr_rows + (6 + v) * plan.r]
        # (V, R*C) scaled -> [N, V] bytes, the XLA final-state layout
        final["vg_used"] = (
            vg_rows.reshape(v, -1)[:, : plan.n].T.astype(np.int64)
            * plan.store.scale
        )
    return place, final
