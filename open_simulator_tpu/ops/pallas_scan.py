"""Fused Pallas TPU kernel for the sequential-commit scheduling scan.

The XLA `lax.scan` step (ops/scan.py) lowers to ~15-20 small kernels
per pod; at N=10k nodes each is latency-bound (~2-3us), so a 100k-pod
capacity probe costs ~3-4 s on a v5e chip. This module runs the ENTIRE
scan inside ONE `pl.pallas_call`: a `fori_loop` over pods with all
cluster state resident in VMEM as (R, 128) int32 tiles — per-step cost
collapses to pure VPU arithmetic with zero kernel-launch overhead.

Scope (automatic fallback to the XLA scan otherwise):
- no GPU-share / open-local / ports / inter-pod-affinity / topology-
  spread / custom-plugin / scalar-resource / nodeName-pin machinery
  (features gates, same contract as ScanFeatures),
- all quantities must fit exactness-preserving int32 encodings:
  memory/ephemeral values are divided by their collective GCD
  (floor-division identities keep every score and fit comparison
  bit-identical to the int64 XLA path), with magnitude guards.

Semantics replicated from ops/scan.py (which is conformance-tested
against the serial oracle):
- NodeResourcesFit (noderesources/fit.go:230-303) incl. the
  zero-request pod-count-only fast path,
- LeastAllocated / BalancedAllocation / NodeAffinity / TaintToleration
  / Simon / ImageLocality / NodePreferAvoidPods scores with their
  normalizes (normalize_score.go:26-53, simon.go:75-100),
- first-max tie rule over feasible nodes (documented deviation shared
  with the XLA engine, scan.py:19-21),
- capacity-sweep masking: node_valid gates candidates, inactive pods
  commit nothing and report INACTIVE.

BalancedAllocation is computed in f32 here (the XLA path uses the
default float width); its inputs are <=24-bit scaled integers so the
fractions are exact in f32 and only the final (1-|d|)*100 truncation
could differ — conformance tests (tests/test_pallas_scan.py) pin
agreement with the XLA path on randomized scenarios.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

LANES = 128
SUBLANES = 8
NEG = -(2**31) + 1
BIG = 2**31 - 1
MAX_SCORE = 100
INACTIVE = -2

# magnitude guards: every intermediate must stay inside int32
_MAX_SCALED = (2**31 - 1) // (MAX_SCORE + 1)


class PallasPlan(NamedTuple):
    """Host-side (numpy) arrays prepared for the kernel, all padded to
    (R, 128) node tiles / int32."""

    n: int  # true node count
    r: int  # padded rows (multiple of 8)
    u: int  # class count
    # [R, C] node vectors
    alloc_mcpu: np.ndarray
    alloc_mem_s: np.ndarray  # fit-scaled
    alloc_eph_s: np.ndarray
    alloc_pods: np.ndarray
    alloc_nzmem_s: np.ndarray  # nz-scaled (balanced/least denominator)
    # [U, R, C] class tables
    static_feasible: np.ndarray
    simon_raw: np.ndarray
    nodeaff_raw: np.ndarray
    taint_intol: np.ndarray
    base_score: np.ndarray  # prefolded image*w_image + avoid*w_avoid
    # [U, 8] class scalars: req_mcpu, req_mem_s, req_eph_s, nz_mcpu,
    # nz_mem_s, has_request, 0, 0
    class_scalars: np.ndarray
    # init state [R, C] i32 x6
    init_used_mcpu: np.ndarray
    init_used_mem_s: np.ndarray
    init_used_eph_s: np.ndarray
    init_nz_mcpu: np.ndarray
    init_nz_mem_s: np.ndarray
    init_pod_cnt: np.ndarray
    # scales to recover true units
    s_mem: int
    s_eph: int
    s_nzmem: int
    # weights (least, balanced, simon+gpushare, nodeaff, tainttol)
    w: tuple
    has_nodeaff: bool
    has_taint: bool


def _pad_nodes(vec: np.ndarray, r: int, fill=0) -> np.ndarray:
    out = np.full(r * LANES, fill, dtype=np.int32)
    out[: vec.shape[0]] = vec
    return out.reshape(r, LANES)


def _pad_class_table(tab: np.ndarray, r: int, fill=0) -> np.ndarray:
    u, n = tab.shape
    out = np.full((u, r * LANES), fill, dtype=np.int32)
    out[:, :n] = tab
    return out.reshape(u, r, LANES)


def _gcd_scale(*arrays) -> int:
    vals = np.concatenate([np.asarray(a, dtype=np.int64).ravel() for a in arrays])
    vals = vals[vals > 0]
    if vals.size == 0:
        return 1
    return int(np.gcd.reduce(vals))


def build_plan(cluster, batch, dyn, features, weights=None) -> Optional[PallasPlan]:
    """Build a kernel plan from the (numpy) ClusterStatic + PodBatch +
    DynamicState, or None when the batch is outside the fast path's
    scope."""
    if (
        features.gpu
        or features.storage
        or features.ipa
        or features.hard_spread
        or features.soft_spread
        or features.ports
        or features.scalars
        or features.custom
        or features.pins
    ):
        return None

    from ..scheduler.schedconfig import DEFAULT_SCORE_WEIGHTS, ScoreWeights

    w = ScoreWeights(*weights) if weights is not None else DEFAULT_SCORE_WEIGHTS
    # plugins the kernel does not model must be disabled or irrelevant
    # (ipa/spread/openlocal have no terms here by the gates above)

    a = np.asarray
    alloc_mcpu = a(cluster.alloc_mcpu, dtype=np.int64)
    alloc_mem = a(cluster.alloc_mem, dtype=np.int64)
    alloc_eph = a(cluster.alloc_eph, dtype=np.int64)
    alloc_pods = a(cluster.alloc_pods, dtype=np.int64)
    req_mcpu = a(batch.req_mcpu, dtype=np.int64)
    req_mem = a(batch.req_mem, dtype=np.int64)
    req_eph = a(batch.req_eph, dtype=np.int64)
    nz_mcpu = a(batch.nz_mcpu, dtype=np.int64)
    nz_mem = a(batch.nz_mem, dtype=np.int64)
    init_used_mcpu = a(dyn.used_mcpu, dtype=np.int64)
    init_used_mem = a(dyn.used_mem, dtype=np.int64)
    init_used_eph = a(dyn.used_eph, dtype=np.int64)
    init_nz_mcpu = a(dyn.nz_mcpu, dtype=np.int64)
    init_nz_mem = a(dyn.nz_mem, dtype=np.int64)
    init_pod_cnt = a(dyn.pod_cnt, dtype=np.int64)

    s_mem = _gcd_scale(alloc_mem, req_mem, init_used_mem)
    s_eph = _gcd_scale(alloc_eph, req_eph, init_used_eph)
    s_nzmem = _gcd_scale(alloc_mem, nz_mem, init_nz_mem)

    simon_raw = a(batch.simon_raw, dtype=np.int64)
    nodeaff_raw = a(batch.nodeaff_raw, dtype=np.int64)
    taint_intol = a(batch.taint_intol, dtype=np.int64)
    image_score = a(batch.image_score, dtype=np.int64)
    avoid_score = a(batch.avoid_score, dtype=np.int64)
    base_score = image_score * int(w.image) + avoid_score * int(w.avoid)

    # int32 exactness guards
    checks = [
        alloc_mcpu.max(initial=0) <= _MAX_SCALED,
        (alloc_mem // s_mem).max(initial=0) <= _MAX_SCALED,
        (alloc_eph // s_eph).max(initial=0) <= _MAX_SCALED,
        (alloc_mem // s_nzmem).max(initial=0) <= _MAX_SCALED,
        alloc_pods.max(initial=0) <= _MAX_SCALED,
        simon_raw.max(initial=0) <= _MAX_SCALED,
        simon_raw.min(initial=0) >= 0,
        nodeaff_raw.max(initial=0) <= _MAX_SCALED,
        nodeaff_raw.min(initial=0) >= 0,
        taint_intol.max(initial=0) <= _MAX_SCALED,
        taint_intol.min(initial=0) >= 0,
        np.abs(base_score).max(initial=0) <= 2**24,
        # balanced runs in f32: its scaled inputs must be f32-exact
        (alloc_mem // s_nzmem).max(initial=0) < 2**24,
        alloc_mcpu.max(initial=0) < 2**24,
    ]
    if not all(bool(c) for c in checks):
        return None

    n = alloc_mcpu.shape[0]
    u = req_mcpu.shape[0]
    r = -(-n // LANES)
    r = -(-r // SUBLANES) * SUBLANES  # row count multiple of 8

    class_scalars = np.zeros((u, 8), dtype=np.int32)
    class_scalars[:, 0] = req_mcpu
    class_scalars[:, 1] = req_mem // s_mem
    class_scalars[:, 2] = req_eph // s_eph
    class_scalars[:, 3] = nz_mcpu
    class_scalars[:, 4] = nz_mem // s_nzmem
    class_scalars[:, 5] = a(batch.has_request).astype(np.int32)

    return PallasPlan(
        n=n,
        r=r,
        u=u,
        alloc_mcpu=_pad_nodes(alloc_mcpu, r),
        alloc_mem_s=_pad_nodes(alloc_mem // s_mem, r),
        alloc_eph_s=_pad_nodes(alloc_eph // s_eph, r),
        alloc_pods=_pad_nodes(alloc_pods, r),
        alloc_nzmem_s=_pad_nodes(alloc_mem // s_nzmem, r),
        static_feasible=_pad_class_table(
            a(batch.static_feasible).astype(np.int32), r
        ),
        simon_raw=_pad_class_table(simon_raw, r),
        nodeaff_raw=_pad_class_table(nodeaff_raw, r),
        taint_intol=_pad_class_table(taint_intol, r),
        base_score=_pad_class_table(base_score, r),
        class_scalars=class_scalars,
        init_used_mcpu=_pad_nodes(init_used_mcpu, r),
        init_used_mem_s=_pad_nodes(init_used_mem // s_mem, r),
        init_used_eph_s=_pad_nodes(init_used_eph // s_eph, r),
        init_nz_mcpu=_pad_nodes(init_nz_mcpu, r),
        init_nz_mem_s=_pad_nodes(init_nz_mem // s_nzmem, r),
        init_pod_cnt=_pad_nodes(init_pod_cnt, r),
        s_mem=s_mem,
        s_eph=s_eph,
        s_nzmem=s_nzmem,
        w=(int(w.least), int(w.balanced), int(w.simon) + int(w.gpushare),
           int(w.nodeaff), int(w.tainttol)),
        has_nodeaff=bool(nodeaff_raw.any()),
        has_taint=bool(taint_intol.any()),
    )


def _make_kernel(p_total: int, w: tuple, has_nodeaff: bool, has_taint: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    w_least, w_bal, w_simon, w_na, w_tt = w

    def kernel(
        pod_scal_ref,  # (8, Pr, 128) i32: class, rc, rm, re, nzc, nzm,
        #                has_req, unused — pod p at [:, p//128, p%128]
        active_ref,  # (Pr, 128) i32
        valid_ref,  # (R, C) i32
        alloc_c_ref,
        alloc_m_ref,
        alloc_e_ref,
        alloc_p_ref,
        alloc_nzm_ref,
        feas_ref,  # (U, R, C)
        simon_ref,
        na_ref,
        tt_ref,
        base_ref,
        ic_ref,  # init-state inputs, copied into the state outputs at
        im_ref,  # kernel start (output aliasing does NOT initialize
        ie_ref,  # aliased outputs on TPU — unread inputs are elided)
        inzc_ref,
        inzm_ref,
        ipc_ref,
        place_ref,  # out (Pr, 128) i32, same packing
        st_c_ref,  # out state, accumulated in VMEM
        st_m_ref,
        st_e_ref,
        st_nzc_ref,
        st_nzm_ref,
        st_p_ref,
    ):
        shape = valid_ref.shape
        rows = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
        idx_mat = rows * LANES + cols
        lane_iota = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)

        valid = valid_ref[:] != 0
        alloc_c = alloc_c_ref[:]
        alloc_m = alloc_m_ref[:]
        alloc_e = alloc_e_ref[:]
        alloc_p = alloc_p_ref[:]
        alloc_nzm = alloc_nzm_ref[:]
        alloc_c_f = alloc_c.astype(jnp.float32)
        alloc_nzm_f = alloc_nzm.astype(jnp.float32)

        st_c_ref[:] = ic_ref[:]
        st_m_ref[:] = im_ref[:]
        st_e_ref[:] = ie_ref[:]
        st_nzc_ref[:] = inzc_ref[:]
        st_nzm_ref[:] = inzm_ref[:]
        st_p_ref[:] = ipc_ref[:]

        def step(p, _):
            # dynamic lane-dim loads are unsupported on TPU: read the
            # pod's 128-lane row and extract via a masked reduce
            pr = p // LANES
            pc = p % LANES
            lane = lane_iota == pc

            def pod_scalar(s):
                row = pod_scal_ref[s, pl.ds(pr, 1), :]
                return jnp.sum(jnp.where(lane, row, 0))

            u = pod_scalar(0)
            rc = pod_scalar(1)
            rm = pod_scalar(2)
            re = pod_scalar(3)
            nzc = pod_scalar(4)
            nzm = pod_scalar(5)
            has_req = pod_scalar(6)
            active = jnp.sum(jnp.where(lane, active_ref[pl.ds(pr, 1), :], 0))

            used_c = st_c_ref[:]
            used_m = st_m_ref[:]
            used_e = st_e_ref[:]
            st_nzc = st_nzc_ref[:]
            st_nzm = st_nzm_ref[:]
            pod_cnt = st_p_ref[:]

            fit = (
                (used_c + rc <= alloc_c)
                & (used_m + rm <= alloc_m)
                & (used_e + re <= alloc_e)
            )
            feas = (
                (feas_ref[u] != 0)
                & valid
                & (pod_cnt + 1 <= alloc_p)
                & (fit | (has_req == 0))
            )

            # LeastAllocated (least_allocated.go:108-117)
            totc = st_nzc + nzc
            totm = st_nzm + nzm
            ok_c = (alloc_c > 0) & (totc <= alloc_c)
            ok_m = (alloc_nzm > 0) & (totm <= alloc_nzm)
            least_c = jnp.where(
                ok_c, (alloc_c - totc) * MAX_SCORE // jnp.maximum(alloc_c, 1), 0
            )
            least_m = jnp.where(
                ok_m, (alloc_nzm - totm) * MAX_SCORE // jnp.maximum(alloc_nzm, 1), 0
            )
            total = base_ref[u] + ((least_c + least_m) // 2) * w_least

            if w_bal:
                # BalancedAllocation: fractions are exact in f32 (inputs
                # < 2^24); only the final truncation is float
                cpu_frac = totc.astype(jnp.float32) / jnp.maximum(alloc_c_f, 1.0)
                cpu_frac = jnp.where(alloc_c > 0, cpu_frac, 1.0)
                mem_frac = totm.astype(jnp.float32) / jnp.maximum(alloc_nzm_f, 1.0)
                mem_frac = jnp.where(alloc_nzm > 0, mem_frac, 1.0)
                balanced = jnp.where(
                    (cpu_frac >= 1.0) | (mem_frac >= 1.0),
                    0,
                    ((1.0 - jnp.abs(cpu_frac - mem_frac)) * MAX_SCORE).astype(
                        jnp.int32
                    ),
                )
                total = total + balanced * w_bal

            if w_simon:
                raw = simon_ref[u]
                hi = jnp.max(jnp.where(feas, raw, NEG))
                lo = jnp.min(jnp.where(feas, raw, BIG))
                rng = hi - lo
                sim = jnp.where(
                    rng > 0, (raw - lo) * MAX_SCORE // jnp.maximum(rng, 1), 0
                )
                total = total + sim * w_simon

            if w_na and has_nodeaff:
                raw = na_ref[u]
                mx = jnp.max(jnp.where(feas, raw, 0))
                na = jnp.where(mx > 0, MAX_SCORE * raw // jnp.maximum(mx, 1), 0)
                total = total + na * w_na

            if w_tt and has_taint:
                raw = tt_ref[u]
                mx = jnp.max(jnp.where(feas, raw, 0))
                base = jnp.where(mx > 0, MAX_SCORE * raw // jnp.maximum(mx, 1), 0)
                tt = jnp.where(mx > 0, MAX_SCORE - base, MAX_SCORE)
                total = total + tt * w_tt

            masked = jnp.where(feas, total, NEG)
            m = jnp.max(masked)
            found = m > NEG
            cand = jnp.where(feas & (masked == m), idx_mat, BIG)
            best = jnp.min(cand)

            place = jnp.where(
                active != 0, jnp.where(found, best, -1), INACTIVE
            )
            # dynamic lane-dim stores are unsupported on TPU: rewrite
            # only the pod's 128-lane row, lane-selected via the mask
            prow = place_ref[pl.ds(pr, 1), :]
            place_ref[pl.ds(pr, 1), :] = jnp.where(lane, place, prow)

            do = found & (active != 0)
            sel = (idx_mat == best) & do
            st_c_ref[:] = used_c + jnp.where(sel, rc, 0)
            st_m_ref[:] = used_m + jnp.where(sel, rm, 0)
            st_e_ref[:] = used_e + jnp.where(sel, re, 0)
            st_nzc_ref[:] = st_nzc + jnp.where(sel, nzc, 0)
            st_nzm_ref[:] = st_nzm + jnp.where(sel, nzm, 0)
            st_p_ref[:] = pod_cnt + jnp.where(sel, 1, 0)
            return 0

        jax.lax.fori_loop(0, p_total, step, 0)

    return kernel


class _Compiled(NamedTuple):
    fn: object


_COMPILED_CACHE: dict = {}

# None = auto (use the kernel only on a real TPU backend — the Pallas
# interpreter would crawl at bench scale on CPU); tests set True to
# exercise the integration paths under interpret mode
FORCE_ENABLE: Optional[bool] = None


def should_use() -> bool:
    """Whether eligible callers should run the fused kernel."""
    if FORCE_ENABLE is not None:
        return FORCE_ENABLE
    import jax

    return jax.default_backend() == "tpu"


def run_scan_pallas(plan: PallasPlan, class_of_pod, pod_active, node_valid,
                    interpret=None):
    """Run the fused scan. Returns (placements[P] np.int32, final used
    dict in TRUE units for utilization reporting). `interpret` forces
    the Pallas interpreter (None = auto: interpret off-TPU)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    p_total = int(np.asarray(class_of_pod).shape[0])
    # dense (Pr, 128) packing: a (P, 1) VMEM array would be lane-padded
    # 128x by the (8, 128) tile layout (51 MB at 100k pods)
    pr_rows = max(-(-p_total // LANES), 1)
    pr_rows = -(-pr_rows // SUBLANES) * SUBLANES
    p_pad = pr_rows * LANES
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    key = (p_total, plan.r, plan.u, plan.w, plan.has_nodeaff, plan.has_taint,
           interpret)
    cached = _COMPILED_CACHE.get(key)
    if cached is None:
        kernel = _make_kernel(p_total, plan.w, plan.has_nodeaff, plan.has_taint)
        rc = (plan.r, LANES)

        @jax.jit
        def call(pod_scal, active_2d, valid, ac, am, ae, ap, anzm,
                 feas, simon, na, tt, base,
                 ic, im, ie, inzc, inzm, ipc):
            def vm():
                return pl.BlockSpec(memory_space=pltpu.VMEM)
            outs = pl.pallas_call(
                kernel,
                out_shape=(
                    jax.ShapeDtypeStruct((pr_rows, LANES), jnp.int32),
                    jax.ShapeDtypeStruct(rc, jnp.int32),
                    jax.ShapeDtypeStruct(rc, jnp.int32),
                    jax.ShapeDtypeStruct(rc, jnp.int32),
                    jax.ShapeDtypeStruct(rc, jnp.int32),
                    jax.ShapeDtypeStruct(rc, jnp.int32),
                    jax.ShapeDtypeStruct(rc, jnp.int32),
                ),
                in_specs=[vm() for _ in range(19)],
                out_specs=tuple(vm() for _ in range(7)),
                interpret=interpret,
            )(
                pod_scal, active_2d, valid, ac, am, ae, ap, anzm,
                feas, simon, na, tt, base,
                ic, im, ie, inzc, inzm, ipc,
            )
            return outs

        cached = _Compiled(fn=call)
        _COMPILED_CACHE[key] = cached

    def pack(vec):
        out = np.zeros(p_pad, dtype=np.int32)
        out[:p_total] = vec
        return out.reshape(pr_rows, LANES)

    cls = np.asarray(class_of_pod, dtype=np.int32)
    # per-pod scalar rows: class + class-derived request scalars,
    # gathered host-side so the kernel never lane-indexes a class table
    pod_scal = np.zeros((8, pr_rows, LANES), dtype=np.int32)
    pod_scal[0] = pack(cls)
    for s in range(6):
        pod_scal[1 + s] = pack(plan.class_scalars[cls, s])
    active_2d = pack(np.asarray(pod_active).astype(np.int32))
    valid = _pad_nodes(np.asarray(node_valid).astype(np.int32), plan.r)

    # the engine enables x64 globally (ops/__init__.py) for the XLA
    # scan's int64 semantics, but this kernel is int32 by construction
    # and Mosaic's convert rules recurse on x64-promoted loop indices —
    # trace and run with x64 off
    with jax.enable_x64(False):
        outs = cached.fn(
            pod_scal, active_2d, valid,
            plan.alloc_mcpu, plan.alloc_mem_s, plan.alloc_eph_s, plan.alloc_pods,
            plan.alloc_nzmem_s,
            plan.static_feasible, plan.simon_raw, plan.nodeaff_raw,
            plan.taint_intol, plan.base_score,
            plan.init_used_mcpu, plan.init_used_mem_s, plan.init_used_eph_s,
            plan.init_nz_mcpu, plan.init_nz_mem_s, plan.init_pod_cnt,
        )
        outs = [np.asarray(o) for o in outs]
    place = np.asarray(outs[0]).reshape(-1)[:p_total]
    # map padded slots: any placement index beyond n means "no node"
    place = np.where((place >= 0) & (place >= plan.n), -1, place)
    final = {
        "used_mcpu": np.asarray(outs[1]).reshape(-1)[: plan.n].astype(np.int64),
        "used_mem": np.asarray(outs[2]).reshape(-1)[: plan.n].astype(np.int64)
        * plan.s_mem,
        "nz_mcpu": np.asarray(outs[4]).reshape(-1)[: plan.n].astype(np.int64),
        "nz_mem": np.asarray(outs[5]).reshape(-1)[: plan.n].astype(np.int64)
        * plan.s_nzmem,
        "pod_cnt": np.asarray(outs[6]).reshape(-1)[: plan.n].astype(np.int64),
    }
    return place, final
