"""Fused Pallas TPU kernel for the sequential-commit scheduling scan.

The XLA `lax.scan` step (ops/scan.py) lowers to ~15-20 small kernels
per pod; at N=10k nodes each is latency-bound (~2-3us), so a 100k-pod
capacity probe costs ~3-4 s on a v5e chip. This module runs the ENTIRE
scan inside ONE `pl.pallas_call`: a `fori_loop` over pods with all
cluster state resident in VMEM as (R, 128) int32 tiles — per-step cost
collapses to pure VPU arithmetic with zero kernel-launch overhead.

Scope (automatic fallback to the XLA scan otherwise):
- no GPU-share / open-local / ports / custom-plugin / scalar-resource
  machinery (features gates, same contract as ScanFeatures); nodeName
  pins ARE in scope (`run_scan_pallas(pinned=...)`),
- inter-pod affinity + hard/soft topology spread ARE in scope: term
  count state rides in VMEM scratch as node-space (T, R, 128) i32
  tiles (ops/scan.py ScanState docstring), per-(class, slot) eval
  scalars are prefolded host-side into SMEM tables, init states stream
  in from ANY/HBM by DMA, and commits are masked broadcasts over
  (topo_val == placed value),
- all quantities must fit exactness-preserving int32 encodings:
  memory/ephemeral values are divided by their collective GCD
  (floor-division identities keep every score and fit comparison
  bit-identical to the int64 XLA path), with magnitude guards
  (_build_terms bounds for counts/weights/raw scores).

Semantics replicated from ops/scan.py (which is conformance-tested
against the serial oracle):
- NodeResourcesFit (noderesources/fit.go:230-303) incl. the
  zero-request pod-count-only fast path,
- LeastAllocated / BalancedAllocation / NodeAffinity / TaintToleration
  / Simon / ImageLocality / NodePreferAvoidPods scores with their
  normalizes (normalize_score.go:26-53, simon.go:75-100),
- InterPodAffinity filter/score (filtering.go:241-430, scoring.go) and
  PodTopologySpread hard filter + soft score (podtopologyspread/),
- first-max tie rule over feasible nodes (documented deviation shared
  with the XLA engine, scan.py:19-21),
- capacity-sweep masking: node_valid gates candidates, inactive pods
  commit nothing and report INACTIVE.

Float care: BalancedAllocation runs in f32 (inputs are <=24-bit scaled
integers, fractions exact, only the final truncation is float). The
soft-spread score needs f64 (cnt * log(sz+2)); TPU Pallas has no f64,
so it runs in double-single f32: log tables are precomputed in f64 on
the host and split into (hi, lo) f32 pairs with hi further Veltkamp-
split into 12-bit halves, partial products of the 8/9-bit-split count
are exact in f32, and 2Sum chains carry the compensation — ~2^-45
relative error against the XLA path's f64, far below the integer
truncation granularity. Conformance tests (tests/test_pallas_scan.py,
tests/test_pallas_terms.py) pin agreement with the XLA path.

Host<->device traffic is the latency floor on a relay-attached chip
(~0.1s per blocking transfer): plan arrays are device-cached per plan
(_device_args), inputs ship as one batched device_put, and the six
state outputs return stacked as a single fetch.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import NamedTuple, Optional

import numpy as np

LANES = 128
SUBLANES = 8
NEG = -(2**31) + 1
BIG = 2**31 - 1
MAX_SCORE = 100
INACTIVE = -2

# magnitude guards: every intermediate must stay inside int32
_MAX_SCALED = (2**31 - 1) // (MAX_SCORE + 1)


class TermsCfg(NamedTuple):
    """Static shape/slot configuration of the term machinery (part of
    the compiled-kernel cache key)."""

    t: int  # term rows
    a: int  # required-affinity group rows
    gn: int  # group count
    ch: int  # hard spread instances
    cs: int  # soft spread instances
    rmax: int  # per-class relevant-row slots
    gmax: int  # per-class group-row slots
    hmax: int  # per-class hard slots
    smax: int  # per-class soft slots
    vs: int  # non-hostname soft vocab size
    has_ipa: bool
    has_hard: bool
    has_soft: bool


class TermsPlan(NamedTuple):
    """Term-machinery arrays for the fused kernel: node-space count
    state as (T, R, 128) i32 tiles (ops/scan.py ScanState docstring),
    per-class tables lane-padded for masked-reduce scalar reads."""

    cfg: TermsCfg
    topo3: np.ndarray  # (T, R, C) i32, -1 = key missing
    tgt0: np.ndarray  # (T, R, C) i32 init counts
    own_anti0: np.ndarray  # (T, R, C)
    own_pref0: np.ndarray  # (T, R, C) combined (scan.py ScanState)
    own_panti0: np.ndarray  # (T, R, C)
    # commit tables: column u is read per step, vectorized over T
    term_match_tu: np.ndarray  # (T, Up) i32
    carry_anti_tu: np.ndarray  # (T, Up)
    carry_prefc_tu: np.ndarray  # (T, Up) prefolded commit increment
    carry_panti_tu: np.ndarray  # (T, Up)
    # SMEM slot tables: every per-(row, class) eval scalar prefolded to
    # (U, slot) so the kernel's unrolled slot loops do scalar SMEM
    # loads instead of masked VPU reduces (~40 reduces/step saved)
    slot_rows: np.ndarray  # (U, Rmax) i32 cls_rows
    slot_m: np.ndarray  # (U, Rmax) term_match[row, u]
    slot_cpaff: np.ndarray  # (U, Rmax) carry_aff_pref_w[row, u]
    slot_cpanti: np.ndarray  # (U, Rmax)
    slot_canti: np.ndarray  # (U, Rmax)
    gid_u: np.ndarray  # (U,)
    self_ok_u: np.ndarray  # (U,) match_all[gid, u]
    slot_grows: np.ndarray  # (U, Gmax)
    slot_h: np.ndarray  # (U, Hmax)
    slot_hself: np.ndarray  # (U, Hmax) h_self[h, u]
    h_row_s: np.ndarray  # (Ch,)
    h_skew_s: np.ndarray  # (Ch,)
    slot_s: np.ndarray  # (U, Smax)
    s_row_s: np.ndarray  # (Cs,)
    s_is_host_s: np.ndarray  # (Cs,)
    s_skew_s: np.ndarray  # (Cs,)
    # groups
    g_topo3: np.ndarray  # (A, R, C)
    group0: np.ndarray  # (A, R, C)
    gtot0: np.ndarray  # (A, 8, 128) per-group-row totals, replicated
    g_match_au: np.ndarray  # (A, Up) = match_all[group_of_row]
    # hard spread (term-row values read from topo3 via h_row_s)
    cand3: np.ndarray  # (Ch, R, C) candidate nodes
    # soft spread
    soft0: np.ndarray  # (Cs, R, C)
    s_topo3: np.ndarray  # (Cs, R, C)
    s_q3: np.ndarray  # (Cs, R, C)
    s_match_cu: np.ndarray  # (Cs, Up) = term_match[s_row] (commit)
    haskeys3: np.ndarray  # (U, R, C)
    # f64 log-weight tables split for double-single arithmetic:
    # w = log(sz+2) computed in f64 on host; hi/lo f32 split, hi further
    # split into 12-bit halves h1+h2 for exact f32 products; 1-D SMEM
    w_hi: np.ndarray  # (Wn,) f32
    w_lo: np.ndarray
    w_h1: np.ndarray
    w_h2: np.ndarray


class PallasPlan(NamedTuple):
    """Host-side (numpy) arrays prepared for the kernel, all padded to
    (R, 128) node tiles / int32."""

    n: int  # true node count
    r: int  # padded rows (multiple of 8)
    u: int  # class count
    # [R, C] node vectors
    alloc_mcpu: np.ndarray
    alloc_mem_s: np.ndarray  # fit-scaled
    alloc_eph_s: np.ndarray
    alloc_pods: np.ndarray
    alloc_nzmem_s: np.ndarray  # nz-scaled (balanced/least denominator)
    # [U, R, C] class tables
    static_feasible: np.ndarray
    simon_raw: np.ndarray
    nodeaff_raw: np.ndarray
    taint_intol: np.ndarray
    base_score: np.ndarray  # prefolded image*w_image + avoid*w_avoid
    # [U, 8] class scalars: req_mcpu, req_mem_s, req_eph_s, nz_mcpu,
    # nz_mem_s, has_request, 0, 0
    class_scalars: np.ndarray
    # init state [R, C] i32 x6
    init_used_mcpu: np.ndarray
    init_used_mem_s: np.ndarray
    init_used_eph_s: np.ndarray
    init_nz_mcpu: np.ndarray
    init_nz_mem_s: np.ndarray
    init_pod_cnt: np.ndarray
    # scales to recover true units
    s_mem: int
    s_eph: int
    s_nzmem: int
    # weights (least, balanced, simon+gpushare, nodeaff, tainttol,
    # spread, ipa)
    w: tuple
    has_nodeaff: bool
    has_taint: bool
    has_pins: bool  # any pod arrives with spec.nodeName
    # inter-pod affinity / topology-spread machinery (None = batch has
    # no terms)
    terms: Optional[TermsPlan]


def _pad_nodes(vec: np.ndarray, r: int, fill=0) -> np.ndarray:
    out = np.full(r * LANES, fill, dtype=np.int32)
    out[: vec.shape[0]] = vec
    return out.reshape(r, LANES)


def _pad_class_table(tab: np.ndarray, r: int, fill=0) -> np.ndarray:
    u, n = tab.shape
    out = np.full((u, r * LANES), fill, dtype=np.int32)
    out[:, :n] = tab
    return out.reshape(u, r, LANES)


def _gcd_scale(*arrays) -> int:
    vals = np.concatenate([np.asarray(a, dtype=np.int64).ravel() for a in arrays])
    vals = vals[vals > 0]
    if vals.size == 0:
        return 1
    return int(np.gcd.reduce(vals))


def _pad_lanes(vec: np.ndarray, dtype=np.int32, fill=0) -> np.ndarray:
    """1-D vector -> (8, Lp) tile, data in row 0."""
    lp = max(-(-vec.shape[0] // LANES) * LANES, LANES)
    out = np.full((SUBLANES, lp), fill, dtype=dtype)
    out[0, : vec.shape[0]] = vec
    return out


def _pad_table(tab: np.ndarray, fill=0, dtype=np.int32) -> np.ndarray:
    """(X, Y) table -> (Xp, Yp) with sublane/lane padding."""
    x, y = tab.shape
    xp = max(-(-x // SUBLANES) * SUBLANES, SUBLANES)
    yp = max(-(-y // LANES) * LANES, LANES)
    out = np.full((xp, yp), fill, dtype=dtype)
    out[:x, :y] = tab
    return out


def _pad_stack(tab: np.ndarray, r: int, fill=0) -> np.ndarray:
    """(X, N) node table -> (Xp, R, C) i32 node tiles."""
    x, n = tab.shape
    xp = max(x, 1)
    out = np.full((xp, r * LANES), fill, dtype=np.int32)
    out[:x, :n] = tab
    return out.reshape(xp, r, LANES)


# slot-count caps keep the kernel's static unrolled loops small; a batch
# beyond them falls back to the XLA scan
_MAX_SLOTS = dict(rmax=8, gmax=4, hmax=4, smax=4, a=8, gn=8, vs=32)
_MAX_COUNT = 1 << 17  # cnt exact-split bound for the soft f64 emulation
_MAX_T = 512


def _build_terms(batch, features, r: int, p_total: int, n: int) -> Optional[TermsPlan]:
    """Term-machinery plan, or None when out of the kernel's scope."""
    t = batch.terms
    has_ipa = bool(features.ipa)
    has_hard = bool(features.hard_spread)
    has_soft = bool(features.soft_spread)

    if t.t > _MAX_T or t.rmax > _MAX_SLOTS["rmax"] or t.gmax > _MAX_SLOTS["gmax"]:
        return None
    if t.hmax > _MAX_SLOTS["hmax"] or t.smax > _MAX_SLOTS["smax"]:
        return None
    if t.a > _MAX_SLOTS["a"] or len(t.match_all) > _MAX_SLOTS["gn"]:
        return None
    if batch.u > LANES or t.ch > 120 or t.cs > 120:
        return None  # lane-table reads assume one 128-lane row

    from .encode import _value_to_node_space
    from .terms import combined_pref_carry, combined_pref_init

    tv = t.topo_val
    tgt0 = _value_to_node_space(t.init_tgt, tv)
    own_anti0 = _value_to_node_space(t.init_own_anti_req, tv)
    own_pref0 = _value_to_node_space(combined_pref_init(t), tv)
    own_panti0 = _value_to_node_space(t.init_own_anti_pref_w, tv)
    group0 = _value_to_node_space(t.init_group_counts, tv[t.group_rows])
    soft0 = _value_to_node_space(t.init_soft_counts, tv[t.s_row])
    carry_prefc = combined_pref_carry(t)

    # int32 exactness bounds (documented in the module docstring)
    cnt_max = int(tgt0.max(initial=0)) + p_total
    pref_max = int(
        max(own_pref0.max(initial=0), own_panti0.max(initial=0))
    ) + p_total * int(
        max(np.abs(carry_prefc).max(initial=0), np.abs(t.carry_anti_pref_w).max(initial=0), 1)
    )
    ipa_raw_max = t.rmax * (
        int(
            (np.abs(t.carry_aff_pref_w) + np.abs(t.carry_anti_pref_w)).max(initial=0)
        )
        * cnt_max
        + 2 * pref_max
    )
    if cnt_max > _MAX_COUNT or pref_max > 2**30 or ipa_raw_max > 2**23:
        return None

    # soft vocab for the distinct-domain loop
    vs = 1
    if has_soft:
        nonhost = ~t.s_is_host
        real = (t.cls_s_rows >= 0).any()
        if real and nonhost.any():
            mx = int(tv[t.s_row][nonhost].max(initial=-1))
            vs = max(mx + 1, 1)
        if vs > _MAX_SLOTS["vs"]:
            return None

    # VMEM budget (~16MB/core): persistent tiles = topo + 4 state
    # scratches + group/soft scratch + cand/s_topo/s_q/haskeys + the
    # base kernel's class tables (feas/simon/base; na/tt only when
    # used). Init-state INPUTS live in ANY (HBM) and are DMAed into
    # the scratches once, so they do not double-count.
    tiles = (
        5 * t.t  # topo3 + tgt/anti/pref/panti scratch
        + 2 * t.a
        + (3 * t.cs if has_soft else 0)  # soft scratch + s_topo + s_q
        + (t.ch if has_hard else 0)
        + (batch.u if has_soft else 0)  # haskeys
        + 3 * batch.u  # feas + simon + base
    )
    if tiles * r * LANES * 4 > 13 * 2**20:
        return None

    # f64 log weights, double-single split (sz ranges over 0..n+1)
    wn = n + 2
    szv = np.arange(wn, dtype=np.float64)
    w64 = np.log(szv + 2.0)
    w_hi = w64.astype(np.float32)
    w_lo = (w64 - w_hi.astype(np.float64)).astype(np.float32)
    # 12-bit split of w_hi for exact f32 products with cnt <= 2^17
    scale = np.float32(2**12 + 1)
    tmp = w_hi * scale
    w_h1 = (tmp - (tmp - w_hi)).astype(np.float32)  # Veltkamp split
    w_h2 = (w_hi - w_h1).astype(np.float32)

    up = LANES  # u <= 128 gate above

    def tab_u(m, dtype=np.int32):
        out = np.zeros((max(m.shape[0], SUBLANES), up), dtype=dtype)
        out[: m.shape[0], : m.shape[1]] = m
        return out

    # per-(class, slot) prefolds: scalar eval reads become SMEM loads
    u_n = batch.u
    uu = np.arange(u_n)
    rows_cl = np.maximum(t.cls_rows, 0)  # (U, Rmax)
    rvalid_cl = t.cls_rows >= 0
    slot_m = np.where(rvalid_cl, t.match[rows_cl, uu[:, None]], False)
    slot_cpaff = np.where(rvalid_cl, t.carry_aff_pref_w[rows_cl, uu[:, None]], 0)
    slot_cpanti = np.where(rvalid_cl, t.carry_anti_pref_w[rows_cl, uu[:, None]], 0)
    slot_canti = np.where(rvalid_cl, t.carry_anti_req[rows_cl, uu[:, None]], 0)
    gid_u = t.cls_group_id.astype(np.int32)
    self_ok_u = np.where(
        gid_u >= 0, t.match_all[np.maximum(gid_u, 0), uu], False
    )
    h_cl = np.maximum(t.cls_h_rows, 0)
    slot_hself = np.where(t.cls_h_rows >= 0, t.h_self[h_cl, uu[:, None]], False)

    cfg = TermsCfg(
        t=t.t, a=t.a, gn=len(t.match_all), ch=t.ch, cs=t.cs,
        rmax=t.rmax, gmax=t.gmax, hmax=t.hmax, smax=t.smax, vs=vs,
        has_ipa=has_ipa, has_hard=has_hard, has_soft=has_soft,
    )
    return TermsPlan(
        cfg=cfg,
        topo3=_pad_stack(tv, r, fill=-1),
        tgt0=_pad_stack(tgt0, r),
        own_anti0=_pad_stack(own_anti0, r),
        own_pref0=_pad_stack(own_pref0, r),
        own_panti0=_pad_stack(own_panti0, r),
        term_match_tu=tab_u(t.match.astype(np.int32)),
        carry_anti_tu=tab_u(t.carry_anti_req.astype(np.int32)),
        carry_prefc_tu=tab_u(carry_prefc.astype(np.int32)),
        carry_panti_tu=tab_u(t.carry_anti_pref_w.astype(np.int32)),
        slot_rows=t.cls_rows.astype(np.int32),
        slot_m=slot_m.astype(np.int32),
        slot_cpaff=slot_cpaff.astype(np.int32),
        slot_cpanti=slot_cpanti.astype(np.int32),
        slot_canti=slot_canti.astype(np.int32),
        gid_u=gid_u,
        self_ok_u=self_ok_u.astype(np.int32),
        slot_grows=t.cls_group_rows.astype(np.int32),
        slot_h=t.cls_h_rows.astype(np.int32),
        slot_hself=slot_hself.astype(np.int32),
        h_row_s=t.h_row.astype(np.int32),
        h_skew_s=t.h_max_skew.astype(np.int32),
        slot_s=t.cls_s_rows.astype(np.int32),
        s_row_s=t.s_row.astype(np.int32),
        s_is_host_s=t.s_is_host.astype(np.int32),
        s_skew_s=t.s_max_skew.astype(np.int32),
        g_topo3=_pad_stack(tv[t.group_rows], r, fill=-1),
        group0=_pad_stack(group0, r),
        gtot0=np.ascontiguousarray(
            np.broadcast_to(
                t.init_group_counts.sum(axis=1).astype(np.int32)[:, None, None],
                (max(t.a, 1), SUBLANES, LANES),
            )
        ),
        g_match_au=tab_u(t.match_all[t.group_of_row].astype(np.int32)),
        cand3=_pad_stack(t.h_cand_nodes.astype(np.int32), r),
        soft0=_pad_stack(soft0, r),
        s_topo3=_pad_stack(tv[t.s_row], r, fill=-1),
        s_q3=_pad_stack(t.s_q.astype(np.int32), r),
        s_match_cu=tab_u(t.match[t.s_row].astype(np.int32)),
        haskeys3=_pad_stack(t.cls_s_haskeys.astype(np.int32), r),
        w_hi=w_hi,
        w_lo=w_lo,
        w_h1=w_h1,
        w_h2=w_h2,
    )


# the term-machinery kernel beats the XLA scan on term-heavy batches
# (affinity-stress: 0.20s vs 0.26s, and the gap widens off the relay's
# ~0.1s/transfer latency floor); on by default, opt out for debugging
TERMS_DEFAULT_ENABLE = True


def build_plan(cluster, batch, dyn, features, weights=None,
               allow_terms: Optional[bool] = None) -> Optional[PallasPlan]:
    """Build a kernel plan from the (numpy) ClusterStatic + PodBatch +
    DynamicState, or None when the batch is outside the fast path's
    scope."""
    if (
        features.gpu
        or features.storage
        or features.ports
        or features.scalars
        or features.custom
    ):
        return None
    if allow_terms is None:
        allow_terms = TERMS_DEFAULT_ENABLE
    if not allow_terms and (
        features.ipa or features.hard_spread or features.soft_spread
    ):
        return None

    from ..scheduler.schedconfig import DEFAULT_SCORE_WEIGHTS, ScoreWeights

    w = ScoreWeights(*weights) if weights is not None else DEFAULT_SCORE_WEIGHTS

    a = np.asarray
    alloc_mcpu = a(cluster.alloc_mcpu, dtype=np.int64)
    alloc_mem = a(cluster.alloc_mem, dtype=np.int64)
    alloc_eph = a(cluster.alloc_eph, dtype=np.int64)
    alloc_pods = a(cluster.alloc_pods, dtype=np.int64)
    req_mcpu = a(batch.req_mcpu, dtype=np.int64)
    req_mem = a(batch.req_mem, dtype=np.int64)
    req_eph = a(batch.req_eph, dtype=np.int64)
    nz_mcpu = a(batch.nz_mcpu, dtype=np.int64)
    nz_mem = a(batch.nz_mem, dtype=np.int64)
    init_used_mcpu = a(dyn.used_mcpu, dtype=np.int64)
    init_used_mem = a(dyn.used_mem, dtype=np.int64)
    init_used_eph = a(dyn.used_eph, dtype=np.int64)
    init_nz_mcpu = a(dyn.nz_mcpu, dtype=np.int64)
    init_nz_mem = a(dyn.nz_mem, dtype=np.int64)
    init_pod_cnt = a(dyn.pod_cnt, dtype=np.int64)

    s_mem = _gcd_scale(alloc_mem, req_mem, init_used_mem)
    s_eph = _gcd_scale(alloc_eph, req_eph, init_used_eph)
    s_nzmem = _gcd_scale(alloc_mem, nz_mem, init_nz_mem)

    simon_raw = a(batch.simon_raw, dtype=np.int64)
    nodeaff_raw = a(batch.nodeaff_raw, dtype=np.int64)
    taint_intol = a(batch.taint_intol, dtype=np.int64)
    image_score = a(batch.image_score, dtype=np.int64)
    avoid_score = a(batch.avoid_score, dtype=np.int64)
    base_score = image_score * int(w.image) + avoid_score * int(w.avoid)

    # int32 exactness guards
    checks = [
        alloc_mcpu.max(initial=0) <= _MAX_SCALED,
        (alloc_mem // s_mem).max(initial=0) <= _MAX_SCALED,
        (alloc_eph // s_eph).max(initial=0) <= _MAX_SCALED,
        (alloc_mem // s_nzmem).max(initial=0) <= _MAX_SCALED,
        alloc_pods.max(initial=0) <= _MAX_SCALED,
        simon_raw.max(initial=0) <= _MAX_SCALED,
        simon_raw.min(initial=0) >= 0,
        nodeaff_raw.max(initial=0) <= _MAX_SCALED,
        nodeaff_raw.min(initial=0) >= 0,
        taint_intol.max(initial=0) <= _MAX_SCALED,
        taint_intol.min(initial=0) >= 0,
        np.abs(base_score).max(initial=0) <= 2**24,
        # balanced runs in f32: its scaled inputs must be f32-exact
        (alloc_mem // s_nzmem).max(initial=0) < 2**24,
        alloc_mcpu.max(initial=0) < 2**24,
    ]
    if not all(bool(c) for c in checks):
        return None

    n = alloc_mcpu.shape[0]
    u = req_mcpu.shape[0]
    r = -(-n // LANES)
    r = -(-r // SUBLANES) * SUBLANES  # row count multiple of 8

    if features.pins:
        # forced pin commits bypass the feasibility gate, so per-node
        # usage is no longer bounded by alloc: bound the worst case
        # (all pinned pods on one node) against the f32/int32 guards
        pin_mask = a(batch.pinned_node) >= 0
        pin_cls = a(batch.class_of_pod)[pin_mask]
        pin_c = int(req_mcpu[pin_cls].sum())
        pin_m = int((req_mem // s_mem)[pin_cls].sum())
        pin_nzc = int(nz_mcpu[pin_cls].sum())
        pin_nzm = int((nz_mem // s_nzmem)[pin_cls].sum())
        worst = max(
            int(init_used_mcpu.max(initial=0)) + pin_c,
            int((init_used_mem // s_mem).max(initial=0)) + pin_m,
            int(init_nz_mcpu.max(initial=0)) + pin_nzc,
            int((init_nz_mem // s_nzmem).max(initial=0)) + pin_nzm,
        )
        if worst >= 2**24:
            return None

    terms = None
    if features.ipa or features.hard_spread or features.soft_spread:
        p_total = int(a(batch.class_of_pod).shape[0])
        terms = _build_terms(batch, features, r, p_total, n)
        if terms is None:
            return None

    class_scalars = np.zeros((u, 8), dtype=np.int32)
    class_scalars[:, 0] = req_mcpu
    class_scalars[:, 1] = req_mem // s_mem
    class_scalars[:, 2] = req_eph // s_eph
    class_scalars[:, 3] = nz_mcpu
    class_scalars[:, 4] = nz_mem // s_nzmem
    class_scalars[:, 5] = a(batch.has_request).astype(np.int32)

    return PallasPlan(
        n=n,
        r=r,
        u=u,
        alloc_mcpu=_pad_nodes(alloc_mcpu, r),
        alloc_mem_s=_pad_nodes(alloc_mem // s_mem, r),
        alloc_eph_s=_pad_nodes(alloc_eph // s_eph, r),
        alloc_pods=_pad_nodes(alloc_pods, r),
        alloc_nzmem_s=_pad_nodes(alloc_mem // s_nzmem, r),
        static_feasible=_pad_class_table(
            a(batch.static_feasible).astype(np.int32), r
        ),
        simon_raw=_pad_class_table(simon_raw, r),
        nodeaff_raw=_pad_class_table(nodeaff_raw, r),
        taint_intol=_pad_class_table(taint_intol, r),
        base_score=_pad_class_table(base_score, r),
        class_scalars=class_scalars,
        init_used_mcpu=_pad_nodes(init_used_mcpu, r),
        init_used_mem_s=_pad_nodes(init_used_mem // s_mem, r),
        init_used_eph_s=_pad_nodes(init_used_eph // s_eph, r),
        init_nz_mcpu=_pad_nodes(init_nz_mcpu, r),
        init_nz_mem_s=_pad_nodes(init_nz_mem // s_nzmem, r),
        init_pod_cnt=_pad_nodes(init_pod_cnt, r),
        s_mem=s_mem,
        s_eph=s_eph,
        s_nzmem=s_nzmem,
        w=(int(w.least), int(w.balanced), int(w.simon) + int(w.gpushare),
           int(w.nodeaff), int(w.tainttol), int(w.spread), int(w.ipa)),
        has_nodeaff=bool(nodeaff_raw.any()),
        has_taint=bool(taint_intol.any()),
        has_pins=bool(features.pins),
        terms=terms,
    )


def _make_kernel(p_total: int, w: tuple, has_nodeaff: bool, has_taint: bool,
                 has_pins: bool, tc: Optional[TermsCfg]):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    w_least, w_bal, w_simon, w_na, w_tt, w_spread, w_ipa = w

    # ---- ref layout: base inputs, term inputs, outputs, term scratch.
    # The na/tt class tables ride along only when their scores are live
    # (a [U, R, C] tile each — meaningful VMEM at U=100).
    BASE_IN = 17 + int(has_nodeaff) + int(has_taint)
    TERM_IN = 39 if tc is not None else 0
    N_OUT = 7

    def two_sum(a, b):
        # Knuth 2Sum (branch-free, round-to-nearest f32): s + err == a + b
        s = a + b
        bb = s - a
        err = (a - (s - bb)) + (b - bb)
        return s, err

    def kernel(*refs):
        it = iter(refs[:BASE_IN])
        pod_scal_ref = next(it)  # (8, Pr, 128) i32: class, rc, rm, re,
        #   nzc, nzm, has_req, unused — pod p at [:, p//128, p%128]
        active_ref = next(it)  # (Pr, 128) i32
        valid_ref = next(it)  # (R, C) i32
        alloc_c_ref = next(it)
        alloc_m_ref = next(it)
        alloc_e_ref = next(it)
        alloc_p_ref = next(it)
        alloc_nzm_ref = next(it)
        feas_ref = next(it)  # (U, R, C)
        simon_ref = next(it)
        na_ref = next(it) if has_nodeaff else None
        tt_ref = next(it) if has_taint else None
        base_ref = next(it)
        ic_ref = next(it)  # init-state inputs, copied into the state
        im_ref = next(it)  # outputs at kernel start (output aliasing
        ie_ref = next(it)  # does NOT initialize aliased outputs on TPU
        inzc_ref = next(it)  # — unread inputs are elided)
        inzm_ref = next(it)
        ipc_ref = next(it)
        if tc is not None:
            (
                topo_ref, tgt0_ref, anti0_ref, pref0_ref, panti0_ref,
                tmatch_ref, canti_ref, cprefc_ref, cpanti_ref,
                srows_ref, sm_ref, scpaff_ref, scpanti_ref, scanti_ref,
                gid_ref, selfok_ref, sgrows_ref, sh_ref, shself_ref,
                hrow_ref, hskew_ref, sslot_ref, srow_ref, sishost_ref,
                sskew_ref,
                gtopo_ref, group0_ref, gtot0_ref, gmatch_ref,
                cand_ref,
                soft0_ref, stopo_ref, sq_ref, smatch_ref, haskeys_ref,
                whi_ref, wlo_ref, wh1_ref, wh2_ref,
            ) = refs[BASE_IN : BASE_IN + TERM_IN]
        outs = refs[BASE_IN + TERM_IN : BASE_IN + TERM_IN + N_OUT]
        (place_ref, st_c_ref, st_m_ref, st_e_ref,
         st_nzc_ref, st_nzm_ref, st_p_ref) = outs
        if tc is not None:
            (tgt_s, anti_s, pref_s, panti_s, group_s, gtot_s, soft_s,
             dma_sem) = refs[BASE_IN + TERM_IN + N_OUT :]

        shape = valid_ref.shape
        rows = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
        idx_mat = rows * LANES + cols
        lane_iota = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)

        valid = valid_ref[:] != 0
        alloc_c = alloc_c_ref[:]
        alloc_m = alloc_m_ref[:]
        alloc_e = alloc_e_ref[:]
        alloc_p = alloc_p_ref[:]
        alloc_nzm = alloc_nzm_ref[:]
        alloc_c_f = alloc_c.astype(jnp.float32)
        alloc_nzm_f = alloc_nzm.astype(jnp.float32)

        st_c_ref[:] = ic_ref[:]
        st_m_ref[:] = im_ref[:]
        st_e_ref[:] = ie_ref[:]
        st_nzc_ref[:] = inzc_ref[:]
        st_nzm_ref[:] = inzm_ref[:]
        st_p_ref[:] = ipc_ref[:]
        if tc is not None:
            # init states arrive in ANY (HBM) so they do not double the
            # VMEM footprint of their scratch copies; one DMA each
            from jax.experimental.pallas import tpu as pltpu_mod

            for src_ref, dst_ref in (
                (tgt0_ref, tgt_s),
                (anti0_ref, anti_s),
                (pref0_ref, pref_s),
                (panti0_ref, panti_s),
                (group0_ref, group_s),
                (gtot0_ref, gtot_s),
                (soft0_ref, soft_s),
            ):
                cp = pltpu_mod.make_async_copy(src_ref, dst_ref, dma_sem)
                cp.start()
                cp.wait()

        def step(p, _):
            # dynamic lane-dim loads are unsupported on TPU: read the
            # pod's 128-lane row and extract via a masked reduce
            pr = p // LANES
            pc = p % LANES
            lane = lane_iota == pc

            def pod_scalar(s):
                row = pod_scal_ref[s, pl.ds(pr, 1), :]
                return jnp.sum(jnp.where(lane, row, 0))

            u = pod_scalar(0)
            rc = pod_scalar(1)
            rm = pod_scalar(2)
            re = pod_scalar(3)
            nzc = pod_scalar(4)
            nzm = pod_scalar(5)
            has_req = pod_scalar(6)
            active = jnp.sum(jnp.where(lane, active_ref[pl.ds(pr, 1), :], 0))

            used_c = st_c_ref[:]
            used_m = st_m_ref[:]
            used_e = st_e_ref[:]
            st_nzc = st_nzc_ref[:]
            st_nzm = st_nzm_ref[:]
            pod_cnt = st_p_ref[:]

            fit = (
                (used_c + rc <= alloc_c)
                & (used_m + rm <= alloc_m)
                & (used_e + re <= alloc_e)
            )
            feas = (
                (feas_ref[u] != 0)
                & valid
                & (pod_cnt + 1 <= alloc_p)
                & (fit | (has_req == 0))
            )

            # ---- inter-pod affinity + topology spread ----
            if tc is not None and tc.has_ipa:
                fail_exist = jnp.zeros(shape, bool)
                fail_own = jnp.zeros(shape, bool)
                ipa_raw = jnp.zeros(shape, jnp.int32)
                for k in range(tc.rmax):
                    r_k = srows_ref[u, k]
                    rv = r_k >= 0
                    rk = jnp.maximum(r_k, 0)
                    vals = topo_ref[rk]
                    hask = (vals >= 0) & rv
                    tgtk = jnp.where(hask, tgt_s[rk], 0)
                    antik = jnp.where(hask, anti_s[rk], 0)
                    prefk = jnp.where(hask, pref_s[rk], 0)
                    pantik = jnp.where(hask, panti_s[rk], 0)
                    m_k = (sm_ref[u, k] != 0) & rv
                    c_paff = scpaff_ref[u, k]
                    c_panti = scpanti_ref[u, k]
                    c_anti = scanti_ref[u, k]
                    fail_exist = fail_exist | (m_k & (antik > 0))
                    fail_own = fail_own | ((c_anti > 0) & (tgtk > 0))
                    ipa_raw = ipa_raw + (c_paff - c_panti) * tgtk + jnp.where(
                        m_k, prefk - pantik, 0
                    )

                # satisfyPodAffinity: required-affinity groups
                gid = gid_ref[u]
                keys_ok = jnp.ones(shape, bool)
                pods_exist = jnp.ones(shape, bool)
                total_g = jnp.zeros((), jnp.int32)
                for k in range(tc.gmax):
                    a_k = sgrows_ref[u, k]
                    gv = a_k >= 0
                    ak = jnp.maximum(a_k, 0)
                    gvals = gtopo_ref[ak]
                    hasg = gvals >= 0
                    gck = jnp.where(hasg, group_s[ak], 0)
                    keys_ok = keys_ok & (hasg | ~gv)
                    pods_exist = pods_exist & ((gck > 0) | ~gv)
                    tot_k = jnp.sum(gtot_s[ak, 0:1, 0:1])
                    total_g = total_g + jnp.where(gv, tot_k, 0)
                self_ok = selfok_ref[u] != 0
                bootstrap = (total_g == 0) & self_ok
                aff_ok = (gid < 0) | (keys_ok & (pods_exist | bootstrap))
                feas = feas & aff_ok & ~fail_own & ~fail_exist

            if tc is not None and tc.has_hard:
                for k in range(tc.hmax):
                    h_k = sh_ref[u, k]
                    hv = h_k >= 0
                    hk = jnp.maximum(h_k, 0)
                    hrow = jnp.maximum(hrow_ref[hk], 0)
                    hvals = topo_ref[hrow]
                    cand = (cand_ref[hk] != 0) & valid
                    counts = tgt_s[hrow]
                    minc = jnp.min(jnp.where(cand, counts, BIG))
                    minc = jnp.where(jnp.any(cand), minc, 0)
                    cnt_eff = jnp.where(cand & (hvals >= 0), counts, 0)
                    selfm = shself_ref[u, k]
                    skew = cnt_eff + selfm - minc
                    maxskew = hskew_ref[hk]
                    ok_c = (skew <= maxskew) & (hvals >= 0)
                    feas = feas & (ok_c | ~hv)

            # ---- scores ----
            # LeastAllocated (least_allocated.go:108-117)
            totc = st_nzc + nzc
            totm = st_nzm + nzm
            ok_c = (alloc_c > 0) & (totc <= alloc_c)
            ok_m = (alloc_nzm > 0) & (totm <= alloc_nzm)
            least_c = jnp.where(
                ok_c, (alloc_c - totc) * MAX_SCORE // jnp.maximum(alloc_c, 1), 0
            )
            least_m = jnp.where(
                ok_m, (alloc_nzm - totm) * MAX_SCORE // jnp.maximum(alloc_nzm, 1), 0
            )
            total = base_ref[u] + ((least_c + least_m) // 2) * w_least

            if w_bal:
                # BalancedAllocation: fractions are exact in f32 (inputs
                # < 2^24); only the final truncation is float
                cpu_frac = totc.astype(jnp.float32) / jnp.maximum(alloc_c_f, 1.0)
                cpu_frac = jnp.where(alloc_c > 0, cpu_frac, 1.0)
                mem_frac = totm.astype(jnp.float32) / jnp.maximum(alloc_nzm_f, 1.0)
                mem_frac = jnp.where(alloc_nzm > 0, mem_frac, 1.0)
                balanced = jnp.where(
                    (cpu_frac >= 1.0) | (mem_frac >= 1.0),
                    0,
                    ((1.0 - jnp.abs(cpu_frac - mem_frac)) * MAX_SCORE).astype(
                        jnp.int32
                    ),
                )
                total = total + balanced * w_bal

            if w_simon:
                raw = simon_ref[u]
                hi = jnp.max(jnp.where(feas, raw, NEG))
                lo = jnp.min(jnp.where(feas, raw, BIG))
                rng = hi - lo
                sim = jnp.where(
                    rng > 0, (raw - lo) * MAX_SCORE // jnp.maximum(rng, 1), 0
                )
                total = total + sim * w_simon

            if w_na and has_nodeaff:
                raw = na_ref[u]
                mx = jnp.max(jnp.where(feas, raw, 0))
                na = jnp.where(mx > 0, MAX_SCORE * raw // jnp.maximum(mx, 1), 0)
                total = total + na * w_na

            if w_tt and has_taint:
                raw = tt_ref[u]
                mx = jnp.max(jnp.where(feas, raw, 0))
                base = jnp.where(mx > 0, MAX_SCORE * raw // jnp.maximum(mx, 1), 0)
                tt = jnp.where(mx > 0, MAX_SCORE - base, MAX_SCORE)
                total = total + tt * w_tt

            if tc is not None and tc.has_ipa and w_ipa:
                # InterPodAffinity NormalizeScore (scoring.go:246-270):
                # integer division reproduces the f64-truncate result for
                # these magnitudes (|numerator| < 2^31, denominator >= 1)
                mxi = jnp.maximum(jnp.max(jnp.where(feas, ipa_raw, 0)), 0)
                mni = jnp.minimum(jnp.min(jnp.where(feas, ipa_raw, 0)), 0)
                diff = mxi - mni
                ipa_sc = jnp.where(
                    diff > 0,
                    (MAX_SCORE * (ipa_raw - mni)) // jnp.maximum(diff, 1),
                    0,
                )
                total = total + ipa_sc * w_ipa

            if tc is not None and tc.has_soft and w_spread:
                # PodTopologySpread soft score (scoring.go). The XLA path
                # computes cnt*log(sz+2) in f64; f64 is unavailable here,
                # so the product runs in double-single f32 (split tables
                # w_h1/w_h2/w_lo, exact partial products, 2Sum chains) —
                # ~2^-45 relative error, then integer truncation.
                hkeys = haskeys_ref[u] != 0
                eligible = feas & hkeys
                acc_hi = jnp.zeros(shape, jnp.float32)
                acc_lo = jnp.zeros(shape, jnp.float32)
                any_svalid = jnp.zeros((), bool)
                for k in range(tc.smax):
                    s_k = sslot_ref[u, k]
                    sv = s_k >= 0
                    any_svalid = any_svalid | sv
                    sk = jnp.maximum(s_k, 0)
                    svals = stopo_ref[sk]
                    is_host = sishost_ref[sk] != 0
                    sz_host = jnp.sum((eligible).astype(jnp.int32))
                    sz_nh = jnp.zeros((), jnp.int32)
                    for v in range(tc.vs):
                        sz_nh = sz_nh + jnp.any(eligible & (svals == v)).astype(
                            jnp.int32
                        )
                    sz = jnp.where(is_host, sz_host, sz_nh)
                    whi = whi_ref[sz]
                    wlo = wlo_ref[sz]
                    wh1 = wh1_ref[sz]
                    wh2 = wh2_ref[sz]
                    srow = jnp.maximum(srow_ref[sk], 0)
                    cnt_host = tgt_s[srow]
                    cnt_soft = soft_s[sk]
                    cnt = jnp.where(is_host, cnt_host, cnt_soft) * (
                        svals >= 0
                    ).astype(jnp.int32)
                    c2 = cnt % 256
                    c1 = (cnt - c2).astype(jnp.float32)
                    c2f = c2.astype(jnp.float32)
                    # exact partial products (<=21-bit each)
                    hi_p, e1 = two_sum(c1 * wh1, c1 * wh2)
                    hi_p, e2 = two_sum(hi_p, c2f * wh1)
                    hi_p, e3 = two_sum(hi_p, c2f * wh2)
                    lo_p = e1 + e2 + e3 + cnt.astype(jnp.float32) * wlo
                    skew_k = (sskew_ref[sk] - 1).astype(jnp.float32)
                    hi_p, e4 = two_sum(hi_p, skew_k)
                    lo_p = lo_p + e4
                    hi_p = jnp.where(sv, hi_p, 0.0)
                    lo_p = jnp.where(sv, lo_p, 0.0)
                    acc_hi, e5 = two_sum(acc_hi, hi_p)
                    acc_lo = acc_lo + e5 + lo_p
                # truncate acc_hi + acc_lo toward zero (scores >= 0)
                base_f = jnp.floor(acc_hi)
                frac = (acc_hi - base_f) + acc_lo
                adj = jnp.where(frac >= 1.0, 1, jnp.where(frac < 0.0, -1, 0))
                raw_s = base_f.astype(jnp.int32) + adj
                validm = feas & hkeys
                anyv = jnp.any(validm)
                mxs = jnp.max(jnp.where(validm, raw_s, -BIG))
                mns = jnp.min(jnp.where(validm, raw_s, BIG))
                norm_s = jnp.where(
                    mxs == 0,
                    MAX_SCORE,
                    (MAX_SCORE * (mxs + mns - raw_s)) // jnp.maximum(mxs, 1),
                )
                soft_sc = jnp.where(validm, norm_s, 0)
                soft_sc = jnp.where(anyv, soft_sc, 0)
                soft_sc = jnp.where(any_svalid, soft_sc, MAX_SCORE)
                total = total + soft_sc * w_spread
            elif w_spread:
                # no soft constraints anywhere: NormalizeScore's
                # no-constraint branch is MaxNodeScore on every node — a
                # constant that cannot change the argmax; omitted
                pass

            masked = jnp.where(feas, total, NEG)
            m = jnp.max(masked)
            found = m > NEG
            cand = jnp.where(feas & (masked == m), idx_mat, BIG)
            best = jnp.min(cand)

            place = jnp.where(found, best, -1)
            if has_pins:
                # spec.nodeName overrides selection regardless of
                # feasibility (scan.py: pinned pods commit as forced
                # placements); a pin outside node_valid is INACTIVE
                pin = pod_scalar(7)
                pinc = jnp.maximum(pin, 0)
                vrow = valid_ref[pl.ds(pinc // LANES, 1), :]
                pin_ok = (
                    jnp.sum(jnp.where(lane_iota == pinc % LANES, vrow, 0)) != 0
                )
                place = jnp.where(
                    pin >= 0, jnp.where(pin_ok, pin, INACTIVE), place
                )
            place = jnp.where(active != 0, place, INACTIVE)
            # dynamic lane-dim stores are unsupported on TPU: rewrite
            # only the pod's 128-lane row, lane-selected via the mask
            prow = place_ref[pl.ds(pr, 1), :]
            place_ref[pl.ds(pr, 1), :] = jnp.where(lane, place, prow)

            do = place >= 0
            sel = (idx_mat == place) & do
            st_c_ref[:] = used_c + jnp.where(sel, rc, 0)
            st_m_ref[:] = used_m + jnp.where(sel, rm, 0)
            st_e_ref[:] = used_e + jnp.where(sel, re, 0)
            st_nzc_ref[:] = st_nzc + jnp.where(sel, nzc, 0)
            st_nzm_ref[:] = st_nzm + jnp.where(sel, nzm, 0)
            st_p_ref[:] = pod_cnt + jnp.where(sel, 1, 0)

            if tc is not None:
                inc = do.astype(jnp.int32)
                nr = jnp.where(do, place // LANES, 0)
                nc = jnp.where(do, place % LANES, 0)
                lane_nc = (lane_iota == nc)[None, :, :]  # (1, 1, C)
                lane_u3 = lane_iota == u  # (1, LANES) for (X, Up) tables

                def col_u(tab_ref):
                    """Column u of a (X, Up) table -> (X, 1, 1) i32."""
                    t2 = jnp.where(lane_u3, tab_ref[:], 0)
                    return jnp.sum(t2, axis=1, keepdims=True)[:, :, None]

                def val_at(t3_ref):
                    """(X, R, C) tile values at the placed node -> (X, 1, 1)."""
                    colslab = t3_ref[:, pl.ds(nr, 1), :]  # (X, 1, C)
                    return jnp.sum(
                        jnp.where(lane_nc, colslab, 0), axis=2, keepdims=True
                    )

                valt = val_at(topo_ref)  # (T, 1, 1)
                eq = ((topo_ref[:] == valt) & (valt >= 0)).astype(jnp.int32)
                m_t = col_u(tmatch_ref)[: tc.t]
                tgt_s[:] = tgt_s[:] + (m_t * inc) * eq
                if tc.has_ipa:
                    anti_s[:] = anti_s[:] + (col_u(canti_ref)[: tc.t] * inc) * eq
                    pref_s[:] = pref_s[:] + (col_u(cprefc_ref)[: tc.t] * inc) * eq
                    panti_s[:] = panti_s[:] + (col_u(cpanti_ref)[: tc.t] * inc) * eq
                    g_valt = val_at(gtopo_ref)  # (A, 1, 1)
                    g_eq = ((gtopo_ref[:] == g_valt) & (g_valt >= 0)).astype(
                        jnp.int32
                    )
                    g_m = col_u(gmatch_ref)[: tc.a] * (g_valt >= 0)
                    group_s[:] = group_s[:] + (g_m * inc) * g_eq
                    gtot_s[:] = gtot_s[:] + g_m * inc
                if tc.has_soft:
                    s_valt = val_at(stopo_ref)  # (Cs, 1, 1)
                    s_q_at = val_at(sq_ref) != 0
                    s_ok = (s_valt >= 0) & s_q_at
                    s_m = col_u(smatch_ref)[: tc.cs] * s_ok
                    s_eq = ((stopo_ref[:] == s_valt) & (s_valt >= 0)).astype(
                        jnp.int32
                    )
                    soft_s[:] = soft_s[:] + (s_m * inc) * s_eq
            return 0

        jax.lax.fori_loop(0, p_total, step, 0)

    return kernel


class _Compiled(NamedTuple):
    fn: object


_COMPILED_CACHE: dict = {}

# device-resident copies of a plan's (numpy) arrays: the axon relay
# makes per-call host->device transfers expensive (~10ms per array;
# a terms plan ships ~55 arrays), so transfer once per plan. Keyed by
# id(plan) with a strong ref pinning it (utils/memo.py contract).
# LRU-ordered: hits move-to-end so eviction under >16 live plans
# (concurrent sweeps) targets the coldest plan, not the hot one.
_DEVICE_PLAN_CACHE: "OrderedDict" = OrderedDict()

# host-packed scenario-invariant pod-scalar rows, same identity contract
_POD_SCAL_CACHE: "OrderedDict" = OrderedDict()

# both caches pin finished plans (host numpy + device buffers) until
# eviction; release them with the memos at the planner boundary
from ..utils.memo import register_cache as _register_cache  # noqa: E402

_register_cache(_DEVICE_PLAN_CACHE.clear)
_register_cache(_POD_SCAL_CACHE.clear)


def _device_args(plan: PallasPlan) -> list:
    import jax

    hit = _DEVICE_PLAN_CACHE.get(id(plan))
    if hit is not None and hit[0] is plan:
        _DEVICE_PLAN_CACHE.move_to_end(id(plan))
        return hit[1]
    args = [
        plan.alloc_mcpu, plan.alloc_mem_s, plan.alloc_eph_s, plan.alloc_pods,
        plan.alloc_nzmem_s,
        plan.static_feasible, plan.simon_raw,
    ]
    if plan.has_nodeaff:
        args.append(plan.nodeaff_raw)
    if plan.has_taint:
        args.append(plan.taint_intol)
    args += [
        plan.base_score,
        plan.init_used_mcpu, plan.init_used_mem_s, plan.init_used_eph_s,
        plan.init_nz_mcpu, plan.init_nz_mem_s, plan.init_pod_cnt,
    ]
    if plan.terms is not None:
        tp = plan.terms
        args += [
            tp.topo3, tp.tgt0, tp.own_anti0, tp.own_pref0, tp.own_panti0,
            tp.term_match_tu, tp.carry_anti_tu, tp.carry_prefc_tu,
            tp.carry_panti_tu,
            tp.slot_rows, tp.slot_m, tp.slot_cpaff, tp.slot_cpanti,
            tp.slot_canti, tp.gid_u, tp.self_ok_u, tp.slot_grows,
            tp.slot_h, tp.slot_hself, tp.h_row_s, tp.h_skew_s,
            tp.slot_s, tp.s_row_s, tp.s_is_host_s, tp.s_skew_s,
            tp.g_topo3, tp.group0, tp.gtot0, tp.g_match_au,
            tp.cand3,
            tp.soft0, tp.s_topo3, tp.s_q3, tp.s_match_cu, tp.haskeys3,
            tp.w_hi, tp.w_lo, tp.w_h1, tp.w_h2,
        ]
    with jax.enable_x64(False):
        dev = [jax.device_put(a) for a in args]
    if len(_DEVICE_PLAN_CACHE) >= 16:
        # evict the least-recently-used entry; a wholesale clear would
        # drop the device copies of plans still in active use
        _DEVICE_PLAN_CACHE.popitem(last=False)
    _DEVICE_PLAN_CACHE[id(plan)] = (plan, dev)
    return dev

# None = auto (use the kernel only on a real TPU backend — the Pallas
# interpreter would crawl at bench scale on CPU); tests set True to
# exercise the integration paths under interpret mode
FORCE_ENABLE: Optional[bool] = None


def should_use() -> bool:
    """Whether eligible callers should run the fused kernel."""
    if FORCE_ENABLE is not None:
        return FORCE_ENABLE
    import jax

    return jax.default_backend() == "tpu"


def run_scan_pallas(plan: PallasPlan, class_of_pod, pod_active, node_valid,
                    pinned=None, interpret=None):
    """Run the fused scan. Returns (placements[P] np.int32, final used
    dict in TRUE units for utilization reporting). `pinned` ([P] node
    index or -1; required when the plan was built with pins) forces
    spec.nodeName placements. `interpret` forces the Pallas interpreter
    (None = auto: interpret off-TPU)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    p_total = int(np.asarray(class_of_pod).shape[0])
    # dense (Pr, 128) packing: a (P, 1) VMEM array would be lane-padded
    # 128x by the (8, 128) tile layout (51 MB at 100k pods)
    pr_rows = max(-(-p_total // LANES), 1)
    pr_rows = -(-pr_rows // SUBLANES) * SUBLANES
    p_pad = pr_rows * LANES
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    tc = plan.terms.cfg if plan.terms is not None else None
    key = (p_total, plan.r, plan.u, plan.w, plan.has_nodeaff, plan.has_taint,
           plan.has_pins, tc, interpret)
    cached = _COMPILED_CACHE.get(key)
    if cached is None:
        kernel = _make_kernel(p_total, plan.w, plan.has_nodeaff, plan.has_taint,
                              plan.has_pins, tc)
        rc = (plan.r, LANES)
        base_n = 17 + int(plan.has_nodeaff) + int(plan.has_taint)
        n_in = base_n + (39 if tc is not None else 0)
        scratch = []
        # term-block memory spaces (offsets relative to base_n):
        # init states (DMAed into scratch) in ANY; slot/scalar tables in
        # SMEM; everything else VMEM
        any_idx = (
            {base_n + k for k in (1, 2, 3, 4, 26, 27, 30)}
            if tc is not None
            else set()
        )
        smem_idx = (
            {base_n + k for k in list(range(9, 25)) + [35, 36, 37, 38]}
            if tc is not None
            else set()
        )
        if tc is not None:
            from jax.experimental.pallas import tpu as _pltpu

            trc = (tc.t, plan.r, LANES)
            scratch = [
                _pltpu.VMEM(trc, jnp.int32),  # tgt
                _pltpu.VMEM(trc, jnp.int32),  # own_anti
                _pltpu.VMEM(trc, jnp.int32),  # own_pref (combined)
                _pltpu.VMEM(trc, jnp.int32),  # own_panti
                _pltpu.VMEM((tc.a, plan.r, LANES), jnp.int32),  # group
                _pltpu.VMEM((tc.a, SUBLANES, LANES), jnp.int32),  # gtot
                _pltpu.VMEM((tc.cs, plan.r, LANES), jnp.int32),  # soft
                _pltpu.SemaphoreType.DMA,
            ]

        @jax.jit
        def call(*arrays):
            def spec(i):
                if i in any_idx:
                    return pl.BlockSpec(memory_space=pltpu.ANY)
                if i in smem_idx:
                    return pl.BlockSpec(memory_space=pltpu.SMEM)
                return pl.BlockSpec(memory_space=pltpu.VMEM)
            outs = pl.pallas_call(
                kernel,
                out_shape=(
                    jax.ShapeDtypeStruct((pr_rows, LANES), jnp.int32),
                    jax.ShapeDtypeStruct(rc, jnp.int32),
                    jax.ShapeDtypeStruct(rc, jnp.int32),
                    jax.ShapeDtypeStruct(rc, jnp.int32),
                    jax.ShapeDtypeStruct(rc, jnp.int32),
                    jax.ShapeDtypeStruct(rc, jnp.int32),
                    jax.ShapeDtypeStruct(rc, jnp.int32),
                ),
                in_specs=[spec(i) for i in range(n_in)],
                out_specs=tuple(pl.BlockSpec(memory_space=pltpu.VMEM) for _ in range(7)),
                scratch_shapes=scratch,
                interpret=interpret,
            )(*arrays)
            # ONE output array (placements + 6 states concatenated on
            # the row axis): every host-blocking point on the relay
            # costs ~0.1s regardless of size, so the whole call must
            # have exactly one — the single fetch below
            return jnp.concatenate(outs, axis=0)

        cached = _Compiled(fn=call)
        _COMPILED_CACHE[key] = cached

    def pack(vec):
        out = np.zeros(p_pad, dtype=np.int32)
        out[:p_total] = vec
        return out.reshape(pr_rows, LANES)

    cls = np.asarray(class_of_pod, dtype=np.int32)
    # per-pod scalar rows: class + class-derived request scalars,
    # gathered host-side so the kernel never lane-indexes a class table;
    # row 7 carries the nodeName pin (-1 = loose). Rows 0-6 are
    # scenario-invariant — memoize per (plan, class array) so sweeps
    # that loop scenarios (defrag depths, capacity counts) pack once.
    memo_key = (id(plan), id(class_of_pod))
    hit = _POD_SCAL_CACHE.get(memo_key)
    if hit is not None and hit[0] is plan and hit[1] is class_of_pod:
        _POD_SCAL_CACHE.move_to_end(memo_key)
        pod_scal = hit[2].copy()
    else:
        pod_scal = np.zeros((8, pr_rows, LANES), dtype=np.int32)
        pod_scal[0] = pack(cls)
        for s in range(6):
            pod_scal[1 + s] = pack(plan.class_scalars[cls, s])
        if len(_POD_SCAL_CACHE) >= 16:
            _POD_SCAL_CACHE.popitem(last=False)
        _POD_SCAL_CACHE[memo_key] = (plan, class_of_pod, pod_scal.copy())
    if plan.has_pins:
        if pinned is None:
            raise ValueError("plan has pins: pass the pinned[] array")
        pin_vec = np.asarray(pinned, dtype=np.int32)
        pod_scal[7] = pack(np.where(pin_vec >= 0, pin_vec, -1))
    elif pinned is not None and (np.asarray(pinned) >= 0).any():
        raise ValueError("pinned pods but the plan was built without pins")
    active_2d = pack(np.asarray(pod_active).astype(np.int32))
    valid = _pad_nodes(np.asarray(node_valid).astype(np.int32), plan.r)

    # the engine enables x64 globally (ops/__init__.py) for the XLA
    # scan's int64 semantics, but this kernel is int32 by construction
    # and Mosaic's convert rules recurse on x64-promoted loop indices —
    # trace and run with x64 off
    with jax.enable_x64(False):
        # per-call inputs ride as numpy straight into the dispatch: an
        # explicit device_put is a second host-blocking relay roundtrip
        # (~0.1s); the implicit transfer pipelines with the dispatch so
        # the single np.asarray fetch is the call's only sync point
        out_d = cached.fn(pod_scal, active_2d, valid, *_device_args(plan))
        out = np.asarray(out_d)
    place = out[:pr_rows]
    states = out[pr_rows:]
    place = place.reshape(-1)[:p_total]
    # map padded slots: any placement index beyond n means "no node"
    place = np.where((place >= 0) & (place >= plan.n), -1, place)
    st = states.reshape(6, -1)[:, : plan.n].astype(np.int64)
    final = {
        "used_mcpu": st[0],
        "used_mem": st[1] * plan.s_mem,
        "nz_mcpu": st[3],
        "nz_mem": st[4] * plan.s_nzmem,
        "pod_cnt": st[5],
    }
    return place, final
