"""JAX ops for the TPU engine.

int64/float64 are enabled globally: cluster resource quantities (memory
bytes, VG bytes, GPU memory) exceed int32 range and the engine must be
bit-exact against the integer arithmetic of the serial oracle. On TPU,
s64 is lowered to 32-bit pairs by XLA; the hot arithmetic (compares,
adds over the node axis) stays cheap, and scores that tolerate rounding
use f32.
"""

import jax

jax.config.update("jax_enable_x64", True)
