"""Shadow decision logs — normalized scheduling decisions on disk.

One JSONL file riding the PR-2 journal discipline (runtime/journal.py):
a header line first, one record per line, flushed and fsync'd per
append, torn final line tolerated on read, interior damage and
fingerprint mismatches refused loudly.

Record kinds (format version 1):

- ``{"kind": "header", "version": 1, "format": "shadow-decision-log",
  "fingerprint": "..."}`` — the fingerprint digests the cluster the
  log was recorded against (``cluster_fingerprint``), so a log can
  never silently replay onto a different cluster;
- ``{"kind": "decision", "seq": N, "pod": {...}, "node": "..."|null,
  "reason": "...", "deltas": [...]}`` — one scheduling decision: the
  UNSCHEDULED pod (no ``spec.nodeName``), the node the real scheduler
  chose (null = it failed, with its reason), and the cluster-delta ops
  that preceded the decision (preemption evictions, node churn);
- ``{"kind": "delta", "seq": N, "ops": [...]}`` — cluster mutations
  with no decision attached (pre-bound pods arriving, node add/remove).

Delta ops (applied in list order, before the step's decision):

- ``{"op": "place_pod", "pod": {...}}`` — a pod that arrived already
  bound (``spec.nodeName`` set); occupies capacity, never scheduled;
- ``{"op": "evict_pod", "namespace": ..., "name": ..., "node": ...}``
  — a pod removed from its node (preemption victim, deletion);
- ``{"op": "add_node", "node": {...}}`` / ``{"op": "remove_node",
  "name": ...}`` — node churn (a remove costs the replayer a state
  reload; everything else is an incremental commit).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..runtime.journal import JournalMismatch, config_fingerprint

LOG_VERSION = 1
LOG_FORMAT = "shadow-decision-log"


def cluster_fingerprint(cluster) -> str:
    """Digest of a loaded ResourceTypes — the same construction as the
    serve Session's fingerprint, so a decision log and a warm session
    over the same cluster agree on identity."""
    return config_fingerprint(
        {k: getattr(cluster, k) for k in sorted(vars(cluster))}
    )


@dataclass
class Step:
    """One log step: a scheduling decision, or a bare delta batch."""

    seq: int
    kind: str  # "decision" | "delta"
    pod: Optional[dict] = None
    node: Optional[str] = None
    reason: str = ""
    deltas: List[dict] = field(default_factory=list)

    @property
    def pod_key(self) -> Tuple[str, str]:
        meta = (self.pod or {}).get("metadata") or {}
        return (meta.get("namespace") or "default", meta.get("name", ""))

    def as_record(self) -> dict:
        if self.kind == "delta":
            return {"kind": "delta", "seq": self.seq, "ops": self.deltas}
        rec = {
            "kind": "decision",
            "seq": self.seq,
            "pod": self.pod,
            "node": self.node,
        }
        if self.reason:
            rec["reason"] = self.reason
        if self.deltas:
            rec["deltas"] = self.deltas
        return rec

    @classmethod
    def from_record(cls, rec: dict) -> "Step":
        kind = rec.get("kind")
        if kind == "delta":
            return cls(
                seq=int(rec.get("seq", 0)),
                kind="delta",
                deltas=list(rec.get("ops") or []),
            )
        if kind != "decision":
            raise ValueError(f"unknown decision-log record kind {kind!r}")
        pod = rec.get("pod")
        if not isinstance(pod, dict):
            raise ValueError("decision record has no pod object")
        node = rec.get("node")
        return cls(
            seq=int(rec.get("seq", 0)),
            kind="decision",
            pod=pod,
            node=str(node) if node is not None else None,
            reason=str(rec.get("reason") or ""),
            deltas=list(rec.get("deltas") or []),
        )


class DecisionLogWriter:
    """Append-only fsync'd JSONL writer (the journal discipline: a
    crash keeps every decision that finished before it)."""

    def __init__(self, path: str, fingerprint: str):
        self.path = path
        self.fingerprint = fingerprint
        self.written = 0
        self._f = open(path, "w", encoding="utf-8")
        self._emit(
            {
                "kind": "header",
                "version": LOG_VERSION,
                "format": LOG_FORMAT,
                "fingerprint": fingerprint,
            }
        )

    def _emit(self, rec: dict):
        from ..runtime import inject as _inject

        line = json.dumps(rec, separators=(",", ":")) + "\n"
        # chaos crash point (runtime/inject.py): a `crash` clause
        # leaves a durable torn prefix, like a real mid-append death
        _inject.crash_write("journal.fsync.shadow", self._f, line)
        self._f.write(line)
        self._f.flush()
        os.fsync(self._f.fileno())

    def append(self, step: Step):
        self._emit(step.as_record())
        self.written += 1

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_decision_log(
    path: str, fingerprint: Optional[str] = None
) -> Tuple[List[Step], dict]:
    """Read a decision log: validate the header (and, when given, the
    cluster fingerprint — mismatch refuses loudly, JournalMismatch),
    replay complete records, tolerate a torn final line. Returns
    ``(steps, meta)`` where meta carries the header plus
    ``{"dropped": n}`` for the torn-tail count."""
    with open(path, "rb") as f:
        raw = f.read()
    lines = raw.split(b"\n")
    if not lines or not lines[0].strip():
        raise JournalMismatch(f"{path}: empty decision log")
    try:
        header = json.loads(lines[0])
    except ValueError as e:
        raise JournalMismatch(f"{path}: unreadable decision-log header: {e}") from e
    if not isinstance(header, dict) or header.get("kind") != "header":
        raise JournalMismatch(f"{path}: first record is not a header")
    if header.get("format") != LOG_FORMAT:
        raise JournalMismatch(
            f"{path}: not a shadow decision log (format "
            f"{header.get('format')!r})"
        )
    if header.get("version") != LOG_VERSION:
        raise JournalMismatch(
            f"{path}: decision-log version {header.get('version')!r} != "
            f"{LOG_VERSION}"
        )
    if fingerprint is not None and header.get("fingerprint") != fingerprint:
        raise JournalMismatch(
            f"{path}: decision log fingerprint "
            f"{header.get('fingerprint')!r} does not match this cluster "
            f"({fingerprint!r}); refusing to replay a log recorded against "
            "different inputs"
        )
    body, tail = lines[1:-1], lines[-1]
    steps: List[Step] = []
    dropped = 0
    for i, line in enumerate(body):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
            if not isinstance(rec, dict):
                raise ValueError("record is not an object")
        except ValueError as e:
            # interior damage: the file was not grown append-only
            raise JournalMismatch(
                f"{path}: corrupt decision-log record on line {i + 2}: {e}"
            ) from e
        steps.append(Step.from_record(rec))
    if tail.strip():
        try:
            rec = json.loads(tail)
            if not isinstance(rec, dict):
                raise ValueError("record is not an object")
            steps.append(Step.from_record(rec))
        except ValueError:
            dropped = 1  # torn mid-append: expected damage, drop it
    meta = dict(header)
    meta["dropped"] = dropped
    return steps, meta
