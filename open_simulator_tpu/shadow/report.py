"""Divergence classification + the shadow audit report.

Every replayed decision lands in exactly one class:

- ``agree`` — simon chose the same node the real scheduler did (or
  both declared the pod unschedulable: agreement on infeasibility);
- ``node-divergence`` — both placed the pod, on different nodes (a
  scoring/tie-rule disagreement: the report attaches both nodes'
  filter verdicts and their positions in simon's weighted score
  vector);
- ``feasibility-divergence`` — one side placed the pod, the other
  declared it unschedulable (a filter disagreement: the report names
  the failing filter per disputed node);
- ``ordering-divergence`` — a disagreement with evidence that decision
  ORDER or preemption, not policy, explains it: the real decision
  carried eviction deltas (the production scheduler preempted), or
  simon's probe failed on a preemption-capable pod (effective priority
  above the committed minimum with preemption-helpable failure codes —
  the shadow probe is read-only and never evicts, so these are
  expected to need the ordering explanation, which the explain
  payload's preemption provenance cites).

There is deliberately no "unknown": the classifier is total over
(real outcome, simon outcome, evidence).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

CLASS_AGREE = "agree"
CLASS_NODE = "node-divergence"
CLASS_FEASIBILITY = "feasibility-divergence"
CLASS_ORDERING = "ordering-divergence"

DIVERGENCE_CLASSES = (CLASS_NODE, CLASS_FEASIBILITY, CLASS_ORDERING)

# full per-step detail is kept for this many divergences; the taxonomy
# histogram and counters cover the rest (a 100k-step replay against a
# badly drifted scheduler must not hold 100k score vectors)
MAX_DIVERGENCE_DETAILS = 200


def classify(
    real_node: Optional[str],
    simon_node: Optional[str],
    ordering_evidence: Optional[str],
) -> str:
    """Total classifier over one decision. ``ordering_evidence`` is a
    human-readable citation (or None); any disagreement with evidence
    becomes ordering-divergence."""
    if real_node == simon_node:
        return CLASS_AGREE
    if ordering_evidence:
        return CLASS_ORDERING
    if real_node is not None and simon_node is not None:
        return CLASS_NODE
    return CLASS_FEASIBILITY


@dataclass
class StepOutcome:
    """One classified replay step (detail payload built by the
    replayer only for divergent steps)."""

    seq: int
    pod: str  # namespace/name
    cls: str
    real_node: Optional[str]
    real_reason: str
    simon_node: Optional[str]
    simon_reason: str
    evidence: Optional[str] = None
    detail: Optional[dict] = None


@dataclass
class DivergenceReport:
    """Aggregated audit over one replay run."""

    fingerprint: str = ""
    engine: str = ""
    steps: int = 0  # log steps applied (decisions + deltas)
    decisions: int = 0
    taxonomy: Dict[str, int] = field(default_factory=dict)
    divergences: List[StepOutcome] = field(default_factory=list)
    truncated_divergences: int = 0
    reloads: int = 0  # oracle rebuilds forced by remove_node deltas
    dropped_records: int = 0  # torn log tail
    # warm-path accounting (obs/profile counters, stamped by finish())
    recompile_steps: List[int] = field(default_factory=list)
    new_shape_recompiles: int = 0
    warm_recompiles: int = 0
    obs: Dict[str, int] = field(default_factory=dict)

    def add(self, outcome: StepOutcome):
        self.decisions += 1
        self.taxonomy[outcome.cls] = self.taxonomy.get(outcome.cls, 0) + 1
        if outcome.cls != CLASS_AGREE:
            if len(self.divergences) < MAX_DIVERGENCE_DETAILS:
                self.divergences.append(outcome)
            else:
                self.truncated_divergences += 1

    @property
    def agreements(self) -> int:
        return self.taxonomy.get(CLASS_AGREE, 0)

    @property
    def divergence_count(self) -> int:
        return self.decisions - self.agreements

    @property
    def agreement_rate(self) -> float:
        return self.agreements / self.decisions if self.decisions else 1.0

    def finish(self, obs_delta: dict):
        """Stamp the run's dispatch/recompile movement (the PR-5
        counters) — the warm-path contract as a measured number."""
        self.obs = {
            "jaxDispatches": int(obs_delta.get("jax_dispatches_total", 0)),
            "jaxRecompiles": int(obs_delta.get("jax_recompiles_total", 0)),
            "dispatchesPerDecision": round(
                obs_delta.get("jax_dispatches_total", 0)
                / max(self.decisions, 1),
                4,
            ),
        }

    def as_dict(self) -> dict:
        out = {
            "fingerprint": self.fingerprint,
            "engine": self.engine,
            "steps": self.steps,
            "decisions": self.decisions,
            "agreements": self.agreements,
            "agreementRate": round(self.agreement_rate, 6),
            "taxonomy": {
                cls: self.taxonomy.get(cls, 0)
                for cls in (CLASS_AGREE,) + DIVERGENCE_CLASSES
            },
            "reloads": self.reloads,
            "droppedRecords": self.dropped_records,
            "recompileSteps": list(self.recompile_steps),
            "newShapeRecompiles": self.new_shape_recompiles,
            "warmRecompiles": self.warm_recompiles,
            "divergences": [],
            "truncatedDivergences": self.truncated_divergences,
        }
        if self.obs:
            out["obs"] = dict(self.obs)
        for d in self.divergences:
            rec = {
                "seq": d.seq,
                "pod": d.pod,
                "class": d.cls,
                "real": {"node": d.real_node, "reason": d.real_reason},
                "simon": {"node": d.simon_node, "reason": d.simon_reason},
            }
            if d.evidence:
                rec["evidence"] = d.evidence
            if d.detail:
                rec.update(d.detail)
            out["divergences"].append(rec)
        return out

    def as_json(self) -> str:
        return json.dumps(self.as_dict(), sort_keys=True)

    def render_text(self) -> str:
        from ..apply.report import render_table

        lines = [
            "Shadow Audit Report",
            f"  engine: {self.engine}   cluster: {self.fingerprint}",
            f"  steps replayed: {self.steps} ({self.decisions} decisions, "
            f"{self.reloads} reload(s))",
            f"  agreement: {self.agreements}/{self.decisions} "
            f"({self.agreement_rate * 100:.2f}%)",
        ]
        if self.obs:
            lines.append(
                f"  warm path: {self.obs['jaxDispatches']} dispatches, "
                f"{self.new_shape_recompiles} new-shape compiles, "
                f"{self.warm_recompiles} warm recompiles"
            )
        lines.append("")
        rows = [
            [cls, str(self.taxonomy.get(cls, 0))]
            for cls in (CLASS_AGREE,) + DIVERGENCE_CLASSES
        ]
        lines.append(render_table(["Class", "Steps"], rows))
        for d in self.divergences:
            lines.append("")
            lines.append(
                f"step {d.seq} pod {d.pod}: {d.cls}\n"
                f"  real:  {d.real_node or 'UNSCHEDULABLE'}"
                + (f" ({d.real_reason})" if d.real_reason else "")
                + f"\n  simon: {d.simon_node or 'UNSCHEDULABLE'}"
                + (f" ({d.simon_reason})" if d.simon_reason else "")
            )
            if d.evidence:
                lines.append(f"  evidence: {d.evidence}")
            disputed = (d.detail or {}).get("disputedNodes") or {}
            if disputed:
                rows = [
                    [
                        name,
                        v.get("verdict", ""),
                        "" if v.get("score") is None else str(v["score"]),
                    ]
                    for name, v in sorted(disputed.items())
                ]
                lines.append(
                    render_table(["Disputed Node", "Filter Verdict", "Score"], rows)
                )
        if self.truncated_divergences:
            lines.append(
                f"\n({self.truncated_divergences} further divergence(s) "
                f"counted in the taxonomy only — detail cap "
                f"{MAX_DIVERGENCE_DETAILS})"
            )
        return "\n".join(lines)
