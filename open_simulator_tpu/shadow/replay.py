"""Warm step-by-step replay of real scheduler decisions.

One ``ShadowReplayer`` holds ONE warm ``Oracle`` (and, on the tpu
engine, one ``TpuEngine`` with its cached ``ClusterStatic`` encoding)
for the whole trace: each step's probe runs against the oracle's
CURRENT state and each real decision commits into it incrementally —
a 1000-step trace is 1000 incremental commits on copy-on-write
NodeStates and warm identity caches, not 1000 cluster reloads. The
only reload is a ``remove_node`` delta (node identity is baked into
every encoding), counted in the report.

The probe is READ-ONLY: it answers "where would simon place this pod
right now" without binding and without preemption (an eviction would
corrupt the mirrored state; preemption-capable failures are classified
as ordering-divergence instead, with the gate condition cited). On the
tpu engine the probe is one single-pod masked scan per step — the same
compiled shapes re-dispatch across same-shaped steps, so replay stays
at zero jit-cache misses after the first step of each shape. That
contract is MEASURED, not assumed: every step's recompile-counter
movement (obs/profile.py) is attributed to a shape signature of the
encoded batch, and a miss on an already-seen signature counts as a
``warm_recompile`` (CI gates this at zero).

After the probe, the REAL decision commits — even when simon disagrees
— so the mirrored state keeps tracking the production cluster and
later steps are judged against reality, not against simon's
counterfactual.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models.decode import ResourceTypes
from ..models.validation import InputError
from ..obs import profile as obs_profile
from ..obs.explain import EXPLAIN
from ..obs.spans import RECORDER
from ..scheduler.oracle import Oracle
from ..utils.trace import COUNTERS
from .log import Step, cluster_fingerprint
from .report import (
    CLASS_AGREE,
    DivergenceReport,
    StepOutcome,
    classify,
)

# score-vector rows carried per divergence (disputed nodes are always
# included on top of this cap)
MAX_SCORE_ROWS = 16


def _own_pod(p: dict) -> dict:
    """Shallow-clone a pod's mutation surface (bind writes
    spec.nodeName / status / metadata.annotations) so replaying from an
    in-memory step list leaves the steps reusable."""
    q = dict(p)
    q["spec"] = dict(p.get("spec") or {})
    meta = dict(p.get("metadata") or {})
    if meta.get("annotations") is not None:
        meta["annotations"] = dict(meta["annotations"])
    q["metadata"] = meta
    if isinstance(q.get("status"), dict):
        q["status"] = dict(q["status"])
    return q


def _pod_name(pod: dict) -> str:
    meta = pod.get("metadata") or {}
    return f"{meta.get('namespace') or 'default'}/{meta.get('name', '')}"


class ShadowReplayer:
    """Replays decision-log steps against a warm mirrored cluster."""

    def __init__(
        self,
        cluster: ResourceTypes,
        engine: str = "tpu",
        explain_divergences: bool = True,
    ):
        if engine not in ("tpu", "oracle"):
            raise InputError(f"unknown shadow engine {engine!r}")
        from ..twin.deltas import MirrorApplicator

        self.cluster = cluster
        self.engine_kind = engine
        self.explain_divergences = explain_divergences
        self.report = DivergenceReport(
            fingerprint=cluster_fingerprint(cluster), engine=engine
        )
        self._obs_before = obs_profile.snapshot()
        self._shapes: set = set()
        # the replayer's mirrored state lives on the shared
        # cluster-delta substrate (twin/deltas.py): the applicator owns
        # the warm Oracle/TpuEngine and every delta op routes through
        # it, so shadow replay, the twin mirror, and the conformance
        # gate can never fork their application semantics
        self._app = MirrorApplicator(cluster, engine=engine)

    @property
    def oracle(self) -> Oracle:
        return self._app.oracle

    @property
    def _engine(self):
        return self._app.engine

    # -- cluster deltas -----------------------------------------------------

    def _apply_delta(self, op: dict):
        from ..twin.deltas import RELOADED, SKIPPED, from_shadow_op

        out = self._app.apply(from_shadow_op(op))
        if out == SKIPPED:
            # a live tail can observe a deletion racing a node it never
            # mirrored (or a dangling pre-bound pod); counted, never
            # fatal to an hours-long audit
            COUNTERS.inc("shadow_delta_skips_total")
        elif out == RELOADED:
            self.report.reloads += 1
            COUNTERS.inc("shadow_reloads_total")

    # -- the probe ----------------------------------------------------------

    def _shape_key(self) -> tuple:
        """Signature of everything that determines the compiled scan's
        shapes for the current single-pod batch: cluster width, the
        static ScanFeatures, and every array shape/dtype in the
        encoding. A recompile on an already-seen signature is a
        warm-path regression."""
        eng = self._engine
        parts: List[tuple] = [("n", eng.cluster_static().n, ""),
                              ("features", eng._features, "")]

        def walk(obj, prefix: str):
            if isinstance(obj, np.ndarray):
                parts.append((prefix, obj.shape, str(obj.dtype)))
            elif dataclasses.is_dataclass(obj) and not isinstance(obj, type):
                for f in dataclasses.fields(obj):
                    if f.name == "class_pods":
                        continue  # host-only representatives
                    walk(getattr(obj, f.name), f"{prefix}.{f.name}")

        walk(eng._batch, "batch")
        return tuple(parts)

    def _probe(self, pod: dict) -> Optional[str]:
        """Simon's placement for `pod` against current state, no
        commit. tpu: one masked single-pod scan (warm shapes); oracle:
        the serial filter+score walk with the first-max tie rule."""
        if self._engine is not None:
            eng = self._engine
            before = COUNTERS.get("jax_recompiles_total")
            eng.begin_batch([pod])
            placements = eng.scan_active(np.ones(1, dtype=bool))
            miss = COUNTERS.get("jax_recompiles_total") - before
            sig = self._shape_key()
            if miss:
                # 0-based index of the CURRENT step (steps was already
                # bumped when this one began)
                self.report.recompile_steps.append(self.report.steps - 1)
                if sig in self._shapes:
                    self.report.warm_recompiles += miss
                    COUNTERS.inc("shadow_warm_recompiles_total", miss)
                else:
                    self.report.new_shape_recompiles += miss
            self._shapes.add(sig)
            place = int(placements[0])
            return self.oracle.nodes[place].name if place >= 0 else None
        node, _, _, _ = self._probe_serial(pod)
        return node

    def _probe_serial(self, pod: dict):
        """Serial probe: (node_or_None, reasons, codes, (feasible,
        scores)) — the same _find_feasible + _prioritize + first-max
        walk as Oracle._select_and_bind, minus the bind."""
        o = self.oracle
        feasible, reasons, codes = o._find_feasible(pod)
        if not feasible:
            return None, reasons, codes, ([], [])
        scores = o._prioritize(pod, feasible)
        best, best_score = feasible[0], scores[0]
        for ns, sc in zip(feasible[1:], scores[1:]):
            if sc > best_score:
                best, best_score = ns, sc
        return best.name, reasons, codes, (feasible, scores)

    # -- divergence explanation ---------------------------------------------

    def _explain_walk(self, pod: dict):
        """Full per-node verdict + score walk against CURRENT state —
        run only for divergent steps (O(nodes) serial Python)."""
        o = self.oracle
        ctx = o._pod_filter_ctx(pod)
        pre = o._prefilter(pod)
        verdicts: List[Tuple[str, Optional[str], str]] = []
        feasible = []
        for ns in o.nodes:
            r = o._check_node(pod, ctx, pre, ns)
            if r is None:
                feasible.append(ns)
                verdicts.append((ns.name, None, "feasible"))
            else:
                verdicts.append((ns.name, r[0], r[1]))
        scores = o._prioritize(pod, feasible) if feasible else []
        return verdicts, feasible, scores

    def _divergence_detail(
        self, pod: dict, real_node: Optional[str], simon_node: Optional[str]
    ) -> dict:
        verdicts, feasible, scores = self._explain_walk(pod)
        verdict_of = {name: (reason, code) for name, reason, code in verdicts}
        score_of = {ns.name: sc for ns, sc in zip(feasible, scores)}
        disputed: Dict[str, dict] = {}
        for name in (real_node, simon_node):
            if not name:
                continue
            reason, code = verdict_of.get(name, ("node not in cluster", "unknown-node"))
            disputed[name] = {
                "verdict": "feasible" if reason is None else reason,
                "code": code,
                "score": score_of.get(name),
            }
        reasons: Dict[str, int] = {}
        for _n, reason, _c in verdicts:
            if reason is not None:
                reasons[reason] = reasons.get(reason, 0) + 1
        # score vector: top rows by score, disputed nodes always kept
        ranked = sorted(score_of.items(), key=lambda kv: (-kv[1], kv[0]))
        keep = {name for name, _ in ranked[:MAX_SCORE_ROWS]} | set(disputed)
        vector = [
            {"node": name, "score": sc}
            for name, sc in ranked
            if name in keep
        ]
        return {
            "disputedNodes": disputed,
            "scoreVector": vector,
            "feasibleNodes": len(feasible),
            "totalNodes": len(verdicts),
            "reasonCounts": reasons,
        }

    def _ordering_evidence(
        self, st: Step, pod: dict, simon_node: Optional[str], real_node: Optional[str]
    ) -> Optional[str]:
        evictions = [op for op in st.deltas if op.get("op") == "evict_pod"]
        if evictions:
            victims = ", ".join(
                f"{op.get('namespace')}/{op.get('name')}" for op in evictions
            )
            return (
                f"real scheduler preempted {len(evictions)} pod(s) for this "
                f"decision ({victims})"
            )
        if simon_node is None and real_node is not None:
            # the probe never preempts; a preemption-capable failure is
            # ordering, not policy — mirror the serial cycle's own gate
            # (oracle._post_filter_preempt)
            o = self.oracle
            prio = o.pod_priority(pod)
            if o.enable_preemption and prio > o._min_prio:
                _, _, codes = o._find_feasible(pod)
                if any(c == "unschedulable" for c in codes.values()):
                    return (
                        f"pod priority {prio} exceeds the committed minimum "
                        f"({o._min_prio}) and preemption-helpable nodes "
                        "exist; the read-only shadow probe does not preempt"
                    )
        return None

    # -- stepping -----------------------------------------------------------

    def step(self, st: Step) -> Optional[StepOutcome]:
        """Apply one log step. Returns the classified outcome for
        decision steps, None for bare deltas."""
        if RECORDER.enabled:
            with RECORDER.span("shadow/step", seq=st.seq, kind=st.kind):
                return self._step(st)
        return self._step(st)

    def _step(self, st: Step) -> Optional[StepOutcome]:
        self.report.steps += 1
        COUNTERS.inc("shadow_steps_total")
        for op in st.deltas:
            self._apply_delta(op)
        if st.kind != "decision":
            return None
        pod = _own_pod(st.pod)
        if (pod.get("spec") or {}).get("nodeName"):
            raise InputError(
                f"decision step {st.seq} pod {_pod_name(pod)} carries "
                "spec.nodeName — pre-bound pods belong in a place_pod delta"
            )
        real_node = st.node
        if real_node is not None and real_node not in self.oracle.node_index:
            raise InputError(
                f"decision step {st.seq} names unknown node {real_node!r}"
            )
        simon_node = self._probe(pod)
        simon_reason = ""
        if simon_node is None:
            # exact failure message from the serial walk at this step's
            # state (the scan path has no reason strings)
            _, reasons, _, _ = self._probe_serial(pod)
            simon_reason = Oracle._failure_message(pod, reasons)
        evidence = None
        if real_node != simon_node:
            evidence = self._ordering_evidence(st, pod, simon_node, real_node)
        cls = classify(real_node, simon_node, evidence)
        outcome = StepOutcome(
            seq=st.seq,
            pod=_pod_name(pod),
            cls=cls,
            real_node=real_node,
            real_reason=st.reason,
            simon_node=simon_node,
            simon_reason=simon_reason,
            evidence=evidence,
        )
        if cls != CLASS_AGREE and self.explain_divergences:
            outcome.detail = self._divergence_detail(pod, real_node, simon_node)
        # flight-recorder hook: a --explain'd pod gets its full
        # decision captured at exactly this step's oracle state, with
        # shadow provenance stamped (obs/explain.capture contract)
        if EXPLAIN.enabled and EXPLAIN.should_record(pod):
            idx = (
                self.oracle.node_index[real_node]
                if real_node is not None
                else None
            )
            EXPLAIN.capture(self.oracle, pod, idx)
            EXPLAIN.annotate(
                pod,
                engine="shadow-replay",
                shadow_seq=st.seq,
                shadow_class=cls,
                real_node=real_node or "",
                simon_node=simon_node or "",
            )
        # commit REALITY, not simon's counterfactual: later steps are
        # judged against the cluster as it actually evolved (a failed
        # real decision leaves the pod pending on the substrate — the
        # population the twin forecast requeues)
        if real_node is not None:
            self._app.commit_decision(pod, self.oracle.node_index[real_node])
        else:
            self._app.note_pending(pod)
        self.report.add(outcome)
        COUNTERS.inc("shadow_decisions_total")
        if cls == CLASS_AGREE:
            COUNTERS.inc("shadow_agree_total")
        else:
            COUNTERS.inc("shadow_divergence_total")
            COUNTERS.inc(
                "shadow_divergence_%s_total" % cls.split("-")[0]
            )
        return outcome

    def run(self, steps, budget=None) -> DivergenceReport:
        """Replay a step sequence and finish the report. Budget is
        checked between steps — the finest safe boundary replay has."""
        for i, st in enumerate(steps):
            if budget is not None and i % 64 == 0:
                budget.check(f"shadow replay (step {i})")
            self.step(st)
        return self.finish()

    def finish(self) -> DivergenceReport:
        self.report.finish(obs_profile.delta(self._obs_before))
        COUNTERS.gauge("shadow_agreement_rate", self.report.agreement_rate)
        return self.report
