"""Record simon's own scheduling decisions as a shadow decision log.

``record_simulation`` drives the exact serial pipeline of
``scheduler/core.simulate`` (cluster workloads first, then each app in
order through the queue sorts) on the serial oracle, observing it
through the Simulator's ``decision_hook`` — the loop itself stays in
``scheduler/core.py``, so the recorder can never drift from the engine
it journals. Each cycle yields one Step: the UNSCHEDULED pod snapshot,
the node the cycle chose (or its failure reason), and — crucially —
the preemption evictions the cycle performed BEFORE the bind, attached
as ``evict_pod`` delta ops. Pre-bound pods (``spec.nodeName``) become
``place_pod`` deltas: they occupy capacity but were never scheduled.

The resulting log replays to 100% agreement by construction
(tests/test_shadow.py, CI self-conformance smoke): the replayer applies
a decision's deltas first, so its probe sees exactly the state the
serial cycle bound against — including post-eviction state for
preemptors. Any drift between the serial cycle and the replay probe is
therefore a real bug, not recording noise.

The recorder is also the seeded-fixture generator: tests mutate a
recorded log (rename the chosen node, drop an eviction delta) to
exercise every divergence class deterministically.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..models.decode import ResourceTypes
from ..models import workloads as wl
from ..scheduler.core import AppResource, Simulator
from .log import Step


def _pod_key(pod: dict) -> Tuple[str, str]:
    meta = pod.get("metadata") or {}
    return (meta.get("namespace") or "default", meta.get("name", ""))


class _StepRecorder:
    """Simulator.decision_hook target: turns serial-loop events into
    log steps (the hook hands PRE-commit pod snapshots)."""

    def __init__(self, steps: List[Step]):
        self.steps = steps

    def prebound(self, pod: dict):
        self.steps.append(
            Step(
                seq=len(self.steps),
                kind="delta",
                deltas=[{"op": "place_pod", "pod": pod}],
            )
        )

    def decision(self, pod: dict, node_name: Optional[str], reason: str, evictions):
        deltas = []
        for ev in evictions:
            ns_name, v_name = _pod_key(ev.pod)
            deltas.append(
                {
                    "op": "evict_pod",
                    "namespace": ns_name,
                    "name": v_name,
                    "node": ev.node_name,
                    "preemptor": ev.preemptor,
                }
            )
        self.steps.append(
            Step(
                seq=len(self.steps),
                kind="decision",
                pod=pod,
                node=node_name,
                reason=reason if node_name is None else "",
                deltas=deltas,
            )
        )


def record_simulation(
    cluster: ResourceTypes,
    apps: List[AppResource],
    budget=None,
    use_greed: bool = False,
    steps_out: Optional[List[Step]] = None,
) -> List[Step]:
    """Run the serial simulation of ``cluster`` + ``apps`` and return
    its decisions as log steps, in commit order. The caller's cluster
    is not mutated (same ``copy()`` discipline as ``simulate()``); the
    generated-name counter is reset so repeated recordings of the same
    inputs produce the identical pod sequence. ``steps_out`` (a list
    the caller owns) receives steps as they happen, so a deadline halt
    still leaves the completed prefix — a valid, replayable log."""
    wl.reset_name_counter()
    steps: List[Step] = steps_out if steps_out is not None else []
    sim = Simulator(engine="oracle", use_greed=use_greed, budget=budget)
    sim.decision_hook = _StepRecorder(steps)
    sim.run_cluster(cluster.copy(), build_status=False)
    for app in apps:
        if budget is not None:
            budget.check(f"shadow recording app boundary ({app.name})")
        sim.schedule_app(app, build_status=False)
    return steps
