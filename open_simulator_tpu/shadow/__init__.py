"""Shadow-scheduler divergence auditor (docs/OBSERVABILITY.md).

The reference's whole value is answering "will it schedule, and where"
with the real kube-scheduler engine (PAPER.md §0); this package closes
the loop in the other direction: take the decisions a REAL scheduler
actually made — tailed live from a cluster (``ingest``) or read from a
recorded decision log (``log``) — replay each one through simon's own
oracle/scan against the same evolving cluster state (``replay``), and
explain every disagreement with per-node filter verdicts and weighted
score vectors (``report``).

Three cooperating uses:

- **continuous conformance**: replaying a production scheduler's log
  reports the agreement rate and a divergence taxonomy (node /
  feasibility / ordering), so simon's answers can be trusted at the
  scale they are meant for;
- **self-conformance**: ``record`` writes a log of simon's OWN serial
  placements; replaying it must report 100% agreement (gated in CI) —
  a loud tripwire for any drift between the serial cycle and the
  warm replay path;
- **trace generation**: a recorded log doubles as the arrival/churn
  trace the time-stepped simulation roadmap item needs.

Entry point: ``simon shadow`` (cli.py).
"""

from .log import (
    DecisionLogWriter,
    Step,
    cluster_fingerprint,
    read_decision_log,
)
from .record import record_simulation
from .replay import ShadowReplayer
from .report import DivergenceReport

__all__ = [
    "DecisionLogWriter",
    "DivergenceReport",
    "ShadowReplayer",
    "Step",
    "cluster_fingerprint",
    "read_decision_log",
    "record_simulation",
]
