"""Live-cluster decision ingestor — watch-style polling over the
LIST-only KubeClient.

The reference's fake-apiserver layer imports a snapshot once
(models/kubeclient.py); shadow mode needs the OTHER half: a stream of
the decisions the production scheduler keeps making. A LIST-only
client cannot watch, so the tailer polls: each ``poll()`` re-lists
pods (and nodes) with the chunked, resourceVersion-anchored pager and
diffs against the previous poll's state, normalizing every observed
change into decision-log steps (shadow/log.py):

- a pod newly carrying ``spec.nodeName`` -> one ``decision`` step (the
  pod is recorded UNBOUND — nodeName/status stripped — with the
  observed node as the real scheduler's choice);
- a pod newly marked unschedulable (``PodScheduled`` condition False,
  reason ``Unschedulable``) -> a failure ``decision`` carrying the
  condition's message (emitted once per pod until its state changes);
- a bound pod that disappeared -> an ``evict_pod`` delta;
- node add/remove -> ``add_node`` / ``remove_node`` deltas.

Decision provenance: the poller ALSO lists the apiserver's Event
objects (``/api/v1/events``) when that endpoint answers — the
scheduler's own ``Scheduled`` / ``FailedScheduling`` events are the
closest thing a LIST-only client gets to the Binding objects
themselves. An observed binding corroborated by a ``Scheduled`` event
counts as an event-sourced decision
(``shadow_ingest_event_decisions_total``); one inferred purely from
the pod diff counts ``shadow_ingest_diff_decisions_total`` — the two
counters make the inference tail measurable instead of silent. A
``FailedScheduling`` event's message (the scheduler's full reason
text) wins over the pod condition's when both exist. Clusters whose
apiserver does not expose the events endpoint probe it ONCE, count
``shadow_ingest_events_unsupported_total``, and fall back to pure
diff inference forever after.

``bootstrap()`` turns the first LIST into the starting state: the node
list plus one ``place_pod`` delta step for every already-bound pod, so
the replayer's mirror begins from the cluster as found. Each pod LIST's
apiserver resourceVersion is recorded (``last_rv``) for diagnostics and
snapshot ordering; WITHIN a list, an expired continue token re-pages
anchored at that version (kubeclient.list_with_rv) instead of forcing
one giant GET. Polling cost is one or two paged LISTs per interval,
which the PR-2 retry/breaker machinery already hardens.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Tuple

from ..utils.trace import COUNTERS
from .log import Step

PODS_PATH = "/api/v1/pods"
NODES_PATH = "/api/v1/nodes"
EVENTS_PATH = "/api/v1/events"


def _pod_key(pod: dict) -> Tuple[str, str]:
    meta = pod.get("metadata") or {}
    return (meta.get("namespace") or "default", meta.get("name", ""))


def _bound_node(pod: dict) -> Optional[str]:
    return (pod.get("spec") or {}).get("nodeName") or None


def _unschedulable_message(pod: dict) -> Optional[str]:
    for cond in ((pod.get("status") or {}).get("conditions")) or []:
        if (
            cond.get("type") == "PodScheduled"
            and cond.get("status") == "False"
            and cond.get("reason") == "Unschedulable"
        ):
            return cond.get("message") or "Unschedulable"
    return None


def _strip_binding(pod: dict) -> dict:
    """The decision records the pod as the scheduler SAW it: unbound,
    no status phase/conditions (the replayer probes this form)."""
    q = copy.deepcopy(pod)
    (q.get("spec") or {}).pop("nodeName", None)
    q.pop("status", None)
    return q


def _looks_unsupported(e: BaseException) -> bool:
    """Does this events-LIST failure mean the endpoint does not exist
    (latch off forever) rather than a transient flap (retry next
    poll)? The apiserver's spellings: HTTP 404 / 403, 'the server
    could not find the requested resource', 'Forbidden'."""
    msg = str(e).lower()
    return any(
        marker in msg
        for marker in ("404", "403", "could not find", "forbidden", "not found")
    )


def _scheduled_event_node(message: str) -> str:
    """Node name from a scheduler `Scheduled` event message
    ("Successfully assigned ns/pod to node-7" — the kube-scheduler's
    fixed format since Binding events exist)."""
    if " to " not in message:
        return ""
    return message.rsplit(" to ", 1)[1].strip()


class ClusterTailer:
    """Diff-based decision stream over one KubeClient, corroborated by
    scheduler Event objects when the apiserver exposes them."""

    def __init__(self, client):
        self.client = client
        self._seq = 0
        # (namespace, name) -> bound node (None = seen but unbound)
        self._pods: Dict[Tuple[str, str], Optional[str]] = {}
        self._failed: set = set()  # pods whose failure was already emitted
        self._nodes: Dict[str, dict] = {}
        # resourceVersion of the latest pod LIST (snapshot ordering)
        self.last_rv: Optional[str] = None
        # events endpoint support: None = unprobed, False = the probe
        # failed once (never retried: a 404/403 apiserver answers the
        # same way every poll), True = event-sourced provenance armed
        self._events_supported: Optional[bool] = None

    def _next(self) -> int:
        s = self._seq
        self._seq += 1
        return s

    # -- event-object ingestion ---------------------------------------------

    def _poll_events(self) -> Dict[Tuple[str, str], Tuple[str, str]]:
        """Latest scheduler event per pod key: ``("scheduled", node)``
        or ``("failed", message)``. Empty on unsupported endpoints and
        transient failures (the pod diff then carries the round)."""
        if self._events_supported is False:
            return {}
        from ..runtime.errors import ExternalIOError

        try:
            items = self.client.list(EVENTS_PATH)
        except (ExternalIOError, OSError, ValueError) as e:
            # degrade to diff inference, never kill the tail — but
            # only LATCH unsupported on an error that actually says so
            # (404/403): a transient flap during the first poll must
            # not disable event provenance for the daemon's lifetime
            if self._events_supported is None and _looks_unsupported(e):
                self._events_supported = False
                COUNTERS.inc("shadow_ingest_events_unsupported_total")
            return {}
        self._events_supported = True
        out: Dict[Tuple[str, str], Tuple[str, str]] = {}
        for ev in items:
            if not isinstance(ev, dict):
                continue
            obj = ev.get("involvedObject") or {}
            if obj.get("kind") != "Pod" or not obj.get("name"):
                continue
            key = (obj.get("namespace") or "default", obj.get("name", ""))
            reason = ev.get("reason")
            if reason == "Scheduled":
                out[key] = (
                    "scheduled",
                    _scheduled_event_node(ev.get("message") or ""),
                )
            elif reason == "FailedScheduling":
                out[key] = ("failed", ev.get("message") or "")
        return out

    def bootstrap(self) -> Tuple[List[dict], List[Step]]:
        """First LIST: returns (nodes, steps) where steps place every
        already-bound pod onto the mirror."""
        nodes = self.client.list(NODES_PATH)
        pods, self.last_rv = self.client.list_with_rv(PODS_PATH)
        self._nodes = {
            (n.get("metadata") or {}).get("name", ""): n for n in nodes
        }
        steps: List[Step] = []
        ops = []
        for pod in pods:
            key = _pod_key(pod)
            node = _bound_node(pod)
            self._pods[key] = node
            if node and node in self._nodes:
                ops.append({"op": "place_pod", "pod": copy.deepcopy(pod)})
        if ops:
            steps.append(Step(seq=self._next(), kind="delta", deltas=ops))
        return nodes, steps

    def poll(self) -> List[Step]:
        """One diff round: LIST pods + nodes (+ events when exposed),
        emit steps for every observed change since the previous
        round."""
        steps: List[Step] = []
        events = self._poll_events()
        nodes = self.client.list(NODES_PATH)
        seen_nodes = {
            (n.get("metadata") or {}).get("name", ""): n for n in nodes
        }
        for name, node in seen_nodes.items():
            if name not in self._nodes:
                steps.append(
                    Step(
                        seq=self._next(),
                        kind="delta",
                        deltas=[{"op": "add_node", "node": copy.deepcopy(node)}],
                    )
                )
        removed_nodes = [n for n in self._nodes if n not in seen_nodes]
        pods, pods_rv = self.client.list_with_rv(PODS_PATH)
        self.last_rv = pods_rv
        seen: Dict[Tuple[str, str], Optional[str]] = {}
        for pod in pods:
            key = _pod_key(pod)
            node = _bound_node(pod)
            prev = self._pods.get(key, "absent")
            if node and prev in ("absent", None):
                if node not in seen_nodes:
                    # bound to a node this round's node LIST has not
                    # shown yet (the pod LIST races node creation):
                    # leave the pod OUT of `seen` so the next poll —
                    # after the add_node delta has landed — emits the
                    # decision instead of dropping it forever
                    continue
                seen[key] = node
                # provenance: a Scheduled event naming this pod (and
                # not contradicting the authoritative spec.nodeName)
                # makes this an event-sourced decision; otherwise the
                # binding was inferred from the pod diff alone
                ev = events.get(key)
                if ev is not None and ev[0] == "scheduled" and ev[1] in ("", node):
                    COUNTERS.inc("shadow_ingest_event_decisions_total")
                else:
                    if ev is not None and ev[0] == "scheduled":
                        # the event names a different node than the
                        # spec — trust the spec, flag the drift
                        COUNTERS.inc("shadow_ingest_event_mismatch_total")
                    COUNTERS.inc("shadow_ingest_diff_decisions_total")
                steps.append(
                    Step(
                        seq=self._next(),
                        kind="decision",
                        pod=_strip_binding(pod),
                        node=node,
                    )
                )
                self._failed.discard(key)
                continue
            seen[key] = node
            if node is None:
                msg = _unschedulable_message(pod)
                ev = events.get(key)
                if ev is not None and ev[0] == "failed" and ev[1]:
                    # the scheduler's own event text is the richer
                    # failure record; it also surfaces failures whose
                    # pod condition has not landed yet
                    msg = ev[1]
                    source = "event"
                else:
                    source = "diff"
                if msg is not None and key not in self._failed:
                    COUNTERS.inc(
                        f"shadow_ingest_{source}_decisions_total"
                    )
                    steps.append(
                        Step(
                            seq=self._next(),
                            kind="decision",
                            pod=_strip_binding(pod),
                            node=None,
                            reason=msg,
                        )
                    )
                    self._failed.add(key)
        # disappeared pods: evict from the mirror (skip pods whose node
        # also vanished — the remove_node reload drops them wholesale).
        # A vanished UNBOUND pod evicts too (no node): the mirror's
        # pending queue must not hold deleted pods forever — the twin
        # forecast requeues that queue (twin/queries.py). Failure dedup
        # state always clears, so a recreated same-name pod that is
        # unschedulable again gets a fresh decision
        evict_ops = []
        for key, node in self._pods.items():
            if key in seen:
                continue
            self._failed.discard(key)
            if node and node in seen_nodes:
                evict_ops.append(
                    {
                        "op": "evict_pod",
                        "namespace": key[0],
                        "name": key[1],
                        "node": node,
                    }
                )
            elif not node:
                evict_ops.append(
                    {"op": "evict_pod", "namespace": key[0], "name": key[1]}
                )
        if evict_ops:
            steps.append(Step(seq=self._next(), kind="delta", deltas=evict_ops))
        for name in removed_nodes:
            steps.append(
                Step(
                    seq=self._next(),
                    kind="delta",
                    deltas=[{"op": "remove_node", "name": name}],
                )
            )
        self._pods = seen
        self._nodes = seen_nodes
        return steps
