"""Live-cluster decision ingestor — watch-style polling over the
LIST-only KubeClient.

The reference's fake-apiserver layer imports a snapshot once
(models/kubeclient.py); shadow mode needs the OTHER half: a stream of
the decisions the production scheduler keeps making. A LIST-only
client cannot watch, so the tailer polls: each ``poll()`` re-lists
pods (and nodes) with the chunked, resourceVersion-anchored pager and
diffs against the previous poll's state, normalizing every observed
change into decision-log steps (shadow/log.py):

- a pod newly carrying ``spec.nodeName`` -> one ``decision`` step (the
  pod is recorded UNBOUND — nodeName/status stripped — with the
  observed node as the real scheduler's choice);
- a pod newly marked unschedulable (``PodScheduled`` condition False,
  reason ``Unschedulable``) -> a failure ``decision`` carrying the
  condition's message (emitted once per pod until its state changes);
- a bound pod that disappeared -> an ``evict_pod`` delta;
- node add/remove -> ``add_node`` / ``remove_node`` deltas.

``bootstrap()`` turns the first LIST into the starting state: the node
list plus one ``place_pod`` delta step for every already-bound pod, so
the replayer's mirror begins from the cluster as found. Each pod LIST's
apiserver resourceVersion is recorded (``last_rv``) for diagnostics and
snapshot ordering; WITHIN a list, an expired continue token re-pages
anchored at that version (kubeclient.list_with_rv) instead of forcing
one giant GET. Polling cost is one paged LIST per interval, which the
PR-2 retry/breaker machinery already hardens.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Tuple

from .log import Step

PODS_PATH = "/api/v1/pods"
NODES_PATH = "/api/v1/nodes"


def _pod_key(pod: dict) -> Tuple[str, str]:
    meta = pod.get("metadata") or {}
    return (meta.get("namespace") or "default", meta.get("name", ""))


def _bound_node(pod: dict) -> Optional[str]:
    return (pod.get("spec") or {}).get("nodeName") or None


def _unschedulable_message(pod: dict) -> Optional[str]:
    for cond in ((pod.get("status") or {}).get("conditions")) or []:
        if (
            cond.get("type") == "PodScheduled"
            and cond.get("status") == "False"
            and cond.get("reason") == "Unschedulable"
        ):
            return cond.get("message") or "Unschedulable"
    return None


def _strip_binding(pod: dict) -> dict:
    """The decision records the pod as the scheduler SAW it: unbound,
    no status phase/conditions (the replayer probes this form)."""
    q = copy.deepcopy(pod)
    (q.get("spec") or {}).pop("nodeName", None)
    q.pop("status", None)
    return q


class ClusterTailer:
    """Diff-based decision stream over one KubeClient."""

    def __init__(self, client):
        self.client = client
        self._seq = 0
        # (namespace, name) -> bound node (None = seen but unbound)
        self._pods: Dict[Tuple[str, str], Optional[str]] = {}
        self._failed: set = set()  # pods whose failure was already emitted
        self._nodes: Dict[str, dict] = {}
        # resourceVersion of the latest pod LIST (snapshot ordering)
        self.last_rv: Optional[str] = None

    def _next(self) -> int:
        s = self._seq
        self._seq += 1
        return s

    def bootstrap(self) -> Tuple[List[dict], List[Step]]:
        """First LIST: returns (nodes, steps) where steps place every
        already-bound pod onto the mirror."""
        nodes = self.client.list(NODES_PATH)
        pods, self.last_rv = self.client.list_with_rv(PODS_PATH)
        self._nodes = {
            (n.get("metadata") or {}).get("name", ""): n for n in nodes
        }
        steps: List[Step] = []
        ops = []
        for pod in pods:
            key = _pod_key(pod)
            node = _bound_node(pod)
            self._pods[key] = node
            if node and node in self._nodes:
                ops.append({"op": "place_pod", "pod": copy.deepcopy(pod)})
        if ops:
            steps.append(Step(seq=self._next(), kind="delta", deltas=ops))
        return nodes, steps

    def poll(self) -> List[Step]:
        """One diff round: LIST pods + nodes, emit steps for every
        observed change since the previous round."""
        steps: List[Step] = []
        nodes = self.client.list(NODES_PATH)
        seen_nodes = {
            (n.get("metadata") or {}).get("name", ""): n for n in nodes
        }
        for name, node in seen_nodes.items():
            if name not in self._nodes:
                steps.append(
                    Step(
                        seq=self._next(),
                        kind="delta",
                        deltas=[{"op": "add_node", "node": copy.deepcopy(node)}],
                    )
                )
        removed_nodes = [n for n in self._nodes if n not in seen_nodes]
        pods, pods_rv = self.client.list_with_rv(PODS_PATH)
        self.last_rv = pods_rv
        seen: Dict[Tuple[str, str], Optional[str]] = {}
        for pod in pods:
            key = _pod_key(pod)
            node = _bound_node(pod)
            prev = self._pods.get(key, "absent")
            if node and prev in ("absent", None):
                if node not in seen_nodes:
                    # bound to a node this round's node LIST has not
                    # shown yet (the pod LIST races node creation):
                    # leave the pod OUT of `seen` so the next poll —
                    # after the add_node delta has landed — emits the
                    # decision instead of dropping it forever
                    continue
                seen[key] = node
                steps.append(
                    Step(
                        seq=self._next(),
                        kind="decision",
                        pod=_strip_binding(pod),
                        node=node,
                    )
                )
                self._failed.discard(key)
                continue
            seen[key] = node
            if node is None:
                msg = _unschedulable_message(pod)
                if msg is not None and key not in self._failed:
                    steps.append(
                        Step(
                            seq=self._next(),
                            kind="decision",
                            pod=_strip_binding(pod),
                            node=None,
                            reason=msg,
                        )
                    )
                    self._failed.add(key)
        # disappeared pods: evict from the mirror (skip pods whose node
        # also vanished — the remove_node reload drops them wholesale).
        # Failure dedup state always clears, so a recreated same-name
        # pod that is unschedulable again gets a fresh decision
        evict_ops = []
        for key, node in self._pods.items():
            if key in seen:
                continue
            self._failed.discard(key)
            if node and node in seen_nodes:
                evict_ops.append(
                    {
                        "op": "evict_pod",
                        "namespace": key[0],
                        "name": key[1],
                        "node": node,
                    }
                )
        if evict_ops:
            steps.append(Step(seq=self._next(), kind="delta", deltas=evict_ops))
        for name in removed_nodes:
            steps.append(
                Step(
                    seq=self._next(),
                    kind="delta",
                    deltas=[{"op": "remove_node", "name": name}],
                )
            )
        self._pods = seen
        self._nodes = seen_nodes
        return steps
