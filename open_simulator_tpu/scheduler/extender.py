"""HTTP scheduler extenders — the host-callback escape hatch.

Mirrors vendor/k8s.io/kubernetes/pkg/scheduler/core/extender.go:
- Filter (extender.go:273-339): POST {urlPrefix}/{filterVerb} with
  ExtenderArgs{pod, nodes|nodenames}; the result's node list replaces
  the feasible set, failedNodes carry per-node reasons; errors fail the
  pod unless `ignorable`
- Prioritize (extender.go:343-383): POST returns HostPriorityList;
  host scores * weight are summed across extenders and scaled by
  MaxNodeScore/MaxExtenderPriority = 10 into the plugin score sum
  (generic_scheduler.go:519-556)
- Bind (extender.go:385-399): a binder extender is delegated the bind
- ProcessPreemption (extender.go:164-205): a preempt-verb extender is
  consulted during DefaultPreemption's candidate selection
  (default_preemption.go:346-393 CallExtenders) with the dry-run victim
  map and returns the subset of (node, victims) it accepts — possibly
  with a different victim list per node
- IsInterested (extender.go:406-424): only pods requesting a managed
  resource reach the extender (no managedResources = all pods)

Extenders run on the host (they are arbitrary RPC), so a simulation
with extenders uses the serial oracle path — the scan cannot carry an
HTTP round-trip per pod (SURVEY.md §2.3: extender fan-out maps to a
host-callback escape hatch, not a kernel).

I/O hardening (runtime/retry.py, docs/ROBUSTNESS.md): every extender
call retries transient transport errors with capped exponential
backoff and deterministic jitter; an endpoint that keeps failing trips
its per-endpoint circuit breaker, after which calls fail fast (for an
`ignorable` extender that is a loud trace-noted skip; a mandatory one
fails the pod) — a dead extender must not hang a 100k-pod plan behind
timeout × retries × pods.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..runtime.errors import ExternalIOError
from ..runtime.retry import retry_io

MAX_NODE_SCORE = 100
MAX_EXTENDER_PRIORITY = 10
DEFAULT_TIMEOUT_S = 5.0


class ExtenderError(ExternalIOError, RuntimeError):
    """Extender transport/protocol failure. Part of the runtime error
    taxonomy (an ExternalIOError) while staying a RuntimeError for the
    oracle's existing handling."""


def _pod_uid(pod: dict) -> str:
    """Pod identifier for MetaPod round-trips. The reference matches on
    metadata.uid alone (convertPodUIDToPod); simulated pods often carry
    no uid, so fall back to namespace/name — stable and unique within a
    simulation."""
    meta = pod.get("metadata") or {}
    uid = meta.get("uid")
    if uid:
        return str(uid)
    return f"{meta.get('namespace') or 'default'}/{meta.get('name', '')}"


@dataclass
class ExtenderConfig:
    """KubeSchedulerConfiguration `extenders:` entry (v1beta1)."""

    url_prefix: str
    filter_verb: str = ""
    prioritize_verb: str = ""
    bind_verb: str = ""
    preempt_verb: str = ""
    weight: int = 1
    node_cache_capable: bool = False
    ignorable: bool = False
    managed_resources: List[str] = field(default_factory=list)
    http_timeout_s: float = DEFAULT_TIMEOUT_S

    @classmethod
    def from_dict(cls, d: dict) -> "ExtenderConfig":
        return cls(
            url_prefix=d.get("urlPrefix", ""),
            filter_verb=d.get("filterVerb", ""),
            prioritize_verb=d.get("prioritizeVerb", ""),
            bind_verb=d.get("bindVerb", ""),
            preempt_verb=d.get("preemptVerb", ""),
            weight=int(d.get("weight", 1) or 1),
            node_cache_capable=bool(d.get("nodeCacheCapable", False)),
            ignorable=bool(d.get("ignorable", False)),
            managed_resources=[
                r.get("name", "") for r in d.get("managedResources") or []
            ],
            http_timeout_s=float(d.get("httpTimeoutSeconds", DEFAULT_TIMEOUT_S)),
        )


class HTTPExtender:
    def __init__(self, config: ExtenderConfig):
        self.config = config

    @property
    def name(self) -> str:
        return self.config.url_prefix

    @property
    def is_binder(self) -> bool:
        return bool(self.config.bind_verb)

    @property
    def supports_preemption(self) -> bool:
        """SupportsPreemption (extender.go:158-162)."""
        return bool(self.config.preempt_verb)

    def is_interested(self, pod: dict) -> bool:
        if not self.config.managed_resources:
            return True
        managed = set(self.config.managed_resources)
        for c in ((pod.get("spec") or {}).get("containers")) or []:
            res = c.get("resources") or {}
            for section in ("requests", "limits"):
                if managed & set((res.get(section) or {}).keys()):
                    return True
        return False

    def _send(self, verb: str, args: dict) -> dict:
        url = self.config.url_prefix.rstrip("/") + "/" + verb
        body = json.dumps(args).encode()

        def attempt() -> dict:
            req = urllib.request.Request(
                url,
                data=body,
                headers={
                    "Content-Type": "application/json",
                    "Accept": "application/json",
                },
                method="POST",
            )
            with urllib.request.urlopen(
                req, timeout=self.config.http_timeout_s
            ) as r:
                return json.load(r)

        def retryable(e: BaseException) -> bool:
            # 4xx and malformed bodies are protocol answers, not
            # transient outages — fail them without retrying
            if isinstance(e, urllib.error.HTTPError) and e.code < 500:
                return False
            return not isinstance(e, json.JSONDecodeError)

        try:
            return retry_io(
                attempt,
                label=f"extender {url}",
                endpoint=url,
                catch=(OSError, json.JSONDecodeError),
                retryable=retryable,
            )
        except ExtenderError:
            raise
        except (ExternalIOError, OSError, json.JSONDecodeError) as e:
            raise ExtenderError(f"extender {url}: {e}", endpoint=url) from e

    def filter(
        self, pod: dict, nodes: List[dict]
    ) -> Tuple[List[dict], Dict[str, str]]:
        """Returns (feasible nodes, failed {node: reason}). Raises
        ExtenderError on transport/protocol errors."""
        if not self.config.filter_verb:
            return nodes, {}
        by_name = {((n.get("metadata") or {}).get("name", "")): n for n in nodes}
        args: dict = {"pod": pod}
        if self.config.node_cache_capable:
            args["nodenames"] = list(by_name.keys())
        else:
            args["nodes"] = {"items": nodes}
        result = self._send(self.config.filter_verb, args)
        if not isinstance(result, dict):
            raise ExtenderError(
                f"extender {self.name}: malformed filter response"
            )
        if result.get("error"):
            raise ExtenderError(f"extender {self.name}: {result['error']}")
        failed = dict(result.get("failedNodes") or {})
        if self.config.node_cache_capable and result.get("nodenames") is not None:
            out = []
            for name in result["nodenames"]:
                if name not in by_name:
                    raise ExtenderError(
                        f"extender {self.name} claims unknown node {name!r}"
                    )
                out.append(by_name[name])
            return out, failed
        if result.get("nodes") is not None:
            out = list((result["nodes"] or {}).get("items") or [])
            for n in out:
                name = (n.get("metadata") or {}).get("name", "")
                if name not in by_name:
                    raise ExtenderError(
                        f"extender {self.name} claims unknown node {name!r}"
                    )
            return out, failed
        return [], failed

    def prioritize(self, pod: dict, nodes: List[dict]) -> Optional[Dict[str, int]]:
        """Returns {node_name: raw score} or None on (ignored) error."""
        if not self.config.prioritize_verb:
            return {
                (n.get("metadata") or {}).get("name", ""): 0 for n in nodes
            }
        args: dict = {"pod": pod}
        if self.config.node_cache_capable:
            args["nodenames"] = [
                (n.get("metadata") or {}).get("name", "") for n in nodes
            ]
        else:
            args["nodes"] = {"items": nodes}
        try:
            result = self._send(self.config.prioritize_verb, args)
        except ExtenderError:
            # prioritization errors are ignored (generic_scheduler.go:536)
            return None
        # A malformed body (non-list, or non-dict entries) is treated the
        # same as a transport error: ignored, like the reference.
        if not isinstance(result, list) or not all(
            isinstance(h, dict) for h in result
        ):
            return None
        return {
            h.get("host", ""): int(h.get("score", 0)) for h in result
        }

    def process_preemption(
        self,
        pod: dict,
        victims_map: Dict[str, dict],
        get_node_pods,
    ) -> Dict[str, dict]:
        """ProcessPreemption (extender.go:164-205).

        `victims_map` is {node_name: {"pods": [pod dicts],
        "numPDBViolations": int}}; `get_node_pods(node_name)` returns the
        pods currently committed on that node (the NodeInfoLister role).

        POSTs ExtenderPreemptionArgs — `nodeNameToMetaVictims` (pod UIDs
        only) when nodeCacheCapable, else `nodeNameToVictims` (full pods)
        — and converts the result's meta victims back to pod objects via
        the node's pod list (convertToNodeNameToVictims,
        extender.go:207-233). A meta victim naming an unknown node or a
        pod not on that node is a scheduler/extender cache inconsistency
        and raises (convertPodUIDToPod, extender.go:236-247).

        Like the reference conversion, numPDBViolations is NOT carried
        back from the extender result (extender.go:218-220 builds Victims
        with pods only), so post-extender candidates tie at 0 violations.
        """
        if not self.supports_preemption:
            raise ExtenderError(
                f"preempt verb is not defined for extender {self.name} "
                "but run into ProcessPreemption"
            )
        args: dict = {"pod": pod}
        if self.config.node_cache_capable:
            args["nodeNameToMetaVictims"] = {
                node: {
                    "pods": [{"uid": _pod_uid(p)} for p in v.get("pods") or []],
                    "numPDBViolations": int(v.get("numPDBViolations") or 0),
                }
                for node, v in victims_map.items()
            }
        else:
            args["nodeNameToVictims"] = {
                node: {
                    "pods": list(v.get("pods") or []),
                    "numPDBViolations": int(v.get("numPDBViolations") or 0),
                }
                for node, v in victims_map.items()
            }
        result = self._send(self.config.preempt_verb, args)
        if not isinstance(result, dict):
            raise ExtenderError(
                f"extender {self.name}: malformed preemption response"
            )
        # extenders always answer with meta victims (extender.go:197-198);
        # accept Go-default field casing too (the structs carry no json
        # tags, so a Go extender marshals `NodeNameToMetaVictims`)
        meta = result.get("nodeNameToMetaVictims")
        if meta is None:
            meta = result.get("NodeNameToMetaVictims")
        out: Dict[str, dict] = {}
        for node, mv in (meta or {}).items():
            if node not in victims_map:
                raise ExtenderError(
                    f"extender {self.name} claims unknown node {node!r}"
                )
            node_pods = {_pod_uid(p): p for p in get_node_pods(node)}
            pods = []
            for mp in (mv or {}).get("pods") or (mv or {}).get("Pods") or []:
                uid = (mp or {}).get("uid") or (mp or {}).get("UID") or ""
                if uid not in node_pods:
                    raise ExtenderError(
                        f"extender {self.name} claims to preempt pod "
                        f"(UID: {uid}) on node: {node}, but the pod is not "
                        "found on that node"
                    )
                pods.append(node_pods[uid])
            out[node] = {"pods": pods, "numPDBViolations": 0}
        return out

    def bind(self, pod: dict, node_name: str) -> None:
        meta = pod.get("metadata") or {}
        result = self._send(
            self.config.bind_verb,
            {
                "podName": meta.get("name", ""),
                "podNamespace": meta.get("namespace", ""),
                "podUID": meta.get("uid", ""),
                "node": node_name,
            },
        )
        if result.get("error"):
            raise ExtenderError(f"extender bind {self.name}: {result['error']}")


def filter_with_extenders(
    extenders: List[HTTPExtender],
    pod: dict,
    feasible: List,
    fail,
    on_node_fail=None,
) -> List:
    """findNodesThatPassExtenders (generic_scheduler.go:345-374) over
    oracle NodeStates. `fail(reason)` records per-node failure reasons;
    `on_node_fail(node_name, reason)` (optional) additionally receives
    the NODE attribution the aggregate counts discard — the --explain
    recorder reads per-node verdicts through it, with the exact same
    message strings `fail` sees, so explain and report stay in
    lockstep."""
    for ext in extenders:
        if not feasible:
            break
        if not ext.is_interested(pod):
            continue
        nodes = [ns.node for ns in feasible]
        try:
            kept_nodes, failed = ext.filter(pod, nodes)
        except ExtenderError:
            if ext.config.ignorable:
                continue
            raise
        for name, msg in sorted(failed.items()):
            fail(msg)
            if on_node_fail is not None:
                on_node_fail(name, msg)
        kept_names = {
            ((n.get("metadata") or {}).get("name", "")) for n in kept_nodes
        }
        feasible = [ns for ns in feasible if ns.name in kept_names]
    return feasible


def extender_scores(
    extenders: List[HTTPExtender], pod: dict, feasible: List
) -> List[int]:
    """Combined extender contribution per feasible node, already scaled
    by MaxNodeScore/MaxExtenderPriority (generic_scheduler.go:552-556)."""
    combined = {ns.name: 0 for ns in feasible}
    for ext in extenders:
        if not ext.is_interested(pod):
            continue
        scores = ext.prioritize(pod, [ns.node for ns in feasible])
        if scores is None:
            continue
        for host, score in scores.items():
            if host in combined:
                combined[host] += score * ext.config.weight
    scale = MAX_NODE_SCORE // MAX_EXTENDER_PRIORITY
    return [combined[ns.name] * scale for ns in feasible]


def call_extenders_preemption(
    extenders: List[HTTPExtender],
    pod: dict,
    victims_map: Dict[str, dict],
    get_node_pods,
) -> Dict[str, dict]:
    """CallExtenders (default_preemption.go:346-393): run every
    preemption-capable, interested extender over the victim map in
    order, each seeing the previous one's output. An erroring ignorable
    extender is skipped; a non-ignorable error propagates (failing the
    preemption attempt). An empty map short-circuits — no later extender
    can resurrect candidates."""
    for ext in extenders:
        if not ext.supports_preemption or not ext.is_interested(pod):
            continue
        try:
            victims_map = ext.process_preemption(pod, victims_map, get_node_pods)
        except ExtenderError:
            if ext.config.ignorable:
                continue
            raise
        if not victims_map:
            break
    return victims_map


def extenders_from_config_doc(doc: dict) -> List[HTTPExtender]:
    """Build extenders from an already-parsed KubeSchedulerConfiguration
    document. Raises ValueError on a malformed `extenders:` section."""
    extenders = doc.get("extenders") or []
    if not isinstance(extenders, list) or not all(
        isinstance(e, dict) for e in extenders
    ):
        raise ValueError("bad extenders section")
    return [HTTPExtender(ExtenderConfig.from_dict(e)) for e in extenders]
