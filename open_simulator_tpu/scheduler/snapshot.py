"""Cluster snapshot: serialize/restore simulation state.

The reference has no persistence — every run rebuilds the fake cluster
from YAML (SURVEY.md §5: checkpoint/resume absent; the `simulator-plan`
ConfigMap constants are vestigial). Here a snapshot is first-class:
the full post-simulation cluster (nodes with mutated storage/GPU
annotations + placed pods) round-trips through one JSON file, enabling

- checkpoint/resume: continue deploying more apps onto a prior result
- defragmentation/what-if studies on a captured cluster state
- exporting a simulated cluster as the customConfig of a new run
"""

from __future__ import annotations

import json

from ..models.decode import ResourceTypes
from .core import NodeStatus, SimulateResult, Simulator

SNAPSHOT_VERSION = 1


def snapshot_to_dict(result: SimulateResult, cluster: ResourceTypes = None) -> dict:
    out = {
        "version": SNAPSHOT_VERSION,
        "nodes": [ns.node for ns in result.node_status],
        "pods": [p for ns in result.node_status for p in ns.pods],
        "unscheduled": [
            {"pod": up.pod, "reason": up.reason} for up in result.unscheduled_pods
        ],
    }
    # cluster-scoped scheduling config (PDBs feed DefaultPreemption,
    # PriorityClasses the admission emulation) so a resumed simulator
    # agrees with a fresh simulate() on identical state
    if cluster is not None:
        out["podDisruptionBudgets"] = list(cluster.pod_disruption_budgets)
        out["priorityClasses"] = list(cluster.priority_classes)
    return out


def save_snapshot(result: SimulateResult, path: str, cluster: ResourceTypes = None):
    with open(path, "w") as f:
        json.dump(snapshot_to_dict(result, cluster), f)


def load_snapshot(path: str) -> SimulateResult:
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict):
        raise ValueError(f"snapshot must be a JSON object, got {type(data).__name__}")
    if data.get("version") != SNAPSHOT_VERSION:
        raise ValueError(f"unsupported snapshot version: {data.get('version')}")
    if not isinstance(data.get("nodes"), list) or not isinstance(data.get("pods"), list):
        raise ValueError("snapshot missing 'nodes'/'pods' lists")
    by_node = {}
    statuses = [NodeStatus(node=n, pods=[]) for n in data["nodes"]]
    for st in statuses:
        by_node[(st.node.get("metadata") or {}).get("name", "")] = st
    for pod in data["pods"]:
        name = (pod.get("spec") or {}).get("nodeName")
        if name in by_node:
            by_node[name].pods.append(pod)
    from .core import UnscheduledPod

    result = SimulateResult(
        unscheduled_pods=[
            UnscheduledPod(pod=u["pod"], reason=u["reason"]) for u in data.get("unscheduled", [])
        ],
        node_status=statuses,
    )
    # carried alongside (not part of the scheduling result proper);
    # resume_simulator picks these up
    result.snapshot_extras = {
        "pdbs": data.get("podDisruptionBudgets") or [],
        "priority_classes": data.get("priorityClasses") or [],
    }
    return result


def resume_simulator(
    result: SimulateResult,
    engine: str = "tpu",
    pdbs=None,
    priority_classes=None,
) -> Simulator:
    """Rebuild a live Simulator from a snapshot: nodes re-admitted with
    their mutated annotations, pods re-placed with their bindings (GPU
    devices honored via the gpu-index annotation). PDBs and
    PriorityClasses default to what load_snapshot carried
    (snapshot_extras) so preemption on the resumed simulator matches a
    fresh simulate().

    Always resumes with the default first-max selectHost: the "sample"
    mode's RNG stream position is not part of a snapshot (Go's global
    rand has no checkpoint either), so a sample-mode run cannot be
    resumed stream-faithfully — re-run it fresh instead."""
    extras = getattr(result, "snapshot_extras", {}) or {}
    if pdbs is None:
        pdbs = extras.get("pdbs") or []
    if priority_classes is None:
        priority_classes = extras.get("priority_classes") or []
    sim = Simulator(engine=engine)
    cluster = ResourceTypes()
    cluster.nodes = [ns.node for ns in result.node_status]
    from .oracle import Oracle

    sim.oracle = Oracle(cluster.nodes, pdbs=pdbs, priority_classes=priority_classes)
    for ns in result.node_status:
        for pod in ns.pods:
            sim.oracle.place_existing_pod(pod)
            sim.cluster_pods.append(pod)
    return sim


def cluster_from_snapshot(result: SimulateResult) -> ResourceTypes:
    """Snapshot -> ResourceTypes, keeping only non-daemonset running
    pods, mirroring CreateClusterResourceFromClient's filter
    (simulator.go:369-441: keeps Running pods without a DaemonSet
    owner)."""
    res = ResourceTypes()
    res.nodes = [ns.node for ns in result.node_status]
    for ns in result.node_status:
        for pod in ns.pods:
            refs = (pod.get("metadata") or {}).get("ownerReferences") or []
            if any(r.get("kind") == "DaemonSet" for r in refs):
                continue
            if ((pod.get("status") or {}).get("phase")) not in (None, "Running"):
                continue
            res.pods.append(pod)
    return res
