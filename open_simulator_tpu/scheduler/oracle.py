"""Serial oracle scheduler.

A pure-Python, bit-exact reimplementation of one kube-scheduler v1.20.5
scheduling cycle (vendor/.../scheduler/core/generic_scheduler.go:131-180)
with the simulator's plugin profile:

  Filter:  NodeUnschedulable, NodeName, TaintToleration, NodeAffinity,
           NodePorts, NodeResourcesFit, PodTopologySpread,
           InterPodAffinity, Open-Local, Open-Gpu-Share
  Score:   NodeResourcesBalancedAllocation(1), ImageLocality(1),
           InterPodAffinity(1), NodeResourcesLeastAllocated(1),
           NodeAffinity(1), NodePreferAvoidPods(10000),
           PodTopologySpread(2), TaintToleration(1), Simon(1),
           Open-Local(1), Open-Gpu-Share(1)
           (default registry algorithmprovider/registry.go:118-131 plus
           the three custom plugins appended by
           pkg/simulator/utils.go:229-241)

The volume plugins of the default profile (VolumeRestrictions,
NodeVolumeLimits, VolumeBinding, VolumeZone) are vacuous here because
MakeValidPod rewrites every PVC volume to a hostPath (pkg/utils/
utils.go:476-484), so no pod ever carries a PVC volume source.

Deviation from the reference (documented, deliberate): selectHost uses
reservoir sampling among top-score nodes (generic_scheduler.go:186-209,
rand.Intn) — by default we pin the deterministic first maximum in node
order so the oracle and the TPU engine agree bit-for-bit. The opt-in
`select_host="sample"` mode reproduces the reference's reservoir
sampling algorithm with exact per-tie Intn consumption semantics
(utils/gorand.py ports Go math/rand, whose global source the reference
never seeds, i.e. the seed-1 stream); the stream itself is
bit-identical to Go's only when the rngCooked warm-up table is
supplied (SIMON_GO_RNG_COOKED — see gorand.py docstring).
tests/test_selecthost.py pins the measured first-max divergence on
tie-heavy clusters.

This oracle exists for conformance: the JAX engine
(open_simulator_tpu/ops/scan.py) must reproduce its placements exactly.
It is also the semantic documentation of every plugin formula.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from fractions import Fraction
from typing import Dict, List, Optional, Tuple

from ..models import labels as lbl
from ..models import requests as req
from ..models import storage as stor
from ..obs.explain import EXPLAIN
from ..utils.memo import IdentityMemo

MAX_NODE_SCORE = 100
MIN_NODE_SCORE = 0

# ImageLocality thresholds (vendor/.../imagelocality/image_locality.go)
_MB = 1024 * 1024
IMG_MIN_THRESHOLD = 23 * _MB
IMG_MAX_CONTAINER_THRESHOLD = 1000 * _MB

HARD_POD_AFFINITY_WEIGHT = 1  # interpodaffinity args default


# ---------------------------------------------------------------- node state


@dataclass
class GpuState:
    """Per-device GPU memory accounting (open-gpu-share GpuNodeInfo)."""

    count: int
    per_device_mem: int
    used: List[int] = field(default_factory=list)

    def __post_init__(self):
        if not self.used:
            self.used = [0] * self.count

    def available(self) -> List[int]:
        return [self.per_device_mem - u for u in self.used]

    def allocatable_count(self) -> int:
        """Number of fully-idle devices (NodeGpuInfo.GpuAllocatable)."""
        return sum(1 for u in self.used if u == 0)

    def allocate_gpu_ids(self, per_gpu_mem: int, count: int) -> Optional[List[int]]:
        """AllocateGpuId (gpunodeinfo.go:232-291).

        1 GPU: tightest fit (min idle memory that still fits, lowest
        device id wins ties via strict '<' on idle memory).
        k GPUs: two-pointer greedy packing in device-id order.
        """
        if per_gpu_mem <= 0 or count <= 0:
            return None
        avail = self.available()
        if count == 1:
            best, best_mem = None, None
            for dev in range(self.count):
                idle = avail[dev]
                if idle >= per_gpu_mem:
                    if best is None or idle < best_mem:
                        best, best_mem = dev, idle
            return None if best is None else [best]
        out: List[int] = []
        dev = 0
        picked = 0
        while dev < self.count and picked < count:
            if avail[dev] >= per_gpu_mem:
                out.append(dev)
                avail[dev] -= per_gpu_mem
                picked += 1
            else:
                dev += 1
        return out if picked == count else None

    def commit(self, devs: List[int], per_gpu_mem: int):
        for d in devs:
            self.used[d] += per_gpu_mem


@dataclass
class NodeState:
    node: dict
    index: int
    pods: List[dict] = field(default_factory=list)
    # Requested (true requests) and NonZeroRequested (scoring defaults)
    req_mcpu: int = 0
    req_mem: int = 0
    req_eph: int = 0
    req_scalar: Dict[str, int] = field(default_factory=dict)
    nz_mcpu: int = 0
    nz_mem: int = 0
    # floor-semantics totals (PodRequestsAndLimits-based report code) —
    # kept alongside the ceil accounting so report/caps aggregation can
    # read node totals instead of re-walking 100k pods (r4 host-tail)
    req_floor_mcpu: int = 0
    req_floor_mem: int = 0
    used_ports: set = field(default_factory=set)  # (ip, proto, port)
    gpu: Optional[GpuState] = None
    storage: Optional[stor.NodeStorage] = None
    # mutable allocatable (gpu-count is updated by the GPU plugin Reserve)
    alloc: Dict[str, Fraction] = field(default_factory=dict)
    # open-local allocations committed at bind, keyed by (namespace,
    # name) — recorded so preemption can reverse them exactly
    local_allocs: Dict[Tuple[str, str], tuple] = field(default_factory=dict)
    # copy-on-write: a pristine NodeState shares the decoded node dict
    # read-only; the ONLY binding-time node mutation is the open-local
    # storage annotation, which clones the metadata layers first via
    # own_node(). (An eager 4-dict clone per node cost ~40 ms per
    # Oracle at 10k nodes for runs that never touch storage.)
    owns_node: bool = False

    def own_node(self) -> dict:
        """Clone the node's metadata layers before the first
        annotation write, leaving the decoded source dict untouched
        (spec/status stay shared read-only, as before)."""
        if not self.owns_node:
            meta = self.node.get("metadata") or {}
            self.node = {
                **self.node,
                "metadata": {
                    **meta,
                    "labels": dict(meta.get("labels") or {}),
                    "annotations": dict(meta.get("annotations") or {}),
                },
            }
            self.owns_node = True
        return self.node

    @property
    def name(self) -> str:
        return (self.node.get("metadata") or {}).get("name", "")

    @property
    def labels(self) -> dict:
        return (self.node.get("metadata") or {}).get("labels") or {}

    def alloc_milli_cpu(self) -> int:
        v = self.alloc.get(req.CPU, Fraction(0)) * 1000
        return v.numerator // v.denominator

    def alloc_int(self, resource: str) -> int:
        v = self.alloc.get(resource, Fraction(0))
        return v.numerator // v.denominator


# per-source-node template memo (allocatable dict + GPU geometry):
# one identity-keyed lookup per add_node instead of three — the entry
# holds a strong ref to the node, so a key hit proves identity
# (utils/memo.py contract; registered with clear_all_memos below)
_NODE_TMPL_CACHE: dict = {}
_NODE_TMPL_CACHE_MAX = 1 << 17


def _node_template(node: dict):
    hit = _NODE_TMPL_CACHE.get(id(node))
    if hit is not None:
        return hit[1], hit[2], hit[3]
    alloc = req.node_allocatable(node)
    gpu_count = stor.node_gpu_count(node)
    per_dev = stor.node_gpu_per_device_memory(node) if gpu_count > 0 else 0
    if len(_NODE_TMPL_CACHE) >= _NODE_TMPL_CACHE_MAX:
        _NODE_TMPL_CACHE.clear()
    _NODE_TMPL_CACHE[id(node)] = (node, alloc, gpu_count, per_dev)
    return alloc, gpu_count, per_dev


def _register_node_tmpl_cache():
    from ..utils.memo import register_cache

    register_cache(_NODE_TMPL_CACHE.clear)


_register_node_tmpl_cache()


# replica clones share their containers list, so the port scan runs
# once per template instead of once per pod on the commit path
# (utils/memo.py contract); hostNetwork rides in the source tuple via
# its interned bool singleton
_PORTS_MEMO = IdentityMemo()


def _pod_host_ports(pod: dict) -> List[Tuple[str, str, int]]:
    spec = pod.get("spec") or {}
    host_net = bool(spec.get("hostNetwork"))
    return _PORTS_MEMO.get(
        (spec.get("containers"), host_net),
        lambda: _scan_host_ports(spec, host_net),
    )


def _scan_host_ports(spec: dict, host_net: bool) -> List[Tuple[str, str, int]]:
    out = []
    for c in spec.get("containers") or []:
        for p in c.get("ports") or []:
            port = p.get("hostPort")
            if not port and host_net:
                port = p.get("containerPort")
            if not port:
                continue
            ip = p.get("hostIP") or "0.0.0.0"
            proto = p.get("protocol") or "TCP"
            out.append((ip, proto, int(port)))
    return out


def _ports_conflict(want: List[Tuple[str, str, int]], used: set) -> bool:
    for ip, proto, port in want:
        for uip, uproto, uport in used:
            if uport != port or uproto != proto:
                continue
            if ip == "0.0.0.0" or uip == "0.0.0.0" or ip == uip:
                return True
    return False


# ------------------------------------------------------------------- oracle


def simple_commit_mask(batch, has_extenders: bool):
    """Per-CLASS mask of pods whose bind has no GPU/storage/extender
    side effects, so replay can use Oracle.commit_simple with a
    per-class ClassCommitCache instead of the general _reserve_and_bind
    (shared by engine.commit_host_at and applier.replay_scenario — the
    eligibility rule must stay identical in both)."""
    import numpy as np

    if has_extenders:
        return np.zeros(batch.u, bool)
    return (np.asarray(batch.gpu_mem) <= 0) & ~np.asarray(batch.wants_storage)


class ClassCommitCache:
    """(request summary, host-port tuple) per batch-scoped pod class —
    class members share request/port content by class-key construction
    (ops/encode.py:_class_key), so the walk runs once per class."""

    __slots__ = ("_info",)

    def __init__(self):
        self._info: Dict[int, tuple] = {}

    def commit(self, oracle: "Oracle", pod: dict, ns: "NodeState", cls: int):
        info = self._info.get(cls)
        if info is None:
            info = self._info[cls] = (
                req.pod_request_summary(pod),
                tuple(_pod_host_ports(pod)),
            )
        oracle.commit_simple(pod, ns, info[0], info[1])


@dataclass
class PreemptedPod:
    """One eviction performed by DefaultPreemption."""

    pod: dict
    node_name: str
    preemptor: str


class PostFilterContext:
    """The narrow cluster view handed to out-of-tree post_filter
    plugins (plugins.py SchedulerPlugin.post_filter): enough to
    implement a custom preemption policy without exposing oracle
    internals. Evictions are recorded exactly like DefaultPreemption's
    (the Simulator re-enqueues the victims; committed plugin state
    unreserves)."""

    def __init__(self, oracle: "Oracle", preemptor: dict):
        self._oracle = oracle
        self._preemptor = ((preemptor.get("metadata") or {}).get("name", ""))

    @property
    def nodes(self) -> List[dict]:
        return [ns.node for ns in self._oracle.nodes]

    def pods_on(self, node_name: str) -> List[dict]:
        idx = self._oracle.node_index.get(node_name)
        if idx is None:
            return []
        return list(self._oracle.nodes[idx].pods)

    def evict(self, pod: dict, node_name: str) -> None:
        idx = self._oracle.node_index.get(node_name)
        if idx is None:
            raise ValueError(f"unknown node {node_name!r}")
        ns = self._oracle.nodes[idx]
        if not any(p is pod for p in ns.pods):
            raise ValueError(
                f"pod {(pod.get('metadata') or {}).get('name', '')!r} "
                f"is not on node {node_name!r}"
            )
        self._oracle.evict_pod(ns, pod)
        self._oracle.preempted.append(
            PreemptedPod(pod=pod, node_name=node_name, preemptor=self._preemptor)
        )


class Oracle:
    """Serial scheduler over mutable node states."""

    def __init__(
        self,
        nodes: List[dict],
        registry=None,
        extenders=None,
        pdbs=None,
        priority_classes=None,
        enable_preemption: bool = True,
        score_weights=None,
        select_host: str = "first-max",
        rng=None,
    ):
        if registry is None:
            from .plugins import default_registry

            registry = default_registry
        self.registry = registry
        # score-plugin weights from an optional KubeSchedulerConfiguration
        # (schedconfig.py); None = the default profile
        from .schedconfig import DEFAULT_SCORE_WEIGHTS

        self.score_weights = (
            score_weights if score_weights is not None else DEFAULT_SCORE_WEIGHTS
        )
        # HTTP scheduler extenders (extender.py); host-side RPC, so a
        # simulation using them runs on this serial path only
        self.extenders = list(extenders or [])
        # DefaultPreemption inputs (scheduler/preemption.py)
        from .preemption import build_priority_resolver

        self.pdbs = list(pdbs or [])
        self._prio_resolver = build_priority_resolver(priority_classes or [])
        self.enable_preemption = enable_preemption
        # selectHost tie rule: "first-max" (default, deterministic,
        # scan-conformant) or "sample" (the reference's reservoir
        # sampling; `rng` must expose .intn(n), default GoRand(1) —
        # see module docstring deviation note)
        if select_host not in ("first-max", "sample"):
            raise ValueError(f"unknown select_host mode {select_host!r}")
        self.select_host = select_host
        if select_host == "sample" and rng is None:
            from ..utils.gorand import GoRand

            rng = GoRand(1)
        self._rng = rng
        # priority bookkeeping: commit sequence is the start-time proxy
        # for MoreImportantPod ties; _min_prio gates the preemption
        # attempt (a preemptor needs a strictly lower-priority pod to
        # exist at all, so the all-default-priority case pays nothing)
        self._seq_counter = 0
        self.commit_seq: Dict[Tuple[str, str], int] = {}
        self._min_prio = math.inf
        self.saw_priority = False
        self.preempted: List[PreemptedPod] = []
        # bumped whenever a node's mutable allocatable changes (GPU
        # Reserve adjusting gpu-count); TpuEngine keys its ClusterStatic
        # cache on this so stale allocatables never reach the scan
        self.alloc_epoch = 0
        self.nodes: List[NodeState] = []
        self.node_index: Dict[str, int] = {}
        # source (pre-clone) node dicts, in add order: the cross-run
        # ClusterStatic cache keys on their identities (encode.py
        # encode_cluster_cached) — strong refs per the IdentityMemo
        # contract, so a key hit proves the same objects
        self.source_nodes: List[dict] = []
        for n in nodes:
            self.add_node(n)
        # a fresh Oracle is a fresh scheduler run: stateful custom
        # plugins reset their per-run caches (plugins.py lifecycle)
        self.registry.begin_run(nodes)

    # -- priority helpers ---------------------------------------------------

    def pod_priority(self, pod: dict) -> int:
        return self._prio_resolver.priority(pod)

    def pod_preemption_policy(self, pod: dict) -> str:
        return self._prio_resolver.preemption_policy(pod)

    def commit_seq_of(self, pod: dict) -> int:
        meta = pod.get("metadata") or {}
        return self.commit_seq.get(
            (meta.get("namespace") or "default", meta.get("name", "")), 0
        )

    def drain_preempted(self) -> List[PreemptedPod]:
        out, self.preempted = self.preempted, []
        return out

    # -- cluster mutation ---------------------------------------------------

    def add_node(self, node: dict):
        # binding mutates ONLY node metadata annotations (storage VG
        # state via set_node_storage; gpu goes through ns.alloc) and
        # labels are report-read — so the decoded dict is shared
        # read-only and the metadata layers clone lazily on the FIRST
        # storage-annotation write (NodeState.own_node copy-on-write;
        # a full deepcopy of 10k nodes cost ~1 s per Oracle at bench
        # scale, the eager metadata clone still ~40 ms)
        self.source_nodes.append(node)
        ns = NodeState(node=node, index=len(self.nodes))
        alloc, gpu_count, per_dev = _node_template(node)
        if gpu_count > 0:
            # copy: GPU accounting writes ns.alloc[gpu-count]; non-GPU
            # nodes share the memoized allocatable read-only (no write
            # path touches ns.alloc when ns.gpu is None)
            ns.alloc = dict(alloc)
            ns.gpu = GpuState(count=gpu_count, per_device_mem=per_dev)
        else:
            ns.alloc = alloc
        ns.storage = stor.parse_node_storage(node)
        self.nodes.append(ns)
        self.node_index[ns.name] = ns.index

    def place_existing_pod(self, pod: dict):
        """Admit a pod that already has spec.nodeName (no scheduling).

        GPU accounting mirrors the reference cache build from running
        pods (open-gpu-share cache.AddOrUpdatePod): a pod carrying a
        gpu-index annotation charges those devices; one without an index
        gets devices allocated as AllocateGpuId would.
        """
        name = (pod.get("spec") or {}).get("nodeName")
        if name not in self.node_index:
            return
        ns = self.nodes[self.node_index[name]]
        gpu_mem, gpu_cnt = stor.pod_gpu_request(pod)
        if gpu_mem > 0 and ns.gpu is not None:
            anno = (pod.get("metadata") or {}).get("annotations") or {}
            idx = anno.get(stor.GPU_INDEX_ANNO)
            if idx:
                devs = [int(d) for d in str(idx).split("-") if str(d).isdigit()]
            else:
                devs = ns.gpu.allocate_gpu_ids(gpu_mem, gpu_cnt or 1)
                if devs:
                    # stamp the allocation so eviction (remove_pod_from_node)
                    # can release exactly these devices
                    pod.setdefault("metadata", {}).setdefault("annotations", {})[
                        stor.GPU_INDEX_ANNO
                    ] = "-".join(str(d) for d in devs)
            if devs:
                ns.gpu.commit(devs, gpu_mem)
                ns.alloc[stor.GPU_COUNT_ANNO] = Fraction(ns.gpu.allocatable_count())
                self.alloc_epoch += 1
        # stateful custom plugins hear about the pre-bound pod through
        # reserve with the veto ignored (the tracker adds it regardless
        # — same as the reference cache's informer ADD event); this
        # keeps their caches balanced with the unreserve on eviction
        for plugin in self.registry.plugins:
            plugin.reserve(pod, ns.node)
        self._commit(pod, ns)

    # -- the scheduling cycle ----------------------------------------------

    def schedule_pod(self, pod: dict) -> Tuple[Optional[str], str]:
        """One scheduleOne cycle. Returns (node_name, reason)."""
        from .extender import ExtenderError

        meta = pod.get("metadata") or {}
        if not self.saw_priority:
            from .preemption import pod_uses_priority

            if pod_uses_priority(pod, self._prio_resolver):
                self.saw_priority = True
        try:
            feasible, reasons, codes = self._find_feasible(pod)
        except ExtenderError as e:
            # a non-ignorable extender failure fails this pod's cycle
            # (scheduleOne error path), not the whole simulation
            return None, (
                f"failed to schedule pod ({meta.get('namespace', 'default')}/"
                f"{meta.get('name', '')}): {e}"
            )
        if not feasible:
            placed = self._post_filter_preempt(pod, codes)
            if placed is not None:
                return placed, ""
            return None, self._failure_message(pod, reasons)
        try:
            # the binder extender runs before any local mutation, so a
            # failure here leaves no partial commit
            best, rejecter = self._select_and_bind(pod, feasible)
        except ExtenderError as e:
            return None, (
                f"failed to bind pod ({meta.get('namespace', 'default')}/"
                f"{meta.get('name', '')}): {e}"
            )
        if rejecter is not None:
            # a plugin veto (permit/reserve/prebind) fails the cycle
            # outright (scheduler.go:536-553) — no retry on other nodes
            return None, (
                f"failed to schedule pod ({meta.get('namespace', 'default')}/"
                f"{meta.get('name', '')}): rejected by {rejecter}"
            )
        return best.name, ""

    def _select_and_bind(self, pod: dict, feasible: List[NodeState]):
        """prioritizeNodes + selectHost (first-max tie rule, see module
        docstring) + the Reserve/Permit/PreBind/Bind/PostBind sequence
        of scheduleOne (scheduler.go:457-620, custom-plugin hooks per
        interface.go:412-524). Returns (node, None) on success or
        (None, 'phase plugin "name"') on a plugin veto; any veto after
        Reserve unreserves in reverse order first. May raise
        ExtenderError from a binder extender."""
        from .extender import ExtenderError

        scores = self._prioritize(pod, feasible)
        best = feasible[0]
        best_score = scores[0]
        if self.select_host == "sample":
            # selectHost (generic_scheduler.go:186-209): keep a count of
            # max-score nodes seen; replace the candidate with
            # probability 1/count — one Intn per tie, same consumption
            # order as the reference
            cnt = 1
            for ns, sc in zip(feasible[1:], scores[1:]):
                if sc > best_score:
                    best, best_score = ns, sc
                    cnt = 1
                elif sc == best_score:
                    cnt += 1
                    if self._rng.intn(cnt) == 0:
                        best = ns
        else:
            for ns, sc in zip(feasible[1:], scores[1:]):
                if sc > best_score:
                    best, best_score = ns, sc
        if EXPLAIN.enabled and EXPLAIN.should_record(pod):
            # the exact weighted score vector selectHost just consumed
            EXPLAIN.record_scores(
                pod,
                [(ns.name, sc) for ns, sc in zip(feasible, scores)],
                best.name,
            )
        # custom Reserve plugins claim state first; any later veto rolls
        # them back in reverse order (framework.go RunReservePlugins*)
        reserved = []

        def unreserve_all():
            for p in reversed(reserved):
                p.unreserve(pod, best.node)

        for plugin in self.registry.plugins:
            if not plugin.reserve(pod, best.node):
                unreserve_all()
                return None, f'reserve plugin "{plugin.name}"'
            reserved.append(plugin)
        for plugin in self.registry.plugins:
            if not plugin.permit(pod, best.node):
                unreserve_all()
                return None, f'permit plugin "{plugin.name}"'
        for plugin in self.registry.plugins:
            if not plugin.prebind(pod, best.node):
                unreserve_all()
                return None, f'prebind plugin "{plugin.name}"'
        # custom Bind plugins (interface.go:499-524): first non-skip
        # verdict handles the bind; the simulator still records the
        # placement locally below (like binder extenders,
        # _reserve_and_bind) so the run keeps tracking it
        for plugin in self.registry.bind_plugins:
            verdict = plugin.bind(pod, best.node)
            if verdict == "success":
                break
            if verdict != "skip":
                unreserve_all()
                return None, f'bind plugin "{plugin.name}"'
        try:
            self._reserve_and_bind(pod, best)
        except ExtenderError:
            # a binder-extender failure aborts the bind after Reserve —
            # the framework runs Unreserve then (scheduler.go:597-608);
            # the caller (schedule_pod) attaches the extender's message
            # to the pod's unschedulable event ("failed to bind pod").
            # Anything else is an internal bug and stays loud: no
            # unreserve, the whole simulation dies with the traceback
            unreserve_all()
            raise
        for plugin in self.registry.plugins:
            plugin.postbind(pod, best.node)
        return best, None

    def _post_filter_preempt(self, pod: dict, codes: Dict[int, str]) -> Optional[str]:
        """DefaultPreemption PostFilter (registered by
        algorithmprovider/registry.go:106-109; logic in
        scheduler/preemption.py). On success the victims are evicted
        from their node, recorded in self.preempted (the Simulator
        re-enqueues them), and the preemptor is scheduled in a fresh
        retry cycle — the reference requeues the nominated pod and
        reruns scheduleOne (scheduler.go:320-369); with the victims
        gone the retry binds.
        """
        # out-of-tree PostFilter plugins run first, in registration
        # order; the first returning a node wins and the built-in
        # DefaultPreemption is skipped for this pod (the framework runs
        # PostFilter plugins until the first Success status). They run
        # even with preemption disabled — that switch disables the
        # DefaultPreemption plugin, not the PostFilter stage
        for plugin in self.registry.post_filter_plugins:
            nominated = plugin.post_filter(pod, PostFilterContext(self, pod))
            if nominated is not None:
                return self._retry_cycle(pod)
        if not self.enable_preemption:
            return None
        prio = self.pod_priority(pod)
        # a victim must have strictly lower priority than the preemptor;
        # when nothing committed is lower, skip the whole dry run
        if not (prio > self._min_prio):
            return None
        from .extender import ExtenderError
        from .preemption import run_preemption

        try:
            result = run_preemption(self, pod, codes)
        except ExtenderError:
            # non-ignorable preempt-verb extender failure: the PostFilter
            # returns an error status and the pod stays unschedulable
            # (CallExtenders error path, default_preemption.go:146-149)
            return None
        if result is None:
            return None
        preemptor = (pod.get("metadata") or {}).get("name", "")
        ns = self.nodes[result.node_index]
        for victim in result.victims:
            self.evict_pod(ns, victim)
            self.preempted.append(
                PreemptedPod(pod=victim, node_name=ns.name, preemptor=preemptor)
            )
        if EXPLAIN.enabled and EXPLAIN.should_record(pod):
            # namespace-qualified victims: the JSON payload's structured
            # `preemption` block (explain.as_dict) is citable by the
            # shadow auditor's ordering-divergence class
            EXPLAIN.annotate(
                pod,
                preemption_node=ns.name,
                preempted=[
                    "%s/%s"
                    % (
                        (v.get("metadata") or {}).get("namespace") or "default",
                        (v.get("metadata") or {}).get("name", ""),
                    )
                    for v in result.victims
                ],
            )
        # retry cycle: with victims evicted the pod fits on the
        # nominated node (it may score another feasible node higher —
        # same as the reference's fresh scheduleOne after requeue).
        # Victims stay evicted even if the retry fails (the reference
        # likewise never restores PrepareCandidate's deletions); an
        # extender error here fails this pod's cycle, not the run.
        return self._retry_cycle(pod)

    def _retry_cycle(self, pod: dict):
        """Fresh filter+score+bind cycle after a PostFilter mutated the
        cluster (built-in preemption or a custom post_filter plugin).
        The nominated node is not forced: the fresh cycle may pick any
        feasible node, like the reference's re-queued scheduleOne."""
        from .extender import ExtenderError

        try:
            feasible, _, _ = self._find_feasible(pod)
            if not feasible:
                return None
            best, rejecter = self._select_and_bind(pod, feasible)
        except ExtenderError:
            return None
        if rejecter is not None:
            return None
        return best.name

    # -- filters ------------------------------------------------------------

    # Per-node failure codes mirror framework.Status codes: a node
    # rejected "unresolvable" (UnschedulableAndUnresolvable) cannot be
    # helped by preemption (nodesWherePreemptionMightHelp,
    # default_preemption.go:259-271). Sources: nodeunschedulable/
    # nodename/nodeaffinity/tainttoleration filters, PodTopologySpread
    # missing-topology-key (filtering.go:298), InterPodAffinity required
    # affinity rules (filtering.go:389).

    def _pod_filter_ctx(self, pod: dict) -> dict:
        """Pod-level filter inputs that do not depend on cluster state."""
        gpu_mem, gpu_cnt = stor.pod_gpu_request(pod)
        lvm_vols, dev_vols = stor.parse_pod_local_volumes(pod)
        return {
            "spec": pod.get("spec") or {},
            "pod_req": req.pod_requests(pod),
            "want_ports": _pod_host_ports(pod),
            "lvm_vols": lvm_vols,
            "dev_vols": dev_vols,
            "gpu_mem": gpu_mem,
            "gpu_cnt": gpu_cnt,
            "gpu_mem_total": stor.pod_gpu_memory(pod),
        }

    def _prefilter(self, pod: dict) -> dict:
        """Cluster-state-dependent PreFilter states (recomputed after
        any mutation — the preemption dry run relies on this instead of
        the reference's incremental AddPod/RemovePod extensions)."""
        return {
            "topo": self._topology_spread_prefilter(pod),
            "ipa": self._interpod_prefilter(pod),
        }

    def _check_node(self, pod: dict, ctx: dict, pre: dict, ns: NodeState):
        """All framework filters against one node. Returns None when the
        node is feasible, else (reason, code)."""
        spec = ctx["spec"]
        node = ns.node
        nspec = node.get("spec") or {}
        # NodeUnschedulable
        if nspec.get("unschedulable") and not lbl.tolerations_tolerate_taint(
            spec.get("tolerations") or [],
            {"key": "node.kubernetes.io/unschedulable", "effect": "NoSchedule"},
        ):
            return "node(s) were unschedulable", "unresolvable"
        # NodeName
        if spec.get("nodeName") and spec["nodeName"] != ns.name:
            return "node(s) didn't match the requested hostname", "unresolvable"
        # TaintToleration
        taint = lbl.find_untolerated_taint(
            nspec.get("taints") or [], spec.get("tolerations") or []
        )
        if taint is not None:
            return (
                "node(s) had taint {%s: %s}, that the pod didn't tolerate"
                % (taint.get("key", ""), taint.get("value", "")),
                "unresolvable",
            )
        # NodeAffinity
        if not lbl.pod_matches_node_selector_and_affinity(spec, node):
            return "node(s) didn't match node selector", "unresolvable"
        # NodePorts
        if _ports_conflict(ctx["want_ports"], ns.used_ports):
            return (
                "node(s) didn't have free ports for the requested pod ports",
                "unschedulable",
            )
        # NodeResourcesFit
        r = self._fits_resources(ctx["pod_req"], ns)
        if r:
            return r, "unschedulable"
        # PodTopologySpread
        r = self._topology_spread_filter(pod, pre["topo"], ns)
        if r:
            return "node(s) didn't match pod topology spread constraints", r
        # InterPodAffinity
        r = self._interpod_filter(pod, pre["ipa"], ns)
        if r:
            code = (
                "unresolvable"
                if r == "node(s) didn't match pod affinity rules"
                else "unschedulable"
            )
            return r, code
        # Open-Local
        r = self._open_local_filter(ctx["lvm_vols"], ctx["dev_vols"], ns)
        if r:
            return r, "unschedulable"
        # Open-Gpu-Share
        if ctx["gpu_mem_total"] > 0:
            if ns.gpu is None or ns.gpu.count * ns.gpu.per_device_mem < ctx["gpu_mem_total"]:
                return "Insufficient GPU memory", "unschedulable"
            if ns.gpu.allocate_gpu_ids(ctx["gpu_mem"], ctx["gpu_cnt"]) is None:
                return "No GPU device can fit the pod", "unschedulable"
        # out-of-tree custom plugins (stateless filter contract)
        for plugin in self.registry.plugins:
            if not plugin.filter(pod, ns.node):
                return f"node(s) didn't pass plugin {plugin.name}", "unschedulable"
        return None

    def _find_feasible(self, pod: dict):
        ctx = self._pod_filter_ctx(pod)
        pre = self._prefilter(pod)

        feasible = []
        reasons: Dict[str, int] = {}
        codes: Dict[int, str] = {}
        # flight-recorder hook (--explain): keep every node's verdict,
        # not just the aggregate counts — one attribute read when off
        explain = EXPLAIN.enabled and EXPLAIN.should_record(pod)
        verdicts = [] if explain else None

        def fail(reason: str):
            reasons[reason] = reasons.get(reason, 0) + 1

        for ns in self.nodes:
            r = self._check_node(pod, ctx, pre, ns)
            if r is None:
                feasible.append(ns)
                if explain:
                    verdicts.append((ns.name, None, "feasible"))
                continue
            reason, code = r
            fail(reason)
            codes[ns.index] = code
            if explain:
                verdicts.append((ns.name, reason, code))
        if self.extenders:
            from .extender import filter_with_extenders

            before = {ns.index for ns in feasible}
            on_node_fail = None
            if explain:
                # the verdict row gets the extender's ACTUAL per-node
                # message — the same string `fail` just aggregated —
                # so the explain block's failure message stays equal
                # to the report's (verdict rows parallel self.nodes)
                def on_node_fail(name, msg):
                    idx = self.node_index.get(name)
                    if idx is not None:
                        verdicts[idx] = (name, msg, "unschedulable")

            feasible = filter_with_extenders(
                self.extenders, pod, feasible, fail, on_node_fail=on_node_fail
            )
            for idx in before - {ns.index for ns in feasible}:
                codes[idx] = "unschedulable"
                if explain and verdicts[idx][1] is None:
                    # dropped without a message: keep reason None so
                    # the aggregate counts still mirror `fail` exactly;
                    # the status code alone records the drop
                    verdicts[idx] = (verdicts[idx][0], None, "unschedulable")
        if explain:
            EXPLAIN.record_filter(pod, verdicts, len(feasible))
        return feasible, reasons, codes

    def passes_filters_on_node(self, pod: dict, ns: NodeState, ctx=None) -> bool:
        """PodPassesFiltersOnNode for the preemption dry run: framework
        filters only (extenders join preemption via ProcessPreemption —
        preemption.run_preemption calls them over the finished candidate
        map, not per dry-run node), with PreFilter state recomputed against current
        cluster state. `ctx` (state-independent, from _pod_filter_ctx)
        may be precomputed by the caller and reused across calls."""
        if ctx is None:
            ctx = self._pod_filter_ctx(pod)
        pre = self._prefilter(pod)
        return self._check_node(pod, ctx, pre, ns) is None

    def _fits_resources(self, pod_req: dict, ns: NodeState) -> Optional[str]:
        """fitsRequest (noderesources/fit.go:230-303)."""
        allowed_pods = ns.alloc_int(req.PODS)
        if len(ns.pods) + 1 > allowed_pods:
            return "Too many pods"
        mcpu = pod_req.get(req.CPU, Fraction(0)) * 1000
        mcpu = -((-mcpu.numerator) // mcpu.denominator)
        mem = pod_req.get(req.MEMORY, Fraction(0))
        mem = -((-mem.numerator) // mem.denominator)
        eph = pod_req.get(req.EPHEMERAL, Fraction(0))
        eph = -((-eph.numerator) // eph.denominator)
        scalars = {
            name: v
            for name, v in pod_req.items()
            if name not in (req.CPU, req.MEMORY, req.EPHEMERAL, req.PODS)
            and req.is_scalar_resource(name)
        }
        if mcpu == 0 and mem == 0 and eph == 0 and not scalars:
            return None
        if ns.alloc_milli_cpu() < mcpu + ns.req_mcpu:
            return "Insufficient cpu"
        if ns.alloc_int(req.MEMORY) < mem + ns.req_mem:
            return "Insufficient memory"
        if ns.alloc_int(req.EPHEMERAL) < eph + ns.req_eph:
            return "Insufficient ephemeral-storage"
        for name, v in scalars.items():
            iv = -((-v.numerator) // v.denominator)
            if ns.alloc_int(name) < iv + ns.req_scalar.get(name, 0):
                return f"Insufficient {name}"
        return None

    # -- topology spread ----------------------------------------------------

    def _hard_spread_constraints(self, pod: dict) -> list:
        out = []
        for c in (pod.get("spec") or {}).get("topologySpreadConstraints") or []:
            if c.get("whenUnsatisfiable", "DoNotSchedule") == "DoNotSchedule":
                out.append(c)
        return out

    def _soft_spread_constraints(self, pod: dict) -> list:
        return [
            c
            for c in (pod.get("spec") or {}).get("topologySpreadConstraints") or []
            if c.get("whenUnsatisfiable") == "ScheduleAnyway"
        ]

    def _count_matching_pods(self, ns: NodeState, selector, namespace: str) -> int:
        """countPodsMatchSelector: same namespace, selector match, not
        terminating (we have no deletion timestamps)."""
        n = 0
        for p in ns.pods:
            pm = p.get("metadata") or {}
            if (pm.get("namespace") or "default") != namespace:
                continue
            if lbl.match_labels_selector(selector, pm.get("labels") or {}):
                n += 1
        return n

    def _topology_spread_prefilter(self, pod: dict):
        """calPreFilterState (podtopologyspread/filtering.go:197-275)."""
        constraints = self._hard_spread_constraints(pod)
        if not constraints:
            return None
        namespace = (pod.get("metadata") or {}).get("namespace") or "default"
        spec = pod.get("spec") or {}
        # candidate topology domains: nodes passing nodeSelector/affinity
        # and having every constraint topology key
        counts: List[Dict[str, int]] = [dict() for _ in constraints]
        for ns in self.nodes:
            node = ns.node
            if not lbl.pod_matches_node_selector_and_affinity(spec, node):
                continue
            nl = ns.labels
            if not all(c.get("topologyKey", "") in nl for c in constraints):
                continue
            for i, c in enumerate(constraints):
                counts[i].setdefault(nl[c["topologyKey"]], 0)
        for ns in self.nodes:
            nl = ns.labels
            for i, c in enumerate(constraints):
                key = c.get("topologyKey", "")
                if key not in nl or nl[key] not in counts[i]:
                    continue
                counts[i][nl[key]] += self._count_matching_pods(
                    ns, c.get("labelSelector"), namespace
                )
        min_counts = [min(v.values()) if v else 0 for v in counts]
        return constraints, counts, min_counts

    def _topology_spread_filter(self, pod: dict, state, ns: NodeState) -> Optional[str]:
        """Returns None (feasible) or the failure code: a missing
        topology key is UnschedulableAndUnresolvable (filtering.go:298),
        a skew violation plain Unschedulable (filtering.go:330)."""
        if state is None:
            return None
        constraints, counts, min_counts = state
        meta = pod.get("metadata") or {}
        pod_labels = meta.get("labels") or {}
        nl = ns.labels
        for i, c in enumerate(constraints):
            key = c.get("topologyKey", "")
            if key not in nl:
                return "unresolvable"
            self_match = 1 if lbl.match_labels_selector(c.get("labelSelector"), pod_labels) else 0
            match_num = counts[i].get(nl[key], 0)
            skew = match_num + self_match - min_counts[i]
            if skew > int(c.get("maxSkew", 1)):
                return "unschedulable"
        return None

    # -- interpod affinity --------------------------------------------------

    def _interpod_prefilter(self, pod: dict):
        """PreFilter (interpodaffinity/filtering.go:241-275): three
        topology-pair count maps."""
        req_aff = lbl.resolve_affinity_terms(
            pod, "podAffinity", "requiredDuringSchedulingIgnoredDuringExecution"
        )
        req_anti = lbl.resolve_affinity_terms(
            pod, "podAntiAffinity", "requiredDuringSchedulingIgnoredDuringExecution"
        )
        # existing pods' required anti-affinity vs the incoming pod
        existing_anti: Dict[Tuple[str, str], int] = {}
        for ns in self.nodes:
            nl = ns.labels
            for p in ns.pods:
                for term in lbl.resolve_affinity_terms(
                    p, "podAntiAffinity", "requiredDuringSchedulingIgnoredDuringExecution"
                ):
                    if term.matches_pod(pod) and term.topology_key in nl:
                        pair = (term.topology_key, nl[term.topology_key])
                        existing_anti[pair] = existing_anti.get(pair, 0) + 1
        # incoming pod's terms vs existing pods
        aff_counts: Dict[Tuple[str, str], int] = {}
        anti_counts: Dict[Tuple[str, str], int] = {}
        for ns in self.nodes:
            nl = ns.labels
            for p in ns.pods:
                # affinity: pod must match ALL terms to count
                if req_aff and all(t.matches_pod(p) for t in req_aff):
                    for t in req_aff:
                        if t.topology_key in nl:
                            pair = (t.topology_key, nl[t.topology_key])
                            aff_counts[pair] = aff_counts.get(pair, 0) + 1
                for t in req_anti:
                    if t.matches_pod(p) and t.topology_key in nl:
                        pair = (t.topology_key, nl[t.topology_key])
                        anti_counts[pair] = anti_counts.get(pair, 0) + 1
        return req_aff, req_anti, existing_anti, aff_counts, anti_counts

    def _interpod_filter(self, pod: dict, state, ns: NodeState) -> Optional[str]:
        req_aff, req_anti, existing_anti, aff_counts, anti_counts = state
        nl = ns.labels
        # satisfyPodAffinity
        if req_aff:
            pods_exist = True
            for t in req_aff:
                if t.topology_key not in nl:
                    return "node(s) didn't match pod affinity rules"
                if aff_counts.get((t.topology_key, nl[t.topology_key]), 0) <= 0:
                    pods_exist = False
            if not pods_exist:
                # bootstrap: no matching pod anywhere and the pod matches
                # its own affinity terms
                if not (not aff_counts and all(t.matches_pod(pod) for t in req_aff)):
                    return "node(s) didn't match pod affinity rules"
        # satisfyPodAntiAffinity
        for t in req_anti:
            if t.topology_key in nl and anti_counts.get((t.topology_key, nl[t.topology_key]), 0) > 0:
                return "node(s) didn't match pod anti-affinity rules"
        # satisfyExistingPodsAntiAffinity
        if existing_anti:
            for k, v in nl.items():
                if existing_anti.get((k, v), 0) > 0:
                    return "node(s) didn't satisfy existing pods anti-affinity rules"
        return None

    # -- open-local ---------------------------------------------------------

    def _lvm_fit(self, lvm_vols, storage: stor.NodeStorage) -> Optional[list]:
        """ProcessLVMPVCPredicate/Priority with the Binpack strategy:
        tightest VG first. Returns allocation [(vg_index, size)] or None.

        Our pod volumes never carry an explicit VG (the reference's
        simon/pod-local-storage volumes don't either), so only the
        without-VG path matters.
        """
        free = [vg.capacity - vg.requested for vg in storage.vgs]
        if not storage.vgs:
            return None
        out = []
        for vol in lvm_vols:
            order = sorted(range(len(free)), key=lambda i: free[i])
            placed = False
            for i in order:
                if free[i] >= vol.size:
                    free[i] -= vol.size
                    out.append((i, vol.size))
                    placed = True
                    break
            if not placed:
                return None
        return out

    def _device_fit(self, dev_vols, storage: stor.NodeStorage) -> Optional[list]:
        """ProcessDevicePVC: SSD then HDD; volumes ascending by size
        against free devices ascending by capacity. Returns [(device
        index in storage.devices, size)] or None."""
        out = []
        for media in ("ssd", "hdd"):
            vols = sorted(
                [v for v in dev_vols if v.kind.lower() == media], key=lambda v: v.size
            )
            if not vols:
                continue
            devs = [
                (i, d)
                for i, d in enumerate(storage.devices)
                if not d.is_allocated and d.media_type == media
            ]
            if len(devs) < len(vols):
                return None
            devs.sort(key=lambda t: t[1].capacity)
            vi = 0
            for j, (idx, d) in enumerate(devs):
                if vi >= len(vols):
                    break
                if d.capacity < vols[vi].size:
                    if j == len(devs) - 1:
                        return None
                    continue
                out.append((idx, vols[vi].size))
                vi += 1
            if vi < len(vols):
                return None
        return out

    def _open_local_filter(self, lvm_vols, dev_vols, ns: NodeState) -> Optional[str]:
        if not lvm_vols and not dev_vols:
            return None
        if ns.storage is None:
            return "no local storage on node"
        if lvm_vols and self._lvm_fit(lvm_vols, ns.storage) is None:
            return "not enough LVM storage"
        if dev_vols and self._device_fit(dev_vols, ns.storage) is None:
            return "not enough device storage"
        return None

    # -- scoring ------------------------------------------------------------

    def _prioritize(self, pod: dict, feasible: List[NodeState]) -> List[int]:
        """prioritizeNodes: per-plugin score + normalize + weighted sum
        (generic_scheduler.go:470-566)."""
        total = [0] * len(feasible)

        def add(scores: List[int], weight: int):
            for i, s in enumerate(scores):
                total[i] += s * weight

        w = self.score_weights
        if w.balanced:
            add(self._score_balanced_allocation(pod, feasible), w.balanced)
        if w.image:
            add(self._score_image_locality(pod, feasible), w.image)
        if w.ipa:
            add(self._score_interpod_affinity(pod, feasible), w.ipa)
        if w.least:
            add(self._score_least_allocated(pod, feasible), w.least)
        if w.nodeaff:
            add(self._score_node_affinity(pod, feasible), w.nodeaff)
        if w.avoid:
            add(self._score_prefer_avoid_pods(pod, feasible), w.avoid)
        if w.spread:
            add(self._score_topology_spread(pod, feasible), w.spread)
        if w.tainttol:
            add(self._score_taint_toleration(pod, feasible), w.tainttol)
        if w.simon:
            add(self._score_simon(pod, feasible), w.simon)
        if w.openlocal:
            add(self._score_open_local(pod, feasible), w.openlocal)
        if w.gpushare:
            add(self._score_gpu_share(pod, feasible), w.gpushare)
        for plugin in self.registry.plugins:
            raw = [int(plugin.score(pod, ns.node)) for ns in feasible]
            if plugin.normalize == "default":
                raw = self._default_normalize(raw, reverse=False)
            elif plugin.normalize == "reverse":
                raw = self._default_normalize(raw, reverse=True)
            elif plugin.normalize == "minmax":
                raw = self._minmax_normalize(raw)
            add(raw, plugin.weight)
        if self.extenders:
            from .extender import extender_scores

            add(extender_scores(self.extenders, pod, feasible), 1)
        return total

    @staticmethod
    def _default_normalize(scores: List[int], reverse: bool) -> List[int]:
        max_count = max(scores) if scores else 0
        if max_count == 0:
            return [MAX_NODE_SCORE if reverse else 0 for _ in scores]
        out = []
        for s in scores:
            v = MAX_NODE_SCORE * s // max_count
            out.append(MAX_NODE_SCORE - v if reverse else v)
        return out

    @staticmethod
    def _minmax_normalize(scores: List[int]) -> List[int]:
        """Simon/Open-Local/Open-Gpu-Share NormalizeScore
        (simon.go:75-100): min-max rescale, all-equal -> MinNodeScore."""
        if not scores:
            return scores
        hi, lo = max(scores), min(scores)
        old_range = hi - lo
        if old_range == 0:
            return [MIN_NODE_SCORE for _ in scores]
        return [
            (s - lo) * (MAX_NODE_SCORE - MIN_NODE_SCORE) // old_range + MIN_NODE_SCORE
            for s in scores
        ]

    def _score_balanced_allocation(self, pod: dict, feasible) -> List[int]:
        cpu_req = req.pod_nonzero_request(pod, req.CPU)
        mem_req = req.pod_nonzero_request(pod, req.MEMORY)
        out = []
        for ns in feasible:
            cpu_alloc = ns.alloc_milli_cpu()
            mem_alloc = ns.alloc_int(req.MEMORY)
            cpu_frac = (ns.nz_mcpu + cpu_req) / cpu_alloc if cpu_alloc else 1.0
            mem_frac = (ns.nz_mem + mem_req) / mem_alloc if mem_alloc else 1.0
            if cpu_frac >= 1 or mem_frac >= 1:
                out.append(0)
                continue
            out.append(int((1 - abs(cpu_frac - mem_frac)) * MAX_NODE_SCORE))
        return out

    def _score_least_allocated(self, pod: dict, feasible) -> List[int]:
        cpu_req = req.pod_nonzero_request(pod, req.CPU)
        mem_req = req.pod_nonzero_request(pod, req.MEMORY)
        out = []
        for ns in feasible:
            cpu_alloc = ns.alloc_milli_cpu()
            mem_alloc = ns.alloc_int(req.MEMORY)

            def least(requested, capacity):
                if capacity == 0 or requested > capacity:
                    return 0
                return (capacity - requested) * MAX_NODE_SCORE // capacity

            s = least(ns.nz_mcpu + cpu_req, cpu_alloc) + least(ns.nz_mem + mem_req, mem_alloc)
            out.append(s // 2)
        return out

    def _score_image_locality(self, pod: dict, feasible) -> List[int]:
        containers = (pod.get("spec") or {}).get("containers") or []
        if not containers:
            return [0] * len(feasible)
        total_nodes = len(self.nodes)
        wanted = set()
        for c in containers:
            name = c.get("image", "")
            if ":" not in name.rsplit("/", 1)[-1]:
                name = name + ":latest"
            wanted.add(name)
        # image -> number of nodes having it (ImageStateSummary.NumNodes),
        # computed once per cycle rather than per candidate node
        spread: Dict[str, int] = {w: 0 for w in wanted}
        for ns in self.nodes:
            seen = set()
            for img in ((ns.node.get("status") or {}).get("images")) or []:
                for n in img.get("names") or []:
                    if n in wanted and n not in seen:
                        spread[n] += 1
                        seen.add(n)
        out = []
        for ns in feasible:
            images = {}
            for img in ((ns.node.get("status") or {}).get("images")) or []:
                size = int(img.get("sizeBytes", 0))
                for name in img.get("names") or []:
                    if name in wanted:
                        images[name] = size
            s = 0
            for c in containers:
                name = c.get("image", "")
                if ":" not in name.rsplit("/", 1)[-1]:
                    name = name + ":latest"
                if name in images:
                    s += int(images[name] * (spread[name] / total_nodes))
            max_threshold = IMG_MAX_CONTAINER_THRESHOLD * len(containers)
            s = min(max(s, IMG_MIN_THRESHOLD), max_threshold)
            out.append(MAX_NODE_SCORE * (s - IMG_MIN_THRESHOLD) // (max_threshold - IMG_MIN_THRESHOLD))
        return out

    def _score_node_affinity(self, pod: dict, feasible) -> List[int]:
        raw = [lbl.preferred_node_affinity_score(pod.get("spec") or {}, ns.node) for ns in feasible]
        return self._default_normalize(raw, reverse=False)

    def _score_taint_toleration(self, pod: dict, feasible) -> List[int]:
        tolerations = (pod.get("spec") or {}).get("tolerations") or []
        raw = [
            lbl.count_intolerable_prefer_no_schedule(
                (ns.node.get("spec") or {}).get("taints") or [], tolerations
            )
            for ns in feasible
        ]
        return self._default_normalize(raw, reverse=True)

    def _score_prefer_avoid_pods(self, pod: dict, feasible) -> List[int]:
        """NodePreferAvoidPods: 0 when the node's
        scheduler.alpha.kubernetes.io/preferAvoidPods annotation matches
        the pod's RC/RS controller, else 100."""
        refs = (pod.get("metadata") or {}).get("ownerReferences") or []
        ctrl = next((r for r in refs if r.get("controller")), None)
        if ctrl is not None and ctrl.get("kind") not in ("ReplicationController", "ReplicaSet"):
            ctrl = None
        out = []
        for ns in feasible:
            if ctrl is None:
                out.append(MAX_NODE_SCORE)
                continue
            anno = (ns.node.get("metadata") or {}).get("annotations") or {}
            raw = anno.get("scheduler.alpha.kubernetes.io/preferAvoidPods")
            avoided = False
            if raw:
                import json as _json

                try:
                    avoids = _json.loads(raw)
                    for item in avoids.get("preferAvoidPods") or []:
                        pc = ((item.get("podSignature") or {}).get("podController")) or {}
                        if pc.get("kind") == ctrl.get("kind") and (
                            not pc.get("uid") or pc.get("uid") == ctrl.get("uid")
                        ):
                            avoided = True
                except (ValueError, AttributeError):
                    avoided = False
            out.append(0 if avoided else MAX_NODE_SCORE)
        return out

    def _score_topology_spread(self, pod: dict, feasible) -> List[int]:
        """PodTopologySpread PreScore/Score/NormalizeScore
        (podtopologyspread/scoring.go)."""
        constraints = self._soft_spread_constraints(pod)
        if not constraints:
            # empty state: every node normalizes to MaxNodeScore
            return [MAX_NODE_SCORE] * len(feasible)
        namespace = (pod.get("metadata") or {}).get("namespace") or "default"
        spec = pod.get("spec") or {}
        # candidate domains from FEASIBLE nodes; ignored = feasible nodes
        # missing a topology key
        ignored = set()
        pair_counts: List[Dict[str, int]] = [dict() for _ in constraints]
        topo_size = [0] * len(constraints)
        for ns in feasible:
            nl = ns.labels
            if not all(c.get("topologyKey", "") in nl for c in constraints):
                ignored.add(ns.index)
                continue
            for i, c in enumerate(constraints):
                key = c["topologyKey"]
                if key == "kubernetes.io/hostname":
                    continue
                val = nl[key]
                if val not in pair_counts[i]:
                    pair_counts[i][val] = 0
                    topo_size[i] += 1
        weights = []
        for i, c in enumerate(constraints):
            sz = topo_size[i]
            if c.get("topologyKey") == "kubernetes.io/hostname":
                sz = len(feasible) - len(ignored)
            weights.append(math.log(sz + 2))
        # count matching pods over ALL nodes that qualify
        for ns in self.nodes:
            nl = ns.labels
            if not lbl.pod_matches_node_selector_and_affinity(spec, ns.node):
                continue
            if not all(c.get("topologyKey", "") in nl for c in constraints):
                continue
            for i, c in enumerate(constraints):
                key = c["topologyKey"]
                if key == "kubernetes.io/hostname":
                    continue
                val = nl[key]
                if val in pair_counts[i]:
                    pair_counts[i][val] += self._count_matching_pods(
                        ns, c.get("labelSelector"), namespace
                    )
        raw = []
        for ns in feasible:
            if ns.index in ignored:
                raw.append(-1)  # invalidScore marker
                continue
            score = 0.0
            nl = ns.labels
            for i, c in enumerate(constraints):
                key = c.get("topologyKey", "")
                if key in nl:
                    if key == "kubernetes.io/hostname":
                        cnt = self._count_matching_pods(ns, c.get("labelSelector"), namespace)
                    else:
                        cnt = pair_counts[i].get(nl[key], 0)
                    score += cnt * weights[i] + (int(c.get("maxSkew", 1)) - 1)
            raw.append(int(score))
        # normalize
        valid = [s for s in raw if s != -1]
        if not valid:
            return [0] * len(feasible)
        min_s, max_s = min(valid), max(valid)
        out = []
        for s in raw:
            if s == -1:
                out.append(0)
            elif max_s == 0:
                out.append(MAX_NODE_SCORE)
            else:
                out.append(MAX_NODE_SCORE * (max_s + min_s - s) // max_s)
        return out

    def _score_interpod_affinity(self, pod: dict, feasible) -> List[int]:
        """InterPodAffinity PreScore/Score/NormalizeScore
        (interpodaffinity/scoring.go)."""
        pref_aff = lbl.resolve_affinity_terms(
            pod, "podAffinity", "preferredDuringSchedulingIgnoredDuringExecution"
        )
        pref_anti = lbl.resolve_affinity_terms(
            pod, "podAntiAffinity", "preferredDuringSchedulingIgnoredDuringExecution"
        )
        topo_score: Dict[Tuple[str, str], int] = {}

        def bump(term: lbl.AffinityTerm, target: dict, node_labels: dict, mult: int):
            if not node_labels:
                return
            if term.matches_pod(target) and term.topology_key in node_labels:
                pair = (term.topology_key, node_labels[term.topology_key])
                topo_score[pair] = topo_score.get(pair, 0) + term.weight * mult

        for ns in self.nodes:
            nl = ns.labels
            for existing in ns.pods:
                for t in pref_aff:
                    bump(t, existing, nl, 1)
                for t in pref_anti:
                    bump(t, existing, nl, -1)
                for t in lbl.resolve_affinity_terms(
                    existing, "podAffinity", "requiredDuringSchedulingIgnoredDuringExecution"
                ):
                    t2 = lbl.AffinityTerm(
                        t.selector, t.topology_key, t.namespaces, HARD_POD_AFFINITY_WEIGHT
                    )
                    bump(t2, pod, nl, 1)
                for t in lbl.resolve_affinity_terms(
                    existing, "podAffinity", "preferredDuringSchedulingIgnoredDuringExecution"
                ):
                    bump(t, pod, nl, 1)
                for t in lbl.resolve_affinity_terms(
                    existing, "podAntiAffinity", "preferredDuringSchedulingIgnoredDuringExecution"
                ):
                    bump(t, pod, nl, -1)
        raw = []
        for ns in feasible:
            s = 0
            for (key, val), v in topo_score.items():
                if ns.labels.get(key) == val:
                    s += v
            raw.append(s)
        if not topo_score:
            return [0] * len(feasible)
        max_c = max(max(raw), 0)
        min_c = min(min(raw), 0)
        diff = max_c - min_c
        out = []
        for s in raw:
            if diff > 0:
                out.append(int(MAX_NODE_SCORE * (s - min_c) / diff))
            else:
                out.append(0)
        return out

    def _simon_raw(self, pod: dict, ns: NodeState) -> int:
        """Simon plugin Score (plugin/simon.go:44-67): max over node
        allocatable resources of share(podReq, alloc - podReq)."""
        requests = req.pod_requests(pod)
        limits = req.pod_limits(pod)
        if not requests and not limits:
            return MAX_NODE_SCORE
        res = 0.0
        for name, alloc in ns.alloc.items():
            pr = float(requests.get(name, Fraction(0)))
            avail = float(alloc) - pr
            if avail == 0:
                share = 0.0 if pr == 0 else 1.0
            else:
                share = pr / avail
            if share > res:
                res = share
        return int((MAX_NODE_SCORE - MIN_NODE_SCORE) * res)

    def _score_simon(self, pod: dict, feasible) -> List[int]:
        raw = [self._simon_raw(pod, ns) for ns in feasible]
        return self._minmax_normalize(raw)

    def _score_gpu_share(self, pod: dict, feasible) -> List[int]:
        # identical formula to Simon (open-gpu-share.go:84-109)
        raw = [self._simon_raw(pod, ns) for ns in feasible]
        return self._minmax_normalize(raw)

    def _score_open_local(self, pod: dict, feasible) -> List[int]:
        """Open-Local Score (open-local.go:93-137): ScoreLVM (binpack:
        sum used/capacity over touched VGs / count * 10) + ScoreDevice
        (sum requested/allocated / count * 10), then min-max normalized."""
        lvm_vols, dev_vols = stor.parse_pod_local_volumes(pod)
        raw = []
        for ns in feasible:
            if not lvm_vols and not dev_vols:
                raw.append(0)
                continue
            if ns.storage is None:
                raw.append(0)
                continue
            score = 0
            if lvm_vols:
                alloc = self._lvm_fit(lvm_vols, ns.storage)
                if alloc:
                    per_vg: Dict[int, int] = {}
                    for vg_idx, size in alloc:
                        per_vg[vg_idx] = per_vg.get(vg_idx, 0) + size
                    f = 0.0
                    for vg_idx, used in per_vg.items():
                        f += used / ns.storage.vgs[vg_idx].capacity
                    score += int(f / len(per_vg) * 10)
            if dev_vols:
                alloc = self._device_fit(dev_vols, ns.storage)
                if alloc:
                    f = 0.0
                    for dev_idx, size in alloc:
                        f += size / ns.storage.devices[dev_idx].capacity
                    score += int(f / len(alloc) * 10)
            raw.append(score)
        return self._minmax_normalize(raw)

    # -- reserve + bind -----------------------------------------------------

    def _reserve_and_bind(self, pod: dict, ns: NodeState):
        meta = pod.setdefault("metadata", {})
        spec = pod.setdefault("spec", {})
        # a binder extender is delegated the bind (scheduler.go bind();
        # extender.go:385-399); local state is updated either way so the
        # simulation keeps tracking the placement
        for ext in self.extenders:
            if ext.is_binder and ext.is_interested(pod):
                ext.bind(pod, ns.name)
                break
        # Open-Gpu-Share Reserve: allocate device ids, update node
        gpu_mem, gpu_cnt = stor.pod_gpu_request(pod)
        if stor.pod_gpu_memory(pod) > 0 and ns.gpu is not None:
            devs = ns.gpu.allocate_gpu_ids(gpu_mem, gpu_cnt)
            if devs is not None:
                ns.gpu.commit(devs, gpu_mem)
                meta.setdefault("annotations", {})[stor.GPU_INDEX_ANNO] = "-".join(
                    str(d) for d in devs
                )
                ns.alloc[stor.GPU_COUNT_ANNO] = Fraction(ns.gpu.allocatable_count())
                self.alloc_epoch += 1
        # Open-Local Bind: commit VG/device allocation (recorded for
        # exact reversal by preemption eviction)
        lvm_vols, dev_vols = stor.parse_pod_local_volumes(pod)
        if ns.storage is not None and (lvm_vols or dev_vols):
            alloc = self._lvm_fit(lvm_vols, ns.storage) if lvm_vols else []
            for vg_idx, size in alloc or []:
                ns.storage.vgs[vg_idx].requested += size
            dalloc = self._device_fit(dev_vols, ns.storage) if dev_vols else []
            for dev_idx, _size in dalloc or []:
                ns.storage.devices[dev_idx].is_allocated = True
            stor.set_node_storage(ns.own_node(), ns.storage)
            ns.local_allocs[self._pod_key(pod)] = (alloc or [], dalloc or [])
        # Simon Bind
        spec["nodeName"] = ns.name
        pod.setdefault("status", {})["phase"] = "Running"
        self._commit(pod, ns)

    @staticmethod
    def _pod_key(pod: dict) -> Tuple[str, str]:
        meta = pod.get("metadata") or {}
        return (meta.get("namespace") or "default", meta.get("name", ""))

    def commit_simple(self, pod: dict, ns: NodeState, s, ports) -> None:
        """The reduction of _reserve_and_bind for a pod with no
        GPU/storage/extender side effects (see simple_commit_mask):
        Simon Bind (nodeName + phase) + NodeInfo accounting, with the
        request summary and port tuple supplied by the caller's
        per-class cache."""
        pod.setdefault("spec", {})["nodeName"] = ns.name
        pod.setdefault("status", {})["phase"] = "Running"
        self._commit_known(pod, ns, s, ports)

    def _commit(self, pod: dict, ns: NodeState):
        """NodeInfo.AddPod accounting."""
        return self._commit_known(
            pod, ns, req.pod_request_summary(pod), None
        )

    def commit_simple_bulk(
        self, pods, node_idx, cls_ids, field_tbl, ports_of_cls, scalars_of_cls,
        prios=None,
    ):
        """Vectorized `commit_simple` over a contiguous run of
        side-effect-free placements (the batched host replay of the
        tiered scan engine and the capacity replay). Exact reduction of
        per-pod `commit_simple` + `_commit_known` in the same order:

        - per-NODE resource aggregates land as one scatter-add of the
          per-class summary deltas (`field_tbl[u]` = (mcpu, mem, eph,
          floor_mcpu, floor_mem, nz_mcpu, nz_mem) int64 — the exact
          RequestSummary integers, summed in int64 so arithmetic stays
          exact), applied once per touched node;
        - `ns.pods` grows by one grouped extend per node, preserving
          batch order within each node (stable argsort) — the order
          MoreImportantPod's commit-seq proxy and the PDB walk read;
        - commit_seq numbers are assigned in batch order from one
          counter advance; `_min_prio`/`saw_priority` update from the
          batch min (prios=None means the caller proved every pod's
          effective priority is 0 — the priority-free engine route);
        - ports / scalar resources are per-pod only for classes that
          carry them (ports_of_cls / scalars_of_cls, usually empty).

        Callers must guarantee every pod is unpinned, placed, and in a
        class with no GPU/storage/extender side effects
        (`simple_commit_mask`); anything else takes the per-pod path.
        """
        import numpy as np

        k = len(pods)
        if k == 0:
            return
        node_idx = np.asarray(node_idx, dtype=np.int64)
        cls_ids = np.asarray(cls_ids, dtype=np.int64)
        nodes = self.nodes
        # per-node aggregate deltas: sum class rows per touched node
        touched, inv = np.unique(node_idx, return_inverse=True)
        sums = np.zeros((len(touched), field_tbl.shape[1]), dtype=np.int64)
        np.add.at(sums, inv, field_tbl[cls_ids])
        for t_i, n_i in enumerate(touched.tolist()):
            ns = nodes[n_i]
            s = sums[t_i]
            ns.req_mcpu += int(s[0])
            ns.req_mem += int(s[1])
            ns.req_eph += int(s[2])
            ns.req_floor_mcpu += int(s[3])
            ns.req_floor_mem += int(s[4])
            ns.nz_mcpu += int(s[5])
            ns.nz_mem += int(s[6])
        # rare per-class extras (most classes have neither)
        has_extra = np.array(
            [bool(ports_of_cls[u]) or bool(scalars_of_cls[u])
             for u in range(len(ports_of_cls))],
            dtype=bool,
        )
        any_extra = bool(has_extra[cls_ids].any())
        # bind writes + per-node pod lists, grouped by node in batch order
        order = np.argsort(node_idx, kind="stable")
        sorted_nodes = node_idx[order]
        group_bounds = np.flatnonzero(np.diff(sorted_nodes)) + 1
        cls_list = cls_ids.tolist() if any_extra else None
        for g in np.split(order, group_bounds):
            ns = nodes[int(node_idx[g[0]])]
            name = ns.name
            plist = ns.pods
            for j in g.tolist():
                pod = pods[j]
                pod.setdefault("spec", {})["nodeName"] = name
                pod.setdefault("status", {})["phase"] = "Running"
                plist.append(pod)
                if any_extra and has_extra[cls_list[j]]:
                    u = cls_list[j]
                    for port in ports_of_cls[u]:
                        ns.used_ports.add(port)
                    for sname, iv in scalars_of_cls[u]:
                        ns.req_scalar[sname] = ns.req_scalar.get(sname, 0) + iv
        # commit sequence + priority bookkeeping, batch order
        seq = self._seq_counter
        commit_seq = self.commit_seq
        for pod in pods:
            meta = pod.get("metadata") or {}
            seq += 1
            commit_seq[(meta.get("namespace") or "default",
                        meta.get("name", ""))] = seq
        self._seq_counter = seq
        if prios is None:
            if self._min_prio > 0:
                self._min_prio = 0
        else:
            mn = int(np.min(prios))
            if mn < self._min_prio:
                self._min_prio = mn
            if not self.saw_priority and bool((np.asarray(prios) != 0).any()):
                self.saw_priority = True

    def _commit_known(self, pod: dict, ns: NodeState, s, ports):
        """_commit with the pod's request summary (and optionally its
        host-port tuple) already in hand — the capacity replay passes
        per-CLASS values so the 100k-pod walk does only aggregate
        arithmetic per pod (class members share request/port content by
        class-key construction, ops/encode.py:_class_key)."""
        ns.pods.append(pod)
        ns.req_mcpu += s.mcpu
        ns.req_mem += s.mem
        ns.req_eph += s.eph
        ns.req_floor_mcpu += s.floor_mcpu
        ns.req_floor_mem += s.floor_mem
        for name, iv in s.scalars:
            ns.req_scalar[name] = ns.req_scalar.get(name, 0) + iv
        ns.nz_mcpu += s.nz_mcpu
        ns.nz_mem += s.nz_mem
        for port in _pod_host_ports(pod) if ports is None else ports:
            ns.used_ports.add(port)
        # priority bookkeeping for DefaultPreemption
        self._seq_counter += 1
        self.commit_seq[self._pod_key(pod)] = self._seq_counter
        prio = self.pod_priority(pod)
        if prio < self._min_prio:
            self._min_prio = prio
        # pod_uses_priority(pod) is exactly `effective priority != 0`
        # (preemption.py:119) — reuse the value already resolved
        if prio != 0 and not self.saw_priority:
            self.saw_priority = True

    # -- pod removal (preemption) -------------------------------------------

    def remove_pod_from_node(self, ns: NodeState, pod: dict):
        """Reverse of _commit + the Reserve/Bind side effects, used by
        the preemption dry run (selectVictimsOnNode's removePod) and by
        the real eviction. Returns an undo token for
        restore_pod_to_node — the token pins the exact GPU device ids
        and open-local allocation so a restore is bit-identical.
        """
        for i, p in enumerate(ns.pods):
            if p is pod:
                pos = i
                break
        else:
            raise ValueError("pod not on node")
        ns.pods.pop(pos)
        s = req.pod_request_summary(pod)
        ns.req_mcpu -= s.mcpu
        ns.req_mem -= s.mem
        ns.req_eph -= s.eph
        ns.req_floor_mcpu -= s.floor_mcpu
        ns.req_floor_mem -= s.floor_mem
        for name, iv in s.scalars:
            ns.req_scalar[name] = ns.req_scalar.get(name, 0) - iv
        ns.nz_mcpu -= s.nz_mcpu
        ns.nz_mem -= s.nz_mem
        for port in _pod_host_ports(pod):
            ns.used_ports.discard(port)
        # GPU devices (from the gpu-index annotation Reserve wrote)
        gpu_devs: List[int] = []
        gpu_mem, _ = stor.pod_gpu_request(pod)
        if gpu_mem > 0 and ns.gpu is not None:
            anno = (pod.get("metadata") or {}).get("annotations") or {}
            idx = anno.get(stor.GPU_INDEX_ANNO)
            if idx:
                gpu_devs = [int(d) for d in str(idx).split("-") if str(d).isdigit()]
                for d in gpu_devs:
                    ns.gpu.used[d] -= gpu_mem
                ns.alloc[stor.GPU_COUNT_ANNO] = Fraction(ns.gpu.allocatable_count())
                self.alloc_epoch += 1
        # open-local allocation
        local = ns.local_allocs.pop(self._pod_key(pod), None)
        if local is not None and ns.storage is not None:
            alloc, dalloc = local
            for vg_idx, size in alloc:
                ns.storage.vgs[vg_idx].requested -= size
            for dev_idx, _size in dalloc:
                ns.storage.devices[dev_idx].is_allocated = False
            stor.set_node_storage(ns.own_node(), ns.storage)
        return (pos, gpu_devs, gpu_mem, local)

    def restore_pod_to_node(self, ns: NodeState, pod: dict, token):
        """Exact inverse of remove_pod_from_node."""
        pos, gpu_devs, gpu_mem, local = token
        ns.pods.insert(pos, pod)
        s = req.pod_request_summary(pod)
        ns.req_mcpu += s.mcpu
        ns.req_mem += s.mem
        ns.req_eph += s.eph
        ns.req_floor_mcpu += s.floor_mcpu
        ns.req_floor_mem += s.floor_mem
        for name, iv in s.scalars:
            ns.req_scalar[name] = ns.req_scalar.get(name, 0) + iv
        ns.nz_mcpu += s.nz_mcpu
        ns.nz_mem += s.nz_mem
        for port in _pod_host_ports(pod):
            ns.used_ports.add(port)
        if gpu_devs and ns.gpu is not None:
            for d in gpu_devs:
                ns.gpu.used[d] += gpu_mem
            ns.alloc[stor.GPU_COUNT_ANNO] = Fraction(ns.gpu.allocatable_count())
            self.alloc_epoch += 1
        if local is not None and ns.storage is not None:
            alloc, dalloc = local
            for vg_idx, size in alloc:
                ns.storage.vgs[vg_idx].requested += size
            for dev_idx, _size in dalloc:
                ns.storage.devices[dev_idx].is_allocated = True
            stor.set_node_storage(ns.own_node(), ns.storage)
            ns.local_allocs[self._pod_key(pod)] = (alloc, dalloc)

    def evict_pod(self, ns: NodeState, pod: dict):
        """Evict a victim for real (PrepareCandidate's DeletePod): the
        binding state written into the pod dict is stripped so the
        Simulator can re-enqueue it as a fresh, schedulable pod.
        Stateful custom plugins get `unreserve` — the analogue of the
        pod-delete informer event their live cache would consume."""
        for plugin in self.registry.plugins:
            plugin.unreserve(pod, ns.node)
        self.remove_pod_from_node(ns, pod)
        (pod.get("spec") or {}).pop("nodeName", None)
        pod.pop("status", None)
        anno = (pod.get("metadata") or {}).get("annotations")
        if anno:
            anno.pop(stor.GPU_INDEX_ANNO, None)

    # -- misc ---------------------------------------------------------------

    @staticmethod
    def _failure_message(pod: dict, reasons: Dict[str, int]) -> str:
        meta = pod.get("metadata") or {}
        parts = ", ".join(f"{n} {r}" for r, n in sorted(reasons.items()))
        total = sum(reasons.values())
        return (
            f"failed to schedule pod ({meta.get('namespace', 'default')}/{meta.get('name', '')}): "
            f"Unschedulable: 0/{total} nodes are available: {parts}."
        )
