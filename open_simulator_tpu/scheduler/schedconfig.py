"""KubeSchedulerConfiguration handling (--default-scheduler-config).

The reference assembles a v1beta1 KubeSchedulerConfiguration in
GetAndSetSchedulerConfig (pkg/simulator/utils.go:212-289): defaults +
the three simulator plugins injected into the Score/Filter/Reserve/Bind
sets, DefaultBinder disabled, PercentageOfNodesToScore forced to 100.
A user-supplied config file feeds the same options machinery
(InitKubeSchedulerConfiguration, utils.go:185-203) — though in the
reference the CLI flag is dead (never forwarded to Simulate; SURVEY.md
§2.1). Here the seam is live:

- `extenders:` spawn HTTP extenders (scheduler/extender.py)
- `profiles[0].plugins.score` enable/disable + per-plugin weights
  overlay the simulator's default score set (defaults below mirror
  algorithmprovider/registry.go:118-131 plus the three injected
  plugins at weight 1)
- `percentageOfNodesToScore` is validated like v1beta1 (0-100) and
  then pinned to 100 exactly as utils.go:278 does — values other than
  100 are rejected loudly instead of silently un-pinned, because every
  engine here scores all nodes
- score and postFilter are the customizable plugin sets (postFilter
  disables turn DefaultPreemption off in both engines); any OTHER set
  carrying enable/disable entries is rejected loudly — the simulator
  owns filter/reserve/bind (utils.go:241-277 rebuilds them
  unconditionally), and silently ignoring a customization there would
  return reference-divergent placements; pluginConfig args are not
  consumed by any in-tree plugin the simulator registers

Score weights flow into both engines: the serial oracle reads the
mapping directly (oracle._prioritize) and the scan receives them as
static compile-time constants (ops/scan.py ScoreWeights) so XLA
constant-folds disabled plugins out of the step entirely.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple

import yaml


class ScoreWeights(NamedTuple):
    """Static (hashable) per-plugin score weights, in-tree + simulator
    plugins. Defaults mirror algorithmprovider/registry.go:118-131 and
    the weight-1 injected plugins (utils.go:230-240)."""

    balanced: int = 1  # NodeResourcesBalancedAllocation
    image: int = 1  # ImageLocality
    least: int = 1  # NodeResourcesLeastAllocated
    nodeaff: int = 1  # NodeAffinity
    avoid: int = 10000  # NodePreferAvoidPods
    spread: int = 2  # PodTopologySpread
    tainttol: int = 1  # TaintToleration
    ipa: int = 1  # InterPodAffinity
    simon: int = 1  # Simon
    gpushare: int = 1  # Open-Gpu-Share
    openlocal: int = 1  # Open-Local


DEFAULT_SCORE_WEIGHTS = ScoreWeights()

# KubeSchedulerConfiguration plugin name -> ScoreWeights field
PLUGIN_FIELDS: Dict[str, str] = {
    "NodeResourcesBalancedAllocation": "balanced",
    "ImageLocality": "image",
    "NodeResourcesLeastAllocated": "least",
    "NodeAffinity": "nodeaff",
    "NodePreferAvoidPods": "avoid",
    "PodTopologySpread": "spread",
    "TaintToleration": "tainttol",
    "InterPodAffinity": "ipa",
    "Simon": "simon",
    "Open-Gpu-Share": "gpushare",
    "Open-Local": "openlocal",
}


@dataclass
class SchedulerConfig:
    score_weights: ScoreWeights = DEFAULT_SCORE_WEIGHTS
    extenders: List = field(default_factory=list)
    # postFilter plugin set: disabling DefaultPreemption (or "*")
    # turns the preemption stage off in both engines
    enable_preemption: bool = True


def _apply_score_set(plugins_score: dict, base: ScoreWeights) -> ScoreWeights:
    """Upstream plugin-set merge semantics (apis/config/v1beta1 +
    runtime/framework.go pluginsNeeded): `disabled` names (or "*") are
    removed from the default set, then `enabled` entries are appended
    with their weight (absent weight -> the plugin's default). Unknown
    *enabled* plugin names and non-positive weights are rejected,
    matching kube-scheduler's startup failure on an unregistered
    enabled plugin or a weight <= 0; unknown disabled names are
    ignored, as upstream only resolves enabled plugins."""
    weights = base._asdict()
    for entry in plugins_score.get("disabled") or []:
        name = (entry or {}).get("name", "")
        if name == "*":
            weights = {k: 0 for k in weights}
        elif name in PLUGIN_FIELDS:
            weights[PLUGIN_FIELDS[name]] = 0
        # unknown names in the disabled set are ignored, like upstream
        # updatePluginList (only *enabled* plugins are resolved against
        # the registry) — a production config disabling a plugin this
        # simulator doesn't model must stay valid
    for entry in plugins_score.get("enabled") or []:
        name = (entry or {}).get("name", "")
        if name not in PLUGIN_FIELDS:
            raise ValueError(f"unknown score plugin {name!r} in enabled set")
        f = PLUGIN_FIELDS[name]
        w = entry.get("weight")
        if w is None:
            weights[f] = getattr(DEFAULT_SCORE_WEIGHTS, f)
        elif int(w) <= 0:
            raise ValueError(
                f"score plugin {name!r} weight {w} is not positive"
            )
        else:
            weights[f] = int(w)
    return ScoreWeights(**weights)


def parse_scheduler_config(doc: dict) -> SchedulerConfig:
    """Parse an already-loaded KubeSchedulerConfiguration document."""
    if not isinstance(doc, dict) or doc.get("kind") not in (
        "KubeSchedulerConfiguration",
        None,
    ):
        raise ValueError("not a KubeSchedulerConfiguration document")
    cfg = SchedulerConfig()

    pct = doc.get("percentageOfNodesToScore")
    if pct is not None:
        pct = int(pct)
        # v1beta1 validation range; the simulator then forces 100
        # (utils.go:278) — reject anything else loudly
        if pct < 0 or pct > 100:
            raise ValueError(
                f"percentageOfNodesToScore {pct} is not in the range [0, 100]"
            )
        if pct not in (0, 100):  # 0 means "use default", which is forced to 100
            raise ValueError(
                "the simulator scores 100% of nodes "
                f"(utils.go:278); percentageOfNodesToScore {pct} is not supported"
            )
    profiles = doc.get("profiles") or []
    if len(profiles) > 1:
        raise ValueError(
            f"{len(profiles)} profiles given; the simulator runs a single "
            "default profile (utils.go:226)"
        )
    if profiles:
        profile = profiles[0] or {}
        sched_name = profile.get("schedulerName")
        if sched_name not in (None, "default-scheduler"):
            raise ValueError(
                f"profile schedulerName {sched_name!r} is not the default "
                "scheduler; the simulator runs a single default profile "
                "(utils.go:226)"
            )
        plugins = profile.get("plugins") or {}
        if not isinstance(plugins, dict):
            raise ValueError(
                f"profile plugins must be a mapping of plugin sets, "
                f"got {type(plugins).__name__}"
            )
        # any plugin set this simulator does not model must fail LOUDLY:
        # silently ignoring a filter/reserve/bind enable or disable
        # would return placements that diverge from a reference
        # scheduler running the same config
        supported_sets = ("score", "postFilter")
        for set_name, set_cfg in plugins.items():
            if set_name in supported_sets:
                continue
            if not isinstance(set_cfg, dict):
                if set_cfg:  # a malformed non-empty set is still a customization
                    raise ValueError(
                        f"plugin set {set_name!r} must be a "
                        "{enabled, disabled} mapping"
                    )
                continue
            if set_cfg.get("enabled") or set_cfg.get("disabled"):
                raise ValueError(
                    f"plugin set {set_name!r} enable/disable is not "
                    "supported by the simulator (score and postFilter "
                    "are); remove it or expect reference-divergent "
                    "placements"
                )
        score = plugins.get("score") or {}
        cfg.score_weights = _apply_score_set(score, cfg.score_weights)
        post = plugins.get("postFilter") or {}
        for entry in post.get("disabled") or []:
            name = (entry or {}).get("name", "")
            if name in ("*", "DefaultPreemption"):
                # the default profile's only PostFilter plugin
                # (algorithmprovider/registry.go:106-109)
                cfg.enable_preemption = False
            # unknown disabled names are ignored, like upstream
        for entry in post.get("enabled") or []:
            name = (entry or {}).get("name", "")
            if name != "DefaultPreemption":
                raise ValueError(
                    f"unknown postFilter plugin {name!r} in enabled set"
                )
            cfg.enable_preemption = True

    from .extender import extenders_from_config_doc

    cfg.extenders = extenders_from_config_doc(doc)
    return cfg


def load_scheduler_config(path: str) -> SchedulerConfig:
    """Load and parse a KubeSchedulerConfiguration file. All failure
    modes (unreadable file, YAML syntax error, invalid content) raise
    ValueError/OSError carrying the path, so the CLI's uniform
    `error: ...` + exit-1 handling applies."""
    with open(path) as f:
        try:
            doc = yaml.safe_load(f) or {}
        except yaml.YAMLError as e:
            raise ValueError(f"invalid scheduler config {path}: {e}") from e
    if not isinstance(doc, dict):
        raise ValueError(f"invalid scheduler config {path}: not a mapping")
    try:
        return parse_scheduler_config(doc)
    except ValueError as e:
        raise ValueError(f"invalid scheduler config {path}: {e}") from e
