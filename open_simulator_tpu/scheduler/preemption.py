"""Priority & preemption (DefaultPreemption PostFilter + PrioritySort).

Reimplements the kube-scheduler v1.20.5 preemption cycle
(vendor/.../framework/plugins/defaultpreemption/default_preemption.go):

- pod priority (component-helpers/scheduling/corev1/helpers.go:25) plus
  an admission-emulation extension: the fake apiserver of the reference
  has no admission chain, so `priorityClassName` on a pod resolves here
  against decoded PriorityClass objects and the two builtin classes —
  exactly what the real priority admission plugin would stamp into
  `spec.priority`.
- PodEligibleToPreemptOthers (default_preemption.go:231-255): a
  `preemptionPolicy: Never` pod never preempts. The terminating-pods
  check is vacuous (no graceful deletion in the simulator).
- nodesWherePreemptionMightHelp (default_preemption.go:259-271): nodes
  rejected with UnschedulableAndUnresolvable (node selector/affinity,
  taints, nodeName, unschedulable node, missing topology key, required
  pod-affinity rules — see oracle.Code) are excluded.
- selectVictimsOnNode (default_preemption.go:578-673): remove all
  lower-priority pods; if the preemptor then fits, reprieve as many as
  possible — PDB-violating victims first, then non-violating, both in
  MoreImportantPod order (priority desc, earlier start first; start
  time is the oracle's commit sequence — simulated pods carry no
  status.startTime).
- filterPodsWithPDBViolation (default_preemption.go:736-781): budget =
  `status.disruptionsAllowed` (defaults to 0, matching the reference
  under a fake client where no disruption controller ever fills the
  status in).
- pickOneNodeForPreemption (default_preemption.go:443-561): the 6
  tie-break criteria, with the final "sort of randomly" step pinned to
  first-in-node-order (same documented determinism deviation as
  selectHost, scheduler/oracle.py).

Deviations (documented, deliberate):
- Candidate search is exhaustive and deterministic: the reference
  dry-runs a random-offset sample of ~10% of nodes
  (default_preemption.go:169-184, getOffsetAndNumCandidates) and its
  parallel candidate list is unordered; we evaluate every potential
  node. More candidates never yields a worse pick.
- The dry run reverses GPU-share device and open-local VG/device state
  too. The reference's dry-run NodeInfo clone only adjusts resource
  accounting, so its gpu/local-storage plugin caches go stale during
  preemption — a bug we do not reproduce.
- Victims are actually removable here: the Simulator re-enqueues them
  (their controller would recreate them in a real cluster), whereas
  the reference deletes them from the fake cluster and the preemptor
  is still reported failed by the serial handshake. See
  scheduler/core.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..models import labels as lbl

# Builtin PriorityClasses (pkg/apis/scheduling/types.go upstream).
BUILTIN_PRIORITY_CLASSES = {
    "system-cluster-critical": 2000000000,
    "system-node-critical": 2000001000,
}


@dataclass
class PriorityAdmission:
    """Admission emulation for the priority plugin: what the real
    apiserver's Priority admission controller would stamp into
    spec.priority / spec.preemptionPolicy from PriorityClass objects.
    Honors value, globalDefault, and per-class preemptionPolicy."""

    values: Dict[str, int] = field(default_factory=dict)
    policies: Dict[str, str] = field(default_factory=dict)
    global_default: int = 0

    def priority(self, pod: dict) -> int:
        """PodPriority (corev1/helpers.go:25) with admission defaults."""
        spec = pod.get("spec") or {}
        if spec.get("priority") is not None:
            return int(spec["priority"])
        name = spec.get("priorityClassName")
        if name and name in self.values:
            return self.values[name]
        return self.global_default

    def preemption_policy(self, pod: dict) -> str:
        spec = pod.get("spec") or {}
        if spec.get("preemptionPolicy") is not None:
            return str(spec["preemptionPolicy"])
        name = spec.get("priorityClassName")
        if name and name in self.policies:
            return self.policies[name]
        return "PreemptLowerPriority"


def build_priority_resolver(priority_classes: List[dict]) -> PriorityAdmission:
    """PriorityAdmission from decoded PriorityClass objects plus the
    builtins (builtin names are rejected by the real apiserver, so user
    classes never shadow them)."""
    adm = PriorityAdmission(values=dict(BUILTIN_PRIORITY_CLASSES))
    for pc in priority_classes or []:
        name = (pc.get("metadata") or {}).get("name")
        if not name:
            continue
        adm.values[name] = int(pc.get("value", 0))
        if pc.get("preemptionPolicy"):
            adm.policies[name] = str(pc["preemptionPolicy"])
        if pc.get("globalDefault"):
            adm.global_default = int(pc.get("value", 0))
    return adm


def pod_priority(pod: dict, resolver: Optional[PriorityAdmission] = None) -> int:
    if resolver is None:
        resolver = PriorityAdmission(values=dict(BUILTIN_PRIORITY_CLASSES))
    return resolver.priority(pod)


def pod_uses_priority(pod: dict, resolver: Optional[PriorityAdmission] = None) -> bool:
    """True when the pod's *effective* priority is non-zero — a batch
    containing such pods rides the ordered scan optimistically with a
    per-pod serial escape hatch for failures that pass the PostFilter
    preemption gates (core.py._schedule_pods_priority).

    An explicit `spec.priority: 0` (what a real apiserver stamps on
    every default pod, so every live-cluster import carries it) is NOT
    a signal: a uniform-priority-0 workload can neither preempt nor be
    reordered, and must keep the TPU fast path."""
    return pod_priority(pod, resolver) != 0


def batch_priorities(pods: List[dict], resolver: Optional[PriorityAdmission] = None):
    """Effective priorities of a whole batch as one int64 vector — the
    single per-pod resolution pass of the tiered scan engine. The
    PrioritySort key, the engine-routing check (`any non-zero?`), the
    tier partition, and the bulk-commit `_min_prio` update all read
    this array instead of re-calling `oracle.pod_priority` per pod
    (which used to run 3x per pod per batch on the dense-priority
    path)."""
    import numpy as np

    if resolver is None:
        resolver = PriorityAdmission(values=dict(BUILTIN_PRIORITY_CLASSES))
    prio = resolver.priority
    return np.fromiter((prio(p) for p in pods), dtype=np.int64, count=len(pods))


def tier_escape_mask(prios, min_prio, preempt_enabled: bool):
    """Per-pod "armed" mask for the tiered scan: True where a FAILING
    pod would pass the serial PostFilter priority gate and must escape
    to the serial preemption cycle (the per-pod preemptionPolicy gate
    is applied lazily by the caller, on failing pods only).

    `prios` is the remaining PrioritySorted suffix; `min_prio` the
    oracle's pre-round `_min_prio`. The batch partitions into
    contiguous equal-priority TIERS, and within a tier the predicate is
    a constant: the serial gate for pod i is
    `prio[i] > min(min_prio, prefix_min(prios[:i]))`, and since
    `x > min(y, x)` is `x > y`, every pod of a tier reduces to
    `tier_prio > min(min_prio, prefix_min_before_tier)`. The whole
    check is three numpy passes over tier boundaries instead of a
    Python predicate per pod.

    Returns (armed[P] bool, n_tiers)."""
    import numpy as np

    p = len(prios)
    if p == 0:
        return np.zeros(0, dtype=bool), 0
    boundaries = np.flatnonzero(np.diff(prios)) + 1
    tier_start = np.concatenate([[0], boundaries])
    tier_len = np.diff(np.concatenate([tier_start, [p]]))
    n_tiers = len(tier_start)
    if not preempt_enabled:
        return np.zeros(p, dtype=bool), n_tiers
    tier_prio = prios[tier_start]
    hi = np.iinfo(np.int64).max
    floor = int(min_prio) if min_prio < hi else hi  # _min_prio starts math.inf
    pm_before = np.concatenate(
        [[hi], np.minimum.accumulate(tier_prio)[:-1]]
    )
    armed_tier = tier_prio > np.minimum(pm_before, floor)
    return np.repeat(armed_tier, tier_len), n_tiers


@dataclass
class Candidate:
    """One preemption candidate node (default_preemption.go Candidate):
    victims ordered by MoreImportantPod (priority desc)."""

    node_index: int
    node_name: str
    victims: List[dict]
    num_pdb_violations: int


@dataclass
class PreemptionResult:
    node_name: str
    node_index: int
    victims: List[dict] = field(default_factory=list)


def filter_pods_with_pdb_violation(
    pods: List[dict], pdbs: List[dict]
) -> Tuple[List[dict], List[dict]]:
    """filterPodsWithPDBViolation (default_preemption.go:736-781).
    Stable: preserves the order of `pods` within each group."""
    allowed = [
        int(((pdb.get("status") or {}).get("disruptionsAllowed")) or 0) for pdb in pdbs
    ]
    violating, non_violating = [], []
    for pod in pods:
        meta = pod.get("metadata") or {}
        pod_labels = meta.get("labels") or {}
        pod_ns = meta.get("namespace") or "default"
        violated = False
        if pod_labels:
            for i, pdb in enumerate(pdbs):
                pdb_ns = ((pdb.get("metadata") or {}).get("namespace")) or "default"
                if pdb_ns != pod_ns:
                    continue
                selector = (pdb.get("spec") or {}).get("selector")
                # nil/empty selector matches nothing (the metav1
                # LabelSelectorAsSelector empty-selector rule there)
                if not selector or not (
                    selector.get("matchLabels") or selector.get("matchExpressions")
                ):
                    continue
                if not lbl.match_labels_selector(selector, pod_labels):
                    continue
                disrupted = ((pdb.get("status") or {}).get("disruptedPods")) or {}
                if meta.get("name") in disrupted:
                    continue
                allowed[i] -= 1
                if allowed[i] < 0:
                    violated = True
        (violating if violated else non_violating).append(pod)
    return violating, non_violating


def pick_one_node(candidates: List[Candidate], oracle) -> Optional[Candidate]:
    """pickOneNodeForPreemption (default_preemption.go:443-561)."""
    if not candidates:
        return None
    if len(candidates) == 1:
        return candidates[0]

    def start_seq(pod: dict) -> int:
        return oracle.commit_seq_of(pod)

    # 1. minimum PDB violations
    best = min(c.num_pdb_violations for c in candidates)
    pool = [c for c in candidates if c.num_pdb_violations == best]
    if len(pool) == 1:
        return pool[0]
    # 2. minimum highest-priority victim (victims sorted desc by priority)
    best = min(oracle.pod_priority(c.victims[0]) for c in pool)
    pool = [c for c in pool if oracle.pod_priority(c.victims[0]) == best]
    if len(pool) == 1:
        return pool[0]
    # 3. minimum sum of victim priorities
    best = min(sum(oracle.pod_priority(p) for p in c.victims) for c in pool)
    pool = [
        c for c in pool if sum(oracle.pod_priority(p) for p in c.victims) == best
    ]
    if len(pool) == 1:
        return pool[0]
    # 4. minimum number of victims
    best = min(len(c.victims) for c in pool)
    pool = [c for c in pool if len(c.victims) == best]
    if len(pool) == 1:
        return pool[0]
    # 5. latest earliest-start-time among each node's *highest-priority*
    #    victims (GetEarliestPodStartTime considers only pods at the max
    #    priority on the node; proxy: commit seq — higher = started later)
    def earliest_high_prio_start(c: Candidate) -> int:
        top = max(oracle.pod_priority(p) for p in c.victims)
        return min(start_seq(p) for p in c.victims if oracle.pod_priority(p) == top)

    best = max(earliest_high_prio_start(c) for c in pool)
    pool = [c for c in pool if earliest_high_prio_start(c) == best]
    # 6. first in node order (reference: "sort of randomly")
    return min(pool, key=lambda c: c.node_index)


def select_victims_on_node(oracle, pod: dict, ns, pdbs: List[dict], ctx=None):
    """selectVictimsOnNode (default_preemption.go:578-673) against live
    oracle state: victims are removed, reprieves re-added, and on exit
    the node is restored exactly (undo tokens carry the GPU device ids
    and open-local allocations of each removed pod).

    Returns (victims, num_pdb_violations) or None when preemption on
    this node cannot help.
    """
    preemptor_prio = oracle.pod_priority(pod)
    potential = [p for p in ns.pods if oracle.pod_priority(p) < preemptor_prio]
    if not potential:
        return None
    undo = {}
    removed: List[dict] = []

    def key(p):
        m = p.get("metadata") or {}
        return (m.get("namespace") or "default", m.get("name", ""))

    def remove(p):
        undo[key(p)] = oracle.remove_pod_from_node(ns, p)
        removed.append(p)

    def restore_all():
        for p in reversed(removed):
            oracle.restore_pod_to_node(ns, p, undo[key(p)])

    for p in list(potential):
        remove(p)
    try:
        if not oracle.passes_filters_on_node(pod, ns, ctx=ctx):
            return None
        # MoreImportantPod order: priority desc, earlier start first
        potential.sort(
            key=lambda p: (-oracle.pod_priority(p), oracle.commit_seq_of(p))
        )
        violating, non_violating = filter_pods_with_pdb_violation(potential, pdbs)
        victims: List[dict] = []
        num_violating = 0

        def reprieve(p) -> bool:
            oracle.restore_pod_to_node(ns, p, undo[key(p)])
            removed.remove(p)
            if oracle.passes_filters_on_node(pod, ns, ctx=ctx):
                return True
            undo[key(p)] = oracle.remove_pod_from_node(ns, p)
            removed.append(p)
            victims.append(p)
            return False

        for p in violating:
            if not reprieve(p):
                num_violating += 1
        for p in non_violating:
            reprieve(p)
        return victims, num_violating
    finally:
        restore_all()


def run_preemption(oracle, pod: dict, codes: Dict[int, str]) -> Optional[PreemptionResult]:
    """The preempt() pipeline (default_preemption.go:118-163) including
    extender ProcessPreemption (CallExtenders,
    default_preemption.go:146): preemption-capable extenders see the
    dry-run candidate map and may drop nodes or rewrite victim lists
    before pickOneNodeForPreemption. A non-ignorable extender error
    raises ExtenderError — the caller fails this preemption attempt
    (PostFilter error status), not the run.

    `codes` is the per-node-index failure code map from the failed
    scheduling cycle ("unschedulable" | "unresolvable")."""
    # PodEligibleToPreemptOthers — policy comes from spec.preemptionPolicy
    # or, absent that, the pod's PriorityClass (admission emulation)
    if oracle.pod_preemption_policy(pod) == "Never":
        return None
    pdbs = oracle.pdbs
    # the pod-level filter context is cluster-state independent; compute
    # it once for the whole dry run instead of per passes_filters call
    ctx = oracle._pod_filter_ctx(pod)
    candidates: List[Candidate] = []
    for ns in oracle.nodes:
        # nodesWherePreemptionMightHelp: filters marked the node
        # UnschedulableAndUnresolvable -> removing pods cannot help
        if codes.get(ns.index) == "unresolvable":
            continue
        got = select_victims_on_node(oracle, pod, ns, pdbs, ctx=ctx)
        if got is None:
            continue
        victims, num_violating = got
        # every victim reprieved -> the cycle's failure on this node
        # came from state the dry run does not model (an extender
        # filter); evicting nothing cannot help, and the vendored
        # pickOneNodeForPreemption would index victims[0] (a latent
        # upstream panic, default_preemption.go:475). Drop it.
        if not victims:
            continue
        candidates.append(
            Candidate(
                node_index=ns.index,
                node_name=ns.name,
                victims=victims,
                num_pdb_violations=num_violating,
            )
        )
    candidates = _call_preemption_extenders(oracle, pod, candidates)
    best = pick_one_node(candidates, oracle)
    if best is None:
        return None
    return PreemptionResult(
        node_name=best.node_name, node_index=best.node_index, victims=best.victims
    )


def _call_preemption_extenders(
    oracle, pod: dict, candidates: List[Candidate]
) -> List[Candidate]:
    """CallExtenders adaptation over oracle Candidates. Rebuilt
    candidates keep the extender's victim lists; like the reference's
    convertToNodeNameToVictims they carry 0 PDB violations. A node whose
    victim list the extender emptied is dropped — deliberate deviation:
    the vendored v1.20.5 pickOneNodeForPreemption would panic on it
    (victims.Pods[0], default_preemption.go:476; later k8s releases
    return such a node immediately as the nominee), and with no eviction
    the retry cycle cannot succeed here anyway. Raises ExtenderError on
    a non-ignorable extender failure."""
    extenders = getattr(oracle, "extenders", None) or []
    if not candidates or not any(e.supports_preemption for e in extenders):
        return candidates
    from .extender import call_extenders_preemption

    victims_map = {
        c.node_name: {
            "pods": list(c.victims),
            "numPDBViolations": c.num_pdb_violations,
        }
        for c in candidates
    }
    new_map = call_extenders_preemption(
        extenders,
        pod,
        victims_map,
        lambda name: oracle.nodes[oracle.node_index[name]].pods,
    )
    if new_map is victims_map:
        return candidates
    out: List[Candidate] = []
    for c in candidates:
        v = new_map.get(c.node_name)
        if v is None or not v.get("pods"):
            continue
        # restore the MoreImportantPod invariant pick_one_node relies on
        # (victims[0] = highest-priority victim) — the extender's
        # response order is arbitrary
        victims = sorted(
            v["pods"],
            key=lambda p: (-oracle.pod_priority(p), oracle.commit_seq_of(p)),
        )
        out.append(
            Candidate(
                node_index=c.node_index,
                node_name=c.node_name,
                victims=victims,
                num_pdb_violations=int(v.get("numPDBViolations") or 0),
            )
        )
    return out
