"""TPU engine: drives the JAX sequential-commit scan and mirrors its
placements back into the host-side Oracle state.

The Oracle stays the single source of truth for object-level state
(annotations, reports, reason strings); the scan is the compute path.
Every commit the scan makes is replayed on the host through the same
binding code the oracle uses, so oracle state after an engine batch is
identical to having scheduled serially — this is asserted by the
conformance tests (tests/test_engine_conformance.py).

Batch lifecycle (the tiered priority engine's contract): `begin_batch`
encodes a pod batch ONCE — class tensors, features, the XLA scan
static, the port vocabulary; `scan_active(mask)` then dispatches one
scan over any active subset of that batch against the oracle's CURRENT
dynamic state. A priority round that escapes re-dispatches the same
encoding with the committed prefix masked off instead of re-encoding
(and re-compiling: the shapes never change) the shrinking remainder —
an escape-heavy batch pays per round only the dynamic re-encode and
the dispatch, not the full host encode. `schedule(pods)` is the
one-shot form.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..ops.encode import (
    ClusterStatic,
    encode_batch,
    encode_cluster_cached,
    encode_dynamic,
    features_of_batch,
)
from ..runtime.errors import GuardError
from .oracle import Oracle

__all__ = ["SampleRngOverflow", "TpuEngine"]

# per-class summary integers above this magnitude lose int64 headroom
# in the bulk scatter-add; such classes (a >2^55-byte request is ~36 PB
# — malformed input, not a workload) take the per-pod commit path
_BULK_MAX_ABS = 1 << 55


class SampleRngOverflow(GuardError, RuntimeError):
    """A sample-mode Intn draw needed more rejection retries than the
    in-scan bound (ops/scan.py _RNG_KMAX; p < 1e-17 per draw). Raised
    BEFORE any commit is replayed, so the caller (core._schedule_pods)
    can rerun the batch on the serial oracle, whose rejection loop is
    unbounded."""


class TpuEngine:
    """Holds the oracle plus a per-node-set cache of the cluster
    encoding: with K apps on an N-node cluster the O(N) ClusterStatic
    build runs once, not K times (per-batch state — DynamicState, pod
    statics, port vocab — is still rebuilt per begin_batch call)."""

    def __init__(self, oracle: Oracle):
        self.oracle = oracle
        self._cluster: ClusterStatic = None
        self._cache_key = None
        # per-batch replay fast path (class ids are batch scoped):
        # classes with no GPU/storage/extender side effects commit via
        # per-class summaries instead of the general bind
        self._last_class_of = None
        self._last_simple = None
        self._class_commit_info = None
        # batch encoding reused across masked rounds (begin_batch)
        self._batch = None
        self._batch_pods: Optional[List[dict]] = None
        self._features = None
        self._scan_static = None
        self._scan_static_cluster = None
        self._bulk_tbl = None
        # sample mode: (pre-round rng history, per-pod consumed-word
        # cumsum) of the last dispatched scan — rewind_sample_rng uses
        # it when a priority-scan escape discards the scanned tail
        self._last_rng = None
        # device mesh override (None = the process-wide configured
        # mesh, parallel/mesh.py current_mesh): the layout planner
        # routes single big-cluster scans through the node-sharded
        # path and scenario batches across the scenario axis
        self.mesh = None
        self._mesh_retired = False

    def cluster_static(self) -> ClusterStatic:
        # keyed on (node count, alloc epoch): GPU-share Reserve mutates
        # ns.alloc[gpu-count], which is baked into ClusterStatic's
        # scalar allocatables — a bind in one batch must invalidate the
        # cache for the next
        key = (len(self.oracle.nodes), self.oracle.alloc_epoch)
        if self._cluster is None or self._cache_key != key:
            self._cluster = encode_cluster_cached(self.oracle)
            self._cache_key = key
        return self._cluster

    def begin_batch(self, pods: List[dict], groups=None) -> None:
        """Encode `pods` once for any number of scan_active dispatches.

        Pods with a spec.nodeName naming an unknown node must be
        filtered out by the caller (the reference leaves them dangling
        in the tracker, simulator.go:221-229). `groups` is the
        (group_of, firsts) content-group index from workload expansion
        (workloads.ExpandIndex) — class keys then resolve once per
        group instead of once per pod."""
        from ..utils.trace import phase

        oracle = self.oracle
        with phase("engine/encode"):
            cluster = self.cluster_static()
            batch = encode_batch(oracle, cluster, pods, groups=groups)
            from .oracle import ClassCommitCache, simple_commit_mask

            self._batch = batch
            self._batch_pods = pods
            self._last_class_of = np.asarray(batch.class_of_pod)
            self._last_simple = simple_commit_mask(batch, bool(oracle.extenders))
            self._class_commit_info = ClassCommitCache()
            self._bulk_tbl = None
            self._scan_static = None
            sample = getattr(oracle, "select_host", "first-max") == "sample"
            self._features = features_of_batch(
                cluster, batch,
                weights=getattr(oracle, "score_weights", None),
                sample=sample,
            )

    def scan_active(
        self, active: np.ndarray, valid: Optional[np.ndarray] = None
    ) -> np.ndarray:
        """One masked scan over the begin_batch encoding against the
        oracle's CURRENT state. Returns placements for the full batch:
        node index, -1 (active but unschedulable), or -2 (inactive —
        `ops.scan.INACTIVE`, positions masked off by `active`).

        `valid` gates candidate nodes (default: all) — the twin's
        drain-safety and N+K queries evaluate "where do these pods go
        WITHOUT nodes X" as one warm dispatch this way (the scenario
        node mask of the chaos substrate, ops.scan.run_scan_masked
        node_valid). Same shapes, so a masked query re-dispatches the
        compiled scan without recompiling."""
        import jax.numpy as jnp

        from ..ops import pallas_scan
        from ..ops import scan as scan_ops
        from ..ops.encode import to_scan_static, to_scan_state
        from ..utils.trace import GLOBAL, phase, profiled

        oracle = self.oracle
        batch = self._batch
        sample = bool(getattr(self._features, "sample", False))
        with phase("engine/encode"):
            cluster = self.cluster_static()
            node_valid = (
                np.ones(cluster.n, bool)
                if valid is None
                else np.asarray(valid, bool)
            )
            dyn = encode_dynamic(oracle, cluster)
            plan = (
                pallas_scan.build_plan(
                    cluster, batch, dyn, self._features,
                    weights=self._features.weights,
                )
                if pallas_scan.should_use()
                else None
            )
            if plan is None:
                # the scan static survives masked rounds; only a
                # ClusterStatic rebuild (GPU alloc epoch) invalidates it
                if self._scan_static is None or self._scan_static_cluster is not cluster:
                    self._scan_static = to_scan_static(cluster, batch)
                    self._scan_static_cluster = cluster
                init = to_scan_state(dyn, batch)
                if sample:
                    # the scan consumes the oracle's Go RNG stream: hand
                    # its 607-output history in via the carry, and (after
                    # the scan) write the advanced stream back so serial
                    # fallbacks continue the exact sequence
                    hist0 = oracle._rng.history()
                    init = init._replace(
                        rng_hist=jnp.asarray(
                            np.array(hist0, dtype=np.uint64)
                        )
                    )
        # node-axis mesh route: ONE scan over a cluster the layout
        # planner says belongs on the mesh (too big / predicted unfit
        # for one device) — the twin's 100k-node drain/what-if queries
        # ride this (parallel/mesh.py). Classified faults degrade to
        # the single-device path below, trace-noted.
        mesh_route = None
        if plan is None and not sample and not self._mesh_retired:
            from ..parallel import mesh as mesh_mod

            m = self.mesh if self.mesh is not None else mesh_mod.current_mesh()
            if m is not None:
                # site "scan": the single-device masked scan whose
                # compiled records say whether ONE device can hold it
                layout = mesh_mod.plan_layout(
                    "scan", mesh=m, n_scenarios=1, n_nodes=cluster.n,
                    sample=sample,
                )
                if layout.axis == "node":
                    mesh_route = m
        # never a silent fallback: name why the fused kernel was out of
        # scope or unavailable (pallas_scan.fallback_reason)
        GLOBAL.note(
            "batch-kernel",
            pallas_scan.kernel_label(plan)
            if plan is not None
            else (
                "mesh-scan" if mesh_route is not None
                else f"xla-scan ({pallas_scan.fallback_reason()})"
            ),
        )
        if plan is not None:
            # fused single-kernel fast path; bit-identical placements
            # (tests/test_pallas_scan.py)
            with profiled("engine/scan"):
                out, _final = pallas_scan.run_scan_pallas(
                    plan,
                    batch.class_of_pod,
                    np.asarray(active, bool),
                    node_valid,
                    pinned=batch.pinned_node,
                )
            return np.asarray(out)
        if mesh_route is not None:
            from ..parallel import mesh as mesh_mod

            try:
                with profiled("engine/scan"):
                    out, *_stats = mesh_mod.run_node_sharded(
                        mesh_route,
                        self._scan_static,
                        init,
                        batch.class_of_pod,
                        batch.pinned_node,
                        node_valid,
                        np.asarray(active, bool),
                        self._features,
                    )
                return np.asarray(out)
            except (RuntimeError, MemoryError, OSError) as e:
                from ..runtime.guard import try_downgrade

                if not try_downgrade(
                    e, label="engine-scan", frm="mesh-scan", to="xla-scan"
                ):
                    raise
                self._mesh_retired = True
        with profiled("engine/scan"):
            placements, final_state = scan_ops.run_scan_masked(
                self._scan_static,
                init,
                jnp.asarray(batch.class_of_pod),
                jnp.asarray(batch.pinned_node),
                jnp.asarray(node_valid),
                jnp.asarray(np.asarray(active, bool)),
                features=self._features,
            )
            if sample:
                placements, consumed = placements
            out = np.asarray(placements)  # blocks on device completion
            from ..obs import profile

            profile.record_d2h(out.nbytes)
        if sample:
            if bool(np.asarray(final_state.rng_overflow)):
                # oracle state is untouched (commits replay only after
                # this returns); core catches this and reruns serially
                raise SampleRngOverflow(
                    "sample-mode RNG rejection overflow; rerunning the "
                    "batch on the serial oracle"
                )
            self._last_rng = (hist0, np.cumsum(np.asarray(consumed)))
            oracle._rng.set_history(
                [int(x) for x in np.asarray(final_state.rng_hist)]
            )
        return out

    def schedule(self, pods: List[dict]) -> np.ndarray:
        """Returns placements[P]: node index or -1 (unschedulable)."""
        self.begin_batch(pods)
        return self.scan_active(np.ones(len(pods), bool))

    def scan_scenarios(self, actives: np.ndarray) -> np.ndarray:
        """Batch-of-requests entry point (serve/coalescer.py): ONE
        vmapped device dispatch evaluating every row of `actives`
        [Sc, P] as an independent masked scan over the begin_batch
        encoding against the oracle's CURRENT state — Sc what-if
        questions for the price of one dispatch. Scenarios share the
        batch's pin vector and see all nodes; each row's placements
        are identical to scan_active(row) run alone (scenarios never
        see each other's commits — nothing is replayed here).

        Returns placements [Sc, P]: node index, -1 (active but
        unschedulable), or -2 (masked off in that scenario)."""
        import jax.numpy as jnp

        from ..ops.encode import to_scan_static, to_scan_state
        from ..utils.trace import phase, profiled

        if bool(getattr(self._features, "sample", False)):
            # the Go-RNG stream is a single serial sequence; scenario
            # rows would race for it (core.py routes sample serially)
            raise ValueError(
                "sample-mode batches cannot ride the scenario scan"
            )
        batch = self._batch
        with phase("engine/encode"):
            cluster = self.cluster_static()
            dyn = encode_dynamic(self.oracle, cluster)
            if self._scan_static is None or self._scan_static_cluster is not cluster:
                self._scan_static = to_scan_static(cluster, batch)
                self._scan_static_cluster = cluster
            init = to_scan_state(dyn, batch)
        actives_arr = np.asarray(actives, bool)
        # scenario-axis sharding: coalesced request rows are
        # independent, so a configured mesh splits them across devices
        # ("computation follows sharding" — the jit compiles an SPMD
        # partition per observed input sharding); a classified device
        # fault degrades to the unsharded dispatch, trace-noted
        from ..parallel import mesh as mesh_mod

        m = self.mesh if self.mesh is not None else mesh_mod.current_mesh()
        mesh_route = None
        if m is not None and not self._mesh_retired:
            layout = mesh_mod.plan_layout(
                "scenario_scan", mesh=m,
                n_scenarios=int(actives_arr.shape[0]), n_nodes=cluster.n,
            )
            if layout.axis == "scenario":
                mesh_route = m
        out = None
        if mesh_route is not None:
            try:
                (actives_s,), rows = mesh_mod.shard_scenario_rows(
                    mesh_route, [actives_arr]
                )
                with profiled("engine/scan"):
                    out = _scenario_scan_jit()(
                        self._scan_static,
                        init,
                        jnp.asarray(batch.class_of_pod),
                        jnp.asarray(batch.pinned_node),
                        jnp.ones(cluster.n, bool),
                        actives_s,
                        self._features,
                    )
                out = np.asarray(out)[:rows]
            except (RuntimeError, MemoryError, OSError) as e:
                from ..runtime.guard import try_downgrade

                if not try_downgrade(
                    e, label="scenario-scan", frm="mesh-scenario",
                    to="xla-scan",
                ):
                    raise
                self._mesh_retired = True
                out = None
        if out is None:
            with profiled("engine/scan"):
                out = _scenario_scan_jit()(
                    self._scan_static,
                    init,
                    jnp.asarray(batch.class_of_pod),
                    jnp.asarray(batch.pinned_node),
                    jnp.ones(cluster.n, bool),
                    jnp.asarray(actives_arr),
                    self._features,
                )
            out = np.asarray(out)
        from ..obs import profile

        profile.record_h2d(actives_arr.nbytes)
        profile.record_d2h(out.nbytes)
        return out

    def rewind_sample_rng(self, batch_pos: int) -> None:
        """Reposition the oracle's sample-mode stream to where it stood
        BEFORE the last scanned round's pod at `batch_pos` consumed its
        draws. A priority-scan escape discards every scanned placement
        from the escape point on and reschedules those pods (serially,
        then by re-dispatching a masked scan), so their draws must be
        un-consumed — the pre-round history advanced by the
        consumed-word prefix is exactly that position
        (gorand.advance_history). Masked-off pods consume zero words,
        so the cumsum is escape-round-local by construction."""
        if self._last_rng is None:
            return
        from ..utils.gorand import advance_history

        hist0, consumed_cum = self._last_rng
        k = int(consumed_cum[batch_pos - 1]) if batch_pos > 0 else 0
        self.oracle._rng.set_history(advance_history(hist0, k))

    def commit_host(self, pod: dict, node_idx: int):
        """Replay one placement into oracle state (same binding code the
        serial path uses, incl. GPU/storage side effects)."""
        self.oracle._reserve_and_bind(pod, self.oracle.nodes[int(node_idx)])

    def commit_host_at(self, pod: dict, node_idx: int, batch_pos: int):
        """commit_host with the pod's position in the last scheduled
        batch: classes with no GPU/storage/extender side effects reduce
        _reserve_and_bind to nodeName+phase+commit, and class members
        share request/port content by class-key construction, so the
        summary/port walk runs once per class (the same fast path the
        capacity replay uses, applier.replay_scenario)."""
        cls_of = self._last_class_of
        if cls_of is not None and batch_pos < len(cls_of):
            cls = int(cls_of[batch_pos])
            if self._last_simple[cls]:
                self._class_commit_info.commit(
                    self.oracle, pod, self.oracle.nodes[int(node_idx)], cls
                )
                return
        self.commit_host(pod, node_idx)

    def bulk_tables(self):
        """(field_tbl[U,7] int64, ports_of_cls, scalars_of_cls,
        bulk_ok[U] bool) for commit_host_bulk — the per-class
        RequestSummary integers resolved once per batch (class members
        share request/port content by class-key construction)."""
        if self._bulk_tbl is None:
            self._bulk_tbl = build_bulk_tables(self._batch, self._last_simple)
        return self._bulk_tbl

    def commit_host_bulk(self, pods, node_idx, cls_ids, prios=None):
        """Bulk replay of a contiguous run of simple-class placements
        (oracle.commit_simple_bulk). Callers gate on `simple &
        bulk_ok`; anything else goes through commit_host_at."""
        field_tbl, ports_of, scalars_of, _ok = self.bulk_tables()
        self.oracle.commit_simple_bulk(
            pods, node_idx, cls_ids, field_tbl, ports_of, scalars_of,
            prios=prios,
        )


def _scan_scenarios_impl(static, init, cls, pinned, valid, actives, features):
    import jax

    from ..ops import scan as scan_ops

    def one(active):
        placements, _final = scan_ops.run_scan_masked(
            static, init, cls, pinned, valid, active, features=features
        )
        return placements

    return jax.vmap(one)(actives)


_SCENARIO_SCAN_JIT = None


def _scenario_scan_jit():
    """The jitted scenario vmap, compiled once per (shape, features)
    pair PROCESS-WIDE: static/init/masks are traced pytree arguments
    (not closures), so a long-lived daemon re-dispatching same-shaped
    request batches hits the jit cache instead of recompiling — the
    warm-compiled-scan property `simon serve` is built on. Wrapped for
    dispatch/recompile accounting (obs/profile.py): the warm-cache
    contract is now a measured number, not a comment."""
    global _SCENARIO_SCAN_JIT
    if _SCENARIO_SCAN_JIT is None:
        import jax

        from ..obs import profile

        _SCENARIO_SCAN_JIT = profile.instrument_jit(
            jax.jit(_scan_scenarios_impl, static_argnums=(6,)),
            "scenario_scan",
            static_argnums=(6,),
            lead_argnum=5,  # actives: the batched request-rows axis
        )
    return _SCENARIO_SCAN_JIT


def build_bulk_tables(batch, simple_mask):
    """Per-class commit tables from a PodBatch's class representatives
    (shared by TpuEngine.commit_host_bulk and the capacity replay,
    applier.replay_masked — the eligibility rule must stay identical in
    both). Only classes marked simple get real rows; the rest never
    reach the bulk path."""
    from ..models import requests as req
    from .oracle import _pod_host_ports

    u = batch.u
    field_tbl = np.zeros((u, 7), dtype=np.int64)
    ports_of = [()] * u
    scalars_of = [()] * u
    bulk_ok = np.zeros(u, dtype=bool)
    for u_i, pod in enumerate(batch.class_pods):
        if not simple_mask[u_i]:
            continue
        s = req.pod_request_summary(pod)
        vals = (s.mcpu, s.mem, s.eph, s.floor_mcpu, s.floor_mem,
                s.nz_mcpu, s.nz_mem)
        if any(abs(v) > _BULK_MAX_ABS for v in vals) or any(
            abs(iv) > _BULK_MAX_ABS for _n, iv in s.scalars
        ):
            continue  # int64 headroom guard: per-pod path
        field_tbl[u_i] = vals
        ports_of[u_i] = tuple(_pod_host_ports(pod))
        scalars_of[u_i] = s.scalars
        bulk_ok[u_i] = True
    return field_tbl, ports_of, scalars_of, bulk_ok
