"""TPU engine: drives the JAX sequential-commit scan and mirrors its
placements back into the host-side Oracle state.

The Oracle stays the single source of truth for object-level state
(annotations, reports, reason strings); the scan is the compute path.
Every commit the scan makes is replayed on the host through the same
binding code the oracle uses, so oracle state after an engine batch is
identical to having scheduled serially — this is asserted by the
conformance tests (tests/test_engine_conformance.py).
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ..ops.encode import (
    ClusterStatic,
    EngineUnsupported,
    PodBatch,
    encode_batch,
    encode_cluster,
    encode_dynamic,
)
from .oracle import Oracle

__all__ = ["TpuEngine", "EngineUnsupported"]


class TpuEngine:
    def __init__(self, oracle: Oracle):
        self.oracle = oracle

    def schedule(self, pods: List[dict]) -> np.ndarray:
        """Returns placements[P]: node index or -1 (unschedulable).

        Pods with a spec.nodeName naming an unknown node must be
        filtered out by the caller (the reference leaves them dangling
        in the tracker, simulator.go:221-229).
        """
        import jax.numpy as jnp

        from ..ops import scan as scan_ops

        oracle = self.oracle
        cluster = encode_cluster(oracle)
        batch = encode_batch(oracle, cluster, pods)
        dyn = encode_dynamic(oracle, cluster)

        n = cluster.n
        g = max(cluster.g, 1)
        dev_valid = np.zeros((n, g), dtype=bool)
        for i in range(n):
            dev_valid[i, : cluster.gpu_count[i]] = True

        static = scan_ops.ScanStatic(
            alloc_mcpu=jnp.asarray(cluster.alloc_mcpu),
            alloc_mem=jnp.asarray(cluster.alloc_mem),
            alloc_eph=jnp.asarray(cluster.alloc_eph),
            alloc_pods=jnp.asarray(cluster.alloc_pods),
            scalar_alloc=jnp.asarray(cluster.scalar_alloc),
            gpu_per_dev=jnp.asarray(cluster.gpu_per_dev),
            gpu_total=jnp.asarray(cluster.gpu_total),
            gpu_count=jnp.asarray(cluster.gpu_count),
            dev_valid=jnp.asarray(dev_valid),
            static_feasible=jnp.asarray(batch.static_feasible),
            simon_raw=jnp.asarray(batch.simon_raw),
            nodeaff_raw=jnp.asarray(batch.nodeaff_raw),
            taint_intol=jnp.asarray(batch.taint_intol),
            avoid_score=jnp.asarray(batch.avoid_score),
            image_score=jnp.asarray(batch.image_score),
            req_mcpu=jnp.asarray(batch.req_mcpu),
            req_mem=jnp.asarray(batch.req_mem),
            req_eph=jnp.asarray(batch.req_eph),
            req_scalar=jnp.asarray(batch.req_scalar),
            has_request=jnp.asarray(batch.has_request),
            nz_mcpu=jnp.asarray(batch.nz_mcpu),
            nz_mem=jnp.asarray(batch.nz_mem),
            gpu_mem=jnp.asarray(batch.gpu_mem),
            gpu_cnt=jnp.asarray(batch.gpu_cnt),
            want_ports=jnp.asarray(batch.want_ports),
            conflict_ports=jnp.asarray(batch.conflict_ports),
        )
        init = scan_ops.ScanState(
            used_mcpu=jnp.asarray(dyn.used_mcpu),
            used_mem=jnp.asarray(dyn.used_mem),
            used_eph=jnp.asarray(dyn.used_eph),
            used_scalar=jnp.asarray(dyn.used_scalar),
            nz_mcpu=jnp.asarray(dyn.nz_mcpu),
            nz_mem=jnp.asarray(dyn.nz_mem),
            pod_cnt=jnp.asarray(dyn.pod_cnt),
            ports_used=jnp.asarray(dyn.ports_used),
            gpu_used=jnp.asarray(dyn.gpu_used),
        )
        placements, _ = scan_ops.run_scan(
            static,
            init,
            jnp.asarray(batch.class_of_pod),
            jnp.asarray(batch.pinned_node),
        )
        return np.asarray(placements)

    def commit_host(self, pod: dict, node_idx: int):
        """Replay one placement into oracle state (same binding code the
        serial path uses, incl. GPU/storage side effects)."""
        self.oracle._reserve_and_bind(pod, self.oracle.nodes[int(node_idx)])
