"""TPU engine: drives the JAX sequential-commit scan and mirrors its
placements back into the host-side Oracle state.

The Oracle stays the single source of truth for object-level state
(annotations, reports, reason strings); the scan is the compute path.
Every commit the scan makes is replayed on the host through the same
binding code the oracle uses, so oracle state after an engine batch is
identical to having scheduled serially — this is asserted by the
conformance tests (tests/test_engine_conformance.py).
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..ops.encode import (
    ClusterStatic,
    encode_batch,
    encode_cluster,
    encode_dynamic,
    features_of_batch,
)
from .oracle import Oracle

__all__ = ["SampleRngOverflow", "TpuEngine"]


class SampleRngOverflow(RuntimeError):
    """A sample-mode Intn draw needed more rejection retries than the
    in-scan bound (ops/scan.py _RNG_KMAX; p < 1e-17 per draw). Raised
    BEFORE any commit is replayed, so the caller (core._schedule_pods)
    can rerun the batch on the serial oracle, whose rejection loop is
    unbounded."""


class TpuEngine:
    """Holds the oracle plus a per-node-set cache of the cluster
    encoding: with K apps on an N-node cluster the O(N) ClusterStatic
    build runs once, not K times (per-batch state — DynamicState, pod
    statics, port vocab — is still rebuilt per schedule call)."""

    def __init__(self, oracle: Oracle):
        self.oracle = oracle
        self._cluster: ClusterStatic = None
        self._cache_key = None
        # per-schedule()-call replay fast path (class ids are batch
        # scoped): classes with no GPU/storage/extender side effects
        # commit via per-class summaries instead of the general bind
        self._last_class_of = None
        self._last_simple = None
        self._class_commit_info = None
        # sample mode: (pre-batch rng history, per-pod consumed-word
        # cumsum) of the last scanned batch — rewind_sample_rng uses it
        # when a priority-scan escape discards the scanned tail
        self._last_rng = None

    def cluster_static(self) -> ClusterStatic:
        # keyed on (node count, alloc epoch): GPU-share Reserve mutates
        # ns.alloc[gpu-count], which is baked into ClusterStatic's
        # scalar allocatables — a bind in one batch must invalidate the
        # cache for the next
        key = (len(self.oracle.nodes), self.oracle.alloc_epoch)
        if self._cluster is None or self._cache_key != key:
            self._cluster = encode_cluster(self.oracle)
            self._cache_key = key
        return self._cluster

    def schedule(self, pods: List[dict]) -> np.ndarray:
        """Returns placements[P]: node index or -1 (unschedulable).

        Pods with a spec.nodeName naming an unknown node must be
        filtered out by the caller (the reference leaves them dangling
        in the tracker, simulator.go:221-229).
        """
        import jax.numpy as jnp

        from ..ops import scan as scan_ops
        from ..ops.encode import to_scan_static, to_scan_state
        from ..utils.trace import phase, profiled

        oracle = self.oracle
        with phase("engine/encode"):
            cluster = self.cluster_static()
            batch = encode_batch(oracle, cluster, pods)
            # replay fast-path tables (commit_host_at): batch-scoped
            from .oracle import ClassCommitCache, simple_commit_mask

            self._last_class_of = np.asarray(batch.class_of_pod)
            self._last_simple = simple_commit_mask(batch, bool(oracle.extenders))
            self._class_commit_info = ClassCommitCache()
            dyn = encode_dynamic(oracle, cluster)
            sample = getattr(oracle, "select_host", "first-max") == "sample"
            features = features_of_batch(
                cluster, batch,
                weights=getattr(oracle, "score_weights", None),
                sample=sample,
            )
            from ..ops import pallas_scan

            plan = (
                pallas_scan.build_plan(
                    cluster, batch, dyn, features, weights=features.weights
                )
                if pallas_scan.should_use()
                else None
            )
            if plan is None:
                static = to_scan_static(cluster, batch)
                init = to_scan_state(dyn, batch)
                if sample:
                    # the scan consumes the oracle's Go RNG stream: hand
                    # its 607-output history in via the carry, and (after
                    # the scan) write the advanced stream back so serial
                    # fallbacks continue the exact sequence
                    hist0 = oracle._rng.history()
                    init = init._replace(
                        rng_hist=jnp.asarray(
                            np.array(hist0, dtype=np.uint64)
                        )
                    )
        from ..utils.trace import GLOBAL

        # never a silent fallback: name why the fused kernel was out of
        # scope or unavailable (pallas_scan.fallback_reason)
        GLOBAL.note(
            "batch-kernel",
            pallas_scan.kernel_label(plan)
            if plan is not None
            else f"xla-scan ({pallas_scan.fallback_reason()})",
        )
        if plan is not None:
            # fused single-kernel fast path; bit-identical placements
            # (tests/test_pallas_scan.py)
            with profiled("engine/scan"):
                out, _final = pallas_scan.run_scan_pallas(
                    plan,
                    batch.class_of_pod,
                    np.ones(len(pods), bool),
                    np.ones(cluster.n, bool),
                    pinned=batch.pinned_node,
                )
            return out
        with profiled("engine/scan"):
            placements, final_state = scan_ops.run_scan(
                static,
                init,
                jnp.asarray(batch.class_of_pod),
                jnp.asarray(batch.pinned_node),
                features=features,
            )
            if sample:
                placements, consumed = placements
            out = np.asarray(placements)  # blocks on device completion
        if sample:
            if bool(np.asarray(final_state.rng_overflow)):
                # oracle state is untouched (commits replay only after
                # this returns); core catches this and reruns serially
                raise SampleRngOverflow(
                    "sample-mode RNG rejection overflow; rerunning the "
                    "batch on the serial oracle"
                )
            self._last_rng = (hist0, np.cumsum(np.asarray(consumed)))
            oracle._rng.set_history(
                [int(x) for x in np.asarray(final_state.rng_hist)]
            )
        return out

    def rewind_sample_rng(self, batch_pos: int) -> None:
        """Reposition the oracle's sample-mode stream to where it stood
        BEFORE the last scanned batch's pod at `batch_pos` consumed its
        draws. A priority-scan escape discards every scanned placement
        from the escape point on and reschedules those pods (serially,
        then by rescanning), so their draws must be un-consumed — the
        pre-batch history advanced by the consumed-word prefix is
        exactly that position (gorand.advance_history)."""
        if self._last_rng is None:
            return
        from ..utils.gorand import advance_history

        hist0, consumed_cum = self._last_rng
        k = int(consumed_cum[batch_pos - 1]) if batch_pos > 0 else 0
        self.oracle._rng.set_history(advance_history(hist0, k))

    def commit_host(self, pod: dict, node_idx: int):
        """Replay one placement into oracle state (same binding code the
        serial path uses, incl. GPU/storage side effects)."""
        self.oracle._reserve_and_bind(pod, self.oracle.nodes[int(node_idx)])

    def commit_host_at(self, pod: dict, node_idx: int, batch_pos: int):
        """commit_host with the pod's position in the last scheduled
        batch: classes with no GPU/storage/extender side effects reduce
        _reserve_and_bind to nodeName+phase+commit, and class members
        share request/port content by class-key construction, so the
        summary/port walk runs once per class (the same fast path the
        capacity replay uses, applier.replay_scenario)."""
        cls_of = self._last_class_of
        if cls_of is not None and batch_pos < len(cls_of):
            cls = int(cls_of[batch_pos])
            if self._last_simple[cls]:
                self._class_commit_info.commit(
                    self.oracle, pod, self.oracle.nodes[int(node_idx)], cls
                )
                return
        self.commit_host(pod, node_idx)
