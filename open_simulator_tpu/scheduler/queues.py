"""Pod queue-ordering heuristics (pkg/algo).

- affinity_sort / toleration_sort: pods with nodeSelector (resp.
  tolerations) first (pkg/algo/affinity.go, toleration.go). Stable
  sorts — the reference's comparators are not strict weak orders under
  Go's unstable sort.Sort, so we define the evident intent (documented
  deviation, scheduler/core.py).
- greed_sort: descending dominant-resource share against total cluster
  allocatable, pods with a nodeName first (pkg/algo/greed.go:45-91).
  Dead code in the reference at this revision (`--use-greed` is parsed
  but never forwarded, SURVEY.md §2.1); here the flag actually applies
  the ordering.
"""

from __future__ import annotations

from fractions import Fraction
from typing import List

from ..models import requests as req


def affinity_sort(pods: List[dict]) -> List[dict]:
    return sorted(pods, key=lambda p: (p.get("spec") or {}).get("nodeSelector") is None)


def toleration_sort(pods: List[dict]) -> List[dict]:
    return sorted(pods, key=lambda p: (p.get("spec") or {}).get("tolerations") is None)


def _share(alloc: float, total: float) -> float:
    """algo.Share (greed.go:78-91)."""
    if total == 0:
        return 0.0 if alloc == 0 else 1.0
    return alloc / total


def greed_sort(nodes: List[dict], pods: List[dict]) -> List[dict]:
    """GreedQueue ordering: dominant share of (cpu, memory) vs the
    cluster total, descending; pods with spec.nodeName first.

    Capacity totals exclude simon-fabricated new nodes so the ordering
    is independent of the capacity-planner's current new-node count —
    the serial escalation run and the batched sweep (which pads to the
    maximum count) must sort pods identically or the sweep's minimal
    count is not valid for the serial run that confirms it."""
    from ..models.workloads import LABEL_NEW_NODE

    total_cpu = 0.0
    total_mem = 0.0
    for node in nodes:
        if LABEL_NEW_NODE in ((node.get("metadata") or {}).get("labels") or {}):
            continue
        alloc = req.node_allocatable(node)
        total_cpu += float(alloc.get(req.CPU, Fraction(0)))
        total_mem += float(alloc.get(req.MEMORY, Fraction(0)))

    def dominant_share(pod: dict) -> float:
        requests = req.pod_requests(pod)
        if not requests:
            return 0.0
        cpu = float(requests.get(req.CPU, Fraction(0)))
        mem = float(requests.get(req.MEMORY, Fraction(0)))
        return max(_share(cpu, total_cpu), _share(mem, total_mem))

    return sorted(
        pods,
        key=lambda p: (
            not (p.get("spec") or {}).get("nodeName"),
            -dominant_share(p),
        ),
    )
