"""Simulate facade: one-shot cluster + ordered app deployment.

Mirrors pkg/simulator/core.go:64-103 (Simulate) and the relevant parts of
pkg/simulator/simulator.go:
- cluster workloads (incl. per-node daemonset pods) are expanded and
  scheduled first (RunCluster -> syncClusterResourceList -> schedulePods)
- then each app in configured order (ScheduleApp): expand, sort by the
  affinity/toleration queues, schedule serially
- pods that fail to schedule are removed from the cluster and reported
  with their reason (simulator.go:231-240)

Deviation (documented): the reference sorts app pods with Go sort.Sort
and comparators that are not strict weak orders (pkg/algo/affinity.go:21,
toleration.go:19), yielding an arbitrary deterministic permutation. We
use stable sorts with the evident intent: pods with nodeSelector first,
then pods with tolerations first.

The `engine` argument selects the scheduling backend:
- "oracle": the serial Python reference implementation
- "tpu": the JAX sequential-commit scan (ops/scan.py), which must agree
  with the oracle placement-for-placement
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..models.decode import ResourceTypes
from ..models import workloads as wl
from .oracle import Oracle


# shortest priority-bearing batch worth routing through the
# priority-scan engine (_schedule_pods_priority) — encode + device
# relay have fixed cost, so short batches are cheaper serially (tests
# lower this to exercise the scan routes on tiny batches)
MIN_SCAN_RUN = 64

# after this many serial escapes the priority-scan engine finishes the
# remainder serially: each escape rescans the remaining batch, so an
# escape-heavy run (a deliberately overloaded probe with a post_filter
# plugin, say) would otherwise pay (#escapes + 1) device scans for
# work the serial oracle does in one linear pass
MAX_SCAN_ESCAPES = 16


@dataclass
class UnscheduledPod:
    pod: dict
    reason: str


@dataclass
class NodeStatus:
    node: dict
    pods: List[dict] = field(default_factory=list)


@dataclass
class PreemptionEvent:
    """One DefaultPreemption eviction: `victim` was removed from
    `node_name` to make room for `preemptor` (pod name)."""

    victim: dict
    node_name: str
    preemptor: str


@dataclass
class SimulateResult:
    unscheduled_pods: List[UnscheduledPod] = field(default_factory=list)
    node_status: List[NodeStatus] = field(default_factory=list)
    preemptions: List[PreemptionEvent] = field(default_factory=list)

    @property
    def all_scheduled(self) -> bool:
        return not self.unscheduled_pods


@dataclass
class AppResource:
    name: str
    resource: ResourceTypes


def _sort_app_pods(pods: List[dict]) -> List[dict]:
    from .queues import affinity_sort, toleration_sort

    return toleration_sort(affinity_sort(pods))


class Simulator:
    """In-memory cluster + serial scheduler (the fake apiserver +
    scheduler goroutine of the reference collapse into this object)."""

    def __init__(
        self,
        engine: str = "oracle",
        use_greed: bool = False,
        extenders=None,
        score_weights=None,
        select_host: str = "first-max",
        enable_preemption: bool = True,
        rng=None,
        budget=None,
    ):
        self.engine_kind = engine
        # execution-guard budget (runtime/budget.py): the serial
        # scheduling loop checks it between pod commits — the finest
        # safe boundary the engine has — so a --deadline / SIGINT stops
        # a 100k-pod serial run without tearing a half-committed pod
        self.budget = budget
        self.use_greed = use_greed
        # KubeSchedulerConfiguration score-plugin weights
        # (scheduler/schedconfig.py); None = default profile
        self.score_weights = score_weights
        # KubeSchedulerConfiguration postFilter set: disabling
        # DefaultPreemption turns the preemption stage off everywhere
        # (the priority-scan escape predicate reads the same flag)
        self.enable_preemption = enable_preemption
        # selectHost tie rule (oracle.py module docstring): "sample"
        # rides the XLA scan since r5 — the Go math/rand stream is
        # carried in the scan state (ops/scan.py _sample_select) and
        # handed back to the oracle after each batch, so serial
        # fallbacks (priority escapes) continue the exact sequence
        self.select_host = select_host
        self.rng = rng  # custom sample-mode rng (oracle.py contract)
        # HTTP extenders are host RPC per pod: they force the serial
        # oracle path (SURVEY.md §2.3 host-callback escape hatch)
        self.extenders = list(extenders or [])
        if self.extenders:
            self.engine_kind = "oracle"
        self.oracle: Optional[Oracle] = None
        self.cluster_pods: List[dict] = []
        self._engine = None  # TpuEngine, created once per cluster
        self._batch_map = None  # (batch indices, orig->pos) of the last batch
        self._events: List[PreemptionEvent] = []  # preemptions this batch
        # optional serial-loop observer (shadow/record.py): an object
        # with `prebound(pod_snapshot)` and `decision(pod_snapshot,
        # node_or_None, reason, evictions)` called per serial cycle.
        # Setting it forces nothing by itself — callers who need every
        # pod to take the serial path must also pick engine="oracle"
        self.decision_hook = None

    # RunCluster (simulator.go:159-164)
    def run_cluster(self, cluster: ResourceTypes, build_status: bool = True) -> SimulateResult:
        import numpy as np

        from ..utils.trace import phase

        with phase("host/oracle-build"):
            self.oracle = Oracle(
                cluster.nodes,
                extenders=self.extenders,
                pdbs=cluster.pod_disruption_budgets,
                priority_classes=cluster.priority_classes,
                score_weights=self.score_weights,
                select_host=self.select_host,
                enable_preemption=self.enable_preemption,
                rng=self.rng,
            )
        with phase("host/expand"):
            index = wl.ExpandIndex()
            pods = wl.pods_excluding_daemon_sets(cluster, index=index)
            for ds in cluster.daemon_sets:
                ds_pods = wl.pods_from_daemon_set(ds, cluster.nodes)
                pods.extend(ds_pods)
                for pod in ds_pods:
                    index.mark_group(pod, 1)
            groups = (np.asarray(index.group_of, dtype=np.int64), index.firsts)
        return self._schedule_pods(pods, groups=groups, build_status=build_status)

    # ScheduleApp (simulator.go:166-184)
    def schedule_app(self, app: AppResource, build_status: bool = True) -> SimulateResult:
        import numpy as np

        from ..utils.trace import phase

        nodes = [ns.node for ns in self.oracle.nodes]
        with phase("host/expand"):
            index = wl.ExpandIndex()
            pods = wl.generate_valid_pods_from_app(
                app.name, app.resource, nodes, index=index
            )
        queue_sort = self.oracle.registry.queue_sort_plugin
        if self.use_greed or queue_sort is not None:
            return self._schedule_app_slow(pods, nodes, queue_sort, build_status)
        # The queue-ordering pipeline — affinity_sort, toleration_sort
        # (queues.py: stable, pods with nodeSelector / tolerations
        # first), then PrioritySort (queuesort/priority_sort.go:41-45:
        # priority desc, ties by queue arrival; in the reference this
        # Less never reorders anything — the serial handshake keeps at
        # most one pod in the active queue) with nodeName-bound pods
        # committing first (their capacity is occupied regardless of
        # queue order, and sorting a pending pod ahead of them would
        # let it bind into capacity they already hold). Three
        # sequential stable sorts + a partition == ONE stable
        # lexicographic sort by (bound-first, -priority | bound-const,
        # tolerations-is-None, nodeSelector-is-None, arrival), and
        # every key is a per-GROUP constant (ExpandIndex: group members
        # are content-identical except name), so the whole ordering is
        # a handful of per-group resolutions plus one np.lexsort —
        # replacing the closure-keyed per-pod sorts of the
        # dense-priority cliff. The priority key applies only when a
        # priority signal exists, so the no-priority case keeps the
        # reference's exact list order.
        from .preemption import batch_priorities

        with phase("priority/sort"):
            firsts = index.firsts
            g = np.asarray(index.group_of, dtype=np.int64)
            ng = len(firsts)
            g_prio = batch_priorities(firsts, self.oracle._prio_resolver)
            g_spec = [f.get("spec") or {} for f in firsts]
            g_aff = np.fromiter(
                (s.get("nodeSelector") is None for s in g_spec), dtype=bool, count=ng
            )
            g_tol = np.fromiter(
                (s.get("tolerations") is None for s in g_spec), dtype=bool, count=ng
            )
            prios = g_prio[g]
            use_priority = self.oracle.saw_priority or bool((g_prio != 0).any())
            if use_priority:
                g_bound = np.fromiter(
                    (bool(s.get("nodeName")) for s in g_spec), dtype=bool, count=ng
                )
                not_bound = ~g_bound[g]
                # bound pods share one priority-key constant: they keep
                # their (toleration, affinity, arrival) order among
                # themselves instead of being priority-sorted
                prio_key = np.where(not_bound, -prios, np.int64(0))
                perm = np.lexsort((g_aff[g], g_tol[g], prio_key, not_bound))
            else:
                perm = np.lexsort((g_aff[g], g_tol[g]))
            pods = [pods[i] for i in perm]
            prios = prios[perm]
            groups = (g[perm], firsts)
        return self._schedule_pods(
            pods, prios=prios, groups=groups, build_status=build_status
        )

    def _schedule_app_slow(self, pods, nodes, queue_sort, build_status):
        """The legacy per-pod ordering pipeline for the two paths that
        cannot use per-group keys: greed_sort (per-pod dominant-share
        key over live totals) and an out-of-tree QueueSort plugin (an
        arbitrary comparator REPLACES PrioritySort; the framework
        allows exactly one queue-sort plugin — stable sort keeps
        arrival order on Less-ties). nodeName-bound pods commit first
        either way."""
        if self.use_greed:
            from .queues import greed_sort

            pods = greed_sort(nodes, pods)
        pods = _sort_app_pods(pods)
        if queue_sort is not None:
            import functools

            less = queue_sort.queue_sort_less
            sort_key = functools.cmp_to_key(
                lambda a, b: -1 if less(a, b) else (1 if less(b, a) else 0)
            )
            bound = [p for p in pods if (p.get("spec") or {}).get("nodeName")]
            pending = [p for p in pods if not (p.get("spec") or {}).get("nodeName")]
            pending.sort(key=sort_key)
            pods = bound + pending
        else:
            from .preemption import batch_priorities

            prios = batch_priorities(pods, self.oracle._prio_resolver)
            if self.oracle.saw_priority or bool((prios != 0).any()):
                import numpy as np

                bound = np.fromiter(
                    (bool((p.get("spec") or {}).get("nodeName")) for p in pods),
                    dtype=bool, count=len(pods),
                )
                bound_idx = np.flatnonzero(bound)
                pend_idx = np.flatnonzero(~bound)
                perm = np.concatenate(
                    [bound_idx,
                     pend_idx[np.argsort(-prios[pend_idx], kind="stable")]]
                )
                pods = [pods[i] for i in perm]
                prios = prios[perm]
            return self._schedule_pods(pods, prios=prios, build_status=build_status)
        return self._schedule_pods(pods, build_status=build_status)

    def _schedule_pods(
        self, pods: List[dict], prios=None, groups=None, build_status: bool = True
    ) -> SimulateResult:
        # Engine routing (VERDICT r1 #3 / r2 weak #4 / r3 weak #2): the
        # JAX scan has no preemption semantics, but the serial cycle
        # only PERFORMS preemption when a pod both fails and passes the
        # PostFilter gates — so a priority batch rides the ordered scan
        # optimistically and drops to the serial oracle per escape, not
        # per batch (_schedule_pods_priority). Dense-priority workloads
        # that place cleanly cost one scan, same as zero-priority ones.
        from .preemption import batch_priorities
        from .engine import SampleRngOverflow
        from ..utils.trace import GLOBAL

        # a permit reject or a stateful plugin hook on the selected node
        # would invalidate / miss every later placement the batched scan
        # committed (plugins.py: needs_serial)
        tpu_ok = self.engine_kind == "tpu" and not self.oracle.registry.needs_serial
        if tpu_ok and self.oracle.select_host == "sample":
            # the scan carries the Go ALFG stream via the rng's
            # history()/set_history(); a CUSTOM rng satisfying only the
            # documented `.intn(n)` contract (oracle.py) cannot ride it
            # — and a non-Go generator would diverge from the scan's
            # hard-coded recurrence — so those stay on the serial path
            rng = self.oracle._rng
            tpu_ok = hasattr(rng, "history") and hasattr(rng, "set_history")
        if tpu_ok and prios is None:
            if groups is not None:
                # per-GROUP resolution broadcast to pods (ExpandIndex:
                # group members share priority-bearing content)
                group_of, firsts = groups
                g_prio = batch_priorities(firsts, self.oracle._prio_resolver)
                prios = g_prio[group_of] if len(pods) else g_prio[:0]
            else:
                prios = batch_priorities(pods, self.oracle._prio_resolver)
        # a custom post_filter plugin can act on ANY failed pod, so
        # such batches take the priority-scan path with every failure
        # escaping to the serial cycle (the armed mask below)
        priority_free = tpu_ok and not self.oracle.registry.has_post_filter and (
            not self.oracle.saw_priority and not bool((prios != 0).any())
        )
        from ..obs.explain import EXPLAIN

        if EXPLAIN.enabled:
            EXPLAIN.set_context(
                engine="batch-scan"
                if priority_free
                else ("priority-scan" if tpu_ok and len(pods) >= MIN_SCAN_RUN
                      else "serial-oracle")
            )
        if priority_free:
            GLOBAL.note("engine", "batch")
            try:
                failed = self._schedule_pods_tpu(pods, groups=groups)
            except SampleRngOverflow:
                # a sample-mode draw exceeded the in-scan rejection
                # bound (p < 1e-17 per draw); nothing was committed, so
                # the serial oracle reruns the batch with exact
                # unbounded rejection semantics
                GLOBAL.note("engine", "serial-oracle (sample rng overflow)")
                failed, _ = self._schedule_pods_oracle(pods)
        elif tpu_ok and len(pods) >= MIN_SCAN_RUN:
            # (sample mode included: an escape DISCARDS the scanned
            # tail, whose Go-RNG draws the scan already consumed — the
            # scan exports per-pod consumption and _scan_and_commit
            # REWINDS the stream to the escape point, so the serial
            # escape and the re-dispatch continue the exact sequence)
            failed = self._schedule_pods_priority(pods, prios, groups=groups)
        else:
            GLOBAL.note("engine", "serial-oracle")
            failed, _ = self._schedule_pods_oracle(pods)
        events = self._events
        self._events = []
        return SimulateResult(
            unscheduled_pods=failed,
            node_status=self.node_status() if build_status else [],
            preemptions=events,
        )

    def _schedule_pods_priority(
        self, pods: List[dict], prios, groups=None
    ) -> List[UnscheduledPod]:
        """Tiered optimistic ordered scan with a per-pod serial escape
        hatch — the round-6 vectorization of the round-4 priority-scan
        engine (VERDICT r3 weak #2: dense-priority batches used to
        route their whole non-zero segment to the serial oracle).

        The batch arrives PrioritySorted (desc, stable; bound pods
        first, schedule_app) with its effective priorities batch-
        resolved once (`prios`, preemption.batch_priorities). The scan
        engine places pods IN ORDER with placements identical to the
        serial cycle (engine conformance) up to the first pod that both
        FAILS and passes the serial PostFilter preemption gates — the
        one event where the serial cycle would mutate state (evict
        victims) in a way the scan cannot. Everything before that pod
        commits (sequential prefix identity), the pod itself runs
        through the full serial cycle (oracle.schedule_pod incl.
        DefaultPreemption), and the next round re-dispatches the SAME
        batch encoding with the committed prefix masked off
        (engine.scan_active) — no re-encode, no XLA recompile. Cost:
        (#preempting-failures + 1) dispatches, so a dense-priority
        batch that places cleanly costs exactly one scan.

        The escape predicate mirrors the oracle's own gates bit-for-bit
        (oracle._post_filter_preempt: enable_preemption, `prio >
        _min_prio`; run_preemption: preemptionPolicy Never) but is
        evaluated per TIER, not per pod: the remaining suffix
        partitions into contiguous equal-priority tiers, within which
        the serial per-pod gate `prio > min(_min_prio, prefix_min)` is
        a constant (preemption.tier_escape_mask derives the identity),
        so each round's escape check is three numpy passes over tier
        boundaries plus a per-candidate preemptionPolicy resolution on
        FAILING pods only. Unsorted input (run_cluster's raw pod list)
        still escapes whenever an earlier batch pod COULD have armed
        the gate — conservative, never wrong: the escape replays that
        pod through the full serial cycle either way.

        Victims evicted by an escape rejoin the serial queue at the
        BACK (behind the remaining batch), so they are deferred into a
        final serial segment in eviction order — the same queue
        equivalence argument as the round-3 hybrid (vendor
        scheduling_queue semantics under the one-pod-in-flight
        handshake)."""
        import numpy as np

        from .engine import SampleRngOverflow
        from .preemption import tier_escape_mask
        from ..obs.explain import EXPLAIN
        from ..utils.trace import GLOBAL

        failed: List[UnscheduledPod] = []
        deferred: List[dict] = []
        p = len(pods)
        prios = np.asarray(prios, dtype=np.int64)
        rounds = escapes = 0
        tiers_round1 = None
        has_post_filter = self.oracle.registry.has_post_filter
        start = 0
        while start < p:
            rounds += 1
            if has_post_filter:
                # a custom post_filter may act on any failure
                armed = np.ones(p - start, dtype=bool)
                policy_gate = False
                n_tiers = 1
            else:
                armed, n_tiers = tier_escape_mask(
                    prios[start:],
                    self.oracle._min_prio,  # re-read per round
                    self.oracle.enable_preemption,
                )
                policy_gate = True
            if tiers_round1 is None:
                tiers_round1 = n_tiers
            if EXPLAIN.enabled:
                # tier/escape provenance: explanations recorded during
                # this round's replay carry the round + tier count
                EXPLAIN.set_context(
                    engine="priority-scan", scan_round=rounds, tiers=n_tiers
                )
            try:
                f, escape_at = self._scan_and_commit(
                    pods, armed=armed, policy_gate=policy_gate,
                    prios=prios, start=start, reuse_batch=rounds > 1,
                    groups=groups,
                )
            except SampleRngOverflow:
                # nothing from this round committed (the engine raises
                # before replay); the remainder drops to the serial
                # tail below, whose rejection loop is unbounded
                GLOBAL.note("priority-scan-sample-overflow", p - start)
                break
            failed.extend(f)
            if escape_at is None:
                start = p
                break
            escapes += 1
            if EXPLAIN.enabled and EXPLAIN.wants(pods[escape_at]):
                EXPLAIN.annotate(
                    pods[escape_at],
                    escape_round=rounds,
                    path="serial-preemption-cycle",
                )
            f2, d2 = self._schedule_pods_oracle(
                [pods[escape_at]], defer_victims=True
            )
            failed.extend(f2)
            deferred.extend(d2)
            start = escape_at + 1
            if escapes >= MAX_SCAN_ESCAPES:
                # escape-heavy batch: each escape re-dispatches the
                # remainder, so past this point one serial pass is
                # cheaper
                break
        if start < p:
            GLOBAL.note("priority-scan-serial-tail", p - start)
            f4, d4 = self._schedule_pods_oracle(pods[start:], defer_victims=True)
            failed.extend(f4)
            deferred.extend(d4)
        if deferred:
            f3, _ = self._schedule_pods_oracle(deferred)
            failed.extend(f3)
        GLOBAL.note("engine", "priority-scan")
        GLOBAL.note("priority-scan-rounds", rounds)
        GLOBAL.note("priority-scan-escapes", escapes)
        GLOBAL.note("priority-scan-tiers", tiers_round1)
        return failed

    def _schedule_pods_oracle(
        self, pods: List[dict], defer_victims: bool = False
    ) -> tuple:
        """Returns (failed, deferred_victims). With defer_victims,
        preemption victims are returned instead of re-enqueued — the
        hybrid path re-enqueues them after its scan segment."""
        import copy
        from collections import deque

        failed: List[UnscheduledPod] = []
        deferred: List[dict] = []
        queue = deque(pods)
        scheduled = 0
        hook = self.decision_hook
        while queue:
            if self.budget is not None and scheduled % 128 == 0:
                self.budget.check(
                    f"serial scheduling ({scheduled}/{len(pods)} pods)"
                )
            scheduled += 1
            pod = queue.popleft()
            if (pod.get("spec") or {}).get("nodeName"):
                # the hook sees the PRE-commit dict (binding mutates it)
                snap = copy.deepcopy(pod) if hook is not None else None
                self.oracle.place_existing_pod(pod)
                self.cluster_pods.append(pod)
                if hook is not None:
                    hook.prebound(snap)
                continue
            snap = copy.deepcopy(pod) if hook is not None else None
            node_name, reason = self.oracle.schedule_pod(pod)
            if node_name is None:
                failed.append(UnscheduledPod(pod=pod, reason=reason))
            else:
                self.cluster_pods.append(pod)
            # victims evicted by DefaultPreemption rejoin the queue at
            # the back (their controller would recreate them; the
            # scheduler then re-places or fails them). Victims arrive
            # in MoreImportantPod order. Termination: a victim's
            # priority is strictly below its preemptor's, so eviction
            # chains strictly descend.
            evictions = []
            for ev in self.oracle.drain_preempted():
                self._events.append(
                    PreemptionEvent(
                        victim=ev.pod, node_name=ev.node_name, preemptor=ev.preemptor
                    )
                )
                for i, p in enumerate(self.cluster_pods):
                    if p is ev.pod:
                        self.cluster_pods.pop(i)
                        break
                evictions.append(ev)
                (deferred if defer_victims else queue).append(ev.pod)
            if hook is not None:
                hook.decision(snap, node_name, reason, evictions)
        return failed, deferred

    def _schedule_pods_tpu(self, pods: List[dict], groups=None) -> List[UnscheduledPod]:
        """JAX scan path. Pods keep their order (pinned pods are forced
        placements inside the scan)."""
        failed, _ = self._scan_and_commit(pods, groups=groups)
        return failed

    def _scan_and_commit(
        self,
        pods: List[dict],
        armed=None,
        policy_gate: bool = True,
        prios=None,
        start: int = 0,
        reuse_batch: bool = False,
        groups=None,
    ):
        """Dispatch one scan round over `pods[start:]` and replay the
        placements onto the oracle in order. Returns
        `(failed, escape_index)`.

        Without `armed` the whole window commits and escape_index is
        None. With it (`armed[i - start]` = the tier-constant escape
        predicate of preemption.tier_escape_mask), the replay stops at
        the first unpinned pod that failed, is armed, and — when
        `policy_gate` — does not carry preemptionPolicy Never: the
        prefix before it is committed (scan placements are
        serial-identical up to there), and its index into `pods` is
        returned so the caller can handle that pod serially and
        re-dispatch the remainder. The scan computed later placements
        against a state the serial escape is about to change, so they
        are discarded, and pods after the escape point (including pins
        and dangling pods) are left untouched for the next round.

        `reuse_batch` re-dispatches the encoding built by an earlier
        call in the same batch loop (engine.begin_batch ran once; each
        round is a masked scan over the full-batch shapes, so escape
        rounds never re-encode or recompile).
        """
        import numpy as np

        from .engine import TpuEngine
        from ..utils.trace import profiled

        p = len(pods)
        if self._engine is None or self._engine.oracle is not self.oracle:
            self._engine = TpuEngine(self.oracle)
        eng = self._engine
        if not reuse_batch:
            # pods pinned to unknown nodes never reach the scheduler
            # (reference: created in the tracker, no bind event);
            # pos_of maps orig index -> batch position (-1 dangling)
            node_index = self.oracle.node_index
            if groups is not None:
                # dangling is a per-GROUP fact (nodeName is group
                # content), so the mask is one numpy gather
                group_of, firsts = groups
                g_dangle = np.fromiter(
                    (
                        bool((f.get("spec") or {}).get("nodeName"))
                        and (f.get("spec") or {})["nodeName"] not in node_index
                        for f in firsts
                    ),
                    dtype=bool, count=len(firsts),
                )
                dang = g_dangle[group_of] if p else g_dangle[:0]
                if dang.any():
                    bidx = np.flatnonzero(~dang)
                    pos_of = np.full(p, -1, dtype=np.int64)
                    pos_of[bidx] = np.arange(len(bidx))
                    batch_pods = [pods[i] for i in bidx.tolist()]
                    batch_groups = (group_of[bidx], firsts)
                else:
                    bidx = np.arange(p, dtype=np.int64)
                    pos_of = bidx
                    batch_pods = pods
                    batch_groups = (group_of, firsts)
            else:
                pos_of = np.full(p, -1, dtype=np.int64)
                bidx_list = []
                for i, pod in enumerate(pods):
                    name = (pod.get("spec") or {}).get("nodeName")
                    if name and name not in node_index:
                        continue
                    pos_of[i] = len(bidx_list)
                    bidx_list.append(i)
                bidx = np.asarray(bidx_list, dtype=np.int64)
                batch_pods = [pods[i] for i in bidx_list]
                batch_groups = None
            if len(bidx):
                eng.begin_batch(batch_pods, groups=batch_groups)
            self._batch_map = (bidx, pos_of)
        bidx, pos_of = self._batch_map
        b = len(bidx)
        if b:
            pos_start = int(np.searchsorted(bidx, start))
            active = np.zeros(b, dtype=bool)
            active[pos_start:] = True
            placements = eng.scan_active(active)
        else:
            pos_start = 0
            placements = np.zeros(0, dtype=np.int64)
        # escape detection: one vectorized pass over the active suffix,
        # then the per-candidate preemptionPolicy gate on FAILING pods
        # only (mirrors run_preemption's PodEligibleToPreemptOthers)
        escape_at = None
        if armed is not None and b and pos_start < b:
            seg = placements[pos_start:]
            seg_pinned = np.asarray(eng._batch.pinned_node)[pos_start:] >= 0
            cand = (seg < 0) & ~seg_pinned
            if cand.any():
                cand &= np.asarray(armed, dtype=bool)[bidx[pos_start:] - start]
                for k in np.flatnonzero(cand).tolist():
                    i = int(bidx[pos_start + k])
                    if (
                        policy_gate
                        and self.oracle.pod_preemption_policy(pods[i]) == "Never"
                    ):
                        continue
                    escape_at = i
                    break
        if escape_at is not None and self.oracle.select_host == "sample":
            # the scan consumed Go-RNG draws for the DISCARDED tail
            # too: rewind the stream to just before the escaped pod so
            # its serial cycle (and the re-dispatch after it) continue
            # the exact serial sequence
            eng.rewind_sample_rng(int(pos_of[escape_at]))
        failed: List[UnscheduledPod] = []
        stop = p if escape_at is None else escape_at
        with profiled("engine/replay"):
            self._replay_window(pods, placements, start, stop, prios, failed)
        return failed, escape_at

    def _replay_window(self, pods, placements, start, stop, prios, failed):
        """Replay committed placements for `pods[start:stop]` in order.

        Contiguous runs of side-effect-free placements commit in bulk
        (oracle.commit_simple_bulk: per-node scatter-add of per-class
        summary deltas); the run breaks at every EVENT pod — dangling,
        pinned, failed, or a class with GPU/storage/extender side
        effects — which takes the exact per-pod path at its position,
        so oracle state evolves in the same order as the serial cycle
        (failure reasons read the state of their own step)."""
        import numpy as np

        if stop <= start:
            return
        from ..obs.explain import EXPLAIN

        eng = self._engine
        bidx, pos_of = self._batch_map
        cluster_pods = self.cluster_pods
        oracle = self.oracle
        w_pos = pos_of[start:stop]
        if len(bidx):
            safe = np.clip(w_pos, 0, None)
            in_batch = w_pos >= 0
            w_place = np.where(in_batch, placements[safe], -3)
            w_cls = np.where(in_batch, eng._last_class_of[safe], 0)
            w_pin = np.where(
                in_batch, np.asarray(eng._batch.pinned_node)[safe] >= 0, False
            )
            simple = eng._last_simple
            _tbl, _po, _so, bulk_ok = eng.bulk_tables()
            bulk_mask = (
                (w_place >= 0) & ~w_pin & in_batch
                & simple[w_cls] & bulk_ok[w_cls]
            )
            if EXPLAIN.enabled and EXPLAIN.target is not None:
                # a TARGETED explained pod must leave the bulk run so
                # its filter/score walk can be captured against the
                # oracle state of exactly its own commit step (failed
                # pods already take the per-pod path); target-less
                # explain does not pay this — committed-pod captures
                # are opt-in by name, failures record regardless
                want = np.fromiter(
                    (EXPLAIN.wants(pods[start + i])
                     for i in range(stop - start)),
                    dtype=bool, count=stop - start,
                )
                bulk_mask &= ~want
        else:
            w_place = np.full(stop - start, -3, dtype=np.int64)
            w_cls = np.zeros(stop - start, dtype=np.int64)
            w_pin = np.zeros(stop - start, dtype=bool)
            bulk_mask = np.zeros(stop - start, dtype=bool)

        def bulk(a, b):
            if b <= a:
                return
            sl = pods[start + a: start + b]
            eng.commit_host_bulk(
                sl, w_place[a:b], w_cls[a:b],
                prios=None if prios is None else prios[start + a: start + b],
            )
            cluster_pods.extend(sl)

        prev = 0
        for e in np.flatnonzero(~bulk_mask).tolist():
            bulk(prev, e)
            prev = e + 1
            pod = pods[start + e]
            if w_pos[e] < 0:
                # dangling: tracked in the cluster, never scheduled
                cluster_pods.append(pod)
            elif w_pin[e]:
                oracle.place_existing_pod(pod)
                cluster_pods.append(pod)
            elif w_place[e] < 0:
                # oracle state here equals the scan state at this step
                # (commits are replayed in order), so reasons are exact
                _, reasons, _ = oracle._find_feasible(pod)
                failed.append(
                    UnscheduledPod(
                        pod=pod, reason=Oracle._failure_message(pod, reasons)
                    )
                )
            else:
                if (
                    EXPLAIN.enabled
                    and EXPLAIN.target is not None
                    and EXPLAIN.wants(pod)
                ):
                    # pre-commit: the oracle state here is the serial
                    # cycle's state at this pod's step (replay order);
                    # committed-pod captures are targeted-only
                    EXPLAIN.capture(oracle, pod, int(w_place[e]))
                # GPU/storage/extender side effects: exact per-pod bind
                eng.commit_host_at(pod, int(w_place[e]), int(w_pos[e]))
                cluster_pods.append(pod)
        bulk(prev, stop - start)

    def node_status(self) -> List[NodeStatus]:
        out = []
        for ns in self.oracle.nodes:
            out.append(NodeStatus(node=ns.node, pods=list(ns.pods)))
        return out


def simulate(
    cluster: ResourceTypes,
    apps: List[AppResource],
    engine: str = "oracle",
    use_greed: bool = False,
    extenders=None,
    score_weights=None,
    select_host: str = "first-max",
    enable_preemption: bool = True,
    rng=None,
    budget=None,
) -> SimulateResult:
    """One-shot simulation (core.go:64-103). `budget` (runtime/budget)
    is checked between apps and between serial pod commits; on expiry
    or SIGINT the raised ExecutionHalted names the boundary."""
    sim = Simulator(
        engine=engine,
        use_greed=use_greed,
        extenders=extenders,
        score_weights=score_weights,
        select_host=select_host,
        enable_preemption=enable_preemption,
        rng=rng,
        budget=budget,
    )
    # NOTE: the identity memos are deliberately NOT cleared here — the
    # planner's serial bisection calls simulate() once per guess over
    # the same object graphs and relies on warm caches. The planner
    # entry points (Applier.run, probe_plan) clear at their boundary;
    # long-lived embedders calling simulate() directly should call
    # utils.memo.clear_all_memos() between runs to release the caches'
    # strong refs to pod/node sub-objects.
    import gc

    cluster = cluster.copy()
    failed: List[UnscheduledPod] = []
    preemptions: List[PreemptionEvent] = []
    # a run allocates hundreds of thousands of short-lived dicts (pod
    # expansion, clones, result rows) but frees almost nothing mid-run
    # — cyclic-GC passes are pure overhead and wall-clock jitter at
    # bench scale (the same pause probe_plan applies, applier.py).
    # Unlike probe_plan there is NO trailing gc.collect(): the run's
    # object graphs are acyclic (dict/list trees), so refcounting
    # frees them without the cyclic collector, and a full collect here
    # would cost more than the pauses it saves on a sub-second run
    gc_was_enabled = gc.isenabled()
    if gc_was_enabled:
        gc.disable()
    try:
        # intermediate node_status snapshots are discarded here (only
        # the final one is returned), so skip building them — an
        # N-node list copy per app otherwise
        result = sim.run_cluster(cluster, build_status=False)
        failed.extend(result.unscheduled_pods)
        preemptions.extend(result.preemptions)
        for app in apps:
            if budget is not None:
                budget.check(f"app boundary ({app.name})")
            result = sim.schedule_app(app, build_status=False)
            failed.extend(result.unscheduled_pods)
            preemptions.extend(result.preemptions)
        return SimulateResult(
            unscheduled_pods=failed,
            node_status=sim.node_status(),
            preemptions=preemptions,
        )
    finally:
        if gc_was_enabled:
            gc.enable()
