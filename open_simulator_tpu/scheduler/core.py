"""Simulate facade: one-shot cluster + ordered app deployment.

Mirrors pkg/simulator/core.go:64-103 (Simulate) and the relevant parts of
pkg/simulator/simulator.go:
- cluster workloads (incl. per-node daemonset pods) are expanded and
  scheduled first (RunCluster -> syncClusterResourceList -> schedulePods)
- then each app in configured order (ScheduleApp): expand, sort by the
  affinity/toleration queues, schedule serially
- pods that fail to schedule are removed from the cluster and reported
  with their reason (simulator.go:231-240)

Deviation (documented): the reference sorts app pods with Go sort.Sort
and comparators that are not strict weak orders (pkg/algo/affinity.go:21,
toleration.go:19), yielding an arbitrary deterministic permutation. We
use stable sorts with the evident intent: pods with nodeSelector first,
then pods with tolerations first.

The `engine` argument selects the scheduling backend:
- "oracle": the serial Python reference implementation
- "tpu": the JAX sequential-commit scan (ops/scan.py), which must agree
  with the oracle placement-for-placement
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..models.decode import ResourceTypes
from ..models import workloads as wl
from .oracle import Oracle


# shortest priority-bearing batch worth routing through the
# priority-scan engine (_schedule_pods_priority) — encode + device
# relay have fixed cost, so short batches are cheaper serially (tests
# lower this to exercise the scan routes on tiny batches)
MIN_SCAN_RUN = 64

# after this many serial escapes the priority-scan engine finishes the
# remainder serially: each escape rescans the remaining batch, so an
# escape-heavy run (a deliberately overloaded probe with a post_filter
# plugin, say) would otherwise pay (#escapes + 1) device scans for
# work the serial oracle does in one linear pass
MAX_SCAN_ESCAPES = 16


@dataclass
class UnscheduledPod:
    pod: dict
    reason: str


@dataclass
class NodeStatus:
    node: dict
    pods: List[dict] = field(default_factory=list)


@dataclass
class PreemptionEvent:
    """One DefaultPreemption eviction: `victim` was removed from
    `node_name` to make room for `preemptor` (pod name)."""

    victim: dict
    node_name: str
    preemptor: str


@dataclass
class SimulateResult:
    unscheduled_pods: List[UnscheduledPod] = field(default_factory=list)
    node_status: List[NodeStatus] = field(default_factory=list)
    preemptions: List[PreemptionEvent] = field(default_factory=list)

    @property
    def all_scheduled(self) -> bool:
        return not self.unscheduled_pods


@dataclass
class AppResource:
    name: str
    resource: ResourceTypes


def _sort_app_pods(pods: List[dict]) -> List[dict]:
    from .queues import affinity_sort, toleration_sort

    return toleration_sort(affinity_sort(pods))


class Simulator:
    """In-memory cluster + serial scheduler (the fake apiserver +
    scheduler goroutine of the reference collapse into this object)."""

    def __init__(
        self,
        engine: str = "oracle",
        use_greed: bool = False,
        extenders=None,
        score_weights=None,
        select_host: str = "first-max",
        enable_preemption: bool = True,
        rng=None,
        budget=None,
    ):
        self.engine_kind = engine
        # execution-guard budget (runtime/budget.py): the serial
        # scheduling loop checks it between pod commits — the finest
        # safe boundary the engine has — so a --deadline / SIGINT stops
        # a 100k-pod serial run without tearing a half-committed pod
        self.budget = budget
        self.use_greed = use_greed
        # KubeSchedulerConfiguration score-plugin weights
        # (scheduler/schedconfig.py); None = default profile
        self.score_weights = score_weights
        # KubeSchedulerConfiguration postFilter set: disabling
        # DefaultPreemption turns the preemption stage off everywhere
        # (the priority-scan escape predicate reads the same flag)
        self.enable_preemption = enable_preemption
        # selectHost tie rule (oracle.py module docstring): "sample"
        # rides the XLA scan since r5 — the Go math/rand stream is
        # carried in the scan state (ops/scan.py _sample_select) and
        # handed back to the oracle after each batch, so serial
        # fallbacks (priority escapes) continue the exact sequence
        self.select_host = select_host
        self.rng = rng  # custom sample-mode rng (oracle.py contract)
        # HTTP extenders are host RPC per pod: they force the serial
        # oracle path (SURVEY.md §2.3 host-callback escape hatch)
        self.extenders = list(extenders or [])
        if self.extenders:
            self.engine_kind = "oracle"
        self.oracle: Optional[Oracle] = None
        self.cluster_pods: List[dict] = []
        self._engine = None  # TpuEngine, created once per cluster
        self._events: List[PreemptionEvent] = []  # preemptions this batch

    # RunCluster (simulator.go:159-164)
    def run_cluster(self, cluster: ResourceTypes) -> SimulateResult:
        self.oracle = Oracle(
            cluster.nodes,
            extenders=self.extenders,
            pdbs=cluster.pod_disruption_budgets,
            priority_classes=cluster.priority_classes,
            score_weights=self.score_weights,
            select_host=self.select_host,
            enable_preemption=self.enable_preemption,
            rng=self.rng,
        )
        pods = wl.pods_excluding_daemon_sets(cluster)
        for ds in cluster.daemon_sets:
            pods.extend(wl.pods_from_daemon_set(ds, cluster.nodes))
        return self._schedule_pods(pods)

    # ScheduleApp (simulator.go:166-184)
    def schedule_app(self, app: AppResource) -> SimulateResult:
        nodes = [ns.node for ns in self.oracle.nodes]
        pods = wl.generate_valid_pods_from_app(app.name, app.resource, nodes)
        if self.use_greed:
            from .queues import greed_sort

            pods = greed_sort(nodes, pods)
        pods = _sort_app_pods(pods)
        # PrioritySort (queuesort/priority_sort.go:41-45): priority
        # desc, ties by queue arrival — our arrival order is the
        # affinity/toleration-sorted order, so a stable sort keeps it.
        # (In the reference this Less never reorders anything: the
        # serial handshake keeps at most one pod in the active queue.)
        # Applied only when a priority signal exists, so the no-priority
        # case keeps the reference's exact list order; nodeName-bound
        # pods commit first — their capacity is occupied regardless of
        # queue order, and sorting a pending pod ahead of them would
        # let it bind into capacity they already hold.
        from .preemption import pod_uses_priority

        queue_sort = self.oracle.registry.queue_sort_plugin
        if queue_sort is not None:
            # an out-of-tree QueueSort plugin REPLACES PrioritySort
            # (the framework allows exactly one queue-sort plugin);
            # stable sort keeps arrival order on Less-ties
            import functools

            less = queue_sort.queue_sort_less
            sort_key = functools.cmp_to_key(
                lambda a, b: -1 if less(a, b) else (1 if less(b, a) else 0)
            )
        elif self.oracle.saw_priority or any(
            pod_uses_priority(p, self.oracle._prio_resolver) for p in pods
        ):
            sort_key = lambda p: -self.oracle.pod_priority(p)  # noqa: E731
        else:
            sort_key = None
        if sort_key is not None:
            # nodeName-bound pods commit first either way: their
            # capacity is occupied regardless of queue order, and
            # sorting a pending pod ahead of them would let it bind
            # into capacity they already hold
            bound = [p for p in pods if (p.get("spec") or {}).get("nodeName")]
            pending = [p for p in pods if not (p.get("spec") or {}).get("nodeName")]
            pending.sort(key=sort_key)
            pods = bound + pending
        return self._schedule_pods(pods)

    def _schedule_pods(self, pods: List[dict]) -> SimulateResult:
        # Engine routing (VERDICT r1 #3 / r2 weak #4 / r3 weak #2): the
        # JAX scan has no preemption semantics, but the serial cycle
        # only PERFORMS preemption when a pod both fails and passes the
        # PostFilter gates — so a priority batch rides the ordered scan
        # optimistically and drops to the serial oracle per escape, not
        # per batch (_schedule_pods_priority). Dense-priority workloads
        # that place cleanly cost one scan, same as zero-priority ones.
        from .preemption import pod_uses_priority
        from .engine import SampleRngOverflow
        from ..utils.trace import GLOBAL

        # a permit reject or a stateful plugin hook on the selected node
        # would invalidate / miss every later placement the batched scan
        # committed (plugins.py: needs_serial)
        tpu_ok = self.engine_kind == "tpu" and not self.oracle.registry.needs_serial
        if tpu_ok and self.oracle.select_host == "sample":
            # the scan carries the Go ALFG stream via the rng's
            # history()/set_history(); a CUSTOM rng satisfying only the
            # documented `.intn(n)` contract (oracle.py) cannot ride it
            # — and a non-Go generator would diverge from the scan's
            # hard-coded recurrence — so those stay on the serial path
            rng = self.oracle._rng
            tpu_ok = hasattr(rng, "history") and hasattr(rng, "set_history")
        # a custom post_filter plugin can act on ANY failed pod, so
        # such batches take the priority-scan path with every failure
        # escaping to the serial cycle (escape_if below)
        priority_free = tpu_ok and not self.oracle.registry.has_post_filter and (
            not self.oracle.saw_priority
            and not any(pod_uses_priority(p, self.oracle._prio_resolver) for p in pods)
        )
        if priority_free:
            GLOBAL.note("engine", "batch")
            try:
                failed = self._schedule_pods_tpu(pods)
            except SampleRngOverflow:
                # a sample-mode draw exceeded the in-scan rejection
                # bound (p < 1e-17 per draw); nothing was committed, so
                # the serial oracle reruns the batch with exact
                # unbounded rejection semantics
                GLOBAL.note("engine", "serial-oracle (sample rng overflow)")
                failed, _ = self._schedule_pods_oracle(pods)
        elif tpu_ok and len(pods) >= MIN_SCAN_RUN:
            # (sample mode included: an escape DISCARDS the scanned
            # tail, whose Go-RNG draws the scan already consumed — the
            # scan exports per-pod consumption and _scan_and_commit
            # REWINDS the stream to the escape point, so the serial
            # escape and the rescan continue the exact serial sequence)
            failed = self._schedule_pods_priority(pods)
        else:
            GLOBAL.note("engine", "serial-oracle")
            failed, _ = self._schedule_pods_oracle(pods)
        events = self._events
        self._events = []
        return SimulateResult(
            unscheduled_pods=failed,
            node_status=self.node_status(),
            preemptions=events,
        )

    def _schedule_pods_priority(self, pods: List[dict]) -> List[UnscheduledPod]:
        """Optimistic ordered scan with a per-pod serial escape hatch —
        the round-4 generalization of the round-3 head/zero-run hybrid
        (VERDICT r3 weak #2: dense-priority batches used to route their
        whole non-zero segment to the serial oracle).

        The batch arrives PrioritySorted (desc, stable; bound pods
        first, schedule_app). The scan engine places pods IN ORDER with
        placements identical to the serial cycle (engine conformance)
        up to the first pod that both FAILS and passes the serial
        PostFilter preemption gates — the one event where the serial
        cycle would mutate state (evict victims) in a way the scan
        cannot. Everything before that pod commits (sequential prefix
        identity), the pod itself runs through the full serial cycle
        (oracle.schedule_pod incl. DefaultPreemption), and the scan
        resumes on the remainder against the updated state. Cost:
        (#preempting-failures + 1) scans, so a dense-priority batch
        that places cleanly costs exactly one scan.

        The escape predicate mirrors the oracle's own gates
        bit-for-bit (oracle._post_filter_preempt: enable_preemption,
        `prio > _min_prio`; run_preemption: preemptionPolicy Never), so
        a NON-escaping failure is one the serial cycle records with no
        state change — recording it in-scan is exact. Batch-internal
        commits are covered by a running prefix-min over the batch's
        own priorities: under schedule_app's PrioritySorted (desc)
        order the prefix-min never drops below the failing pod's
        priority, so the predicate reduces to the pre-scan `_min_prio`
        (re-read per round); unsorted input (run_cluster's raw pod
        list) still escapes whenever an earlier batch pod COULD have
        armed the gate — conservative, never wrong: the escape replays
        that pod through the full serial cycle either way.

        Victims evicted by an escape rejoin the serial queue at the
        BACK (behind the remaining batch), so they are deferred into a
        final serial segment in eviction order — the same queue
        equivalence argument as the round-3 hybrid (vendor
        scheduling_queue semantics under the one-pod-in-flight
        handshake)."""
        import math

        from .engine import SampleRngOverflow
        from ..utils.trace import GLOBAL

        failed: List[UnscheduledPod] = []
        deferred: List[dict] = []
        rest = list(pods)
        rounds = escapes = 0
        has_post_filter = self.oracle.registry.has_post_filter
        while rest:
            rounds += 1
            min_prio = self.oracle._min_prio
            preempt_enabled = self.oracle.enable_preemption
            prios = [self.oracle.pod_priority(p) for p in rest]
            prefix_min, m = [], math.inf
            for v in prios:
                prefix_min.append(m)
                m = min(m, v)

            def escape_if(p, i, _mp=min_prio, _en=preempt_enabled, _pm=prefix_min):
                if has_post_filter:
                    # a custom post_filter may act on any failure
                    return True
                return (
                    _en
                    and self.oracle.pod_priority(p) > min(_mp, _pm[i])
                    and self.oracle.pod_preemption_policy(p) != "Never"
                )

            try:
                f, escape_at = self._scan_and_commit(rest, escape_if=escape_if)
            except SampleRngOverflow:
                # nothing from this round committed (the engine raises
                # before replay); the remainder drops to the serial
                # tail below, whose rejection loop is unbounded
                GLOBAL.note("priority-scan-sample-overflow", len(rest))
                break
            failed.extend(f)
            if escape_at is None:
                rest = []
                break
            escapes += 1
            f2, d2 = self._schedule_pods_oracle(
                [rest[escape_at]], defer_victims=True
            )
            failed.extend(f2)
            deferred.extend(d2)
            rest = rest[escape_at + 1 :]
            if escapes >= MAX_SCAN_ESCAPES:
                # escape-heavy batch: each escape rescans the remainder,
                # so past this point one serial pass is cheaper
                break
        if rest:
            GLOBAL.note("priority-scan-serial-tail", len(rest))
            f4, d4 = self._schedule_pods_oracle(rest, defer_victims=True)
            failed.extend(f4)
            deferred.extend(d4)
        if deferred:
            f3, _ = self._schedule_pods_oracle(deferred)
            failed.extend(f3)
        GLOBAL.note("engine", "priority-scan")
        GLOBAL.note("priority-scan-rounds", rounds)
        GLOBAL.note("priority-scan-escapes", escapes)
        return failed

    def _schedule_pods_oracle(
        self, pods: List[dict], defer_victims: bool = False
    ) -> tuple:
        """Returns (failed, deferred_victims). With defer_victims,
        preemption victims are returned instead of re-enqueued — the
        hybrid path re-enqueues them after its scan segment."""
        from collections import deque

        failed: List[UnscheduledPod] = []
        deferred: List[dict] = []
        queue = deque(pods)
        scheduled = 0
        while queue:
            if self.budget is not None and scheduled % 128 == 0:
                self.budget.check(
                    f"serial scheduling ({scheduled}/{len(pods)} pods)"
                )
            scheduled += 1
            pod = queue.popleft()
            if (pod.get("spec") or {}).get("nodeName"):
                self.oracle.place_existing_pod(pod)
                self.cluster_pods.append(pod)
                continue
            node_name, reason = self.oracle.schedule_pod(pod)
            if node_name is None:
                failed.append(UnscheduledPod(pod=pod, reason=reason))
            else:
                self.cluster_pods.append(pod)
            # victims evicted by DefaultPreemption rejoin the queue at
            # the back (their controller would recreate them; the
            # scheduler then re-places or fails them). Victims arrive
            # in MoreImportantPod order. Termination: a victim's
            # priority is strictly below its preemptor's, so eviction
            # chains strictly descend.
            for ev in self.oracle.drain_preempted():
                self._events.append(
                    PreemptionEvent(
                        victim=ev.pod, node_name=ev.node_name, preemptor=ev.preemptor
                    )
                )
                for i, p in enumerate(self.cluster_pods):
                    if p is ev.pod:
                        self.cluster_pods.pop(i)
                        break
                (deferred if defer_victims else queue).append(ev.pod)
        return failed, deferred

    def _schedule_pods_tpu(self, pods: List[dict]) -> List[UnscheduledPod]:
        """JAX scan path. Pods keep their order (pinned pods are forced
        placements inside the scan)."""
        failed, _ = self._scan_and_commit(pods)
        return failed

    def _scan_and_commit(self, pods: List[dict], escape_if=None):
        """Scan a batch and replay the placements onto the oracle in
        order. Returns `(failed, escape_index)`.

        Without `escape_if` the whole batch commits and escape_index is
        None. With it, the replay stops at the first unpinned pod that
        failed AND satisfies `escape_if(pod, index)` — the prefix before it is
        committed (scan placements are serial-identical up to there),
        and its index into `pods` is returned so the caller can handle
        that pod serially and rescan the remainder: the scan computed
        later placements against a state the serial escape is about to
        change, so they are discarded, and pods after the escape point
        (including pins and dangling pods) are left untouched for the
        next round."""
        from .engine import TpuEngine

        # pods pinned to unknown nodes never reach the scheduler
        # (reference: created in the tracker, no bind event)
        batch = []  # (orig_idx, pod) that the scan engine sees
        dangling_idx = set()
        for i, p in enumerate(pods):
            name = (p.get("spec") or {}).get("nodeName")
            if name and name not in self.oracle.node_index:
                dangling_idx.add(i)
            else:
                batch.append((i, p))
        placements = []
        if batch:
            if self._engine is None or self._engine.oracle is not self.oracle:
                self._engine = TpuEngine(self.oracle)
            placements = self._engine.schedule([p for _, p in batch])
        escape_at = None
        if escape_if is not None:
            for (i, p), idx in zip(batch, placements):
                if (
                    int(idx) < 0
                    and not (p.get("spec") or {}).get("nodeName")
                    and escape_if(p, i)
                ):
                    escape_at = i
                    break
        by_idx = {i: int(idx) for (i, _), idx in zip(batch, placements)}
        pos_of = {i: pos for pos, (i, _) in enumerate(batch)}
        if escape_at is not None and self.oracle.select_host == "sample":
            # the scan consumed Go-RNG draws for the DISCARDED tail
            # too: rewind the stream to just before the escaped pod so
            # its serial cycle (and the rescan after it) continue the
            # exact serial sequence
            self._engine.rewind_sample_rng(pos_of[escape_at])
        failed: List[UnscheduledPod] = []
        stop = len(pods) if escape_at is None else escape_at
        for i in range(stop):
            pod = pods[i]
            if i in dangling_idx:
                self.cluster_pods.append(pod)
            elif (pod.get("spec") or {}).get("nodeName"):
                self.oracle.place_existing_pod(pod)
                self.cluster_pods.append(pod)
            elif by_idx[i] < 0:
                # oracle state here equals the scan state at this step
                # (commits are replayed in order), so reasons are exact
                _, reasons, _ = self.oracle._find_feasible(pod)
                failed.append(
                    UnscheduledPod(pod=pod, reason=Oracle._failure_message(pod, reasons))
                )
            else:
                self._engine.commit_host_at(pod, by_idx[i], pos_of[i])
                self.cluster_pods.append(pod)
        return failed, escape_at

    def node_status(self) -> List[NodeStatus]:
        out = []
        for ns in self.oracle.nodes:
            out.append(NodeStatus(node=ns.node, pods=list(ns.pods)))
        return out


def simulate(
    cluster: ResourceTypes,
    apps: List[AppResource],
    engine: str = "oracle",
    use_greed: bool = False,
    extenders=None,
    score_weights=None,
    select_host: str = "first-max",
    enable_preemption: bool = True,
    rng=None,
    budget=None,
) -> SimulateResult:
    """One-shot simulation (core.go:64-103). `budget` (runtime/budget)
    is checked between apps and between serial pod commits; on expiry
    or SIGINT the raised ExecutionHalted names the boundary."""
    sim = Simulator(
        engine=engine,
        use_greed=use_greed,
        extenders=extenders,
        score_weights=score_weights,
        select_host=select_host,
        enable_preemption=enable_preemption,
        rng=rng,
        budget=budget,
    )
    # NOTE: the identity memos are deliberately NOT cleared here — the
    # planner's serial bisection calls simulate() once per guess over
    # the same object graphs and relies on warm caches. The planner
    # entry points (Applier.run, probe_plan) clear at their boundary;
    # long-lived embedders calling simulate() directly should call
    # utils.memo.clear_all_memos() between runs to release the caches'
    # strong refs to pod/node sub-objects.
    cluster = cluster.copy()
    failed: List[UnscheduledPod] = []
    preemptions: List[PreemptionEvent] = []
    result = sim.run_cluster(cluster)
    failed.extend(result.unscheduled_pods)
    preemptions.extend(result.preemptions)
    for app in apps:
        if budget is not None:
            budget.check(f"app boundary ({app.name})")
        result = sim.schedule_app(app)
        failed.extend(result.unscheduled_pods)
        preemptions.extend(result.preemptions)
    return SimulateResult(
        unscheduled_pods=failed,
        node_status=sim.node_status(),
        preemptions=preemptions,
    )
