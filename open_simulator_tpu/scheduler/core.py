"""Simulate facade: one-shot cluster + ordered app deployment.

Mirrors pkg/simulator/core.go:64-103 (Simulate) and the relevant parts of
pkg/simulator/simulator.go:
- cluster workloads (incl. per-node daemonset pods) are expanded and
  scheduled first (RunCluster -> syncClusterResourceList -> schedulePods)
- then each app in configured order (ScheduleApp): expand, sort by the
  affinity/toleration queues, schedule serially
- pods that fail to schedule are removed from the cluster and reported
  with their reason (simulator.go:231-240)

Deviation (documented): the reference sorts app pods with Go sort.Sort
and comparators that are not strict weak orders (pkg/algo/affinity.go:21,
toleration.go:19), yielding an arbitrary deterministic permutation. We
use stable sorts with the evident intent: pods with nodeSelector first,
then pods with tolerations first.

The `engine` argument selects the scheduling backend:
- "oracle": the serial Python reference implementation
- "tpu": the JAX sequential-commit scan (ops/scan.py), which must agree
  with the oracle placement-for-placement
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..models.decode import ResourceTypes
from ..models import workloads as wl
from .oracle import Oracle


# shortest zero-priority run worth routing through the batch engine —
# encode + device relay have fixed cost, so short runs are cheaper
# serially (tests lower this to exercise the hybrid on tiny batches)
MIN_SCAN_RUN = 64


@dataclass
class UnscheduledPod:
    pod: dict
    reason: str


@dataclass
class NodeStatus:
    node: dict
    pods: List[dict] = field(default_factory=list)


@dataclass
class PreemptionEvent:
    """One DefaultPreemption eviction: `victim` was removed from
    `node_name` to make room for `preemptor` (pod name)."""

    victim: dict
    node_name: str
    preemptor: str


@dataclass
class SimulateResult:
    unscheduled_pods: List[UnscheduledPod] = field(default_factory=list)
    node_status: List[NodeStatus] = field(default_factory=list)
    preemptions: List[PreemptionEvent] = field(default_factory=list)

    @property
    def all_scheduled(self) -> bool:
        return not self.unscheduled_pods


@dataclass
class AppResource:
    name: str
    resource: ResourceTypes


def _sort_app_pods(pods: List[dict]) -> List[dict]:
    from .queues import affinity_sort, toleration_sort

    return toleration_sort(affinity_sort(pods))


class Simulator:
    """In-memory cluster + serial scheduler (the fake apiserver +
    scheduler goroutine of the reference collapse into this object)."""

    def __init__(
        self,
        engine: str = "oracle",
        use_greed: bool = False,
        extenders=None,
        score_weights=None,
        select_host: str = "first-max",
    ):
        self.engine_kind = engine
        self.use_greed = use_greed
        # KubeSchedulerConfiguration score-plugin weights
        # (scheduler/schedconfig.py); None = default profile
        self.score_weights = score_weights
        # selectHost tie rule (oracle.py module docstring): "sample"
        # consumes a host RNG per tie, so it forces the serial path
        self.select_host = select_host
        # HTTP extenders are host RPC per pod: they force the serial
        # oracle path (SURVEY.md §2.3 host-callback escape hatch)
        self.extenders = list(extenders or [])
        if self.extenders or select_host == "sample":
            self.engine_kind = "oracle"
        self.oracle: Optional[Oracle] = None
        self.cluster_pods: List[dict] = []
        self._engine = None  # TpuEngine, created once per cluster
        self._events: List[PreemptionEvent] = []  # preemptions this batch

    # RunCluster (simulator.go:159-164)
    def run_cluster(self, cluster: ResourceTypes) -> SimulateResult:
        self.oracle = Oracle(
            cluster.nodes,
            extenders=self.extenders,
            pdbs=cluster.pod_disruption_budgets,
            priority_classes=cluster.priority_classes,
            score_weights=self.score_weights,
            select_host=self.select_host,
        )
        pods = wl.pods_excluding_daemon_sets(cluster)
        for ds in cluster.daemon_sets:
            pods.extend(wl.pods_from_daemon_set(ds, cluster.nodes))
        return self._schedule_pods(pods)

    # ScheduleApp (simulator.go:166-184)
    def schedule_app(self, app: AppResource) -> SimulateResult:
        nodes = [ns.node for ns in self.oracle.nodes]
        pods = wl.generate_valid_pods_from_app(app.name, app.resource, nodes)
        if self.use_greed:
            from .queues import greed_sort

            pods = greed_sort(nodes, pods)
        pods = _sort_app_pods(pods)
        # PrioritySort (queuesort/priority_sort.go:41-45): priority
        # desc, ties by queue arrival — our arrival order is the
        # affinity/toleration-sorted order, so a stable sort keeps it.
        # (In the reference this Less never reorders anything: the
        # serial handshake keeps at most one pod in the active queue.)
        # Applied only when a priority signal exists, so the no-priority
        # case keeps the reference's exact list order; nodeName-bound
        # pods commit first — their capacity is occupied regardless of
        # queue order, and sorting a pending pod ahead of them would
        # let it bind into capacity they already hold.
        from .preemption import pod_uses_priority

        if self.oracle.saw_priority or any(
            pod_uses_priority(p, self.oracle._prio_resolver) for p in pods
        ):
            bound = [p for p in pods if (p.get("spec") or {}).get("nodeName")]
            pending = [p for p in pods if not (p.get("spec") or {}).get("nodeName")]
            pending.sort(key=lambda p: -self.oracle.pod_priority(p))
            pods = bound + pending
        return self._schedule_pods(pods)

    def _schedule_pods(self, pods: List[dict]) -> SimulateResult:
        # Engine routing (VERDICT r1 #3 / r2 weak #4): the JAX scan has
        # no preemption semantics, so priority signals route to the
        # oracle — but only the pods that need it. A batch with a
        # priority signal is split around its longest zero-priority run
        # (the 100k-pod capacity plan with three priority pods keeps
        # the fused kernel for the 100k).
        from .preemption import pod_uses_priority
        from ..utils.trace import GLOBAL

        # a permit reject or a stateful plugin hook on the selected node
        # would invalidate / miss every later placement the batched scan
        # committed (plugins.py: needs_serial)
        tpu_ok = self.engine_kind == "tpu" and not self.oracle.registry.needs_serial
        priority_free = tpu_ok and (
            not self.oracle.saw_priority
            and not any(pod_uses_priority(p, self.oracle._prio_resolver) for p in pods)
        )
        split = None if priority_free or not tpu_ok else self._zero_priority_run(pods)
        if priority_free:
            GLOBAL.note("engine", "batch")
            failed = self._schedule_pods_tpu(pods)
        elif split is not None:
            # _schedule_pods_hybrid notes "hybrid" or "hybrid-serial"
            # once it knows whether the mid segment actually scanned
            failed = self._schedule_pods_hybrid(pods, split)
        else:
            GLOBAL.note("engine", "serial-oracle")
            failed, _ = self._schedule_pods_oracle(pods)
        events = self._events
        self._events = []
        return SimulateResult(
            unscheduled_pods=failed,
            node_status=self.node_status(),
            preemptions=events,
        )

    def _zero_priority_run(self, pods: List[dict]):
        """Longest contiguous run of pods with effective priority 0, as
        (start, end), or None when shorter than MIN_SCAN_RUN. Zero-prio
        pods can neither be reordered by PrioritySort (the stable sort
        keeps their relative order) nor preempt anything unless a
        negative-priority pod is committed — checked at dispatch time."""
        from .preemption import pod_uses_priority

        resolver = self.oracle._prio_resolver
        best = (0, 0)
        start = None
        for i, p in enumerate(pods):
            if not pod_uses_priority(p, resolver):
                if start is None:
                    start = i
            elif start is not None:
                if i - start > best[1] - best[0]:
                    best = (start, i)
                start = None
        if start is not None and len(pods) - start > best[1] - best[0]:
            best = (start, len(pods))
        return best if best[1] - best[0] >= MIN_SCAN_RUN else None

    def _schedule_pods_hybrid(self, pods, split) -> List[UnscheduledPod]:
        """Scan-or-serial prefix, scan the zero-priority run, serial
        suffix. Exact queue equivalence with the full serial run:
        victims evicted during the prefix would rejoin the serial queue
        BEHIND the suffix pods (they append to the back), so they are
        deferred into the final serial segment in eviction order.

        The priority prefix itself first rides the scan optimistically:
        preemption (the one semantic the scan lacks) only triggers when
        a pod FAILS to place, so a prefix the scan places completely is
        placement-identical to the serial cycle (engine conformance) —
        a serial cycle costs ~0.5 s at 10k nodes, the scan ~0.1 s for
        the whole prefix. Any failure discards the attempt and replays
        the prefix serially with full preemption."""
        from .preemption import pod_uses_priority
        from ..utils.trace import GLOBAL

        start, end = split
        head = pods[:start]
        mid, tail = pods[start:end], list(pods[end:])
        failed: List[UnscheduledPod] = []
        deferred: List[dict] = []

        # fused fast path: when the head carries no NEGATIVE priority
        # (so its commits cannot arm later preemption) and nothing
        # negative is committed, head+mid ride ONE scan — aborting only
        # if a PRIORITY pod fails to place (the one event that would
        # have preempted serially). A zero-priority failure commits
        # normally: with min committed priority >= 0 the serial cycle
        # would just record the failure too.
        fused_aborted = False
        if (
            head
            and self.oracle._min_prio >= 0
            and all(self.oracle.pod_priority(p) >= 0 for p in head)
        ):
            resolver = self.oracle._prio_resolver
            fused = self._scan_and_commit(
                head + mid,
                all_or_nothing=True,
                abort_if=lambda p: pod_uses_priority(p, resolver),
            )
            if fused is not None:
                GLOBAL.note("engine", "hybrid")
                GLOBAL.note("hybrid-head", "scan-fused")
                f2, _ = self._schedule_pods_oracle(tail)
                return fused + f2
            # the abort means a priority pod failed; a head-only scan
            # from the same state would fail the same pod (sequential
            # prefix identity), so go straight to the serial replay
            fused_aborted = True
        if head:
            if not fused_aborted and self._try_scan_segment(head):
                GLOBAL.note("hybrid-head", "scan")
            else:
                GLOBAL.note("hybrid-head", "serial")
                failed, deferred = self._schedule_pods_oracle(
                    head, defer_victims=True
                )
        # a zero-priority pod can preempt only a committed pod with
        # negative priority (PostFilter gate: prio > min committed);
        # if one exists the run must stay serial for exactness
        if self.oracle._min_prio >= 0:
            GLOBAL.note("engine", "hybrid")
            failed.extend(self._schedule_pods_tpu(mid))
        else:
            GLOBAL.note("engine", "hybrid-serial")
            tail = mid + tail
        f2, _ = self._schedule_pods_oracle(tail + deferred)
        failed.extend(f2)
        return failed

    def _try_scan_segment(self, pods: List[dict]) -> bool:
        """Optimistically place a segment through the scan engine;
        commit and return True only when every schedulable pod placed —
        the case where the serial cycle could not have preempted either,
        so the placements are identical by engine conformance. Commits
        nothing and returns False otherwise (caller replays serially)."""
        return self._scan_and_commit(pods, all_or_nothing=True) is not None

    def _schedule_pods_oracle(
        self, pods: List[dict], defer_victims: bool = False
    ) -> tuple:
        """Returns (failed, deferred_victims). With defer_victims,
        preemption victims are returned instead of re-enqueued — the
        hybrid path re-enqueues them after its scan segment."""
        from collections import deque

        failed: List[UnscheduledPod] = []
        deferred: List[dict] = []
        queue = deque(pods)
        while queue:
            pod = queue.popleft()
            if (pod.get("spec") or {}).get("nodeName"):
                self.oracle.place_existing_pod(pod)
                self.cluster_pods.append(pod)
                continue
            node_name, reason = self.oracle.schedule_pod(pod)
            if node_name is None:
                failed.append(UnscheduledPod(pod=pod, reason=reason))
            else:
                self.cluster_pods.append(pod)
            # victims evicted by DefaultPreemption rejoin the queue at
            # the back (their controller would recreate them; the
            # scheduler then re-places or fails them). Victims arrive
            # in MoreImportantPod order. Termination: a victim's
            # priority is strictly below its preemptor's, so eviction
            # chains strictly descend.
            for ev in self.oracle.drain_preempted():
                self._events.append(
                    PreemptionEvent(
                        victim=ev.pod, node_name=ev.node_name, preemptor=ev.preemptor
                    )
                )
                for i, p in enumerate(self.cluster_pods):
                    if p is ev.pod:
                        self.cluster_pods.pop(i)
                        break
                (deferred if defer_victims else queue).append(ev.pod)
        return failed, deferred

    def _schedule_pods_tpu(self, pods: List[dict]) -> List[UnscheduledPod]:
        """JAX scan path. Pods keep their order (pinned pods are forced
        placements inside the scan)."""
        return self._scan_and_commit(pods)

    def _scan_and_commit(
        self,
        pods: List[dict],
        all_or_nothing: bool = False,
        abort_if=None,
    ):
        """Scan a batch and replay the placements onto the oracle.
        Returns the failed pods, or None — nothing committed — when
        `all_or_nothing` is set and a schedulable pod failed (the
        optimistic hybrid contract). `abort_if(pod)` narrows which
        failures abort: the fused head+mid path aborts only on a
        priority pod's failure (the one that would have preempted)."""
        from .engine import TpuEngine

        # pods pinned to unknown nodes never reach the scheduler
        # (reference: created in the tracker, no bind event)
        batch, dangling = [], []
        for p in pods:
            name = (p.get("spec") or {}).get("nodeName")
            if name and name not in self.oracle.node_index:
                dangling.append(p)
            else:
                batch.append(p)
        placements = []
        if batch:
            if self._engine is None or self._engine.oracle is not self.oracle:
                self._engine = TpuEngine(self.oracle)
            placements = self._engine.schedule(batch)
            if all_or_nothing and any(
                int(idx) < 0
                and not (p.get("spec") or {}).get("nodeName")
                and (abort_if is None or abort_if(p))
                for p, idx in zip(batch, placements)
            ):
                return None
        self.cluster_pods.extend(dangling)
        failed: List[UnscheduledPod] = []
        for pod, node_idx in zip(batch, placements):
            if (pod.get("spec") or {}).get("nodeName"):
                self.oracle.place_existing_pod(pod)
                self.cluster_pods.append(pod)
            elif node_idx < 0:
                # oracle state here equals the scan state at this step
                # (commits are replayed in order), so reasons are exact
                _, reasons, _ = self.oracle._find_feasible(pod)
                failed.append(
                    UnscheduledPod(pod=pod, reason=Oracle._failure_message(pod, reasons))
                )
            else:
                self._engine.commit_host(pod, int(node_idx))
                self.cluster_pods.append(pod)
        return failed

    def node_status(self) -> List[NodeStatus]:
        out = []
        for ns in self.oracle.nodes:
            out.append(NodeStatus(node=ns.node, pods=list(ns.pods)))
        return out


def simulate(
    cluster: ResourceTypes,
    apps: List[AppResource],
    engine: str = "oracle",
    use_greed: bool = False,
    extenders=None,
    score_weights=None,
    select_host: str = "first-max",
) -> SimulateResult:
    """One-shot simulation (core.go:64-103)."""
    sim = Simulator(
        engine=engine,
        use_greed=use_greed,
        extenders=extenders,
        score_weights=score_weights,
        select_host=select_host,
    )
    # NOTE: the identity memos are deliberately NOT cleared here — the
    # planner's serial bisection calls simulate() once per guess over
    # the same object graphs and relies on warm caches. The planner
    # entry points (Applier.run, probe_plan) clear at their boundary;
    # long-lived embedders calling simulate() directly should call
    # utils.memo.clear_all_memos() between runs to release the caches'
    # strong refs to pod/node sub-objects.
    cluster = cluster.copy()
    failed: List[UnscheduledPod] = []
    preemptions: List[PreemptionEvent] = []
    result = sim.run_cluster(cluster)
    failed.extend(result.unscheduled_pods)
    preemptions.extend(result.preemptions)
    for app in apps:
        result = sim.schedule_app(app)
        failed.extend(result.unscheduled_pods)
        preemptions.extend(result.preemptions)
    return SimulateResult(
        unscheduled_pods=failed,
        node_status=sim.node_status(),
        preemptions=preemptions,
    )
