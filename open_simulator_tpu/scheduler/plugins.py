"""Custom scheduling-plugin extension API.

The reference's headline extensibility is the scheduler-framework
out-of-tree plugin registry (pkg/simulator/simulator.go:127-137 +
GetAndSetSchedulerConfig injecting Simon/Open-Local/Open-Gpu-Share into
the plugin sets). The TPU engine's equivalent: a registry of
*stateless* host plugins whose verdicts are evaluated once per pod
class and folded into the scan's static tensors —

    class MyPlugin(SchedulerPlugin):
        name = "My-Plugin"
        weight = 1
        def filter(self, pod, node) -> bool: ...
        def score(self, pod, node) -> int: ...      # raw 0..100
        normalize = "none" | "default" | "reverse" | "minmax"

`filter` ANDs into the static feasibility matrix; `score` is
normalized over the feasible set in-scan like the built-ins
(DefaultNormalizeScore / min-max, helper semantics of
vendor/.../plugins/helper/normalize_score.go and plugin/simon.go:75).

Stateless means: the verdict may depend on the pod and the node's
static definition, not on placements made during the run — the same
contract the reference's Filter plugins get from the immutable cycle
snapshot, minus pod-derived state. Stateful custom plugins (like the
built-in GPU/storage/affinity machinery) need tensor state in the scan
carry and are built-in only.

The serial oracle honors the same registry, so conformance between the
two paths holds for custom plugins too.
"""

from __future__ import annotations

from typing import Dict, List, Optional

NORMALIZE_MODES = ("none", "default", "reverse", "minmax")


class SchedulerPlugin:
    """Base class for out-of-tree plugins."""

    name: str = "Custom"
    weight: int = 1
    normalize: str = "none"

    def filter(self, pod: dict, node: dict) -> bool:  # pragma: no cover - interface
        return True

    def score(self, pod: dict, node: dict) -> int:  # pragma: no cover - interface
        return 0

    def permit(self, pod: dict, node: dict) -> bool:  # pragma: no cover - interface
        """Permit extension point (framework interface.go:470-489,
        RunPermitPlugins at scheduler.go:536-553): a last allow/reject
        gate on the SELECTED node. Rejecting fails the pod's cycle
        outright — unlike `filter`, the scheduler does not retry other
        nodes. The reference runs Permit after Reserve and unreserves
        on reject; the oracle runs it just before its combined
        reserve+bind step, which leaves identical net state (plugins
        here see only the raw pod/node dicts, never reserved state).
        `wait` verdicts are meaningless in a simulator (there is no
        clock) and are not modeled. A batch with a permit-defining
        plugin routes to the serial engine: a post-hoc reject would
        invalidate every later placement the batched scan made against
        the committed state."""
        return True


class PluginRegistry:
    def __init__(self):
        self._plugins: Dict[str, SchedulerPlugin] = {}

    def register(self, plugin: SchedulerPlugin):
        if plugin.normalize not in NORMALIZE_MODES:
            raise ValueError(
                f"plugin {plugin.name}: invalid normalize mode {plugin.normalize!r}"
            )
        self._plugins[plugin.name] = plugin

    def unregister(self, name: str):
        self._plugins.pop(name, None)

    def clear(self):
        self._plugins.clear()

    @property
    def plugins(self) -> List[SchedulerPlugin]:
        return list(self._plugins.values())

    @property
    def has_permit(self) -> bool:
        """Whether any registered plugin overrides `permit` (forces the
        serial engine — see SchedulerPlugin.permit)."""
        return any(
            type(p).permit is not SchedulerPlugin.permit
            for p in self._plugins.values()
        )


# process-global out-of-tree registry (WithFrameworkOutOfTreeRegistry
# analogue); simulate()/Applier consult it
default_registry = PluginRegistry()
