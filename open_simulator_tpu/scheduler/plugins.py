"""Custom scheduling-plugin extension API.

The reference's headline extensibility is the scheduler-framework
out-of-tree plugin registry (pkg/simulator/simulator.go:127-137 +
GetAndSetSchedulerConfig injecting Simon/Open-Local/Open-Gpu-Share into
the plugin sets). The TPU engine's equivalent: a registry of
*stateless* host plugins whose verdicts are evaluated once per pod
class and folded into the scan's static tensors —

    class MyPlugin(SchedulerPlugin):
        name = "My-Plugin"
        weight = 1
        def filter(self, pod, node) -> bool: ...
        def score(self, pod, node) -> int: ...      # raw 0..100
        normalize = "none" | "default" | "reverse" | "minmax"

`filter` ANDs into the static feasibility matrix; `score` is
normalized over the feasible set in-scan like the built-ins
(DefaultNormalizeScore / min-max, helper semantics of
vendor/.../plugins/helper/normalize_score.go and plugin/simon.go:75).

Stateless means: the verdict may depend on the pod and the node's
static definition, not on placements made during the run — the same
contract the reference's Filter plugins get from the immutable cycle
snapshot, minus pod-derived state.

STATEFUL plugins (interface.go:412-524 ReservePlugin / PreBindPlugin /
PostBindPlugin / BindPlugin) are supported too: override `reserve` /
`unreserve` / `prebind` / `postbind` / `bind` and keep whatever state
you need on the plugin instance (the role the reference plugin's
informer-fed cache plays — e.g. open-gpu-share's GpuNodeInfo). A
registry containing any stateful plugin routes every batch to the
serial oracle automatically (same mechanism as `permit`): scan
placements are committed in-kernel, where a host-side veto or cache
mutation per pod cannot participate. With plugin state feeding
`filter`/`score`, such plugins behave exactly like the reference's
out-of-tree framework plugins in the serial scheduler. Two documented
deviations, both shared with the reference: preemption dry runs do not
notify plugins (the reference's dry run clones NodeInfo but not plugin
caches — they go stale the same way), and a real eviction calls
`unreserve` (the analogue of the delete informer event a live cache
would consume).

The remaining framework plugin types (round 4, VERDICT r3 missing #3):
`queue_sort_less` replaces PrioritySort (one queue-sort plugin max,
pure reordering — scan-compatible); `post_filter` replaces/augments
the preemption policy (runs before DefaultPreemption; scan batches
keep scanning and escape each FAILURE to the serial cycle so the
plugin observes exactly what the reference framework would); `bind`
replaces the binder (first non-skip verdict wins; stateful, so
serial). Together the out-of-tree surface covers every extension
point of interface.go that is meaningful without a live apiserver
(PreFilter/PreScore are folded into filter/score — the per-cycle
precompute split is a host-code optimization, not an observable
semantic).

The serial oracle honors the same registry, so conformance between the
two paths holds for custom plugins too.
"""

from __future__ import annotations

from typing import Dict, List, Optional

NORMALIZE_MODES = ("none", "default", "reverse", "minmax")


class SchedulerPlugin:
    """Base class for out-of-tree plugins."""

    name: str = "Custom"
    weight: int = 1
    normalize: str = "none"

    def filter(self, pod: dict, node: dict) -> bool:  # pragma: no cover - interface
        return True

    def score(self, pod: dict, node: dict) -> int:  # pragma: no cover - interface
        return 0

    def permit(self, pod: dict, node: dict) -> bool:  # pragma: no cover - interface
        """Permit extension point (framework interface.go:470-489,
        RunPermitPlugins at scheduler.go:536-553): a last allow/reject
        gate on the SELECTED node. Rejecting fails the pod's cycle
        outright — unlike `filter`, the scheduler does not retry other
        nodes. The reference runs Permit after Reserve and unreserves
        on reject; the oracle runs it just before its combined
        reserve+bind step, which leaves identical net state (plugins
        here see only the raw pod/node dicts, never reserved state).
        `wait` verdicts are meaningless in a simulator (there is no
        clock) and are not modeled. A batch with a permit-defining
        plugin routes to the serial engine: a post-hoc reject would
        invalidate every later placement the batched scan made against
        the committed state."""
        return True

    # -- stateful extension points (serial path only) -------------------
    #
    # Lifecycle: a fresh Oracle (one per simulate()/probe run) calls
    # `begin_run` — clear per-run caches there, the way the reference
    # constructs plugins fresh via their factory per scheduler run.
    # Pre-bound cluster pods are admitted through `reserve` with the
    # veto ignored (the tracker's unconditional add / informer ADD
    # event); evictions arrive as `unreserve`. So a cache that charges
    # in reserve and releases in unreserve stays balanced across
    # admission, scheduling, preemption, and re-scheduling.

    def begin_run(self, nodes: List[dict]) -> None:  # pragma: no cover - interface
        """Called by each new Oracle before any pod is admitted —
        reset per-run plugin state here (the factory-construction
        analogue of the reference framework)."""

    def reserve(self, pod: dict, node: dict) -> bool:  # pragma: no cover - interface
        """ReservePlugin.Reserve (interface.go:412-424): claim plugin
        state for the pod on the selected node. Returning False fails
        the pod's cycle; every already-reserved plugin is unreserved in
        reverse registration order (RunReservePluginsReserve,
        framework.go error path)."""
        return True

    def unreserve(self, pod: dict, node: dict) -> None:  # pragma: no cover - interface
        """ReservePlugin.Unreserve (interface.go:426-431): roll back
        `reserve`. Called when a later reserve/permit/prebind phase
        fails, and when a committed pod is evicted by preemption (the
        analogue of the cache's pod-delete informer event)."""

    def prebind(self, pod: dict, node: dict) -> bool:  # pragma: no cover - interface
        """PreBindPlugin.PreBind (interface.go:462-468): last plugin
        work before the bind is recorded (the reference open-gpu-share
        patches the pod's GPU annotation here). Returning False fails
        the cycle and unreserves."""
        return True

    def postbind(self, pod: dict, node: dict) -> None:  # pragma: no cover - interface
        """PostBindPlugin.PostBind (interface.go:491-497):
        informational; runs after a successful bind."""

    def queue_sort_less(self, pod_a: dict, pod_b: dict) -> bool:  # pragma: no cover
        """QueueSortPlugin.Less (interface.go:292-303): True when pod_a
        should schedule before pod_b. A plugin overriding this REPLACES
        the default PrioritySort ordering of each app's pending pods
        (the framework allows exactly one enabled queue-sort plugin —
        registering a second raises). Must be a strict weak ordering,
        like the reference's Less functions. Queue sorting is pure
        reordering, so batches still ride the scan engines."""
        raise NotImplementedError

    def post_filter(self, pod: dict, ctx) -> Optional[str]:  # pragma: no cover
        """PostFilterPlugin (interface.go:330-350): runs when `pod`
        failed every node; may mutate the cluster through `ctx`
        (a PostFilterContext: `.nodes`, `.pods_on(node_name)`,
        `.evict(pod, node_name)`) and return a node name to retry on,
        or None for Unschedulable. Custom post-filter plugins run in
        registration order BEFORE the built-in DefaultPreemption; the
        first non-None wins and DefaultPreemption is skipped for that
        pod (the framework runs PostFilter plugins until the first
        Success). Scan batches stay on the scan: every scan failure
        takes the serial escape hatch when a post-filter plugin is
        registered, so the plugin observes exactly the serial cycle."""
        return None

    def bind(self, pod: dict, node: dict) -> str:  # pragma: no cover - interface
        """BindPlugin.Bind (interface.go:499-524): handle the bind
        yourself. Return "success" (bind handled — the simulator still
        records the placement locally so the run keeps tracking it,
        exactly like binder extenders), "skip" (let the next bind
        plugin or the default binder handle it), or "error" (fail the
        pod's cycle; reserved plugins unreserve in reverse order).
        Bind-capable plugins are stateful: batches route to the serial
        oracle."""
        return "skip"


class PluginRegistry:
    def __init__(self):
        self._plugins: Dict[str, SchedulerPlugin] = {}

    def register(self, plugin: SchedulerPlugin):
        if plugin.normalize not in NORMALIZE_MODES:
            raise ValueError(
                f"plugin {plugin.name}: invalid normalize mode {plugin.normalize!r}"
            )
        overrides_qs = (
            type(plugin).queue_sort_less is not SchedulerPlugin.queue_sort_less
        )
        if overrides_qs and any(
            type(p).queue_sort_less is not SchedulerPlugin.queue_sort_less
            for n, p in self._plugins.items()
            if n != plugin.name
        ):
            # framework.go NewFramework: "only one queue sort plugin
            # can be enabled"
            raise ValueError(
                f"plugin {plugin.name}: a queue-sort plugin is already registered"
            )
        self._plugins[plugin.name] = plugin

    def unregister(self, name: str):
        self._plugins.pop(name, None)

    def clear(self):
        self._plugins.clear()

    @property
    def plugins(self) -> List[SchedulerPlugin]:
        return list(self._plugins.values())

    def _overrides(self, method: str) -> bool:
        return any(
            getattr(type(p), method) is not getattr(SchedulerPlugin, method)
            for p in self._plugins.values()
        )

    @property
    def has_permit(self) -> bool:
        """Whether any registered plugin overrides `permit` (forces the
        serial engine — see SchedulerPlugin.permit)."""
        return self._overrides("permit")

    @property
    def has_stateful(self) -> bool:
        """Whether any plugin overrides a stateful extension point
        (reserve/unreserve/prebind/postbind/bind)."""
        return any(
            self._overrides(m)
            for m in ("reserve", "unreserve", "prebind", "postbind", "bind")
        )

    @property
    def queue_sort_plugin(self) -> Optional[SchedulerPlugin]:
        for p in self._plugins.values():
            if type(p).queue_sort_less is not SchedulerPlugin.queue_sort_less:
                return p
        return None

    @property
    def has_post_filter(self) -> bool:
        return self._overrides("post_filter")

    @property
    def post_filter_plugins(self) -> List[SchedulerPlugin]:
        return [
            p
            for p in self._plugins.values()
            if type(p).post_filter is not SchedulerPlugin.post_filter
        ]

    @property
    def bind_plugins(self) -> List[SchedulerPlugin]:
        return [
            p
            for p in self._plugins.values()
            if type(p).bind is not SchedulerPlugin.bind
        ]

    def begin_run(self, nodes: List[dict]) -> None:
        for p in self._plugins.values():
            p.begin_run(nodes)

    @property
    def needs_serial(self) -> bool:
        """True when the registry cannot ride the batched scan: permit
        vetoes and stateful hooks both act per pod on the host."""
        return self.has_permit or self.has_stateful


# process-global out-of-tree registry (WithFrameworkOutOfTreeRegistry
# analogue); simulate()/Applier consult it
default_registry = PluginRegistry()
