"""AOT compiled-cost ledger: what each compiled executable costs.

The flight recorder (obs/profile.py) counts *that* a dispatch happened;
this module records *what it costs*: for every ``InstrumentedJit``
site, the first call of each shape-signature lowers and compiles the
function ahead of time (``jit(...).lower(...).compile()``) and extracts

- ``cost_analysis()``: FLOPs and bytes accessed of the compiled
  executable, and
- ``memory_analysis()``: argument / output / temp / generated-code
  bytes — the compiler's own statement of how much device memory one
  dispatch of this shape needs.

The AOT artifact is then REUSED for the dispatch itself (the first
step toward ROADMAP item 4's persisted compile cache: the executable
exists as a named object keyed by shape-signature, not an invisible
entry in the pjit cache), so cost capture adds zero extra compiles.
Records land in a process-wide registry exported as ``Counters``
gauges (``jax_cost_*``), a ``costs`` sub-block in every bench obs
line, and ``simon_jax_cost_*`` lines in serve ``/metrics``.

The memory ledger (obs/ledger.py) reads ``estimate_bytes`` /
``chunk_estimator`` to predict whether a dispatch will fit in device
memory BEFORE launching it — the predictive half of the degradation
ladder (runtime/guard.py).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from ..utils.trace import COUNTERS


@dataclass
class CostRecord:
    """One compiled executable's cost/memory analysis. ``lead_dim`` is
    the compile's row count along the CHUNKED axis (the batched
    argument's leading dimension when the site declares one via
    ``instrument_jit(lead_argnum=...)``, else the largest leading
    dimension among all array leaves) — the scaling proxy
    ``estimate_bytes`` uses to extrapolate a chunk of a different row
    count from a known compile."""

    site: str
    flops: float = 0.0
    bytes_accessed: float = 0.0
    argument_bytes: int = 0
    output_bytes: int = 0
    temp_bytes: int = 0
    generated_code_bytes: int = 0
    lead_dim: int = 0

    @property
    def workspace_bytes(self) -> int:
        """Device bytes one dispatch allocates beyond its arguments:
        outputs + XLA temp buffers."""
        return int(self.output_bytes) + int(self.temp_bytes)

    @property
    def dispatch_bytes(self) -> int:
        """Upper bound on fresh device bytes one dispatch needs when
        none of its arguments are live yet: arguments + outputs + XLA
        temp buffers. The chunked executors (guard.run_chunked callers)
        build each chunk's argument arrays AFTER the fit prediction, so
        predictions must budget for them."""
        return int(self.argument_bytes) + self.workspace_bytes

    def as_dict(self) -> dict:
        return {
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "generated_code_bytes": self.generated_code_bytes,
            "lead_dim": self.lead_dim,
        }


def _merge_cost_analysis(raw) -> dict:
    """``Compiled.cost_analysis()`` is a dict on current JAX and a
    list-of-dicts (one per computation) on older releases; merge to
    one {metric: summed value} map either way."""
    if raw is None:
        return {}
    if isinstance(raw, dict):
        entries = [raw]
    else:
        try:
            entries = [e for e in raw if isinstance(e, dict)]
        except TypeError:
            return {}
    out: dict = {}
    for e in entries:
        for k, v in e.items():
            if isinstance(v, (int, float)):
                out[k] = out.get(k, 0.0) + float(v)
    return out


def extract_record(site: str, compiled, lead_dim: int = 0) -> CostRecord:
    """Build a CostRecord from a ``jax.stages.Compiled`` artifact.
    Backends without one of the analyses (or raising NotImplemented)
    degrade to zeros for that half — the record stays usable."""
    rec = CostRecord(site=site, lead_dim=int(lead_dim))
    try:
        cost = _merge_cost_analysis(compiled.cost_analysis())
    except Exception:  # noqa: BLE001 - backend-optional analysis: absent/unimplemented on some platforms, never load-bearing
        cost = {}
    rec.flops = float(cost.get("flops", 0.0))
    rec.bytes_accessed = float(cost.get("bytes accessed", 0.0))
    try:
        mem = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 - backend-optional analysis: absent/unimplemented on some platforms, never load-bearing
        mem = None
    if mem is not None:
        rec.argument_bytes = int(
            getattr(mem, "argument_size_in_bytes", 0) or 0
        )
        rec.output_bytes = int(getattr(mem, "output_size_in_bytes", 0) or 0)
        rec.temp_bytes = int(getattr(mem, "temp_size_in_bytes", 0) or 0)
        rec.generated_code_bytes = int(
            getattr(mem, "generated_code_size_in_bytes", 0) or 0
        )
    return rec


class CostRegistry:
    """Process-wide (site, signature) -> CostRecord store plus per-site
    aggregates, mirrored into the ``Counters`` registry so serve
    ``/metrics`` and the bench harness read the same numbers."""

    def __init__(self):
        self._lock = threading.Lock()
        self._records: Dict[str, Dict[object, CostRecord]] = {}

    def record(self, site: str, sig, rec: CostRecord, loaded: bool = False) -> None:
        """``loaded`` marks a record rehydrated from the persistent
        artifact store (incremental/store.py): the executable exists
        without a compile having happened in THIS process, so it counts
        in ``jax_cost_store_loads_total`` instead of the compile
        counters — `simon doctor`'s recompile dimension stays exact."""
        with self._lock:
            self._records.setdefault(site, {})[sig] = rec
        if loaded:
            COUNTERS.inc("jax_cost_store_loads_total")
            COUNTERS.inc(f"jax_cost_store_loads_{site}")
        else:
            COUNTERS.inc("jax_cost_compiles_total")
            COUNTERS.inc(f"jax_cost_compiles_{site}")
        # last-compiled cost per site as gauges: the newest signature
        # is almost always the workload's live shape
        COUNTERS.gauge(f"jax_cost_flops_{site}", rec.flops)
        COUNTERS.gauge(f"jax_cost_bytes_accessed_{site}", rec.bytes_accessed)
        COUNTERS.gauge(f"jax_cost_argument_bytes_{site}", rec.argument_bytes)
        COUNTERS.gauge(f"jax_cost_output_bytes_{site}", rec.output_bytes)
        COUNTERS.gauge(f"jax_cost_temp_bytes_{site}", rec.temp_bytes)
        COUNTERS.gauge(
            f"jax_cost_generated_code_bytes_{site}", rec.generated_code_bytes
        )

    def on_dispatch(self, rec: CostRecord) -> None:
        """Accumulate the itemized totals a dispatch of this executable
        moves: the "what did this run actually cost" counters."""
        if rec.flops:
            COUNTERS.inc("jax_cost_flops_dispatched_total", int(rec.flops))
        if rec.bytes_accessed:
            COUNTERS.inc(
                "jax_cost_bytes_dispatched_total", int(rec.bytes_accessed)
            )

    def sites(self):
        with self._lock:
            return sorted(self._records)

    def records_for(self, site: str) -> Dict[object, CostRecord]:
        with self._lock:
            return dict(self._records.get(site, {}))

    def signatures(self, site: str) -> int:
        with self._lock:
            return len(self._records.get(site, ()))

    def reset(self) -> None:
        with self._lock:
            self._records.clear()

    def estimate_bytes(
        self, site: str, lead_dim: Optional[int] = None, shards: int = 1
    ) -> Optional[int]:
        """Predicted fresh device bytes for one dispatch of ``site``
        at ``lead_dim`` rows (None = the largest known shape),
        arguments included — the chunked executors allocate each
        chunk's argument arrays after asking, so a prediction that
        omitted them would bless dispatches whose inputs alone bust
        the budget. Exact when a record of that lead_dim exists;
        shrinking below the largest known record scales only the
        workspace (outputs + temps grow with the row count by
        construction) and keeps the argument bytes whole, an upper
        bound for the splitting direction; growing past it scales
        everything linearly. None when the site has never compiled —
        the caller falls back to the reactive ladder.

        ``shards`` > 1 asks for the PER-DEVICE bytes of a mesh-sharded
        dispatch (parallel/mesh.py): the batched axis splits across
        devices, so the workspace scales by the per-shard row count
        (ceil(lead_dim / shards)) while the argument bytes stay whole
        — the static/init pytrees replicate onto every device and
        dominate the inputs. Without this a sharded dispatch would be
        predicted at full-replica size and spuriously chunk-split or
        rung-skip."""
        recs = [r for r in self.records_for(site).values()]
        if not recs:
            return None
        if shards > 1 and lead_dim is not None:
            lead_dim = -(-int(lead_dim) // int(shards))
        if lead_dim is not None:
            exact = [r for r in recs if r.lead_dim == lead_dim]
            if exact:
                return max(r.dispatch_bytes for r in exact)
        best = max(recs, key=lambda r: r.lead_dim)
        if lead_dim is None or best.lead_dim <= 0:
            return best.dispatch_bytes
        if lead_dim <= best.lead_dim:
            return best.argument_bytes + int(
                best.workspace_bytes * (lead_dim / best.lead_dim)
            )
        return int(best.dispatch_bytes * (lead_dim / best.lead_dim))

    def chunk_estimator(
        self, site: str, shards: int = 1
    ) -> Callable[[int, int], Optional[int]]:
        """An ``estimate(lo, hi)`` callable for guard.run_chunked:
        predicted fresh device bytes (arguments + workspace) of
        dispatching rows [lo, hi) at this site (None until the site's
        first compile). ``shards`` makes the estimate per-device for a
        mesh-sharded dispatch (see estimate_bytes) — pair it with
        ``run_chunked(shards=...)`` so the ledger verdict compares
        per-device bytes against the per-device budget slice."""

        def estimate(lo: int, hi: int) -> Optional[int]:
            return self.estimate_bytes(site, hi - lo, shards=shards)

        return estimate

    def summary(self) -> dict:
        """Per-site cost table for bench obs blocks / trace artifacts:
        the max-shape record's analysis plus the signature count and
        the dispatched-flops running total."""
        out = {}
        for site in self.sites():
            recs = list(self.records_for(site).values())
            if not recs:
                continue
            best = max(recs, key=lambda r: (r.lead_dim, r.workspace_bytes))
            d = best.as_dict()
            d["signatures"] = len(recs)
            out[site] = d
        if out:
            out["_totals"] = {
                "compiles": COUNTERS.get("jax_cost_compiles_total"),
                "flops_dispatched": COUNTERS.get(
                    "jax_cost_flops_dispatched_total"
                ),
                "bytes_dispatched": COUNTERS.get(
                    "jax_cost_bytes_dispatched_total"
                ),
            }
        return out


COSTS = CostRegistry()
