"""Device-memory ledger: how much HBM is in use, peaked, and predicted.

Today the degradation ladder learns about the memory wall by CATCHING
RESOURCE_EXHAUSTED and halving (runtime/guard.py) — every OOM costs a
doomed dispatch plus a recompile at the smaller shape. This module
makes device memory a first-class observable and turns OOM handling
predictive:

- ``poll()``: current device bytes in use, from the backend's
  ``memory_stats()`` (TPU/GPU: allocator truth incl. ``bytes_limit``)
  with a live-buffer fallback (CPU: sum of ``jax.live_arrays()``
  nbytes — the backend reports no allocator stats there). Polled at
  every instrumented jit dispatch and at top-level span boundaries,
  maintaining the process peak and per-top-level-span watermarks
  ("which command phase owned the memory high-water mark").
- ``predict_fit(estimate_bytes)``: would a dispatch needing
  ``estimate_bytes`` of fresh workspace (the AOT ``memory_analysis``
  totals, obs/costs.py) fit next to what is live right now, under the
  device budget? Three-valued: True / False / None (no budget known —
  the caller stays reactive). ``guard.run_chunked`` asks before every
  chunk and splits proactively; ``guard.run_laddered`` asks per rung
  and skips rungs that cannot fit — zero doomed dispatches, with
  reactive halving unchanged underneath as the fallback.
- predicted-vs-actual counters (``ledger_predict_*``) so CI can gate
  on ledger accuracy instead of trusting it.

The budget comes from ``memory_stats()['bytes_limit']`` when the
backend reports one, else the ``SIMON_DEVICE_MEM_BUDGET`` env var
(bytes; how operators bound the CPU/test ladder), else None.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional

from ..utils.trace import COUNTERS

# fraction of the budget a predicted dispatch may fill: allocator
# fragmentation and untracked framework buffers mean "exactly fits" is
# already an OOM in practice
DEFAULT_HEADROOM = 0.92

# on backends without allocator stats, each poll enumerates EVERY live
# array in the process (jax.live_arrays()); unthrottled, a dispatch-hot
# sweep pays that sweep per dispatch and the overhead lands in the very
# latency histograms the doctor gates on — so hot-path polls on that
# source are rate-limited, while span boundaries always sample
LIVE_POLL_MIN_INTERVAL_S = 0.05

# per-device row refresh cadence for UNFORCED polls (forced polls —
# span boundaries — always refresh): the rows feed /metrics gauges and
# sharded-fit verdicts, neither of which needs per-dispatch freshness
PER_DEVICE_MIN_INTERVAL_S = 1.0


def device_memory_stats_per_device():
    """Per-device memory accounting: a list of {device, in_use, limit}
    covering EVERY local device — the mesh makes "device 0's memory"
    the wrong question, a sharded dispatch lives or dies on the
    tightest shard. ``memory_stats()`` backends report allocator truth
    per device; the live-buffer fallback (CPU) attributes each live
    array's bytes to every device holding a shard of it (committed
    sharded arrays enumerate their device set) and splits the
    SIMON_DEVICE_MEM_BUDGET budget evenly. Returns ([], source) when
    no backend is importable."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 - no backend at all: the ledger reports unknown rather than failing the caller
        return [], "unavailable"
    rows = []
    saw_stats = False
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 - some platforms raise instead of returning None
            stats = None
        if stats:
            saw_stats = True
            rows.append(
                {
                    "device": f"{d.platform}:{d.id}",
                    "in_use": int(stats.get("bytes_in_use", 0) or 0),
                    "limit": int(stats.get("bytes_limit", 0) or 0) or None,
                }
            )
    if saw_stats:
        return rows, "memory_stats"
    import jax

    per_dev = {f"{d.platform}:{d.id}": 0 for d in devices}
    for a in jax.live_arrays():
        try:
            holders = a.devices()
        except Exception:  # noqa: BLE001 - deleted/donated buffer mid-enumeration: skip it
            continue
        n_holders = max(len(holders), 1)
        for d in holders:
            key = f"{d.platform}:{d.id}"
            if key in per_dev:
                per_dev[key] += int(a.nbytes) // n_holders
    env = os.environ.get("SIMON_DEVICE_MEM_BUDGET")
    try:
        budget = int(env) if env else None
    except ValueError:
        budget = None
    per_limit = budget // max(len(devices), 1) if budget else None
    return (
        [
            {"device": k, "in_use": v, "limit": per_limit}
            for k, v in per_dev.items()
        ],
        "live_arrays",
    )


def device_memory_stats():
    """(bytes_in_use, bytes_limit, source) for the process's devices.
    ``bytes_limit``/``bytes_in_use`` sum across local devices when the
    backend reports allocator stats; otherwise in-use falls back to
    live-buffer accounting and the limit to SIMON_DEVICE_MEM_BUDGET."""
    rows, source = device_memory_stats_per_device()
    if source == "unavailable":
        return 0, None, source
    in_use = sum(r["in_use"] for r in rows)
    if source == "memory_stats":
        limit = sum(r["limit"] or 0 for r in rows)
        return in_use, (limit or None), source
    # live-buffer fallback: per-device rows split shared arrays, so the
    # process total is their sum; the limit is the whole env budget
    env = os.environ.get("SIMON_DEVICE_MEM_BUDGET")
    try:
        limit = int(env) if env else None
    except ValueError:
        limit = None
    return in_use, limit, source


class MemoryLedger:
    """Process-wide memory observatory. All mutation under one lock;
    ``poll()`` is the only device-touching call and runs outside it."""

    def __init__(self):
        self._lock = threading.Lock()
        self.peak_bytes = 0
        self.samples = 0
        self.source = "unpolled"
        # open top-level spans: frame id -> [name, peak-while-open];
        # closed frames fold into `watermarks` (max per name)
        self._frames: Dict[int, list] = {}
        self._next_frame = 1
        self.watermarks: Dict[str, int] = {}
        self._last_poll = 0.0
        self._last_in_use = 0
        # last per-device rows ({device, in_use, limit}) — every mesh
        # device, not just device 0; exported as labeled
        # simon_device_mem_*{device=...} gauges on /metrics
        self._per_device: list = []
        self._last_rows_poll = 0.0

    # -- sampling -----------------------------------------------------------

    def poll(self, force: bool = False) -> int:
        """Sample current device bytes; update the process peak, every
        open span frame, and the exported gauges. Unforced polls on the
        live-buffer source (CPU fallback — O(live arrays) per sample)
        are rate-limited to LIVE_POLL_MIN_INTERVAL_S and answer the
        last sample; allocator-stats backends and forced polls (span
        boundaries) always sample."""
        with self._lock:
            if (
                not force
                and self.source == "live_arrays"
                and time.monotonic() - self._last_poll
                < LIVE_POLL_MIN_INTERVAL_S
            ):
                return self._last_in_use
            last_rows_poll = self._last_rows_poll
        # totals through device_memory_stats (the module's test seam);
        # per-device rows refresh on forced polls and at a bounded
        # cadence otherwise — a second full device sweep per hot-path
        # poll would double the cost the rate limiter exists to bound
        in_use, limit, source = device_memory_stats()
        now = time.monotonic()
        rows = None
        if force or now - last_rows_poll >= PER_DEVICE_MIN_INTERVAL_S:
            rows, _row_source = device_memory_stats_per_device()
        with self._lock:
            self._last_poll = time.monotonic()
            self._last_in_use = in_use
            if rows is not None:
                self._per_device = rows
                self._last_rows_poll = now
            self.samples += 1
            self.source = source
            if in_use > self.peak_bytes:
                self.peak_bytes = in_use
            peak = self.peak_bytes
            for frame in self._frames.values():
                if in_use > frame[1]:
                    frame[1] = in_use
        COUNTERS.gauge("device_mem_bytes_in_use", float(in_use))
        COUNTERS.gauge("device_mem_peak_bytes", float(peak))
        if limit:
            COUNTERS.gauge("device_mem_bytes_limit", float(limit))
        return in_use

    def span_open(self, name: str) -> int:
        """Begin a top-level-span watermark frame (spans.py boundary
        hook). Returns the frame id to close with."""
        in_use = self.poll(force=True)
        with self._lock:
            fid = self._next_frame
            self._next_frame += 1
            self._frames[fid] = [name, in_use]
        return fid

    def span_close(self, fid: int) -> None:
        self.poll(force=True)
        with self._lock:
            frame = self._frames.pop(fid, None)
            if frame is None:
                return
            name, peak = frame
            if peak > self.watermarks.get(name, 0):
                self.watermarks[name] = peak

    # -- prediction ---------------------------------------------------------

    def budget_bytes(self) -> Optional[int]:
        _in_use, limit, _src = device_memory_stats()
        return limit

    def predict_fit(
        self,
        estimate_bytes: int,
        *,
        headroom: float = DEFAULT_HEADROOM,
        label: str = "",
        shards: int = 1,
    ) -> Optional[bool]:
        """Would a dispatch allocating ``estimate_bytes`` of fresh
        workspace fit right now? None when no budget is known (the
        caller must stay reactive); every real verdict is counted so
        predicted-vs-actual accuracy is a number, not a hope.

        ``shards`` > 1 means the dispatch is mesh-sharded and
        ``estimate_bytes`` is PER-DEVICE (the shard-aware chunk
        estimator, obs/costs.py): the verdict then compares it against
        the TIGHTEST device's real headroom from the per-device rows
        (a sharded dispatch lives or dies on its tightest shard) —
        never against the summed budget divided by the shard count,
        which would overstate per-device room whenever the mesh uses
        fewer devices than the host has.

        ``ledger.predict_fit`` is an injection point: a ``lie:low``
        clause answers True (everything fits — the predictive path is
        blinded, the reactive ladder must still save the run) and
        ``lie:high`` answers False (nothing fits — splits and serial
        routing happen with zero real OOMs). The lies flow through the
        same verdict counters, so ``ledger_predict_miss_total``
        records exactly how often the liar was caught."""
        from ..runtime import inject as _inject

        lie = _inject.value("ledger.predict_fit")
        if lie in ("low", "high"):
            fits = lie == "low"
            COUNTERS.inc("ledger_predictions_total")
            COUNTERS.inc(
                "ledger_predict_fit_total" if fits else "ledger_predict_unfit_total"
            )
            if not fits and label:
                COUNTERS.inc(f"ledger_predict_unfit_{label}")
            return fits
        if shards > 1:
            fits = self._fits_per_device(int(estimate_bytes), headroom)
            if fits is None:
                return None
        else:
            in_use, limit, _src = device_memory_stats()
            if not limit:
                return None
            fits = in_use + int(estimate_bytes) <= limit * headroom
        COUNTERS.inc("ledger_predictions_total")
        COUNTERS.inc(
            "ledger_predict_fit_total" if fits else "ledger_predict_unfit_total"
        )
        if not fits and label:
            COUNTERS.inc(f"ledger_predict_unfit_{label}")
        return fits

    def _fits_per_device(
        self, per_device_bytes: int, headroom: float
    ) -> Optional[bool]:
        """Would ``per_device_bytes`` fit on the TIGHTEST device? None
        when no device reports a limit (no budget known)."""
        rows, _src = device_memory_stats_per_device()
        limited = [r for r in rows if r.get("limit")]
        if not limited:
            return None
        free = min(
            r["limit"] * headroom - r["in_use"] for r in limited
        )
        return per_device_bytes <= free

    def would_fit(
        self,
        estimate_bytes: int,
        *,
        headroom: float = DEFAULT_HEADROOM,
    ) -> Optional[bool]:
        """predict_fit's verdict WITHOUT the prediction counters — for
        planning probes (parallel/mesh.py plan_layout) that correspond
        to no dispatch, so predicted-vs-actual accounting stays about
        dispatches that actually ran."""
        in_use, limit, _src = device_memory_stats()
        if not limit:
            return None
        return in_use + int(estimate_bytes) <= limit * headroom

    def rung_predictor(
        self, estimators: Dict[str, Callable[[], Optional[int]]]
    ) -> Callable[[str], Optional[bool]]:
        """A ``predictor(rung)`` for guard.run_laddered: rungs with an
        estimator get a predict_fit verdict; unknown rungs (or unknown
        budget/estimate) return None and run normally."""

        def predictor(rung: str) -> Optional[bool]:
            est_fn = estimators.get(rung)
            if est_fn is None:
                return None
            est = est_fn()
            if est is None:
                return None
            return self.predict_fit(int(est), label=rung)

        return predictor

    # -- reporting ----------------------------------------------------------

    def device_summary(self) -> list:
        """Last per-device rows ({device, in_use, limit}) — the
        labeled ``simon_device_mem_*{device=...}`` /metrics series and
        the ``per_device`` ledger block."""
        with self._lock:
            return [dict(r) for r in self._per_device]

    def reset(self) -> None:
        with self._lock:
            self.peak_bytes = 0
            self.samples = 0
            self.source = "unpolled"
            self._frames.clear()
            self.watermarks.clear()
            self._last_poll = 0.0
            self._last_in_use = 0
            self._per_device = []
            self._last_rows_poll = 0.0

    def summary(self, top: int = 8) -> dict:
        """The ``ledger`` block for bench obs lines, trace artifacts,
        and the serve drain dump."""
        with self._lock:
            marks = sorted(
                self.watermarks.items(), key=lambda kv: -kv[1]
            )[:top]
            out = {
                "peak_bytes": self.peak_bytes,
                "samples": self.samples,
                "source": self.source,
                "watermarks": {k: v for k, v in marks},
                "per_device": [dict(r) for r in self._per_device],
            }
        out["predictions"] = {
            "total": COUNTERS.get("ledger_predictions_total"),
            "fit": COUNTERS.get("ledger_predict_fit_total"),
            "unfit": COUNTERS.get("ledger_predict_unfit_total"),
            "miss": COUNTERS.get("ledger_predict_miss_total"),
            "hit": COUNTERS.get("ledger_predict_hit_total"),
        }
        return out


LEDGER = MemoryLedger()


def _span_boundary(event: str, name: str, token=None):
    """obs.spans boundary hook: top-level spans open/close ledger
    watermark frames (installed by obs/profile.py at import — the
    first module that can touch jax safely)."""
    if event == "open":
        return LEDGER.span_open(name)
    LEDGER.span_close(token)
    return None
